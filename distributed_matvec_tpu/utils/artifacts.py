"""Content-addressed on-disk artifact cache — default ON.

Every measured config spends orders of magnitude longer *constructing* an
engine than applying it (the recorded TPU bench round: ``engine_init_s``
59–207 s vs ``device_ms`` 6.5–663 ms), yet each of the three expensive
construction
products is a pure function of content that rarely changes:

  basis/       representative + norm arrays, keyed by the basis JSON
               (sector, symmetries, particle content) — the
               ``makeBasisStates`` restore of Diagonalize.chpl:227-246,
               now automatic instead of opt-in;
  structure/   ELL/compact structure sidecars, keyed by the engines'
               ``_structure_fingerprint()`` (basis content + operator term
               tables + mode/dtype/padding);
  xla/         the persistent XLA compilation cache (see utils/cache.py),
               shared by every program the engines compile.

Two cheaper-but-still-cacheable decision products ride in the same tree:

  calibration/ measured hardware rates (obs/roofline.py sidecars);
  tuning/      autotuner decisions (tune/search.py: the chosen knob
               config per (structure, rates, mode) fingerprint) and the
               live rate posteriors (tune/live.py, ``*.posterior.json``)
               that capacity planning and serve admission price from.

All of it lives under one root (first hit wins):

  ``DMT_ARTIFACT_DIR`` env var > ``artifact_dir`` config field >
  ``~/.cache/distributed_matvec_tpu/artifacts``

and the whole layer is switched by the ``artifact_cache`` config knob
(``DMT_ARTIFACT_CACHE=off`` to disable).  Engines consult this layer only
when the caller did not pass an explicit ``structure_cache`` path; explicit
paths keep their exact previous semantics (including loud save errors),
while default-path saves fail soft — a read-only checkout must never turn
a cache write into an engine-construction error.

This is the GSPMD-style separation of one-time partitioning/compilation
cost from steady-state throughput (arXiv:2105.04663): the build is paid
once per *content*, not once per process.
"""

from __future__ import annotations

import os
from typing import Optional

from .config import get_config
from .logging import log_debug, log_warn

__all__ = [
    "artifact_root",
    "artifacts_enabled",
    "artifact_path",
    "default_structure_cache",
    "basis_fingerprint",
    "soft_save_structure",
    "make_or_restore_basis",
    "ensure_compilation_cache",
    "within_size_cap",
    "record_cache_event",
    "note_artifact_corrupt",
    "quarantine_artifact",
]


def record_cache_event(kind: str, event: str) -> None:
    """One artifact-cache outcome into the metrics registry
    (``artifact_cache{kind=basis|structure|tuning,
    event=hit|miss|save|evict}``)
    — the single call site engines and this module share, so the report
    tooling's hit-rate math cannot drift from the recording."""
    from ..obs.metrics import counter

    counter("artifact_cache", kind=kind, event=event).inc()

_DEFAULT_ROOT = os.path.join(os.path.expanduser("~"), ".cache",
                             "distributed_matvec_tpu", "artifacts")

# per-path corrupt-read tally for the retry/quarantine policy (DESIGN.md
# §21): one failure is counted (transient disks happen), a second moves
# the file out of the cache's way
_read_failures: dict = {}


def note_artifact_ok(path: str) -> None:
    """Clear the corruption tally for ``path`` — called by the atomic
    save paths after a successful write, so a rebuilt-and-re-saved
    artifact starts with a clean record (one later transient failure must
    not quarantine a healed file)."""
    _read_failures.pop(path, None)


def note_artifact_corrupt(path: str, kind: str, error=None) -> bool:
    """Record a corrupt/unreadable artifact read and apply the quarantine
    policy: every failure bumps ``artifact_cache{kind=...,event=corrupt}``
    and emits an ``artifact_cache`` corrupt event; the SECOND failure on
    the same path moves the file into a ``.quarantine/`` sibling directory
    (:func:`quarantine_artifact`) so the cache stops serving it — the
    caller's rebuild-from-structure fallback then becomes permanent for
    that entry instead of retrying a bad file forever.  Returns True when
    the file was quarantined."""
    record_cache_event(kind, "corrupt")
    try:
        from ..obs.events import emit

        # NB: "kind" is an envelope key — the artifact kind rides as
        # artifact_kind (same convention as the counter's labels)
        emit("artifact_cache", artifact_kind=kind, event="corrupt",
             path=path, error=repr(error))
    except Exception:
        pass
    n = _read_failures.get(path, 0) + 1
    _read_failures[path] = n
    if n < 2:
        log_warn(f"corrupt {kind} artifact {path} ({error!r}); rebuilding "
                 "— a second failure will quarantine the file")
        return False
    return quarantine_artifact(path, kind, reason=repr(error))


def quarantine_artifact(path: str, kind: str, reason: str = "") -> bool:
    """Move a bad artifact into ``.quarantine/`` next to it (same
    filesystem, atomic rename) and emit an ``artifact_quarantine`` event.
    Fails soft: an unmovable file logs one warning and stays — readers
    already treat it as a miss."""
    if not os.path.exists(path):
        return False
    qdir = os.path.join(os.path.dirname(os.path.abspath(path)),
                        ".quarantine")
    try:
        os.makedirs(qdir, exist_ok=True)
        dest = os.path.join(qdir, os.path.basename(path))
        i = 1
        while os.path.exists(dest):
            dest = os.path.join(qdir, f"{os.path.basename(path)}.{i}")
            i += 1
        os.replace(path, dest)
    except OSError as e:
        log_warn(f"quarantine of {path} failed: {e!r}")
        return False
    _read_failures.pop(path, None)
    record_cache_event(kind, "quarantine")
    try:
        from ..obs.events import emit

        emit("artifact_quarantine", artifact_kind=kind, path=path,
             moved_to=dest, reason=reason)
    except Exception:
        pass
    try:
        # a quarantine means a cache is actively serving corrupt bytes —
        # bundle the context (what was being read, by which span) so the
        # post-mortem names the artifact even if the run later dies
        from ..obs.flight import flight_dump

        flight_dump("quarantine", artifact_kind=kind, path=path,
                    moved_to=dest, error=reason)
    except Exception:
        pass
    log_warn(f"quarantined corrupt {kind} artifact: {path} -> {dest}")
    return True


def artifacts_enabled() -> bool:
    """Whether the default-on artifact layer is active.

    The env var is consulted directly (not just through the config
    snapshot) so a harness can flip it for a subprocess without racing
    the config cache."""
    env = os.environ.get("DMT_ARTIFACT_CACHE")
    knob = env if env is not None else get_config().artifact_cache
    knob = str(knob).strip().lower()
    if knob in ("on", "1", "true", "yes", ""):
        return True
    if knob not in ("off", "0", "false", "no"):
        # fail SOFT and closed: this runs inside every engine construction,
        # so an unrecognized value (typo for "off", most likely) must not
        # crash the engine — and silently caching when the user tried to
        # disable would be the surprising direction
        import warnings

        warnings.warn(f"unknown artifact_cache setting {knob!r} "
                      "(use on | off); treating as off", stacklevel=2)
    return False


def artifact_root() -> str:
    """Resolve the artifact root directory (no filesystem side effects)."""
    return (os.environ.get("DMT_ARTIFACT_DIR")
            or get_config().artifact_dir
            or _DEFAULT_ROOT)


def artifact_path(kind: str, fingerprint: str, suffix: str = "") -> str:
    """``root/<kind>/<fp[:2]>/<fp><suffix>`` with the directory created.

    The two-hex-char shard keeps any one directory from accumulating an
    unbounded flat listing on long-lived caches."""
    d = os.path.join(artifact_root(), kind, fingerprint[:2])
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, fingerprint + suffix)


def default_structure_cache(fingerprint: str) -> Optional[str]:
    """Content-addressed base path for an engine structure sidecar, or
    ``None`` when the layer is off (or the root is uncreatable — a broken
    cache disk must degrade to a plain rebuild, not an engine error)."""
    if not artifacts_enabled():
        return None
    try:
        return artifact_path("structure", fingerprint)
    except OSError as e:
        log_debug(f"artifact cache unavailable: {e!r}")
        return None


def within_size_cap(nbytes: int) -> bool:
    """Whether a DEFAULT-path structure sidecar of ``nbytes`` may be written
    (the ``artifact_max_gb`` knob; explicit paths are never capped)."""
    return nbytes <= get_config().artifact_max_gb * 1e9


def soft_save_structure(sidecar: str, fingerprint: str, mode: str,
                        payload: dict) -> bool:
    """DEFAULT-path (artifact cache) structure/plan sidecar save: honors
    the ``artifact_max_gb`` size cap and degrades to a debug log on I/O
    errors — a read-only checkout or full cache disk must never turn a
    cache write into an engine-construction error.  True when written."""
    from ..io.hdf5 import save_engine_structure

    nbytes = sum(getattr(v, "nbytes", 0) for v in payload.values())
    if not within_size_cap(nbytes):
        record_cache_event("structure", "evict")
        log_debug(f"structure artifact save skipped: {nbytes/1e9:.1f} GB "
                  "exceeds artifact_max_gb")
        return False
    try:
        save_engine_structure(sidecar, fingerprint, mode, payload)
    except OSError as e:
        log_warn(f"structure artifact save skipped: {e!r}")
        return False
    record_cache_event("structure", "save")
    return True


def basis_fingerprint(basis) -> str:
    """Identity of a basis *definition* (not its enumerated output): the
    JSON dict that also seeds the engines' structure fingerprints."""
    import hashlib
    import json

    h = hashlib.sha256()
    h.update(json.dumps(basis._json_dict(), sort_keys=True,
                        default=str).encode())
    h.update(b"|basis-v1")
    return h.hexdigest()


def make_or_restore_basis(basis, path: Optional[str] = None,
                          save: bool = True) -> bool:
    """Build ``basis``, restoring representatives from the artifact cache
    when a matching checkpoint exists (True = restored).

    ``path=None`` resolves the content-addressed default; an explicit path
    keeps :func:`~..io.hdf5.make_or_restore_representatives` semantics.
    Restores use the existing loader; saves go through an atomic
    temp-file + ``os.replace`` so concurrent processes warming the same
    basis can never interleave partial writes (only process 0 of a
    multi-controller run writes at all).  Everything fails soft: with the
    layer off, h5py missing, or the cache dir unwritable this is exactly
    ``basis.build()``.
    """
    if basis.is_built:
        return False
    if path is None:
        if not artifacts_enabled():
            basis.build()
            return False
        try:
            path = artifact_path("basis", basis_fingerprint(basis), ".h5")
        except OSError as e:
            log_debug(f"artifact cache unavailable: {e!r}")
            basis.build()
            return False
    try:
        from ..io.hdf5 import load_basis, save_basis
    except Exception as e:  # pragma: no cover - h5py always present in CI
        log_debug(f"basis artifact cache disabled (no HDF5 I/O): {e!r}")
        basis.build()
        return False
    from . import faults

    def _load():
        if os.path.exists(path):
            faults.check("artifact_read", path=path)
        return load_basis(path)

    try:
        # bounded retry: a transient read blip must not cost a rebuild;
        # a persistently corrupt checkpoint falls through to the rebuild
        # path AND the corrupt/quarantine tally
        got = faults.with_retries("artifact_read", _load)
    except OSError as e:
        got = None          # truncated/corrupt checkpoint: rebuild
        note_artifact_corrupt(path, "basis", e)
    if got is not None and got[1] is not None:
        reps, norms = got
        basis.unchecked_set_representatives(reps, norms)
        record_cache_event("basis", "hit")
        log_debug(f"basis representatives restored from {path}")
        return True
    record_cache_event("basis", "miss")
    basis.build()
    if not save:
        return False
    try:
        import jax

        if jax.process_count() > 1 and jax.process_index() != 0:
            return False
    except Exception:
        pass
    try:
        import tempfile

        faults.check("artifact_save", path=path)
        d = os.path.dirname(os.path.abspath(path))
        fd, tmp = tempfile.mkstemp(suffix=".h5.tmp", dir=d)
        os.close(fd)
        os.chmod(tmp, 0o644)
        try:
            save_basis(tmp, basis.representatives, basis.norms)
            os.replace(tmp, path)
            note_artifact_ok(path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        record_cache_event("basis", "save")
        log_debug(f"basis representatives checkpointed to {path}")
    except OSError as e:
        log_warn(f"basis artifact save skipped: {e!r}")
    return False


def ensure_compilation_cache() -> Optional[str]:
    """Point JAX's persistent compilation cache under the artifact root.

    No-op (returning the active directory) when a cache dir is already
    configured — via ``JAX_COMPILATION_CACHE_DIR`` or an earlier explicit
    :func:`~.cache.enable_compilation_cache` call — and ``None`` when the
    artifact layer is off or the directory cannot be created.  Safe for
    engines to call at construction time: the harness's explicit choice
    always wins.
    """
    if not artifacts_enabled():
        return None
    try:
        import jax

        current = getattr(jax.config, "jax_compilation_cache_dir", None)
        if current:
            return current
        from .cache import enable_compilation_cache

        # no explicit directory: cache._default_dir resolves the artifact
        # root's xla/ subtree — ONE place derives that path
        return enable_compilation_cache()
    except (OSError, ImportError) as e:
        log_debug(f"compilation cache not enabled: {e!r}")
        return None
