"""Preemption-safe solves: SIGTERM/SIGINT latch + the ``Preempted`` exit.

Preemptible TPU slices (the operating regime of the source paper) deliver
a SIGTERM and a short grace window; a multi-hour Lanczos solve must turn
that into a *generation-consistent checkpoint* and a distinct exit code,
not a torn process.  The contract:

* :func:`ensure_installed` installs latch-setting handlers — no I/O, no
  locks, nothing a signal context can deadlock on.  Installed
  process-wide (idempotent, main thread only, ``DMT_PREEMPT=off`` to opt
  out) and deliberately NOT uninstalled after a solve: in a multi-solve
  driver a signal landing *between* solves must still latch, so the next
  safe point exits preempted instead of the default disposition killing
  an un-checkpointed process.  The solver loops install **SIGTERM only**
  (the actual preemption signal) so a library user's Ctrl-C keeps its
  ordinary KeyboardInterrupt semantics; ``apps/diagonalize.py`` — a batch
  driver — opts SIGINT into the latch too.
* The solver checks :func:`requested` at a *safe point* — the block
  boundary, where the Krylov recurrence state is host-consistent and no
  collective is in flight — agrees on the verdict across ranks
  (:func:`agreed`, the same allgather protocol as the checkpoint-restore
  generation agreement, DESIGN.md §15/§21), writes a checkpoint on every
  rank, flushes the obs sinks, and raises :class:`Preempted`.
* ``apps/diagonalize.py`` catches :class:`Preempted` and exits
  :data:`EXIT_PREEMPTED` (75, ``EX_TEMPFAIL``: "transient, retry") so a
  supervisor can relaunch the SAME argv and resume from the checkpoint.

A second signal while the latch is already set restores the default
disposition and re-raises it — a stuck checkpoint write can always be
killed the ordinary way.
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Optional

__all__ = ["EXIT_PREEMPTED", "Preempted", "ensure_installed", "requested",
           "agreed", "trigger", "reset", "set_flight_hook"]

#: Distinct exit code for a checkpoint-and-exit preemption (EX_TEMPFAIL:
#: transient failure, relaunch with the same argv to resume).
EXIT_PREEMPTED = 75

_latch = False
_signum: Optional[int] = None
_prev: dict = {}
_flight_hook = None           # obs/flight registers its dump at import
_flight_fired = False


class Preempted(Exception):
    """A solve stopped at a safe point in response to a preemption signal
    (or a programmatic :func:`trigger`).  ``checkpoint_path`` is the
    checkpoint the resume should restore from (None when the solve ran
    without one)."""

    def __init__(self, solver: str, iters: int,
                 checkpoint_path: Optional[str] = None):
        self.solver = solver
        self.iters = int(iters)
        self.checkpoint_path = checkpoint_path
        where = f" (checkpoint: {checkpoint_path})" if checkpoint_path \
            else " (no checkpoint configured)"
        super().__init__(
            f"{solver} preempted at iteration {iters}{where}; relaunch "
            f"with the same arguments to resume")


def _handler(signum, frame):
    global _latch, _signum
    if _latch:
        # second signal: the graceful path is already in progress (or
        # stuck) — restore the default disposition and deliver it
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)
        return
    _latch = True
    _signum = signum


def ensure_installed(signals=(signal.SIGTERM,)) -> bool:
    """Install the latch handlers process-wide (idempotent per signal).
    The default covers SIGTERM only — the preemption signal — so library
    solves never change a user's Ctrl-C semantics; the CLI driver passes
    SIGINT too.  Main thread only — signal dispositions cannot be set
    elsewhere; a worker-thread caller still reads a latch set by a
    main-thread installation or :func:`trigger`.  ``DMT_PREEMPT=off`` (or
    config ``preempt="off"``) opts out for embeddings with their own
    signal plumbing.  Returns True when all requested handlers are (now)
    active."""
    from .config import get_config

    knob = os.environ.get("DMT_PREEMPT")
    if knob is None:
        knob = get_config().preempt
    if str(knob).strip().lower() in ("off", "0", "false", "no"):
        return False
    if threading.current_thread() is not threading.main_thread():
        return False
    ok = True
    for s in signals:
        if s in _prev:
            continue
        try:
            _prev[s] = signal.signal(s, _handler)
        except (ValueError, OSError):   # exotic embedding: leave as-is
            ok = False
    return ok


def set_flight_hook(fn) -> None:
    """Register the flight recorder's dump callback (``obs/flight.py``
    does this at import).  The signal handler itself stays I/O-free per
    its contract, so the hook runs on the SOLVE thread the first time the
    latch is observed via :func:`requested` — a safe context where file
    writes and locks are allowed.  ``fn(signum)`` is called at most once
    per process; a failing hook is dropped (a crash-path diagnostic must
    never break the graceful exit it documents)."""
    global _flight_hook
    _flight_hook = fn


def _fire_flight_hook() -> None:
    global _flight_hook, _flight_fired
    if _flight_fired or _flight_hook is None:
        return
    _flight_fired = True
    try:
        _flight_hook(_signum)
    except Exception:
        _flight_hook = None


def requested() -> bool:
    """Whether a preemption signal has been latched (this process)."""
    if _latch:
        _fire_flight_hook()
    return _latch


def signal_number() -> Optional[int]:
    return _signum


def agreed(multi: bool) -> bool:
    """Cross-rank verdict on the latch: in a multi-controller run every
    rank must take the checkpoint-and-exit branch *together* or the
    survivors hang in the next collective, so the local flags are
    max-reduced over the same allgather protocol the checkpoint restore
    uses.  ``multi=False`` (single controller, or rank-local meshes whose
    collectives never cross processes) returns the local latch."""
    if not multi:
        return _latch
    try:
        import numpy as np
        from jax.experimental import multihost_utils as mhu

        return bool(np.max(mhu.process_allgather(np.int32(_latch))))
    except Exception:
        # backends without cross-process host collectives: the local
        # verdict is all we have (rank-local-mesh rigs land here and their
        # solves are process-local anyway)
        return _latch


def trigger() -> None:
    """Programmatically set the latch (tests, embedding harnesses with
    their own signal plumbing)."""
    global _latch
    _latch = True


def reset() -> None:
    """Clear the latch (tests; a resumed in-process solve after a handled
    ``Preempted``)."""
    global _latch, _signum, _flight_fired
    _latch = False
    _signum = None
    _flight_fired = False
