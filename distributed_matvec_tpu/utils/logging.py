"""Debug logging — ``logDebug`` parity (``/root/reference/src/FFI.chpl:78-80``:
stderr lines prefixed ``[Debug] [<locale>]``; here the "locale" is the JAX
process index)."""

from __future__ import annotations

import sys
import time
from typing import Optional

from .config import get_config

__all__ = ["log_debug", "log_info", "log_warn"]

_START = time.time()

# Cached after the FIRST SUCCESSFUL jax.process_index() call: importing jax
# and querying the backend on every log line costs a dict of module lookups
# per message (and, before the backend comes up, an exception per line).
# Failure is deliberately NOT cached.  Caching success is SAFE because the
# query itself creates the backend, and jax.distributed.initialize() raises
# ("must be called before any JAX computations") once a backend exists —
# i.e. a successful query freezes the process topology, so the cached value
# can never silently go stale (verified against this jaxlib).
_proc_idx: Optional[int] = None
_proc_count: Optional[int] = None


def _process_index() -> int:
    global _proc_idx
    if _proc_idx is not None:
        return _proc_idx
    try:
        import jax

        _proc_idx = int(jax.process_index())
        return _proc_idx
    except Exception:
        return 0


def _process_count() -> int:
    """Total process (rank) count, cached on the same freeze-on-success
    contract as :func:`_process_index` (a successful backend query pins the
    process topology for the life of the process)."""
    global _proc_count
    if _proc_count is not None:
        return _proc_count
    try:
        import jax

        _proc_count = int(jax.process_count())
        return _proc_count
    except Exception:
        return 1


def log_debug(*parts) -> None:
    if not get_config().log_debug:
        return
    msg = "".join(str(p) for p in parts)
    print(
        f"[Debug] [{_process_index()}] [{time.time() - _START:9.3f}] {msg}",
        file=sys.stderr,
        flush=True,
    )


def log_info(*parts) -> None:
    msg = "".join(str(p) for p in parts)
    print(f"[Info] [{_process_index()}] {msg}", file=sys.stderr, flush=True)


def log_warn(*parts) -> None:
    """Always-on warning level for soft-fail paths (artifact-cache saves,
    event-sink writes): degraded-but-continuing conditions the user should
    see once without turning on debug logging, and that must not masquerade
    as ordinary [Info] progress lines."""
    msg = "".join(str(p) for p in parts)
    print(f"[Warn] [{_process_index()}] {msg}", file=sys.stderr, flush=True)
