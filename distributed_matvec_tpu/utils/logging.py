"""Debug logging — ``logDebug`` parity (``/root/reference/src/FFI.chpl:78-80``:
stderr lines prefixed ``[Debug] [<locale>]``; here the "locale" is the JAX
process index)."""

from __future__ import annotations

import sys
import time

from .config import get_config

__all__ = ["log_debug", "log_info"]

_START = time.time()


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def log_debug(*parts) -> None:
    if not get_config().log_debug:
        return
    msg = "".join(str(p) for p in parts)
    print(
        f"[Debug] [{_process_index()}] [{time.time() - _START:9.3f}] {msg}",
        file=sys.stderr,
        flush=True,
    )


def log_info(*parts) -> None:
    msg = "".join(str(p) for p in parts)
    print(f"[Info] [{_process_index()}] {msg}", file=sys.stderr, flush=True)
