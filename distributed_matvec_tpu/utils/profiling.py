"""Device-level profiling hooks.

The reference carries Chapel ``CommDiagnostics``/``VisualDebug`` hooks behind
``kVerboseComm`` (``DistributedMatrixVector.chpl:19``, ``v1/basis.chpl:7``);
the TPU-native analog is a ``jax.profiler`` trace (viewable in TensorBoard /
Perfetto) gated by the ``profile_dir`` config field (``DMT_PROFILE_DIR=…``).
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Optional

from .config import get_config

__all__ = ["maybe_profile"]


@contextmanager
def maybe_profile(create_perfetto_link: bool = False,
                  profile_dir: Optional[str] = None):
    """Trace the enclosed block when a profile directory is set; otherwise
    a no-op.  Usage::

        with maybe_profile():
            y = eng.matvec(x)

    ``profile_dir`` overrides the global ``config.profile_dir`` field for
    this one block — harnesses (bench.py) can profile exactly one apply per
    config into its own directory without mutating process-global config or
    env vars.  An explicit empty string forces the no-op regardless of the
    config field; ``None`` (default) defers to the config.

    Part of the continuous-profiling plane (obs/profile.py): a captured
    trace directory is stamped with ``PROFILE_META.json``
    (trace_id/job_id) and announced by a ``profile_captured`` event, so
    manual profiles are discoverable from the event stream instead of
    being orphan directories.
    """
    d = profile_dir if profile_dir is not None else get_config().profile_dir
    if not d:
        yield
        return
    import jax

    with jax.profiler.trace(d, create_perfetto_link=create_perfetto_link):
        yield
    # stamp + announce AFTER the trace closes (its files exist now);
    # soft-fail — a broken obs layer must not break the profiled block
    try:
        from ..obs.events import emit, obs_enabled
        from ..obs.profile import stamp_profile_dir

        if obs_enabled():
            stamp_profile_dir(d, capture="manual")
            emit("profile_captured", capture="manual", dir=d)
    except Exception:
        pass
