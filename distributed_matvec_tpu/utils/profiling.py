"""Device-level profiling hooks.

The reference carries Chapel ``CommDiagnostics``/``VisualDebug`` hooks behind
``kVerboseComm`` (``DistributedMatrixVector.chpl:19``, ``v1/basis.chpl:7``);
the TPU-native analog is a ``jax.profiler`` trace (viewable in TensorBoard /
Perfetto) gated by the ``profile_dir`` config field (``DMT_PROFILE_DIR=…``).
"""

from __future__ import annotations

from contextlib import contextmanager

from .config import get_config

__all__ = ["maybe_profile"]


@contextmanager
def maybe_profile(create_perfetto_link: bool = False):
    """Trace the enclosed block when ``config.profile_dir`` is set; otherwise
    a no-op.  Usage::

        with maybe_profile():
            y = eng.matvec(x)
    """
    d = get_config().profile_dir
    if not d:
        yield
        return
    import jax

    with jax.profiler.trace(d, create_perfetto_link=create_perfetto_link):
        yield
