"""Typed runtime configuration — the analog of the reference's ``config const``
flag system (``/root/reference/src/CommonParameters.chpl:1-7`` plus per-module
knobs, e.g. ``DistributedMatrixVector.chpl:456-460``).

Chapel ``config const`` values are compile-time defaults overridable on the
command line (``--kFlag=value``).  Here they are dataclass fields overridable
via environment variables (``DMT_<NAME>=value``) or programmatically through
:func:`get_config` / :func:`set_config`.
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass

__all__ = ["RuntimeConfig", "get_config", "set_config", "update_config"]


@dataclass
class RuntimeConfig:
    # -- observability (CommonParameters.chpl:2) ----------------------------
    display_timings: bool = False          # kDisplayTimings
    log_debug: bool = False                # logDebug gating (FFI.chpl:78-80)
    profile_dir: str = ""                  # non-empty → jax.profiler traces
    #   (the device-side analog of the reference's kVerboseComm/CommDiagnostics
    #    hooks, DistributedMatrixVector.chpl:19)
    obs: str = "on"                        # telemetry layer (obs/): metrics
    #   registry + structured event sink.  "off" (DMT_OBS=off) disables the
    #   whole layer — every instrument becomes a shared no-op object and the
    #   hot paths add zero device-side work
    obs_dir: str = ""                      # event-sink run directory
    #   (DMT_OBS_DIR): non-empty → append-only JSONL stream per process at
    #   <obs_dir>/rank_<r>/events.jsonl; empty → in-memory only
    health: str = "on"                     # numerical-health watchdog
    #   (DMT_HEALTH): "on" emits `health`/`solver_health` events and logs
    #   critical conditions but continues; "strict" raises HealthError on
    #   critical; "off" disables the probes entirely (obs off implies off)
    health_every: int = 16                 # engine-apply probe cadence
    #   (DMT_HEALTH_EVERY): every Nth eager apply piggybacks one fused
    #   NaN/Inf-count + output-norm reduction on the result; the scalar is
    #   fetched DEFERRED so no sync is added to the hot path
    memory_every: int = 64                 # device-memory watermark cadence
    #   (DMT_MEMORY_EVERY): every Nth eager apply polls
    #   device.memory_stats() into hbm_bytes_in_use/hbm_peak_bytes gauges
    #   and a memory_watermark event; backends without stats (CPU) latch
    #   off after the first miss (obs/memory.py)
    trace: str = "on"                      # end-to-end solve tracing
    #   (DMT_TRACE, obs/trace.py): "on" stamps every event's envelope with
    #   trace_id/job_id/span_id and emits one `span` event per closed span
    #   (solve > iteration > apply > chunk) — pure host bookkeeping, the
    #   apply HLO is byte-identical on or off (guard-tested by `make
    #   trace-check`); "off" disables stamping + span events while the
    #   rest of the obs layer keeps running (obs off implies off)
    job_id: str = ""                       # job-namespacing id
    #   (DMT_JOB_ID): stamped into every event envelope; empty defaults to
    #   the run's trace id.  The groundwork the solve service needs to
    #   multiplex many concurrent jobs' telemetry through shared engines
    obs_port: int = 0                      # OpenMetrics exporter base port
    #   (DMT_OBS_PORT, obs/export.py): >0 → each rank serves GET /metrics
    #   (Prometheus text format, fresh registry snapshot per scrape) and
    #   GET /healthz on port obs_port + rank; rank 0's /metrics also
    #   aggregates every peer's textfile under the shared run directory.
    #   0 (the default) binds nothing, and DMT_OBS=off never touches a
    #   socket regardless — the provable-no-op contract
    flight_ring: int = 256                 # flight-recorder ring depth
    #   (DMT_FLIGHT_RING, obs/flight.py): how many of the newest in-memory
    #   events a post-mortem bundle carries alongside the open-span stack,
    #   metrics snapshot and config identity when a rank dies (OOM, stall
    #   exit 76, preemption exit 75, quarantine, fatal signals)
    phases: str = "on"                     # per-apply phase attribution
    #   (DMT_PHASES): "on" emits one `apply_phases` event per eager apply
    #   (host-side structural counts only — the apply HLO is byte-identical
    #   on or off, guard-tested by `make roofline-check`); "off" disables
    #   the events (obs off implies off)
    profile: str = "off"                   # continuous profiling plane
    #   (DMT_PROFILE, obs/profile.py): "sampled" captures a bounded
    #   jax.profiler trace window every profile_every-th eager apply into
    #   <run_dir>/rank_<r>/profiles/ (plus triggered deep capture);
    #   "triggered" keeps only the incident-driven capture path; "off"
    #   (default) is a provable no-op — the apply HLO is byte-identical
    #   on or off, guard-tested by `make profile-check`
    profile_every: int = 64                # sampled-profile cadence
    #   (DMT_PROFILE_EVERY): every Nth eager apply runs inside a trace
    #   window when profile=sampled — same cadence pattern as
    #   health_every, skipping apply 0 (compile noise)
    profile_overhead_pct: float = 2.0      # measured-overhead budget
    #   (DMT_PROFILE_OVERHEAD_PCT): when the trace windows' own measured
    #   start/stop cost exceeds this percent of the un-profiled apply
    #   wall (after ≥2 windows), sampling latches OFF for the process
    #   and emits `profile_overhead_latch` — profiling must never become
    #   the regression it is hunting

    # -- enumeration (CommonParameters.chpl:5-6) ----------------------------
    is_representative_batch_size: int = 10240   # kIsRepresentativeBatchSize
    enumeration_backend: str = "auto"           # auto | native (C++) | numpy

    # -- matvec engine (DistributedMatrixVector.chpl:456-460,55-57) ---------
    remote_buffer_size: int = 150_000      # kRemoteBufferSize → fused-mode all_to_all cap
    all_to_all_capacity_factor: float = 1.25  # padding headroom over mean bucket size

    # -- device/layout ------------------------------------------------------
    matvec_batch_size: int = 1 << 16       # row block B fed to the off-diag kernel
    ell_build_budget_gb: float = 12.0      # device-memory budget for the ELL
    #   structure build; when the one-pass build's full-width [T, N_pad]
    #   buffers would exceed it, the engine switches to the two-pass
    #   low-memory build (count → pack), enabling ELL for bases like
    #   square_6x6 whose packed tables fit HBM but whose full-width
    #   intermediates do not
    matvec_mode: str = "ell"               # "ell" (precomputed structure) |
    #   "compact" (4 B/entry, isotropic real sectors) | "streamed"
    #   (DistributedEngine: fused-class structure resolved once into a
    #   host-RAM plan, streamed H2D per apply — no per-apply orbit scan) |
    #   "fused" (recompute structure every apply) | "hybrid"
    #   (DistributedEngine: per-term recompute-vs-stream split priced by
    #   the calibrated cost model — cheap-orbit terms recompute on device
    #   beside the streamed terms' decode, one merged exchange; see the
    #   `hybrid` knob below and DESIGN.md §28)
    stream_plan_ram_gb: float = 8.0        # host-RAM budget for a streamed
    #   engine's resolved plan; beyond it the plan is demoted to the
    #   artifact-cache sidecar (disk tier) and chunks are read back per
    #   apply — with the artifact layer off the plan stays in RAM with a
    #   warning (pure host-RAM streaming never writes disk)
    stream_compress: str = "off"           # streamed-plan codec tier
    #   (DMT_STREAM_COMPRESS, ops/plan_codec.py): "off" (raw arrays, rok
    #   still bitpacked — bit-identical to fused), "lossless" (bitpacked
    #   indices + f64 dictionary coefficients; decoded values are exact,
    #   gated by the measured-error gate), "f32"/"bf16" (quantized
    #   coefficients, f64 accumulation — for operators whose coefficients
    #   don't repeat enough to dictionary-code).  The plan sidecar, the
    #   host-RAM copy, and the per-apply H2D stream all carry the ENCODED
    #   bytes; decode happens on device inside the chunk program
    pipeline: str = "off"                  # pipelined distributed applies
    #   (DMT_PIPELINE, DESIGN.md §25): software-pipeline depth for the
    #   fused/streamed DistributedEngine apply — "off" (sequential
    #   compute-then-exchange per chunk, bit-identical to every earlier
    #   round), an integer >= 2 (streamed: that many chunks in flight —
    #   plan staging prefetched by worker threads, produce/exchange split
    #   programs with bounded send slots, exchange decomposed into
    #   ppermute rounds; fused: the in-program software pipeline —
    #   chunk i's staged exchange overlaps chunk i+1's gather/multiply
    #   inside one lax.scan), or "auto" (consult the roofline
    #   calibration: on when the priced overlappable time is worth it,
    #   obs/roofline.choose_pipeline_depth).  Accumulation order is
    #   UNCHANGED at any depth, so pipelined applies stay bit-identical
    #   to sequential ones (gated by `make pipeline-check`)
    hybrid: str = "auto"                   # hybrid-mode term split policy
    #   (DMT_HYBRID, DESIGN.md §28): which Hamiltonian terms a
    #   mode="hybrid" DistributedEngine STREAMS (compressed plan slices)
    #   versus RECOMPUTES on device inside the chunk program — "auto"
    #   prices every term off the calibrated roofline (recompute flops at
    #   the measured flop rate vs encoded plan bytes + decode gathers at
    #   the measured H2D/gather rates, obs/roofline.choose_hybrid_split),
    #   "all-stream" / "all-recompute" pin the degenerate splits (equal
    #   to the pure streamed / pure recompute tiers — gate-tested), and
    #   "stream:i,j,..." pins an explicit streamed term set (tests and
    #   controlled experiments).  The resolved split is baked into the
    #   engine fingerprint (v4), so each split mix compiles and caches
    #   as its own static program
    tune: str = "off"                      # self-tuning runtime (DMT_TUNE,
    #   DESIGN.md §30): "off" (every knob is hand-set — all prior
    #   behavior), "static" (at streamed/hybrid engine build, price the
    #   full knob cross-product — row-chunk size × pipeline depth ×
    #   stream_compress tier × hybrid split × prefetch workers ×
    #   plan RAM/disk tier — through the calibrated roofline and take
    #   the argmin; the choice is allgather-agreed across ranks, stamped
    #   into the engine fingerprint via the knobs it sets, and cached as
    #   a content-addressed tuning artifact so repeat builds skip the
    #   search), "live" (static, plus each apply window's measured phase
    #   walls refine a per-(device kind, mode) rate posterior; when
    #   measured-vs-priced drifts outside tune/live.DRIFT_BAND the
    #   engine re-tunes at the next safe boundary — never mid-apply).
    #   Only bit-identity-preserving knob values are ever auto-selected
    #   (compress off|lossless, order-preserving pipeline depths), and
    #   explicitly passed constructor/config knobs always win over tuned
    #   ones.  DMT_TUNE_WINDOW overrides the live update window (8)
    stream_kernel: str = "auto"            # compressed-chunk decode path
    #   (DMT_STREAM_KERNEL): "auto" (currently = xla), "xla" (decode ops
    #   traced into the chunk program — XLA fuses unpack+gather+multiply+
    #   segment-add), "pallas" (the explicit fused decode+gather+multiply+
    #   scatter kernel, interpret mode on non-TPU backends; real-sector
    #   single-column dict-coded chunks only, others fall back to xla)
    split_gather: str = "auto"             # triple-f32 gathers: auto | on | off
    #   (auto = on for the TPU backend; see ops/split_gather.py)
    term_loop: str = "auto"                # ELL/compact per-term loop form:
    #   auto (unroll until the estimated gather scratch would exceed ~2 GB,
    #   then lax.scan — see engine.unroll_terms_ok) | scan (force the
    #   serialized low-memory form everywhere) | unroll (force concurrent
    #   gathers whenever width permits).  "scan" lets small configs exercise
    #   the large-T0 code path the big bases take.
    complex_pair: str = "auto"             # (re,im)-f64 pair engines for
    #   complex sectors: auto | on | off.  auto = pair form on the TPU
    #   backend (whose compiler cannot handle complex128 — see below),
    #   native c128 elsewhere.  "on" forces pair everywhere (useful for
    #   testing), "off" forces native c128 (subject to the TPU guard).
    allow_complex_on_tpu: bool = False     # override the c128-on-TPU guard
    #   (measured here: ANY complex128 program hangs this platform's TPU
    #    compiler indefinitely while f64 and c64 compile in <1 s; engines
    #    refuse native-c128 sectors on the TPU backend unless this is set —
    #    with complex_pair="auto" they run in pair form instead)

    # -- solvers (solve/lanczos.py) -----------------------------------------
    lanczos_reorth: str = "selective"      # per-iteration reorthogonalization
    #   policy: "selective" (window MGS against the trailing rows, escalated
    #   to full MGS blocks when the accumulated ω-recurrence orthogonality
    #   estimate crosses √ε — the Simon semiorthogonality bound; chain_20 is
    #   reorth-bound at ~26× the apply cost) | "full" (the pre-round-9
    #   behavior: full MGS sweeps every iteration)

    # -- fault tolerance (utils/faults.py / preempt.py, parallel/heartbeat.py)
    fault: str = ""                        # deterministic fault injection
    #   (DMT_FAULT): "site[:p=..][:n=..][:skip=..][:seed=..][:delay=..],..."
    #   arms named failure sites on the I/O and comms edges; empty (the
    #   default) resolves to a shared no-op registry — provably inert,
    #   same guard style as DMT_OBS=off
    io_retries: int = 3                    # bounded retry attempts for
    #   idempotent I/O reads (disk-tier plan chunks, artifact loads);
    #   backoff doubles from io_retry_base_s per attempt
    io_retry_base_s: float = 0.05
    heartbeat_s: float = 0.0               # >0 → cross-rank heartbeat
    #   watchdog beat interval (DMT_HEARTBEAT_S); a peer rank whose beat
    #   goes stale past heartbeat_timeout_s triggers a stall_report event
    #   + abort (EXIT_STALLED) instead of an infinite all_to_all wait
    heartbeat_timeout_s: float = 120.0
    preempt: str = "auto"                  # SIGTERM/SIGINT preemption latch
    #   (DMT_PREEMPT): "auto" installs checkpoint-and-exit handlers around
    #   solves (apps/diagonalize exits EXIT_PREEMPTED=75 so a supervisor
    #   relaunches the same argv and resumes); "off" leaves signal
    #   dispositions alone

    # -- artifact cache (utils/artifacts.py) --------------------------------
    artifact_cache: str = "on"             # default-on content-addressed
    #   cache of basis representatives, engine structure sidecars, and the
    #   XLA compilation cache ("off" disables the whole layer; explicit
    #   structure_cache= paths are unaffected either way)
    artifact_dir: str = ""                 # cache root override (also
    #   DMT_ARTIFACT_DIR); default ~/.cache/distributed_matvec_tpu/artifacts
    artifact_max_gb: float = 8.0           # per-sidecar size cap for
    #   DEFAULT-path structure saves: tables beyond this are rebuilt per
    #   process instead of silently filling the cache disk (explicit
    #   structure_cache= paths are never capped)

    # -- solve service (serve/, DESIGN.md §26) ------------------------------
    serve_pool_gb: float = 2.0             # engine-pool byte budget
    #   (DMT_SERVE_POOL_GB): resident engines (device tables + host-RAM
    #   streamed plans) beyond it are evicted LRU — the artifact_max_gb
    #   analog for WARM engines rather than on-disk sidecars
    serve_block_width: int = 6             # max jobs packed into one
    #   batched lanczos_block call (DMT_SERVE_BLOCK_WIDTH): the multi-RHS
    #   block width cap — wider amortizes gathers further but raises the
    #   per-step cost every still-running job pays
    serve_accept_horizon_s: float = 30.0   # admission verdict boundary
    #   (DMT_SERVE_ACCEPT_HORIZON_S): a job whose priced queue-wait ETA
    #   exceeds this is admitted with verdict "queue" (ETA attached)
    #   instead of "accept"; jobs that do not fit at all are rejected



_ENV_PREFIX = "DMT_"
_config: RuntimeConfig | None = None


def _from_env(cfg: RuntimeConfig) -> RuntimeConfig:
    for f in dataclasses.fields(cfg):
        env = os.environ.get(_ENV_PREFIX + f.name.upper())
        if env is None:
            continue
        if f.type in ("bool", bool):
            value = env.lower() in ("1", "true", "yes", "on")
        elif f.type in ("int", int):
            value = int(env)
        elif f.type in ("float", float):
            value = float(env)
        else:
            value = env
        setattr(cfg, f.name, value)
    return cfg


def get_config() -> RuntimeConfig:
    global _config
    if _config is None:
        _config = _from_env(RuntimeConfig())
    return _config


def set_config(cfg: RuntimeConfig) -> None:
    global _config
    _config = cfg


def update_config(**kwargs) -> RuntimeConfig:
    cfg = get_config()
    for k, v in kwargs.items():
        if not hasattr(cfg, k):
            raise AttributeError(f"unknown config field {k!r}")
        setattr(cfg, k, v)
    return cfg


_xla_flag_support: dict = {}


def xla_flag_supported(flag: str) -> bool:
    """Whether this jaxlib's XLA knows ``flag`` (an ``XLA_FLAGS`` name).

    XLA *hard-aborts the whole process* on unknown names in ``XLA_FLAGS``
    ("Unknown flags in XLA_FLAGS", parse_flags_from_env.cc) at first
    backend creation — long after the append, in whatever innocent code
    happens to build the first client (observed: pytest collection dying
    inside ``jax.devices()``).  There is no query API, but a supported
    flag's name string is necessarily embedded in the extension binary
    that parses it, so a byte scan of ``jaxlib.xla_extension`` decides
    support without risking the fatal.  False when the binary cannot be
    located — the safe direction (worst case we skip an optional flag).
    """
    if flag in _xla_flag_support:
        return _xla_flag_support[flag]
    found = False
    try:
        import mmap

        import jaxlib.xla_extension as _xe

        path = getattr(_xe, "__file__", None)
        if path and os.path.isfile(path) and os.path.getsize(path):
            with open(path, "rb") as f, \
                    mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ) as m:
                found = m.find(flag.encode()) != -1
    except Exception:
        found = False
    _xla_flag_support[flag] = found
    return found


def ensure_cpu_collective_timeout(seconds: int = 1200) -> bool:
    """Raise XLA's CPU collective rendezvous termination timeout.

    XLA's CPU runtime kills the whole process when collective participants
    arrive more than 40 s apart ("Termination timeout ... exceeded").  On an
    oversubscribed virtual-device CPU mesh — the multi-chip development
    path of SURVEY.md §6, where N devices execute serially on few host
    cores — a large apply (≥10⁷ states/shard) routinely has >40 s of
    arrival skew, so the default kills runs that would finish fine.  The
    flag must be in ``XLA_FLAGS`` before the CPU client is created, which
    is why the package appends it at import time (harmless for TPU/GPU
    backends: it only governs the CPU collective rendezvous).

    Returns True when the flag is (now) present in ``XLA_FLAGS``; False
    when a backend already initialised without it (the caller must re-exec
    to benefit — this is an XLA runtime flag, not an engine parameter) or
    when this jaxlib's XLA does not know the flag at all (appending it
    would turn the first backend init into a process abort; such builds
    predate the CPU rendezvous kill-switch, so there is nothing to raise).
    """
    flag = "xla_cpu_collective_call_terminate_timeout_seconds"
    flags = os.environ.get("XLA_FLAGS", "")
    if flag in flags:
        return True
    try:
        from jax._src import xla_bridge
        if xla_bridge._backends:        # too late: client already built
            return False
    except Exception:                   # private API moved: assume not yet
        pass
    if not xla_flag_supported(flag):
        return False
    os.environ["XLA_FLAGS"] = (flags + f" --{flag}={seconds}").strip()
    return True
