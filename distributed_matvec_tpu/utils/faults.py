"""Deterministic fault injection — the chaos layer behind ``DMT_FAULT``.

Every carefully built failure path in this repo (corrupt-checkpoint
rebuild, retry-with-backoff, quarantine, preemption checkpoints) is dead
code until something actually fails, and real failures on a preemptible
TPU slice are neither deterministic nor cheap to reproduce.  This module
turns them into a knob: named *sites* sit on every I/O and comms edge
(artifact load/save, streamed plan-chunk reads, checkpoint write/rename,
the D→D′ checkpoint reshard (``ckpt_reshard``, parallel/reshard.py — a
torn redistribution must degrade to a fresh solve, never resume a
half-resharded basis), H2D plan upload, the exchange dispatch, the
solver block boundary), and

    DMT_FAULT="site[:field=value]*[,site2...]"

arms any subset with per-site deterministic behavior:

    p=<float>      fire probability per eligible call (default 1.0)
    n=<int>        maximum number of fires (default 1 — fail once, then
                   heal: exactly what a retry path needs to be exercised)
    skip=<int>     skip the first k eligible calls (default 0 — lets a
                   fault land mid-solve instead of on the first touch)
    seed=<int>     per-site RNG seed for p < 1 (default 0)
    rank=<int>     fire only on this JAX process index (default: all)
    delay=<ms>     SLEEP instead of raising — latency injection, used by
                   the chaos gate to stretch a solve so a kill lands
                   mid-iteration deterministically

Examples::

    DMT_FAULT=artifact_read                  # first artifact read fails
    DMT_FAULT=plan_chunk_read:n=2:skip=3     # chunk reads 4 and 5 fail
    DMT_FAULT=exchange:p=0.1:seed=7,ckpt_rename
    DMT_FAULT=solver_block:delay=250:n=10000   # 250 ms on EVERY solver
                                               # block (n=1 default would
                                               # delay only the first)

Unset, the layer is **provably inert** — the same no-op-singleton pattern
as ``DMT_OBS=off``: :func:`check` resolves to a shared null registry and
returns after one identity test; no site state, no RNG, no event, and
(since every site is host-side) the compiled apply HLO is byte-identical
with the layer armed or not (guard-tested in ``tests/test_faults.py``).

A fired site raises the *caller-chosen* exception type (``OSError`` for
I/O sites, ``RuntimeError`` for comms) with a ``[fault-injection]`` message
prefix, so the failure flows through exactly the handling a real failure
would take — retries, rebuild fallbacks, quarantine — and emits one
``fault_injected`` event plus a ``fault_injected{site=...}`` counter so a
chaos run's event log shows precisely which faults actually landed.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from .config import get_config

__all__ = ["check", "enabled", "fired_count", "reset", "with_retries",
           "FaultSpecError"]


class FaultSpecError(ValueError):
    """Malformed ``DMT_FAULT`` spec (loud: a chaos harness with a typo'd
    site spec must not silently test nothing)."""


class _Site:
    __slots__ = ("name", "p", "n", "skip", "seed", "rank", "delay_ms",
                 "calls", "fired", "_rng")

    def __init__(self, name: str, p: float = 1.0, n: int = 1, skip: int = 0,
                 seed: int = 0, rank: Optional[int] = None,
                 delay_ms: float = 0.0):
        self.name = name
        self.p = p
        self.n = n
        self.skip = skip
        self.seed = seed
        self.rank = rank
        self.delay_ms = delay_ms
        self.calls = 0
        self.fired = 0
        self._rng = None

    def should_fire(self) -> bool:
        self.calls += 1
        if self.calls <= self.skip or self.fired >= self.n:
            return False
        if self.rank is not None:
            from .logging import _process_index
            if _process_index() != self.rank:
                return False
        if self.p < 1.0:
            if self._rng is None:
                import zlib

                import numpy as np
                # keyed by (seed, site) so two armed sites never share a
                # random stream even under the default seed; crc32, NOT
                # hash() — str hashing is salted per process and would
                # make the firing pattern unreproducible across runs/ranks
                self._rng = np.random.default_rng(
                    (self.seed, zlib.crc32(self.name.encode())))
            if self._rng.random() >= self.p:
                return False
        self.fired += 1
        return True


class _NullRegistry:
    """Shared inert registry when ``DMT_FAULT`` is unset/empty."""

    __slots__ = ()
    sites: dict = {}

    def check(self, site, exc=None, **ctx):
        return None


_NULL = _NullRegistry()


class _Registry:
    __slots__ = ("sites",)

    def __init__(self, sites: dict):
        self.sites = sites

    def check(self, site: str, exc=OSError, **ctx) -> None:
        s = self.sites.get(site)
        if s is None or not s.should_fire():
            return
        if s.delay_ms > 0.0:
            time.sleep(s.delay_ms / 1e3)
            self._record(site, s, "delay", ctx)
            return
        self._record(site, s, "raise", ctx)
        raise exc(f"[fault-injection] site {site!r} fired "
                  f"(#{s.fired}/{s.n})")

    @staticmethod
    def _record(site: str, s: _Site, action: str, ctx: dict) -> None:
        try:
            from ..obs.events import emit
            from ..obs.metrics import counter

            counter("fault_injected", site=site).inc()
            emit("fault_injected", site=site, action=action,
                 fired=int(s.fired), call=int(s.calls), **ctx)
        except Exception:
            pass   # injection must never fail for a telemetry reason


def _parse(spec: str) -> "_Registry":
    sites: dict = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        name = fields[0].strip()
        if not name:
            raise FaultSpecError(f"empty site name in DMT_FAULT {spec!r}")
        kw: dict = {}
        for f in fields[1:]:
            if "=" not in f:
                raise FaultSpecError(
                    f"bad field {f!r} in DMT_FAULT site {name!r} "
                    "(use key=value)")
            k, v = f.split("=", 1)
            k = k.strip()
            try:
                if k == "p":
                    kw["p"] = float(v)
                elif k == "n":
                    kw["n"] = int(v)
                elif k == "skip":
                    kw["skip"] = int(v)
                elif k == "seed":
                    kw["seed"] = int(v)
                elif k == "rank":
                    kw["rank"] = int(v)
                elif k == "delay":
                    kw["delay_ms"] = float(v)
                else:
                    raise FaultSpecError(
                        f"unknown field {k!r} in DMT_FAULT site {name!r} "
                        "(use p | n | skip | seed | rank | delay)")
            except ValueError as e:
                if isinstance(e, FaultSpecError):
                    raise
                raise FaultSpecError(
                    f"bad value {v!r} for field {k!r} in DMT_FAULT site "
                    f"{name!r}") from e
        sites[name] = _Site(name, **kw)
    return _Registry(sites) if sites else _NULL


_REG = None


def _registry():
    global _REG
    if _REG is None:
        # env consulted directly (not just the config snapshot) so a chaos
        # harness can arm a subprocess without racing the config cache —
        # the same contract as artifacts_enabled / obs_enabled
        env = os.environ.get("DMT_FAULT")
        spec = env if env is not None else get_config().fault
        _REG = _parse(spec or "")
    return _REG


def check(site: str, exc=OSError, **ctx) -> None:
    """One injection point.  Inert (shared-null fast path) unless
    ``DMT_FAULT`` arms ``site``; armed, either sleeps (``delay=``) or
    raises ``exc`` with a ``[fault-injection]`` message."""
    reg = _registry()
    if reg is _NULL:
        return
    reg.check(site, exc=exc, **ctx)


def enabled() -> bool:
    """Whether any fault site is armed."""
    return _registry() is not _NULL


def fired_count(site: str) -> int:
    """How many times ``site`` has fired in this process (0 when unarmed)."""
    s = _registry().sites.get(site)
    return int(s.fired) if s is not None else 0


def reset() -> None:
    """Drop the parsed registry so the next :func:`check` re-reads
    ``DMT_FAULT`` (tests / long-lived harnesses re-arming a process)."""
    global _REG
    _REG = None


def with_retries(site: str, fn, exc_types=(OSError,),
                 attempts: Optional[int] = None,
                 base_s: Optional[float] = None):
    """Bounded retry-with-backoff for idempotent I/O reads.

    Runs ``fn()`` up to ``attempts`` times (default ``io_retries``),
    sleeping ``base_s · 2^(attempt-1)`` between tries; each retry emits an
    ``io_retry`` event + ``io_retry{site=...}`` counter, and the final
    failure re-raises — callers keep their existing degraded fallbacks
    (rebuild, quarantine) for the persistent case.  Transient failures
    (a NFS blip mid plan-chunk read, hundreds of Lanczos iterations into
    a solve) heal here instead of killing the run."""
    cfg = get_config()
    tries = attempts if attempts is not None else max(int(cfg.io_retries), 1)
    delay = base_s if base_s is not None else float(cfg.io_retry_base_s)
    for attempt in range(1, tries + 1):
        try:
            return fn()
        except exc_types as e:
            if attempt == tries:
                raise
            try:
                from ..obs.events import emit
                from ..obs.metrics import counter

                counter("io_retry", site=site).inc()
                emit("io_retry", site=site, attempt=attempt,
                     error=repr(e))
            except Exception:
                pass
            from .logging import log_warn
            log_warn(f"{site}: transient failure ({e!r}); "
                     f"retry {attempt}/{tries - 1}")
            time.sleep(delay * (2 ** (attempt - 1)))
