"""Cross-cutting utilities: config flags, logging, timers, profiling."""

from . import config, logging, profiling, timers  # noqa: F401
