"""Cross-cutting utilities: config flags, logging, timers, I/O helpers."""

from . import config, logging, timers  # noqa: F401
