"""Persistent XLA compilation cache.

First-compile of the engine programs costs tens of seconds per process over
a tunneled TPU (measured 10.6 s → 0.7 s for a toy program once cached, and
30-70 s for the structure-build programs).  JAX's persistent cache removes
that for every process after the first.  Entry points (bench, CLI, graft
entry) opt in via :func:`enable_compilation_cache` with their own directory
choice; the engines themselves route through
:func:`~.artifacts.ensure_compilation_cache`, which defers to any explicit
harness choice and is gated by the ``artifact_cache`` knob.
"""

from __future__ import annotations

import os

__all__ = ["enable_compilation_cache"]

_DEFAULT = os.path.join(os.path.expanduser("~"), ".cache",
                        "distributed_matvec_tpu", "xla")


def _default_dir() -> str:
    """Default cache dir: under the artifact root when the artifact layer
    is on (one warmable tree), the legacy ``…/xla`` path otherwise."""
    try:
        from .artifacts import artifact_root, artifacts_enabled

        if artifacts_enabled():
            return os.path.join(artifact_root(), "xla")
    except Exception:
        pass
    return _DEFAULT


def enable_compilation_cache(directory: str | None = None) -> str:
    """Point JAX at a persistent compilation cache directory and return it.

    Respects an existing ``JAX_COMPILATION_CACHE_DIR`` environment setting;
    otherwise uses ``directory``, the artifact root's ``xla/`` subtree, or
    ``~/.cache/distributed_matvec_tpu/xla``.  Safe to call multiple times.
    """
    import jax

    directory = (os.environ.get("JAX_COMPILATION_CACHE_DIR") or directory
                 or _default_dir())
    os.makedirs(directory, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", directory)
    # cache everything that took meaningful compile time — unless the user
    # already chose a threshold via the standard env var
    if "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS" not in os.environ:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return directory
