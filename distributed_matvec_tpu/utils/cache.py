"""Persistent XLA compilation cache.

First-compile of the engine programs costs tens of seconds per process over
a tunneled TPU (measured 10.6 s → 0.7 s for a toy program once cached, and
30-70 s for the structure-build programs).  JAX's persistent cache removes
that for every process after the first; entry points (bench, CLI, graft
entry) opt in via :func:`enable_compilation_cache`.  Library code does NOT
enable it implicitly — the cache directory choice belongs to the harness.
"""

from __future__ import annotations

import os

__all__ = ["enable_compilation_cache"]

_DEFAULT = os.path.join(os.path.expanduser("~"), ".cache",
                        "distributed_matvec_tpu", "xla")


def enable_compilation_cache(directory: str | None = None) -> str:
    """Point JAX at a persistent compilation cache directory and return it.

    Respects an existing ``JAX_COMPILATION_CACHE_DIR`` environment setting;
    otherwise uses ``directory`` or ``~/.cache/distributed_matvec_tpu/xla``.
    Safe to call multiple times.
    """
    import jax

    directory = (os.environ.get("JAX_COMPILATION_CACHE_DIR") or directory
                 or _DEFAULT)
    os.makedirs(directory, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", directory)
    # cache everything that took meaningful compile time — unless the user
    # already chose a threshold via the standard env var
    if "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS" not in os.environ:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return directory
