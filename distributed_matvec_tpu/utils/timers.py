"""Hierarchical wall-clock timers with tree-formatted reports.

Parity with the reference's pervasive ``Timer`` instrumentation and its
tree-shaped breakdowns gated by ``--kDisplayTimings``
(``/root/reference/src/DistributedMatrixVector.chpl:1028-1052``,
``StatesEnumeration.chpl:561-566``), including mean ± stderr summaries over
repeated phases (``meanAndErrString``, DistributedMatrixVector.chpl:24-32).
"""

from __future__ import annotations

import math
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .config import get_config
from .logging import log_info

__all__ = ["TreeTimer", "timed"]


@dataclass
class _Node:
    name: str
    total: float = 0.0
    count: int = 0
    samples: List[float] = field(default_factory=list)
    children: Dict[str, "_Node"] = field(default_factory=dict)

    def child(self, name: str) -> "_Node":
        if name not in self.children:
            self.children[name] = _Node(name)
        return self.children[name]

    def mean_and_err(self) -> str:
        n = len(self.samples)
        if n <= 1:
            return f"{self.total:.6f}"
        mean = sum(self.samples) / n
        var = sum((s - mean) ** 2 for s in self.samples) / (n - 1)
        return f"{self.total:.6f} (mean {mean:.6f} ± {math.sqrt(var / n):.6f}, n={n})"


class TreeTimer:
    """Nested scope timer::

        t = TreeTimer("matvec")
        with t.scope("off-diagonal"):
            with t.scope("kernel"): ...
            with t.scope("all_to_all"): ...
        t.report()   # prints only when display_timings is on
    """

    def __init__(self, name: str = "total"):
        self.root = _Node(name)
        self._stack: List[_Node] = [self.root]
        self._t0 = time.perf_counter()

    @contextmanager
    def scope(self, name: str):
        node = self._stack[-1].child(name)
        self._stack.append(node)
        t0 = time.perf_counter()
        try:
            yield node
        finally:
            dt = time.perf_counter() - t0
            node.total += dt
            node.count += 1
            node.samples.append(dt)
            self._stack.pop()

    def stop(self) -> float:
        self.root.total = time.perf_counter() - self._t0
        self.root.count = 1
        return self.root.total

    def to_dict(self) -> dict:
        """Nested ``{name: {total, count, children}}`` snapshot of the tree
        — machine-readable counterpart of :meth:`report` (bench.py records
        the engine-init build/compile/transfer split from it)."""
        def walk(node: _Node) -> dict:
            return {"total": node.total, "count": node.count,
                    "children": {k: walk(c)
                                 for k, c in node.children.items()}}
        return walk(self.root)

    def scope_total(self, *path: str) -> float:
        """Sum of one scope's total seconds at ``path`` under the root
        (0.0 when the scope never ran)."""
        node = self.root
        for name in path:
            node = node.children.get(name)
            if node is None:
                return 0.0
        return node.total

    def emit(self, kind: str = "timer_tree", **fields) -> Optional[dict]:
        """Bridge into the telemetry event sink: record the whole timing
        tree (:meth:`to_dict`) as ONE structured event, so existing timer
        instrumentation lands in the same JSONL stream the metrics and
        solver traces use.  Extra ``fields`` ride along (e.g.
        ``config="chain_16"``).  Returns the event dict, or None when the
        obs layer is disabled."""
        from ..obs.events import emit as _emit

        return _emit(kind, timer=self.root.name, tree=self.to_dict(),
                     **fields)

    def report(self, force: bool = False) -> Optional[str]:
        if not (force or get_config().display_timings):
            return None
        if self.root.count == 0:
            self.stop()
        lines: List[str] = []

        def walk(node: _Node, prefix: str, is_last: bool, is_root: bool):
            if is_root:
                lines.append(f"{node.name}: {node.total:.6f}")
                kids = list(node.children.values())
                for i, k in enumerate(kids):
                    walk(k, "", i == len(kids) - 1, False)
                return
            tee = "└─ " if is_last else "├─ "
            lines.append(f"{prefix}{tee}{node.name}: {node.mean_and_err()}")
            kids = list(node.children.values())
            ext = "   " if is_last else "│  "
            for i, k in enumerate(kids):
                walk(k, prefix + ext, i == len(kids) - 1, False)

        walk(self.root, "", True, True)
        text = "\n".join(lines)
        log_info(text)
        return text


@contextmanager
def timed(label: str):
    """One-off timing context, logged through log_info when timings are on."""
    t0 = time.perf_counter()
    yield
    if get_config().display_timings:
        log_info(f"{label}: {time.perf_counter() - t0:.6f}")
