"""Compressed plan streams: host-side encode, on-device decode (format v1).

The streamed engine (``parallel/distributed.py``) turned the apply into a
bandwidth-bound stream of precomputed plan chunks, and the PR-7 roofline
names ``plan_h2d`` as the binding resource on symm configs — so the next
win must shrink the bytes themselves.  This module is the codec: plan
arrays are *encoded* once at build time and *decoded on device* inside the
chunk program, so the H2D stream (and the sidecar disk tier) carries the
encoded bytes while the arithmetic still runs on exact/f64-accumulated
values.

Because the plan is static, the codec can exploit structure the dynamic
fused path cannot:

* **Dead-entry compaction.**  Roughly half of a Heisenberg chunk's
  (row, term) entries are structurally dead (coefficient 0 — the term
  does not fire on that row).  The compressed tiers store only the live
  entries, each carrying an explicit bitpacked *row* index (the "gather"
  of the decode-gather kernel: ``x[row]`` replaces the implicit
  ``i // T``), shrinking the multiply + scatter work — not just the
  bytes — by the dead fraction (measured 48% on chain_24_symm).
* **Exchange-capacity trim.**  The build sizes the all_to_all buckets
  for the worst case (``Cap ≈ B·T/D × headroom``); the finished plan
  KNOWS the true maximum bucket fill.  The compressed tiers re-base the
  exchange slots to ``cap_eff = max fill`` (global across chunks/shards/
  ranks), halving the send buffer, the collective payload, and the
  receive-side ``segment_sum`` length on symm configs.  The remap is
  monotone per bucket and bucket-major order is preserved, so the
  accumulation ORDER — and therefore every bit of the result — is
  unchanged.

Per (row chunk, shard) the streamed plan holds four arrays
(``DistributedEngine._STREAM_ARRAYS``), encoded as:

``dest``  compressed tiers: TWO concatenated little-endian u32 word
    streams — the live entries' trimmed exchange slots at
    ``w_dest = bits(D·cap_eff)`` bits each (the ``D·cap_eff`` sentinel
    marks padding), then their row indices at ``w_row = bits(B−1)``
    bits.  Fixed-width bitpacking (the ISSUE's alternative to
    delta+varint): the decode is a branch-free vector gather+shift —
    one static program, no data-dependent loop.  ``off``: the raw
    [B·T] i32 array, unchanged.
``ridx``  [D·cap_eff] i32 (< M), bitpacked at ``bits(M−1)``; ``off``:
    raw i32.
``rok``   [D·cap_eff] bool, bitpacked 1 bit/flag — **in the
    uncompressed tier too** (a free lossless 8× on the flags,
    independent of the compress knob).
``coeff`` live entries only, **dictionary-coded** when the number of
    distinct coefficient values fits ``DICT_MAX`` (symm sectors:
    coefficients are ±W·n(β)/n(α)·χ over a finite set of orbit-norm
    ratios, so they repeat massively): u8/u16 codes on the wire + one
    tiny per-shard value table that is device-resident (uploaded once,
    NOT streamed).  Otherwise **raw** per the tier: ``lossless`` keeps
    f64 components, ``f32``/``bf16`` quantize (bf16 travels as its u16
    bit pattern — HDF5 has no bf16).  Decode always lands in f64 (c128)
    before the multiply, so accumulation stays f64 regardless of tier.

Tiers (``stream_compress`` knob / ``DMT_STREAM_COMPRESS``):

* ``off``       — today's raw layout with ``rok`` bitpacked.
  Bit-identical to fused (the existing gate).
* ``lossless``  — compaction + trim + exact f64/c128 coefficient
  values.  The decoded arithmetic is value-identical AND
  order-identical, so the apply stays bit-identical to fused — but the
  tier is gated by the *measured-error* gate, not asserted
  bit-identical (DESIGN.md §23).
* ``f32`` / ``bf16`` — coefficient values quantized; indices stay exact
  (they must).  Gated by measured relative error per config.

Versioned: ``spec["version"]`` rides the sidecar (and the engine
fingerprint), so a format change misses and rebuilds — never misreads.

The decode runs either as plain XLA ops traced into the chunk program
(the default — XLA fuses unpack+gather+multiply+segment-add into the one
compiled chunk executable) or through the explicit Pallas kernel
:func:`fused_decode_gather_scatter` (``stream_kernel=pallas``, interpret
mode on non-TPU backends — the CPU rig's path), which fuses
decode + x-gather + multiply + the send-side scatter in one kernel; the
``all_to_all`` necessarily splits the region, so the receive-side
``segment_sum`` stays in the XLA epilogue either way.
"""

from __future__ import annotations

import json
from typing import Callable, Dict, Optional

import numpy as np

__all__ = [
    "PLAN_CODEC_VERSION",
    "DICT_MAX",
    "TIERS",
    "bits_for",
    "packed_words",
    "pack_bits",
    "unpack_bits_np",
    "unpack_bits",
    "PlanCodec",
    "decode_plan_shard",
    "fused_decode_gather_scatter",
]

PLAN_CODEC_VERSION = 1

#: Per-shard dictionary ceiling: u16 codes.  Beyond it the coefficient
#: stream falls back to the tier's raw form.
DICT_MAX = 1 << 16

TIERS = ("off", "lossless", "f32", "bf16")


# ---------------------------------------------------------------------------
# fixed-width bitpacking (host pack / host + device unpack)


def bits_for(maxval: int) -> int:
    """Bits needed to represent values in ``[0, maxval]`` (min 1)."""
    return max(int(maxval).bit_length(), 1)


def packed_words(n: int, width: int) -> int:
    """u32 words holding ``n`` ``width``-bit values, +1 spare word so the
    branch-free two-word device read never runs off the end."""
    return (n * width + 31) // 32 + 1


#: pack_bits block size: bounds the transient bit-expansion scratch to
#: ~BLK·width bytes instead of O(n·width) — a chain_32-class dest stream
#: must not allocate a multi-hundred-MB intermediate during engine init.
#: A multiple of 8, so every block's bit run starts on a byte boundary.
_PACK_BLOCK = 1 << 17


def pack_bits(values, width: int) -> np.ndarray:
    """``values`` → little-endian u32 word stream at ``width`` bits each
    (bit ``k`` of value ``j`` lands at global bit ``j·width + k``).
    Packs in bounded blocks: peak scratch is O(_PACK_BLOCK·width), not
    O(n·width)."""
    if not 1 <= width <= 32:
        raise ValueError(f"width {width} outside [1, 32]")
    v = np.asarray(values).reshape(-1)
    if v.dtype == np.bool_:
        v = v.astype(np.uint8)
    v = v.astype(np.uint64)
    n = v.size
    if n and width < 64 and int(v.max()) >> width:
        raise ValueError(
            f"value {int(v.max())} does not fit in {width} bits")
    shifts = np.arange(width, dtype=np.uint64)
    nw = packed_words(n, width)
    out = np.zeros(nw * 4, np.uint8)
    for s in range(0, n, _PACK_BLOCK):
        blk = v[s: s + _PACK_BLOCK]
        bits = ((blk[:, None] >> shifts[None, :])
                & np.uint64(1)).astype(np.uint8)
        packed = np.packbits(bits.reshape(-1), bitorder="little")
        b0 = (s * width) // 8          # block-aligned: s·width ≡ 0 (mod 8)
        out[b0: b0 + packed.size] = packed
    return out.view("<u4").copy()


def unpack_bits_np(packed: np.ndarray, n: int, width: int) -> np.ndarray:
    """Host inverse of :func:`pack_bits` (u64 values) — the reference the
    device unpack is tested against, and the host round-trip decoder."""
    b = np.unpackbits(np.ascontiguousarray(packed).view(np.uint8),
                      bitorder="little")
    idx = (np.arange(n, dtype=np.int64)[:, None] * width
           + np.arange(width, dtype=np.int64)[None, :])
    sh = np.arange(width, dtype=np.uint64)[None, :]
    return (b[idx].astype(np.uint64) << sh).sum(axis=1, dtype=np.uint64)


def unpack_bits(packed, n: int, width: int):
    """Device (jax) unpack: one gather + shifts per value, branch-free
    (both words of a potentially-straddling value are always read; the
    second index is clamped so the read is in-bounds even without the
    spare word — a masked ``where`` discards it when unused).  The ONE
    implementation — also the Pallas kernel's body helper (``jnp.take``
    works on loaded values and Refs-read-as-arrays alike), so the
    XLA-vs-Pallas bit-identity gate covers a single decode.  Bit offsets
    are computed in i64: ``n·width`` routinely exceeds 2³² at
    chain_32-class shard sizes, and u32 offset wrap would decode silently
    wrong destinations."""
    import jax
    import jax.numpy as jnp

    bit0 = jax.lax.iota(jnp.int64, n) * width
    w0 = bit0 >> 5                       # i64 word index: no wrap anywhere
    off = (bit0 & 31).astype(jnp.uint32)
    lo = jnp.take(packed, w0) >> off
    spill = (off + jnp.uint32(width)) > jnp.uint32(32)
    # when spill is True, off >= 1, so the shift 32-off is in [1, 31];
    # the False branch's shift operand is forced to 0 (never 32 — XLA's
    # shift-by-bit-width is undefined)
    sh = jnp.where(spill, jnp.uint32(32) - off, jnp.uint32(0))
    w1 = jnp.minimum(w0 + 1, packed.shape[0] - 1)
    hi = jnp.where(spill, jnp.take(packed, w1) << sh, jnp.uint32(0))
    mask = jnp.uint32(0xFFFFFFFF) if width == 32 \
        else jnp.uint32((1 << width) - 1)
    return (lo | hi) & mask


# ---------------------------------------------------------------------------
# coefficient canonicalization / quantization


def _canonical(cf: np.ndarray, ckind: str) -> np.ndarray:
    """Flat complex128/float64 view of a coeff array (the dictionary's key
    space and the liveness test): pair [B, T, 2] folds to complex so one
    dict entry covers both components."""
    cf = np.asarray(cf)
    if ckind == "real":
        return cf.astype(np.float64, copy=False).reshape(-1)
    if ckind == "pair":
        return (cf[..., 0] + 1j * cf[..., 1]).reshape(-1)
    return cf.astype(np.complex128, copy=False).reshape(-1)


def _quantize(vals: np.ndarray, tier: str) -> np.ndarray:
    """Round values through the tier's storage precision (returned at full
    precision — the error is baked in exactly once, at encode time)."""
    if tier in ("off", "lossless"):
        return vals
    if np.iscomplexobj(vals):
        if tier == "f32":
            return vals.astype(np.complex64).astype(np.complex128)
        import ml_dtypes
        re = vals.real.astype(ml_dtypes.bfloat16).astype(np.float64)
        im = vals.imag.astype(ml_dtypes.bfloat16).astype(np.float64)
        return re + 1j * im
    if tier == "f32":
        return vals.astype(np.float32).astype(np.float64)
    import ml_dtypes
    return vals.astype(ml_dtypes.bfloat16).astype(np.float64)


def _raw_store(flat: np.ndarray, ckind: str, tier: str) -> np.ndarray:
    """Storage form of a compacted raw (non-dictionary) coefficient
    vector (canonical f64/c128 live values): [n] f64/f32/bf16-as-u16 for
    real, [n, 2] (re, im) columns for pair/complex."""
    if ckind != "real":
        flat = np.stack([flat.real, flat.imag], axis=-1)
    else:
        flat = flat.real
    if tier == "lossless":
        return flat.astype(np.float64)
    if tier == "f32":
        return flat.astype(np.float32)
    import ml_dtypes
    return flat.astype(ml_dtypes.bfloat16).view(np.uint16)


def _raw_load(stored: np.ndarray, ckind: str) -> np.ndarray:
    """Host inverse of :func:`_raw_store` back to canonical f64/c128."""
    if stored.dtype == np.uint16:
        import ml_dtypes
        v = stored.view(ml_dtypes.bfloat16).astype(np.float64)
    else:
        v = stored.astype(np.float64)
    if ckind != "real":
        return v[..., 0] + 1j * v[..., 1]
    return v


# ---------------------------------------------------------------------------
# the codec


class PlanCodec:
    """One engine's plan codec: a static ``spec`` (JSON-serializable —
    it rides the sidecar) plus the per-shard coefficient dictionaries.

    Construction paths: :meth:`build` scans the raw plan chunks once
    (fresh build), :meth:`from_spec_json` + :meth:`set_dict` restore from
    a sidecar.  Both yield byte-identical encodings for the same raw
    plan — the corrupt-chunk rebuild path re-encodes from structure and
    must reproduce the stored CRC.
    """

    def __init__(self, spec: Dict, dicts: Optional[Dict[int, np.ndarray]]
                 = None):
        if spec.get("version") != PLAN_CODEC_VERSION:
            raise ValueError(
                f"plan codec version {spec.get('version')} != "
                f"{PLAN_CODEC_VERSION}")
        if spec["tier"] not in TIERS:
            raise ValueError(f"unknown compress tier {spec['tier']!r}")
        self.spec = spec
        self.dicts: Dict[int, np.ndarray] = dicts or {}

    # -- construction ----------------------------------------------------

    @classmethod
    def build(cls, tier: str, chunks, n_dest: int, cap_build: int,
              n_devices: int, shard_size: int, cshape, ckind: str,
              agree: Optional[Callable] = None,
              dict_max: int = DICT_MAX,
              term_mask: Optional[np.ndarray] = None) -> "PlanCodec":
        """Codec for a freshly built plan.  ``chunks`` is the engine's
        ``[{shard: pc}]`` raw-chunk list; the scan measures the live-entry
        census (compaction bound), the true maximum bucket fill (capacity
        trim), and the distinct-coefficient census (dictionary decision).
        ``agree`` (multi-controller) maps the local decisions to job-wide
        ones — the encoded operand shapes enter a collective program, so
        every rank must encode identically.

        ``term_mask`` (hybrid mode, DESIGN.md §28) is a [T] bool array
        marking which terms' entries are STORED (True = streamed); the
        other terms are recomputed on device per apply.  The capacity trim
        still measures ALL live entries — the merged slot layout is the
        full plan's, so the streamed entries' stored slots stay exactly
        the slots the full-streamed apply would use and the recompute side
        fills the per-bucket complement — while the dest/row/coeff streams
        (and the dictionary) carry only the masked subset."""
        D = int(n_devices)
        T = int(cshape[1])
        spec = {"version": PLAN_CODEC_VERSION, "tier": tier,
                "n_dest": int(n_dest), "D": D,
                "cap_build": int(cap_build), "cap_eff": int(cap_build),
                "n_recv": D * int(cap_build),
                "w_dest": bits_for(D * int(cap_build)),
                "w_ridx": bits_for(max(shard_size - 1, 1)),
                "w_row": bits_for(max(int(cshape[0]) - 1, 1)),
                "n_live": int(n_dest),
                "cshape": [int(s) for s in cshape], "ckind": ckind,
                "coeff": "raw", "code_bits": 0, "ndict": 0}
        if term_mask is not None:
            term_mask = np.asarray(term_mask, bool).reshape(-1)
            if term_mask.size != T:
                raise ValueError(
                    f"term_mask has {term_mask.size} entries for "
                    f"{T} terms")
            spec["hybrid"] = True
            spec["stream_terms"] = [int(t) for t in
                                    np.nonzero(term_mask)[0]]
            if tier == "off":
                raise ValueError(
                    "a term-masked (hybrid) plan requires a compacted "
                    "tier — the raw [B, T] layout cannot drop terms")
        if tier == "off":
            return cls(spec)
        mask_flat = None if term_mask is None \
            else np.tile(term_mask, int(cshape[0]))
        uniq: Dict[int, np.ndarray] = {}
        n_live = 0
        fill = 0
        for per in chunks:
            for d, pc in per.items():
                flat = _canonical(pc["coeff"], ckind)
                # live = contributes to the apply: nonzero coefficient AND
                # a real exchange slot (the D·Cap sentinel marks entries
                # the raw scatter drops — dead rows, and overflow, which
                # the build already validated to zero)
                dest_all = np.asarray(pc["dest"], np.int64).reshape(-1)
                live = (flat != 0) & (dest_all < D * cap_build)
                dest = dest_all[live]
                if dest.size:
                    # in-bucket rank: dead entries sit in their own
                    # bucket (the D·Cap sentinel), so live positions are
                    # consecutive per bucket and max(pos)+1 is the fill.
                    # ALL live entries count here even under a term mask:
                    # the trim defines the merged slot space
                    fill = max(fill, int((dest % cap_build).max()) + 1)
                if mask_flat is not None:
                    live &= mask_flat
                n_live = max(n_live, int(live.sum()))
                u = np.unique(flat[live])
                prev = uniq.get(d)
                uniq[d] = u if prev is None else \
                    np.unique(np.concatenate([prev, u]))
        nd = max((u.size for u in uniq.values()), default=0)
        use_dict = bool(uniq) and nd <= dict_max
        fill = max(fill, 1)
        n_live = max(((n_live + 7) // 8) * 8, 8)
        if agree is not None:
            use_dict, nd, fill, n_live = agree(use_dict, nd, fill, n_live)
        spec["cap_eff"] = int(min(fill, cap_build))
        spec["n_recv"] = D * spec["cap_eff"]
        spec["w_dest"] = bits_for(spec["n_recv"])
        spec["n_live"] = int(min(n_live, n_dest))
        if use_dict and nd:
            spec["coeff"] = "dict"
            spec["code_bits"] = 8 if nd <= (1 << 8) else 16
            spec["ndict"] = int(nd)
            return cls(spec, uniq)
        return cls(spec)

    def spec_json(self) -> str:
        return json.dumps(self.spec, sort_keys=True)

    @classmethod
    def from_spec_json(cls, s: str) -> "PlanCodec":
        spec = json.loads(s)
        for k in ("tier", "n_dest", "D", "cap_build", "cap_eff", "n_recv",
                  "w_dest", "w_ridx", "w_row", "n_live", "cshape", "ckind",
                  "coeff"):
            if k not in spec:
                raise ValueError(f"codec spec missing {k!r}")
        return cls(spec)

    def set_dict(self, d: int, values: np.ndarray) -> None:
        """Attach shard ``d``'s dictionary (sidecar restore path).  Stored
        values are the original-precision sorted table :meth:`dict_store`
        wrote — real f64 or (re, im) f64 pairs."""
        if self.spec["ckind"] == "real":
            self.dicts[d] = np.asarray(values, np.float64).reshape(-1)
        else:
            v = np.asarray(values, np.float64)
            self.dicts[d] = v[:, 0] + 1j * v[:, 1]

    def dict_store(self, d: int) -> np.ndarray:
        """Shard ``d``'s dictionary in sidecar form: the UNPADDED sorted
        original-precision values (always plain f64 columns —
        HDF5-friendly, negligible next to the chunk stream).  Originals,
        not quantized: they are the ``searchsorted`` key space, and the
        corrupt-chunk rebuild path re-encodes raw coefficients against a
        restored codec — quantized keys would never match.  Quantization
        is applied downstream, in :meth:`dict_device_row` and
        :meth:`decode_chunk_host`."""
        vals = self.dicts[d]
        if self.spec["ckind"] == "real":
            return np.asarray(vals.real, np.float64)
        return np.stack([vals.real, vals.imag], axis=-1).astype(np.float64)

    def dict_device_row(self, d: int) -> np.ndarray:
        """Shard ``d``'s device-resident decode table, padded to the
        agreed ``ndict`` so the assembled [D, nd] operand is uniform:
        [nd] f64 (real), [nd, 2] f64 (pair), or [nd] c128 (complex) —
        what the in-program code gather indexes.  Values are quantized
        per the tier (the one place the precision loss happens).  Empty
        row when the codec carries no dict."""
        ckind = self.spec["ckind"]
        nd = self.spec["ndict"]
        if not nd or self.spec["coeff"] != "dict":
            if ckind == "complex":
                return np.zeros(0, np.complex128)
            return np.zeros((0, 2) if ckind == "pair" else 0, np.float64)
        vals = _quantize(self.dicts[d], self.spec["tier"])
        if ckind == "real":
            out = np.zeros(nd, np.float64)
            out[: vals.size] = vals.real
            return out
        if ckind == "pair":
            out = np.zeros((nd, 2), np.float64)
            out[: vals.size, 0] = vals.real
            out[: vals.size, 1] = vals.imag
            return out
        out = np.zeros(nd, np.complex128)
        out[: vals.size] = vals
        return out

    # -- compaction (host) ------------------------------------------------

    def term_mask(self) -> Optional[np.ndarray]:
        """The [T] bool stream mask of a hybrid (term-masked) codec, None
        otherwise — reconstructed from the spec so a sidecar restore
        carries the split without a separate payload field."""
        if not self.spec.get("hybrid"):
            return None
        mask = np.zeros(int(self.spec["cshape"][1]), bool)
        mask[np.asarray(self.spec.get("stream_terms", []), np.int64)] = True
        return mask

    def compact_raw(self, pc: Dict) -> Dict:
        """One raw (chunk, shard) record → its compacted host-side form:
        live entries only (the masked term subset for a hybrid codec),
        trimmed exchange slots, explicit row indices.
        The shared oracle of :meth:`encode_chunk` and the round-trip
        tests.  Keys: ``dest``/``row``/``coeff`` ([n_live], canonical
        f64/c128 coeff, pads: drop-sentinel / 0 / 0) and
        ``ridx``/``rok`` ([D·cap_eff], the per-bucket prefix of the raw
        receive layout)."""
        s = self.spec
        D, cap_b, cap_e = s["D"], s["cap_build"], s["cap_eff"]
        nl = s["n_live"]
        flat = _canonical(pc["coeff"], s["ckind"])
        dest_all = np.asarray(pc["dest"], np.int64).reshape(-1)
        live = (flat != 0) & (dest_all < D * cap_b)   # build's definition
        mask = self.term_mask()
        if mask is not None:
            live &= np.tile(mask, int(s["cshape"][0]))
        dest = dest_all[live]
        if dest.size > nl:
            raise ValueError(
                f"{dest.size} live entries exceed the codec's n_live "
                f"{nl} — plan/codec mismatch")
        key = dest // cap_b
        pos = dest - key * cap_b
        if pos.size and int(pos.max()) >= cap_e:
            raise ValueError(
                f"bucket fill {int(pos.max()) + 1} exceeds the codec's "
                f"cap_eff {cap_e} — plan/codec mismatch")
        d_out = np.full(nl, D * cap_e, np.int64)
        d_out[: dest.size] = key * cap_e + pos
        r_out = np.zeros(nl, np.int64)
        r_out[: dest.size] = np.nonzero(live)[0] // s["cshape"][1]
        c_out = np.zeros(nl, flat.dtype)
        c_out[: dest.size] = flat[live]
        ridx = np.asarray(pc["ridx"]).reshape(D, cap_b)[:, :cap_e]
        rok = np.asarray(pc["rok"]).reshape(D, cap_b)[:, :cap_e]
        return {"dest": d_out, "row": r_out, "coeff": c_out,
                "ridx": np.ascontiguousarray(ridx).reshape(-1),
                "rok": np.ascontiguousarray(rok).reshape(-1)}

    # -- encode / decode (host) ------------------------------------------

    def encode_chunk(self, pc: Dict, d: int) -> Dict:
        """One raw (chunk, shard) record → its encoded form (same keys, so
        the CRC/sidecar/upload machinery is tier-blind).  Compressed
        tiers fold the row-index stream into the ``dest`` array (two
        concatenated word streams) — no schema change."""
        s = self.spec
        if s["tier"] == "off":
            return {"dest": np.asarray(pc["dest"]),
                    "coeff": np.asarray(pc["coeff"]),
                    "ridx": np.asarray(pc["ridx"]),
                    "rok": pack_bits(pc["rok"], 1)}
        cp = self.compact_raw(pc)
        out = {"dest": np.concatenate([pack_bits(cp["dest"], s["w_dest"]),
                                       pack_bits(cp["row"], s["w_row"])]),
               "ridx": pack_bits(cp["ridx"], s["w_ridx"]),
               "rok": pack_bits(cp["rok"], 1)}
        if s["coeff"] == "dict":
            codes = np.searchsorted(self.dicts[d], cp["coeff"])
            np.clip(codes, 0, max(self.dicts[d].size - 1, 0), out=codes)
            ok = self.dicts[d][codes] == cp["coeff"]
            # padding zeros may legitimately be absent from the dict —
            # their decode value is irrelevant (drop-sentinel dest)
            if not np.all(ok | (cp["coeff"] == 0)):
                raise ValueError(
                    f"shard {d}: coefficient outside its dictionary — "
                    "plan/codec mismatch (stale codec for a rebuilt "
                    "plan?)")
            # pads (coeff 0) take a deterministic in-range code: their
            # decode value is dropped at the sentinel dest either way
            pad_code = min(int(np.searchsorted(self.dicts[d], 0.0)),
                           max(self.dicts[d].size - 1, 0))
            codes[cp["coeff"] == 0] = pad_code
            out["coeff"] = codes.astype(
                np.uint8 if s["code_bits"] == 8 else np.uint16)
        else:
            out["coeff"] = _raw_store(cp["coeff"], s["ckind"], s["tier"])
        return out

    def decode_chunk_host(self, enc: Dict, d: int) -> Dict:
        """Host inverse of :meth:`encode_chunk` — the round-trip test
        oracle and the shape/dtype reference for the device decode.  For
        the ``off`` tier this is the raw record back; compressed tiers
        return the COMPACT form (:meth:`compact_raw` keys — the raw
        (row, term) grid is not invertible once dead entries are gone,
        and the device consumes the compact form anyway).  Quantized
        tiers return the quantized values at full precision."""
        s = self.spec
        n_recv = s["n_recv"]
        if s["tier"] == "off":
            return {"dest": enc["dest"], "coeff": enc["coeff"],
                    "ridx": enc["ridx"],
                    "rok": unpack_bits_np(enc["rok"], n_recv,
                                          1).astype(bool)}
        nl = s["n_live"]
        nwd = packed_words(nl, s["w_dest"])
        dest = unpack_bits_np(enc["dest"][:nwd], nl,
                              s["w_dest"]).astype(np.int64)
        row = unpack_bits_np(enc["dest"][nwd:], nl,
                             s["w_row"]).astype(np.int64)
        ridx = unpack_bits_np(enc["ridx"], n_recv,
                              s["w_ridx"]).astype(np.int32)
        rok = unpack_bits_np(enc["rok"], n_recv, 1).astype(bool)
        if s["coeff"] == "dict":
            coeff = _quantize(self.dicts[d], s["tier"])[
                np.asarray(enc["coeff"], np.int64)]
        else:
            coeff = _raw_load(np.asarray(enc["coeff"]), s["ckind"])
        if s["ckind"] == "real":
            coeff = coeff.real if np.iscomplexobj(coeff) else coeff
        # padding entries decode to dest == drop sentinel; zero their
        # coeff so the host form equals compact_raw exactly
        coeff = np.where(dest == n_recv, 0, coeff)
        return {"dest": dest, "row": row, "coeff": coeff,
                "ridx": ridx, "rok": rok}

    # -- size accounting --------------------------------------------------

    def raw_chunk_bytes(self) -> int:
        """Uncompressed bytes of ONE (chunk, shard) record — dest i32 +
        native-dtype coeff + untrimmed ridx i32 + rok byte-bool.  The
        denominator of the compression ratio (and ``plan_bytes_raw``),
        identical whether the plan was freshly built or
        sidecar-restored."""
        s = self.spec
        cb = 8 if s["ckind"] == "real" else 16
        ncf = int(np.prod(s["cshape"][:2]))
        n_recv_raw = s["D"] * s["cap_build"]
        return s["n_dest"] * 4 + ncf * cb + n_recv_raw * (4 + 1)

    @staticmethod
    def encoded_bytes(enc: Dict) -> int:
        return sum(int(np.asarray(a).nbytes) for a in enc.values())


# ---------------------------------------------------------------------------
# device decode (traced into the streamed chunk program)


def decode_plan_shard(spec: Dict, dest, coeff, ridx, rok, cdict):
    """Shard-local device decode.  ``off`` tier: pass-through plus the
    rok mask unpack, returning ``(dest, coeff, ridx, rok)`` in the raw
    chunk-program layout.  Compressed tiers: the compact form
    ``(dest i32 [n_live], row i32 [n_live], coeff f64/c128/[.,2]f64,
    ridx i32 [D·cap_eff], rok bool)``.  Pure jax ops — traced into the
    (shard_mapped) chunk program, where XLA fuses the unpack/gather
    chain with the multiply + scatter + ``segment_sum`` that follows
    (the default "fused decode" path; ``stream_kernel=pallas`` swaps the
    send side for the explicit kernel below)."""
    import jax.numpy as jnp

    n_recv = spec["n_recv"]
    rok_b = unpack_bits(rok, n_recv, 1).astype(bool)
    if spec["tier"] == "off":
        return dest, coeff, ridx, rok_b
    nl = spec["n_live"]
    nwd = packed_words(nl, spec["w_dest"])
    dest_i = unpack_bits(dest[:nwd], nl, spec["w_dest"]).astype(jnp.int32)
    row_i = unpack_bits(dest[nwd:], nl, spec["w_row"]).astype(jnp.int32)
    ridx_i = unpack_bits(ridx, n_recv, spec["w_ridx"]).astype(jnp.int32)
    cf = _decode_coeff_vals(spec, coeff, cdict)
    return dest_i, row_i, cf, ridx_i, rok_b


def _decode_coeff_vals(spec: Dict, coeff, cdict):
    """Compacted coefficient stream → live values at full precision:
    [n_live] f64 (real), [n_live, 2] f64 (pair), [n_live] c128
    (complex)."""
    import jax
    import jax.numpy as jnp

    ckind = spec["ckind"]
    if spec["coeff"] == "dict":
        return cdict[coeff.astype(jnp.int32)]
    if coeff.dtype == jnp.uint16:             # bf16 raw, as bit patterns
        v = jax.lax.bitcast_convert_type(
            coeff, jnp.bfloat16).astype(jnp.float64)
    else:
        v = coeff.astype(jnp.float64)
    if ckind == "complex":
        return (v[..., 0] + 1j * v[..., 1]).astype(jnp.complex128)
    return v


def fused_decode_gather_scatter(spec: Dict, edest, ecodes, cdict, x_c,
                                interpret: bool):
    """The explicit fused decode+gather+multiply+scatter kernel (Pallas):
    unpack the bitpacked destination and row streams, decode the
    coefficient codes through the dictionary, gather each live entry's x
    row, multiply, and scatter the amplitudes into the send buffer — one
    kernel, nothing materialized in HBM between steps.  Returns the
    ``[D·cap_eff + 1]`` f64 send buffer (the trailing slot collects the
    padding entries; the caller slices it off before the ``all_to_all``).
    The receive-side ``segment_sum`` stays in the XLA epilogue — the
    collective necessarily splits the fused region.

    Scope (enforced by the caller's eligibility check in
    ``_make_streamed_matvec``): real sector, single column, dict-coded
    coefficients.  ``interpret=True`` on non-TPU backends (the CPU rig);
    opt-in via ``stream_kernel=pallas`` — the XLA-ops path in
    :func:`decode_plan_shard` is the default and the fallback.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    nl, wd, wr = spec["n_live"], spec["w_dest"], spec["w_row"]
    n_recv = spec["n_recv"]
    nwd = packed_words(nl, wd)

    def kernel(edest_ref, codes_ref, cdict_ref, x_ref, out_ref):
        out_ref[...] = jnp.zeros_like(out_ref)
        packed = edest_ref[...]
        dest = unpack_bits(packed[:nwd], nl, wd).astype(jnp.int32)
        rows = unpack_bits(packed[nwd:], nl, wr).astype(jnp.int32)
        cf = jnp.take(cdict_ref[...], codes_ref[...].astype(jnp.int32))
        amps = cf * jnp.take(x_ref[...], rows)
        # dest slots are unique by construction (in-bucket rank), so the
        # scatter is collision-free; padding entries land in the
        # trailing drop slot
        dest = jnp.minimum(dest, n_recv)

        def body(i, _):
            out_ref[dest[i]] = amps[i]
            return 0

        jax.lax.fori_loop(0, nl, body, 0)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n_recv + 1,), jnp.float64),
        interpret=interpret,
    )(edest, ecodes, cdict, x_c)
