"""Jitted device kernels: bit ops, operator application, orbit canonicalization."""

from . import bits, kernels  # noqa: F401
