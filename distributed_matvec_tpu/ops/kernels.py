"""Jitted operator-application kernels: diag, off-diag, and state_info.

These are the device replacements for the reference's three hot native kernels
(all called from ``BatchedOperator.computeOffDiag``, /root/reference/src/BatchedOperator.chpl:82-213):

  * ``ls_internal_operator_apply_diag_x1``      → :func:`apply_diag`
  * ``ls_internal_operator_apply_off_diag_x1``  → :func:`apply_off_diag`
  * ``ls_hs_state_info``                        → :func:`state_info`

Design notes (TPU-first, SURVEY.md §7.3):
  * The reference kernels *compact* their output through an offsets array —
    a dynamic shape hostile to XLA.  Here the off-diag kernel emits a dense
    ``[B, T]`` (T = flip-mask groups) with **zero amplitude** marking absent
    elements; downstream routing multiplies by x and drops exact zeros.
  * ``state_info`` canonicalizes through an orbit scan: a ``fori_loop`` over
    the |G| group elements, each applied to the whole ``[M]`` batch via its
    shift/mask network — no gathers, pure vector bit-ops on the VPU, O(G·S)
    passes and O(M) memory (never materializes the [M, G] orbit).
  * Everything is static-shape; chunking over row blocks happens in the engine.

Tables are plain pytrees (NamedTuples of arrays) produced by
:func:`device_tables` from a compiled :class:`~..models.operator.Operator`.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.symmetry import _CHAR_TOL
from .bits import popcount64, sign_from_parity

__all__ = [
    "DiagKernelTables",
    "OffDiagKernelTables",
    "GroupTables",
    "OperatorTables",
    "device_tables",
    "apply_diag",
    "apply_off_diag",
    "gather_coefficients",
    "mask_structure",
    "state_info",
    "cmul_pair",
    "conj_pair",
    "pair_from_complex",
    "complex_from_pair",
]

_U = jnp.uint64

# Zero-norm snap tolerance for the stabilizer character sum, shared with
# the host enumeration (models.symmetry._CHAR_TOL): sectors whose character
# sum cancels exactly (e.g. 1 + 2·cos(2π/3)) leave ~1e-16 of floating-point
# residue, which must read as "state not in sector" on device exactly as it
# does on the host, or the engine build flags phantom out-of-basis targets.
_NORM2_TOL = _CHAR_TOL

# state_info unrolls the per-coset orbit scan for at most this many cosets;
# beyond it (2-D translation groups + point group) a dynamic fori_loop keeps
# the XLA program O(Sc+P) instead of O(J·(Sc+P)) — a J=48 unroll was observed
# to hang the TPU compiler for >35 min.
_COSET_UNROLL_MAX = 8


# ---------------------------------------------------------------------------
# (re, im) pair representation of complex values
#
# TPU has no native complex128 (and this platform's compiler hangs on any
# c128 program — see parallel.engine.check_complex_backend).  Complex-
# character momentum sectors therefore run in *pair* form: every complex
# array carries a trailing axis of length 2 holding (re, im) as f64.  A
# Hermitian H on C^N is exactly a real-symmetric operator on R^{2N}
# ([[Hr, −Hi], [Hi, Hr]]), so the whole engine/solver stack stays in f64.
# ---------------------------------------------------------------------------


def cmul_pair(c: jax.Array, g: jax.Array) -> jax.Array:
    """Complex multiply on (re, im) pairs: ``[..., 2] × [..., 2] → [..., 2]``."""
    cr, ci = c[..., 0], c[..., 1]
    gr, gi = g[..., 0], g[..., 1]
    return jnp.stack([cr * gr - ci * gi, cr * gi + ci * gr], axis=-1)


def conj_pair(c: jax.Array) -> jax.Array:
    """Complex conjugate on (re, im) pairs (negates the im slot)."""
    return jnp.stack([c[..., 0], -c[..., 1]], axis=-1)


def pair_from_complex(z) -> np.ndarray:
    """Host-side complex ``[...]`` → f64 pair ``[..., 2]`` (NumPy)."""
    z = np.asarray(z)
    return np.stack([z.real.astype(np.float64),
                     z.imag.astype(np.float64)], axis=-1)


def complex_from_pair(p) -> np.ndarray:
    """Host-side f64 pair ``[..., 2]`` → complex128 ``[...]`` (NumPy)."""
    p = np.asarray(p)
    return p[..., 0] + 1j * p[..., 1]


class DiagKernelTables(NamedTuple):
    v: jax.Array  # [K] f64 (real diagonal; Hermiticity enforced upstream)
    s: jax.Array  # [K] u64
    m: jax.Array  # [K] u64
    r: jax.Array  # [K] u64


class OffDiagKernelTables(NamedTuple):
    x: jax.Array  # [T] u64 flip mask per group
    v: jax.Array  # [T,K] f64/c128 — or [T,K,2] f64 (re, im) pair form
    s: jax.Array  # [T,K] u64
    m: jax.Array  # [T,K] u64
    r: jax.Array  # [T,K] u64


class GroupTables(NamedTuple):
    """Coset-walk tables for the symmetry group (symmetry.SymmetryGroup.coset_walk).

    The orbit scan applies each coset representative once (few, possibly wide
    networks) and then advances through the cyclic subgroup ``H = ⟨h⟩`` with
    the cheap ``h`` network — O(Σ|c_j| + G·|h|) bit-ops per state instead of
    the naive O(G·S_max) (an ~10× cut for reflection/inversion-extended
    translation groups, where the composed elements have O(n)-wide networks).
    """

    h_ls: jax.Array       # [Sh] u64 — advance network h (exact width)
    h_rs: jax.Array       # [Sh] u64
    h_m: jax.Array        # [Sh] u64
    c_ls: jax.Array       # [J,Sc] u64 — coset rep networks (zero-mask padded)
    c_rs: jax.Array       # [J,Sc] u64
    c_m: jax.Array        # [J,Sc] u64
    c_xor: jax.Array      # [J] u64 — spin-inversion xor per coset rep
    elem: jax.Array       # [J,P] i32 — canonical element index of h^k·c_j
    char_conj: jax.Array  # [G] f64/c128 — or [G,2] f64 pair form — χ*(g)
    char_real: jax.Array  # [G] f64 — Re χ(g) for stabilizer norm sums


class OperatorTables(NamedTuple):
    diag: DiagKernelTables
    off: OffDiagKernelTables
    group: Optional[GroupTables]  # None when the basis needs no projection


def device_tables(op, pair: bool = False) -> OperatorTables:
    """Compile an :class:`Operator` into device-resident kernel tables.

    ``pair=True`` stores complex amplitudes/characters in (re, im) f64 pair
    form (trailing axis 2) instead of complex128 — the TPU-safe layout.  It
    is a no-op for operators that are effectively real.
    """
    real = op.effective_is_real
    pair = pair and not real
    dt, ot = op.diag_table, op.off_diag_table
    assert np.abs(dt.v.imag).max(initial=0.0) < 1e-12, "non-real diagonal"
    diag = DiagKernelTables(
        v=jnp.asarray(dt.v.real, jnp.float64),
        s=jnp.asarray(dt.s),
        m=jnp.asarray(dt.m),
        r=jnp.asarray(dt.r),
    )
    if pair:
        off_v = jnp.asarray(pair_from_complex(ot.v))
    elif not real:
        off_v = jnp.asarray(ot.v, jnp.complex128)
    else:
        assert np.abs(ot.v.imag).max(initial=0.0) < 1e-12
        off_v = jnp.asarray(ot.v.real, jnp.float64)
    off = OffDiagKernelTables(
        x=jnp.asarray(ot.x), v=off_v, s=jnp.asarray(ot.s),
        m=jnp.asarray(ot.m), r=jnp.asarray(ot.r),
    )
    group = None
    if op.basis.requires_projection:
        g = op.basis.group
        (h_ls, h_rs, h_m, _), coset_nets, elem_idx = g.coset_walk()
        sc = max(n[2].size for n in coset_nets)
        J = len(coset_nets)
        c_ls = np.zeros((J, sc), np.uint64)
        c_rs = np.zeros((J, sc), np.uint64)
        c_m = np.zeros((J, sc), np.uint64)
        c_xor = np.zeros(J, np.uint64)
        for j, (ls_j, rs_j, m_j, xor_j) in enumerate(coset_nets):
            c_ls[j, : ls_j.size] = ls_j
            c_rs[j, : rs_j.size] = rs_j
            c_m[j, : m_j.size] = m_j
            c_xor[j] = xor_j
        cc = np.conj(g.characters)
        if pair:
            char_conj = jnp.asarray(pair_from_complex(cc))
        else:
            char_conj = jnp.asarray(cc.real if real else cc,
                                    jnp.float64 if real else jnp.complex128)
        group = GroupTables(
            h_ls=jnp.asarray(h_ls), h_rs=jnp.asarray(h_rs),
            h_m=jnp.asarray(h_m),
            c_ls=jnp.asarray(c_ls), c_rs=jnp.asarray(c_rs),
            c_m=jnp.asarray(c_m), c_xor=jnp.asarray(c_xor),
            elem=jnp.asarray(np.stack(elem_idx)),
            char_conj=char_conj,
            char_real=jnp.asarray(g.characters.real, jnp.float64),
        )
    return OperatorTables(diag=diag, off=off, group=group)


def apply_diag(t: DiagKernelTables, alphas: jax.Array) -> jax.Array:
    """d(α) for a batch: [B] u64 → [B] f64."""
    if t.v.shape[0] == 0:
        return jnp.zeros(alphas.shape, jnp.float64)
    a = alphas[:, None]
    sign = sign_from_parity(a & t.s[None, :])
    ok = (a & t.m[None, :]) == t.r[None, :]
    return jnp.sum(t.v[None, :] * sign * ok, axis=1)


def apply_off_diag(t: OffDiagKernelTables, alphas: jax.Array):
    """H's off-diagonal action: [B] u64 → betas [B,T] u64, amps [B,T].

    amps[i,j] = Σ_k v[j,k]·(−1)^pc(α_i∧s)·[α_i∧m==r]; betas[i,j] = α_i⊕x[j].
    Pair-form tables (``v`` of shape [T,K,2]) yield pair amps [B,T,2].
    """
    betas = alphas[:, None] ^ t.x[None, :]
    a = alphas[:, None, None]
    sign = sign_from_parity(a & t.s[None])
    ok = (a & t.m[None]) == t.r[None]
    if t.v.ndim == 3:  # pair form
        w = sign * ok                                      # [B,T,K] f64
        amps = jnp.stack([jnp.sum(t.v[None, ..., 0] * w, axis=2),
                          jnp.sum(t.v[None, ..., 1] * w, axis=2)], axis=-1)
    else:
        amps = jnp.sum(t.v[None] * sign * ok, axis=2)
    return betas, amps


def gather_coefficients(t: OperatorTables, alphas: jax.Array,
                        norms_alpha: jax.Array):
    """Row-form (gather) neighbor structure of a Hermitian operator.

    For each row state ``α`` returns the canonical target states and the
    *row* matrix elements ``A[α, rep(β)] = conj(⟨β|H|α⟩·χ*(g))·n(β)/n(α)``
    (valid because H_eff is Hermitian: A_ij = conj(A_ji); the scatter-form
    rescale is BatchedOperator.chpl:198-203).  Shapes: [B] u64 → ([B,T] u64,
    [B,T] amp).  Zero amplitude marks "no matrix element" (padding included).
    """
    betas, amps = apply_off_diag(t.off, alphas)  # amps = ⟨β|H|α⟩
    pair = amps.ndim == 3
    if t.group is not None:
        rep_b, char_conj_b, norm_b = state_info(t.group, betas)
        ratio = norm_b / norms_alpha[:, None]
        if pair:
            amps = conj_pair(cmul_pair(amps, char_conj_b)) * ratio[..., None]
        else:
            amps = jnp.conj(amps * char_conj_b) * ratio
        betas = rep_b
    else:
        amps = conj_pair(amps) if pair else jnp.conj(amps)
    return betas, amps


def mask_structure(coeff: jax.Array, idx: jax.Array, found: jax.Array,
                   valid_row: jax.Array):
    """Shared post-kernel masking: zero out absent/padded entries and count
    out-of-basis targets.

    ``valid_row`` marks non-SENTINEL rows ([B] bool).  Returns
    (idx, coeff, invalid) where entries with a *structurally* nonzero
    coefficient targeting a state not found in the basis are counted as
    ``invalid`` (the halt condition of DistributedMatrixVector.chpl:113-118).
    Counting structure (coeff ≠ 0) rather than amplitude·x keeps the result
    independent of x's zero pattern, so a first-call check is valid for every
    subsequent application.  Pair-form coefficients (trailing axis 2) count
    as nonzero when either slot is.
    """
    vr = valid_row[:, None]
    pair = coeff.ndim == idx.ndim + 1
    live = (coeff != 0).any(axis=-1) if pair else (coeff != 0)
    nz = live & vr
    invalid = jnp.sum(nz & ~found)
    nz = nz & found
    coeff = jnp.where(nz[..., None] if pair else nz, coeff, 0)
    idx = jnp.where(nz, idx, 0)
    return idx, coeff, invalid


def state_info(g: GroupTables, states: jax.Array):
    """Orbit scan: canonical representative, χ*, and norm for each state.

    Contract of ``ls_hs_state_info`` (FFI.chpl:181-184) with the convention
    validated against the dense projector path (tests/test_operator.py):
      rep(σ)  = min_g g·σ
      char(σ) = χ*(g_first-achieving-min)
      norm(σ) = sqrt((1/|G|)·Σ_{g·σ=σ} Re χ(g))   (0 ⇒ not in the sector)

    The scan carry tracks the *index* of the winning group element (i32) —
    never a character value — so the loop body is pure integer/f64 work even
    for complex-character sectors; ``χ*`` is one ``[G]``-table gather at the
    end (``char_conj`` rows may be scalars or (re, im) pairs).
    """
    G = g.char_conj.shape[0]
    J, P = g.elem.shape
    flat = states.reshape(-1)

    def apply_coset_rep(j, s):
        acc = jnp.zeros_like(s)
        for k in range(g.c_m.shape[1]):  # padded width, zero masks are no-ops
            acc = acc | (((s & g.c_m[j, k]) << g.c_ls[j, k]) >> g.c_rs[j, k])
        return acc ^ g.c_xor[j]

    def advance(s):
        acc = jnp.zeros_like(s)
        for k in range(g.h_m.shape[0]):  # exact (small) width of h
            acc = acc | (((s & g.h_m[k]) << g.h_ls[k]) >> g.h_rs[k])
        return acc

    def update(carry, y, gi):
        best, gidx, stab = carry
        better = y < best
        best = jnp.where(better, y, best)
        gidx = jnp.where(better, gi, gidx)
        stab = stab + jnp.where(y == flat, g.char_real[gi], 0.0)
        return best, gidx, stab

    # Zeros with the same device-varying type as the input (so the carry is
    # stable when this runs inside shard_map; XLA folds the xor away).
    zero = (flat ^ flat).astype(jnp.float64)
    izero = (flat ^ flat).astype(jnp.int32)
    carry = (flat + jnp.uint64(0),  # identity (elem index 0); re-updated below
             izero, zero)

    def one_coset(j, carry):
        z = apply_coset_rep(j, flat)
        carry = update(carry, z, g.elem[j, 0])

        def body(k, c):
            best, gidx, stab, z = c
            z = advance(z)
            best, gidx, stab = update((best, gidx, stab), z, g.elem[j, k])
            return best, gidx, stab, z

        best, gidx, stab, _ = jax.lax.fori_loop(1, P, body, carry + (z,))
        return best, gidx, stab

    if J <= _COSET_UNROLL_MAX:
        # few cosets — unrolled (cheapest compile, constant-folds g.elem)
        for j in range(J):
            carry = one_coset(j, carry)
    else:
        # many cosets (2-D translation groups + point group: square_6x6 has
        # J=48) — a Python unroll makes the XLA program O(J·(Sc+P)) and the
        # compile pathological (>35 min observed); loop dynamically instead
        carry = jax.lax.fori_loop(0, J, one_coset, carry)
    best, gidx, stab = carry
    char = g.char_conj[gidx]
    norm2 = stab / G
    norm = jnp.where(norm2 > _NORM2_TOL, jnp.sqrt(jnp.maximum(norm2, 0.0)),
                     0.0)
    shape = states.shape
    char_shape = shape + g.char_conj.shape[1:]
    return best.reshape(shape), char.reshape(char_shape), norm.reshape(shape)
