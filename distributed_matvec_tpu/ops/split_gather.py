"""Exact triple-float32 split gathers for 64-bit values on TPU.

TPU has no native 64-bit types: XLA emulates f64/c128 as 32-bit pairs, and
an emulated-f64 gather issues *two* index-rate-bound gathers (measured on
v5e: 42 M elem/s for f64 vs 110 M for f32 — gathers pay per index, not per
byte).  Splitting ``x`` into three f32 parts ``x = a + b + c`` (24-bit
mantissa each, 72 ≥ 53 bits total) turns every table gather into ONE gather
of a ``[..., 3]`` f32 row at the f32 index rate — measured 3.6× faster
(147 M elem/s) and **bit-exact**:

* ``a = f32(x)``, ``b = f32(x − a)``, ``c = f32(x − a − b)`` — consecutive
  roundings, so ``b ≲ ulp32(a)``, ``c ≲ ulp32(b)``.
* Reassembly ``(f64(a) + f64(b)) + f64(c)`` is exact: ``a + b`` spans ≤ 50
  mantissa bits, and the final add rounds to the representable true value
  ``x`` itself.
* Parts smaller than the f32 denormal floor (|x| < ~1e-41) are flushed; the
  absolute error is < 1e-41 — far below the engine tolerance (atol 1e-14,
  reference TestMatrixVectorProduct.chpl:15-16) for the solver-normalized
  vectors the engines consume.
* Precondition: |x| must stay below f32 max (~3.4e38).  Inf/NaN inputs and
  finite values beyond that bound poison the split (``f32(x) = inf`` →
  ``x − inf = NaN``) and the result is NaN — loud, not silently wrong.
  Engine vectors are solver-normalized, far inside the bound.

complex128 uses six parts (re then im).  The ``split_gather`` config knob
gates the rewrite: ``"auto"`` (default) enables it exactly when the default
JAX backend is TPU — on CPU the native f64 gather is faster than
split + join.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..utils.config import get_config

__all__ = ["split_gather_enabled", "split_parts", "join_parts",
           "prep_gather"]


def split_gather_enabled() -> bool:
    """True when gathers should use the triple-f32 form (see module doc)."""
    knob = get_config().split_gather
    if knob == "on":
        return True
    if knob == "off":
        return False
    if knob != "auto":
        raise ValueError(
            f"unknown split_gather setting {knob!r} (use auto | on | off)")
    return jax.default_backend() == "tpu"


def prep_gather(x, dtype, enabled: bool):
    """Row-gather closure over ``x``: ``gather(idx) == x[idx]`` numerically.

    When ``enabled``, ``x`` is pre-split once and every gather moves one
    ``[..., P]`` f32 row instead of an emulated-64-bit element (see module
    doc); otherwise the plain gather is returned.

    Batched/pair vectors (trailing axes) are flattened so each gather moves
    ONE contiguous ``[k·P]`` f32 row: on v5e the row-gather rate is flat up
    to width ~6 (tools/gather_bound.py), so a k=2 batch costs nearly the
    same as a single vector — XLA would otherwise issue separate gathers
    per trailing-axis slice (measured 1.14× instead of ~2× per-vector).
    """
    if not enabled:
        return lambda i: x[i]
    xs = split_parts(x)
    tail = xs.shape[1:]
    flat = xs.reshape(xs.shape[0], -1)
    return lambda i: join_parts(flat[i].reshape(i.shape + tail), dtype)


def _split3(x):
    a = x.astype(jnp.float32)
    r = x - a.astype(jnp.float64)
    b = r.astype(jnp.float32)
    c = (r - b.astype(jnp.float64)).astype(jnp.float32)
    return jnp.stack([a, b, c], axis=-1)


def _join3(g):
    return (g[..., 0].astype(jnp.float64) + g[..., 1].astype(jnp.float64)
            + g[..., 2].astype(jnp.float64))


def split_parts(x):
    """f64 ``[...]`` → f32 ``[..., 3]``; c128 ``[...]`` → f32 ``[..., 6]``."""
    if jnp.iscomplexobj(x):
        return jnp.concatenate([_split3(x.real), _split3(x.imag)], axis=-1)
    return _split3(x)


def join_parts(g, dtype):
    """Inverse of :func:`split_parts` on gathered rows (consumes last axis)."""
    if jnp.issubdtype(jnp.dtype(dtype), jnp.complexfloating):
        return jax.lax.complex(_join3(g[..., :3]), _join3(g[..., 3:]))
    return _join3(g)
