"""Device-side bit manipulation: popcount, shard hashing, state lookup.

JAX/XLA equivalents of the reference's hot scalar kernels:
  * ``hash64`` — splitmix64 finalizer (StatesEnumeration.chpl:122-127) used to
    route each generated state to its owning shard,
  * ``state_index_sorted`` — batched basis lookup replacing ``ls_hs_state_index``
    (FFI.chpl:173-175) with a vectorized binary search over the *sorted* local
    representative shard (shards are sorted by construction, so searchsorted is
    exact),
  * ``popcount64`` — sign-mask parity for the nonbranching term kernels.

All functions are shape-polymorphic, jit-safe, and uint64-clean (require
``jax_enable_x64``; on TPU XLA lowers 64-bit integer ops to u32 pairs).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["popcount64", "hash64", "shard_index", "state_index_sorted",
           "sign_from_parity", "choose_dir_bits", "build_sorted_lookup",
           "state_index_bucketed"]

_U = jnp.uint64


def popcount64(x: jax.Array) -> jax.Array:
    return jax.lax.population_count(x.astype(jnp.uint64))


def sign_from_parity(x: jax.Array) -> jax.Array:
    """(−1)^popcount(x) as float (f64): +1 for even parity, −1 for odd."""
    return 1.0 - 2.0 * (popcount64(x) & _U(1)).astype(jnp.float64)


def hash64(x: jax.Array) -> jax.Array:
    """splitmix64 finalizer — bit-exact with enumeration.host.hash64."""
    x = x.astype(jnp.uint64)
    x = (x ^ (x >> _U(30))) * _U(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> _U(27))) * _U(0x94D049BB133111EB)
    return x ^ (x >> _U(31))


def shard_index(states: jax.Array, n_shards: int) -> jax.Array:
    """Owning device of each state (``localeIdxOf``, StatesEnumeration.chpl:129-136)."""
    if n_shards == 1:
        return jnp.zeros(states.shape, dtype=jnp.int32)
    return (hash64(states) % _U(n_shards)).astype(jnp.int32)


def state_index_sorted(sorted_reps: jax.Array, states: jax.Array):
    """(index, found) of each state in a sorted representative array.

    ``index`` is clipped into range; ``found`` marks exact hits.  The identity
    fast path of the reference (DistributedMatrixVector.chpl:86-95) is
    subsumed: XLA folds the search when the caller knows indices are trivial.
    """
    idx = jnp.searchsorted(sorted_reps, states)
    idx = jnp.clip(idx, 0, sorted_reps.shape[0] - 1)
    found = sorted_reps[idx] == states
    return idx.astype(jnp.int64), found


def choose_dir_bits(n: int, n_bits: int, max_dir_bits: int = 24) -> int:
    """Directory width for an ``n``-entry basis over ``n_bits``-bit states:
    ~1-entry average buckets, capped by the state width and a memory bound
    (2^24 × i32 = 64 MB)."""
    return min(max(n_bits, 1),
               max(int(np.ceil(np.log2(max(n, 2)))) + 1, 1), max_dir_bits)


def build_sorted_lookup(reps, n_bits: int, max_dir_bits: int = 24,
                        dir_bits: int | None = None):
    """Precompute the bucket-directory lookup structure for a sorted basis.

    ``jnp.searchsorted`` costs ~log2(N) sequential emulated-u64 gathers per
    query — it dominated the ELL structure build (measured 1.1 s per 2M
    lookups in a 4.7M-state basis on v5e, 96% of the per-chunk time).  The
    bucketed form cuts that 4–9× (synthetic uniform keys: 22.5 vs 5.4 M
    lookups/s; the real chain_32_symm reps: 17.2 vs 1.8): a
    directory over the top ``b`` state bits yields a ≲ few-entry bucket, and
    the remaining probes compare (hi, lo) u32 pairs fetched with ONE row
    gather each instead of an emulated 64-bit gather.

    Host-side; returns ``(pair [N,2] u32, dir [2^b+1] i32, shift, probes)``
    — arrays are NumPy (callers ship them to devices as jit arguments),
    ``shift``/``probes`` are Python ints to close over statically.
    """
    reps = np.asarray(reps, dtype=np.uint64)
    n = int(reps.size)
    b = dir_bits if dir_bits is not None \
        else choose_dir_bits(n, n_bits, max_dir_bits)
    shift = n_bits - b
    edges = np.arange(1 << b, dtype=np.uint64) << np.uint64(shift)
    dir_tab = np.empty((1 << b) + 1, np.int32)
    dir_tab[: 1 << b] = np.searchsorted(reps, edges)
    dir_tab[1 << b] = n                     # 2^n_bits would overflow u64
    max_bucket = int((dir_tab[1:] - dir_tab[:-1]).max()) if n else 0
    probes = max(1, int(np.ceil(np.log2(max_bucket + 1)))) if max_bucket \
        else 1
    pair = np.stack([(reps >> np.uint64(32)).astype(np.uint32),
                     reps.astype(np.uint32)], axis=1)
    return pair, dir_tab, shift, probes


def state_index_bucketed(pair: jax.Array, dir_tab: jax.Array,
                         states: jax.Array, *, shift: int, probes: int):
    """(index, found) via the directory from :func:`build_sorted_lookup`.

    Exact same contract as :func:`state_index_sorted`.  Out-of-range states
    (e.g. SENTINEL-derived garbage) clamp into the last bucket and report
    ``found=False``.
    """
    n = pair.shape[0]
    states = states.astype(jnp.uint64)
    # clamp in u64 BEFORE the int32 cast: a garbage state (e.g. SENTINEL)
    # would wrap negative and index the directory from the end
    k = jnp.minimum(states >> _U(shift),
                    _U(dir_tab.shape[0] - 2)).astype(jnp.int32)
    lo = dir_tab[k]
    hi = dir_tab[k + 1]
    s_hi = (states >> _U(32)).astype(jnp.uint32)
    s_lo = states.astype(jnp.uint32)
    for _ in range(probes):
        mid = (lo + hi) >> 1
        g = pair[jnp.minimum(mid, n - 1)]
        ge = (g[..., 0] > s_hi) | ((g[..., 0] == s_hi) & (g[..., 1] >= s_lo))
        hi = jnp.where(ge, mid, hi)
        lo = jnp.where(ge, lo, mid + 1)
    idx = jnp.minimum(lo, max(n - 1, 0))
    g = pair[idx]
    found = (g[..., 0] == s_hi) & (g[..., 1] == s_lo)
    return idx.astype(jnp.int64), found
