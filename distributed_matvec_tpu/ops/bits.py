"""Device-side bit manipulation: popcount, shard hashing, state lookup.

JAX/XLA equivalents of the reference's hot scalar kernels:
  * ``hash64`` — splitmix64 finalizer (StatesEnumeration.chpl:122-127) used to
    route each generated state to its owning shard,
  * ``state_index_sorted`` — batched basis lookup replacing ``ls_hs_state_index``
    (FFI.chpl:173-175) with a vectorized binary search over the *sorted* local
    representative shard (shards are sorted by construction, so searchsorted is
    exact),
  * ``popcount64`` — sign-mask parity for the nonbranching term kernels.

All functions are shape-polymorphic, jit-safe, and uint64-clean (require
``jax_enable_x64``; on TPU XLA lowers 64-bit integer ops to u32 pairs).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["popcount64", "hash64", "shard_index", "state_index_sorted", "sign_from_parity"]

_U = jnp.uint64


def popcount64(x: jax.Array) -> jax.Array:
    return jax.lax.population_count(x.astype(jnp.uint64))


def sign_from_parity(x: jax.Array) -> jax.Array:
    """(−1)^popcount(x) as float (f64): +1 for even parity, −1 for odd."""
    return 1.0 - 2.0 * (popcount64(x) & _U(1)).astype(jnp.float64)


def hash64(x: jax.Array) -> jax.Array:
    """splitmix64 finalizer — bit-exact with enumeration.host.hash64."""
    x = x.astype(jnp.uint64)
    x = (x ^ (x >> _U(30))) * _U(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> _U(27))) * _U(0x94D049BB133111EB)
    return x ^ (x >> _U(31))


def shard_index(states: jax.Array, n_shards: int) -> jax.Array:
    """Owning device of each state (``localeIdxOf``, StatesEnumeration.chpl:129-136)."""
    if n_shards == 1:
        return jnp.zeros(states.shape, dtype=jnp.int32)
    return (hash64(states) % _U(n_shards)).astype(jnp.int32)


def state_index_sorted(sorted_reps: jax.Array, states: jax.Array):
    """(index, found) of each state in a sorted representative array.

    ``index`` is clipped into range; ``found`` marks exact hits.  The identity
    fast path of the reference (DistributedMatrixVector.chpl:86-95) is
    subsumed: XLA folds the search when the caller knows indices are trivial.
    """
    idx = jnp.searchsorted(sorted_reps, states)
    idx = jnp.clip(idx, 0, sorted_reps.shape[0] - 1)
    found = sorted_reps[idx] == states
    return idx.astype(jnp.int64), found
