"""SolveService — the long-running loop a spool directory is served by.

Composes the queue/pool/scheduler into the process ``apps/
solve_service.py`` runs: adopt spool arrivals, run batches, repeat —
either until the queue drains (``drain`` mode, the batch/CI shape) or
forever at a poll interval (``watch`` mode, the service shape).  A
latched SIGTERM (PR 6 preemption machinery) exits the loop at the next
block boundary with every in-flight job respooled as queued, and
:meth:`run` returns ``EXIT_PREEMPTED`` (75) so a supervisor relaunches
and resumes the undone work.
"""

from __future__ import annotations

import signal
import time
from typing import Optional

from ..obs import emit as obs_emit, flush as obs_flush
from ..obs.slo import check_slos
from ..utils import preempt
from ..utils.preempt import EXIT_PREEMPTED, Preempted
from .queue import JobQueue
from .scheduler import Scheduler

__all__ = ["SolveService"]


class SolveService:
    """One spool-serving process: ``run()`` returns a process exit code
    (0 drained/idle-stopped, 75 preempted)."""

    def __init__(self, serve_dir: str, scheduler: Optional[Scheduler] = None,
                 poll_s: float = 0.5, **scheduler_kwargs):
        self.serve_dir = serve_dir
        self.poll_s = float(poll_s)
        self.scheduler = scheduler if scheduler is not None else Scheduler(
            queue=JobQueue(serve_dir), **scheduler_kwargs)

    def run(self, drain: bool = False,
            max_idle_s: Optional[float] = None) -> int:
        """Serve the spool.  ``drain=True`` exits 0 once the queue is
        empty; otherwise the loop polls until ``max_idle_s`` of
        continuous idleness (None = forever) — and either way a latched
        SIGTERM/SIGINT exits 75 with in-flight jobs requeued."""
        preempt.ensure_installed(signals=(signal.SIGTERM, signal.SIGINT))
        sched = self.scheduler
        obs_emit("serve_start", serve_dir=self.serve_dir,
                 drain=bool(drain),
                 block_width=sched.block_width,
                 pool_max_bytes=int(sched.pool.max_bytes))
        idle_since = None
        finished = 0
        try:
            while True:
                n = sched.drain(scan_spool=True)
                finished += n
                if n:
                    # SLO pass at the batch boundary: evaluates the live
                    # event ring, emits slo_alert ONLY on firing/clear
                    # transitions — a healthy service's stream stays
                    # alert-free (obs/slo.py)
                    check_slos()
                if drain and sched.queue.pending() == 0:
                    break
                if n:
                    idle_since = None
                else:
                    now = time.monotonic()
                    idle_since = idle_since if idle_since is not None \
                        else now
                    if max_idle_s is not None \
                            and now - idle_since >= max_idle_s:
                        break
                    if preempt.requested():
                        raise Preempted("serve_loop", finished, None)
                    time.sleep(self.poll_s)
        except Preempted as e:
            # every in-flight job was requeued at the safe point (its
            # spool file never left queue/), so a relaunch resumes the
            # undone work — the job-level PR 6 checkpoint contract
            obs_emit("serve_preempted", serve_dir=self.serve_dir,
                     jobs_finished=finished,
                     jobs_pending=sched.queue.pending(),
                     solver=e.solver, exit_code=EXIT_PREEMPTED)
            obs_flush()
            return EXIT_PREEMPTED
        obs_emit("serve_end", serve_dir=self.serve_dir,
                 jobs_finished=finished,
                 engine_builds=sched.pool.builds,
                 engine_hits=sched.pool.hits,
                 engine_evictions=sched.pool.evictions)
        obs_flush()
        return 0
