"""Scheduler — admission, fingerprint grouping, batch packing, execution.

The unit of work here is a JOB STREAM, not a single solve:

* **Admission** is priced, not guessed: every submission is run through
  ``tools/capacity.price_job`` (the calibrated roofline rates of PR 7;
  when the autotuner has persisted a live-rate posterior for the spec's
  mode, those LEARNED rates win — DESIGN.md §30) and gets a verdict — ``accept`` (fits, runs within the accept
  horizon), ``queue`` (fits, but the priced backlog puts its start
  beyond the horizon — the verdict carries the ETA), or ``reject``
  (does not fit the device/host budgets at all, or cannot meet its
  deadline).  The device is never oversubscribed on a hunch.
* **Grouping**: queued jobs are grouped by :meth:`JobSpec.engine_key`;
  a batch takes up to ``serve_block_width`` jobs of ONE group (FIFO by
  the group's oldest submission, then job_id — deterministic packing),
  so same-basis requests share one warm engine from the
  :class:`~.pool.EnginePool`.
* **Execution**: the batch runs as ONE ``lanczos_block`` call with
  per-job ``column_targets`` — each job contributes a start column
  seeded by its own job_id, converges against its own (k, tol), and its
  column EXITS the batch when done (the block narrows; see
  ``solve/lanczos.py``).  Per-job results, latencies, and ``job`` spans
  land under the run's trace tree.
* **Preemption**: a SIGTERM latched by the PR 6 machinery surfaces as
  ``Preempted`` at a block boundary; the batch's unfinished jobs are
  requeued (their spool files never left ``queue/``) and the exception
  propagates so the service can exit 75 — the drain contract
  ``make serve-check`` gates.
"""

from __future__ import annotations

import importlib.util
import os
import time
from typing import Dict, List, Optional

import numpy as np

from ..obs import emit as obs_emit
from ..obs import gauge as obs_gauge
from ..obs import trace as obs_trace
from ..utils import preempt
from ..utils.config import get_config
from .pool import EnginePool
from .queue import DONE, FAILED, REJECTED, JobQueue
from .spec import JobSpec

__all__ = ["Scheduler", "load_capacity_module"]

_capacity = None


def load_capacity_module():
    """``tools/capacity.py`` as a module (tools/ is not a package; the
    pricing API lives there so the CLI and the scheduler share one
    model).  Cached — the import cost is paid once."""
    global _capacity
    if _capacity is None:
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))),
            "tools", "capacity.py")
        spec = importlib.util.spec_from_file_location("dmt_capacity", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _capacity = mod
    return _capacity


class Scheduler:
    """Admission + packing + execution over one queue and one pool."""

    def __init__(self, queue: Optional[JobQueue] = None,
                 pool: Optional[EnginePool] = None,
                 rates: Optional[dict] = None,
                 calibration_path: Optional[str] = None,
                 block_width: Optional[int] = None,
                 hbm_gb: float = 16.0, host_ram_gb: float = 64.0,
                 accept_horizon_s: Optional[float] = None,
                 mesh=None, live_devices: Optional[int] = None):
        cfg = get_config()
        self.queue = queue if queue is not None else JobQueue()
        self.pool = pool if pool is not None else EnginePool(
            mesh=mesh, live_devices=live_devices)
        #: override of the live topology admission prices against
        #: (default: the pool's view — mesh size, else the local device
        #: count at admit time)
        self.live_devices = live_devices
        self.block_width = int(block_width or cfg.serve_block_width)
        self.hbm_gb = float(hbm_gb)
        self.host_ram_gb = float(host_ram_gb)
        self.accept_horizon_s = float(
            accept_horizon_s if accept_horizon_s is not None
            else cfg.serve_accept_horizon_s)
        if rates is None:
            try:
                rates = load_capacity_module().load_rate_calibration(
                    calibration_path)
            except Exception:
                if calibration_path:    # an explicit path must not be
                    raise               # silently dropped
                rates = None
        self.rates = rates
        # the autotuner's persisted state (DESIGN.md §30): live-rate
        # posteriors override the static calibration inside price_job,
        # so admission prices what the hardware actually did once a
        # tuned engine has run — None cleanly when nothing is persisted
        try:
            self.tuning = load_capacity_module().load_tuning()
        except Exception:
            self.tuning = None
        self._backlog_s = 0.0          # priced est_solve_s of queued work
        self._est_s: Dict[str, float] = {}

    def live_device_count(self) -> int:
        """The topology admission prices against (see :meth:`admit`)."""
        if self.live_devices is not None:
            return int(self.live_devices)
        return self.pool.live_device_count()

    # -- admission ---------------------------------------------------------

    def admit(self, spec: JobSpec) -> dict:
        """Price one spec and return the admission verdict (also emitted
        as an ``admission`` event).  Does NOT enqueue — :meth:`submit`
        composes the two.

        Pricing runs against the LIVE device count, not the spec's
        original one: a job respooled from a service that ran at D
        devices re-admits after a relaunch at D′ against the capacity
        that actually exists (clamped mesh, re-priced apply/solve
        estimates) — the elastic-fleet contract the serve leg of
        ``make elastic-check`` gates."""
        cap = load_capacity_module()
        pricing = spec.pricing()
        live = self.live_device_count()
        asked = max(int(pricing.get("n_devices") or 1), 1)
        pricing["n_devices"] = max(1, min(asked, live))
        price = cap.price_job(pricing, calibration=self.rates,
                              hbm_gb=self.hbm_gb,
                              host_ram_gb=self.host_ram_gb,
                              tuning=self.tuning)
        eta_s = round(self._backlog_s, 3)
        if not price["fits"]:
            verdict = "reject"
            reason = price.get("reason") or "does not fit the device budget"
        elif (spec.deadline_s is not None
              and price.get("est_solve_s") is not None
              and eta_s + price["est_solve_s"] > float(spec.deadline_s)):
            verdict = "reject"
            reason = (f"priced finish {eta_s + price['est_solve_s']:.1f}s "
                      f"exceeds deadline {spec.deadline_s:.1f}s")
        elif eta_s > self.accept_horizon_s:
            verdict, reason = "queue", f"priced backlog {eta_s:.1f}s"
        else:
            verdict, reason = "accept", ""
        out = {"verdict": verdict, "eta_s": eta_s, "reason": reason,
               "live_devices": int(live),
               "priced_devices": int(pricing["n_devices"]),
               **{k: price.get(k) for k in
                  ("est_apply_ms", "est_solve_s", "fits", "rate_source")}}
        with obs_trace.job_scope(spec.job_id):
            obs_emit("admission", job_id=spec.job_id,
                     engine_key=spec.engine_key(), **{
                         k: v for k, v in out.items() if v is not None})
        return out

    def _admit_and_track(self, spec: JobSpec, enqueue: bool) -> dict:
        """The one admit -> reject-or-track path both submission routes
        share: price the spec, record a rejection terminally, otherwise
        fold its priced solve time into the backlog (and enqueue it when
        it is not already in the queue).  The spec instance is marked
        admitted, so a re-adopted (resubmitted) spec — a FRESH instance
        from the spool — is re-priced while an already-admitted queued
        one is not."""
        verdict = self.admit(spec)
        spec.__dict__["_admitted"] = True
        if verdict["verdict"] == "reject":
            self.queue.finish(spec, REJECTED, reason=verdict["reason"],
                              eta_s=verdict["eta_s"])
            return verdict
        if enqueue:
            self.queue.submit(spec)
        if verdict.get("est_solve_s") is not None:
            self._est_s[spec.job_id] = float(verdict["est_solve_s"])
            self._backlog_s += self._est_s[spec.job_id]
        return verdict

    def submit(self, spec: JobSpec) -> dict:
        """Admit + enqueue (or record the rejection).  Returns the
        verdict dict."""
        return self._admit_and_track(spec, enqueue=True)

    def adopt_spool(self) -> int:
        """Scan the spool for new ``--submit`` arrivals and run admission
        on each (a spooled job that does not fit is rejected with a
        terminal record, exactly like an API submission)."""
        adopted = self.queue.scan_spool()
        if adopted:
            for spec in list(self.queue.queued()):
                if not spec.__dict__.get("_admitted"):
                    self._admit_and_track(spec, enqueue=False)
        return adopted

    # -- packing -----------------------------------------------------------

    def next_batch(self) -> List[JobSpec]:
        """Up to ``block_width`` queued jobs of ONE (engine-key, solver)
        group: the group whose head job queued earliest goes first
        (FIFO fairness across groups), members ordered by (submit_ts,
        job_id) — deterministic, so a rerun of the same queue packs the
        same batches (the §26 bit-identity argument).  Dynamics jobs
        (solver kpm/evolve — DESIGN.md §29) group by the same engine
        key, so they still hit the warm engine a same-basis eigensolve
        built, but run ONE per batch: their state is a whole
        moment/trajectory recurrence, not a column of a shared block."""
        groups: Dict[tuple, List[JobSpec]] = {}
        for s in self.queue.queued():
            solver = getattr(s, "solver", "eigs") or "eigs"
            groups.setdefault((s.engine_key(), solver), []).append(s)
        if not groups:
            return []
        (_, solver), head = min(
            groups.items(),
            key=lambda kv: min((s.submit_ts, s.job_id) for s in kv[1]))
        head.sort(key=lambda s: (s.submit_ts, s.job_id))
        return head[: self.block_width if solver == "eigs" else 1]

    # -- execution ---------------------------------------------------------

    def run_batch(self, batch: List[JobSpec]) -> List[dict]:
        """One batched solve: acquire the group's engine, start columns
        seeded per job, per-job convergence targets, results recorded per
        job.  ``Preempted`` requeues the whole batch and propagates."""
        from ..solve import lanczos_block

        key = batch[0].engine_key()
        t_start = time.time()
        # in-flight width as a real gauge (reset on every exit path
        # below): the exporter's serve_batch_width and the job_event
        # payloads must tell the same story
        obs_gauge("serve_batch_width").set(len(batch))
        for spec in batch:
            self.queue.mark_running(spec, batch_width=len(batch))
        try:
            with obs_trace.span("serve_batch", kind="batch",
                                engine_key=key, jobs=len(batch)):
                eng = self.pool.acquire(batch[0])
                solver = getattr(batch[0], "solver", "eigs") or "eigs"
                if solver != "eigs":
                    return [self._run_dynamics(batch[0], eng, solver,
                                               t_start)]
                p = max(len(batch), max(int(s.k) for s in batch), 2)
                V0 = self._start_block(eng, batch, p)
                targets = [{"k": int(s.k), "tol": float(s.tol),
                            "max_iters": int(s.max_iters),
                            "job_id": s.job_id} for s in batch]
                res = lanczos_block(
                    eng.matvec,
                    n=None if V0 is not None else eng.n_states,
                    k=max(int(s.k) for s in batch),
                    block_size=p, V0=V0,
                    max_iters=max(int(s.max_iters) for s in batch),
                    tol=min(float(s.tol) for s in batch),
                    column_targets=targets)
                out = []
                now = time.time()
                for spec, cr in zip(batch, res.column_results or []):
                    rec = self._finish(
                        spec, DONE if cr["converged"] else FAILED,
                        t_start,
                        eigenvalues=[float(w) for w in
                                     np.atleast_1d(cr["eigenvalues"])],
                        residuals=[float(r) for r in
                                   np.atleast_1d(cr["residuals"])],
                        iters=int(cr["iters"]),
                        converged=bool(cr["converged"]),
                        batch_width=len(batch))
                    # per-job span: the job's in-batch execution window
                    # (batch start -> batch close), a CHILD of the still-
                    # open serve_batch span, envelope-stamped with the
                    # job's own id via job_scope
                    with obs_trace.job_scope(spec.job_id):
                        obs_trace.emit_span(
                            f"job:{spec.job_id}", "job", t0=t_start,
                            dur_ms=(now - t_start) * 1e3,
                            engine_key=key, iters=int(cr["iters"]))
                    out.append(rec)
                return out
        except preempt.Preempted:
            for spec in batch:
                self.queue.requeue(spec, reason="preempted")
            raise
        except Exception as e:              # noqa: BLE001 — one broken
            for spec in batch:              # batch must not kill the service
                self._finish(spec, FAILED, t_start, error=repr(e))
            obs_emit("serve_batch_failed", engine_key=key, error=repr(e))
            return [self.queue.result(s.job_id) for s in batch]
        finally:
            obs_gauge("serve_batch_width").set(0)

    def _run_dynamics(self, spec: JobSpec, eng, solver: str,
                      t_start: float) -> dict:
        """One dynamics job (solver kpm/evolve, DESIGN.md §29) on the
        group's warm engine — the engine acquisition, admission pricing
        and spool lifecycle are exactly the eigensolve path's; only the
        solver call differs.  ``Preempted`` propagates to the caller
        (requeue + exit 75 — the job-level checkpoint contract; a
        requeued dynamics job restarts from its spool file)."""
        from ..solve import kpm_dos, krylov_evolve

        if solver == "kpm":
            energies, rho, res = kpm_dos(
                eng.matvec, n_moments=int(spec.n_moments),
                n=int(eng.n_states), n_vectors=int(spec.n_vectors),
                seed=spec.column_seed())
            rec = self._finish(
                spec, DONE, t_start, solver="kpm", converged=True,
                bounds=[float(res.bounds[0]), float(res.bounds[1])],
                n_moments=int(spec.n_moments),
                moments_head=[float(m) for m in res.moments[:8]],
                dos_peak=float(np.max(rho)),
                moments_per_s=round(res.steady_moments_per_s, 3),
                iters=int(res.num_applies))
        else:
            res = krylov_evolve(
                eng.matvec, t_final=float(spec.t_final),
                n=int(eng.n_states), krylov_dim=int(spec.krylov_dim),
                tol=float(spec.tol), seed=spec.column_seed())
            rec = self._finish(
                spec, DONE, t_start, solver="evolve",
                converged=bool(res.times[-1]
                               >= float(spec.t_final) * (1 - 1e-12)),
                t=float(res.times[-1]), steps=int(res.num_steps),
                norm_drift=float(res.norm_drift),
                energy_drift=float(res.energy_drift),
                energy_final=float(res.energies[-1]),
                iters=int(res.num_applies))
        now = time.time()
        with obs_trace.job_scope(spec.job_id):
            obs_trace.emit_span(
                f"job:{spec.job_id}", "job", t0=t_start,
                dur_ms=(now - t_start) * 1e3,
                engine_key=spec.engine_key(), solver=solver)
        return rec

    def _finish(self, spec: JobSpec, status: str, t_start: float,
                **result) -> dict:
        self._backlog_s = max(
            0.0, self._backlog_s - self._est_s.pop(spec.job_id, 0.0))
        latency_ms = (time.time() - float(spec.submit_ts or t_start)) * 1e3
        return self.queue.finish(spec, status,
                                 latency_ms=round(latency_ms, 3), **result)

    def _start_block(self, eng, batch: List[JobSpec], p: int):
        """The batch's start block: column j is seeded by job j's
        :meth:`~.spec.JobSpec.column_seed` (extra columns past the job
        count — a job wanting k > len(batch) eigenpairs — are seeded off
        the first job's seed), so the block depends only on batch
        membership, never on wall-clock or scheduler timing."""
        seeds = [s.column_seed() for s in batch]
        seeds += [seeds[0] + 1 + i for i in range(p - len(seeds))]
        if hasattr(eng, "random_hashed"):       # hashed [D, M, p] layout
            import jax.numpy as jnp
            cols = [eng.random_hashed(seed=sd, cols=1) for sd in seeds]
            return jnp.concatenate(cols, axis=-1)
        n = int(eng.n_states)
        cols = [np.random.default_rng(sd).standard_normal(n)
                for sd in seeds]
        V0 = np.stack(cols, axis=1)
        return V0 / np.linalg.norm(V0, axis=0, keepdims=True)

    # -- drain loop --------------------------------------------------------

    def drain(self, scan_spool: bool = True) -> int:
        """Run batches until the queue is empty (adopting spool arrivals
        between batches).  Returns the number of jobs driven to a
        terminal state.  ``Preempted`` propagates after requeueing — the
        caller owns the exit code."""
        finished = 0
        while True:
            if scan_spool:
                self.adopt_spool()
            if preempt.requested():
                raise preempt.Preempted("serve_drain", finished, None)
            batch = self.next_batch()
            if not batch:
                return finished
            self.run_batch(batch)
            finished += len(batch)
