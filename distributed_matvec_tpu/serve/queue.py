"""Job queue — lifecycle state + the on-disk spool a service drains.

In-memory view: ordered ``queued`` jobs (FIFO by submission), a
``running`` set, and terminal results (``done`` / ``failed`` /
``rejected``).  Every transition emits ONE ``job_event`` telemetry event
(``{job_id, status, engine_key, ...}``) — the stream the
``obs_report watch`` queue panel renders live.

Optional spool directory (what ``apps/diagonalize.py --submit`` writes
into and ``apps/solve_service.py`` serves from)::

    <serve_dir>/queue/<job_id>.json    the spec, while queued OR running
    <serve_dir>/done/<job_id>.json     spec + result, terminal

A job's spool file stays under ``queue/`` until its TERMINAL transition
— deliberately: a service killed mid-batch (SIGTERM drain, SIGKILL, OOM)
leaves every in-flight job spooled as queued, so a relaunched service
resumes exactly the undone work with no recovery pass.  That is the
job-level analog of the PR 6 solver checkpoint contract (the solver
exits at a safe block boundary; the JOB restarts from its spec).
Result writes are atomic (``os.replace``), so readers never see a torn
terminal file.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

from ..obs import emit as obs_emit
from ..obs import gauge as obs_gauge
from ..obs.trace import job_scope
from .spec import JobSpec

__all__ = ["JobQueue", "QUEUED", "RUNNING", "DONE", "FAILED", "REJECTED",
           "submit_to_spool"]

QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
REJECTED = "rejected"
_TERMINAL = (DONE, FAILED, REJECTED)


def _spool_paths(serve_dir: str) -> tuple:
    return (os.path.join(serve_dir, "queue"),
            os.path.join(serve_dir, "done"))


def submit_to_spool(serve_dir: str, spec: JobSpec) -> str:
    """Write one spec into a spool directory (creating the layout) —
    the standalone submission path ``--submit`` uses; a running service
    picks the file up on its next scan.  Returns the spool path."""
    qdir, ddir = _spool_paths(serve_dir)
    os.makedirs(qdir, exist_ok=True)
    os.makedirs(ddir, exist_ok=True)
    if spec.submit_ts <= 0:
        spec.submit_ts = time.time()
    path = os.path.join(qdir, f"{spec.job_id}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(spec.to_json())
    os.replace(tmp, path)
    return path


class JobQueue:
    """Lifecycle bookkeeping for the scheduler, optionally mirrored to a
    spool directory."""

    def __init__(self, serve_dir: Optional[str] = None):
        self.serve_dir = serve_dir
        self._queued: List[JobSpec] = []      # FIFO
        self._running: Dict[str, JobSpec] = {}
        self._results: Dict[str, dict] = {}   # job_id -> terminal record
        self._status: Dict[str, str] = {}
        self._unreadable: Dict[str, tuple] = {}   # jid -> (size, mtime)
        self._spool_pending: Dict[str, dict] = {}  # terminal recs whose
        #   done/-write failed (full disk): retried per scan, and their
        #   queue/ files are NOT re-adopted as resubmissions meanwhile
        if serve_dir:
            qdir, ddir = _spool_paths(serve_dir)
            os.makedirs(qdir, exist_ok=True)
            os.makedirs(ddir, exist_ok=True)

    # -- submission / scanning --------------------------------------------

    def submit(self, spec: JobSpec, event: bool = True) -> None:
        if spec.job_id in self._status:
            raise ValueError(f"duplicate job_id {spec.job_id!r}")
        if spec.submit_ts <= 0:
            spec.submit_ts = time.time()
        self._queued.append(spec)
        self._status[spec.job_id] = QUEUED
        if self.serve_dir:
            submit_to_spool(self.serve_dir, spec)
        if event:
            self._event(spec, QUEUED)

    def scan_spool(self) -> int:
        """Pick up spool files this queue does not know yet (new
        ``--submit`` arrivals, or respooled in-flight jobs of a killed
        predecessor).  A queue/ file whose job_id is already TERMINAL is
        a RE-submission (``--submit`` overwrote it after the first run
        finished): the old result is discarded and the job runs again.
        An unreadable file is reported once per (size, mtime) — a
        watch-mode service polling every half-second must not emit an
        ``unreadable`` event per poll forever.  Returns how many specs
        were adopted."""
        if not self.serve_dir:
            return 0
        # retry terminal records whose done/-write failed before looking
        # at queue/ — while one is pending, its queue/ file is this
        # job's crash-safety net, not a resubmission
        for jid, rec in list(self._spool_pending.items()):
            if self._spool_finish(jid, rec):
                del self._spool_pending[jid]
        qdir, _ = _spool_paths(self.serve_dir)
        adopted = 0
        for name in sorted(os.listdir(qdir)):
            if not name.endswith(".json"):
                continue
            jid = name[: -len(".json")]
            if jid in self._spool_pending:
                continue
            status = self._status.get(jid)
            if status in (QUEUED, RUNNING):
                continue
            path = os.path.join(qdir, name)
            try:
                st = os.stat(path)
                stamp = (st.st_size, st.st_mtime_ns)
            except OSError:
                continue                     # raced with a finish()
            if self._unreadable.get(jid) == stamp:
                continue                     # known-bad, unchanged
            try:
                with open(path) as f:
                    spec = JobSpec.from_json(f.read())
            except (OSError, ValueError, TypeError, KeyError) as e:
                self._unreadable[jid] = stamp
                obs_emit("job_event", job_id=jid, status="unreadable",
                         error=repr(e))
                continue
            self._unreadable.pop(jid, None)
            resubmit = status is not None    # terminal -> run again
            if resubmit:
                self._results.pop(jid, None)
            self._queued.append(spec)
            self._status[spec.job_id] = QUEUED
            self._event(spec, QUEUED,
                        **({"resubmitted": True} if resubmit else {}))
            adopted += 1
        return adopted

    # -- views -------------------------------------------------------------

    def queued(self) -> List[JobSpec]:
        return list(self._queued)

    def running(self) -> List[JobSpec]:
        return list(self._running.values())

    def status(self, job_id: str) -> Optional[str]:
        return self._status.get(job_id)

    def result(self, job_id: str) -> Optional[dict]:
        return self._results.get(job_id)

    def pending(self) -> int:
        return len(self._queued) + len(self._running)

    # -- transitions -------------------------------------------------------

    def mark_running(self, spec: JobSpec, **info) -> None:
        self._queued = [s for s in self._queued if s.job_id != spec.job_id]
        self._running[spec.job_id] = spec
        self._status[spec.job_id] = RUNNING
        self._event(spec, RUNNING, **info)

    def requeue(self, spec: JobSpec, **info) -> None:
        """A running job back to the head of the queue (preemption drain:
        its spool file never left ``queue/``, so only the in-memory state
        moves)."""
        self._running.pop(spec.job_id, None)
        if self._status.get(spec.job_id) != QUEUED:
            self._queued.insert(0, spec)
            self._status[spec.job_id] = QUEUED
            self._event(spec, QUEUED, requeued=True, **info)

    def finish(self, spec: JobSpec, status: str, **result) -> dict:
        """Terminal transition: record the result, move the spool file
        from ``queue/`` to ``done/`` atomically."""
        if status not in _TERMINAL:
            raise ValueError(f"not a terminal status: {status!r}")
        self._running.pop(spec.job_id, None)
        self._queued = [s for s in self._queued if s.job_id != spec.job_id]
        rec = {"job_id": spec.job_id, "status": status,
               "spec": json.loads(spec.to_json()),
               "finish_ts": round(time.time(), 6), **result}
        self._results[spec.job_id] = rec
        self._status[spec.job_id] = status
        if self.serve_dir and not self._spool_finish(spec.job_id, rec):
            # an unwritable spool must not lose the run: keep the record
            # pending (retried per scan; its queue/ file is NOT treated
            # as a resubmission while pending)
            self._spool_pending[spec.job_id] = rec
        self._event(spec, status, **{k: v for k, v in result.items()
                                     if isinstance(v, (int, float, str,
                                                       bool))})
        return rec

    def _spool_finish(self, jid: str, rec: dict) -> bool:
        """Move one job's spool state to terminal: write ``done/``
        atomically, then drop the ``queue/`` file.  False on I/O
        failure (a ``spool_write_failed`` event is emitted)."""
        qdir, ddir = _spool_paths(self.serve_dir)
        out = os.path.join(ddir, f"{jid}.json")
        tmp = out + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(rec, f, sort_keys=True)
            os.replace(tmp, out)
            qf = os.path.join(qdir, f"{jid}.json")
            if os.path.exists(qf):
                os.remove(qf)
        except OSError as e:
            obs_emit("job_event", job_id=jid,
                     status="spool_write_failed", error=repr(e))
            return False
        return True

    # -- events ------------------------------------------------------------

    def _event(self, spec: JobSpec, status: str, **extra) -> None:
        # depth gauge rides every transition: the exporter's
        # job_queue_depth and the watch panel's queue line must agree
        obs_gauge("job_queue_depth").set(self.pending())
        # job_scope: the envelope job_id IS the job (payload job_id
        # fields are dropped by the envelope-wins rule) — the watch
        # queue panel and `obs_report trace` key per-job state on it
        with job_scope(spec.job_id):
            obs_emit("job_event", job_id=spec.job_id, status=status,
                     engine_key=spec.engine_key(), k=int(spec.k),
                     submit_ts=round(float(spec.submit_ts), 6), **extra)
