"""serve — the solve service: a job queue + scheduler multiplexing many
concurrent diagonalize requests over warm engines (DESIGN.md §26).

The production traffic shape is many small-to-medium solves, not one
giant one.  This package is the first layer whose unit of work is a
*job stream*: specs (:mod:`~.spec`) enter a queue (:mod:`~.queue`),
admission is priced by the calibrated capacity model
(``tools/capacity.price_job``), compatible jobs are grouped by engine
fingerprint and batched through ``lanczos_block``'s multi-RHS path with
per-job convergence targets (:mod:`~.scheduler`), engines stay warm in
an LRU byte-budgeted pool (:mod:`~.pool`), and the whole loop runs as a
preemption-safe service (:mod:`~.service`).

Quickstart::

    from distributed_matvec_tpu.serve import (JobSpec, JobQueue,
                                              EnginePool, Scheduler)
    sched = Scheduler()
    sched.submit(JobSpec(job_id="j0", basis={"number_spins": 12,
                                             "hamming_weight": 6}))
    sched.drain()
    sched.queue.result("j0")["eigenvalues"]

Load-generate with ``python bench.py --serve``; run a spool-backed
service with ``python apps/solve_service.py DIR``; submit from the CLI
with ``python apps/diagonalize.py model.yaml --submit --serve-dir DIR``.
"""

from .pool import EnginePool, build_engine, build_operator, engine_bytes
from .queue import (DONE, FAILED, QUEUED, REJECTED, RUNNING, JobQueue,
                    submit_to_spool)
from .scheduler import Scheduler, load_capacity_module
from .service import SolveService
from .spec import JobSpec, estimate_dimension

__all__ = [
    "JobSpec", "estimate_dimension",
    "JobQueue", "submit_to_spool",
    "QUEUED", "RUNNING", "DONE", "FAILED", "REJECTED",
    "EnginePool", "build_engine", "build_operator", "engine_bytes",
    "Scheduler", "load_capacity_module",
    "SolveService",
]
