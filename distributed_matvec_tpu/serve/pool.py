"""Engine pool — warm engines shared across jobs, LRU under a byte budget.

Engine acquisition is the expensive part of a small solve (structure
build / plan resolution; the content-addressed artifact + AOT caches of
PR 1 make a REBUILD cheap, but a resident engine is free).  The pool
holds built engines keyed by :meth:`JobSpec.engine_key` so every job of
a same-basis group shares ONE engine — device-resident tables, host-RAM
compressed plans, and cached AOT executables included — and evicts
least-recently-used engines when the resident bytes exceed the budget
(``serve_pool_gb``, the ``artifact_max_gb``-style knob of this layer).

Eviction drops the pool's reference; the device-memory ledger's weakref
finalizers (PR 4) release the tracked allocations when the engine is
collected, so pool occupancy and the ledger stay consistent.  Every
acquire/build/evict emits an ``engine_pool`` event — the watch panel's
occupancy line.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Optional

from ..obs import emit as obs_emit
from ..obs import gauge as obs_gauge
from ..utils.config import get_config
from .spec import JobSpec

__all__ = ["EnginePool", "build_operator", "build_engine", "engine_bytes"]


def build_operator(spec: JobSpec):
    """The spec's Hamiltonian: inline Heisenberg (basis + edges, chain
    when ``edges`` is None) or the yaml config's hamiltonian."""
    if spec.yaml is not None:
        from ..models.yaml_io import load_config_from_yaml
        cfg = load_config_from_yaml(spec.yaml, hamiltonian=True)
        if cfg.hamiltonian is None:
            raise ValueError(f"{spec.yaml}: config has no hamiltonian")
        return cfg.hamiltonian
    from ..models.basis import SpinBasis
    from ..models.lattices import chain_edges, heisenberg_from_edges
    basis = SpinBasis(**spec.basis)
    edges = (list(map(tuple, spec.edges)) if spec.edges is not None
             else chain_edges(int(spec.basis["number_spins"])))
    return heisenberg_from_edges(basis, edges)


def build_engine(spec: JobSpec, mesh=None, live_devices=None):
    """One engine for the spec: LocalEngine for single-device non-streamed
    jobs, DistributedEngine otherwise (``mesh`` — e.g. a rank-local mesh
    on the 2-proc CPU rig — wins over ``n_devices``).

    ``live_devices`` clamps the spec's requested mesh to the CURRENT
    topology: a spec respooled from a service that ran at D devices must
    still build after a relaunch at D′ < D (the elastic-fleet contract —
    the job re-admits and runs on what exists, it does not crash asking
    for departed hardware)."""
    op = build_operator(spec)
    n_devices = int(spec.n_devices or 0)
    if live_devices is not None and n_devices > int(live_devices):
        obs_emit("engine_clamp", job_id=spec.job_id,
                 requested_devices=n_devices,
                 live_devices=int(live_devices))
        n_devices = int(live_devices)
    if mesh is None and n_devices in (0, 1) \
            and spec.mode not in ("streamed", "hybrid"):
        from ..parallel.engine import LocalEngine
        return LocalEngine(op, mode=spec.mode)
    from ..parallel.distributed import DistributedEngine
    return DistributedEngine(op, mesh=mesh,
                             n_devices=None if mesh is not None
                             else (n_devices or 1),
                             mode=spec.mode)


def engine_bytes(eng) -> int:
    """Resident footprint the budget counts: device structure tables
    plus the streamed mode's host-RAM plan (encoded bytes)."""
    total = 0
    for attr in ("ell_nbytes", "plan_bytes"):
        try:
            total += int(getattr(eng, attr, 0) or 0)
        except (TypeError, ValueError):
            pass
    return total


class EnginePool:
    """LRU of warm engines keyed by engine fingerprint.

    ``live_devices`` (default: the mesh size, else
    ``jax.local_device_count()`` at acquire time) is the pool's view of
    the CURRENT topology: a warm engine whose mesh no longer fits —
    built at D, the fleet shrank to D′ < D — is dropped on its next
    acquire and rebuilt clamped to the live capacity, instead of
    dispatching collectives onto departed devices."""

    def __init__(self, max_bytes: Optional[int] = None, mesh=None,
                 builder: Optional[Callable] = None,
                 live_devices: Optional[int] = None):
        if max_bytes is None:
            max_bytes = int(get_config().serve_pool_gb * 1e9)
        self.max_bytes = int(max_bytes)
        self.mesh = mesh
        self.live_devices = live_devices
        self._builder = builder or (lambda spec: build_engine(
            spec, mesh=self.mesh, live_devices=self.live_device_count()))
        self._engines: "OrderedDict[str, object]" = OrderedDict()
        self._bytes: dict = {}
        self.builds = 0
        self.hits = 0
        self.evictions = 0

    def live_device_count(self) -> int:
        """The current topology the pool serves on."""
        if self.live_devices is not None:
            return int(self.live_devices)
        if self.mesh is not None:
            return int(self.mesh.devices.size)
        import jax
        return int(jax.local_device_count())

    # -- introspection -----------------------------------------------------

    def total_bytes(self) -> int:
        return sum(self._bytes.values())

    def __len__(self) -> int:
        return len(self._engines)

    def __contains__(self, key: str) -> bool:
        return key in self._engines

    def keys(self):
        return list(self._engines)

    # -- acquire / evict ---------------------------------------------------

    def acquire(self, spec: JobSpec):
        """The warm engine for ``spec`` (LRU-refreshed), building on miss
        and evicting LRU engines past the byte budget.  The just-built
        engine is never evicted by its own insertion — a single engine
        larger than the budget still serves its batch (and is evicted by
        the NEXT insertion)."""
        key = spec.engine_key()
        eng = self._engines.get(key)
        if eng is not None and not self._mesh_ok(eng, spec):
            # the fleet resized under a warm engine: its mesh spans
            # devices that no longer exist (shrink), OR it was built
            # clamped during an earlier shrink and the fleet has since
            # regrown (a 1-device engine must not serve a spec that
            # would get 4 today — admission prices the LIVE capacity,
            # the engine must match it) — drop and rebuild
            self._engines.pop(key, None)
            freed = self._bytes.pop(key, 0)
            self.evictions += 1
            self._event("evict", key, freed_bytes=int(freed),
                        reason="mesh_mismatch",
                        engine_devices=int(getattr(eng, "n_devices", 1)
                                           or 1),
                        live_devices=self.live_device_count())
            eng = None
        if eng is not None:
            self._engines.move_to_end(key)
            self.hits += 1
            # between-jobs is a safe re-key boundary (DESIGN.md §30): a
            # live-tuned engine whose drift check proposed new knobs
            # re-plans HERE, never inside a caller's apply sequence
            retune = getattr(eng, "maybe_retune", None)
            if retune is not None:
                try:
                    if retune():
                        # the re-key rebuilt the plan — refresh the
                        # budget's view of this engine's footprint
                        self._bytes[key] = engine_bytes(eng)
                        self._evict(keep=key)
                except Exception:  # a failed re-key keeps the old plan
                    pass
            self._event("hit", key)
            return eng
        eng = self._builder(spec)
        self.builds += 1
        self._engines[key] = eng
        self._bytes[key] = engine_bytes(eng)
        self._evict(keep=key)
        self._event("build", key)
        return eng

    def _mesh_ok(self, eng, spec: JobSpec) -> bool:
        """Whether a warm engine's mesh matches what ``spec`` would be
        built at TODAY: not spanning departed devices (shrink), and not
        smaller than ``min(spec.n_devices, live)`` (an engine clamped
        during a shrink must be rebuilt once the fleet regrows, or the
        pool serves under-sized engines forever while admission prices
        the full live capacity).  With a fixed ``mesh`` supplied, builds
        always use that mesh, so both conditions hold by construction."""
        live = self.live_device_count()
        have = int(getattr(eng, "n_devices", 1) or 1)
        if have > live:
            return False
        want = int(spec.n_devices or 0)
        return not (want and have < min(want, live))

    def _evict(self, keep: str) -> None:
        while self.total_bytes() > self.max_bytes and len(self._engines) > 1:
            victim = next(k for k in self._engines if k != keep)
            self._engines.pop(victim)
            freed = self._bytes.pop(victim, 0)
            self.evictions += 1
            self._event("evict", victim, freed_bytes=int(freed))

    def _event(self, event: str, key: str, **extra) -> None:
        # gauges mirror the event payload so the OpenMetrics exporter and
        # the watch panel read the SAME values (ISSUE 17: pool occupancy
        # existed only as events before)
        obs_gauge("engine_pool_bytes").set(self.total_bytes())
        obs_gauge("engine_pool_max_bytes").set(self.max_bytes)
        obs_gauge("engine_pool_engines").set(len(self._engines))
        obs_emit("engine_pool", event=event, engine_key=key,
                 engine_bytes=int(self._bytes.get(key, 0)),
                 pool_bytes=int(self.total_bytes()),
                 pool_max_bytes=int(self.max_bytes),
                 engines=len(self._engines), builds=self.builds,
                 hits=self.hits, evictions=self.evictions, **extra)
