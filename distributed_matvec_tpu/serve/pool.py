"""Engine pool — warm engines shared across jobs, LRU under a byte budget.

Engine acquisition is the expensive part of a small solve (structure
build / plan resolution; the content-addressed artifact + AOT caches of
PR 1 make a REBUILD cheap, but a resident engine is free).  The pool
holds built engines keyed by :meth:`JobSpec.engine_key` so every job of
a same-basis group shares ONE engine — device-resident tables, host-RAM
compressed plans, and cached AOT executables included — and evicts
least-recently-used engines when the resident bytes exceed the budget
(``serve_pool_gb``, the ``artifact_max_gb``-style knob of this layer).

Eviction drops the pool's reference; the device-memory ledger's weakref
finalizers (PR 4) release the tracked allocations when the engine is
collected, so pool occupancy and the ledger stay consistent.  Every
acquire/build/evict emits an ``engine_pool`` event — the watch panel's
occupancy line.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Optional

from ..obs import emit as obs_emit
from ..utils.config import get_config
from .spec import JobSpec

__all__ = ["EnginePool", "build_operator", "build_engine", "engine_bytes"]


def build_operator(spec: JobSpec):
    """The spec's Hamiltonian: inline Heisenberg (basis + edges, chain
    when ``edges`` is None) or the yaml config's hamiltonian."""
    if spec.yaml is not None:
        from ..models.yaml_io import load_config_from_yaml
        cfg = load_config_from_yaml(spec.yaml, hamiltonian=True)
        if cfg.hamiltonian is None:
            raise ValueError(f"{spec.yaml}: config has no hamiltonian")
        return cfg.hamiltonian
    from ..models.basis import SpinBasis
    from ..models.lattices import chain_edges, heisenberg_from_edges
    basis = SpinBasis(**spec.basis)
    edges = (list(map(tuple, spec.edges)) if spec.edges is not None
             else chain_edges(int(spec.basis["number_spins"])))
    return heisenberg_from_edges(basis, edges)


def build_engine(spec: JobSpec, mesh=None):
    """One engine for the spec: LocalEngine for single-device non-streamed
    jobs, DistributedEngine otherwise (``mesh`` — e.g. a rank-local mesh
    on the 2-proc CPU rig — wins over ``n_devices``)."""
    op = build_operator(spec)
    if mesh is None and spec.n_devices in (0, 1) \
            and spec.mode != "streamed":
        from ..parallel.engine import LocalEngine
        return LocalEngine(op, mode=spec.mode)
    from ..parallel.distributed import DistributedEngine
    return DistributedEngine(op, mesh=mesh,
                             n_devices=None if mesh is not None
                             else (spec.n_devices or 1),
                             mode=spec.mode)


def engine_bytes(eng) -> int:
    """Resident footprint the budget counts: device structure tables
    plus the streamed mode's host-RAM plan (encoded bytes)."""
    total = 0
    for attr in ("ell_nbytes", "plan_bytes"):
        try:
            total += int(getattr(eng, attr, 0) or 0)
        except (TypeError, ValueError):
            pass
    return total


class EnginePool:
    """LRU of warm engines keyed by engine fingerprint."""

    def __init__(self, max_bytes: Optional[int] = None, mesh=None,
                 builder: Optional[Callable] = None):
        if max_bytes is None:
            max_bytes = int(get_config().serve_pool_gb * 1e9)
        self.max_bytes = int(max_bytes)
        self.mesh = mesh
        self._builder = builder or (lambda spec: build_engine(spec,
                                                              mesh=self.mesh))
        self._engines: "OrderedDict[str, object]" = OrderedDict()
        self._bytes: dict = {}
        self.builds = 0
        self.hits = 0
        self.evictions = 0

    # -- introspection -----------------------------------------------------

    def total_bytes(self) -> int:
        return sum(self._bytes.values())

    def __len__(self) -> int:
        return len(self._engines)

    def __contains__(self, key: str) -> bool:
        return key in self._engines

    def keys(self):
        return list(self._engines)

    # -- acquire / evict ---------------------------------------------------

    def acquire(self, spec: JobSpec):
        """The warm engine for ``spec`` (LRU-refreshed), building on miss
        and evicting LRU engines past the byte budget.  The just-built
        engine is never evicted by its own insertion — a single engine
        larger than the budget still serves its batch (and is evicted by
        the NEXT insertion)."""
        key = spec.engine_key()
        eng = self._engines.get(key)
        if eng is not None:
            self._engines.move_to_end(key)
            self.hits += 1
            self._event("hit", key)
            return eng
        eng = self._builder(spec)
        self.builds += 1
        self._engines[key] = eng
        self._bytes[key] = engine_bytes(eng)
        self._evict(keep=key)
        self._event("build", key)
        return eng

    def _evict(self, keep: str) -> None:
        while self.total_bytes() > self.max_bytes and len(self._engines) > 1:
            victim = next(k for k in self._engines if k != keep)
            self._engines.pop(victim)
            freed = self._bytes.pop(victim, 0)
            self.evictions += 1
            self._event("evict", victim, freed_bytes=int(freed))

    def _event(self, event: str, key: str, **extra) -> None:
        obs_emit("engine_pool", event=event, engine_key=key,
                 engine_bytes=int(self._bytes.get(key, 0)),
                 pool_bytes=int(self.total_bytes()),
                 pool_max_bytes=int(self.max_bytes),
                 engines=len(self._engines), builds=self.builds,
                 hits=self.hits, evictions=self.evictions, **extra)
