"""Job specs — the unit of work the solve service schedules.

A :class:`JobSpec` is a config-like description of ONE diagonalize
request: the model (either an inline ``basis`` + ``edges`` pair for the
Heisenberg family, or a ``yaml`` config path for anything
``load_config_from_yaml`` handles), the solver targets (``k``, ``tol``,
``max_iters``), and the engine shape (``mode``, ``n_devices``).  Specs
are plain JSON (the spool-file format ``apps/diagonalize.py --submit``
writes and the service reads), and every spec carries a ``job_id`` — the
PR 9 namespacing key all of its telemetry is stamped with.

The scheduling key is :meth:`JobSpec.engine_key`: a content hash of
every field that determines the ENGINE a job needs (model + mode +
device count — not the solver targets).  Two specs with equal keys can
share one warm engine from the pool and batch through
``lanczos_block``'s multi-RHS path; the key is a pure function of the
spec, so grouping never has to build a basis first.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import os
from dataclasses import dataclass
from typing import Optional

__all__ = ["JobSpec", "estimate_dimension"]


@dataclass
class JobSpec:
    """One diagonalize request.  ``basis``/``edges`` describe an inline
    Heisenberg model (``edges=None`` = periodic chain over
    ``number_spins`` sites); ``yaml`` points at a config file instead.
    Exactly one of the two model sources must be present."""

    job_id: str
    # -- model (one of) ----------------------------------------------------
    basis: Optional[dict] = None       # SpinBasis kwargs
    edges: Optional[list] = None       # [[i, j], ...]; None = chain
    yaml: Optional[str] = None         # config path (diagonalize --submit)
    # -- solver targets ----------------------------------------------------
    #: solver kind: ``eigs`` (lowest-k eigenpairs, the batched
    #: lanczos_block path), ``kpm`` (Chebyshev/KPM spectral density) or
    #: ``evolve`` (Krylov exp(-iHt) time evolution) — DESIGN.md §29.
    #: Dynamics jobs share the SAME warm engines (grouped by engine_key
    #: like everything else) but run one job per batch: their state is a
    #: trajectory, not a column of a shared block.
    solver: str = "eigs"
    k: int = 1
    tol: float = 1e-10
    max_iters: int = 400
    seed: Optional[int] = None         # start-column seed; None = from job_id
    # -- dynamics targets (solver="kpm" / "evolve") ------------------------
    n_moments: int = 256               # kpm: Chebyshev moment count
    n_vectors: int = 4                 # kpm: stochastic-trace columns
    t_final: float = 1.0               # evolve: trajectory length
    krylov_dim: int = 24               # evolve: per-step Krylov dimension
    # -- engine shape ------------------------------------------------------
    mode: str = "ell"
    n_devices: int = 0                 # 0/1 = LocalEngine (unless streamed)
    # -- admission hints ---------------------------------------------------
    n_states: Optional[int] = None     # exact dimension when the caller
    #   knows it; None = admission prices the un-reduced upper bound
    deadline_s: Optional[float] = None  # reject when the priced
    #   queue-wait + solve time exceeds this
    submit_ts: float = 0.0             # stamped by the queue at submission

    def __post_init__(self):
        if not self.job_id:
            raise ValueError("JobSpec needs a job_id")
        if (self.yaml is None) == (self.basis is None):
            raise ValueError(
                "JobSpec needs exactly one model source: inline "
                "basis(+edges) or a yaml config path")
        if self.solver not in ("eigs", "kpm", "evolve"):
            raise ValueError(
                f"unknown solver kind {self.solver!r} "
                "(use eigs | kpm | evolve)")
        if self.solver == "kpm":
            if int(self.n_moments) < 2:
                raise ValueError("kpm jobs need n_moments >= 2")
            if int(self.n_vectors) < 1:
                raise ValueError("kpm jobs need n_vectors >= 1")
        if self.solver == "evolve":
            if not float(self.t_final) > 0.0:
                raise ValueError("evolve jobs need t_final > 0")
            if int(self.krylov_dim) < 2:
                raise ValueError("evolve jobs need krylov_dim >= 2")

    # -- scheduling --------------------------------------------------------

    def engine_key(self) -> str:
        """Content hash of the fields that determine the ENGINE this job
        runs on (model + mode + mesh size).  Solver targets (k, tol,
        iteration budget, seed) are deliberately excluded: jobs that
        differ only there still share one warm engine and batch.

        A yaml model is keyed by the FILE'S CONTENT, not its path — an
        edited model must never hit the pool's warm engine for the old
        Hamiltonian (the same contract as the PR 1 content-addressed
        caches).  The content is hashed once per spec instance (cached),
        so one spec's grouping decisions stay consistent even if the
        file changes while the job is queued."""
        cached = self.__dict__.get("_engine_key")
        if cached is not None:
            return cached
        if self.yaml is not None:
            try:
                with open(self.yaml, "rb") as f:
                    yaml_id = hashlib.sha256(f.read()).hexdigest()
            except OSError:
                # unreadable at keying time: fall back to the path (the
                # job will fail loudly at build time anyway)
                yaml_id = "path:" + os.path.abspath(self.yaml)
        else:
            yaml_id = None
        ident = {
            "basis": dict(sorted(self.basis.items())) if self.basis else None,
            "edges": sorted(map(tuple, self.edges))
            if self.edges is not None else None,
            "yaml": yaml_id,
            "mode": self.mode,
            "n_devices": int(self.n_devices),
        }
        h = hashlib.sha256(
            json.dumps(ident, sort_keys=True, default=list).encode())
        self.__dict__["_engine_key"] = h.hexdigest()[:16]
        return self.__dict__["_engine_key"]

    def column_seed(self) -> int:
        """The deterministic seed of this job's start column: explicit
        ``seed`` wins, else a stable hash of the job_id — so a job's
        column data depends only on the job itself, never on scheduler
        timing (the §26 bit-identity argument)."""
        if self.seed is not None:
            return int(self.seed)
        return int.from_bytes(
            hashlib.sha256(self.job_id.encode()).digest()[:4], "big")

    # -- admission pricing inputs -----------------------------------------

    def pricing(self) -> dict:
        """The mapping ``tools/capacity.price_job`` consumes: dimension
        (exact when carried, else the un-reduced upper bound), term
        count, mode, devices, solver budget.  Pure spec arithmetic — no
        basis build."""
        n = self.n_states
        num_terms = None
        group_order = 1
        if self.basis is not None:
            ns = int(self.basis.get("number_spins", 0))
            if n is None:
                n = estimate_dimension(self.basis)
            # Heisenberg off-diagonal terms: one σ⁺σ⁻ + σ⁻σ⁺ pair per
            # edge (the chain default has one edge per site)
            num_terms = 2 * (len(self.edges) if self.edges is not None
                             else ns)
            # |G| estimate for the hybrid recompute pricing (DESIGN.md
            # §28): the product of the generator orders — exact for the
            # standard chain sectors (translation · reversal ·
            # inversion), an upper bound in general, which is the
            # CONSERVATIVE direction (overpriced recompute biases the
            # split toward streaming)
            for perm, _sector in self.basis.get("symmetries") or ():
                seen, order = set(), 1
                for start in range(len(perm)):
                    if start in seen:
                        continue
                    clen, j = 0, start
                    while j not in seen:
                        seen.add(j)
                        j = perm[j]
                        clen += 1
                    order = order * clen // math.gcd(order, clen)
                group_order *= max(order, 1)
            if self.basis.get("spin_inversion"):
                group_order *= 2
        return {"n_states": n, "num_terms": num_terms,
                "mode": self.mode, "n_devices": max(int(self.n_devices), 1),
                "pair": False, "k": int(self.k),
                "max_iters": int(self.max_iters),
                "group_order": int(group_order),
                # dynamics pricing inputs (price_job converts moment /
                # trajectory budgets into matvec-column counts at the
                # same calibrated est ms/apply eigensolves price at)
                "solver": self.solver,
                "n_moments": int(self.n_moments),
                "n_vectors": int(self.n_vectors),
                "t_final": float(self.t_final),
                "krylov_dim": int(self.krylov_dim)}

    # -- JSON --------------------------------------------------------------

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "JobSpec":
        data = json.loads(text)
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in data.items() if k in names})


def estimate_dimension(basis_args: dict) -> int:
    """Upper bound on a SpinBasis dimension without building it: the
    Hamming-sector binomial (or 2^n), NOT reduced by symmetries — a
    conservative admission estimate (a job admitted against the bound
    certainly fits its reduced basis; the measured calibration wins once
    an engine exists)."""
    n = int(basis_args.get("number_spins", 0))
    hw = basis_args.get("hamming_weight")
    dim = math.comb(n, int(hw)) if hw is not None else 2 ** n
    if basis_args.get("spin_inversion"):
        dim = max(dim // 2, 1)
    return int(dim)
