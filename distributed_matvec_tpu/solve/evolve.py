"""Krylov ``exp(-iHt)`` time evolution over the engines (DESIGN.md §29).

Each accepted step projects the propagator onto a small Krylov space:
``psi(t + dt) ~= ||psi|| * V_m exp(-i dt T_m) e_1`` with ``V_m`` built by
``m`` eager engine applies (Lanczos with one full reorthogonalization
pass — m is small, the matmuls are trivial next to the matvec) and
``T_m`` the m-by-m real symmetric tridiagonal, exponentiated on the host
through its eigendecomposition.

Complex states on REAL-sector engines ride the multi-RHS path: a real
Hamiltonian acts on Re and Im independently, so ``psi`` is applied as a
2-column real block ``[Re psi, Im psi]`` — ONE engine apply per Krylov
vector, and a streamed engine streams each plan chunk once per apply
with its plan built once for the whole trajectory.  Complex-sector
engines (native c128 on CPU) consume complex states directly.
Pair-mode engines (the TPU (re, im) form) are refused with a pointer.

Adaptive stepping is free of extra applies: the Krylov basis is valid
for ANY dt, so a rejected step only re-exponentiates the SAME small T
at dt/2 — the residual-based local error estimate
``err(dt) = beta_m * |[exp(-i dt T)]_{m,1}|`` (Saad '92) prices the
step before the state is committed.  Acceptance is deterministic in the
state, so trajectories are reproducible and a preempted-and-resumed run
(checkpoint restores psi, t, dt bit-exactly) continues bit-consistent
with the uninterrupted one.

Telemetry: per-step ``evolve_trace`` events carry t, dt, the error
estimate, the norm drift ``| ||psi|| - 1 |`` (the propagator is unitary;
drift is pure roundoff and a numerical-health signal) and the energy
drift ``|E(t) - E(0)|`` (H commutes with its own propagator; the
recurrence's first alpha is <psi|H|psi> for free).  Solver contracts
match the eigensolvers: preemption latch at accepted-step boundaries,
checkpoint/resume through the shared topology-portable machinery, and
``solve > iteration > apply`` spans.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import memory as obs_memory
from ..obs import trace as obs_trace
from ..obs.events import emit as obs_emit, flush as obs_flush, obs_enabled
from ..utils import faults, preempt
from .lanczos import (_operator_key, _rand_like, _restore_ckpt,
                      _sharded_ckpt_engine, _soft_save_ckpt)

__all__ = ["EvolveResult", "krylov_evolve"]

#: breakdown threshold: a residual norm this far below the state scale
#: means the Krylov space closed and exp(-i dt T) is exact ("happy
#: breakdown" — the step is accepted with zero error estimate)
_BREAKDOWN = 1e-14


@dataclass
class EvolveResult:
    psi: object                     # final state, engine layout, complex
    times: np.ndarray               # [steps + 1] accepted times (t_0 = 0)
    energies: np.ndarray            # [steps + 1] <psi|H|psi> trajectory
    norm_drift: float               # max | ||psi|| - 1 | over the run
    energy_drift: float             # max |E(t) - E(0)| / max(1, |E(0)|)
    num_steps: int
    num_applies: int
    num_rejects: int = 0
    resumed_from: int = 0           # accepted steps restored from ckpt
    observables: Optional[dict] = None   # name -> [(t, value), ...]
    first_step_seconds: float = 0.0
    steady_seconds: float = 0.0

    @property
    def steady_steps_per_s(self) -> float:
        """Accepted-step rate over the steady window: steps taken THIS
        run (checkpoint-restored ones cost this run nothing) minus the
        compile-bearing first."""
        rest = self.num_steps - self.resumed_from - 1
        if rest > 0 and self.steady_seconds > 0:
            return rest / self.steady_seconds
        return 0.0


def krylov_evolve(matvec: Callable, psi0=None, t_final: float = 1.0,
                  **kwargs) -> EvolveResult:
    """Solve-span wrapper over :func:`_krylov_evolve_impl` (full
    contract there): the trajectory is ONE ``solve`` span, each accepted
    step an ``iteration`` span, the eager engine applies nest as
    ``apply`` spans."""
    with obs_trace.span("evolve", kind="solve", t_final=float(t_final)):
        return _krylov_evolve_impl(matvec, psi0=psi0, t_final=t_final,
                                   **kwargs)


def _krylov_evolve_impl(
    matvec: Callable,
    psi0=None,
    t_final: float = 1.0,
    n: Optional[int] = None,
    dt0: Optional[float] = None,
    krylov_dim: int = 24,
    tol: float = 1e-12,
    seed: int = 0,
    max_steps: Optional[int] = None,
    checkpoint_path: Optional[str] = None,
    checkpoint_every: int = 8,
    observables=None,
    obs_every: int = 1,
) -> EvolveResult:
    """Evolve ``psi0`` under ``exp(-i H t)`` to ``t_final``.

    ``psi0`` is a state in the matvec's layout (real or complex; None
    draws a seeded normalized random state — useful for dynamical
    correlation baselines).  ``tol`` is the local-error budget PER UNIT
    TIME: a step of size dt is accepted when its Krylov residual
    estimate is below ``tol * dt``, so the accumulated error over the
    trajectory is ~``tol * t_final``.  ``dt0`` seeds the adaptive step
    (default ``t_final / 16``); accepted steps grow by sqrt(2) while the
    estimate stays an order under budget, rejected steps halve and
    re-exponentiate the same basis (no extra applies).  ``max_steps``
    bounds the accepted-step count (the remaining trajectory is simply
    not taken — a budget exit, reported unfinished via
    ``times[-1] < t_final``).

    ``observables`` is a list of ``models/observables.BoundObservable``
    (or ``(name, callable)`` pairs) evaluated against the state every
    ``obs_every`` accepted steps; values land in
    :attr:`EvolveResult.observables`.

    Checkpoint/resume (``checkpoint_path``): the state + (t, dt, step,
    drift) are written through the shared topology-portable machinery
    every ``checkpoint_every`` accepted steps and at preemption; the
    fingerprint bakes in the operator key, layout, dtype and the
    (t_final, tol, krylov_dim) plan.  Restores are bit-exact, and step
    acceptance is deterministic in the state — a resumed trajectory is
    bit-consistent with an uninterrupted one (gated by
    ``make dynamics-check``).
    """
    from .kpm import _refuse_pair

    owner = getattr(matvec, "__self__", None)
    _refuse_pair(owner, "krylov_evolve")
    t_final = float(t_final)
    if not t_final > 0.0:
        raise ValueError(f"t_final must be > 0, got {t_final}")
    m_cap = max(int(krylov_dim), 2)

    def raw_mv(x):
        y = matvec(x)
        return y[0] if isinstance(y, tuple) else y

    if psi0 is None:
        if owner is not None and hasattr(owner, "random_hashed"):
            psi0 = owner.random_hashed(seed)
        elif n is not None:
            psi0 = _rand_like((n,), np.float64, seed)
        else:
            raise ValueError("pass psi0 or n")
    psi = jnp.asarray(psi0)
    # complex support: a REAL-sector engine gets the 2-column real
    # trick, a complex-sector (c128) engine runs native.  Engine-backed
    # matvecs answer this STATICALLY (operator.effective_is_real /
    # engine dtype — the same rule models/observables applies), so no
    # probe apply is spent; only a bare callable pays one probe (on a
    # giant streamed engine an apply streams the whole plan)
    if owner is not None:
        from ..models.observables import _complex_native
        complex_native = _complex_native(owner)
        napply = 0
    else:
        probe = raw_mv(psi.real if jnp.iscomplexobj(psi) else psi)
        complex_native = jnp.iscomplexobj(probe)
        napply = 1
        del probe
    cdtype = jnp.promote_types(jnp.complex128, psi.dtype)
    psi = psi.astype(cdtype)
    shape = psi.shape

    if complex_native:
        def apply_c(z):
            return raw_mv(z).astype(cdtype)
    else:
        def apply_c(z):
            # ONE engine apply of the 2-column real block [Re z, Im z]:
            # a real H acts on the parts independently, and the block
            # rides the same multi-RHS path lanczos_block batches
            # through (a streamed plan chunk uploads once per apply)
            blk = jnp.stack([jnp.real(z), jnp.imag(z)], axis=-1)
            w = raw_mv(blk)
            return (w[..., 0] + 1j * w[..., 1]).astype(cdtype)

    nrm0 = float(jnp.sqrt(jnp.real(jnp.vdot(psi, psi))))
    if not np.isfinite(nrm0) or nrm0 <= 0.0:
        raise ValueError("psi0 has no norm")
    psi = psi / nrm0

    dt = float(dt0) if dt0 else t_final / 16.0
    dt_max = t_final / 2.0
    t = 0.0
    step = 0
    rejects = 0
    norm_drift = 0.0
    energy_drift = 0.0
    e0_ref: Optional[float] = None
    times: List[float] = [0.0]
    energies: List[float] = []
    obs_vals: dict = {}
    obs_list = []
    for o in (observables or ()):
        if hasattr(o, "expectation"):
            obs_list.append((getattr(o, "name", None) or "observable",
                             o.expectation))
        else:
            obs_list.append((o[0], o[1]))

    agree_multi = jax.process_count() > 1 and (
        owner is None or bool(getattr(owner, "_multi", True)))
    preempt.ensure_installed()

    hashed_layout = _sharded_ckpt_engine(owner, shape)
    base = (f"hashed{tuple(shape[2:])}" if hashed_layout
            else f"{tuple(shape)}")
    # seed is part of the trajectory identity: a rerun with a different
    # --seed must start fresh, never restore another start state's
    # trajectory.  An EXPLICIT psi0 keys by seed too (its content is
    # not hashed — fetching a sharded state just to fingerprint it
    # would cost a full D2H pass); reruns that change psi0 under the
    # same path are the caller's responsibility, the same contract as
    # bare-callable Lanczos checkpoints.
    ckpt_fp = (f"{base}|{np.dtype(cdtype).str}|{_operator_key(owner)}"
               f"|evolve-v1|t{t_final!r}|tol{float(tol)!r}|m{m_cap}"
               f"|s{int(seed)}")
    multi = jax.process_count() > 1
    sharded_ckpt = multi and hashed_layout
    if checkpoint_path and multi and not sharded_ckpt:
        from ..utils.logging import log_debug
        log_debug("evolve checkpointing disabled: multi-process run with "
                  "a non-engine matvec (no per-shard vector layout)")
        checkpoint_path = None
    resumed_from = 0
    if checkpoint_path:
        got = _restore_ckpt(checkpoint_path, ckpt_fp, owner, shape,
                            sharded=sharded_ckpt, solver="evolve",
                            dtype=np.dtype(cdtype))
        if got is not None:
            psi = got["V_rows"][0].astype(cdtype)
            t = float(got["t"])
            dt = float(got["dt"])
            step = resumed_from = int(got["total_iters"])
            norm_drift = float(got["norm_drift"])
            energy_drift = float(got["energy_drift"])
            # NaN marks "no step accepted yet" — restoring a literal
            # 0.0 there would poison the drift reference and skip the
            # t=0 observable sample on resume
            _e0 = float(got["e0_ref"])
            e0_ref = None if np.isnan(_e0) else _e0
            times = [float(x) for x in np.asarray(got["times"])]
            energies = [float(x) for x in np.asarray(got["energies"])]
            # observable trajectories resume too (stored in obs_list
            # ORDER — the same-argv resume contract); a changed
            # observable count means a different run: series start fresh
            ser = got.get("obs_series")
            if ser is not None and obs_list \
                    and np.asarray(ser).shape[0] == len(obs_list):
                ser = np.asarray(ser)
                for (name, _), row in zip(obs_list, ser):
                    obs_vals[name] = [(float(tt), float(vv))
                                      for tt, vv in row]
            obs_emit("solver_resume", solver="evolve", iters=int(step),
                     t=float(t))

    obs_emit("solver_start", solver="evolve", t_final=t_final,
             tol=float(tol), krylov_dim=int(m_cap),
             complex_native=bool(complex_native),
             resumed_from=int(resumed_from))

    mem_h = obs_memory.NULL_HANDLE
    if obs_enabled():
        mem_h = obs_memory.track(
            f"solver/{obs_memory.next_instance('evolve')}/krylov_basis",
            (m_cap + 1) * int(psi.nbytes), krylov_dim=int(m_cap))

    def save_ckpt(reason):
        meta = {
            "t": float(t), "dt": float(dt), "m": 0,
            "total_iters": int(step), "norm_drift": float(norm_drift),
            "energy_drift": float(energy_drift),
            "e0_ref": float(e0_ref) if e0_ref is not None else np.nan,
            "times": np.asarray(times), "energies": np.asarray(energies)}
        if obs_list and obs_vals:
            # [n_obs, K, 2] (t, value) series in obs_list order, so a
            # same-argv resume returns the FULL trajectory aligned
            # with times, not a post-resume stub
            meta["obs_series"] = np.asarray(
                [[[tt, vv] for tt, vv in obs_vals.get(name, [])]
                 for name, _ in obs_list])
        _soft_save_ckpt(checkpoint_path, ckpt_fp, owner, psi[None], meta,
                        0, sharded_ckpt, solver="evolve", reason=reason)

    def eval_observables():
        for name, fn in obs_list:
            obs_vals.setdefault(name, []).append((t, fn(psi)))

    first_s = 0.0
    steady_s = 0.0
    while t < t_final * (1.0 - 1e-15):
        if max_steps is not None and step - resumed_from >= int(max_steps):
            break
        faults.check("solver_block", exc=RuntimeError, solver="evolve",
                     iter=int(step))
        if preempt.agreed(agree_multi):
            if checkpoint_path:
                save_ckpt("preempt")
            obs_emit("solver_preempted", solver="evolve", iters=int(step),
                     checkpoint=checkpoint_path or "")
            obs_flush()
            mem_h.release()
            raise preempt.Preempted("evolve", step, checkpoint_path)
        t_wall = time.perf_counter()
        with obs_trace.span("iteration", kind="iteration",
                            solver="evolve", iter=int(step), t=float(t)):
            # -- Krylov basis for THIS state (valid for any dt) --------
            nrm = float(jnp.sqrt(jnp.real(jnp.vdot(psi, psi))))
            V = [psi / nrm]
            alph: List[float] = []
            bet: List[float] = []
            breakdown = False
            for jj in range(m_cap):
                w = apply_c(V[jj])
                napply += 1
                a = float(jnp.real(jnp.vdot(V[jj], w)))
                w = w - a * V[jj]
                if jj:
                    w = w - bet[jj - 1] * V[jj - 1]
                # one full reorthogonalization pass: m is small, the
                # dots are trivial next to the matvec, and the small-T
                # exponential needs an orthonormal basis
                for vi in V:
                    w = w - jnp.vdot(vi, w) * vi
                alph.append(a)
                b = float(jnp.sqrt(jnp.real(jnp.vdot(w, w))))
                if b <= _BREAKDOWN * max(abs(a), 1.0):
                    breakdown = True
                    bet.append(b)
                    break
                bet.append(b)
                V.append(w / b)
            m_eff = len(alph)
            T = np.diag(np.asarray(alph))
            for i in range(m_eff - 1):
                T[i + 1, i] = T[i, i + 1] = bet[i]
            theta, S = np.linalg.eigh(T)
            # energies[i] = <psi|H|psi> at times[i]; the recurrence's
            # first alpha IS the energy of the state this step starts
            # from, so the trajectory records it for free
            if len(energies) < len(times):
                energies.append(alph[0])
                if e0_ref is None:
                    e0_ref = alph[0]
                    eval_observables()

            # -- adaptive acceptance: rejections re-exponentiate the
            # SAME T, no applies --------------------------------------
            dt_try = min(dt, t_final - t)
            while True:
                u = S @ (np.exp(-1j * dt_try * theta) * S[0, :])
                err = (0.0 if breakdown
                       else abs(bet[m_eff - 1] * u[m_eff - 1]))
                if err <= float(tol) * dt_try or dt_try <= 1e-12 * t_final:
                    break
                rejects += 1
                obs_emit("evolve_reject", solver="evolve", iter=int(step),
                         dt=float(dt_try), err=float(err))
                dt_try *= 0.5

            # -- commit ------------------------------------------------
            uj = jnp.asarray(u, dtype=cdtype)
            psi_new = nrm * sum(uj[i] * V[i] for i in range(m_eff))
            jax.block_until_ready(psi_new)
            psi = psi_new
            t += dt_try
            step += 1
            nrm_new = float(jnp.sqrt(jnp.real(jnp.vdot(psi, psi))))
            norm_drift = max(norm_drift, abs(nrm_new - 1.0))
            e_t = alph[0]           # <psi|H|psi> at the step START
            energy_drift = max(energy_drift,
                               abs(e_t - e0_ref) / max(1.0, abs(e0_ref)))
            times.append(t)
            if obs_list and step % max(int(obs_every), 1) == 0:
                eval_observables()
            # grow only when the estimate is an order under budget (and
            # never past the remaining trajectory / dt_max)
            if not breakdown and err < 0.1 * float(tol) * dt_try:
                dt = min(dt_try * 1.41421356, dt_max)
            else:
                dt = dt_try
        dwall = time.perf_counter() - t_wall
        if step - resumed_from == 1:
            first_s = dwall
        else:
            steady_s += dwall
        if obs_enabled():
            obs_emit("evolve_trace", solver="evolve", iter=int(step),
                     t=float(t), dt=float(dt_try), err=float(err),
                     krylov_m=int(m_eff), energy=float(e_t),
                     norm_drift=float(norm_drift),
                     energy_drift=float(energy_drift))
        if checkpoint_path and \
                (step - resumed_from) % max(int(checkpoint_every), 1) == 0:
            save_ckpt("cadence")

    # close the energy trajectory at the FINAL state (one extra apply —
    # trivial next to the trajectory) so energies aligns with times;
    # this also covers a run that never took a step
    if len(energies) < len(times):
        w = apply_c(psi)
        napply += 1
        nrm2 = float(jnp.real(jnp.vdot(psi, psi)))
        e_fin = float(jnp.real(jnp.vdot(psi, w))) / max(nrm2, 1e-300)
        if e0_ref is None:
            e0_ref = e_fin
            eval_observables()
        energies.append(e_fin)
        energy_drift = max(energy_drift,
                           abs(e_fin - e0_ref) / max(1.0, abs(e0_ref)))

    obs_emit("solver_end", solver="evolve", iters=int(step),
             converged=bool(t >= t_final * (1.0 - 1e-12)),
             t=float(t), num_applies=int(napply),
             norm_drift=float(norm_drift),
             energy_drift=float(energy_drift))
    mem_h.release()
    return EvolveResult(
        psi=psi, times=np.asarray(times), energies=np.asarray(energies),
        norm_drift=float(norm_drift), energy_drift=float(energy_drift),
        num_steps=step, num_applies=napply, num_rejects=rejects,
        resumed_from=resumed_from,
        observables=obs_vals if obs_list else None,
        first_step_seconds=first_s, steady_seconds=steady_s)
