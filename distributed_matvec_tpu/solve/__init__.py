"""Eigensolvers (L6) — the PRIMME/Diagonalize analog (SURVEY.md §7.7)."""

from .lanczos import LanczosResult, lanczos, lanczos_block  # noqa: F401
from .lobpcg import lobpcg  # noqa: F401
