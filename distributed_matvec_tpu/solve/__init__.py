"""Solvers (L6) — eigenpairs (the PRIMME/Diagonalize analog, SURVEY.md
§7.7) plus the dynamics family (DESIGN.md §29): Chebyshev/KPM spectral
densities, Krylov time evolution — every solver drives the same engines
through the same matvec contract."""

from .evolve import EvolveResult, krylov_evolve  # noqa: F401
from .kpm import (KPMResult, exact_moments, jackson_kernel,  # noqa: F401
                  kpm_dos, kpm_moments, kpm_spectral_function,
                  lorentz_kernel, reconstruct_dos, spectral_bounds)
from .lanczos import LanczosResult, lanczos, lanczos_block  # noqa: F401
from .lobpcg import lobpcg  # noqa: F401

# module aliases so the refusal-message pointers ("solve.kpm",
# "solve.evolve") resolve as written
from . import evolve, kpm  # noqa: F401, E402
