"""Block eigensolver: LOBPCG over the engine's batched matvec.

The reference's PRIMME runs *blocked* Davidson (``kMaxBlockSize``,
``Diagonalize.chpl:171``, block loop ``:154-158``); the TPU-native analog is
LOBPCG on the rank-2 matvec (one fused gather pass for the whole block).
Built on ``jax.experimental.sparse.linalg.lobpcg_standard``, which computes
the *largest* eigenvalues of an SPD-ish operator — we flip the spectrum with
``σ·I − H`` (σ = a cheap upper bound via Gershgorin over the ELL tables is
overkill; a power-iteration estimate of ‖H‖ suffices).

For a :class:`~..parallel.distributed.DistributedEngine` the whole iteration
runs in the engine's HASHED space: block columns are flattened ``[D·M(·2), m]``
views of the hashed layout, every matvec is one sharded apply (one
``all_to_all``), and the small dense algebra inside ``lobpcg_standard``
operates on the sharded flats.  Pad slots start at zero (``to_hashed`` zero
fills) and stay zero — H maps them to 0 and all LOBPCG updates are linear
combinations — so the flat space behaves exactly like the n-dimensional
physical space.

Multi-process runs work for distributed engines: jax's jitted
``lobpcg_standard`` cannot bake process-spanning engine operands into its
closure, so the UNJITTED body runs under this module's own jit with the
operands as explicit arguments (closures over tracers are ordinary jax);
the start block is generated per shard, orthonormalization of the tall
block uses Gram + Cholesky, and only the final eigenvector output
allgathers.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import health as obs_health
from ..obs import memory as obs_memory
from ..obs import trace as obs_trace
from ..obs.events import emit as obs_emit, flush as obs_flush, obs_enabled
from ..utils import preempt
from .lanczos import _operator_key, _restore_ckpt, _soft_save_ckpt

__all__ = ["lobpcg"]


def _emit_end(iters: int, evals,
              mem_h: obs_memory.Handle = obs_memory.NULL_HANDLE) -> None:
    """Final telemetry event (lobpcg_standard's jitted while_loop exposes no
    per-iteration host callback, so unlike Lanczos the trace granularity
    here is the solve, not the step — and the health check likewise runs on
    the finished spectrum: a NaN/Inf eigenvalue is the one silent-decay
    signature visible at this granularity).  Also releases the solve's
    memory-ledger registration."""
    mem_h.release()
    vals = [float(v) for v in np.atleast_1d(evals)]
    obs_emit("solver_end", solver="lobpcg", iters=int(iters),
             eigenvalues=vals)
    if vals and not np.all(np.isfinite(vals)) \
            and obs_health.probes_enabled():
        obs_health.record(
            "nonfinite_eigenvalues", "critical", solver="lobpcg",
            iters=int(iters),
            count=int(np.sum(~np.isfinite(np.asarray(vals)))))


def _norm_estimate(matvec: Callable, n: int, iters: int = 20, seed: int = 3):
    """Power-iteration estimate of ‖H‖₂ (upper-bounded by ×1.05)."""
    v = jnp.asarray(np.random.default_rng(seed).standard_normal(n))
    v = v / jnp.linalg.norm(v)
    lam = 0.0
    for _ in range(iters):
        w = matvec(v)
        if isinstance(w, tuple):
            w = w[0]
        lam = float(jnp.linalg.norm(w))
        v = w / lam
    return 1.05 * lam


def lobpcg(matvec: Callable, n: int, *args, **kwargs
           ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Solve-span wrapper over :func:`_lobpcg_impl` (see there for the
    full contract): the whole LOBPCG call is ONE ``solve`` span and each
    checkpoint segment an ``iteration`` span — the causal tree
    ``obs_report trace`` exports."""
    with obs_trace.span("lobpcg", kind="solve",
                        k=int(kwargs.get("k", args[0] if args else 1))):
        return _lobpcg_impl(matvec, n, *args, **kwargs)


def _lobpcg_impl(matvec: Callable, n: int, k: int = 1, max_iters: int = 200,
           tol: float = 1e-9, seed: int = 0,
           X0: Optional[np.ndarray] = None,
           pair: Optional[bool] = None,
           cluster_rtol: float = 1e-6,
           rank_tol: float = 0.3,
           checkpoint_path: Optional[str] = None,
           checkpoint_every: int = 50
           ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Lowest-``k`` eigenpairs via spectrum-flipped LOBPCG.

    Returns (eigenvalues [k] ascending, eigenvectors [n, k], iterations).
    ``matvec`` may be a LocalEngine's (rank-2 ``[n, k]`` blocks) or a
    DistributedEngine's (hashed ``[D, M, k(, 2)]`` blocks — handled via the
    flat hashed space, see module docstring); eigenvectors always come back
    in block (global sorted) order.

    ``pair`` (auto-detected from a pair-mode engine) runs the realified
    operator on R^{2n}: each complex eigenvalue appears twice (along v and
    J·v), so the block is doubled to 2k and complex-parallel duplicates are
    filtered from the result; eigenvectors come back complex ``[n, k]``.
    J-copies are decided *per eigenvalue cluster* (eigenvalues within
    ``cluster_rtol``·‖H‖ of each other): each cluster's complexified
    columns are projected against every already-kept vector and then
    rank-decided by column-pivoted QR, keeping columns whose independent
    residual exceeds ``rank_tol`` — so a near-threshold residual on one
    column cannot silently drop a genuine degenerate partner the way a
    fixed per-column scalar cutoff could.

    ``checkpoint_path`` brings LOBPCG to checkpoint/resume parity with
    :func:`~.lanczos.lanczos`: the iteration is driven in *segments* of
    ``checkpoint_every`` iterations, the current block is snapshotted
    after each segment (atomically, keyed by (dim, block, operator) via
    the same fingerprint/sharded-snapshot machinery as Lanczos — each
    rank of a multi-controller run writes its addressable shards, and
    restore is generation-agreed across ranks), and a rerun with the same
    arguments warm-starts from the last saved block with the cumulative
    iteration count.  LOBPCG restarted from its own block loses only the
    implicit momentum direction of the segment boundary — convergence
    continues, it does not restart.  A latched preemption signal exits at
    a segment boundary (checkpoint written) with
    :class:`~..utils.preempt.Preempted`.  Without ``checkpoint_path`` the
    solve runs one-shot, exactly as before.
    """
    from jax.experimental.sparse.linalg import lobpcg_standard

    owner = getattr(matvec, "__self__", None)
    if pair is None:
        pair = bool(getattr(owner, "pair", False))
    obs_emit("solver_start", solver="lobpcg", k=int(k),
             max_iters=int(max_iters), tol=float(tol), pair=bool(pair))
    # lobpcg_standard keeps X, P, R plus their H-applies resident — ~6
    # blocks of [n(, 2), m] columns; an estimate, flagged as such, so OOM
    # forensics attribute block-solver footprint without instrumenting
    # jax's own solver internals
    mem_h = obs_memory.NULL_HANDLE
    if obs_enabled():
        cols = 2 * k if pair else k
        mem_h = obs_memory.track(
            f"solver/{obs_memory.next_instance('lobpcg')}/block_workspace",
            6 * 8 * int(n) * max(int(cols), 1) * (2 if pair else 1),
            estimate=True, k=int(k))
    dist = owner is not None and hasattr(owner, "from_hashed")
    multi = dist and jax.process_count() > 1
    raw_lobpcg = None
    if multi:
        # jax's lobpcg_standard jits its matvec CALLABLE with the closure's
        # captured arrays baked in as compile-time constants; a distributed
        # engine's operands span processes, and jit refuses process-spanning
        # constants ("closing over jax.Array that spans non-addressable
        # devices").  The multi-process path therefore runs the UNJITTED
        # LOBPCG body under OUR jit with the engine operands as explicit
        # arguments — inside that jit the operands are tracers, and a
        # closure over tracers is ordinary jax.  Every step is
        # SPMD-consistent device math (matmuls/reductions over the sharded
        # flat axis; eigh/QR only on small replicated matrices).
        from jax.experimental.sparse.linalg import (
            _lobpcg_standard_callable as _cal)
        raw_lobpcg = getattr(_cal, "__wrapped__", None)
        if raw_lobpcg is None or not hasattr(owner, "bound_matvec"):
            raise ValueError(
                "multi-process LOBPCG needs jax's unjitted lobpcg body "
                "and an engine exposing bound_matvec; use solve.lanczos"
            )
        if getattr(matvec, "__func__", None) \
                is not getattr(type(owner), "matvec", None):
            # the multi path substitutes the engine's bound_matvec; a
            # wrapped/shifted bound method would silently solve a
            # DIFFERENT operator (same contract as solve/lanczos.py)
            raise ValueError(
                "multi-process LOBPCG only accepts the engine's own "
                "matvec method; wrap the operator, not the matvec, or "
                "use solve.lanczos"
            )
        if X0 is not None:
            raise ValueError(
                "multi-process LOBPCG cannot consume a global warm-start "
                "X0; run without X0 or use solve.lanczos")

    preempt.ensure_installed()

    def _ckpt_fp(dim_, cols):
        """Checkpoint identity: vector space + block width + operator —
        the same keying contract as the Lanczos checkpoints (a rerun
        against an edited Hamiltonian of the same size misses instead of
        restoring a foreign block).  Distributed-engine solves key
        TOPOLOGY-FREE (v2: n_states, not the flat padded dim, which bakes
        in D·M), so a block snapshot written at D devices is found at D′
        and resharded on restore — the lanczos-v3 contract."""
        if dist:
            return (f"lobpcg|nst{int(owner.n_states)}|{cols}"
                    f"|{int(bool(pair))}|{_operator_key(owner)}|v2")
        return f"lobpcg|{dim_}|{cols}|{int(bool(pair))}" \
               f"|{_operator_key(owner)}|v1"

    def _ckpt_fp_legacy(dim_, cols):
        """The pre-elastic fixed-topology fingerprint, still probed on
        restore so v1 checkpoints resume unchanged on a matching D."""
        return f"lobpcg|{dim_}|{cols}|{int(bool(pair))}" \
               f"|{_operator_key(owner)}|v1"

    def _exit_preempted(done):
        obs_emit("solver_preempted", solver="lobpcg", iters=int(done),
                 checkpoint=checkpoint_path or "")
        obs_flush()
        mem_h.release()
        raise preempt.Preempted("lobpcg", done, checkpoint_path)

    def run_flipped(mv, dim_, U0):
        """sigma estimate, spectrum-flipped lobpcg_standard, ascending
        (evals, columns, iters) output: the scaffold every branch shares.
        With ``checkpoint_path`` the call is segmented (see docstring);
        single-controller, so the snapshot is the flat block itself."""
        sigma = _norm_estimate(mv, dim_)
        flip = lambda X: sigma * X - mv(X)            # noqa: E731
        U0q, _ = np.linalg.qr(np.asarray(U0))
        X = jnp.asarray(U0q)
        cols = int(X.shape[1])
        done = 0
        # distributed engines snapshot the block as HASHED rows
        # [cols, D, M(, 2)] — the topology-portable layout the stanza
        # describes, resharded on a D→D′ restore; local solves keep the
        # flat [cols, n] rows (fixed layout by construction)
        hashed_tail = ((2,) if pair else ()) if dist else ()
        row_shape = ((owner.n_devices, owner.shard_size) + hashed_tail) \
            if dist else (dim_,)
        if checkpoint_path:
            fp = _ckpt_fp(dim_, cols)
            got = _restore_ckpt(
                checkpoint_path, fp, owner if dist else None, row_shape,
                sharded=False, solver="lobpcg",
                legacy_fp=_ckpt_fp_legacy(dim_, cols) if dist else None,
                # the v1 distributed format stored FLAT padded columns
                legacy_shape=(dim_,) if dist else None)
            if got is not None and len(got["V_rows"]) == cols:
                rows = got["V_rows"]
                if dist and rows[0].ndim >= 2:
                    # hashed rows → flat columns (stack cols on axis 2:
                    # [D, M, cols(, 2)], exactly to_flat's input layout)
                    X = jax.jit(to_flat)(
                        jnp.stack(rows, axis=2)).astype(X.dtype)
                else:
                    X = jnp.stack(rows, axis=1).astype(X.dtype)
                done = int(got["total_iters"])
                obs_emit("solver_resume", solver="lobpcg",
                         iters=int(done), path=checkpoint_path)
        theta = U = None
        if done >= max_iters:
            # resume with the budget already spent: return the restored
            # block's Rayleigh-Ritz estimates without iterating (the
            # lanczos restore-path contract — the cap is never exceeded)
            G = np.asarray(X.conj().T @ flip(X))
            theta, W = np.linalg.eigh((G + G.conj().T) / 2)
            U = np.asarray(X @ jnp.asarray(W))
        while done < max_iters:
            seg = (max_iters - done) if not checkpoint_path else \
                min(max(int(checkpoint_every), 1), max_iters - done)
            # iteration span: one LOBPCG segment (seg driven iterations)
            with obs_trace.span("iteration", kind="iteration",
                                solver="lobpcg", iter=int(done),
                                steps=int(seg)):
                theta, U, it = lobpcg_standard(flip, X, m=seg, tol=tol)
            done += int(it)
            X = U
            if not checkpoint_path:
                break
            V_save = jnp.moveaxis(from_flat(U), 2, 0) if dist \
                else jnp.swapaxes(U, 0, 1)
            _soft_save_ckpt(checkpoint_path, fp, owner if dist else None,
                            V_save,
                            {"m": cols - 1, "total_iters": int(done)},
                            cols - 1, sharded=False, solver="lobpcg")
            # lobpcg_standard breaks early on convergence, so a full
            # segment (it == seg) means "not converged yet"
            if int(it) < seg:
                break
            if preempt.agreed(False):
                _exit_preempted(done)
        evals = sigma - np.asarray(theta)
        order = np.argsort(evals)
        return sigma, evals[order], np.asarray(U)[:, order], int(done)

    def raw_mv(x):
        y = matvec(x)
        return y[0] if isinstance(y, tuple) else y

    if dist:
        # ---- hashed flat space adapters --------------------------------
        D, M = owner.n_devices, owner.shard_size
        dim = D * M * (2 if pair else 1)

        def to_flat(Xh):
            Xh = jnp.asarray(Xh)
            if pair:                           # [D, M, m, 2] → [2DM, m]
                return jnp.moveaxis(Xh, 3, 2).reshape(D * M * 2, Xh.shape[2])
            return Xh.reshape(D * M, Xh.shape[2])

        def from_flat(U):
            m = U.shape[1]
            if pair:
                return jnp.moveaxis(U.reshape(D, M, 2, m), 2, 3)
            return U.reshape(D, M, m)

        def mv_flat(U):
            if U.ndim == 1:                    # norm-estimate probe
                return mv_flat(U[:, None])[:, 0]
            return to_flat(raw_mv(from_flat(U)))

        def block_x0(m):
            """Random start block (pads zero), warm-start columns capped
            at k.  Multi-process: generated directly in hashed layout per
            shard (deterministic in (seed, shard)) — no global host array;
            X0 was rejected up front."""
            if multi:
                # per-shard generation lives in the engine (one home for
                # the seeding/pad-zero invariants)
                return to_flat(owner.random_hashed(seed=seed, cols=m))
            rng = np.random.default_rng(seed)
            Xb = rng.standard_normal((n, m))
            if pair:
                Xb = Xb + 1j * rng.standard_normal((n, m))
            if X0 is not None:
                W = np.asarray(X0)
                if W.ndim != 2 or W.shape[0] != n or W.shape[1] > k:
                    raise ValueError(
                        f"X0 must be [n, j] with j <= k={k}, got {W.shape}")
                Xb = Xb.astype(np.result_type(Xb, W))
                Xb[:, : W.shape[1]] = W
            return np.asarray(to_flat(owner.to_hashed(Xb)))

        def cols_to_block(U):
            """Flat columns → block order; complex for pair engines.
            (from_hashed allgathers in multi-process runs — the global
            eigenvector output is inherently global.)"""
            V = owner.from_hashed(from_flat(jnp.asarray(U)))
            if pair:
                return V[..., 0] + 1j * V[..., 1]       # [n, m] complex
            return V                                    # [n, m]

        def run_flipped_multi(U0):
            """Multi-process scaffold: eager hashed power iteration for
            sigma (also runs the engine's counter validation), Gram +
            Cholesky orthonormalization of the sharded block (the [m, m]
            Gram is a psum-reduced matmul, replicated on every rank), then
            the unjitted LOBPCG body under one jit with the engine
            operands as arguments — segmented per ``checkpoint_every``
            when checkpointing, with per-rank shard snapshots and the
            generation-agreed restore of the Lanczos machinery."""
            vh = owner.random_hashed(seed=seed + 1)
            lam = 0.0
            for _ in range(20):
                w = raw_mv(vh)
                lam = float(jnp.sqrt(jnp.real(jnp.vdot(w, w))))
                vh = w / lam
            sigma = 1.05 * lam

            # The [m, m] Gram must be FULLY REPLICATED before the host
            # fetch: jit's default output sharding over a process-spanning
            # operand is unspecified, and np.asarray raises on
            # non-fully-addressable arrays.  Explicit replicated
            # out_shardings makes the psum-reduced matmul land addressable
            # on every process.
            from jax.sharding import NamedSharding, PartitionSpec
            _rep = NamedSharding(owner.mesh, PartitionSpec())

            # hoisted jitted helpers: a fresh jit(lambda) per segment
            # would miss jax's trace cache and recompile every checkpoint
            # segment
            _gram = jax.jit(lambda A: A.T @ A, out_shardings=_rep)
            _snap = jax.jit(lambda u: jnp.moveaxis(from_flat(u), 2, 0))

            def gram_li(X):
                G = np.asarray(_gram(X))
                L = np.linalg.cholesky(
                    G + 1e-12 * np.trace(G) * np.eye(G.shape[1]))
                return jnp.asarray(np.linalg.inv(L))

            apply_fn, operands = owner.bound_matvec()

            def mv_ops(Xb, ops):
                Y = apply_fn(from_flat(Xb), ops)
                return to_flat(Y[0] if isinstance(Y, tuple) else Y)

            _progs: dict = {}

            def _run(X, Li_, ops, m_seg):
                f = _progs.get(m_seg)
                if f is None:
                    def _body(X, Li_, ops):
                        Xq = X @ Li_.T
                        return raw_lobpcg(
                            lambda Xb: sigma * Xb - mv_ops(Xb, ops),
                            Xq, m_seg, tol, False)
                    f = _progs[m_seg] = jax.jit(_body)
                return f(X, Li_, ops)

            cols = int(U0.shape[1])
            X = U0
            done = 0
            fp = _ckpt_fp(dim, cols)
            # rank-local-mesh engines inside a multi-process job (the CPU
            # test rig) solve independently — no cross-rank agreement
            # collectives, same gating as lanczos's agree_multi
            agree = bool(getattr(owner, "_multi", True))
            if checkpoint_path:
                got = _restore_block_multi(fp, cols)
                if got is not None:
                    X, done = got
                    obs_emit("solver_resume", solver="lobpcg",
                             iters=int(done), path=checkpoint_path)
            theta = U = None
            if done >= max_iters:
                # budget already spent at restore: Rayleigh-Ritz estimates
                # from the saved block, no further iterations (the psum'd
                # Gram lands replicated like gram_li's)
                G = np.asarray(jax.jit(
                    lambda Xb, ops: Xb.T @ (sigma * Xb - mv_ops(Xb, ops)),
                    out_shardings=_rep)(X, operands))
                theta, W = np.linalg.eigh((G + G.T) / 2)
                U = jax.jit(jnp.matmul)(X, jnp.asarray(W))
            while done < max_iters:
                seg = (max_iters - done) if not checkpoint_path else \
                    min(max(int(checkpoint_every), 1), max_iters - done)
                with obs_trace.span("iteration", kind="iteration",
                                    solver="lobpcg", iter=int(done),
                                    steps=int(seg)):
                    theta, U, it = _run(X, gram_li(X), operands, seg)
                done += int(it)
                X = U
                if not checkpoint_path:
                    break
                # columns → hashed rows [cols, D, M(, 2)] for the
                # per-shard snapshot (every op on the process-spanning
                # block stays under jit)
                V = _snap(U)
                _soft_save_ckpt(checkpoint_path, fp, owner, V,
                                {"m": cols - 1,
                                 "total_iters": int(done)},
                                cols - 1, sharded=True, solver="lobpcg")
                if int(it) < seg:
                    break
                if preempt.agreed(agree):
                    _exit_preempted(done)
            evals = sigma - np.asarray(theta)
            order = np.argsort(evals)
            return sigma, evals[order], U[:, jnp.asarray(order)], int(done)

        def _restore_block_multi(fp, cols):
            """Per-shard block restore via the solver-shared
            :func:`lanczos._restore_sharded_rows`: fingerprint probe
            (primary then legacy, so v1 checkpoints restore unchanged on
            a matching D), D→D′ reshard on a topology-stanza mismatch
            (the lanczos contract, ``parallel/reshard.py``), and the
            fixed-point cross-rank readiness agreement — per-rank
            snapshot files are written without a barrier, so all ranks
            restore the same generation or all start fresh (rank-local
            meshes keep a local verdict).  ``expect_m`` pins the block
            width: a snapshot of a different ``cols`` is not this
            solve's."""
            from .lanczos import _restore_sharded_rows

            tail = (2,) if pair else ()
            meta, rows = _restore_sharded_rows(
                checkpoint_path, fp, _ckpt_fp_legacy(dim, cols), owner,
                (owner.n_devices, owner.shard_size) + tail, "lobpcg",
                dtype=np.float64, expect_m=cols - 1)
            if meta is None:
                return None
            # per-column hashed rows → the [D, M, cols(, 2)] block
            # layout the flat adapters consume
            Xh = jax.jit(lambda *rs: jnp.stack(rs, axis=2))(*rows)
            return jax.jit(to_flat)(Xh), int(meta["total_iters"])

    if not pair:
        if dist:
            _, evals, U, iters = (run_flipped_multi(block_x0(k)) if multi
                                  else run_flipped(mv_flat, dim,
                                                   block_x0(k)))
            _emit_end(iters, evals, mem_h)
            return evals, cols_to_block(U), iters
        if X0 is None:
            X0 = np.random.default_rng(seed).standard_normal((n, k))
        _, evals, U, iters = run_flipped(raw_mv, n, X0)
        _emit_end(iters, evals, mem_h)
        return evals, U, iters

    # -- pair form: flat realified operator ---------------------------------
    # 2k for the J-doubling plus 2 guard vectors: the tail of an LOBPCG
    # block converges last, and the k-th *distinct* eigenvalue sits at
    # block position 2k-1 without the guard.  jax's lobpcg_standard
    # requires 5·block < dim.
    kk = 2 * k + 2
    dim2 = dim if dist else 2 * n
    if 5 * kk >= dim2:
        raise ValueError(
            f"pair-mode LOBPCG needs dim > 5·(2k+2) (jax lobpcg block bound "
            f"on the realified R^{{2n}}); got n={n}, k={k} — reduce k or "
            "use solve.lanczos"
        )

    if dist:
        sigma, evals, U, iters = (run_flipped_multi(block_x0(kk)) if multi
                                  else run_flipped(mv_flat, dim,
                                                   block_x0(kk)))
    else:
        def mv_flat_local(U):
            """[2n, m] f64 → engine pair batch [n, m, 2] → back."""
            if U.ndim == 1:           # norm-estimate probe vector
                return mv_flat_local(U[:, None])[:, 0]
            m = U.shape[1]
            X = jnp.transpose(U.reshape(n, 2, m), (0, 2, 1))
            Y = raw_mv(X)
            return jnp.transpose(Y, (0, 2, 1)).reshape(2 * n, m)

        rng = np.random.default_rng(seed)
        U0 = rng.standard_normal((2 * n, kk))
        if X0 is not None:
            # warm start: complex [n, j] columns (j ≤ k) realified into the
            # leading block columns; remaining columns stay random
            X0 = np.asarray(X0)
            if X0.ndim != 2 or X0.shape[0] != n or X0.shape[1] > k:
                raise ValueError(
                    f"pair-mode X0 must be complex [n, j] with j <= k="
                    f"{k}, got shape {X0.shape}"
                )
            # realify in the (re, im)-interleaved row layout mv_flat uses
            U0[:, : X0.shape[1]] = np.stack(
                [X0.real, X0.imag], axis=1).reshape(2 * n, X0.shape[1])
        sigma, evals, U, iters = run_flipped(mv_flat_local, 2 * n, U0)
    # Complex view; keep one representative per complex direction.  Columns
    # are processed per eigenvalue *cluster*: each cluster block is first
    # projected against ALL previously kept vectors (so a J-copy whose
    # eigenvalue estimate drifted into a later cluster still deduplicates),
    # then column-pivoted QR ranks the residual columns — a copy's residual
    # is ~0 while a genuinely degenerate partner keeps an O(1) independent
    # component, and within a cluster the partner with the LARGEST residual
    # is decided first, so a noisy copy processed earlier cannot push a
    # genuine partner under the threshold (the per-column scalar-cutoff
    # failure mode).  Pivoted QR keeps (orthonormalized) *actual columns*
    # rather than SVD mixtures, so near-degenerate-but-distinct eigenpairs
    # that share a cluster are not 50/50 blended, and each kept vector
    # carries the eigenvalue of its own pivot column.
    from scipy.linalg import qr as _pivoted_qr

    if dist:
        Z = cols_to_block(U)
    else:
        Z = U.reshape(n, 2, kk)[:, 0] + 1j * U.reshape(n, 2, kk)[:, 1]
    Z = Z / np.maximum(np.linalg.norm(Z, axis=0, keepdims=True), 1e-300)
    gap = cluster_rtol * max(abs(sigma), 1.0)
    kept_vals, kept_vecs = [], []
    j = 0
    while j < kk and len(kept_vals) < k:
        j_end = j + 1
        while j_end < kk and evals[j_end] - evals[j_end - 1] <= gap:
            j_end += 1
        Zc = Z[:, j:j_end].copy()
        if kept_vecs:
            Qm = np.stack(kept_vecs, axis=1)
            Zc -= Qm @ (Qm.conj().T @ Zc)
        Qc, R, piv = _pivoted_qr(Zc, mode="economic", pivoting=True)
        diag = np.abs(np.diag(R))
        for r_i in range(diag.size):
            if diag[r_i] <= rank_tol or len(kept_vals) == k:
                break
            kept_vals.append(evals[j + piv[r_i]])
            kept_vecs.append(Qc[:, r_i])
        j = j_end
    if kept_vals:
        # pivot order within a cluster is by residual norm, not eigenvalue —
        # restore the documented ascending contract (pairing preserved)
        asc = np.argsort(kept_vals)
        kept_vals = [kept_vals[i] for i in asc]
        kept_vecs = [kept_vecs[i] for i in asc]
    if len(kept_vals) < k:
        import warnings
        warnings.warn(
            f"pair-mode LOBPCG resolved only {len(kept_vals)} of {k} "
            "distinct eigenpairs (unconverged tail); re-run with more "
            "iterations or use solve.lanczos", RuntimeWarning)
    _emit_end(iters, kept_vals, mem_h)
    return (np.asarray(kept_vals), np.stack(kept_vecs, axis=1),
            int(iters))
