"""Block eigensolver: LOBPCG over the engine's batched matvec.

The reference's PRIMME runs *blocked* Davidson (``kMaxBlockSize``,
``Diagonalize.chpl:171``, block loop ``:154-158``); the TPU-native analog is
LOBPCG on the rank-2 matvec (one fused gather pass for the whole block).
Built on ``jax.experimental.sparse.linalg.lobpcg_standard``, which computes
the *largest* eigenvalues of an SPD-ish operator — we flip the spectrum with
``σ·I − H`` (σ = a cheap upper bound via Gershgorin over the ELL tables is
overkill; a power-iteration estimate of ‖H‖ suffices).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["lobpcg"]


def _norm_estimate(matvec: Callable, n: int, iters: int = 20, seed: int = 3):
    """Power-iteration estimate of ‖H‖₂ (upper-bounded by ×1.05)."""
    v = jnp.asarray(np.random.default_rng(seed).standard_normal(n))
    v = v / jnp.linalg.norm(v)
    lam = 0.0
    for _ in range(iters):
        w = matvec(v)
        if isinstance(w, tuple):
            w = w[0]
        lam = float(jnp.linalg.norm(w))
        v = w / lam
    return 1.05 * lam


def lobpcg(matvec: Callable, n: int, k: int = 1, max_iters: int = 200,
           tol: float = 1e-9, seed: int = 0,
           X0: Optional[np.ndarray] = None) -> Tuple[np.ndarray, np.ndarray, int]:
    """Lowest-``k`` eigenpairs via spectrum-flipped LOBPCG.

    Returns (eigenvalues [k] ascending, eigenvectors [n, k], iterations).
    Requires a matvec that accepts rank-2 ``[n, k]`` blocks (both engines do).
    """
    from jax.experimental.sparse.linalg import lobpcg_standard

    def mv1(x):
        y = matvec(x)
        return y[0] if isinstance(y, tuple) else y

    sigma = _norm_estimate(mv1, n)

    def flipped(X):
        return sigma * X - mv1(X)

    if X0 is None:
        X0 = np.random.default_rng(seed).standard_normal((n, k))
    X0, _ = np.linalg.qr(X0)
    theta, U, iters = lobpcg_standard(
        flipped, jnp.asarray(X0), m=max_iters, tol=tol)
    evals = sigma - np.asarray(theta)
    order = np.argsort(evals)
    return evals[order], np.asarray(U)[:, order], int(iters)
