"""Block eigensolver: LOBPCG over the engine's batched matvec.

The reference's PRIMME runs *blocked* Davidson (``kMaxBlockSize``,
``Diagonalize.chpl:171``, block loop ``:154-158``); the TPU-native analog is
LOBPCG on the rank-2 matvec (one fused gather pass for the whole block).
Built on ``jax.experimental.sparse.linalg.lobpcg_standard``, which computes
the *largest* eigenvalues of an SPD-ish operator — we flip the spectrum with
``σ·I − H`` (σ = a cheap upper bound via Gershgorin over the ELL tables is
overkill; a power-iteration estimate of ‖H‖ suffices).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["lobpcg"]


def _norm_estimate(matvec: Callable, n: int, iters: int = 20, seed: int = 3):
    """Power-iteration estimate of ‖H‖₂ (upper-bounded by ×1.05)."""
    v = jnp.asarray(np.random.default_rng(seed).standard_normal(n))
    v = v / jnp.linalg.norm(v)
    lam = 0.0
    for _ in range(iters):
        w = matvec(v)
        if isinstance(w, tuple):
            w = w[0]
        lam = float(jnp.linalg.norm(w))
        v = w / lam
    return 1.05 * lam


def lobpcg(matvec: Callable, n: int, k: int = 1, max_iters: int = 200,
           tol: float = 1e-9, seed: int = 0,
           X0: Optional[np.ndarray] = None,
           pair: Optional[bool] = None
           ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Lowest-``k`` eigenpairs via spectrum-flipped LOBPCG.

    Returns (eigenvalues [k] ascending, eigenvectors [n, k], iterations).
    Requires a matvec that accepts rank-2 ``[n, k]`` blocks (both engines do).

    ``pair`` (auto-detected from a pair-mode engine) runs the realified
    operator on R^{2n}: each complex eigenvalue appears twice (along v and
    J·v), so the block is doubled to 2k and complex-parallel duplicates are
    filtered from the result; eigenvectors come back complex ``[n, k]``.
    """
    from jax.experimental.sparse.linalg import lobpcg_standard

    owner = getattr(matvec, "__self__", None)
    if pair is None:
        pair = bool(getattr(owner, "pair", False))

    def mv1(x):
        y = matvec(x)
        return y[0] if isinstance(y, tuple) else y

    if not pair:
        sigma = _norm_estimate(mv1, n)

        def flipped(X):
            return sigma * X - mv1(X)

        if X0 is None:
            X0 = np.random.default_rng(seed).standard_normal((n, k))
        X0, _ = np.linalg.qr(X0)
        theta, U, iters = lobpcg_standard(
            flipped, jnp.asarray(X0), m=max_iters, tol=tol)
        evals = sigma - np.asarray(theta)
        order = np.argsort(evals)
        return evals[order], np.asarray(U)[:, order], int(iters)

    # -- pair form: flat realified operator on R^{2n} -----------------------
    if hasattr(owner, "from_hashed"):
        raise ValueError(
            "pair-mode LOBPCG supports local engines only (the realified "
            "block is in flat block order, not the hashed [D, M, 2] layout "
            "a DistributedEngine consumes); use solve.lanczos for "
            "distributed complex sectors"
        )
    # 2k for the J-doubling plus 2 guard vectors: the tail of an LOBPCG
    # block converges last, and the k-th *distinct* eigenvalue sits at
    # block position 2k-1 without the guard.  jax's lobpcg_standard
    # requires 5·block < dim, i.e. 5·(2k+2) < 2n here.
    kk = 2 * k + 2
    if 5 * kk >= 2 * n:
        raise ValueError(
            f"pair-mode LOBPCG needs n > 5·(k+1) (jax lobpcg block bound on "
            f"the realified R^{{2n}}); got n={n}, k={k} — reduce k or use "
            "solve.lanczos"
        )

    def mv_flat(U):
        """[2n, m] f64 → engine pair batch [n, m, 2] → back."""
        if U.ndim == 1:           # norm-estimate probe vector
            return mv_flat(U[:, None])[:, 0]
        m = U.shape[1]
        X = jnp.transpose(U.reshape(n, 2, m), (0, 2, 1))
        Y = mv1(X)
        return jnp.transpose(Y, (0, 2, 1)).reshape(2 * n, m)

    sigma = _norm_estimate(mv_flat, 2 * n)

    def flipped(U):
        return sigma * U - mv_flat(U)

    rng = np.random.default_rng(seed)
    U0 = rng.standard_normal((2 * n, kk))
    if X0 is not None:
        # warm start: complex [n, j] columns (j ≤ k) realified into the
        # leading block columns; remaining columns stay random
        X0 = np.asarray(X0)
        if X0.ndim != 2 or X0.shape[0] != n or X0.shape[1] > k:
            raise ValueError(
                f"pair-mode X0 must be complex [n, j] with j <= k="
                f"{k}, got shape {X0.shape}"
            )
        # realify in the (re, im)-interleaved row layout mv_flat uses
        U0[:, : X0.shape[1]] = np.stack(
            [X0.real, X0.imag], axis=1).reshape(2 * n, X0.shape[1])
    U0, _ = np.linalg.qr(U0)
    theta, U, iters = lobpcg_standard(
        flipped, jnp.asarray(U0), m=max_iters, tol=tol)
    evals = sigma - np.asarray(theta)
    order = np.argsort(evals)
    evals, U = evals[order], np.asarray(U)[:, order]
    # Complex view; keep one representative per complex direction.  A J-copy
    # of a kept vector lies entirely in the complex span of the kept set at
    # that eigenvalue, so complex Gram-Schmidt against the kept vectors
    # leaves ~zero residual for copies while a genuinely degenerate partner
    # retains an O(1) independent component (which we keep, orthonormalized —
    # so returned vectors are complex-orthonormal even within degenerate
    # clusters).
    Z = U.reshape(n, 2, kk)[:, 0] + 1j * U.reshape(n, 2, kk)[:, 1]
    kept_vals, kept_vecs = [], []
    for j in range(kk):
        z = Z[:, j] / np.linalg.norm(Z[:, j])
        for z0 in kept_vecs:
            z = z - np.vdot(z0, z) * z0
        r = np.linalg.norm(z)
        if r < 0.3:
            continue                       # complex-parallel J-copy
        kept_vals.append(evals[j])
        kept_vecs.append(z / r)
        if len(kept_vals) == k:
            break
    if len(kept_vals) < k:
        import warnings
        warnings.warn(
            f"pair-mode LOBPCG resolved only {len(kept_vals)} of {k} "
            "distinct eigenpairs (unconverged tail); re-run with more "
            "iterations or use solve.lanczos", RuntimeWarning)
    return (np.asarray(kept_vals), np.stack(kept_vecs, axis=1),
            int(iters))
