"""Lanczos eigensolver over a matvec closure.

The reference drives PRIMME (block Davidson/JDQMR — ``src/PRIMME.chpl``,
``src/Diagonalize.chpl:258-332``) through three callbacks: the distributed
matvec, a global sum, and a broadcast (``PRIMME.chpl:267-373``).  PRIMME is a
native C/Fortran library we don't vendor; the TPU-native replacement is a
host-orchestrated Lanczos with full reorthogonalization whose inner products
ride the same engine: for the distributed engine the vectors are hash-sharded
``[D, M]`` arrays and ``jnp.vdot`` over them is XLA's psum over ICI — exactly
the ``globalSumReal`` semantics.

Works with *any* vector pytree layout: vectors are whatever ``matvec``
consumes/produces (``[N]`` for LocalEngine, ``[D, M]`` hashed for
DistributedEngine; padded slots are zero by engine invariant so dots are
exact).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from scipy.linalg import eigh_tridiagonal

__all__ = ["LanczosResult", "lanczos"]


@dataclass
class LanczosResult:
    eigenvalues: np.ndarray          # [k] ascending
    eigenvectors: Optional[list]     # k vectors in the matvec's layout
    residual_norms: np.ndarray       # [k] |β_m · s_last|  bound
    num_iters: int
    converged: bool


def _scalar(c, dtype):
    """A python scalar as a 0-d device constant of the recurrence dtype."""
    if not np.issubdtype(np.dtype(dtype), np.complexfloating):
        c = c.real if isinstance(c, complex) else c
    return jnp.asarray(c, dtype=dtype)


def _rand_like(shape, dtype, seed):
    rng = np.random.default_rng(seed)
    v = rng.standard_normal(shape)
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        v = v + 1j * rng.standard_normal(shape)
    return v.astype(dtype)


def lanczos(
    matvec: Callable,
    n: Optional[int] = None,
    k: int = 1,
    max_iters: int = 300,
    tol: float = 1e-10,
    seed: int = 0,
    v0=None,
    compute_eigenvectors: bool = False,
    full_reorth: bool = True,
) -> LanczosResult:
    """Lowest-``k`` eigenpairs of the Hermitian operator behind ``matvec``.

    ``v0`` (or ``n`` + ``seed``) fixes the start vector; convergence is the
    standard residual bound ``|β_m s_m,i| < tol·max(1,|θ_i|)`` for the k
    lowest Ritz pairs.
    """
    if v0 is None:
        if n is None:
            raise ValueError("pass v0 or n")
        v0 = _rand_like((n,), np.float64, seed)
    v = jnp.asarray(v0)
    dtype = v.dtype
    nrm = jnp.sqrt(jnp.real(jnp.vdot(v, v)))
    v = v / nrm.astype(dtype)

    alphas: List[float] = []
    betas: List[float] = []
    V: List[jax.Array] = [v]
    v_prev = None
    converged = False
    m = 0
    res = None

    for m in range(1, max_iters + 1):
        w = matvec(V[-1])
        if isinstance(w, tuple):  # engines returning (y, counters)
            w = w[0]
        w = jnp.asarray(w)
        if m == 1 and w.dtype != dtype:
            # complex-Hermitian operator applied to a real start vector:
            # promote the whole recurrence (momentum sectors, symmetry.py)
            dtype = jnp.promote_types(dtype, w.dtype)
            V[0] = V[0].astype(dtype)
        w = w.astype(dtype)
        # Collective discipline: every inner product is scalarized (blocking)
        # immediately, so at most one collective program is in flight at a
        # time.  Overlapping all-reduce programs can deadlock the XLA CPU
        # collective rendezvous when the device pool is oversubscribed (the
        # virtual-device test substrate); on TPU this also keeps the solver's
        # psum latency deterministic.
        jax.block_until_ready(w)
        a = float(jnp.real(jnp.vdot(V[-1], w)))
        w = w - _scalar(a, dtype) * V[-1]
        if v_prev is not None:
            w = w - _scalar(betas[-1], dtype) * v_prev
        if full_reorth:
            # Two passes of classical Gram-Schmidt against the whole basis.
            for _ in range(2):
                for u in V:
                    c = complex(jnp.vdot(u, w))
                    w = w - _scalar(c, dtype) * u
        alphas.append(a)
        b = float(jnp.sqrt(jnp.real(jnp.vdot(w, w))))
        # Ritz values + residual bounds from the tridiagonal.
        kk = min(k, m)
        theta, S = eigh_tridiagonal(
            np.array(alphas), np.array(betas),
            select="i", select_range=(0, kk - 1))
        res = np.abs(b * S[-1, :])
        if m >= k and np.all(res < tol * np.maximum(1.0, np.abs(theta))):
            converged = True
            break
        if b < 1e-14:
            # Krylov space exhausted: every eigenpair it contains is exact,
            # but if fewer than k were found the start vector was deficient —
            # report not-converged so callers don't index missing pairs.
            converged = m >= k
            break
        betas.append(b)
        v_prev = V[-1]
        v = w / jnp.asarray(b).astype(dtype)
        V.append(v)

    kk = min(k, len(alphas))
    theta, S = eigh_tridiagonal(
        np.array(alphas), np.array(betas[: len(alphas) - 1]),
        select="i", select_range=(0, kk - 1))
    evecs = None
    if compute_eigenvectors:
        evecs = []
        for i in range(kk):
            acc = jnp.zeros_like(V[0])
            for j, u in enumerate(V[: len(alphas)]):
                acc = acc + jnp.asarray(S[j, i]).astype(dtype) * u
            nrm = jnp.sqrt(jnp.real(jnp.vdot(acc, acc)))
            evecs.append(acc / nrm.astype(dtype))
    return LanczosResult(
        eigenvalues=np.asarray(theta),
        eigenvectors=evecs,
        residual_norms=np.asarray(res if res is not None else []),
        num_iters=len(alphas),
        converged=converged,
    )
