"""Lanczos eigensolver over a matvec closure.

The reference drives PRIMME (block Davidson/JDQMR — ``src/PRIMME.chpl``,
``src/Diagonalize.chpl:258-332``) through three callbacks: the distributed
matvec, a global sum, and a broadcast (``PRIMME.chpl:267-373``).  PRIMME is a
native C/Fortran library we don't vendor; the TPU-native replacement is a
host-orchestrated Lanczos with full reorthogonalization whose inner products
ride the same engine: for the distributed engine the vectors are hash-sharded
``[D, M]`` arrays and ``jnp.vdot`` over them is XLA's psum over ICI — exactly
the ``globalSumReal`` semantics.

Works with *any* vector pytree layout: vectors are whatever ``matvec``
consumes/produces (``[N]`` for LocalEngine, ``[D, M]`` hashed for
DistributedEngine; padded slots are zero by engine invariant so dots are
exact).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from scipy.linalg import eigh_tridiagonal

__all__ = ["LanczosResult", "lanczos"]


@dataclass
class LanczosResult:
    eigenvalues: np.ndarray          # [k] ascending
    eigenvectors: Optional[list]     # k vectors in the matvec's layout
    residual_norms: np.ndarray       # [k] |β_m · s_last|  bound
    num_iters: int
    converged: bool


def _scalar(c, dtype):
    """A python scalar as a 0-d device constant of the recurrence dtype."""
    if not np.issubdtype(np.dtype(dtype), np.complexfloating):
        c = c.real if isinstance(c, complex) else c
    return jnp.asarray(c, dtype=dtype)


def _rand_like(shape, dtype, seed):
    rng = np.random.default_rng(seed)
    v = rng.standard_normal(shape)
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        v = v + 1j * rng.standard_normal(shape)
    return v.astype(dtype)


def _lanczos_fast(matvec, v0, k, max_iters, tol, compute_eigenvectors):
    """Single-device fast path: the Krylov basis lives in a fixed ``[m+1, N]``
    device buffer and each iteration is one fused program — matvec, the
    three-term recurrence, and TWO classical-Gram-Schmidt reorth passes as
    matmuls on the MXU — with only the (α, β) scalars synced to host.

    This is the TPU replacement for PRIMME's blocked orthogonalization: a
    per-vector dot loop costs ~2m host round-trips per iteration (measured
    2 iters/s on chain-20); the stacked form runs at matvec speed.
    """
    import jax

    v = jnp.asarray(v0)
    dtype = v.dtype
    w_probe = matvec(v)
    if isinstance(w_probe, tuple):
        w_probe = w_probe[0]
    dtype = jnp.promote_types(dtype, w_probe.dtype)
    n = v.shape[0]
    mmax = max_iters

    V = jnp.zeros((mmax + 1, n), dtype)
    nrm = jnp.sqrt(jnp.real(jnp.vdot(v, v)))
    V = V.at[0].set((v / nrm.astype(dtype)).astype(dtype))

    def mv(x):
        y = matvec(x)
        return (y[0] if isinstance(y, tuple) else y).astype(dtype)

    @jax.jit
    def step(V, m, beta_prev):
        vm = V[m]
        w = mv(vm)
        a = jnp.real(jnp.vdot(vm, w))
        w = w - a.astype(dtype) * vm - beta_prev.astype(dtype) * V[m - 1]
        # row mask: only the filled 0..m rows participate in reorth
        mask = (jnp.arange(mmax + 1) <= m).astype(dtype)
        for _ in range(2):
            coeffs = (V.conj() @ w) * mask
            w = w - coeffs @ V
        b = jnp.sqrt(jnp.real(jnp.vdot(w, w)))
        V = V.at[m + 1].set((w / jnp.where(b == 0, 1.0, b).astype(dtype)))
        return V, a, b

    alphas, betas = [], []
    converged = False
    res = None
    beta_prev = jnp.zeros((), jnp.float64)
    for m in range(max_iters):
        V, a, b = step(V, m, beta_prev)
        a, b = float(a), float(b)
        alphas.append(a)
        kk = min(k, m + 1)
        theta, S = eigh_tridiagonal(
            np.array(alphas), np.array(betas),
            select="i", select_range=(0, kk - 1))
        res = np.abs(b * S[-1, :])
        if m + 1 >= k and np.all(res < tol * np.maximum(1.0, np.abs(theta))):
            converged = True
            break
        if b < 1e-14:
            converged = (m + 1) >= k
            break
        betas.append(b)
        beta_prev = jnp.asarray(b)

    kk = min(k, len(alphas))
    theta, S = eigh_tridiagonal(
        np.array(alphas), np.array(betas[: len(alphas) - 1]),
        select="i", select_range=(0, kk - 1))
    evecs = None
    if compute_eigenvectors:
        Sj = jnp.asarray(S.astype(np.complex128 if
                                  np.issubdtype(np.dtype(dtype),
                                                np.complexfloating)
                                  else np.float64), dtype=dtype)
        E = (Sj.T @ V[: len(alphas)])
        evecs = []
        for i in range(kk):
            e = E[i]
            nrm = jnp.sqrt(jnp.real(jnp.vdot(e, e)))
            evecs.append(e / nrm.astype(dtype))
    return LanczosResult(
        eigenvalues=np.asarray(theta),
        eigenvectors=evecs,
        residual_norms=np.asarray(res if res is not None else []),
        num_iters=len(alphas),
        converged=converged,
    )


def lanczos(
    matvec: Callable,
    n: Optional[int] = None,
    k: int = 1,
    max_iters: int = 300,
    tol: float = 1e-10,
    seed: int = 0,
    v0=None,
    compute_eigenvectors: bool = False,
    full_reorth: bool = True,
) -> LanczosResult:
    """Lowest-``k`` eigenpairs of the Hermitian operator behind ``matvec``.

    ``v0`` (or ``n`` + ``seed``) fixes the start vector; convergence is the
    standard residual bound ``|β_m s_m,i| < tol·max(1,|θ_i|)`` for the k
    lowest Ritz pairs.

    Rank-1 (single-device) vectors take the fused fast path
    (:func:`_lanczos_fast`); sharded/hashed vectors use the collective-safe
    sequential loop below.
    """
    if v0 is None and n is not None and full_reorth:
        v0 = _rand_like((n,), np.float64, seed)
    if (v0 is not None and full_reorth
            and getattr(np.asarray(v0), "ndim", 0) == 1):
        return _lanczos_fast(matvec, v0, k, max_iters, tol,
                             compute_eigenvectors)
    if v0 is None:
        if n is None:
            raise ValueError("pass v0 or n")
        v0 = _rand_like((n,), np.float64, seed)
    v = jnp.asarray(v0)
    dtype = v.dtype
    nrm = jnp.sqrt(jnp.real(jnp.vdot(v, v)))
    v = v / nrm.astype(dtype)

    alphas: List[float] = []
    betas: List[float] = []
    V: List[jax.Array] = [v]
    v_prev = None
    converged = False
    m = 0
    res = None

    for m in range(1, max_iters + 1):
        w = matvec(V[-1])
        if isinstance(w, tuple):  # engines returning (y, counters)
            w = w[0]
        w = jnp.asarray(w)
        if m == 1 and w.dtype != dtype:
            # complex-Hermitian operator applied to a real start vector:
            # promote the whole recurrence (momentum sectors, symmetry.py)
            dtype = jnp.promote_types(dtype, w.dtype)
            V[0] = V[0].astype(dtype)
        w = w.astype(dtype)
        # Collective discipline: every inner product is scalarized (blocking)
        # immediately, so at most one collective program is in flight at a
        # time.  Overlapping all-reduce programs can deadlock the XLA CPU
        # collective rendezvous when the device pool is oversubscribed (the
        # virtual-device test substrate); on TPU this also keeps the solver's
        # psum latency deterministic.
        jax.block_until_ready(w)
        a = float(jnp.real(jnp.vdot(V[-1], w)))
        w = w - _scalar(a, dtype) * V[-1]
        if v_prev is not None:
            w = w - _scalar(betas[-1], dtype) * v_prev
        if full_reorth:
            # Two passes of classical Gram-Schmidt against the whole basis.
            for _ in range(2):
                for u in V:
                    c = complex(jnp.vdot(u, w))
                    w = w - _scalar(c, dtype) * u
        alphas.append(a)
        b = float(jnp.sqrt(jnp.real(jnp.vdot(w, w))))
        # Ritz values + residual bounds from the tridiagonal.
        kk = min(k, m)
        theta, S = eigh_tridiagonal(
            np.array(alphas), np.array(betas),
            select="i", select_range=(0, kk - 1))
        res = np.abs(b * S[-1, :])
        if m >= k and np.all(res < tol * np.maximum(1.0, np.abs(theta))):
            converged = True
            break
        if b < 1e-14:
            # Krylov space exhausted: every eigenpair it contains is exact,
            # but if fewer than k were found the start vector was deficient —
            # report not-converged so callers don't index missing pairs.
            converged = m >= k
            break
        betas.append(b)
        v_prev = V[-1]
        v = w / jnp.asarray(b).astype(dtype)
        V.append(v)

    kk = min(k, len(alphas))
    theta, S = eigh_tridiagonal(
        np.array(alphas), np.array(betas[: len(alphas) - 1]),
        select="i", select_range=(0, kk - 1))
    evecs = None
    if compute_eigenvectors:
        evecs = []
        for i in range(kk):
            acc = jnp.zeros_like(V[0])
            for j, u in enumerate(V[: len(alphas)]):
                acc = acc + jnp.asarray(S[j, i]).astype(dtype) * u
            nrm = jnp.sqrt(jnp.real(jnp.vdot(acc, acc)))
            evecs.append(acc / nrm.astype(dtype))
    return LanczosResult(
        eigenvalues=np.asarray(theta),
        eigenvectors=evecs,
        residual_norms=np.asarray(res if res is not None else []),
        num_iters=len(alphas),
        converged=converged,
    )
