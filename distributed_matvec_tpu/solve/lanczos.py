"""Thick-restart Lanczos eigensolver over a matvec closure.

The reference drives PRIMME (block Davidson/JDQMR — ``src/PRIMME.chpl``,
``src/Diagonalize.chpl:258-332``) through three callbacks: the distributed
matvec, a global sum, and a broadcast (``PRIMME.chpl:267-373``).  PRIMME is a
native C/Fortran library we don't vendor; the TPU-native replacement is a
**device-resident** thick-restart Lanczos:

* The Krylov basis lives in a fixed ``[m_cap+1, ...]`` device buffer and a
  whole *block* of iterations (matvec, two passes of blocked modified
  Gram-Schmidt as MXU matmuls, the (α, β) recurrence) runs as ONE jitted
  program (``lax.fori_loop``) with donated buffers — the host only syncs the
  small (α, β) arrays every ``check_every`` steps for the convergence test.
  A per-iteration host round-trip costs ~1 s over a tunneled device; the
  blocked form runs at matvec speed.
* Memory is bounded by **thick restarting** (the TRLan scheme): when the
  basis hits ``max_basis_size`` (the analog of the reference's
  ``kMaxBasisSize``, Diagonalize.chpl:169), the ``min_restart_size`` lowest
  Ritz vectors are kept (one [l, m]·[m, N] matmul on the MXU) together with
  the last residual vector; the projected matrix becomes
  arrowhead-plus-tridiagonal and the recurrence continues.
* For the distributed engine the vectors are hash-sharded ``[D, M]`` arrays;
  every inner product XLA emits is a psum over ICI — exactly the
  ``globalSumReal`` semantics (PRIMME.chpl:267-311).

Works with *any* dense vector layout: vectors are whatever ``matvec``
consumes/produces (``[N]`` for LocalEngine, ``[D, M]`` hashed for
DistributedEngine; padded slots are zero by engine invariant so dots are
exact).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from scipy.linalg import eigh

from ..obs import health as obs_health
from ..obs import memory as obs_memory
from ..obs import trace as obs_trace
from ..obs.events import emit as obs_emit, flush as obs_flush, obs_enabled
from ..utils import faults, preempt

__all__ = ["LanczosResult", "lanczos", "lanczos_block"]


def _emit_trace(solver: str, it: int, m: int, theta, res,
                omega: Optional[float] = None) -> None:
    """One per-convergence-check telemetry event: the current lowest Ritz
    values and their residual bounds (plus the ω orthogonality-loss
    estimate when the health layer computed one) — a stalled eigensolve is
    diagnosable from the event log alone (``obs_report summarize`` turns
    these into convergence plot data).  ``theta``/``res`` are small host
    arrays already; no device fetch happens here."""
    if not obs_enabled():
        return
    obs_emit("lanczos_trace", solver=solver, iter=int(it), basis_size=int(m),
             ritz=[float(t) for t in np.atleast_1d(theta)],
             residual=[float(r) for r in np.atleast_1d(res)],
             **({} if omega is None else {"omega": float(omega)}))


class _Watchdog:
    """Per-solve health state: Ritz-stagnation tracking plus the ω and
    breakdown checks, reported as ``solver_health`` events through
    :mod:`obs.health` (warn = log only; critical = one ``[Warn]`` line,
    or a :class:`~obs.health.HealthError` under ``DMT_HEALTH=strict``)."""

    #: consecutive convergence checks without a ≥1% residual improvement
    #: before a stagnation warning (restart plateaus are normal — one flat
    #: check is not a stall)
    STALL_CHECKS = 5

    def __init__(self, solver: str):
        self.solver = solver
        self.best_res = np.inf
        self.stalled = 0

    def report_omega(self, omega: Optional[float], it: int) -> None:
        """Threshold a precomputed ω estimate.  Called only on checks that
        did NOT converge: a converged check's estimate still rides the
        trace event, but a solve that just met its tolerance must not be
        failed (strict mode) by the same tiny β that delivered it."""
        if omega is None or not obs_health.probes_enabled():
            return
        if omega >= obs_health.OMEGA_CRITICAL:
            obs_health.record("orthogonality_loss", "critical",
                              solver=self.solver, iter=int(it),
                              omega=float(omega))
        elif omega >= obs_health.OMEGA_WARN:
            obs_health.record("orthogonality_loss", "warn",
                              solver=self.solver, iter=int(it),
                              omega=float(omega))

    def check_stagnation(self, res, it: int) -> None:
        if not obs_health.probes_enabled():
            return
        cur = float(np.max(np.atleast_1d(res)))
        if not np.isfinite(cur):
            obs_health.record("nonfinite_residual", "critical",
                              solver=self.solver, iter=int(it), residual=cur)
            return
        if cur < 0.99 * self.best_res:
            self.best_res = cur
            self.stalled = 0
            return
        self.stalled += 1
        if self.stalled >= self.STALL_CHECKS:
            obs_health.record("ritz_stagnation", "warn", solver=self.solver,
                              iter=int(it), residual=cur,
                              checks_without_progress=self.stalled)
            self.stalled = 0

    def breakdown(self, it: int, beta: float, converged: bool) -> None:
        """β-breakdown: the Krylov space closed.  Converged closure is the
        happy path (exact invariant subspace — no event); an UNCONVERGED
        breakdown means the solve cannot reach the tolerance and is
        critical."""
        if not obs_health.probes_enabled() or converged:
            return
        obs_health.record("beta_breakdown", "critical", solver=self.solver,
                          iter=int(it), beta=float(beta))

# Row-block size for the blocked Gram-Schmidt sweeps: live basis rows are
# visited in blocks of this many rows so the sweep cost scales with the
# *current* basis size m, not the buffer capacity.
_GS_BLOCK = 8


class _OmegaTracker:
    """Accumulated ω-recurrence (Paige/Simon) across selective-reorth blocks.

    Unlike :func:`~..obs.health.omega_estimate` — which assumes a full MGS
    pass resets the ω table every step and therefore reports only one-step
    amplification — this tracks the full table ω_{j,i} ≈ |⟨v_j, v_i⟩|
    across iterations that ran with WINDOW-only reorthogonalization, so the
    host loop can escalate to a full sweep *before* semiorthogonality
    (max ω ≤ √ε, Simon '84) is lost.  A full-reorth block (or a thick
    restart, which rebuilds the basis from Ritz combinations) resets the
    table to roundoff via :meth:`reset`.
    """

    def __init__(self, eps: float = 2.0 ** -52):
        self.eps = eps
        self.reset(0)

    def reset(self, m: int) -> None:
        self.m = int(m)
        # w_curr[i] = ω_{m,i} for i <= m (1 on the diagonal); w_prev the
        # m-1 row.  Baseline ε: the basis was just (re)orthogonalized.
        # w_prev's own diagonal (ω_{m-1,m-1} = 1) matters: the recurrence's
        # −β_{j−1}·ω_{j−1,i} term must cancel the β_{i}·ω_{j,i+1} term at
        # i = j−1, and an ε there instead of 1 leaves an O(β/β) ~ O(1)
        # residue that falsely trips the √ε gate on the first window block
        # after every full sweep.
        self.w_curr = np.full(self.m + 1, self.eps)
        self.w_curr[-1] = 1.0
        self.w_prev = np.full(max(self.m, 1), self.eps)
        if self.m >= 1:
            self.w_prev[-1] = 1.0

    def advance(self, alph: np.ndarray, bet: np.ndarray, m_new: int
                ) -> float:
        """Evolve the table through steps ``self.m .. m_new-1`` using the
        recorded (α, β) and return the max off-pair estimate at m_new.

        SIGNED arithmetic, exactly the Paige recurrence — an absolute-value
        upper bound compounds ~(Σβ)/β per step and saturates √ε within one
        16-step block, forcing a full sweep every other block (measured:
        the whole selective win evaporates); the signed form keeps the
        cancellation that makes real loss grow only as Ritz pairs converge.
        """
        a = np.asarray(alph, np.float64)
        b = np.asarray(bet, np.float64)
        worst = 0.0
        for j in range(self.m, int(m_new)):
            bj = max(float(b[j]), 1e-300)
            w, wp = self.w_curr, self.w_prev
            new = np.empty(j + 2)
            if j:
                i = np.arange(j)
                up = b[i] * w[i + 1]
                mid = (a[i] - a[j]) * w[i]
                dn = np.zeros(j)
                dn[1:] = b[i[1:] - 1] * w[i[1:] - 1]
                back = b[j - 1] * wp[i]
                # ϑ ≈ ε(β_i + β_j): the local roundoff injected per step
                new[:j] = (up + mid + dn - back
                           + self.eps * (b[i] + bj)) / bj
            new[j] = self.eps          # fresh adjacent pair (ψ term)
            new[j + 1] = 1.0
            self.w_prev = w
            self.w_curr = new
            if j:
                worst = max(worst, float(np.max(np.abs(new[:j]))))
        self.m = int(m_new)
        return worst


@dataclass
class LanczosResult:
    eigenvalues: np.ndarray          # [k] ascending
    eigenvectors: Optional[list]     # k vectors in the matvec's layout
    residual_norms: np.ndarray       # [k] |β_m · s_last| bound
    num_iters: int
    converged: bool
    resumed_from: int = 0            # iterations restored from a checkpoint
    #: thick (memory-bounding) restarts taken by a ``max_basis_size``-
    #: capped ``lanczos_block`` solve (narrowing restarts not counted)
    restarts: int = 0
    # steady-state rate bookkeeping: the first block pays jit compile, so
    # iters/sec is (num_iters - first_block_iters) / steady_seconds
    first_block_seconds: float = 0.0
    first_block_iters: int = 0
    steady_seconds: float = 0.0
    #: per-target results of a ``column_targets`` batch solve (the solve
    #: service's heterogeneous-convergence path), aligned with the
    #: targets list; None for ordinary solves
    column_results: Optional[list] = None

    @property
    def steady_iters_per_s(self) -> float:
        """Iteration rate excluding the compile-bearing first block; 0.0 when
        the solve finished inside the first block (no steady data)."""
        rest = self.num_iters - self.first_block_iters
        if rest > 0 and self.steady_seconds > 0:
            return rest / self.steady_seconds
        return 0.0


def _operator_key(owner) -> str:
    """Hash of the (basis, operator) pair behind an engine's matvec, used to
    key mid-solve checkpoints.  Delegates to the engines' shared
    ``hash_basis_operator`` with ``include_arrays=False`` (basis JSON +
    nonbranching term tables — everything that determines H as a matrix —
    but not the representative arrays, so shard-native engines whose basis
    is never materialized globally get the same key as a global build of
    the same problem).  Returns ``"bare"`` for non-engine callables."""
    op = getattr(owner, "operator", None)
    if op is None:
        return "bare"
    import hashlib

    from ..parallel.engine import hash_basis_operator

    h = hashlib.sha256()
    hash_basis_operator(h, op, include_arrays=False)
    return h.hexdigest()[:16]


def _sharded_ckpt_engine(owner, shape) -> bool:
    """True when the matvec's owner is a distributed engine whose hashed
    [D, M(, 2)] vector layout matches ``shape`` — the case where a
    multi-process checkpoint can be written per shard (each rank saves its
    addressable shards; no rank ever fetches the global Krylov basis).
    The capability probe is ``reshard.hashed_ckpt_engine`` — the SAME
    predicate that decides whether a save gets the topology stanza, so
    layout detection and stanza writing can never disagree."""
    from ..parallel.reshard import hashed_ckpt_engine
    return (hashed_ckpt_engine(owner)
            and len(shape) >= 2
            and shape[0] == owner.n_devices
            and shape[1] == owner.shard_size)


def _save_ckpt(path, fp, owner, V, meta, m, sharded) -> None:
    """One checkpoint write.  Single-controller: the live basis rows in one
    structure file (global array).  Multi-process engine-backed: each rank
    writes its shards of every Krylov row plus the (replicated) recurrence
    metadata in ONE atomic per-rank file — metadata and rows can never be
    of mixed generations, and a crash mid-save leaves the previous
    checkpoint intact.

    Engine-backed saves add the v2 TOPOLOGY STANZA (D, shard size,
    per-shard counts, partition fingerprint — ``parallel/reshard.py``) to
    the metadata, so a restore on a different device count reshards the
    snapshot instead of refusing it."""
    from ..parallel.reshard import topology_stanza
    meta = dict(meta, **topology_stanza(owner))
    if not sharded:
        from ..io.hdf5 import save_engine_structure
        save_engine_structure(path, fp, "lanczos",
                              dict(meta, V=np.asarray(V[: m + 1])))
        return
    from ..io.sharded_io import save_hashed_vectors
    from ..parallel.mesh import shard_spec

    spec = shard_spec(owner.mesh, V.ndim - 1)
    row = jax.jit(lambda Vb, i: Vb[i], out_shardings=spec)
    # one device row in flight at a time (a whole-basis dict of device
    # rows would transiently double HBM right at the basis-size cap);
    # host staging is this rank's shards only
    rows = {}
    for i in range(m + 1):
        r = row(V, jnp.int32(i))
        rows[f"krylov_{i}"] = {
            piece.index[0].start: np.asarray(piece.data)[0]
            for piece in r.addressable_shards}
        del r
    save_hashed_vectors(path, rows, owner.counts,
                        meta=dict(meta, fingerprint=fp))


def _soft_save_ckpt(path, fp, owner, V, meta, m, sharded,
                    solver: str = "lanczos", reason: str = "cadence") -> bool:
    """A checkpoint write that cannot kill the solve it protects: failures
    (full disk, read-only checkout, injected ``ckpt_write``/``ckpt_rename``
    faults) degrade to one ``log_warn`` plus a
    ``solver_checkpoint{status=failed}`` event — a run hundreds of
    iterations deep keeps going and tries again at the next cadence.
    Success emits the ``solver_checkpoint`` event the chaos gate and a
    post-mortem read to locate the last good generation."""
    try:
        _save_ckpt(path, fp, owner, V, meta, m, sharded)
    except OSError as e:
        from ..utils.logging import log_warn
        log_warn(f"{solver} checkpoint save failed ({e!r}); "
                 "solve continues without this generation")
        obs_emit("solver_checkpoint", solver=solver, status="failed",
                 reason=reason, path=str(path), error=repr(e),
                 iters=int(meta.get("total_iters", 0)))
        return False
    obs_emit("solver_checkpoint", solver=solver, status="written",
             reason=reason, path=str(path),
             iters=int(meta.get("total_iters", 0)))
    return True


def _partition_ok(meta, solver, path) -> bool:
    """Refusal-with-pointer when the checkpoint's partition fingerprint
    genuinely differs from this build's (a different shard hash): the
    shard snapshots are NOT a permutation of the new partition, so a
    reshard would scatter rows to wrong owners — refuse loudly, name both
    fingerprints, and let the caller start fresh."""
    from ..parallel.reshard import partition_fingerprint
    want = partition_fingerprint()
    got = str(meta.get("partition_fp", "") or "")
    if not got or got == want:
        return True
    from ..utils.logging import log_warn
    log_warn(
        f"{solver} checkpoint at {path} was partitioned under {got}; this "
        f"build partitions under {want} — the shard snapshots cannot be "
        "resharded onto a different partition.  Starting fresh (delete "
        "the checkpoint, or resume it on a build with the original "
        "shard hash)")
    obs_emit("solver_checkpoint", solver=solver, status="refused_partition",
             path=str(path), checkpoint_partition=got,
             build_partition=want)
    return False


def _reshard_degrade(solver, path, e) -> None:
    """A torn/partial reshard (injected ``ckpt_reshard`` fault, missing
    source shard, I/O failure) must degrade to a FRESH solve, never to a
    half-redistributed basis — one warn + one event, then the caller
    returns None."""
    from ..utils.logging import log_warn
    log_warn(f"{solver} checkpoint reshard failed ({e!r}); the restore "
             "degrades to a fresh solve")
    obs_emit("solver_checkpoint", solver=solver, status="reshard_failed",
             path=str(path), error=repr(e))


def _sharded_ckpt_meta(path, fp, legacy_fp):
    """``(meta, fp_used)`` for a sharded checkpoint scan: the primary
    topology-free fingerprint first, then the legacy fixed-D one —
    shared by the Lanczos and LOBPCG restores so the probe order can
    never diverge between the solvers."""
    from ..io.sharded_io import load_hashed_meta
    meta = load_hashed_meta(path, expected_fingerprint=fp)
    if meta is not None or legacy_fp is None:
        return meta, fp
    return load_hashed_meta(path, expected_fingerprint=legacy_fp), legacy_fp


def _needs_reshard(meta, owner) -> bool:
    """Whether the checkpoint's topology stanza names a layout other
    than the live engine's (stanza-free v1 metadata reads as matching —
    fixed topology by construction)."""
    src_d = int(meta.get("topology_d", owner.n_devices))
    src_counts = np.asarray(meta.get("topology_counts", owner.counts),
                            np.int64)
    return (src_d != int(owner.n_devices)
            or not np.array_equal(src_counts,
                                  np.asarray(owner.counts, np.int64)))


def _stage_reshard(path, fp, owner, meta, tail, n_rows, dtype):
    """Collective-free half of a D→D′ restore: build the routing plan
    and stage every source slice this rank's devices host.  Returns
    ``(plan, staged, dt, err)`` — err instead of raising, so the caller
    can fold the outcome into the fixed-point readiness agreement of
    :func:`_restore_sharded_rows` before any collective dispatches."""
    from ..io.sharded_io import hashed_shard_reader
    from ..parallel import reshard as _rs

    try:
        plan = _rs.Resharder(owner, int(meta["topology_d"]),
                             np.asarray(meta["topology_counts"], np.int64),
                             tail=tail)
        # scan-once reader: resolves the candidate .r* files one time
        # (O(m·D) fetches would otherwise re-glob per slice, billed to
        # resume_reshard_s) and rejects files whose own generation
        # disagrees with the selected meta — barrier-free per-rank saves
        # can leave mixed generations under one fingerprint, and the
        # reshard path deliberately reads DEPARTED ranks' files
        with hashed_shard_reader(path, expected_fingerprint=fp,
                                 match_meta=meta) as fetch:
            staged, dt = plan.stage_rows(
                lambda i, s: fetch(s, name=f"krylov_{i}"),
                n_rows, dtype=dtype)
        return plan, staged, dt, None
    except (_rs.PartitionMismatch, OSError, KeyError, ValueError) as e:
        return None, None, None, e


def _read_direct_rows(path, fp, owner, meta, n_rows, tail):
    """Collective-free fixed-D read: this rank's shards of every
    checkpointed row, assembled into ``[D, M, *tail]`` device rows.
    ``(rows, err)`` — same err-returning contract as
    :func:`_stage_reshard`."""
    from ..io.sharded_io import hashed_shard_reader

    M = owner.shard_size
    rows_out = []
    try:
        # match_meta scopes every fetch to the generation load_hashed_meta
        # selected — a stale same-fingerprint .r* file from before a thick
        # restart must fail the restore (KeyError → fresh), not splice its
        # old basis rows in
        with hashed_shard_reader(path, expected_fingerprint=fp,
                                 match_meta=meta) as fetch:
            for i in range(n_rows):
                pieces = [None] * owner.n_devices
                for d in range(owner.n_devices):
                    if not owner._shard_addressable(d):
                        continue
                    r = fetch(d, name=f"krylov_{i}")
                    # dtype from the stored rows: a complex snapshot
                    # (the evolve solver's state) must not silently
                    # cast through a float64 staging buffer
                    full = np.zeros((M,) + tail, dtype=r.dtype)
                    full[: r.shape[0]] = r
                    pieces[d] = full
                rows_out.append(owner._assemble_sharded(pieces))
        return rows_out, None
    except (OSError, KeyError, ValueError) as e:
        return None, e


def _restore_sharded_rows(path, fp, legacy_fp, owner, shape, solver,
                          dtype=None, expect_m=None):
    """Sharded-format restore, safe on process-spanning meshes: select
    the metadata (primary then legacy fingerprint), dispatch direct read
    vs staged D→D′ reshard, agree, exchange.  Returns ``(meta, rows)``
    with ``rows`` in the target ``[D, M, *tail]`` layout, or
    ``(None, None)`` for a fresh start.

    On a process-spanning engine every rank runs ONE fixed-shape
    readiness allgather at this FIXED point, no matter which local
    sub-path it took — metadata missing, partition refusal, torn
    staging, incomplete direct read.  Scattering the agreement across
    sub-paths would let ranks rendezvous on DIFFERENT collectives (one
    rank's meta probe fails → it skips to the caller's generation
    agreement while its peers sit in a staging vote) and hang the job.
    The token carries (ok, reshard?, rows, total_iters, topology_d), so
    ranks that prepared DIFFERENT restores — mixed generations, or one
    resharding while another reads direct — all degrade to fresh
    together; only a unanimous matching-token vote lets the exchange
    dispatch its ppermute rounds.  Staging holds every one-sided
    failure mode (file I/O, the injected ``ckpt_reshard`` fault); the
    exchange after a unanimous vote is one identical static program on
    every rank.

    ``expect_m`` rejects a metadata generation whose basis size is not
    the caller's (LOBPCG: the block width is fixed) before any staging.
    """
    import time as _time

    meta, fp_used = _sharded_ckpt_meta(path, fp, legacy_fp)
    if meta is not None and expect_m is not None \
            and int(meta["m"]) != int(expect_m):
        meta = None
    if meta is not None and _needs_reshard(meta, owner) \
            and not _partition_ok(meta, solver, path):
        meta = None               # refusal-with-pointer: no restore
    multi_span = bool(getattr(owner, "_multi", False))
    if meta is None and not multi_span:
        return None, None
    tail = tuple(shape[2:])
    reshard = meta is not None and _needs_reshard(meta, owner)
    n_rows = int(meta["m"]) + 1 if meta is not None else 0
    plan = staged = dt = rows = err = None
    t0 = _time.perf_counter()
    if reshard:
        plan, staged, dt, err = _stage_reshard(path, fp_used, owner, meta,
                                               tail, n_rows, dtype)
    elif meta is not None:
        rows, err = _read_direct_rows(path, fp_used, owner, meta, n_rows,
                                      tail)
    ok = meta is not None and err is None
    if multi_span:
        from jax.experimental import multihost_utils as _mhu
        tok = np.array(
            [int(ok), int(reshard), n_rows,
             int(meta["total_iters"]) if meta is not None else -1,
             int(meta.get("topology_d", owner.n_devices))
             if meta is not None else -1], np.int64)
        all_tok = _mhu.process_allgather(tok)
        ok = bool((all_tok[:, 0] == 1).all()
                  and (all_tok == all_tok[0]).all())
    if not ok:
        if err is not None and reshard:
            _reshard_degrade(solver, path, err)
        elif err is not None:
            from ..utils.logging import log_debug
            log_debug(f"{solver} sharded checkpoint incomplete ({err!r}); "
                      "starting fresh")
        elif multi_span and meta is not None:
            from ..utils.logging import log_debug
            log_debug(f"{solver} checkpoint restore readiness disagrees "
                      "across ranks; starting fresh")
        return None, None
    if reshard:
        rows = plan.exchange_rows(staged, dt)
        obs_emit("solver_checkpoint", solver=solver, status="resharded",
                 path=str(path), d_from=int(meta["topology_d"]),
                 d_to=int(owner.n_devices), rows=int(n_rows),
                 reshard_s=round(_time.perf_counter() - t0, 6))
    return meta, rows


def _global_rows_for_layout(got, owner, shape, solver, legacy_shape=None):
    """Row list for a SINGLE-CONTROLLER checkpoint payload ``got`` in the
    caller's vector layout ``shape``: direct when the stored topology
    matches, resharded (``parallel/reshard.py``) on a D→D′ mismatch,
    None (fresh start) when the rows fit neither.  ``legacy_shape``
    additionally accepts pre-stanza rows of that shape verbatim (the
    fixed-D v1 format — matching topology by construction)."""
    import time as _time

    V = got["V"]
    src_d = got.get("topology_d")
    if src_d is None or not hasattr(owner, "counts"):
        # legacy fixed-D checkpoint (or a bare-callable solve): rows must
        # already be in the caller's layout
        for want in (tuple(shape),) + ((tuple(legacy_shape),)
                                       if legacy_shape is not None else ()):
            if tuple(V.shape[1:]) == want:
                return [jnp.asarray(r) for r in V]
        return None
    src_d = int(src_d)
    counts = np.asarray(got["topology_counts"], np.int64)
    if not _needs_reshard(got, owner) and tuple(V.shape[1:]) == tuple(shape):
        return [jnp.asarray(r) for r in V]
    if not _partition_ok(got, solver, path="<engine_structure>"):
        return None
    t0 = _time.perf_counter()
    try:
        from ..parallel import reshard as _rs
        plan = _rs.Resharder(owner, src_d, counts, tail=tuple(shape[2:]))
        rows = plan.reshard_rows(
            lambda i, s: V[i, s, : counts[s]], V.shape[0], dtype=V.dtype)
    except (OSError, KeyError, ValueError) as e:      # PartitionMismatch
        _reshard_degrade(solver, "<engine_structure>", e)   # ⊂ ValueError
        return None
    obs_emit("solver_checkpoint", solver=solver, status="resharded",
             d_from=src_d, d_to=int(owner.n_devices), rows=int(V.shape[0]),
             reshard_s=round(_time.perf_counter() - t0, 6))
    return rows


def _restore_ckpt(path, fp, owner, shape, sharded, legacy_fp=None,
                  solver="lanczos", legacy_shape=None, dtype=None):
    """Inverse of :func:`_save_ckpt`; returns a dict with ``V_rows`` (list
    of per-row arrays in the vector layout) plus the recurrence metadata,
    or None when no matching checkpoint exists.

    ``legacy_fp`` additionally probes the pre-elastic shape-keyed
    fingerprint, so fixed-D v1 checkpoints still restore unchanged on a
    matching device count; ``legacy_shape`` is the per-row shape that
    format stored when it differs from ``shape`` (the distributed LOBPCG
    v1 format kept FLAT padded columns where v2 keeps hashed rows).
    ``dtype`` pins the row dtype for a sharded reshard (a rank whose
    devices host no source shard must still build dtype-consistent
    slabs).  A checkpoint whose topology stanza names a DIFFERENT device
    count is resharded onto the live topology (``parallel/reshard.py``)
    instead of refused; a reshard that cannot proceed (foreign partition
    fingerprint, torn source files, the injected ``ckpt_reshard`` fault)
    degrades to a fresh solve with one warn + ``solver_checkpoint``
    event.  A single-controller restore (``sharded=False``) whose
    base-path probe misses falls through to the sharded-format scan, so
    per-rank ``.r*`` files written by a larger multi-process incarnation
    still resume after an elastic shrink to one process."""
    if not sharded:
        from ..io.hdf5 import load_engine_structure
        got = load_engine_structure(path, fp)
        legacy = None
        if got is None and legacy_fp is not None:
            got = load_engine_structure(path, legacy_fp)
            legacy = legacy_shape if legacy_shape is not None else shape
        if got is not None:
            rows = _global_rows_for_layout(got, owner, shape, solver,
                                           legacy_shape=legacy)
            if rows is None:
                return None
            return dict(got, V_rows=rows)
        # The single-controller probe missed, but a LARGER multi-process
        # incarnation of this job may have left per-rank .r* files on
        # shared storage — an elastic shrink to ONE process must not
        # orphan them.  Fall through to the sharded-format scan when the
        # owner can consume the hashed layout: the reshard machinery
        # already reads departed ranks' files, the single-controller
        # restore just has to probe the format.
        if not _sharded_ckpt_engine(owner, shape):
            return None
    meta, rows_out = _restore_sharded_rows(path, fp, legacy_fp, owner,
                                           shape, solver, dtype=dtype)
    if meta is None:
        return None
    return dict(meta, V_rows=rows_out)


def _rand_like(shape, dtype, seed):
    rng = np.random.default_rng(seed)
    v = rng.standard_normal(shape)
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        v = v + 1j * rng.standard_normal(shape)
    return v.astype(dtype)


def _projected_matrix(alph, bet, lock_theta, lock_sigma, m):
    """Rayleigh projection T = V†HV in the current basis ``V[:m]``.

    Tridiagonal before the first restart; afterwards arrowhead (locked Ritz
    values on the diagonal, coupling row σ) + tridiagonal tail — the standard
    thick-restart structure.  Real symmetric even for complex-Hermitian H.
    """
    l = len(lock_theta)
    T = np.zeros((m, m))
    if l:
        T[:l, :l] = np.diag(lock_theta)
        T[l, :l] = lock_sigma
        T[:l, l] = lock_sigma
    for i in range(l, m):
        T[i, i] = alph[i]
    for i in range(l, m - 1):
        T[i + 1, i] = T[i, i + 1] = bet[i]
    return T


def _buffer_rows(mcap: int) -> int:
    """V-buffer row count: mcap+1 live rows padded up to a multiple of
    ``_GS_BLOCK`` so the blocked sweeps' ``dynamic_slice`` never clamps
    (a clamped start would desynchronize the row mask; pad rows stay zero
    and contribute nothing)."""
    return mcap + 1 + (-(mcap + 1)) % _GS_BLOCK


def _make_block_runner(mv, mcap, shape, dtype, n_reorth, pair=False):
    """One jitted program advancing the recurrence by ``nsteps`` iterations.

    State: V [_buffer_rows, *shape] basis buffer (donated), alph/bet [mcap]
    f64.  Each iteration: w = H·V[m]; α = ⟨v, w⟩; ``n_reorth`` passes of
    blocked MGS against the live rows; β = ‖w‖; V[m+1] = w/β.

    ``pair=True`` marks (re, im)-f64 pair vectors (trailing axis 2, the
    TPU-safe complex form).  The realified operator commutes with
    J: (re, im) ↦ (−im, re) (multiplication by i), so each eigenvalue of the
    complex H appears twice — once along v, once along J·v.  MGS therefore
    orthogonalizes against J·V as well: ⟨v, w⟩ and ⟨J·v, w⟩ are exactly
    Re and −Im of the complex ⟨z, w⟩, so the J-aware recurrence *is*
    complex-arithmetic Lanczos (each eigenvalue once, no phantom copies) —
    in pure f64.

    ``mv(x, operands)`` is a pure function: the engine's matrix tables ride
    in ``operands`` as real jit arguments.  Closing over them instead would
    bake gigabyte-scale constants into this program (see
    ``LocalEngine.bound_matvec``).
    """
    nflat = int(np.prod(shape))
    nrows = _buffer_rows(mcap)

    def J_rows(A):
        """Multiply-by-i on flattened pair rows: (re, im) → (−im, re)."""
        p = A.reshape(A.shape[:-1] + (nflat // 2, 2))
        return jnp.stack([-p[..., 1], p[..., 0]],
                         axis=-1).reshape(A.shape)

    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def run_block(V, alph, bet, m0, nsteps, operands):
        def mgs_pass(wf, Vf, m):
            # NOTE on form: the projections are written as elementwise
            # multiply + sum, NOT `Vb @ wf` / `c @ Vb` — XLA's f64
            # dot_general is ~10× slower than the fused elementwise reduce
            # on v5e (no f64 MXU; measured 16.5 vs 2.5 ms for a [48, 4.7M]
            # slab), and the reorth passes dominated the iteration at scale.
            def project(wf, Vb, mask):
                c = jnp.sum(Vb.conj() * wf[None, :], axis=1) \
                    * mask.astype(wf.dtype)
                return wf - jnp.sum(c[:, None] * Vb, axis=0)

            def one_block(r0, wf):
                Vb = jax.lax.dynamic_slice(
                    Vf, (r0, jnp.zeros((), r0.dtype)), (_GS_BLOCK, nflat))
                mask = (r0 + jnp.arange(_GS_BLOCK)) <= m
                wf = project(wf, Vb, mask)
                if pair:
                    wf = project(wf, J_rows(Vb), mask)
                return wf

            nblk = (m + 1 + _GS_BLOCK - 1) // _GS_BLOCK
            return jax.lax.fori_loop(
                0, nblk, lambda j, wf: one_block(j * _GS_BLOCK, wf), wf)

        def body(i, carry):
            V, alph, bet = carry
            m = m0 + i
            Vf = V.reshape(nrows, nflat)
            vm = jax.lax.dynamic_index_in_dim(Vf, m, keepdims=False)
            w = mv(vm.reshape(shape), operands)
            a = jnp.real(jnp.vdot(vm, w))
            wf = w.reshape(nflat)
            for _ in range(n_reorth):
                wf = mgs_pass(wf, Vf, m)
            b = jnp.sqrt(jnp.real(jnp.vdot(wf, wf)))
            vnew = (wf / jnp.where(b <= 1e-300, 1.0, b)).astype(dtype)
            V = jax.lax.dynamic_update_index_in_dim(
                Vf, vnew, m + 1, axis=0).reshape(V.shape)
            alph = alph.at[m].set(a)
            bet = bet.at[m].set(b)
            return V, alph, bet

        return jax.lax.fori_loop(0, nsteps, body, (V, alph, bet))

    return run_block


def _make_window_runner(mv, mcap, shape, dtype, n_reorth, nsteps,
                        pair=False):
    """Selective-reorthogonalization block: ``nsteps`` iterations whose MGS
    passes project only against the trailing ``W_ROWS`` rows.

    Structured around a SMALL ring buffer, not the big V carry: the full
    runner's ``fori_loop`` carries the whole [_buffer_rows, N] basis and
    XLA's CPU runtime copies that carry on every iteration (measured 28
    ms/iter for chain_20's 83 MB buffer — a floor that swallowed the whole
    selective win).  Here the loop carries only the [W_ROWS, N] window,
    ``lax.scan`` stacks the new vectors in place, and the basis buffer is
    written ONCE per block — the per-iteration traffic drops from O(mcap·N)
    to O(window·N).  ``nsteps`` is a compile-time constant (scan needs a
    static length); a solve sees at most a handful of distinct block
    lengths, each compiled once.

    The ω-gated host loop guarantees the window is enough: whenever the
    accumulated orthogonality estimate threatens √ε, the next block runs
    the full sweep via :func:`_make_block_runner`."""
    nflat = int(np.prod(shape))
    nrows = _buffer_rows(mcap)
    # the trailing window: v_m and v_{m-1} (the recurrence pair) plus two
    # more recent rows of slack — PROPACK's local reorthogonalization uses
    # exactly the pair; the ω gate upgrades to full sweeps when locality
    # stops being enough, so the window stays minimal
    W_ROWS = 4
    # one local MGS pass per step (the three-term recurrence + cleanup);
    # escalated blocks run the full runner with its n_reorth sweeps
    n_local = max(1, n_reorth - 1)

    def J_rows(A):
        p = A.reshape(A.shape[:-1] + (nflat // 2, 2))
        return jnp.stack([-p[..., 1], p[..., 0]],
                         axis=-1).reshape(A.shape)

    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def run_window(V, alph, bet, m0, operands):
        Vf = V.reshape(nrows, nflat)
        r0 = jnp.maximum(m0 - (W_ROWS - 1), 0)
        W = jax.lax.dynamic_slice(
            Vf, (r0, jnp.zeros((), r0.dtype)), (W_ROWS, nflat))
        # rows above m0 can be stale (short thick restarts leave old basis
        # rows beyond l) — zero them; zero rows project to nothing
        W = jnp.where(((r0 + jnp.arange(W_ROWS)) <= m0)[:, None], W, 0)
        # ring invariant: v_{m0} sits in the LAST row.  When m0 < W_ROWS-1
        # the clamped slice leaves it at index m0 — roll the (zeroed)
        # stale rows over the top
        W = jnp.roll(W, (W_ROWS - 1) - (m0 - r0), axis=0)

        def project(wf, Vb):
            c = jnp.sum(Vb.conj() * wf[None, :], axis=1)
            return wf - jnp.sum(c[:, None] * Vb, axis=0)

        def step(W, _i):
            vm = W[W_ROWS - 1]
            w = mv(vm.reshape(shape), operands)
            a = jnp.real(jnp.vdot(vm, w))
            wf = w.reshape(nflat)
            for _ in range(n_local):
                wf = project(wf, W)
                if pair:
                    wf = project(wf, J_rows(W))
            b = jnp.sqrt(jnp.real(jnp.vdot(wf, wf)))
            vnew = (wf / jnp.where(b <= 1e-300, 1.0, b)).astype(dtype)
            W = jnp.concatenate([W[1:], vnew[None]], axis=0)
            return W, (vnew, a, b)

        _, (Vnew, a_blk, b_blk) = jax.lax.scan(
            step, W, jnp.arange(nsteps))
        Vf = jax.lax.dynamic_update_slice(
            Vf, Vnew, (m0 + 1, jnp.zeros((), m0.dtype)))
        alph = jax.lax.dynamic_update_slice(alph, a_blk, (m0,))
        bet = jax.lax.dynamic_update_slice(bet, b_blk, (m0,))
        return Vf.reshape(V.shape), alph, bet

    return run_window


def _make_restart(mcap, shape, dtype, l):
    """V[:l] ← SᵀV[:m] (kept Ritz vectors), V[l] ← last residual vector."""
    nflat = int(np.prod(shape))
    nrows = _buffer_rows(mcap)

    @partial(jax.jit, donate_argnums=(0,))
    def restart(V, S_l):
        Vf = V.reshape(nrows, nflat)
        v_last = Vf[mcap]
        Y = jnp.tensordot(S_l.astype(dtype), Vf[:mcap], axes=[[0], [0]])
        Vf = jax.lax.dynamic_update_slice(Vf, Y, (0, 0))
        Vf = jax.lax.dynamic_update_index_in_dim(Vf, v_last, l, axis=0)
        return Vf.reshape(V.shape)

    return restart


def lanczos_block(matvec: Callable, *args, **kwargs) -> LanczosResult:
    """Solve-span wrapper over :func:`_lanczos_block_impl` (see there for
    the full contract): the solver call is ONE ``solve`` span, each block
    step an ``iteration`` span, and the eager engine applies inside nest
    as ``apply`` spans — the span tree ``obs_report trace`` exports."""
    with obs_trace.span("lanczos_block", kind="solve",
                        k=int(kwargs.get("k", args[1] if len(args) > 1
                                          else 1))):
        return _lanczos_block_impl(matvec, *args, **kwargs)


def _lanczos_block_impl(
    matvec: Callable,
    n: Optional[int] = None,
    k: int = 1,
    block_size: Optional[int] = None,
    max_iters: int = 200,
    tol: float = 1e-10,
    seed: int = 0,
    V0=None,
    compute_eigenvectors: bool = False,
    column_targets=None,
    max_basis_size: Optional[int] = None,
    min_restart_size: Optional[int] = None,
) -> LanczosResult:
    """Lowest-``k`` eigenpairs via *block* Lanczos over the batched matvec.

    Each step applies H to a whole ``[n, p]`` block in ONE engine call —
    the multi-RHS ELL apply gathers each structure row once and contracts
    over the p columns, so the per-vector cost drops well below p separate
    applies (the amortization PRIMME's blocked Davidson gets from
    ``kMaxBlockSize``, Diagonalize.chpl:171).  Block recurrence with full
    reorthogonalization (two MGS passes against every kept block) and QR
    between steps; the projected matrix is block tridiagonal
    ``[A_0 B_0ᵀ; B_0 A_1 …]``, and the residual bound for a Ritz pair
    (θ, s) is ``‖B_j · s[last p rows]‖``.

    **Thick restarts** (``max_basis_size``): by default the basis grows
    to ``max_iters`` vectors; with a cap, whenever the next step would
    exceed it the basis is COMPRESSED to the ``min_restart_size``
    (default: the block width) lowest Ritz vectors and the recurrence
    restarts from that block — the same compression-restart machinery
    the narrowing column exit uses (DESIGN.md §26/§29), so every
    reported residual stays an exact recurrence residual.  This bounds
    the Krylov workspace at ``max_basis_size`` columns — the only way a
    streamed-engine solve at the chain_36-class rung keeps its solver
    state in memory — at the price of more total iterations (each
    epoch restarts from the best Ritz subspace, so convergence stays
    monotone).  Pair-mode engines are refused — the J-aware
    reorthogonalization lives in :func:`lanczos`; complex sectors run
    natively here (CPU) or via :func:`lanczos` on TPU.

    ``max_iters`` counts *individual matvec columns* (p per block step),
    so budgets are comparable with :func:`lanczos`.

    Heterogeneous convergence (``column_targets``, the solve service's
    batched path — DESIGN.md §26): a list of ``{"k", "tol", "job_id"}``
    mappings, one per batched job.  Every target is judged each step
    against ITS OWN (k, tol) on the shared Ritz pairs; when a target
    converges its result is snapshotted (eigenvalues/residuals at that
    basis size) and its column EXITS the batch: the basis is compressed
    to the lowest Ritz vectors and the recurrence RESTARTS at the
    narrower width (restarted block Lanczos — naive column truncation
    would discard live Krylov directions and silently break the
    residual bound, so narrowing always goes through a restart; every
    reported residual is an exact recurrence residual).  The solve ends
    when every target is done (``converged`` = all converged);
    per-target records land in :attr:`LanczosResult.column_results`.
    Narrowing recompiles the engine apply per new width — worth it
    whenever the remaining work is more than a few steps (the AOT cache
    makes repeat widths free).

    Hashed multi-RHS: a :class:`~..parallel.distributed.DistributedEngine`
    behind ``matvec`` is driven natively in its hashed ``[D, M, p]``
    layout — pass ``V0`` of that shape, or pass neither ``V0`` nor ``n``
    and the start block comes from ``owner.random_hashed(seed, cols=p)``.
    Each block step is then ONE eager engine apply, so a STREAMED engine
    streams each plan chunk once per k-column block instead of once per
    column — this is the solver loop the streamed mode's amortization
    targets (eigenvectors come back in hashed layout).
    """
    owner = getattr(matvec, "__self__", None)
    if bool(getattr(owner, "pair", False)):
        streamed = getattr(owner, "mode", None) in ("streamed", "hybrid")
        raise ValueError(
            "lanczos_block does not support pair-mode engines "
            "(J-aware reorthogonalization lives in lanczos())"
            + ("; a PAIR-mode STREAMED engine currently has no in-tree "
               "solver — use mode='ell'/'fused' for pair sectors, or run "
               "the sector native-c128 on CPU" if streamed else ""))
    targets = None
    if column_targets is not None:
        targets = [{"k": int(t.get("k", 1)), "tol": float(t.get("tol", tol)),
                    "max_iters": int(t["max_iters"])
                    if t.get("max_iters") else None,
                    "job_id": t.get("job_id")} for t in column_targets]
        if not targets:
            raise ValueError("column_targets must be a non-empty sequence")
        k = max(int(k), max(t["k"] for t in targets))
    p = int(block_size or max(k, 2,
                              len(targets) if targets is not None else 0))
    if p < 1:
        raise ValueError(f"block_size must be >= 1, got {p}")
    if targets is not None and len(targets) > p:
        raise ValueError(f"{len(targets)} column targets need a block of "
                         f"at least that many columns, got {p}")
    mcap = l_thick = None
    if max_basis_size is not None:
        # restart width: the Ritz block the compression keeps — by
        # default max(width, 2k+2): keeping only the k targets starves
        # the restarted epoch near convergence (the residual directions
        # of converged pairs collapse the next QR into a breakdown
        # before the bound crosses tol — measured on chain_12 at
        # tol 1e-13), while 2k+2 is the same slack the single-vector
        # thick restart keeps.  The cap itself must leave the restart
        # block room to grow by two steps, or the recurrence could
        # never advance — undersized caps round UP to that minimum
        # rather than refuse.
        l_thick = max(int(min_restart_size) if min_restart_size
                      else max(p, 2 * k + 2), k, 1)
        mcap = max(int(max_basis_size), l_thick + 2 * p)

    hashed_owner = (owner is not None and hasattr(owner, "shard_size")
                    and hasattr(owner, "random_hashed"))
    if V0 is None:
        if n is None:
            if not hashed_owner:
                raise ValueError("pass V0 or n")
            V0 = owner.random_hashed(seed, cols=p)      # [D, M, p]
        else:
            V0 = _rand_like((n, p), np.float64, seed)
    V0 = jnp.asarray(V0)
    vec_shape = None         # non-None: hashed [D, M] engine layout
    if (hashed_owner and V0.ndim == 3
            and V0.shape[:2] == (owner.n_devices, owner.shard_size)):
        vec_shape = V0.shape[:2]
        V0 = V0.reshape(-1, V0.shape[2])   # flat [D·M, p] for the algebra
    if V0.ndim != 2:
        raise ValueError(f"V0 must be [n, p] (or hashed [D, M, p] for a "
                         f"distributed engine), got shape {V0.shape}")
    n, p = V0.shape

    def mv(X):
        # hashed engines consume/produce [D, M, p]; the dense algebra
        # (QR, projections) runs on the flat [D·M, p] view — pad slots are
        # zero by engine invariant, so inner products and factorizations
        # are exact.  Width read off X, not closed over: a column-target
        # solve narrows the block as jobs finish.
        pc = int(X.shape[1])
        Y = matvec(X.reshape(vec_shape + (pc,))) if vec_shape else matvec(X)
        Y = Y[0] if isinstance(Y, tuple) else Y
        return Y.reshape(-1, pc) if vec_shape else Y

    # Probe eagerly with the QR'd first block and REUSE the result as
    # step 0's apply: fixes the dtype (a complex-Hermitian operator
    # promotes a real block) and runs engine first-apply validation
    # without discarding a p-column matvec — the single most expensive
    # operation here.  QR commutes with the later real→complex cast.
    import time as _time
    t0 = _time.perf_counter()
    Q, _ = jnp.linalg.qr(V0)
    W0 = mv(Q)
    dtype = jnp.promote_types(V0.dtype, W0.dtype)
    Q = Q.astype(dtype)
    probe_s = _time.perf_counter() - t0
    blocks = [Q]                     # each [n, w_i], mutually orthonormal
    A_list: list = []                # diagonal blocks   [w_i, w_i]
    B_list: list = []                # subdiagonal blocks [w_{i+1}, w_i]
    widths: list = []                # per-step block widths (uniform at
    #                                  p_cur within an epoch — a narrowing
    #                                  restart resets these lists at the
    #                                  new width)
    theta = S = res = None
    converged = False
    total = 0
    p_cur = p
    n_restarts = 0
    a_seq: list = []        # scalarized per-step (α, β) for the ω estimate
    b_seq: list = []
    # thick-restart lock state (DESIGN.md §29): locked Ritz values, their
    # orthonormal basis block, and the residual coupling of the FIRST
    # active block to them — the block arrowhead, the same structure the
    # single-vector solver's (lock_theta, lock_sigma) carry.  Locked
    # vectors are never fed back through H (doing so collapses the next
    # QR once a pair converges); the recurrence continues from the NEXT
    # Krylov block, with the coupling keeping every residual exact.
    lock_theta = np.zeros(0)
    lock_Y = None                       # [n, l] locked Ritz block
    lock_C = None                       # [widths[0], l] coupling row

    def _ritz_block(S_cols, m_rows):
        """[n, c] Ritz combinations over the kept basis covering the
        first ``m_rows`` rows — locked rows first, then the active
        blocks (snapshots are taken at step ends, so block boundaries
        always align).  Reads the lock/blocks state at CALL time —
        valid for any snapshot taken since the last restart."""
        l0 = int(lock_theta.shape[0])
        Sj = jnp.asarray(S_cols, dtype=dtype)
        offs = np.concatenate(([0], np.cumsum(widths))).astype(int)
        nb = int(np.searchsorted(offs, m_rows - l0))
        out = sum(blocks[i] @ Sj[l0 + offs[i]: l0 + offs[i + 1]]
                  for i in range(nb))
        if l0:
            out = lock_Y @ Sj[:l0] + out
        return out

    def _assemble(S_cols, m_rows):
        """Normalized Ritz vectors in the matvec's layout."""
        E = _ritz_block(np.asarray(S_cols), m_rows)
        out = []
        for i in range(np.asarray(S_cols).shape[1]):
            e = E[:, i]
            e = e / jnp.sqrt(jnp.real(jnp.vdot(e, e))).astype(dtype)
            out.append(e.reshape(vec_shape) if vec_shape else e)
        return out

    first_block_s = 0.0
    first_block_iters = 0
    steady_s = 0.0
    watchdog = _Watchdog("lanczos_block")
    preempt.ensure_installed()
    agree_multi = jax.process_count() > 1 and (
        owner is None or bool(getattr(owner, "_multi", True)))
    obs_emit("solver_start", solver="lanczos_block", k=int(k),
             block_size=int(p), max_iters=int(max_iters), tol=float(tol),
             **({"column_targets": len(targets)} if targets else {}))

    # unbounded-basis solver: the block list GROWS — the ledger entry is
    # updated per appended block so forensics show the live footprint
    mem_h = obs_memory.NULL_HANDLE
    blk_path = None
    if obs_enabled():
        blk_path = (f"solver/{obs_memory.next_instance('lanczos_block')}"
                    "/block_basis")
        mem_h = obs_memory.track(blk_path, int(Q.nbytes),
                                 block_size=int(p))

    j = 0
    while True:
        faults.check("solver_block", exc=RuntimeError,
                     solver="lanczos_block", iter=int(total))
        # safe point between block steps (no checkpoint machinery here —
        # the block basis is unbounded; the exit is still clean and agreed
        # so a preempted streamed solve dies at a block boundary, not
        # inside a half-streamed plan pass)
        if preempt.agreed(agree_multi):
            obs_emit("solver_preempted", solver="lanczos_block",
                     iters=int(total), checkpoint="")
            obs_flush()
            mem_h.release()
            raise preempt.Preempted("lanczos_block", total, None)
        t0 = _time.perf_counter()
        # iteration span: one block step (p_cur matvec columns + the block
        # recurrence) — the eager engine apply inside nests as its child
        with obs_trace.span("iteration", kind="iteration",
                            solver="lanczos_block", iter=int(total),
                            block=j):
            Qj = blocks[-1]
            # step 0 reuses the probe's apply (timed via probe_s below)
            W = (W0 if j == 0 else mv(Qj)).astype(dtype)
            W0 = None
            A = Qj.conj().T @ W
            W = W - Qj @ A
            if B_list:          # empty right after a narrowing restart
                W = W - blocks[-2] @ B_list[-1].conj().T
            # full reorthogonalization, two passes, LOCKED block
            # included (classic block-Lanczos loss of orthogonality is
            # what makes the naive recurrence useless; the locked
            # coupling is carried by the arrowhead, so the projection
            # here just enforces exact orthogonality)
            for _ in range(2):
                for Qi in (() if lock_Y is None else (lock_Y,)) \
                        + tuple(blocks):
                    W = W - Qi @ (Qi.conj().T @ W)
            Qn, B = jnp.linalg.qr(W)
            jax.block_until_ready(Qn)
        dt = _time.perf_counter() - t0
        if j == 0:
            first_block_s, first_block_iters = dt + probe_s, p
        else:
            steady_s += dt
        A_list.append(np.asarray(A))
        B_list.append(np.asarray(B))
        widths.append(p_cur)
        total += p_cur
        l0 = int(lock_theta.shape[0])
        m = l0 + sum(widths)
        # scalarized (α, β) proxy for the ω-recurrence: the block analog of
        # β_j is the smallest new-direction magnitude min|diag(R_j)| — the
        # quantity whose collapse signals orthogonality/rank loss — and of
        # α_j the block's magnitude scale
        a_seq.append(float(np.max(np.abs(A_list[-1]))))
        b_seq.append(float(np.min(np.abs(np.diag(B_list[-1])))))

        # projected matrix (Hermitian by construction; A is numerically
        # Hermitian only to roundoff — symmetrize): block tridiagonal,
        # preceded after a thick restart by the arrowhead — locked Ritz
        # values on the diagonal, the coupling row against the first
        # active block.  Offsets come from the widths list; within one
        # epoch (between restarts, which reset these lists) every block
        # is p_cur wide, so all blocks here are square at widths[i]
        T = np.zeros((m, m), dtype=np.result_type(
            *(A_list + ([lock_C] if lock_C is not None else []))))
        if l0:
            T[:l0, :l0] = np.diag(lock_theta)
            w0 = widths[0]
            T[l0: l0 + w0, :l0] = lock_C
            T[:l0, l0: l0 + w0] = lock_C.conj().T
        off = l0
        for i, Ai in enumerate(A_list):
            w = widths[i]
            T[off: off + w, off: off + w] = (Ai + Ai.conj().T) / 2
            off += w
        off = l0
        for i, Bi in enumerate(B_list[:-1]):
            w0, w1 = widths[i], widths[i + 1]
            T[off + w0: off + w0 + w1, off: off + w0] = Bi
            T[off: off + w0, off + w0: off + w0 + w1] = Bi.conj().T
            off += w0
        kk = min(k, m)
        theta, S = eigh(T, subset_by_index=(0, kk - 1))
        res = np.linalg.norm(
            np.asarray(B_list[-1]) @ S[m - widths[-1]:, :], axis=0)
        omega = obs_health.omega_estimate(
            np.asarray(a_seq), np.asarray(b_seq),
            len(b_seq) - 1, len(b_seq)) \
            if obs_health.probes_enabled() else None
        _emit_trace("lanczos_block", total, m, theta, res, omega)
        newly_done = 0
        if targets is None:
            if m >= k and np.all(res < tol * np.maximum(1.0,
                                                        np.abs(theta))):
                converged = True
                break
        else:
            # heterogeneous convergence: every unfinished target judged
            # against ITS OWN (k, tol) on the shared Ritz pairs; a
            # converged target's result is snapshotted here and its
            # column exits below
            for t in targets:
                if t.get("done"):
                    continue
                kt = min(t["k"], kk)
                ok = m >= t["k"] and np.all(
                    res[:kt] < t["tol"]
                    * np.maximum(1.0, np.abs(theta[:kt])))
                # a target whose OWN column budget is spent exits too —
                # unconverged, exactly like its solo run would have: a
                # batch must never bill a job more columns than its spec
                # (and its admission pricing) allowed
                spent = (not ok and t["max_iters"] is not None
                         and total >= t["max_iters"])
                if not ok and not spent:
                    continue
                t["done"] = True
                t["snapshot"] = {
                    "theta": np.asarray(theta[:kt]).copy(),
                    "res": np.asarray(res[:kt]).copy(),
                    "S": np.asarray(S[:, :kt]).copy(),
                    "m": int(m), "iters": int(total),
                    "converged": bool(ok)}
                newly_done += 1
                obs_emit("solver_column_converged"
                         if ok else "solver_column_budget_exhausted",
                         solver="lanczos_block",
                         target_job_id=str(t.get("job_id") or ""),
                         k=int(t["k"]), iters=int(total),
                         basis_size=int(m), width=int(p_cur))
            if all(t.get("done") for t in targets):
                converged = all(t["snapshot"]["converged"]
                                for t in targets)
                break
        watchdog.report_omega(omega, total)
        # breakdown: the Krylov space closed (rank-deficient new block) —
        # with full reorth a deficient column is numerical noise, stop
        rdiag = np.abs(np.diag(np.asarray(B)))
        if rdiag.min() < 1e-12 * max(rdiag.max(), 1.0):
            watchdog.breakdown(total, float(rdiag.min()), converged=False)
            break
        if total + p_cur > max_iters:
            break
        watchdog.check_stagnation(res, total)
        if newly_done:
            remaining = [t for t in targets if not t.get("done")]
            p_new = max(len(remaining),
                        max(t["k"] for t in remaining), 1)
            if p_new < p_cur:
                # Column exit via a COMPRESSION RESTART: simply dropping
                # columns of the QR'd new block would discard genuine
                # Krylov directions and silently break the residual
                # bound (||B·s_last|| no longer accounts for the
                # discarded component — measured: a 1e-10 claim with a
                # 1e-6 true error).  Instead the basis is compressed to
                # the p_new lowest Ritz vectors and the recurrence
                # RESTARTS at the narrower width — restarted block
                # Lanczos, every subsequent residual an exact recurrence
                # residual again.  Finished targets' eigenvectors are
                # materialized first (their snapshots reference the
                # blocks this restart is about to drop).
                if compute_eigenvectors:
                    for t in targets:
                        snap = t.get("snapshot")
                        if snap is not None and "vecs" not in snap:
                            snap["vecs"] = _assemble(snap["S"], snap["m"])
                _, S_r = eigh(T, subset_by_index=(0, p_new - 1))
                Q0, _ = jnp.linalg.qr(_ritz_block(S_r, m))
                jax.block_until_ready(Q0)
                blocks = [Q0.astype(dtype)]
                A_list, B_list, widths = [], [], []
                a_seq, b_seq = [], []      # ω table resets with the basis
                # the narrowing compression folds any locked block into
                # Q0 (the _ritz_block above spans it) — lock state clears
                lock_theta = np.zeros(0)
                lock_Y = lock_C = None
                obs_emit("solver_restart_narrow", solver="lanczos_block",
                         iters=int(total), width=int(p_cur),
                         new_width=int(p_new), basis_size=int(m),
                         remaining=len(remaining))
                p_cur = p_new
                if blk_path is not None:
                    mem_h.set(blk_path,
                              int(sum(b.nbytes for b in blocks)))
                j += 1
                continue
        if mcap is not None and m + p_cur > mcap:
            # Thick (memory-bounding) restart — the TRLan scheme in
            # block form: keep the l_thick lowest Ritz vectors as a
            # LOCKED block, continue the recurrence from the NEXT
            # Krylov block Qn (already orthonormal to everything), and
            # carry the exact coupling C = B·S[last rows] into the
            # arrowhead of every later projection.  H is never applied
            # to the locked vectors again — re-applying it is what
            # collapses the next QR into a spurious breakdown once a
            # pair converges — and H·(basis·S) = basis·S·Θ + Qn·C
            # exactly, so every later residual bound stays an exact
            # recurrence residual.  Finished targets' eigenvectors are
            # materialized first: their snapshots reference the blocks
            # this restart drops.
            if compute_eigenvectors and targets:
                for t in targets:
                    snap = t.get("snapshot")
                    if snap is not None and "vecs" not in snap:
                        snap["vecs"] = _assemble(snap["S"], snap["m"])
            ll = min(int(l_thick), m - 1)
            theta_all, S_all = eigh(T)
            Y_new = _ritz_block(S_all[:, :ll], m).astype(dtype)
            C_new = np.asarray(B) @ S_all[m - widths[-1]:, :ll]
            jax.block_until_ready(Y_new)
            lock_theta = np.asarray(theta_all[:ll])
            lock_Y = Y_new
            lock_C = C_new             # [p_cur, ll]: next epoch's first
            blocks = [Qn]              # block is Qn, width p_cur
            A_list, B_list, widths = [], [], []
            a_seq, b_seq = [], []      # ω table resets with the basis
            n_restarts += 1
            obs_emit("solver_restart_thick", solver="lanczos_block",
                     iters=int(total), basis_size=int(m), kept=int(ll),
                     width=int(p_cur), cap=int(mcap))
            if blk_path is not None:
                mem_h.set(blk_path,
                          int(sum(b.nbytes for b in blocks)
                              + lock_Y.nbytes))
            j += 1
            continue
        blocks.append(Qn)
        if blk_path is not None:
            mem_h.set(blk_path, int(
                sum(b.nbytes for b in blocks)
                + (lock_Y.nbytes if lock_Y is not None else 0)))
        j += 1

    m_fin = int(lock_theta.shape[0]) + sum(widths)
    kk = min(k, m_fin) if m_fin else 0

    evecs = None
    if compute_eigenvectors and theta is not None:
        # `blocks` may hold one extra (not yet projected) block when the
        # loop ran to its last step — _assemble() stops at the m-th row
        evecs = _assemble(np.asarray(S[:, :kk]), m_fin)

    column_results = None
    if targets is not None:
        column_results = []
        for t in targets:
            snap = t.get("snapshot")
            if snap is None and theta is not None:
                # unfinished target: its best-so-far reading at the final
                # basis size, marked unconverged
                kt = min(t["k"], kk)
                snap = {"theta": np.asarray(theta[:kt]),
                        "res": np.asarray(res[:kt]),
                        "S": np.asarray(S[:, :kt]),
                        "m": int(m_fin), "iters": int(total),
                        "converged": False}
            entry = {"job_id": t.get("job_id"), "k": int(t["k"]),
                     "tol": float(t["tol"]),
                     "converged": bool(snap and snap["converged"]),
                     "eigenvalues": np.asarray(snap["theta"])
                     if snap else np.zeros(0),
                     "residuals": np.asarray(snap["res"])
                     if snap else np.zeros(0),
                     "iters": int(snap["iters"]) if snap else 0,
                     "basis_size": int(snap["m"]) if snap else 0}
            if compute_eigenvectors and snap is not None:
                # materialized at a narrowing restart when the snapshot's
                # blocks were dropped; assembled here otherwise
                entry["eigenvectors"] = snap.get("vecs") \
                    or _assemble(np.asarray(snap["S"]), snap["m"])
            column_results.append(entry)

    obs_emit("solver_end", solver="lanczos_block", iters=int(total),
             converged=bool(converged),
             eigenvalues=[float(t) for t in np.atleast_1d(theta)[:kk]]
             if theta is not None else [])
    mem_h.release()
    return LanczosResult(
        eigenvalues=np.asarray(theta[:kk]) if theta is not None
        else np.zeros(0),
        eigenvectors=evecs,
        residual_norms=np.asarray(res[:kk]) if res is not None
        else np.zeros(0),
        num_iters=total,
        converged=converged,
        restarts=n_restarts,
        first_block_seconds=first_block_s,
        first_block_iters=first_block_iters,
        steady_seconds=steady_s,
        column_results=column_results,
    )


def lanczos(matvec: Callable, *args, **kwargs) -> LanczosResult:
    """Solve-span wrapper over :func:`_lanczos_impl` — the whole solver
    call (setup, restore, every iteration block, the eigenvector
    epilogue) becomes ONE ``solve`` span, so a traced run's events nest
    iteration ⊂ solve even across preemption exits.  See
    :func:`_lanczos_impl` for the full contract."""
    with obs_trace.span("lanczos", kind="solve",
                        k=int(kwargs.get("k", args[1] if len(args) > 1
                                          else 1))):
        return _lanczos_impl(matvec, *args, **kwargs)


def _lanczos_impl(
    matvec: Callable,
    n: Optional[int] = None,
    k: int = 1,
    max_iters: int = 300,
    tol: float = 1e-10,
    seed: int = 0,
    v0=None,
    compute_eigenvectors: bool = False,
    full_reorth: bool = True,
    max_basis_size: Optional[int] = None,
    min_restart_size: Optional[int] = None,
    check_every: int = 16,
    pair: Optional[bool] = None,
    checkpoint_path: Optional[str] = None,
    checkpoint_every: int = 4,
    reorth: Optional[str] = None,
) -> LanczosResult:
    """Lowest-``k`` eigenpairs of the Hermitian operator behind ``matvec``.

    ``v0`` (or ``n`` + ``seed``) fixes the start vector; convergence is the
    standard residual bound ``|β_m s_m,i| < tol·max(1,|θ_i|)`` for the k
    lowest Ritz pairs.  ``max_basis_size``/``min_restart_size`` mirror the
    reference driver's ``kMaxBasisSize``/``kMinRestartSize``
    (Diagonalize.chpl:169-170) and bound device memory at
    ``(max_basis_size+1)`` vectors via thick restarts.

    ``pair`` marks (re, im)-f64 pair vectors (see ``_make_block_runner``);
    default: auto-detected from a pair-mode engine behind ``matvec``.

    ``checkpoint_path`` enables mid-solve checkpoint/resume (something the
    reference's PRIMME driver cannot do): every ``checkpoint_every``-th
    block boundary the live Krylov basis + recurrence state are written
    atomically, and a rerun with the same path, operator, and solver
    geometry resumes where it left off.  The checkpoint is keyed by the
    vector shape/dtype AND, when an engine is behind ``matvec``, by the
    operator itself (basis JSON + term tables), so a rerun against an
    edited Hamiltonian of the same size starts fresh instead of restoring
    a foreign Krylov state.  Bare callables are keyed by shape only —
    there, a fresh path per problem remains the caller's responsibility.
    In a multi-process run an ENGINE-backed solve checkpoints per shard
    (each rank atomically writes its addressable shards of every Krylov
    row + the replicated recurrence state to ``path.r<rank>``); bare
    callables have no per-shard layout and are ignored with a debug log.

    ``reorth`` picks the reorthogonalization policy (default: the
    ``lanczos_reorth`` config knob, ``"selective"``): ``"selective"`` runs
    each iteration's MGS pass against only a trailing window of recent
    vectors and, when the accumulated ω-recurrence estimate crosses √ε,
    DISCARDS the block and redoes it with the full sweep (window blocks
    never touch rows ≤ m, so rollback is free; an info-level
    ``solver_health`` event marks each trigger; the first block after a
    restart or resume is always full — the arrowhead coupling row must be
    projected out).  ``"full"`` is the pre-round-9 behavior: full MGS
    sweeps every iteration.
    """
    # Engines expose (apply_fn, operands) so the block runner can pass the
    # matrix tables as jit arguments; plain callables fall back to empty
    # operands (fine unless they close over very large device arrays).
    # Only the engine's own ``matvec`` method is substituted — any other
    # bound method (shifted/wrapped/global-layout variants) must keep its
    # semantics and goes through the generic fallback.
    owner = getattr(matvec, "__self__", None)
    if pair is None:
        pair = bool(getattr(owner, "pair", False))
    if getattr(owner, "mode", None) in ("streamed", "hybrid"):
        raise ValueError(
            "lanczos() traces the matvec into one jitted block program, "
            "which a streamed/hybrid engine cannot provide (its plan "
            "lives in host RAM and streams per apply) — streamed/hybrid "
            "engines are driven by the EAGER solver family instead: "
            "solve.lanczos_block (eigenpairs; multi-RHS block applies "
            "stream each plan chunk once per block, thick-restartable "
            "via max_basis_size), solve.kpm (Chebyshev/KPM spectral "
            "densities), and solve.evolve (Krylov exp(-iHt) time "
            "evolution)")
    if reorth is None:
        from ..utils.config import get_config
        reorth = get_config().lanczos_reorth
    if reorth not in ("selective", "full"):
        raise ValueError(
            f"unknown reorth policy {reorth!r} (use selective | full)")

    if v0 is None:
        if n is None:
            raise ValueError("pass v0 or n")
        v0 = _rand_like((n, 2) if pair else (n,), np.float64, seed)
    elif pair and np.iscomplexobj(v0):
        # warm starts may arrive in complex form; the recurrence (and the
        # engine's bound apply_fn) runs on (re, im)-f64 pair vectors
        from ..ops.kernels import pair_from_complex
        v0 = pair_from_complex(np.asarray(v0))
    v = jnp.asarray(v0)
    shape = v.shape
    if pair and (len(shape) < 2 or shape[-1] != 2):
        raise ValueError(
            f"pair-mode Lanczos needs an [..., 2] (re, im) f64 start vector "
            f"(or complex v0), got shape {shape}")

    # Probe matvec once eagerly: fixes the recurrence dtype (a complex
    # Hermitian operator promotes a real start vector) and lets engines run
    # their first-apply counter checks outside of jit.
    w_probe = matvec(v)
    if isinstance(w_probe, tuple):
        w_probe = w_probe[0]
    dtype = jnp.promote_types(v.dtype, w_probe.dtype)
    del w_probe

    if (owner is not None and hasattr(owner, "bound_matvec")
            and getattr(matvec, "__func__", None)
            is getattr(type(owner), "matvec", None)):
        apply_fn, operands = owner.bound_matvec()
    else:
        apply_fn, operands = (lambda x, _ops: matvec(x)), ()

    def mv(x, ops):
        y = apply_fn(x, ops)
        return (y[0] if isinstance(y, tuple) else y).astype(dtype)

    mcap = max_basis_size or min(max(4 * k + 16, 96), max_iters + 1)
    mcap = max(mcap, k + 2)
    l_restart = min_restart_size or max(2 * k + 2, min(mcap // 3, 24))
    l_restart = int(np.clip(l_restart, k, mcap - 2))
    n_reorth = 2 if full_reorth else 1

    V = jnp.zeros((_buffer_rows(mcap),) + shape, dtype)
    nrm = jnp.sqrt(jnp.real(jnp.vdot(v, v)))
    V = V.at[0].set((v / nrm.astype(dtype)).astype(dtype))
    alph_d = jnp.zeros(mcap, jnp.float64)
    bet_d = jnp.zeros(mcap, jnp.float64)

    # Block programs compiled lazily: ONE full-sweep runner (dynamic step
    # count) and, in selective mode, a window runner per distinct block
    # length (scan needs a static length; a solve sees only a handful).  A
    # selective solve that never trips the ω gate compiles only the cheap
    # window program(s).
    _runners: dict = {}

    def run_steps(full_pass: bool, V, alph, bet, m, nsteps, operands):
        if full_pass:
            rb = _runners.get("full")
            if rb is None:
                rb = _runners["full"] = _make_block_runner(
                    mv, mcap, shape, dtype, n_reorth, pair=pair)
            return rb(V, alph, bet, jnp.int32(m), jnp.int32(nsteps),
                      operands)
        key = ("window", int(nsteps))
        rw = _runners.get(key)
        if rw is None:
            rw = _runners[key] = _make_window_runner(
                mv, mcap, shape, dtype, n_reorth, int(nsteps), pair=pair)
        return rw(V, alph, bet, jnp.int32(m), operands)

    restart_fn = _make_restart(mcap, shape, dtype, l_restart)

    # the Krylov buffer is the solver's whole device footprint — register
    # it in the memory ledger for the solve's lifetime (released at normal
    # completion; a failed solve keeps the entry live, which is what an
    # OOM forensics report should show)
    mem_h = obs_memory.NULL_HANDLE
    if obs_enabled():
        mem_h = obs_memory.track(
            f"solver/{obs_memory.next_instance('lanczos')}/krylov_basis",
            int(V.nbytes) + int(alph_d.nbytes) + int(bet_d.nbytes),
            rows=int(_buffer_rows(mcap)))

    lock_theta = np.zeros(0)
    lock_sigma = np.zeros(0)
    m = 0                       # live basis: V[0..m] (m completed steps)
    total_iters = 0
    converged = False
    theta = S = res = None

    # keyed by the vector space AND (when an engine is behind the matvec)
    # the operator itself — NOT by solver geometry, so a rerun with a
    # different max_iters / basis bound still resumes (the saved rows are
    # valid in any buffer that fits them), but a rerun against an EDITED
    # Hamiltonian with the same lattice size (same shape) refuses the
    # foreign Krylov state instead of silently restoring it.  Bare
    # callables fall back to shape-only keying (documented caller
    # responsibility).
    #
    # Engine-backed hashed solves key TOPOLOGY-FREE (lanczos-v3): the
    # (D, M) layout dims are deliberately out of the fingerprint — the
    # operator key + row tail identify the vector SPACE — so a checkpoint
    # written at D devices is FOUND at D′ and resharded on restore
    # (parallel/reshard.py).  The legacy shape-keyed v2 fingerprint is
    # still probed on restore, so pre-elastic fixed-D checkpoints resume
    # unchanged on a matching device count.
    hashed_layout = _sharded_ckpt_engine(owner, shape)
    if hashed_layout:
        ckpt_fp = (f"hashed{tuple(shape[2:])}|{np.dtype(dtype).str}"
                   f"|{_operator_key(owner)}|lanczos-v3")
        legacy_fp = (f"{tuple(shape)}|{np.dtype(dtype).str}"
                     f"|{_operator_key(owner)}|lanczos-v2")
    else:
        ckpt_fp = (f"{tuple(shape)}|{np.dtype(dtype).str}"
                   f"|{_operator_key(owner)}|lanczos-v2")
        legacy_fp = None
    resumed_from = 0
    multi = jax.process_count() > 1
    # Multi-process checkpointing needs a per-shard vector format (no rank
    # can fetch the global Krylov basis): available for engine-backed
    # matvecs over hashed [D, M(, 2)] vectors; bare callables stay
    # single-controller-only.
    sharded_ckpt = multi and hashed_layout
    if checkpoint_path and multi and not sharded_ckpt:
        from ..utils.logging import log_debug
        log_debug("lanczos checkpointing disabled: multi-process run with "
                  "a non-engine matvec (no per-shard vector layout)")
        checkpoint_path = None
    if checkpoint_path:
        got = _restore_ckpt(checkpoint_path, ckpt_fp, owner, shape,
                            sharded=sharded_ckpt, legacy_fp=legacy_fp,
                            dtype=np.dtype(dtype))
        if sharded_ckpt and (owner is None
                             or bool(getattr(owner, "_multi", True))):
            # Per-rank checkpoint files are written without a barrier, so
            # ranks can observe different generations (or one none at all).
            # Resuming from mixed states would desynchronize the SPMD
            # collective programs — agree on (m, total_iters) and start
            # fresh everywhere unless every rank restored the same state.
            # Rank-local-mesh engines (_multi False) skip the agreement:
            # their solves are process-local.  For a TRUE process-spanning
            # engine a FAILED agreement collective propagates and kills
            # the rank — deliberately NOT the local-fallback arm
            # agree_restored uses for plan caches.  There a rebuild is
            # bit-identical to a restore, so a locally-kept verdict is
            # harmless; here fresh and resumed solver states genuinely
            # differ, and a rank deciding "fresh" locally while a peer's
            # allgather succeeded (it contributed our token before we
            # raised) would desynchronize the very SPMD programs this
            # agreement exists to protect.  Any backend that can run a
            # process-spanning engine can run this collective.
            from jax.experimental import multihost_utils as _mhu
            tok = np.array([got["m"], got["total_iters"]]
                           if got is not None else [-1, -1], np.int64)
            all_tok = _mhu.process_allgather(tok)
            if not (all_tok >= 0).all() or \
                    not (all_tok == all_tok[0]).all():
                if got is not None:
                    from ..utils.logging import log_debug
                    log_debug("lanczos checkpoint generations disagree "
                              "across ranks; starting fresh")
                got = None
        if got is not None:
            rows = int(got["m"]) + 1
            if rows > _buffer_rows(mcap) or int(got["m"]) > mcap:
                from ..utils.logging import log_debug
                log_debug("lanczos checkpoint basis exceeds max_basis_size; "
                          "starting fresh")
            else:
                for i, row in enumerate(got["V_rows"]):
                    V = V.at[i].set(row)
                na = min(int(got["m"]), mcap)
                alph_d = alph_d.at[:na].set(
                    jnp.asarray(got["alph"][:na]))
                bet_d = bet_d.at[:na].set(jnp.asarray(got["bet"][:na]))
                lock_theta = np.asarray(got["lock_theta"])
                lock_sigma = np.asarray(got["lock_sigma"])
                m = int(got["m"])
                total_iters = resumed_from = int(got["total_iters"])
    blocks_done = 0

    if m:
        # Rayleigh-Ritz on the restored state up front: a resume whose
        # budget is already spent still returns the checkpointed estimates
        # (and may exit converged immediately) instead of empty arrays
        alph = np.asarray(alph_d)
        bet = np.asarray(bet_d)
        kk = min(k, m)
        T = _projected_matrix(alph, bet, lock_theta, lock_sigma, m)
        theta, S = eigh(T, subset_by_index=(0, kk - 1))
        res = np.abs(bet[m - 1] * S[m - 1, :])
        if m >= k and np.all(res < tol * np.maximum(1.0, np.abs(theta))):
            converged = True

    import time as _time

    first_block_s = 0.0
    first_block_iters = 0
    steady_s = 0.0
    watchdog = _Watchdog("lanczos")
    preempt.ensure_installed()
    # the preemption latch needs cross-rank agreement only when the
    # solve's collectives actually span processes — a rank-local-mesh
    # engine in a multi-process job preempts independently
    agree_multi = multi and (owner is None
                             or bool(getattr(owner, "_multi", True)))
    obs_emit("solver_start", solver="lanczos", k=int(k),
             max_iters=int(max_iters), tol=float(tol), pair=bool(pair),
             max_basis_size=int(mcap), resumed_from=int(resumed_from),
             reorth=str(reorth))
    if m and theta is not None:
        _emit_trace("lanczos", total_iters, m, theta, res)

    # Selective-reorth state: the accumulated ω table, and whether the
    # NEXT block must run the full sweep.  The first block after a resume
    # (m > 0: the checkpointed basis's ω history is unknown) and after
    # every thick restart (the arrowhead coupling row must be projected
    # out of w = H·v_l against ALL locked rows) is always full.
    selective = reorth == "selective"
    omega_tr = _OmegaTracker() if selective else None
    pending_full = bool(m)
    if selective:
        # warm the dynamic-step full runner with a ZERO-step call: short
        # remainder blocks, restarts, and ω fallbacks then reuse its
        # compiled program instead of landing a compile inside the
        # steady-rate window (the window program compiles in the first —
        # rate-excluded — block)
        V, alph_d, bet_d = run_steps(True, V, alph_d, bet_d, m, 0,
                                     operands)

    while total_iters < max_iters and not converged:
        if m == mcap:
            # Thick restart at the TOP of the loop (a resumed checkpoint
            # may arrive with a full buffer): keep the l lowest Ritz
            # vectors + the residual vector; the projection becomes
            # arrowhead + tridiagonal.
            alph = np.asarray(alph_d)
            bet = np.asarray(bet_d)
            T = _projected_matrix(alph, bet, lock_theta, lock_sigma, m)
            l = l_restart   # clipped to <= mcap-2 at setup; restart_fn
            theta_all, S_all = eigh(T)   # hard-codes the residual row at l
            V = restart_fn(V, jnp.asarray(S_all[:, :l]))
            lock_theta = theta_all[:l].copy()
            lock_sigma = bet[m - 1] * S_all[m - 1, :l]
            m = l
            pending_full = True
        nsteps = min(check_every, mcap - m, max_iters - total_iters)
        # tiny remainder stubs (< half a block) reuse the prewarmed
        # dynamic-step full runner: a fresh window program would spend
        # more wall on its compile than the handful of iterations saves.
        # Half-block-or-larger lengths get window programs — pre-restart
        # remainders recur every restart cycle, so their one compile
        # amortizes.
        used_full = (not selective or pending_full
                     or nsteps < max(check_every // 2, 1))
        pending_full = False
        t0 = _time.perf_counter()
        # iteration span: one convergence-check block of nsteps Lanczos
        # steps (the applies run INSIDE the jitted block program, so the
        # block is the finest host-visible iteration granule here)
        with obs_trace.span("iteration", kind="iteration",
                            solver="lanczos", iter=int(total_iters),
                            steps=int(nsteps)):
            V, alph_d, bet_d = run_steps(
                used_full, V, alph_d, bet_d, m, nsteps, operands)
            jax.block_until_ready(V)   # one collective program in flight
        if selective and not used_full:
            om_acc = omega_tr.advance(np.asarray(alph_d),
                                      np.asarray(bet_d), m + nsteps)
            if om_acc >= obs_health.OMEGA_WARN:   # √ε — Simon's bound
                # ω crossed √ε inside the window block: semiorthogonality
                # is no longer guaranteed and cannot be repaired after the
                # fact — but the block only WROTE rows above m, so the
                # pre-block state is intact.  Discard it and redo the same
                # steps with the full sweep (iterations are counted once;
                # only the wall clock pays).
                # level "info": a trigger near convergence is the scheme
                # WORKING (loss grows exactly as Ritz pairs converge),
                # not a health problem — the zero-warning gate of `make
                # health-check` must not fail a healthy converged solve
                obs_emit("solver_health",
                         check="selective_reorth_fallback", level="info",
                         solver="lanczos", iter=int(total_iters + nsteps),
                         omega=float(om_acc))
                with obs_trace.span("iteration", kind="iteration",
                                    solver="lanczos",
                                    iter=int(total_iters),
                                    steps=int(nsteps), redo=True):
                    V, alph_d, bet_d = run_steps(
                        True, V, alph_d, bet_d, m, nsteps, operands)
                    jax.block_until_ready(V)
                used_full = True
        dt = _time.perf_counter() - t0
        if first_block_iters == 0:
            first_block_s, first_block_iters = dt, nsteps
        else:
            steady_s += dt
        alph = np.asarray(alph_d)
        bet = np.asarray(bet_d)
        m += nsteps
        total_iters += nsteps

        # Breakdown: a ~zero β means the Krylov space closed at that step;
        # discard the garbage steps after it.
        lo = len(lock_theta)
        broke = None
        for i in range(max(lo, m - nsteps), m):
            if bet[i] < 1e-14:
                broke = i
                break
        if broke is not None:
            m = broke + 1

        if selective and used_full:
            # the full sweep left every new vector orthogonal to the
            # whole live basis — the ω table restarts at roundoff
            omega_tr.reset(m)

        kk = min(k, m)
        T = _projected_matrix(alph, bet, lock_theta, lock_sigma, m)
        theta, S = eigh(T, subset_by_index=(0, kk - 1))
        res = np.abs(bet[m - 1] * S[m - 1, :])
        omega = obs_health.omega_estimate(alph, bet, max(lo, m - nsteps), m) \
            if obs_health.probes_enabled() else None
        _emit_trace("lanczos", total_iters, m, theta, res, omega)
        if m >= k and np.all(res < tol * np.maximum(1.0, np.abs(theta))):
            converged = True
            break
        watchdog.report_omega(omega, total_iters)
        if broke is not None:
            # Krylov space closed without meeting the tolerance
            watchdog.breakdown(total_iters, float(bet[broke]),
                               converged=False)
            break
        watchdog.check_stagnation(res, total_iters)

        blocks_done += 1
        # chaos site at the block boundary: `delay=` stretches a solve so
        # the chaos gate can land a kill mid-iteration deterministically;
        # inert (shared no-op) when DMT_FAULT is unset
        faults.check("solver_block", exc=RuntimeError, solver="lanczos",
                     iter=int(total_iters))
        # safe point: the recurrence state is host-consistent and no
        # collective is in flight — the latch verdict is agreed across
        # ranks so every rank checkpoints the SAME generation and exits
        # together (DESIGN.md §21).  ckpt_meta (four D2H fetches) is built
        # only when a save actually happens — the plain hot loop pays
        # nothing here.
        cadence_due = bool(checkpoint_path) \
            and blocks_done % max(checkpoint_every, 1) == 0
        preempted = preempt.agreed(agree_multi)
        if cadence_due or (preempted and checkpoint_path):
            _soft_save_ckpt(
                checkpoint_path, ckpt_fp, owner, V, {
                    "alph": np.asarray(alph_d), "bet": np.asarray(bet_d),
                    "lock_theta": np.asarray(lock_theta),
                    "lock_sigma": np.asarray(lock_sigma),
                    "m": int(m), "total_iters": int(total_iters)},
                m, sharded_ckpt,
                reason="cadence" if cadence_due else "preempt")
        if preempted:
            obs_emit("solver_preempted", solver="lanczos",
                     iters=int(total_iters),
                     checkpoint=checkpoint_path or "")
            obs_flush()
            mem_h.release()
            raise preempt.Preempted("lanczos", total_iters,
                                    checkpoint_path)

    kk = min(k, m)
    evecs = None
    if compute_eigenvectors and m:
        Vf = V.reshape(_buffer_rows(mcap), -1)
        Sj = jnp.asarray(S[:, :kk].astype(
            np.complex128 if np.issubdtype(np.dtype(dtype), np.complexfloating)
            else np.float64), dtype=dtype)
        E = jnp.tensordot(Sj, Vf[:m], axes=[[0], [0]])
        evecs = []
        for i in range(kk):
            e = E[i]
            enrm = jnp.sqrt(jnp.real(jnp.vdot(e, e)))
            evecs.append((e / enrm.astype(dtype)).reshape(shape))
    obs_emit("solver_end", solver="lanczos", iters=int(total_iters),
             converged=bool(converged),
             eigenvalues=[float(t) for t in np.atleast_1d(theta)[:kk]]
             if theta is not None else [])
    mem_h.release()
    return LanczosResult(
        eigenvalues=np.asarray(theta[:kk]) if theta is not None
        else np.zeros(0),
        eigenvectors=evecs,
        residual_norms=np.asarray(res[:kk]) if res is not None
        else np.zeros(0),
        num_iters=total_iters,
        resumed_from=resumed_from,
        converged=converged,
        first_block_seconds=first_block_s,
        first_block_iters=first_block_iters,
        steady_seconds=steady_s,
    )
