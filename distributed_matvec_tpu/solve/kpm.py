"""Chebyshev / kernel-polynomial spectral densities (KPM) over the engines.

Everything the repo computed before this module is extremal eigenpairs;
the kernel polynomial method opens the FULL spectrum for the same matvec
cost model: the density of states (and any spectral function) is
reconstructed from Chebyshev moments ``mu_n = Tr[T_n(H~)]`` where ``H~``
is the Hamiltonian rescaled into (-1, 1), and every moment is nothing
but repeated matvec against a FIXED operator — the best-case workload
for the streamed/hybrid plan amortization (DESIGN.md §20/§23/§28): the
plan is resolved and encoded ONCE at engine build and then re-streamed
per apply for hundreds of moments.

Three pieces (DESIGN.md §29):

* :func:`spectral_bounds` — a short plain Lanczos pass (no
  reorthogonalization, no stored basis: bounds only) whose extremal
  Ritz values, widened by their residual bounds plus a safety margin,
  bracket the spectrum.  KPM diverges if any eigenvalue maps outside
  [-1, 1], so the margin is applied OUTWARD on both ends.
* :func:`kpm_moments` — the three-term recurrence
  ``t_{j+1} = 2 H~ t_j - t_{j-1}`` over a block of ``n_vectors`` seeded
  random columns in the engine's native layout (hashed ``[D, M, R]``
  for distributed engines — the moments batch through the SAME
  multi-RHS apply path ``lanczos_block`` uses, so a streamed engine
  streams each plan chunk once per moment step, not once per vector).
  Moments come in pairs per apply (the standard doubling identities
  ``mu_{2j} = 2<t_j, t_j> - mu_0``, ``mu_{2j-1} = 2<t_j, t_{j-1}> -
  mu_1``), so ``n_moments`` moments cost ~``n_moments/2`` applies.
  The stochastic-trace estimate is the column mean: for isotropic
  normalized random vectors ``E[<r|A|r>] = Tr A / N``, so the averaged
  moments are the NORMALIZED moments of a unit-mass density.
* :func:`reconstruct_dos` / :func:`jackson_kernel` /
  :func:`lorentz_kernel` — the kernel-damped Chebyshev series summed on
  an energy grid.  Jackson is the DOS default (strictly positive,
  near-Gaussian broadening ~ pi/n_moments); Lorentz suits Green's
  functions.

Solver contracts match the eigensolvers: a preemption latch checked at
moment-step boundaries (SIGTERM → checkpoint → ``Preempted`` → exit 75
from the apps), checkpoint/resume through the SAME topology-portable
machinery as the Lanczos Krylov basis (the recurrence state is two
layout vectors + the host moment table; a resume restores bit-identical
state, so resumed moment series equal uninterrupted ones exactly), and
``solve > iteration > apply`` trace spans.

Pair-mode engines (the TPU (re, im)-f64 complex form) are refused with
a pointer: the recurrence would need the J-aware projections that live
in ``lanczos()``; complex sectors run native c128 on CPU.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import memory as obs_memory
from ..obs import trace as obs_trace
from ..obs.events import emit as obs_emit, flush as obs_flush, obs_enabled
from ..utils import faults, preempt
from .lanczos import (_operator_key, _rand_like, _restore_ckpt,
                      _sharded_ckpt_engine, _soft_save_ckpt)

__all__ = ["KPMResult", "spectral_bounds", "kpm_moments", "kpm_dos",
           "kpm_spectral_function", "jackson_kernel", "lorentz_kernel",
           "reconstruct_dos", "exact_moments"]


def _refuse_pair(owner, what: str) -> None:
    if bool(getattr(owner, "pair", False)):
        raise ValueError(
            f"{what} does not support pair-mode engines (the (re, im)-f64 "
            "recurrence needs the J-aware projections that live in "
            "solve.lanczos) — run the sector native-c128 on CPU, or use "
            "a real sector")


def _mv_fn(matvec: Callable):
    """Tuple-stripping eager apply (same contract as lanczos_block)."""
    def mv(x):
        y = matvec(x)
        return y[0] if isinstance(y, tuple) else y
    return mv


def _col_dots(a, b) -> jax.Array:
    """Per-column Re<a_r, b_r> over layout axes: [R] f64.  Pad slots are
    zero by engine invariant, so the flat reduction is exact; for a
    complex-Hermitian operator the diagonal/adjacent Chebyshev products
    are real up to roundoff — the real part IS the moment."""
    R = a.shape[-1]
    af = a.reshape(-1, R)
    bf = b.reshape(-1, R)
    return jnp.real(jnp.sum(af.conj() * bf, axis=0))


def spectral_bounds(matvec: Callable, n: Optional[int] = None,
                    v0=None, iters: int = 64, seed: int = 0,
                    margin: float = 0.05) -> Tuple[float, float, int]:
    """Safe spectral bracket ``(emin, emax, n_applies)`` via a short
    Lanczos pass.

    Plain three-term recurrence, no reorthogonalization and no stored
    basis (orthogonality loss only duplicates converged extremal Ritz
    values — harmless for a bracket): ``iters`` eager applies, then the
    tridiagonal eigenvalues.  The bracket widens each end by that end's
    residual bound ``|beta_m * s_m|`` PLUS ``margin`` of the Ritz span —
    the safety margin KPM needs (a single eigenvalue outside [-1, 1]
    makes the Chebyshev recurrence diverge geometrically, so the
    conservative direction is always outward; the only cost of a loose
    bracket is mildly coarser energy resolution per moment).
    """
    from scipy.linalg import eigh_tridiagonal

    mv = _mv_fn(matvec)
    owner = getattr(matvec, "__self__", None)
    _refuse_pair(owner, "spectral_bounds")
    if v0 is None:
        if owner is not None and hasattr(owner, "random_hashed"):
            v0 = owner.random_hashed(seed)
        elif n is not None:
            v0 = _rand_like((n,), np.float64, seed)
        else:
            raise ValueError("pass v0 or n")
    v = jnp.asarray(v0)
    nrm = jnp.sqrt(jnp.real(jnp.vdot(v, v)))
    w0 = mv(v)                                   # probe fixes the dtype
    dtype = jnp.promote_types(v.dtype, w0.dtype)
    v = (v / nrm.astype(v.dtype)).astype(dtype)
    w0 = (w0 / nrm.astype(w0.dtype)).astype(dtype)
    v_prev = jnp.zeros_like(v)
    alph, bet = [], []
    napply = 0
    for j in range(max(int(iters), 2)):
        w = w0 if j == 0 else mv(v)
        napply += 0 if j == 0 else 1             # probe reused as apply 0
        w0 = None
        a = float(jnp.real(jnp.vdot(v, w)))
        w = w - a * v - (bet[-1] * v_prev if bet else 0.0)
        b = float(jnp.sqrt(jnp.real(jnp.vdot(w, w))))
        alph.append(a)
        if b <= 1e-300:                          # Krylov space closed:
            bet.append(0.0)                      # bounds are exact
            break
        bet.append(b)
        v_prev, v = v, (w / b).astype(dtype)
    napply += 1
    m = len(alph)
    theta, S = eigh_tridiagonal(np.asarray(alph), np.asarray(bet[:m - 1]))
    res_lo = abs(bet[-1] * S[m - 1, 0])
    res_hi = abs(bet[-1] * S[m - 1, -1])
    span = max(float(theta[-1] - theta[0]), 1e-12)
    emin = float(theta[0] - res_lo - margin * span)
    emax = float(theta[-1] + res_hi + margin * span)
    obs_emit("kpm_bounds", emin=emin, emax=emax, iters=int(m),
             ritz_lo=float(theta[0]), ritz_hi=float(theta[-1]),
             res_lo=float(res_lo), res_hi=float(res_hi),
             margin=float(margin))
    return emin, emax, napply


@dataclass
class KPMResult:
    moments: np.ndarray            # [n_moments] normalized mu_n (mu_0 = 1)
    moment_stderr: np.ndarray      # [n_moments] stderr over the R columns
    bounds: Tuple[float, float]    # (emin, emax) bracket actually used
    scale: Tuple[float, float]     # (a, b): H~ = (H - b)/a
    n_vectors: int
    num_applies: int               # engine applies (bounds pass included)
    resumed_from: int = 0          # moment STEPS restored from a checkpoint
    # rate bookkeeping, same convention as LanczosResult: the first
    # recurrence apply pays compile + first plan stream
    first_block_seconds: float = 0.0
    first_block_moments: int = 0
    steady_seconds: float = 0.0

    @property
    def steady_moments_per_s(self) -> float:
        rest = len(self.moments) - self.first_block_moments
        if rest > 0 and self.steady_seconds > 0:
            return rest / self.steady_seconds
        return 0.0


def kpm_moments(matvec: Callable, n_moments: int = 256,
                n: Optional[int] = None, n_vectors: int = 4,
                seed: int = 0, V0=None,
                bounds: Optional[Tuple[float, float]] = None,
                bounds_iters: int = 64, margin: float = 0.05,
                checkpoint_path: Optional[str] = None,
                checkpoint_every: int = 64,
                check_every: int = 32) -> KPMResult:
    """Solve-span wrapper over :func:`_kpm_moments_impl` (full contract
    there): the whole moment run is ONE ``solve`` span, each recurrence
    step an ``iteration`` span, eager engine applies nest as ``apply``
    spans — the tree ``obs_report trace`` exports."""
    with obs_trace.span("kpm", kind="solve", n_moments=int(n_moments)):
        return _kpm_moments_impl(
            matvec, n_moments, n=n, n_vectors=n_vectors, seed=seed, V0=V0,
            bounds=bounds, bounds_iters=bounds_iters, margin=margin,
            checkpoint_path=checkpoint_path,
            checkpoint_every=checkpoint_every, check_every=check_every)


def _kpm_moments_impl(matvec, n_moments, n=None, n_vectors=4, seed=0,
                      V0=None, bounds=None, bounds_iters=64, margin=0.05,
                      checkpoint_path=None, checkpoint_every=64,
                      check_every=32) -> KPMResult:
    """Stochastic-trace Chebyshev moments of the operator behind
    ``matvec``.

    ``V0`` (engine-layout ``[..., R]`` block of NORMALIZED columns)
    overrides the seeded random block — the spectral-function path
    passes ``O|psi>/||O|psi>||`` here.  ``bounds`` skips the Lanczos
    bracket when the caller already knows one (it is stored in the
    checkpoint, so a RESUME always reuses the original scale — the
    trajectory stays bit-consistent even if a fresh bracket pass would
    land on slightly different floats).

    Checkpoint/resume (``checkpoint_path``): every
    ``checkpoint_every``-th step the two live recurrence vectors + the
    host moment table are written through the same atomic,
    topology-portable machinery as the Lanczos Krylov basis
    (``_save_ckpt``/``_restore_ckpt``); the fingerprint bakes in the
    operator key, layout tail, dtype and the (n_moments, R, seed)
    geometry, so a rerun against an edited Hamiltonian or different
    moment plan starts fresh instead of restoring foreign state.
    """
    if int(n_moments) < 2:
        raise ValueError(f"n_moments must be >= 2, got {n_moments}")
    if V0 is None and int(n_vectors) < 1:
        # guard BEFORE random_hashed: cols=0 falls into its scalar form
        # and the recurrence would silently treat shard slots as columns
        raise ValueError(f"n_vectors must be >= 1, got {n_vectors}")
    n_moments = int(n_moments)
    mv = _mv_fn(matvec)
    owner = getattr(matvec, "__self__", None)
    _refuse_pair(owner, "kpm_moments")

    v0_given = V0 is not None
    if V0 is None:
        if owner is not None and hasattr(owner, "random_hashed"):
            V0 = owner.random_hashed(seed, cols=int(n_vectors))
        elif n is not None:
            V0 = _rand_like((n, int(n_vectors)), np.float64, seed)
            V0 = V0 / np.linalg.norm(V0, axis=0, keepdims=True)
        else:
            raise ValueError("pass V0 or n")
    V0 = jnp.asarray(V0)
    R = int(V0.shape[-1])
    shape = V0.shape

    # probe apply reused as the j=0 recurrence apply (fixes dtype, runs
    # the engine's first-apply counter validation, and is the single
    # most expensive operation here — never discard it)
    t_wall = time.perf_counter()
    y0 = mv(V0)
    napply = 1
    dtype = jnp.promote_types(V0.dtype, y0.dtype)
    t0 = V0.astype(dtype)
    first_s = time.perf_counter() - t_wall

    hashed_layout = _sharded_ckpt_engine(owner, shape)
    base = (f"hashed{tuple(shape[2:])}" if hashed_layout
            else f"{tuple(shape)}")
    ckpt_fp = (f"{base}|{np.dtype(dtype).str}|{_operator_key(owner)}"
               f"|kpm-v1|m{n_moments}|r{R}|s{int(seed)}")
    multi = jax.process_count() > 1
    sharded_ckpt = multi and hashed_layout
    if checkpoint_path and multi and not sharded_ckpt:
        from ..utils.logging import log_debug
        log_debug("kpm checkpointing disabled: multi-process run with a "
                  "non-engine matvec (no per-shard vector layout)")
        checkpoint_path = None
    # RESTORE probe before any bounds pass: a resume must reuse the
    # STORED scale (the recurrence continues in exactly the rescaling
    # it started in), so re-running the ~bounds_iters-apply Lanczos
    # bracket just to discard it would waste a third of a typical run
    resumed_from = 0
    got = None
    if checkpoint_path:
        got = _restore_ckpt(checkpoint_path, ckpt_fp, owner, shape,
                            sharded=sharded_ckpt, solver="kpm",
                            dtype=np.dtype(dtype))
    mu_cols = np.zeros((n_moments, R))
    if got is not None:
        t_lo, t_hi = (r.astype(dtype) for r in got["V_rows"][:2])
        mu_saved = np.asarray(got["mu_cols"])
        mu_cols[: mu_saved.shape[0]] = mu_saved
        j = int(got["j"])
        filled = int(got["filled"])
        resumed_from = j
        a, b = float(got["scale_a"]), float(got["scale_b"])
        emin, emax = b - a, b + a
        obs_emit("solver_resume", solver="kpm", iters=int(j),
                 moments_filled=int(filled))
    else:
        if bounds is None:
            # an explicit start block also seeds the bounds pass (its
            # first column): the spectral-function path has no `n` and
            # no random draw, and a deterministic bracket keeps reruns
            # bit-identical
            bv0 = V0[..., 0] if v0_given else None
            emin, emax, nb = spectral_bounds(
                matvec, n=n, v0=bv0, iters=bounds_iters, seed=seed + 1,
                margin=margin)
            napply += nb
        else:
            emin, emax = float(bounds[0]), float(bounds[1])
        if not emax > emin:
            raise ValueError(
                f"degenerate spectral bounds ({emin}, {emax})")
        a = (emax - emin) / 2.0
        b = (emax + emin) / 2.0
        # per-column moment table on the host; mu_0 = <r|r> = 1 exactly
        # for normalized columns, mu_1 = <r|H~|r>
        t_lo, t_hi = t0, ((y0.astype(dtype) - b * t0) / a)
        mu_cols[0] = np.asarray(_col_dots(t_lo, t_lo))
        mu_cols[1] = np.asarray(_col_dots(t_lo, t_hi))
        # j: highest recurrence index for which t_j is live in `t_hi`
        j = 1
        filled = 2
    del y0

    agree_multi = jax.process_count() > 1 and (
        owner is None or bool(getattr(owner, "_multi", True)))
    preempt.ensure_installed()
    obs_emit("solver_start", solver="kpm", n_moments=n_moments,
             n_vectors=R, emin=emin, emax=emax,
             bounds_iters=int(bounds_iters),
             resumed_from=int(resumed_from))

    mem_h = obs_memory.NULL_HANDLE
    if obs_enabled():
        mem_h = obs_memory.track(
            f"solver/{obs_memory.next_instance('kpm')}/chebyshev_pair",
            2 * int(t_lo.nbytes), n_vectors=R)

    def save_ckpt(reason):
        V = jnp.stack([t_lo, t_hi])
        _soft_save_ckpt(checkpoint_path, ckpt_fp, owner, V, {
            "mu_cols": mu_cols[:filled].copy(), "j": int(j),
            "filled": int(filled), "scale_a": float(a),
            "scale_b": float(b), "m": 1, "total_iters": int(j)},
            1, sharded_ckpt, solver="kpm", reason=reason)

    steady_s = 0.0
    # the probe apply (compile + first plan stream) is the first block;
    # every loop pass after it is steady-state.  A resumed run's
    # restored moments cost THIS run nothing — they count as "first
    # block" so the steady rate divides only work actually done here
    first_moments = 2 if resumed_from == 0 else filled
    # each loop pass: harvest the doubling pair for the CURRENT t_j,
    # then advance the recurrence by one apply
    while filled < n_moments:
        faults.check("solver_block", exc=RuntimeError, solver="kpm",
                     iter=int(j))
        preempted = preempt.agreed(agree_multi)
        if preempted:
            if checkpoint_path:
                save_ckpt("preempt")
            obs_emit("solver_preempted", solver="kpm", iters=int(j),
                     checkpoint=checkpoint_path or "")
            obs_flush()
            mem_h.release()
            raise preempt.Preempted("kpm", j, checkpoint_path)
        t_step = time.perf_counter()
        with obs_trace.span("iteration", kind="iteration", solver="kpm",
                            iter=int(j)):
            # doubling identities at index j (t_lo = t_{j-1}, t_hi = t_j)
            if 2 * j - 1 < n_moments and 2 * j - 1 >= filled:
                mu_cols[2 * j - 1] = \
                    2.0 * np.asarray(_col_dots(t_hi, t_lo)) - mu_cols[1]
                filled += 1
            if 2 * j < n_moments and 2 * j >= filled:
                mu_cols[2 * j] = \
                    2.0 * np.asarray(_col_dots(t_hi, t_hi)) - mu_cols[0]
                filled += 1
            if filled < n_moments:
                y = mv(t_hi).astype(dtype)
                napply += 1
                t_lo, t_hi = t_hi, (2.0 / a) * y - (2.0 * b / a) * t_hi \
                    - t_lo
                jax.block_until_ready(t_hi)
                j += 1
        steady_s += time.perf_counter() - t_step
        if checkpoint_path and j % max(int(checkpoint_every), 1) == 0:
            save_ckpt("cadence")
        if obs_enabled() and j % max(int(check_every), 1) == 0:
            obs_emit("kpm_trace", solver="kpm", iter=int(j),
                     filled=int(filled),
                     mu_last=float(np.mean(mu_cols[max(filled - 1, 0)])))

    mu = mu_cols.mean(axis=1)
    stderr = (mu_cols.std(axis=1) / np.sqrt(max(R, 1))
              if R > 1 else np.zeros(n_moments))
    obs_emit("solver_end", solver="kpm", iters=int(j),
             converged=True, n_moments=int(n_moments),
             num_applies=int(napply))
    mem_h.release()
    return KPMResult(
        moments=mu, moment_stderr=stderr, bounds=(emin, emax),
        scale=(a, b), n_vectors=R, num_applies=napply,
        resumed_from=resumed_from,
        first_block_seconds=first_s,
        first_block_moments=first_moments,
        steady_seconds=steady_s)


# -- kernels and reconstruction -------------------------------------------

def jackson_kernel(n_moments: int) -> np.ndarray:
    """Jackson damping ``g_n`` — the DOS default: the reconstructed
    density is strictly positive and each delta broadens to a
    near-Gaussian of width ~ pi * a / n_moments (Weisse et al.,
    Rev. Mod. Phys. 78, 275 (2006), Eq. 71)."""
    N = int(n_moments)
    nn = np.arange(N)
    q = np.pi / (N + 1)
    return ((N - nn + 1) * np.cos(q * nn)
            + np.sin(q * nn) / np.tan(q)) / (N + 1)


def lorentz_kernel(n_moments: int, lam: float = 4.0) -> np.ndarray:
    """Lorentz damping — delta functions broaden to Lorentzians (the
    right shape for Green's-function resolvents); ``lam`` trades
    resolution (small) against damping (large)."""
    N = int(n_moments)
    nn = np.arange(N)
    return np.sinh(lam * (1.0 - nn / N)) / np.sinh(lam)


def _kernel(name: str, n_moments: int, lam: float) -> np.ndarray:
    if name == "jackson":
        return jackson_kernel(n_moments)
    if name == "lorentz":
        return lorentz_kernel(n_moments, lam)
    if name in (None, "none"):
        return np.ones(int(n_moments))
    raise ValueError(f"unknown KPM kernel {name!r} "
                     "(use jackson | lorentz | none)")


def reconstruct_dos(moments: np.ndarray, scale: Tuple[float, float],
                    energies: Optional[np.ndarray] = None,
                    npoints: int = 512, kernel: str = "jackson",
                    lam: float = 4.0) -> Tuple[np.ndarray, np.ndarray]:
    """Kernel-damped Chebyshev series → density on an energy grid.

    ``rho(E) = (1 / (pi a sqrt(1 - x^2))) * [g_0 mu_0 + 2 sum_n g_n
    mu_n T_n(x)]`` with ``x = (E - b)/a``.  The default grid is the
    Chebyshev-node grid ``x_k = cos(pi (k + 1/2) / K)`` (uniform
    resolution in the angle variable — the grid KPM results are usually
    quoted on); pass ``energies`` for an explicit grid, which is clipped
    strictly inside the bracket so the ``1/sqrt(1-x^2)`` weight stays
    finite.  Normalized moments (``mu_0 = 1``) integrate to unit mass.
    """
    a, b = float(scale[0]), float(scale[1])
    mu = np.asarray(moments, np.float64)
    N = mu.shape[0]
    g = _kernel(kernel, N, lam)
    coeff = g * mu
    coeff[1:] *= 2.0
    if energies is None:
        k = np.arange(int(npoints))
        x = np.cos(np.pi * (k + 0.5) / int(npoints))[::-1]
    else:
        x = np.clip((np.asarray(energies, np.float64) - b) / a,
                    -1.0 + 1e-12, 1.0 - 1e-12)
    rho_x = np.polynomial.chebyshev.chebval(x, coeff) \
        / (np.pi * np.sqrt(1.0 - x * x))
    return a * x + b, rho_x / a


def exact_moments(eigenvalues, scale: Tuple[float, float],
                  n_moments: int) -> np.ndarray:
    """Normalized Chebyshev moments of a KNOWN spectrum — the reference
    side of broadening-aware DOS comparisons: push these through
    :func:`reconstruct_dos` with the SAME kernel as the stochastic
    moments and the residual is pure trace noise, never resolution
    mismatch (used by the bench's ``kpm_dos_rel_err`` and
    ``make dynamics-check``)."""
    a, b = float(scale[0]), float(scale[1])
    ang = np.arccos(np.clip(
        (np.asarray(eigenvalues, np.float64) - b) / a, -1.0, 1.0))
    return np.array([np.mean(np.cos(k * ang))
                     for k in range(int(n_moments))])


def kpm_dos(matvec: Callable, n_moments: int = 256,
            n: Optional[int] = None, n_vectors: int = 4, seed: int = 0,
            npoints: int = 512, kernel: str = "jackson", lam: float = 4.0,
            bounds: Optional[Tuple[float, float]] = None,
            bounds_iters: int = 64, margin: float = 0.05,
            checkpoint_path: Optional[str] = None,
            checkpoint_every: int = 64):
    """Density of states in one call: moments + reconstruction.
    Returns ``(energies, rho, KPMResult)`` — ``rho`` integrates to 1
    (per-state density; multiply by ``n_states`` for a count density).
    """
    res = kpm_moments(matvec, n_moments, n=n, n_vectors=n_vectors,
                      seed=seed, bounds=bounds, bounds_iters=bounds_iters,
                      margin=margin, checkpoint_path=checkpoint_path,
                      checkpoint_every=checkpoint_every)
    energies, rho = reconstruct_dos(res.moments, res.scale,
                                    npoints=npoints, kernel=kernel,
                                    lam=lam)
    return energies, rho, res


def kpm_spectral_function(matvec: Callable, psi, op_apply: Callable,
                          n_moments: int = 256, npoints: int = 512,
                          kernel: str = "jackson", lam: float = 4.0,
                          bounds: Optional[Tuple[float, float]] = None,
                          bounds_iters: int = 64, margin: float = 0.05):
    """Dynamical structure factor ``S(E) = <psi|O† delta(E - H) O|psi>``.

    ``op_apply`` applies the (bound) observable O in the solve engine's
    layout (``models/observables.bind_observables`` produces exactly
    such engines sharing the basis artifacts).  The moments are the
    single-vector Chebyshev moments of ``phi = O|psi>`` — the same
    recurrence, start block ``phi/||phi||``, with the density weighted
    by ``||phi||^2``.  Returns ``(energies, S, KPMResult, weight)``.
    """
    phi = op_apply(psi)
    phi = phi[0] if isinstance(phi, tuple) else phi
    phi = jnp.asarray(phi)
    w2 = float(jnp.real(jnp.vdot(phi, phi)))
    if w2 <= 0.0:
        raise ValueError("O|psi> vanishes: no spectral weight")
    V0 = (phi / np.sqrt(w2))[..., None]
    res = kpm_moments(matvec, n_moments, V0=V0, bounds=bounds,
                      bounds_iters=bounds_iters, margin=margin)
    energies, rho = reconstruct_dos(res.moments, res.scale,
                                    npoints=npoints, kernel=kernel,
                                    lam=lam)
    return energies, w2 * rho, res, w2
