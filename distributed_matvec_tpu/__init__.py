"""distributed_matvec_tpu — a TPU-native framework for distributed matrix-free
quantum-Hamiltonian linear algebra.

A from-scratch JAX/XLA re-design with the capabilities of
``twesterhout/distributed-matvec`` (Chapel + GASNet + Haskell kernels +
PRIMME): symmetry-reduced basis enumeration, hash-sharded state distribution
over a ``jax.sharding.Mesh``, matrix-free ``y = H·x`` with on-device operator
application and ICI ``all_to_all`` amplitude routing, layout shuffles, and
iterative eigensolvers (Lanczos/LOBPCG).

Layers (bottom → top; compare SURVEY.md §1):
  utils/        — config flags, logging, tree timers               (L-cross)
  models/       — expressions → nonbranching terms, symmetry groups,
                  bases, operators, YAML configs, lattice builders (L2)
  enumeration/  — representative enumeration (host)                (L4)
  ops/          — jitted device kernels (diag/off-diag apply,
                  state_info orbit scans, searchsorted indexing)   (L5)
  parallel/     — mesh/sharding, all_to_all matvec engine,
                  block↔hashed shuffles, collective reductions     (L0/L5)
  solve/        — eigensolvers (Lanczos, LOBPCG) + drivers         (L6)
"""

# Basis states are uint64 bitstrings and the accuracy contract is double
# precision (atol 1e-14 / rtol 1e-12 — reference TestMatrixVectorProduct.chpl:15-16),
# so 64-bit types are a hard requirement, enabled before any tracing happens.
# (On TPU, XLA lowers u64/f64 to 32-bit pairs; the hot kernels are
# integer/VPU-bound so the cost is acceptable — see SURVEY.md §7 hard part 4.)
try:
    import os as _os

    import jax as _jax

    _jax.config.update("jax_enable_x64", True)
    # sitecustomize may import jax before a launcher's JAX_PLATFORMS env edit
    # is seen by the plugin registry; re-assert the choice here so
    # `JAX_PLATFORMS=cpu python …` really keeps every entry point (CLI, bench,
    # examples, library users) off the TPU tunnel.
    if _os.environ.get("JAX_PLATFORMS"):
        _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])
    # Raise XLA's 40 s CPU collective rendezvous kill-switch up front (it
    # only takes effect if no backend is built yet): big applies on an
    # oversubscribed virtual CPU mesh legitimately skew past 40 s, and the
    # flag cannot be set after the fact — see
    # utils/config.py::ensure_cpu_collective_timeout.
    from .utils.config import ensure_cpu_collective_timeout as _ect

    _ect()
except ImportError:  # pragma: no cover - jax is a hard dep in practice
    pass

from . import models, utils  # noqa: F401
from .models.basis import SpinBasis, SpinfulFermionBasis, SpinlessFermionBasis
from .models.operator import Operator
from .models.yaml_io import Config, load_config_from_yaml
from .utils.config import get_config, update_config

__version__ = "0.1.0"

__all__ = [
    "SpinBasis",
    "SpinlessFermionBasis",
    "SpinfulFermionBasis",
    "Operator",
    "Config",
    "load_config_from_yaml",
    "get_config",
    "update_config",
    "__version__",
]
