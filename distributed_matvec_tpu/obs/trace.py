"""End-to-end solve tracing: one ``trace_id`` per run, parent-linked spans.

Fifth pillar of the telemetry subsystem (see ``obs/__init__``).  Four PRs of
telemetry answer questions *after* a run by grepping JSONL files; nothing
ties a solve's events into one causal tree.  This module gives every run a
**trace id** and every solve → solver-iteration → apply → chunk a
parent-linked **span id**, stamped into the event envelope next to
``rank``/``seq`` — so every existing event (``apply_phases``,
``plan_stream``, ``lanczos_trace``, ``memory_ledger``, ``fault_injected``,
``stall_report``) becomes attributable to the exact solve and iteration
that produced it, and ``tools/obs_report.py trace`` can export the merged
span tree as a Chrome/Perfetto trace (one track per rank, cross-rank
correlation via the PR 3 skew-corrected merge).

Identity
--------
* ``trace_id()`` — 16-hex id shared by every rank of one run.  Resolution
  order: ``DMT_TRACE_ID`` (a supervisor pinning the id explicitly) > the
  ``trace_id`` file under the obs run directory (first rank to arrive
  creates it atomically with ``O_EXCL``; every other rank reads the
  winner's value — multi-rank runs already share the directory, and the
  id is thereby a property of the *run directory*, exactly like the event
  streams themselves) > a per-process random id (in-memory-only runs).
* ``job_id()`` — the solve-service namespacing knob (``DMT_JOB_ID`` /
  ``config.job_id``); defaults to the trace id.  Stamped into every event
  so a multiplexed scheduler can filter one job's telemetry out of a
  shared stream.

Spans
-----
``span(name, kind=..., **attrs)`` is a context manager pushing onto a
process-global stack (engines and solvers run on the main thread; the
heartbeat watchdog only *reads* the stack, which is why it is global and
locked rather than thread-local).  Closing a span emits ONE ``span`` event
carrying ``name``/``cat``/``t0``/``dur_ms``/``parent_span_id`` — emitted
*before* the pop, so the envelope's ``span_id`` stamp is the span's own id.
The canonical taxonomy (DESIGN.md §24)::

    run (diagonalize / bench)  >  solve (one solver call)
      >  iteration (one convergence block / block step / segment)
        >  apply (one eager matvec)
          >  chunk (one streamed plan chunk: H2D wait + dispatch)

Contracts (the health-probe pattern applied to causality): spans are pure
host bookkeeping — the apply HLO is **byte-identical** with tracing on or
off (guard-tested by ``make trace-check``); ``DMT_TRACE=off`` disables
stamping and span events while leaving the rest of obs running;
``DMT_OBS=off`` is a provable no-op (``span`` returns a shared null
context, no ids are generated, nothing is emitted).
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager, nullcontext
from typing import Dict, List, Optional

from ..utils.config import get_config
from ..utils.logging import log_warn
from .events import emit, obs_enabled, run_dir, set_trace_stamper

__all__ = [
    "trace_enabled",
    "trace_id",
    "job_id",
    "span",
    "current_span_id",
    "open_spans",
    "deepest_span",
    "span_path",
    "reset_trace",
]

_lock = threading.Lock()
_stack: List["_Span"] = []
_trace_id: Optional[str] = None
_id_counter = 0


def trace_enabled() -> bool:
    """Whether span tracing + envelope stamping is active (requires obs
    on; the env var is consulted directly so harnesses can flip it per
    subprocess — same contract as :func:`~.events.obs_enabled`)."""
    if not obs_enabled():
        return False
    env = os.environ.get("DMT_TRACE")
    knob = env if env is not None else get_config().trace
    return str(knob).strip().lower() not in ("off", "0", "false", "no")


def _rand_id(nbytes: int = 8) -> str:
    return os.urandom(nbytes).hex()


def _agree_trace_id(directory: str, proposal: str) -> str:
    """Cross-rank agreement through the shared run directory: the first
    rank to arrive creates ``<dir>/trace_id`` atomically (``O_EXCL``) with
    its proposal; everyone else reads the winner.  Soft-fail (an
    unwritable or vanished directory degrades to the per-rank proposal —
    telemetry must never turn a computation into an I/O error)."""
    path = os.path.join(directory, "trace_id")
    try:
        os.makedirs(directory, exist_ok=True)
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        os.close(fd)
        # the O_EXCL create is the winner marker only; the CONTENT lands
        # via an atomic replace, so a racing reader observes either an
        # empty file (retries below) or the full id — never a torn prefix
        tmp = f"{path}.{proposal}.tmp"
        with open(tmp, "w") as f:
            f.write(proposal + "\n")
        os.replace(tmp, path)
        return proposal
    except FileExistsError:
        pass
    except OSError as e:
        log_warn(f"trace_id agreement unavailable ({path}): {e!r}")
        return proposal
    # another rank won the create — read its id (retry while empty: the
    # winner's atomic replace may not have landed yet)
    for _ in range(50):
        try:
            with open(path) as f:
                got = f.read().strip()
            if got:
                return got
        except OSError:
            pass
        time.sleep(0.01)
    log_warn(f"trace_id file {path} stayed empty; using a rank-local id")
    return proposal


def trace_id() -> Optional[str]:
    """This run's trace id (lazy; None when tracing is disabled).  See the
    module docstring for the resolution order."""
    global _trace_id
    if not trace_enabled():
        return None
    if _trace_id is not None:
        return _trace_id
    # resolve OUTSIDE the span lock: the file agreement touches the shared
    # run directory, and the heartbeat watchdog must be able to read the
    # span stack even while a rank wedges on that mount.  Two threads
    # racing here both reach the same agreed value (the O_EXCL winner);
    # first store wins.
    pinned = os.environ.get("DMT_TRACE_ID", "").strip()
    if pinned:
        resolved = pinned
    else:
        proposal = _rand_id()
        d = run_dir()
        resolved = _agree_trace_id(d, proposal) if d else proposal
    with _lock:
        if _trace_id is None:
            _trace_id = resolved
    return _trace_id


def job_id() -> Optional[str]:
    """The job-namespacing id (``DMT_JOB_ID`` env > ``config.job_id`` >
    the trace id) — the groundwork the solve service's multiplexed
    scheduler keys per-job telemetry on."""
    if not trace_enabled():
        return None
    env = os.environ.get("DMT_JOB_ID")
    knob = env if env is not None else get_config().job_id
    knob = str(knob).strip()
    return knob if knob else trace_id()


class _Span:
    __slots__ = ("name", "kind", "sid", "parent_sid", "t0", "attrs")

    def __init__(self, name: str, kind: str, sid: str,
                 parent_sid: Optional[str], attrs: Dict):
        self.name = name
        self.kind = kind
        self.sid = sid
        self.parent_sid = parent_sid
        self.t0 = time.time()
        self.attrs = attrs


def _next_span_id() -> str:
    """Span ids are ``<rank-local ordinal>-<4 random hex>`` — unique
    within a trace once prefixed by the rank (the envelope carries the
    rank, and readers key spans on ``(rank, span_id)``), cheap to
    generate, and stable enough to grep."""
    global _id_counter
    _id_counter += 1
    return f"{_id_counter:x}-{_rand_id(2)}"


@contextmanager
def _span_cm(name: str, kind: str, attrs: Dict):
    with _lock:
        parent = _stack[-1].sid if _stack else None
        sp = _Span(str(name), str(kind), _next_span_id(), parent, attrs)
        _stack.append(sp)
    try:
        yield sp
    finally:
        dur_ms = (time.time() - sp.t0) * 1e3
        # emit BEFORE the pop: the envelope stamper sees the closing span
        # on top of the stack, so the span event's own span_id is itself
        # and its children's events (already written) point at it
        emit("span", name=sp.name, cat=sp.kind,
             parent_span_id=sp.parent_sid,
             t0=round(sp.t0, 6), dur_ms=round(dur_ms, 4), **sp.attrs)
        with _lock:
            try:
                _stack.remove(sp)
            except ValueError:      # reset_trace() ran inside the span
                pass


def span(name: str, kind: str = "span", **attrs):
    """Context manager for one traced span.  With tracing disabled this is
    a shared null context: no id, no lock, no event — the provable-no-op
    contract of ``DMT_OBS=off``."""
    if not trace_enabled():
        return nullcontext()
    return _span_cm(name, kind, attrs)


def emit_span(name: str, kind: str, t0: float, dur_ms: float,
              **attrs) -> None:
    """One retro-dated span event parented to the CURRENTLY open span —
    for work whose extent is known only after the fact and cannot ride
    the context-manager nesting (the solve service's per-job spans: a
    job's in-batch window closes when its column converges, while the
    batch span is still open).  The span is pushed for exactly the
    duration of its own event emission — same emit-before-pop move as
    the context manager, so the envelope stamper records the span's own
    id on its event — and carries the caller's ``t0``/``dur_ms`` rather
    than wall-clock-now."""
    if not trace_enabled():
        return
    with _lock:
        parent = _stack[-1].sid if _stack else None
        sp = _Span(str(name), str(kind), _next_span_id(), parent, attrs)
        _stack.append(sp)
    try:
        emit("span", name=sp.name, cat=sp.kind, parent_span_id=parent,
             t0=round(float(t0), 6), dur_ms=round(float(dur_ms), 4),
             **attrs)
    finally:
        with _lock:
            try:
                _stack.remove(sp)
            except ValueError:
                pass


@contextmanager
def _job_scope_cm(jid: str):
    from ..utils.config import get_config, update_config
    old_env = os.environ.get("DMT_JOB_ID")
    old_cfg = get_config().job_id
    # env AND config, the both-or-neither contract of --job-id: the env
    # var outranks the config field, so scoping only the config would be
    # silently defeated by an inherited DMT_JOB_ID
    os.environ["DMT_JOB_ID"] = jid
    update_config(job_id=jid)
    try:
        yield
    finally:
        if old_env is None:
            os.environ.pop("DMT_JOB_ID", None)
        else:
            os.environ["DMT_JOB_ID"] = old_env
        update_config(job_id=old_cfg)


def job_scope(jid: Optional[str]):
    """Context manager stamping ``jid`` as the envelope ``job_id`` of
    every event emitted inside — how the solve service namespaces one
    job's lifecycle events and spans inside a multiplexed stream (the
    envelope drops payload fields that collide with its keys, so a
    payload ``job_id=`` could never do this).  No-op when tracing is off
    or ``jid`` is empty."""
    if not trace_enabled() or not jid:
        return nullcontext()
    return _job_scope_cm(str(jid))


def current_span_id() -> Optional[str]:
    """The innermost open span's id, or None."""
    with _lock:
        return _stack[-1].sid if _stack else None


def open_spans() -> List[dict]:
    """Snapshot of the open-span stack, root first — each entry
    ``{name, kind, span_id, attrs...}``."""
    with _lock:
        return [dict(name=s.name, kind=s.kind, span_id=s.sid, **s.attrs)
                for s in _stack]


def deepest_span(timeout: Optional[float] = None) -> Optional[dict]:
    """The innermost open span (``{name, kind, span_id, attrs...}``), or
    None — what a stall report attaches so a watchdog exit names the
    phase/chunk the wedged rank was executing.  ``timeout`` bounds the
    lock wait (the watchdog passes one: it must be able to abort a
    wedged process even if the main thread died holding the lock)."""
    if not _lock.acquire(timeout=-1 if timeout is None else timeout):
        return None
    try:
        if not _stack:
            return None
        s = _stack[-1]
        return dict(name=s.name, kind=s.kind, span_id=s.sid, **s.attrs)
    finally:
        _lock.release()


def span_path(timeout: Optional[float] = None) -> str:
    """Human-readable ancestry of the open stack (``solve>iteration>
    apply>chunk``), empty when nothing is open (or, with ``timeout``,
    when the lock could not be taken in time)."""
    if not _lock.acquire(timeout=-1 if timeout is None else timeout):
        return ""
    try:
        return ">".join(s.name for s in _stack)
    finally:
        _lock.release()


def _stamp() -> Dict[str, object]:
    """The envelope fields :func:`~.events.emit` merges into every event:
    ``trace_id`` + ``job_id`` always (when tracing is on), ``span_id``
    when a span is open.  Registered with the event sink at import time —
    the sink stays standalone and import-cycle-free."""
    if not trace_enabled():
        return {}
    tid = trace_id()
    out: Dict[str, object] = {"trace_id": tid}
    jid = job_id()
    if jid is not None:
        out["job_id"] = jid
    sid = current_span_id()
    if sid is not None:
        out["span_id"] = sid
    return out


set_trace_stamper(_stamp)


def reset_trace() -> None:
    """Drop the cached trace id and any open spans (tests; also how a
    long-lived process re-keys itself after re-pointing ``obs_dir`` at a
    new run directory)."""
    global _trace_id, _id_counter
    with _lock:
        _trace_id = None
        _id_counter = 0
        _stack.clear()
