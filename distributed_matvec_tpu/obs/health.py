"""Numerical-health probes and the solver watchdog.

Third pillar of the telemetry subsystem (see ``obs/__init__``): the signals
that catch *silent* numerical decay — NaN/Inf amplitudes, exchange-buffer
overflow, Lanczos orthogonality loss and breakdown — before they surface as
a wrong eigenvalue.

Two kinds of producer report through here:

* **Engine apply probes** (:func:`probe_due` + :func:`probe_apply`): every
  ``health_every``-th eager matvec dispatches ONE fused reduction over the
  result (nonfinite count + output norm, a single tiny program XLA runs
  right after the apply it reads from) and parks the device scalars on a
  pending queue.  The fused-mode engines' overflow/invalid exchange
  counters — already computed on-device by the apply program itself — ride
  the same queue via :func:`defer_exchange_counters`.  Nothing is fetched
  inline: :func:`drain` (called from the next apply, ``obs.snapshot()``,
  and the harness exit points) converts the scalars only after the device
  work that produced them has long been consumed, so the default path adds
  **zero host↔device syncs** and the hot program itself is byte-identical
  with probes on or off.
* **Solver watchdogs** (:func:`record` + :func:`omega_estimate`): Lanczos
  emits orthogonality-loss estimates, β-breakdown and Ritz-stagnation
  detectors as structured ``solver_health`` events with ``warn`` /
  ``critical`` levels; LOBPCG reports nonfinite eigenvalues.

Modes (``DMT_HEALTH`` env var > ``config.health``): ``on`` (default)
logs-and-continues — events + counters, one ``[Warn]`` line per critical
condition; ``strict`` turns critical conditions into a loud
:class:`HealthError` (probe fetches become synchronous there — strictness
buys immediacy at the price of the sync); ``off`` disables the probes.
``DMT_OBS=off`` implies off: the probes are part of the telemetry layer
and must be provably absent from the compiled path when it is disabled
(guard-tested in ``tests/test_obs.py``).
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Optional

import numpy as np

from ..utils.config import get_config
from ..utils.logging import log_warn
from .events import emit, obs_enabled
from .metrics import counter, gauge

__all__ = [
    "HealthError",
    "health_mode",
    "probes_enabled",
    "probe_due",
    "probe_apply",
    "defer_exchange_counters",
    "defer_compress_drift",
    "drain",
    "record",
    "omega_estimate",
    "reset_health",
    "OMEGA_WARN",
    "OMEGA_CRITICAL",
]

#: ω-recurrence thresholds: √ε is the classical "semi-orthogonality lost"
#: line (Simon '84); 1e-4 marks an estimate so large the recurrence output
#: can no longer be trusted at all.
OMEGA_WARN = 1e-8
OMEGA_CRITICAL = 1e-4


class HealthError(RuntimeError):
    """A critical numerical-health condition under ``DMT_HEALTH=strict``."""


_warned_modes: set = set()


def health_mode() -> str:
    """``"on"`` (log-and-continue, default), ``"strict"``, or ``"off"``.
    The env var is consulted directly (not just the config snapshot) so a
    harness can flip it per subprocess — same contract as
    :func:`~.events.obs_enabled`.  An unrecognized value warns ONCE and
    falls back to ``on``: a typo'd ``strict`` must not silently demote the
    loud failure mode the operator asked for."""
    env = os.environ.get("DMT_HEALTH")
    knob = env if env is not None else get_config().health
    knob = str(knob).strip().lower()
    if knob in ("off", "0", "false", "no"):
        return "off"
    if knob in ("strict",):
        return "strict"
    if knob not in ("on", "1", "true", "yes", "") \
            and knob not in _warned_modes:
        _warned_modes.add(knob)
        log_warn(f"unknown DMT_HEALTH value {knob!r} "
                 "(use on | strict | off); treating as 'on'")
    return "on"


def probes_enabled() -> bool:
    """Whether the health layer is active (requires obs on as well)."""
    return obs_enabled() and health_mode() != "off"


_lock = threading.Lock()
# pending device-scalar fetches: ("probe"|"exchange", fields, scalars dict)
_pending: deque = deque(maxlen=4096)
_stats_fn = None


def probe_due(apply_index: int) -> bool:
    """Whether eager apply number ``apply_index`` (the engine's own 0-based
    counter) should dispatch the health reduction: the first and every
    ``health_every``-th apply.  Always False when the layer is off, so
    callers never branch on enablement themselves."""
    if not probes_enabled():
        return False
    every = max(int(get_config().health_every), 1)
    return apply_index % every == 0


def _stats(y):
    """ONE fused reduction over the apply result: (nonfinite count, ‖y‖).
    Compiled once per (shape, dtype) process-wide; dispatched asynchronously
    right behind the apply it reads, so it rides the device queue instead of
    forcing a sync."""
    global _stats_fn
    if _stats_fn is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(a):
            flat = a.reshape(-1)
            bad = jnp.sum(~jnp.isfinite(flat))
            return bad, jnp.sqrt(jnp.abs(jnp.vdot(flat, flat)))

        _stats_fn = f
    return _stats_fn(y)


def probe_apply(engine: str, y, apply_index: int) -> None:
    """Dispatch the health reduction for one apply result and queue the
    scalars for a deferred fetch (strict mode fetches immediately — the
    loud-and-synchronous contract)."""
    bad, norm = _stats(y)
    item = ("probe", {"engine": engine, "apply": int(apply_index)},
            {"nonfinite": bad, "norm": norm})
    if health_mode() == "strict":
        _resolve(item)
        return
    _pending.append(item)


def defer_exchange_counters(engine: str, apply_index: int,
                            overflow, invalid) -> None:
    """Queue the fused-mode overflow/invalid exchange counters (already
    on-device outputs of the apply program — they ride the result transfer,
    no extra device work) for a deferred fetch into obs counters."""
    if not probes_enabled():
        return
    item = ("exchange", {"engine": engine, "apply": int(apply_index)},
            {"overflow": overflow, "invalid": invalid})
    if health_mode() == "strict":
        _resolve(item)
        return
    _pending.append(item)


def defer_compress_drift(engine: str, apply_index: int, tier: str,
                         chunk: int, num, den) -> None:
    """Queue one lossy-tier numerical-drift sample (streamed engines with
    ``stream_compress=f32|bf16``, probe-cadence applies only): ``num`` /
    ``den`` are device scalars ‖Δc·x[rows]‖ / ‖c·x[rows]‖ over the probe
    chunk's live plan entries — the *input-weighted* relative coefficient
    error of this exact apply, against the lossless path's exact
    coefficients.  Resolved deferred like every probe into a
    ``compress_rel_err`` gauge + ``compress_drift`` event, so a solve-long
    drift SERIES exists where the one-shot compress-check gate measures
    error once."""
    if not probes_enabled():
        return
    item = ("drift", {"engine": engine, "apply": int(apply_index),
                      "tier": str(tier), "chunk": int(chunk)},
            {"num": num, "den": den})
    if health_mode() == "strict":
        _resolve(item)
        return
    _pending.append(item)


def _resolve(item) -> None:
    kind, fields, scalars = item
    try:
        vals = {k: np.asarray(v) for k, v in scalars.items()}
    except Exception as e:  # a failed program must not cascade through obs
        log_warn(f"health probe fetch failed ({fields}): {e!r}")
        return
    engine = fields.get("engine", "")
    if kind == "drift":
        num, den = float(vals["num"]), float(vals["den"])
        rel = num / max(den, 1e-300)
        gauge("compress_rel_err", engine=engine,
              tier=fields.get("tier", "")).set(rel)
        emit("compress_drift", rel_err=rel, **fields)
        return
    if kind == "probe":
        bad = int(vals["nonfinite"])
        norm = float(vals["norm"])
        gauge("matvec_output_norm", engine=engine).set(norm)
        counter("matvec_nonfinite", engine=engine).inc(bad)
        if bad:
            record("nonfinite_output", "critical", source="matvec_probe",
                   count=bad, norm=norm, **fields)
    else:
        ov, iv = int(vals["overflow"]), int(vals["invalid"])
        # inc(0) still CREATES the series: the counters are visible in
        # every summarize, zero being the healthy reading
        counter("exchange_overflow", engine=engine).inc(ov)
        counter("exchange_invalid", engine=engine).inc(iv)
        if ov or iv:
            record("exchange_counters", "critical", source="exchange",
                   overflow=ov, invalid=iv, **fields)


def drain() -> None:
    """Fetch every queued probe scalar and fold it into events/counters.
    Called from the engines' next eager apply, ``obs.snapshot()``, and the
    harness exit points — by then the device work that produced the scalars
    has been consumed, so the fetch costs a ready-buffer copy, not a sync.
    In strict mode a critical condition raises :class:`HealthError`."""
    while True:
        with _lock:     # concurrent drains (solver thread + monitor
            if not _pending:            # thread's snapshot) must not race
                return                  # the popleft
            item = _pending.popleft()
        _resolve(item)


def record(check: str, level: str, **fields) -> Optional[dict]:
    """One structured ``health`` event (``solver_health`` for solver
    watchdogs — pass ``solver=...``): ``level`` is ``warn`` or
    ``critical``; critical logs one ``[Warn]`` line and, under
    ``DMT_HEALTH=strict``, raises :class:`HealthError`."""
    if not probes_enabled():
        return None
    kind = "solver_health" if "solver" in fields else "health"
    ev = emit(kind, check=str(check), level=str(level), **fields)
    counter("health_events", level=str(level)).inc()
    if level == "critical":
        detail = " ".join(f"{k}={v}" for k, v in fields.items())
        log_warn(f"health: {check} critical ({detail})")
        if health_mode() == "strict":
            raise HealthError(f"{check}: {detail} (DMT_HEALTH=strict)")
    return ev


def omega_estimate(alph: np.ndarray, bet: np.ndarray, lo: int, m: int,
                   eps: float = 2.0 ** -52) -> float:
    """Orthogonality-loss estimate for the last Lanczos block via the
    ω-recurrence (Paige/Simon)::

        ω_{j+1,i} = (β_i ω_{j,i+1} + (α_i−α_j) ω_{j,i}
                     + β_{i−1} ω_{j,i−1} − β_{j−1} ω_{j−1,i}) / β_j

    The recurrence is evaluated with the post-reorthogonalization baseline
    ω_{j,·} = ε (the solver here always runs ≥1 full MGS pass per step,
    which resets the ω table to roundoff), so what survives is the ONE-STEP
    amplification ε·(β_i + |α_i−α_j| + β_{i−1} + β_{j−1})/β_j — ~ε for a
    healthy recurrence, exploding exactly when β_j collapses relative to
    the spectrum scale (the precursor of breakdown and of genuine
    orthogonality loss).  Returns the max estimate over steps
    ``[lo, m)``; compare against :data:`OMEGA_WARN` / :data:`OMEGA_CRITICAL`.
    """
    a = np.asarray(alph, dtype=np.float64)[:m]
    b = np.asarray(bet, dtype=np.float64)[:m]
    if m - lo <= 0 or a.size == 0:
        return 0.0
    scale = float(np.max(np.abs(a))) + float(np.max(b)) if m else 0.0
    tiny = max(scale, 1.0) * 1e-300
    worst = 0.0
    for j in range(max(lo, 1), m):
        if float(b[j]) < 1e-14:
            # exact breakdown step: the Krylov space closed there, which is
            # the β-breakdown detector's (converged-aware) call, not an
            # orthogonality-loss signal — a HAPPY closure must not trip ω
            continue
        num = float(np.max(b[:j] + np.abs(a[:j] - a[j]))) + float(b[j - 1])
        worst = max(worst, eps * num / max(float(b[j]), tiny, eps * scale))
    return worst


def health_event_count() -> int:
    """Total warn/critical ``health`` + ``solver_health`` events in this
    process's in-memory buffer, after draining pending probe fetches —
    the one shared tally harnesses (bench, the health-check gate) diff
    before/after a run, so the kind list cannot drift between them.
    ``info``-level events (e.g. the selective-reorthogonalization
    fallback marker, which fires on perfectly healthy converging solves)
    are deliberately excluded: the gate's contract is "zero PROBLEMS",
    not "zero telemetry"."""
    drain()
    from .events import events
    return sum(1 for kind in ("health", "solver_health")
               for e in events(kind)
               if e.get("level") in ("warn", "critical"))


def reset_health() -> None:
    """Drop pending fetches (tests)."""
    with _lock:
        _pending.clear()
