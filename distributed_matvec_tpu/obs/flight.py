"""Flight recorder — a crash's last words, bundled before the lights go out.

A rank that dies with exit 75 (preemption), exit 76 (stall watchdog), an
:class:`~.memory.OomError`, an artifact quarantine, or a fatal signal
leaves its diagnosis scattered: the tail of ``rank_<r>/events.jsonl``,
the open-span stack (gone with the process), the config/tuning identity
(never written anywhere).  This module collects all of it at the moment
of death into ONE content-addressed post-mortem bundle::

    <run_dir>/rank_<r>/postmortem/<reason>-<sha16>.json
    <run_dir>/rank_<r>/postmortem/LATEST        (name of the newest bundle)

Bundle contents (``version`` 1): the trigger (``reason`` / ``exit_code``
/ ``signum``), rank + trace/job identity **from the trace layer, never
from a payload** (the envelope-wins spoof-rejection contract of
``obs/events.py`` extended to bundles), the open-span stack root-first
plus the ``span_path``/``deepest_span`` the stall reports already attach,
the last ``flight_ring`` events of the in-memory ring, the full metrics
snapshot, the runtime config as plain data (which carries the tuned-knob
and calibration identity — ``tune``/``stream_compress``/``pipeline``/
``hybrid`` are what a post-mortem needs to reproduce the program), and
the memory picture (last watermark + ledger total).  The filename's
``sha16`` is SHA-256 over the file's exact bytes, so a bundle is
self-verifying: ``obs_report postmortem`` re-hashes on read and flags
tampering or torn writes.

Contracts: with ``DMT_OBS=off`` nothing happens — no ring is consulted,
no directory is created, no bundle is written (:func:`flight_dump`
returns None before touching anything).  Dumps are once-per-reason per
process (a stall that then drains on SIGTERM yields one ``stall`` and
one ``preempt`` bundle, not a pile), reentrancy-guarded, and soft-fail:
a full disk costs one warning, never a second exception inside a crash
path.  Lock waits against the trace layer are bounded (1 s) — the
watchdog must be able to bundle even when the main thread died holding
the span lock.

Triggers wired in this PR: ``attach_oom`` (``obs/memory.py``), the
heartbeat watchdog's stall path (``parallel/heartbeat.py``, before
``on_stall`` so the bundle exists when ``os._exit(76)`` fires), the
preemption latch's first observation (``utils/preempt.py`` — the signal
handler itself stays I/O-free per its contract; the dump runs on the
solve thread when the latch is first seen), artifact quarantine
(``utils/artifacts.py``), and :func:`install_fatal_handlers` (a
``faulthandler`` traceback file pre-armed inside the postmortem
directory for SIGSEGV/SIGFPE/SIGABRT/SIGBUS, plus a pre-written
``context.json`` carrying the identity a signal context cannot collect).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time
from typing import List, Optional

from ..utils.config import get_config
from ..utils.logging import _process_count, _process_index, log_warn
from . import metrics as _metrics
from . import trace as _trace
from .events import (_json_default, emit, flush, obs_enabled, run_dir)
from .events import events as _ring_events

__all__ = [
    "flight_dump",
    "postmortem_dir",
    "list_bundles",
    "read_bundle",
    "verify_bundle",
    "install_fatal_handlers",
    "reset_flight",
]

_lock = threading.Lock()
_dumped: set = set()          # reasons already bundled by this process
_dumping = threading.local()  # reentrancy guard (emit inside dump)


def postmortem_dir(rank: Optional[int] = None) -> Optional[str]:
    """``<run_dir>/rank_<r>/postmortem``, or None without a sink dir."""
    d = run_dir()
    if not d:
        return None
    r = _process_index() if rank is None else int(rank)
    return os.path.join(d, f"rank_{r}", "postmortem")


def _open_spans_bounded(timeout: float = 1.0) -> List[dict]:
    """Root-first open-span stack with a bounded lock wait — same
    rationale as :func:`~.trace.deepest_span`: a crash dump must not
    deadlock on a lock the dying main thread holds."""
    if not _trace._lock.acquire(timeout=timeout):
        return []
    try:
        return [dict(name=s.name, kind=s.kind, span_id=s.sid, **s.attrs)
                for s in _trace._stack]
    finally:
        _trace._lock.release()


def _memory_picture() -> dict:
    from . import memory as _memory
    try:
        return {"watermark": _memory.last_watermark(),
                "ledger_total": _memory.ledger_total()}
    except Exception:
        return {}


def flight_dump(reason: str, exit_code: Optional[int] = None,
                signum: Optional[int] = None, **extra) -> Optional[str]:
    """Write one post-mortem bundle for ``reason``; returns its path.

    None when the layer is off, no run directory is configured (there is
    nowhere durable to put it), this reason already dumped, or the write
    failed (soft — one warning).  ``extra`` fields (e.g. the watchdog's
    stall report) join the bundle top level unless they would collide
    with its identity keys, which always win."""
    if not obs_enabled():
        return None
    if getattr(_dumping, "active", False):
        return None
    pm_dir = postmortem_dir()
    if not pm_dir:
        return None
    with _lock:
        if reason in _dumped:
            return None
        _dumped.add(reason)
    _dumping.active = True
    try:
        cap = max(1, int(get_config().flight_ring))
        bundle = {
            "version": 1,
            "reason": str(reason),
            "exit_code": exit_code,
            "signum": signum,
            "ts": round(time.time(), 6),
            "rank": _process_index(),
            "n_ranks": _process_count(),
            "trace_id": _trace.trace_id(),
            "job_id": _trace.job_id(),
            "span_path": _trace.span_path(timeout=1.0),
            "span": _trace.deepest_span(timeout=1.0),
            "open_spans": _open_spans_bounded(),
            "config": dataclasses.asdict(get_config()),
            "metrics": _metrics.snapshot(),
            "memory": _memory_picture(),
            "events": _ring_events()[-cap:],
        }
        for k, v in extra.items():
            if k not in bundle:
                bundle[k] = v
        data = json.dumps(bundle, sort_keys=True,
                          default=_json_default).encode()
        sha = hashlib.sha256(data).hexdigest()[:16]
        path = os.path.join(pm_dir, f"{reason}-{sha}.json")
        os.makedirs(pm_dir, exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        latest = os.path.join(pm_dir, "LATEST")
        ltmp = f"{latest}.{os.getpid()}.tmp"
        with open(ltmp, "w") as f:
            f.write(os.path.basename(path) + "\n")
        os.replace(ltmp, latest)
        _metrics.counter("flight_dump_count").inc()
        emit("flight_dump", level="critical", reason=str(reason),
             exit_code=exit_code, bundle=path, sha=sha,
             span_path=bundle["span_path"])
        flush()
        return path
    except OSError as e:
        log_warn(f"flight recorder dump failed ({reason}): {e!r}")
        return None
    finally:
        _dumping.active = False


def list_bundles(directory: Optional[str] = None) -> List[str]:
    """Every bundle under a run directory (all ranks), sorted by path.
    ``directory`` defaults to the configured run dir."""
    d = directory or run_dir()
    if not d or not os.path.isdir(d):
        return []
    out: List[str] = []
    for name in sorted(os.listdir(d)):
        pm = os.path.join(d, name, "postmortem")
        if name.startswith("rank_") and os.path.isdir(pm):
            out.extend(os.path.join(pm, b) for b in sorted(os.listdir(pm))
                       if b.endswith(".json"))
    return out


def read_bundle(path: str) -> dict:
    """Load one bundle (no verification — see :func:`verify_bundle`)."""
    with open(path, "rb") as f:
        return json.loads(f.read().decode())


def verify_bundle(path: str) -> bool:
    """Whether the filename's content address matches the bytes — the
    bundle is untampered and untorn."""
    name = os.path.basename(path)
    stem = name[: -len(".json")] if name.endswith(".json") else name
    claimed = stem.rsplit("-", 1)[-1]
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return False
    return hashlib.sha256(data).hexdigest()[:16] == claimed


def install_fatal_handlers() -> Optional[str]:
    """Arm ``faulthandler`` to dump Python tracebacks for fatal signals
    (SIGSEGV/SIGFPE/SIGABRT/SIGBUS) into the postmortem directory, and
    pre-write a ``context.json`` with the identity a signal handler
    could never collect (trace/job id, rank, config).  Returns the
    traceback file path; None when the layer is off or sink-less."""
    if not obs_enabled():
        return None
    pm_dir = postmortem_dir()
    if not pm_dir:
        return None
    try:
        import faulthandler

        os.makedirs(pm_dir, exist_ok=True)
        ctx = {"ts": round(time.time(), 6), "rank": _process_index(),
               "n_ranks": _process_count(), "trace_id": _trace.trace_id(),
               "job_id": _trace.job_id(),
               "config": dataclasses.asdict(get_config())}
        ctx_path = os.path.join(pm_dir, "context.json")
        tmp = f"{ctx_path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(ctx, f, sort_keys=True)
        os.replace(tmp, ctx_path)
        tb_path = os.path.join(pm_dir, "fatal_signals.txt")
        # the file object must outlive the process — faulthandler keeps
        # only the fd; stash the handle on the module so GC cannot close it
        global _fatal_file
        _fatal_file = open(tb_path, "a")
        faulthandler.enable(file=_fatal_file, all_threads=True)
        return tb_path
    except OSError as e:
        log_warn(f"fatal-signal handlers unavailable: {e!r}")
        return None


_fatal_file = None


def reset_flight() -> None:
    """Forget which reasons dumped (tests)."""
    with _lock:
        _dumped.clear()


def _preempt_hook(signum) -> None:
    from ..utils.preempt import EXIT_PREEMPTED
    flight_dump("preempt", exit_code=EXIT_PREEMPTED, signum=signum)


# Route the preemption latch through the recorder: the first safe-point
# observation of the latch dumps a bundle (the handler itself stays
# I/O-free — see utils/preempt.py).
from ..utils.preempt import set_flight_hook as _set_flight_hook  # noqa: E402

_set_flight_hook(_preempt_hook)
