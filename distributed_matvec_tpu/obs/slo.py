"""Declarative SLOs with multi-window burn-rate alerting.

An SLO here is a :class:`SloSpec`: a sampled signal (events of one
``kind``, optionally filtered by payload ``where`` and sampled at one
``field``), an objective, and **burn-rate windows**.  Evaluation follows
the multi-window discipline of SRE practice: an alert fires only when
EVERY window's burn rate exceeds its threshold — the long window proves
the budget is really burning, the short window proves it is burning
*now* (so a stale incident auto-clears instead of paging forever).  The
default pair ``((300 s, 14.4), (3600 s, 6))`` is the classic fast-burn
page: 14.4× burn over 5 minutes AND 6× over the hour.

Three spec modes:

* ``threshold`` — samples are field values; a sample violates when it
  crosses ``target`` (direction from ``higher_is_better``).  Burn rate =
  (violating fraction in window) / (1 − objective).  ``target=None``
  self-baselines from the run's earliest quartile of samples times
  ``baseline_slack`` — which is exactly how "steady apply ms vs the
  tuned/priced estimate" works without a calibration file: the tuned
  steady state IS the early baseline, and an explicit priced estimate
  can always be pinned via ``targets=`` / ``obs_report slo --target``.
* ``count`` — samples are occurrences (stalls, faults, OOMs); ``target``
  is the allowed events/hour (0 ⇒ any occurrence in every window is an
  infinite burn).
* ``rate_min`` — a throughput floor (solves/min); burn = target/actual,
  so falling throughput burns hotter.  ``target=None`` self-baselines
  at a quarter of the run's average rate.

This module is import-dual like ``obs/directions.py``: inside the
package it emits ``slo_alert`` events and bumps the ``slo_alert_count``
counter on firing↔clear transitions (:func:`check_slos`); loaded
standalone by file (``tools/obs_report.py slo`` — which must never
import jax) only the pure evaluation surface exists and
:func:`check_slos` is inert.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

try:                                    # package mode
    from .directions import is_higher_better
    from .events import emit as _emit
    from .events import events as _ring_events
    from .events import obs_enabled as _obs_enabled
    from .metrics import counter as _counter
    _STANDALONE = False
except ImportError:                     # file-loaded by tools/obs_report.py
    _STANDALONE = True

    def _load_directions():
        import importlib.util
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "directions.py")
        spec = importlib.util.spec_from_file_location("_dmt_directions",
                                                      path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    is_higher_better = _load_directions().is_higher_better

    def _obs_enabled():
        return False

    def _emit(kind, **fields):
        return None

    def _ring_events(kind=None):
        return []

    def _counter(name, **labels):
        raise RuntimeError("no metrics registry in standalone mode")

__all__ = [
    "SloSpec",
    "DEFAULT_WINDOWS",
    "default_slos",
    "evaluate",
    "check_slos",
    "reset_slo",
]

#: (window seconds, burn-rate threshold) — fast-burn page: the alert
#: fires when BOTH the 5-minute and the 1-hour burn exceed their bound.
DEFAULT_WINDOWS: Tuple[Tuple[float, float], ...] = ((300.0, 14.4),
                                                    (3600.0, 6.0))


@dataclass
class SloSpec:
    """One service-level objective over the event stream."""

    name: str                          # metric-style id (direction rules)
    kind: str                          # event kind sampled
    field: str = ""                    # payload field (threshold mode)
    where: dict = None                 # payload equality filter
    mode: str = "threshold"            # threshold | count | rate_min
    target: Optional[float] = None     # None => self-baseline
    objective: float = 0.99            # promised good-sample fraction
    higher_is_better: Optional[bool] = None   # None => directions table
    windows: Sequence[Tuple[float, float]] = DEFAULT_WINDOWS
    baseline_slack: float = 4.0        # auto-target = baseline * slack
    description: str = ""

    def __post_init__(self):
        if self.where is None:
            self.where = {}
        if self.higher_is_better is None:
            self.higher_is_better = is_higher_better(self.name)


def default_slos(targets: Optional[Dict[str, float]] = None
                 ) -> List[SloSpec]:
    """The stock SLO set (ISSUE 17): serve latency + throughput, solver
    steady-state walls, compression drift, and the incident counters.
    ``targets`` pins explicit objectives (e.g. the tuner's priced
    steady-apply estimate) by SLO name."""
    t = dict(targets or {})
    return [
        SloSpec("serve_p99_latency_ms", kind="job_event",
                where={"status": "done"}, field="latency_ms",
                target=t.get("serve_p99_latency_ms"),
                description="terminal job latency vs objective"),
        SloSpec("serve_solves_per_min", kind="job_event",
                where={"status": "done"}, mode="rate_min",
                target=t.get("serve_solves_per_min"),
                description="solve throughput floor"),
        SloSpec("steady_apply_ms", kind="matvec_apply", field="wall_ms",
                target=t.get("steady_apply_ms"),
                description="eager apply wall vs tuned/priced estimate"),
        SloSpec("solver_iteration_ms", kind="span",
                where={"cat": "iteration"}, field="dur_ms",
                target=t.get("solver_iteration_ms"),
                description="solver iteration wall vs steady baseline"),
        SloSpec("compress_rel_err", kind="compress_drift", field="rel_err",
                target=t.get("compress_rel_err", 1e-3),
                description="streamed-plan decode drift bound"),
        SloSpec("stall_reports", kind="stall_report", mode="count",
                target=t.get("stall_reports", 0.0),
                description="heartbeat stall reports (allowed/h)"),
        SloSpec("faults_injected", kind="fault_injected", mode="count",
                target=t.get("faults_injected", 0.0),
                description="injected faults fired (allowed/h)"),
        SloSpec("oom_reports", kind="memory_report", mode="count",
                target=t.get("oom_reports", 0.0),
                description="OOM diagnoses (allowed/h)"),
    ]


def _matches(ev: dict, spec: SloSpec) -> bool:
    if ev.get("kind") != spec.kind:
        return False
    for k, v in spec.where.items():
        if ev.get(k) != v:
            return False
    return True


def _samples(events: List[dict], spec: SloSpec) -> List[Tuple[float, float]]:
    out = []
    for ev in events:
        if not _matches(ev, spec):
            continue
        ts = ev.get("ts")
        if ts is None:
            continue
        if spec.field:
            v = ev.get(spec.field)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                continue
            out.append((float(ts), float(v)))
        else:
            out.append((float(ts), 1.0))
    out.sort(key=lambda s: s[0])
    return out


def _auto_target(spec: SloSpec,
                 samples: List[Tuple[float, float]]) -> Optional[float]:
    """Self-baseline: the earliest quartile (≥5 samples) of the run sets
    the steady state; the target is its median scaled by the slack (or
    its rate scaled DOWN for throughput floors)."""
    if spec.mode == "rate_min":
        if len(samples) < 2:
            return None
        dt = samples[-1][0] - samples[0][0]
        if dt <= 0:
            return None
        return (len(samples) / dt) * 60.0 * 0.25
    n = len(samples)
    if n < 2:
        return None
    head = sorted(v for _, v in samples[: max(5, n // 4)])
    median = head[len(head) // 2]
    if spec.higher_is_better:
        return median / spec.baseline_slack
    return median * spec.baseline_slack


def _violates(spec: SloSpec, value: float, target: float) -> bool:
    return value < target if spec.higher_is_better else value > target


def evaluate(events: List[dict], specs: Optional[List[SloSpec]] = None,
             now: Optional[float] = None) -> List[dict]:
    """Pure evaluation of ``specs`` over ``events`` (any rank mix; the
    envelope ``ts`` orders them).  ``now`` anchors the windows — defaults
    to the newest event timestamp, which makes post-hoc reads
    deterministic.  Returns one status dict per spec::

        {"name", "mode", "state": "ok"|"firing"|"no-data", "target",
         "samples", "worst_burn",
         "windows": [{"window_s", "max_burn", "burn", "samples", "bad"}]}
    """
    if specs is None:
        specs = default_slos()
    if now is None:
        now = max((e.get("ts", 0.0) for e in events), default=0.0)
    out = []
    for spec in specs:
        samples = _samples(events, spec)
        target = spec.target
        if target is None:
            target = _auto_target(spec, samples)
        budget = max(1.0 - float(spec.objective), 1e-9)
        windows = []
        firing = bool(spec.windows) and (target is not None
                                         or spec.mode == "count")
        for window_s, max_burn in spec.windows:
            sub = [s for s in samples if s[0] > now - window_s]
            if spec.mode == "count":
                n = len(sub)
                allowed = float(target or 0.0)
                if allowed <= 0.0:
                    burn = float("inf") if n else 0.0
                else:
                    burn = (n / window_s * 3600.0) / allowed
                bad = n
            elif spec.mode == "rate_min":
                # a window larger than the observed run must not dilute
                # the rate: a 5-min window over a 2-s CI drain would
                # grade any throughput as near-zero, so the denominator
                # is clamped to the data span actually covered
                eff_s = min(window_s, max(now - samples[0][0], 1e-3)) \
                    if samples else window_s
                rate = len(sub) / eff_s * 60.0
                tgt = float(target) if target is not None else 0.0
                burn = (float("inf") if rate <= 0.0 else tgt / rate) \
                    if tgt > 0.0 else 0.0
                bad = 0
            else:
                bad = sum(1 for _, v in sub
                          if target is not None
                          and _violates(spec, v, float(target)))
                frac = bad / len(sub) if sub else 0.0
                burn = frac / budget
                if spec.mode == "threshold" and not sub:
                    firing = False
            windows.append({"window_s": window_s, "max_burn": max_burn,
                            "burn": burn, "samples": len(sub), "bad": bad})
            if not (burn > max_burn):
                firing = False
        if spec.mode == "rate_min" and not samples:
            firing = False              # a run with no serve plane at all
        state = "firing" if firing else (
            "no-data" if not samples and spec.mode != "count" else "ok")
        worst = max((w["burn"] for w in windows), default=0.0)
        out.append({"name": spec.name, "mode": spec.mode, "state": state,
                    "target": target, "samples": len(samples),
                    "worst_burn": worst, "windows": windows,
                    "description": spec.description})
    return out


_state_lock = threading.Lock()
_fired: Dict[str, bool] = {}


def check_slos(specs: Optional[List[SloSpec]] = None,
               now: Optional[float] = None,
               events: Optional[List[dict]] = None) -> List[dict]:
    """Evaluate in-process (over the live event ring by default) and emit
    ``slo_alert`` events on state TRANSITIONS: ``state="firing"`` (also
    bumping the ``slo_alert_count`` counter — the bench_trend gate
    metric) when an ok SLO starts burning, ``state="clear"`` when a
    firing one recovers.  Steady states emit nothing, so a healthy
    service's stream stays alert-free.  Inert when the layer is off or
    in standalone (reader) mode."""
    if _STANDALONE or not _obs_enabled():
        return []
    statuses = evaluate(events if events is not None else _ring_events(),
                        specs, now=now)
    fired_now: List[dict] = []
    with _state_lock:
        for st in statuses:
            prev = _fired.get(st["name"], False)
            if st["state"] == "firing" and not prev:
                _fired[st["name"]] = True
                _counter("slo_alert_count").inc()
                _emit("slo_alert", level="critical", slo=st["name"],
                      state="firing", burn=round(st["worst_burn"], 4)
                      if st["worst_burn"] != float("inf") else "inf",
                      target=st["target"], mode=st["mode"],
                      samples=st["samples"])
                fired_now.append(st)
            elif st["state"] == "ok" and prev:
                _fired[st["name"]] = False
                _emit("slo_alert", slo=st["name"], state="clear",
                      target=st["target"], mode=st["mode"])
    # triggered deep capture (obs/profile.py): a burning SLO snapshots
    # the hottest HLO ops + newest sampled trace into one flight bundle
    # so the incident carries its own profile.  Lazy + soft-fail: the
    # alert must land even when the capture path cannot.
    for st in fired_now:
        try:
            from . import profile as _profile
            _profile.trigger_capture(f"slo_burn_{st['name']}",
                                     slo=st["name"],
                                     burn=st["worst_burn"]
                                     if st["worst_burn"] != float("inf")
                                     else "inf")
        except Exception:
            pass
    return statuses


def reset_slo() -> None:
    """Forget firing state (tests)."""
    with _state_lock:
        _fired.clear()
