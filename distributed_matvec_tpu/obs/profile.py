"""Sampled continuous profiling with an overhead guard and triggered
deep capture.

Three modes, selected by the ``profile`` knob (``DMT_PROFILE``, env
consulted directly like ``DMT_OBS`` so harnesses can flip it without
racing the config cache):

* ``off`` (default) — the apply hot path sees one branch and nothing
  else; the apply HLO is byte-identical to a profiled run because
  ``jax.profiler.trace`` never alters the program, only observes it.
* ``sampled`` — every ``profile_every``-th apply (the ``health_every``
  cadence pattern) runs inside a bounded ``jax.profiler.trace`` window
  written to ``<run_dir>/rank_<r>/profiles/<engine>-apply<N>``, stamped
  with ``trace_id``/``job_id`` and announced by a ``profile_captured``
  event.  A **measured-overhead guard** times the trace start/stop
  itself against the cumulative apply wall; when measured overhead
  exceeds ``profile_overhead_pct`` (default 2%) after at least two
  profiled windows, sampling latches OFF for the rest of the process
  and says so (``profile_overhead_latch`` event) — profiling must never
  become the regression it is hunting.
* ``triggered`` — no cadence; only :func:`trigger_capture` fires.

**Triggered deep capture** (active in both non-off modes): an SLO
burn-rate alert (obs/slo.py) or a ``bench_trend`` gate failure calls
:func:`trigger_capture`, which snapshots the hottest HLO ops, the
newest sampled-trace directory, and the overhead ledger into one
flight-recorder bundle (PR 17 format, ``trace_id``/``job_id`` stamped
by ``flight_dump`` itself) so the incident carries its own profile.
"""

from __future__ import annotations

import contextlib
import json
import os
import re
import threading
import time
from typing import Dict, Optional

from ..utils.config import get_config
from ..utils.logging import log_debug
from .events import emit, obs_enabled, run_dir
from .metrics import counter

__all__ = [
    "profile_mode",
    "profile_due",
    "sample_window",
    "stamp_profile_dir",
    "observe_apply",
    "measured_overhead_pct",
    "overhead_snapshot",
    "overhead_latched",
    "trigger_capture",
    "reset_profile",
]

_MODES = ("off", "sampled", "triggered")

_lock = threading.Lock()
_state = {
    "apply_ms": 0.0,      # cumulative apply dispatch wall, all applies
    "extra_ms": 0.0,      # cumulative measured trace start/stop cost
    "applies": 0,
    "profiled": 0,
    "latched": False,     # overhead budget blown -> sampling off
    "last_dir": "",       # newest sampled trace directory
}


def profile_mode() -> str:
    """The active profiling mode (``off``/``sampled``/``triggered``).
    Env wins over the config snapshot; anything unrecognized, or the
    whole obs layer being off, reads as ``off``."""
    if not obs_enabled():
        return "off"
    env = os.environ.get("DMT_PROFILE")
    knob = env if env is not None else get_config().profile
    mode = str(knob).strip().lower()
    return mode if mode in _MODES else "off"


def overhead_latched() -> bool:
    """Whether the overhead guard has latched sampling off."""
    with _lock:
        return _state["latched"]


def profile_due(apply_index: int) -> bool:
    """Whether eager apply ``apply_index`` should capture a sampled
    trace window: ``sampled`` mode, a run directory to write into, the
    overhead guard not latched, and the ``profile_every`` cadence
    (skipping apply 0, which pays compile)."""
    if profile_mode() != "sampled" or run_dir() is None:
        return False
    with _lock:
        if _state["latched"]:
            return False
    every = max(int(get_config().profile_every), 1)
    return apply_index > 0 and apply_index % every == 0


def observe_apply(wall_ms: float, extra_ms: float = 0.0,
                  profiled: bool = False) -> None:
    """Feed one apply's dispatch wall (and, for profiled applies, the
    measured trace start/stop cost) into the overhead ledger."""
    with _lock:
        _state["apply_ms"] += float(wall_ms)
        _state["extra_ms"] += float(extra_ms)
        _state["applies"] += 1
        if profiled:
            _state["profiled"] += 1


def measured_overhead_pct() -> float:
    """Measured profiling overhead: trace start/stop cost as a percent
    of the un-profiled apply wall.  0.0 until anything is profiled."""
    with _lock:
        base = _state["apply_ms"] - _state["extra_ms"]
        if base <= 0.0 or _state["extra_ms"] <= 0.0:
            return 0.0
        return 100.0 * _state["extra_ms"] / base


def overhead_snapshot() -> Dict[str, float]:
    """Copy of the overhead ledger (bench deltas read this before and
    after a config to attribute per-config overhead)."""
    with _lock:
        snap = dict(_state)
    snap["overhead_pct"] = measured_overhead_pct()
    return snap


def _sample_dir(engine: str, apply_index: int) -> Optional[str]:
    d = run_dir()
    if not d:
        return None
    from .events import _process_index
    return os.path.join(d, f"rank_{_process_index()}", "profiles",
                        f"{engine}-apply{int(apply_index)}")


def stamp_profile_dir(path: str, **fields) -> Optional[str]:
    """Write ``PROFILE_META.json`` (trace_id/job_id + caller fields)
    into a captured trace directory so the orphan-directory era is
    over: every profile on disk names the run that produced it."""
    from .trace import job_id, trace_id

    try:
        os.makedirs(path, exist_ok=True)
        meta = {"trace_id": trace_id(), "job_id": job_id(),
                "ts": time.time(), **fields}
        mpath = os.path.join(path, "PROFILE_META.json")
        tmp = mpath + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f, sort_keys=True)
        os.replace(tmp, mpath)
        return mpath
    except OSError as e:
        log_debug(f"profile dir stamp skipped for {path}: {e!r}")
        return None


def _check_budget() -> None:
    """Latch sampling off when measured overhead exceeds the budget
    after at least two profiled windows (one window is all compile/IO
    noise; two is the contract's minimum evidence)."""
    budget = float(get_config().profile_overhead_pct)
    pct = measured_overhead_pct()
    with _lock:
        if _state["latched"] or _state["profiled"] < 2:
            return
        if pct <= budget:
            return
        _state["latched"] = True
    counter("profile_overhead_latch_count").inc()
    emit("profile_overhead_latch", overhead_pct=pct, budget_pct=budget)
    log_debug(f"profile sampling latched off: measured overhead "
              f"{pct:.2f}% > budget {budget:.2f}%")


@contextlib.contextmanager
def sample_window(engine: str, apply_index: int):
    """Wrap one apply dispatch.  Almost always a timed pass-through
    (one mode check + one ``perf_counter`` pair); on a due sampled
    apply, the body runs inside a bounded ``jax.profiler.trace``
    window and the window's own start/stop cost feeds the overhead
    guard.  Yields True iff a trace was captured."""
    if not profile_due(apply_index):
        if profile_mode() == "off":
            yield False                 # provable no-op: no ledger
            return
        t0 = time.perf_counter()
        try:
            yield False
        finally:
            observe_apply((time.perf_counter() - t0) * 1e3)
        return

    target = _sample_dir(engine, apply_index)
    t0 = time.perf_counter()
    extra_s = 0.0
    ctx = None
    try:
        import jax.profiler
        ta = time.perf_counter()
        ctx = jax.profiler.trace(target)
        ctx.__enter__()
        extra_s += time.perf_counter() - ta
    except Exception as e:
        log_debug(f"profiler trace start failed ({target}): {e!r}")
        ctx = None
    try:
        yield ctx is not None
    finally:
        if ctx is not None:
            tb = time.perf_counter()
            try:
                ctx.__exit__(None, None, None)
            except Exception as e:
                log_debug(f"profiler trace stop failed: {e!r}")
            extra_s += time.perf_counter() - tb
        wall_ms = (time.perf_counter() - t0) * 1e3
        observe_apply(wall_ms, extra_s * 1e3, profiled=ctx is not None)
        if ctx is not None:
            with _lock:
                _state["last_dir"] = target
            stamp_profile_dir(target, capture="sampled", engine=engine,
                              apply=int(apply_index))
            counter("profile_capture_count", capture="sampled").inc()
            emit("profile_captured", capture="sampled", engine=engine,
                 apply=int(apply_index), dir=target,
                 overhead_ms=extra_s * 1e3,
                 overhead_pct=measured_overhead_pct())
            _check_budget()


def trigger_capture(reason: str, **extra) -> Optional[str]:
    """Deep capture on an incident: snapshot the hottest HLO ops, the
    newest sampled-trace directory, and the overhead ledger into one
    flight-recorder bundle named after ``reason``.  Active whenever
    profiling is on at all (``sampled`` includes triggers); returns the
    bundle path or None (off / no run dir / reason already dumped)."""
    if profile_mode() == "off":
        return None
    safe = re.sub(r"[^A-Za-z0-9_-]+", "_", str(reason)).strip("_")
    safe = safe or "trigger"

    payload: Dict[str, object] = {"overhead": overhead_snapshot()}
    try:
        from . import hlo as _hlo

        hot = []
        for key, prof in sorted(_hlo.executable_costs().items()):
            hot.append({"key": key, "program": prof.get("program", key),
                        "fingerprint": prof.get("fingerprint", ""),
                        "artifact": prof.get("artifact", ""),
                        "top_ops": _hlo.hottest_ops(prof, 3)})
        payload["hlo"] = hot
    except Exception as e:
        log_debug(f"trigger capture: hlo snapshot failed: {e!r}")
    with _lock:
        payload["last_sample_dir"] = _state["last_dir"]

    from .flight import flight_dump

    path = flight_dump(f"profile_{safe}", profile=payload, **extra)
    if path:
        counter("profile_capture_count", capture="triggered").inc()
        emit("profile_captured", capture="triggered", reason=safe,
             bundle=path)
    return path


def reset_profile() -> None:
    """Reset the overhead ledger and latch (test isolation)."""
    with _lock:
        _state.update(apply_ms=0.0, extra_ms=0.0, applies=0,
                      profiled=0, latched=False, last_dir="")
