"""Structured event sink: append-only JSONL per process + profiler bridge.

One pillar of the telemetry subsystem (see ``obs/__init__``).  Every event is
a flat JSON object with a fixed envelope::

    {"seq": 17, "ts": 1754092800.123456, "proc": 0, "rank": 0, "n_ranks": 2,
     "kind": "engine_init", "trace_id": "9f2c...", "job_id": "9f2c...",
     "span_id": "3-a1b2", ...payload fields...}

``seq`` is a per-process monotonic sequence number (readers order one rank's
stream by ``seq`` — wall clocks across hosts are not trusted), ``rank`` the
JAX process index and ``n_ranks`` the process count (``proc`` is kept as a
``rank`` alias for pre-rank readers).  When the tracing layer is on
(``obs/trace.py``, default) the envelope also carries the run's
``trace_id``, the ``job_id`` namespacing knob, and the ``span_id`` of the
innermost open span — readers treat all three as optional (pre-trace
streams simply lack them).  With ``DMT_OBS_DIR`` (or
``config.obs_dir``) set, each process appends to its OWN file
``<dir>/rank_<r>/events.jsonl`` — multi-host safe by construction, no
cross-process file locking — and every event is
also kept in a bounded in-memory ring buffer (:func:`events`) so a live
process can inspect its own stream.  With no directory configured the layer
still runs in-memory only (the default), and with ``DMT_OBS=off`` it is
fully disabled (:func:`emit` returns ``None`` without building an event).

Sink writes fail SOFT, mirroring the artifact layer's loud/quiet split
(``utils/artifacts.py``): a read-only checkout or full disk logs one
``log_warn`` and degrades to in-memory — telemetry must never turn a
computation into an I/O error.

:func:`annotate` bridges the host-side event timeline into device-side
``jax.profiler`` traces: it returns a ``TraceAnnotation`` context so the
phases instrumented here (engine init, chunk build, apply) show up as named
spans in Perfetto/TensorBoard, lining up with the JSONL timestamps.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import nullcontext
from typing import List, Optional

from ..utils.config import get_config
from ..utils.logging import _process_count, _process_index, log_warn

__all__ = [
    "obs_enabled",
    "run_dir",
    "event_path",
    "emit",
    "events",
    "annotate",
    "flush",
    "reset",
    "set_trace_stamper",
]

_BUFFER_CAP = 1 << 16

_lock = threading.Lock()
_buffer: deque = deque(maxlen=_BUFFER_CAP)
_seq = 0
_sink = None                 # open file object, or None
_sink_path: Optional[str] = None
_sink_failed = False
_atexit_registered = False
_trace_stamper = None        # obs/trace.py registers its envelope stamper


def set_trace_stamper(fn) -> None:
    """Register the tracing layer's envelope stamper (``obs/trace.py``
    calls this at import).  ``fn()`` returns the ``trace_id``/``job_id``/
    ``span_id`` fields :func:`emit` merges into every event's envelope —
    a callback instead of an import so this sink stays standalone and
    cycle-free.  A failing stamper is dropped for the process: causality
    stamps must never cost the event itself."""
    global _trace_stamper
    _trace_stamper = fn


def obs_enabled() -> bool:
    """Whether the telemetry layer is active (default on).

    The env var is consulted directly (not just through the config
    snapshot) so a harness can flip it for a subprocess without racing the
    config cache — same contract as ``artifacts_enabled``."""
    env = os.environ.get("DMT_OBS")
    knob = env if env is not None else get_config().obs
    return str(knob).strip().lower() not in ("off", "0", "false", "no")


def run_dir() -> Optional[str]:
    """The event-sink run directory, or None for in-memory-only operation
    (``DMT_OBS_DIR`` env var > ``obs_dir`` config field)."""
    if not obs_enabled():
        return None
    return os.environ.get("DMT_OBS_DIR") or get_config().obs_dir or None


def event_path() -> Optional[str]:
    """This process's JSONL file path (``<dir>/rank_<r>/events.jsonl`` — one
    subdirectory per rank so multi-rank runs merge by construction), or None
    when no sink is configured."""
    d = run_dir()
    if not d:
        return None
    return os.path.join(d, f"rank_{_process_index()}", "events.jsonl")


def _json_default(o):
    """Make numpy scalars/arrays (the payloads solvers and engines carry)
    JSON-serializable; anything else degrades to its repr — an exotic field
    must not cost the event line."""
    try:
        import numpy as np

        if isinstance(o, np.generic):
            return o.item()
        if isinstance(o, np.ndarray):
            return o.tolist()
    except ImportError:  # pragma: no cover - numpy is a hard dep
        pass
    return repr(o)


def _write(ev: dict) -> None:
    global _sink, _sink_path, _sink_failed, _atexit_registered
    if _sink_failed:
        return
    path = event_path()
    if path is None:
        return
    try:
        if _sink is None or _sink_path != path:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            if _sink is not None:
                _sink.close()
            # line-buffered append so `obs_report tail --follow` sees events
            # as they happen, and a crash loses at most the current line
            _sink = open(path, "a", buffering=1)
            _sink_path = path
            if not _atexit_registered:
                # flush-on-exit backstop: the final events of a preempted
                # or crashing run (checkpoint-written, solver_preempted,
                # stall_report) must reach rank_<r>/events.jsonl even when
                # the harness never reaches its explicit flush()
                import atexit

                atexit.register(flush)
                _atexit_registered = True
        _sink.write(json.dumps(ev, default=_json_default) + "\n")
    except OSError as e:
        _sink_failed = True  # degrade to in-memory; warn ONCE, not per event
        log_warn(f"event sink disabled ({path}): {e!r}")


def emit(kind: str, **fields) -> Optional[dict]:
    """Record one event; returns the full event dict, or None when the
    layer is disabled.  The envelope keys (``seq``/``ts``/``proc``/
    ``rank``/``n_ranks``/``kind``) always win: a payload field colliding
    with one is DROPPED — readers key cross-rank ordering and straggler
    attribution on the envelope, so a producer must never be able to
    spoof it."""
    global _seq, _trace_stamper
    if not obs_enabled():
        return None
    stamp = None
    if _trace_stamper is not None:
        # outside _lock: the stamper takes the trace layer's own lock and
        # may touch the run directory once (trace-id agreement)
        try:
            stamp = _trace_stamper()
        except Exception as e:
            log_warn(f"trace stamper disabled: {e!r}")
            _trace_stamper = None
    with _lock:
        seq = _seq
        _seq += 1
        rank = _process_index()
        ev = {"seq": seq, "ts": round(time.time(), 6),
              "proc": rank, "rank": rank, "n_ranks": _process_count(),
              "kind": str(kind)}
        if stamp:
            # trace_id / job_id / span_id join the envelope: causal
            # identity is envelope truth, so a producer cannot spoof it
            ev.update(stamp)
        for k, v in fields.items():
            if k not in ev:
                ev[k] = v
        _buffer.append(ev)
        _write(ev)
    return ev


def events(kind: Optional[str] = None) -> List[dict]:
    """Snapshot of this process's in-memory event buffer (optionally
    filtered by ``kind``) — newest last."""
    with _lock:
        evs = list(_buffer)
    if kind is not None:
        evs = [e for e in evs if e.get("kind") == kind]
    return evs


def annotate(name: str):
    """Context manager marking a named span in the active ``jax.profiler``
    trace (no-op when the layer is off or jax is unavailable).  Host-side
    only — a ``TraceAnnotation`` never launches device work."""
    if not obs_enabled():
        return nullcontext()
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return nullcontext()


def flush() -> None:
    """Flush the JSONL sink (harness exit points; in-memory mode no-op)."""
    with _lock:
        if _sink is not None:
            try:
                _sink.flush()
            except OSError:
                pass


def reset() -> None:
    """Close the sink and clear buffer + sequence counter (tests; also the
    way to re-point an already-running process at a new ``obs_dir``)."""
    global _seq, _sink, _sink_path, _sink_failed
    with _lock:
        if _sink is not None:
            try:
                _sink.close()
            except OSError:
                pass
        _sink = None
        _sink_path = None
        _sink_failed = False
        _seq = 0
        _buffer.clear()
