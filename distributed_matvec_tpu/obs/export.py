"""OpenMetrics export — the scrape plane over the metrics registry.

Fifteen PRs of telemetry answer questions *after* a run (JSONL streams,
``obs_report``, ``bench_trend``); nothing exposes a LIVE fleet to a
monitoring stack.  This module renders :func:`~.metrics.snapshot` —
the exact registry the harnesses already emit as ``metrics_snapshot``
events — into the Prometheus / OpenMetrics text exposition format, and
serves it three ways:

* **Per-rank HTTP endpoint** (:func:`start_exporter`): a stdlib
  ``ThreadingHTTPServer`` answering ``GET /metrics`` (fresh snapshot per
  scrape) and ``GET /healthz`` (rank identity + uptime).  The port comes
  from ``DMT_OBS_PORT`` / ``config.obs_port`` **plus the process index**,
  so every rank of a multi-host run is scrapeable side by side; unset/0
  means no server (and with ``DMT_OBS=off`` no socket is ever bound —
  the provable-no-op contract, guard-tested).
* **Textfile mode** (:func:`write_textfile`): the same rendering written
  atomically to ``<run_dir>/rank_<r>/metrics.prom`` — the node-exporter
  textfile-collector path for fleets without per-rank scrape access.
* **Rank-0 aggregation**: rank 0's ``/metrics`` merges every peer's
  textfile under the shared run directory behind its own snapshot
  (:func:`merge_openmetrics`), so one scrape target covers the run.

Naming contract (DESIGN.md §31): every sample is ``dmt_<name>`` with the
registry's labels, counters gain the OpenMetrics ``_total`` suffix,
histograms export cumulative ``_bucket{le=...}``/``_sum``/``_count``,
and a ``rank`` label pins each sample to its producer.  HELP text and
gate direction both come from ``obs/directions.py`` — the exporter and
``bench_trend`` read the same table, so the scrape plane can never
disagree with the gate plane about what a metric means.  Values are
rendered with ``repr`` (shortest round-trip form), so a scraped number
is **exactly** the registry value — parity with the JSONL-recovered
``metrics_snapshot`` is tested, not hoped for.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

from ..utils.config import get_config
from ..utils.logging import _process_index, log_info, log_warn
from . import metrics as _metrics
from .events import obs_enabled, run_dir

__all__ = [
    "render_openmetrics",
    "parse_openmetrics",
    "merge_openmetrics",
    "write_textfile",
    "textfile_path",
    "start_exporter",
    "stop_exporter",
    "MetricsServer",
]

_PREFIX = "dmt_"


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _fmt(value) -> str:
    """Shortest exact decimal form: ints stay ints, floats render via
    ``repr`` (round-trips bit-exactly through ``float()``) — the parity
    contract with the JSONL ``metrics_snapshot`` depends on this."""
    if isinstance(value, bool):
        return str(int(value))
    if isinstance(value, int):
        return str(value)
    v = float(value)
    if v != v:
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    return repr(v)


def _split_series(sname: str) -> Tuple[str, Dict[str, str]]:
    """Inverse of :func:`~.metrics.series_name`:
    ``name{k=v,...}`` → ``(name, {k: v})``."""
    if "{" not in sname:
        return sname, {}
    name, _, rest = sname.partition("{")
    labels: Dict[str, str] = {}
    for pair in rest.rstrip("}").split(","):
        if pair:
            k, _, v = pair.partition("=")
            labels[k] = v
    return name, labels


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _help_line(base: str) -> str:
    from .directions import metric_meta
    return metric_meta(base)["help"]


def render_openmetrics(snap: Optional[dict] = None,
                       extra_labels: Optional[Dict[str, str]] = None,
                       info: Optional[Dict[str, str]] = None) -> str:
    """The registry snapshot as OpenMetrics text.  ``extra_labels`` are
    stamped onto every sample (the per-rank exporter passes
    ``{"rank": "<r>"}``); ``info`` fields ride a ``dmt_run_info`` gauge
    (trace/job identity — labels, value always 1)."""
    if snap is None:
        snap = _metrics.snapshot()
    extra = dict(extra_labels or {})
    lines: List[str] = []

    def _family(sname: str) -> Tuple[str, str]:
        base, labels = _split_series(sname)
        labels.update(extra)
        return _PREFIX + base, _label_str(labels)

    seen_types: set = set()

    def _head(fam: str, mtype: str, base: str) -> None:
        if fam not in seen_types:
            seen_types.add(fam)
            lines.append(f"# TYPE {fam} {mtype}")
            lines.append(f"# HELP {fam} {_escape_label(_help_line(base))}")

    for sname in sorted(snap.get("counters", {})):
        base, _ = _split_series(sname)
        fam, lab = _family(sname)
        _head(fam, "counter", base)
        lines.append(f"{fam}_total{lab} {_fmt(snap['counters'][sname])}")
    for sname in sorted(snap.get("gauges", {})):
        base, _ = _split_series(sname)
        fam, lab = _family(sname)
        _head(fam, "gauge", base)
        lines.append(f"{fam}{lab} {_fmt(snap['gauges'][sname])}")
    for sname in sorted(snap.get("histograms", {})):
        base, labels = _split_series(sname)
        labels.update(extra)
        fam = _PREFIX + base
        _head(fam, "histogram", base)
        h = snap["histograms"][sname]
        cum = 0
        for ub, c in zip(list(h["buckets"]) + ["+Inf"], h["counts"]):
            cum += c
            blab = _label_str({**labels, "le": ub if ub == "+Inf"
                               else _fmt(ub)})
            lines.append(f"{fam}_bucket{blab} {cum}")
        lab = _label_str(labels)
        lines.append(f"{fam}_sum{lab} {_fmt(h['sum'])}")
        lines.append(f"{fam}_count{lab} {h['count']}")
    if info:
        fam = _PREFIX + "run_info"
        lines.append(f"# TYPE {fam} gauge")
        lines.append(f"# HELP {fam} Run identity (labels carry the ids)")
        lines.append(f"{fam}{_label_str({**info, **extra})} 1")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def parse_openmetrics(text: str,
                      drop_labels: Iterable[str] = ("rank",)) -> dict:
    """Inverse of :func:`render_openmetrics` back into the
    :func:`~.metrics.snapshot` shape (the parity tests' other half).
    ``drop_labels`` strips exporter-added labels (``rank``) so the
    reconstructed series names match the registry's own."""
    drop = set(drop_labels)
    types: Dict[str, str] = {}
    out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
    hists: Dict[str, dict] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line == "# EOF":
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        # sample: name{labels} value   (label values may contain spaces)
        if "}" in line:
            head, _, val = line.rpartition(" ")
            name, _, rest = head.partition("{")
            labels = {}
            for m in rest.rstrip("}").split('",'):
                if not m:
                    continue
                k, _, v = m.partition("=")
                labels[k.strip()] = (v.strip().strip('"')
                                     .replace(r'\"', '"')
                                     .replace(r"\n", "\n")
                                     .replace(r"\\", "\\"))
        else:
            name, _, val = line.partition(" ")
            labels = {}
        value = float(val)
        le = labels.pop("le", None)
        labels = {k: v for k, v in labels.items() if k not in drop}
        base = name
        kind = None
        for suffix, k in (("_bucket", "histogram"), ("_sum", "histogram"),
                          ("_count", "histogram"), ("_total", "counter")):
            fam = name[: -len(suffix)] if name.endswith(suffix) else None
            if fam and types.get(fam) == k:
                base, kind = fam, k
                break
        if kind is None:
            kind = types.get(name, "gauge")
        if base == _PREFIX + "run_info":
            continue
        short = base[len(_PREFIX):] if base.startswith(_PREFIX) else base
        sname = _metrics.series_name(short, labels)
        if kind == "counter":
            iv = int(value)
            out["counters"][sname] = iv if iv == value else value
        elif kind == "gauge":
            out["gauges"][sname] = value
        else:
            h = hists.setdefault(sname, {"buckets": [], "cum": [],
                                         "sum": 0.0, "count": 0})
            if name.endswith("_bucket"):
                if le != "+Inf":
                    h["buckets"].append(float(le))
                h["cum"].append(int(value))
            elif name.endswith("_sum"):
                h["sum"] = value
            elif name.endswith("_count"):
                h["count"] = int(value)
    for sname, h in hists.items():
        counts = [c - p for c, p in zip(h["cum"], [0] + h["cum"][:-1])]
        out["histograms"][sname] = {"buckets": h["buckets"],
                                    "counts": counts, "sum": h["sum"],
                                    "count": h["count"]}
    return out


def merge_openmetrics(texts: List[str]) -> str:
    """Concatenate exposition texts from several ranks into one valid
    document: one ``# TYPE``/``# HELP`` head per family (first writer
    wins — every rank derives them from the same shared table), samples
    appended in input order (they are disjoint by their ``rank`` label),
    one trailing ``# EOF``."""
    seen: set = set()
    out: List[str] = []
    for text in texts:
        for line in text.splitlines():
            if line == "# EOF" or not line.strip():
                continue
            if line.startswith("# TYPE") or line.startswith("# HELP"):
                if line in seen:
                    continue
                seen.add(line)
            out.append(line)
    out.append("# EOF")
    return "\n".join(out) + "\n"


def _identity() -> Dict[str, str]:
    from . import trace as _trace
    info: Dict[str, str] = {}
    tid = _trace.trace_id()
    if tid:
        info["trace_id"] = tid
        jid = _trace.job_id()
        if jid:
            info["job_id"] = jid
    return info


def _render_self() -> str:
    rank = _process_index()
    return render_openmetrics(extra_labels={"rank": str(rank)},
                              info=_identity())


def textfile_path(rank: Optional[int] = None) -> Optional[str]:
    """``<run_dir>/rank_<r>/metrics.prom``, or None without a sink dir."""
    d = run_dir()
    if not d:
        return None
    r = _process_index() if rank is None else int(rank)
    return os.path.join(d, f"rank_{r}", "metrics.prom")


def write_textfile(path: Optional[str] = None) -> Optional[str]:
    """Render this rank's snapshot to its textfile atomically (tmp +
    rename — a collector never reads a torn file).  Returns the path, or
    None when the layer is off or no run directory is configured."""
    if not obs_enabled():
        return None
    path = path or textfile_path()
    if not path:
        return None
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            f.write(_render_self())
        os.replace(tmp, path)
    except OSError as e:
        log_warn(f"metrics textfile write failed ({path}): {e!r}")
        return None
    return path


def _peer_textfiles(own_rank: int) -> List[str]:
    d = run_dir()
    if not d or not os.path.isdir(d):
        return []
    texts = []
    for name in sorted(os.listdir(d)):
        if not name.startswith("rank_"):
            continue
        try:
            r = int(name[len("rank_"):])
        except ValueError:
            continue
        if r == own_rank:
            continue
        path = os.path.join(d, name, "metrics.prom")
        try:
            with open(path) as f:
                texts.append(f.read())
        except OSError:
            continue
    return texts


def _aggregate() -> str:
    """Rank 0's scrape body: own fresh snapshot + every peer's textfile
    merged into one document (non-zero ranks serve only themselves)."""
    rank = _process_index()
    own = _render_self()
    if rank != 0:
        return own
    peers = _peer_textfiles(own_rank=0)
    return merge_openmetrics([own] + peers) if peers else own


class MetricsServer:
    """Tiny stdlib HTTP exporter: ``/metrics`` (OpenMetrics, fresh
    snapshot per scrape; rank 0 aggregates peer textfiles) and
    ``/healthz`` (JSON liveness: rank, trace id, uptime)."""

    def __init__(self, port: int, host: str = "127.0.0.1"):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        t_start = time.time()

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):    # scrapes must not spam stderr
                pass

            def _send(self, code: int, body: str, ctype: str) -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                path = self.path.split("?")[0]
                try:
                    if path == "/metrics":
                        self._send(200, _aggregate(),
                                   "application/openmetrics-text; "
                                   "version=1.0.0; charset=utf-8")
                    elif path == "/healthz":
                        body = json.dumps(
                            {"status": "ok", "rank": _process_index(),
                             "uptime_s": round(time.time() - t_start, 3),
                             **_identity()})
                        self._send(200, body + "\n", "application/json")
                    else:
                        self._send(404, "not found\n", "text/plain")
                except BrokenPipeError:   # scraper hung up mid-response
                    pass

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="dmt-metrics-exporter",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


_server: Optional[MetricsServer] = None
_server_lock = threading.Lock()


def _resolve_port() -> int:
    """``DMT_OBS_PORT`` / ``config.obs_port`` plus the process index
    (side-by-side rank endpoints); 0/unset means no exporter."""
    env = os.environ.get("DMT_OBS_PORT")
    base = int(env) if env is not None else int(get_config().obs_port)
    if base <= 0:
        return 0
    return base + _process_index()


def start_exporter(port: Optional[int] = None,
                   host: str = "127.0.0.1") -> Optional[MetricsServer]:
    """Start (or return) this process's exporter.  ``port=None`` resolves
    ``DMT_OBS_PORT``/``config.obs_port`` (+rank) and returns None when
    unset — the knob is opt-in; an explicit ``port=0`` binds an ephemeral
    port (tests).  With ``DMT_OBS=off`` this returns None without ever
    touching a socket (the provable-no-op contract)."""
    global _server
    if not obs_enabled():
        return None
    with _server_lock:
        if _server is not None:
            return _server
        p = _resolve_port() if port is None else int(port)
        if port is None and p <= 0:
            return None
        try:
            _server = MetricsServer(p, host=host)
        except OSError as e:
            log_warn(f"metrics exporter failed to bind :{p}: {e!r}")
            return None
        log_info(f"metrics exporter serving http://{host}:{_server.port}"
                 f"/metrics (rank {_process_index()})")
        return _server


def stop_exporter() -> None:
    """Shut the exporter down (idempotent)."""
    global _server
    with _server_lock:
        if _server is not None:
            _server.stop()
            _server = None
