"""Per-apply phase attribution: where one matvec spends its time and bytes.

Fourth pillar of the telemetry subsystem (see ``obs/__init__``).  Whole-apply
wall clocks (``matvec_apply`` events, PR 3) say *that* an apply was slow;
the ROADMAP's next levers — plan compression ("attacks the roofline itself")
and pipelined applies (overlap exchange with chunk compute) — are bets about
*where inside one apply* the time goes.  This module decomposes every eager
apply into named phases and emits one ``apply_phases`` event per apply:

==============  ============================================================
phase           meaning
==============  ============================================================
``plan_h2d``    host→device plan streaming (streamed mode's per-apply chunk
                uploads; zero for resident-structure modes)
``compute``     gather + multiply: structure-table / exchange-slot gathers,
                the fused orbit scan, coefficient multiply-accumulate
``exchange``    the cross-shard amplitude ``all_to_all`` payload
``accumulate``  receive-side ``segment_sum`` / tail scatter-adds
``overhead``    dispatch + validation + everything unattributed (defined as
                whole-apply wall minus the attributed phases at report time)
==============  ============================================================

Contract (the health-probe pattern, DESIGN.md §18 applied to timing): the
apply HLO is **byte-identical** with phase attribution on or off.  Nothing
here adds device work — ``bytes`` / ``gathers`` / ``flops`` are *structural*
counts the engines already know host-side (pure functions of the engine
geometry, computed once per (mode, columns) and cached), and wall times are
host ``perf_counter`` readings around dispatch segments the engines already
take (the streamed chunk-stream loop measures its H2D waits anyway).  Phase
*wall* attribution for single-program applies happens at report time
(``obs/roofline.py`` splits the measured wall across phases in proportion to
the cost model), so the recording path stays sync-free.

Exactness invariant (pinned by ``tests/test_phases.py``): the per-phase
``bytes``/``gathers``/``flops`` sum to the event's ``*_total`` fields
exactly, and cross-check against independent engine quantities
(``plan_bytes``, ``_exchange_nbytes``, the ``bytes_h2d`` counter).

``DMT_PHASES=off`` (or ``config.phases``) disables the events while leaving
every apply program untouched — the byte-identity guard in
``tools/roofline_check.py`` compiles the apply both ways and compares HLO.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from ..utils.config import get_config
from .events import emit, obs_enabled

__all__ = [
    "PHASES",
    "PHASE_RESOURCE",
    "ORBIT_OPS",
    "phases_enabled",
    "zero_counts",
    "emit_apply_phases",
    "emit_tune_config",
    "emit_retune",
]

#: Flops charged per group element of the fused orbit scan (coset-walk
#: step: permute + phase + compare).  A documented constant of the cost
#: model (DESIGN.md §22), not a hardware truth — both engines' fused-mode
#: compute counts use it.
ORBIT_OPS = 16

#: Canonical phase order (reports render in this order; ``overhead`` is
#: derived at report time and carries no structural counts).  The two
#: ``compute_*`` phases are HYBRID mode's split of ``compute``
#: (DESIGN.md §28): ``compute_decode`` is the streamed term subset's
#: decode + x-row gather + multiply, ``compute_recompute`` the recompute
#: subset's on-device orbit scan + routing + multiply — the roofline
#: report prices each against its own resource, so a mispriced split
#: shows up as one of them running far off its bound.  Non-hybrid modes
#: keep the single ``compute`` phase (trend continuity).
PHASES = ("plan_h2d", "compute", "compute_decode", "compute_recompute",
          "exchange", "accumulate", "overhead")

#: The hardware resource each phase is bound by — what a roofline report
#: names when a phase dominates.
PHASE_RESOURCE = {
    "plan_h2d": "h2d bandwidth",
    "compute": "gather rate",
    "compute_decode": "gather rate",
    "compute_recompute": "flop rate (orbit scan)",
    "exchange": "interconnect bandwidth",
    "accumulate": "scatter rate",
    "overhead": "host dispatch",
}


def phases_enabled() -> bool:
    """Whether ``apply_phases`` events are emitted (requires obs on; the
    env var is consulted directly so harnesses can flip it per subprocess —
    same contract as :func:`~.events.obs_enabled`)."""
    if not obs_enabled():
        return False
    env = os.environ.get("DMT_PHASES")
    knob = env if env is not None else get_config().phases
    return str(knob).strip().lower() not in ("off", "0", "false", "no")


def zero_counts() -> Dict[str, Dict[str, int]]:
    """A fresh all-zero per-phase count dict (``overhead`` excluded — it
    carries no structural counts by definition; the hybrid-only
    ``compute_*`` split phases excluded too — only the hybrid engine adds
    them, so every other mode's events keep their exact historical key
    set)."""
    return {p: {"bytes": 0, "gathers": 0, "flops": 0}
            for p in ("plan_h2d", "compute", "exchange", "accumulate")}


def emit_apply_phases(engine: str, mode: str, apply_index: int,
                      wall_ms: float, counts: Dict[str, Dict[str, int]],
                      chunks: int = 1, columns: int = 1,
                      measured_ms: Optional[Dict[str, float]] = None,
                      chunk_timeline: Optional[list] = None,
                      pipeline: Optional[dict] = None
                      ) -> Optional[dict]:
    """Record one apply's phase decomposition.

    ``counts`` maps phase → ``{bytes, gathers, flops}`` (structural, exact);
    ``measured_ms`` carries phases whose wall time was *measured* host-side
    (streamed mode's ``plan_h2d`` H2D waits; a pipelined apply's exposed
    ``exchange`` dispatch wall) rather than model-attributed;
    ``chunk_timeline`` is the streamed per-chunk record
    ``[{chunk, stall_ms, dispatch_ms}, ...]`` the pipelined-apply estimate
    reads; ``pipeline`` carries the measured overlap/time-at-barrier split
    of a pipelined apply (``{depth, barrier_ms, hidden_ms,
    overlap_fraction}`` — DESIGN.md §25): ``barrier_ms`` is the host wall
    actually EXPOSED waiting on plan staging / exchange feeds,
    ``hidden_ms`` the staging work that ran behind chunk compute, and a
    measured ``exchange`` phase beating its bound renders ``hidden`` in
    the roofline report (= overlap working).  Totals are computed here so
    readers (and the exactness tests) never re-derive them."""
    if not phases_enabled():
        return None
    totals = {"bytes": 0, "gathers": 0, "flops": 0}
    phases = {}
    for p, c in counts.items():
        rec = {k: int(c.get(k, 0)) for k in ("bytes", "gathers", "flops")}
        if measured_ms and p in measured_ms:
            rec["wall_ms"] = round(float(measured_ms[p]), 4)
        for k in totals:
            totals[k] += rec[k]
        phases[p] = rec
    ev = {"engine": str(engine), "mode": str(mode),
          "apply": int(apply_index), "wall_ms": round(float(wall_ms), 4),
          "chunks": int(chunks), "columns": int(columns),
          "phases": phases,
          "bytes_total": totals["bytes"],
          "gathers_total": totals["gathers"],
          "flops_total": totals["flops"]}
    if chunk_timeline:
        ev["chunk_timeline"] = chunk_timeline
    if pipeline:
        ev["pipeline"] = {k: (round(float(v), 4)
                              if isinstance(v, float) else v)
                          for k, v in pipeline.items()}
    return emit("apply_phases", **ev)


def emit_tune_config(engine: str, mode: str, config: dict, token: str,
                     priced_ms: float, source: str, search_s: float,
                     fingerprint: str) -> Optional[dict]:
    """One autotune decision (DESIGN.md §30): the knob config an engine
    build adopted, where it came from (``search`` | ``artifact`` |
    ``retune``), its roofline price, and what the search cost.  Rides
    the obs switch only — tune events are build-time bookkeeping, not
    per-apply work, so the ``phases`` knob does not gate them."""
    if not obs_enabled():
        return None
    return emit("tune_config", engine=str(engine), mode=str(mode),
                config=dict(config), token=str(token),
                priced_ms=round(float(priced_ms), 4), source=str(source),
                search_s=round(float(search_s), 6),
                fingerprint=str(fingerprint))


def emit_retune(engine: str, mode: str, apply_index: int,
                old_token: str, new_token: str, ratio: float,
                priced_ms: float, rebuild_s: float) -> Optional[dict]:
    """One drift-triggered re-tune applied at a safe boundary: the
    measured/priced ``ratio`` that tripped ``tune/live.DRIFT_BAND``, the
    old and new knob tokens, and what the boundary re-key cost.  The
    ``obs_report roofline`` console renders these rows so an operator
    sees *when* the runtime re-decided, not just that walls changed."""
    if not obs_enabled():
        return None
    return emit("retune", engine=str(engine), mode=str(mode),
                apply=int(apply_index), old_token=str(old_token),
                new_token=str(new_token), ratio=round(float(ratio), 4),
                priced_ms=round(float(priced_ms), 4),
                rebuild_s=round(float(rebuild_s), 4))
