"""obs — process-wide telemetry: metrics registry, structured event log,
profiler annotations.

The reference answers "why was this run slow?" with tree timers and comm
diagnostics behind ``--kDisplayTimings``/``--kVerboseComm``; after the
warm-start caches of DESIGN.md §16 the same question here spans artifact
hits, AOT executable reuse, host↔device transfer volume, and solver
convergence — none of it visible from a wall clock.  This package is the
observability spine those signals report through (the per-phase accounting
arXiv:2112.09017 credits for its scaling wins, plus the compile/retrace
visibility GSPMD (arXiv:2105.04663) treats as a first-class signal):

* :mod:`~.metrics` — counters / gauges / fixed-bucket histograms with
  labeled series (``matvec_apply_ms{engine=local}``,
  ``artifact_cache{event=hit}``, ``bytes_h2d``, ``retrace_count``);
  :func:`snapshot` turns the registry into plain data.
* :mod:`~.events` — append-only JSONL per process
  (``<run_dir>/rank_<r>/events.jsonl``, rank-tagged envelope, monotonic
  ``seq``, soft-fail writes), an in-memory ring buffer, and
  :func:`annotate` spans that line the JSONL timeline up with
  ``jax.profiler`` Perfetto traces.
* :mod:`~.health` — numerical-health probes (deferred-fetch NaN/Inf +
  norm reductions on engine applies, exchange overflow/invalid counters)
  and the solver watchdog (``solver_health`` events; ``DMT_HEALTH=strict``
  raises :class:`~.health.HealthError` on critical conditions).
* :mod:`~.phases` / :mod:`~.roofline` — per-apply phase attribution
  (``apply_phases`` events: plan H2D / compute / exchange / accumulate
  with exact structural byte/gather/flop counts, apply HLO byte-identical
  on or off) and the analytical roofline model over them (calibrated
  rates, binding-resource naming, pipelined-apply speedup estimates) —
  DESIGN.md §22.
* :mod:`~.trace` — end-to-end solve tracing (DESIGN.md §24): one
  ``trace_id`` per run (file-agreed across ranks through the shared run
  directory), a ``job_id`` namespacing knob (``DMT_JOB_ID``), and
  parent-linked spans (solve > iteration > apply > chunk) stamped into
  every event's envelope; one ``span`` event per closed span.
* ``tools/obs_report.py`` — the reader: ``summarize`` one run, ``merge`` /
  ``report --ranks`` a multi-rank one (skew-corrected timeline, per-rank
  straggler attribution), ``diff`` two runs as a CI perf gate,
  ``roofline`` the phase/cost-model report, ``trace`` a Perfetto export
  of the merged span tree, ``watch`` a live terminal dashboard over the
  rank streams, ``tail`` a live one.

Config: ``DMT_OBS_DIR`` (or ``obs_dir``) points the sink at a run
directory; unset ⇒ in-memory only; ``DMT_OBS=off`` disables the layer
entirely, at which point every instrument is the shared no-op
:data:`~.metrics.NULL` and the instrumented hot paths add **zero
device-side work** (no syncs, no fetches — guard-tested).
"""

from .events import (annotate, emit, event_path, events, flush, obs_enabled,
                     reset, run_dir)
from .export import (merge_openmetrics, parse_openmetrics,
                     render_openmetrics, start_exporter, stop_exporter,
                     textfile_path, write_textfile)
from .flight import (flight_dump, install_fatal_handlers, list_bundles,
                     postmortem_dir, read_bundle, reset_flight,
                     verify_bundle)
from .health import (HealthError, drain as drain_health, health_event_count,
                     health_mode, probes_enabled, record as record_health,
                     reset_health)
from .hlo import (diff_profiles, executable_costs, hottest_ops,
                  load_profile, record_executable_costs, reset_hlo)
from .profile import (measured_overhead_pct, overhead_snapshot,
                      profile_due, profile_mode, reset_profile,
                      sample_window, stamp_profile_dir, trigger_capture)
from .memory import (MemoryReport, OomError, attach_oom,
                     build_memory_report, emit_ledger, executable_analyses,
                     last_watermark, ledger_entries, ledger_total,
                     ledger_tree, record_executable_analysis, reset_memory,
                     sample_watermark, track, track_tree, watermark_due)
from .metrics import (DEFAULT_BUCKETS, NULL, counter, gauge, histogram,
                      reset_metrics, series_name)
from .metrics import snapshot as _metrics_snapshot
from .phases import (PHASES, emit_apply_phases, phases_enabled, zero_counts)
from .slo import (SloSpec, check_slos, default_slos, reset_slo)
from .slo import evaluate as evaluate_slos
from .trace import (current_span_id, deepest_span, job_id, open_spans,
                    reset_trace, span, span_path, trace_enabled, trace_id)

__all__ = [
    "annotate",
    "emit",
    "event_path",
    "events",
    "flush",
    "obs_enabled",
    "reset",
    "run_dir",
    "counter",
    "gauge",
    "histogram",
    "snapshot",
    "series_name",
    "reset_metrics",
    "NULL",
    "DEFAULT_BUCKETS",
    "HealthError",
    "drain_health",
    "health_event_count",
    "health_mode",
    "probes_enabled",
    "record_health",
    "reset_health",
    "MemoryReport",
    "OomError",
    "attach_oom",
    "build_memory_report",
    "emit_ledger",
    "executable_analyses",
    "last_watermark",
    "ledger_entries",
    "ledger_total",
    "ledger_tree",
    "record_executable_analysis",
    "reset_memory",
    "sample_watermark",
    "track",
    "track_tree",
    "watermark_due",
    "PHASES",
    "emit_apply_phases",
    "phases_enabled",
    "zero_counts",
    "current_span_id",
    "deepest_span",
    "job_id",
    "open_spans",
    "reset_trace",
    "span",
    "span_path",
    "trace_enabled",
    "trace_id",
    "merge_openmetrics",
    "parse_openmetrics",
    "render_openmetrics",
    "start_exporter",
    "stop_exporter",
    "textfile_path",
    "write_textfile",
    "flight_dump",
    "install_fatal_handlers",
    "list_bundles",
    "postmortem_dir",
    "read_bundle",
    "reset_flight",
    "verify_bundle",
    "SloSpec",
    "check_slos",
    "default_slos",
    "evaluate_slos",
    "reset_slo",
    "diff_profiles",
    "executable_costs",
    "hottest_ops",
    "load_profile",
    "record_executable_costs",
    "reset_hlo",
    "measured_overhead_pct",
    "overhead_snapshot",
    "profile_due",
    "profile_mode",
    "reset_profile",
    "sample_window",
    "stamp_profile_dir",
    "trigger_capture",
]


def snapshot() -> dict:
    """The metrics registry as plain data — after draining any pending
    health-probe fetches, so a closing ``metrics_snapshot`` always carries
    the final overflow/invalid/nonfinite counter totals."""
    drain_health()
    return _metrics_snapshot()


def reset_all() -> None:
    """Reset events, metrics, health, memory, trace, SLO, flight, HLO
    and profiling state (test isolation helper); also stops a running
    exporter."""
    stop_exporter()
    reset()
    reset_metrics()
    reset_health()
    reset_memory()
    reset_trace()
    reset_slo()
    reset_flight()
    reset_hlo()
    reset_profile()
