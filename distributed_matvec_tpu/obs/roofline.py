"""Analytical roofline model over ``apply_phases`` events + rate calibration.

DESIGN.md §2 established that the gather roofline governs the apply; this
module turns that one-off measurement into a per-run report: for every
(engine, mode) seen in a telemetry run it combines the *structural* phase
counts (``obs/phases.py``) with measured hardware rates to compute

* a **bound time** per phase (the time the phase would take running at the
  hardware rate: bytes / bandwidth, gathers / gather-rate),
* an **attributed wall** per phase — measured where the engines measured it
  (streamed ``plan_h2d`` H2D waits), otherwise the leftover apply wall split
  in proportion to the bounds, so the phase walls *sum to the measured apply
  wall exactly*,
* the per-phase **achieved-vs-bound fraction** (bound / attributed wall:
  1.0 = running at the roofline),
* the **binding resource** — the phase with the largest bound share, named
  via :data:`~.phases.PHASE_RESOURCE` (chain_32_symm's answer is "gather
  rate" at ≈93%, DESIGN.md §2; a streamed run's is typically "h2d
  bandwidth" or "gather rate" depending on plan size), and
* a **pipelined-apply speedup estimate** — the ROADMAP's overlap item priced
  before it's built: overlapping the exchange of chunk *i* with the compute
  of chunk *i+1* saves ``min(compute, exchange) · (1 − 1/nchunks)``, so

      speedup = wall / (wall − min(compute_wall, exchange_wall)·(1 − 1/nchunks))

  (1.0 for single-shard/local engines — nothing to overlap).

Calibration: measured rates live in a content-addressed JSON sidecar under
the artifact root (``calibration/<fp>.json``; fingerprint = backend +
device kind).  ``tools/gather_bound.py`` writes it (the microbenchmark that
used to print-and-discard); this module and ``tools/capacity.py`` read it.
Without a sidecar the documented DESIGN.md §2 defaults apply (TPU v5e) or
conservative CPU-rig defaults — every report states its calibration source.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..utils.logging import log_debug, log_warn
from .phases import PHASE_RESOURCE, PHASES

__all__ = [
    "DEFAULT_CALIBRATIONS",
    "default_calibration",
    "calibration_path",
    "save_calibration",
    "load_calibration",
    "resolve_calibration",
    "phase_bounds_ms",
    "attribute_phases",
    "choose_pipeline_depth",
    "price_term_split",
    "choose_hybrid_split",
    "hlo_phase_split",
    "roofline_report",
    "print_roofline",
    "reconcile_error",
]

#: Rate fields every calibration carries (units in the name).
RATE_FIELDS = ("gather_rows_per_s", "h2d_bytes_per_s",
               "exchange_bytes_per_s", "flops_per_s")

#: Documented defaults per backend family.  TPU numbers are the DESIGN.md §2
#: v5e measurements (gather 160–185 M rows/s at large tables — the flat,
#: locality-independent per-row rate); h2d/ICI are nominal catalog numbers.
#: CPU numbers are conservative single-core-rig figures for the virtual-
#: device test mesh; a `tools/gather_bound.py` run replaces them with
#: measured rates.
DEFAULT_CALIBRATIONS: Dict[str, Dict[str, float]] = {
    "tpu": {"gather_rows_per_s": 185e6, "h2d_bytes_per_s": 8e9,
            "exchange_bytes_per_s": 45e9, "flops_per_s": 2e11},
    "cpu": {"gather_rows_per_s": 25e6, "h2d_bytes_per_s": 8e9,
            "exchange_bytes_per_s": 4e9, "flops_per_s": 5e9},
}

#: Scatter-side entries are weighted 2× a gather (the ELL split cost model's
#: measured weighting, parallel/engine.py::choose_ell_split).
SCATTER_WEIGHT = 2.0


def default_calibration(backend: Optional[str] = None) -> dict:
    """The analytic default rates for ``backend`` (``jax.default_backend()``
    when None), tagged ``source="default"`` so reports say so."""
    if backend is None:
        try:
            import jax
            backend = jax.default_backend()
        except Exception:
            backend = "cpu"
    base = DEFAULT_CALIBRATIONS.get(
        str(backend).lower(), DEFAULT_CALIBRATIONS["cpu"])
    return dict(base, backend=str(backend), source="default")


def _calibration_fingerprint(backend: str, device_kind: str) -> str:
    import hashlib

    return hashlib.sha256(
        f"calibration|{backend}|{device_kind}|v1".encode()).hexdigest()


def calibration_path(backend: Optional[str] = None,
                     device_kind: Optional[str] = None) -> Optional[str]:
    """Content-addressed sidecar path for this backend's measured rates
    (None when the artifact layer is off)."""
    from ..utils.artifacts import artifact_path, artifacts_enabled

    if not artifacts_enabled():
        return None
    if backend is None or device_kind is None:
        try:
            import jax
            backend = backend or jax.default_backend()
            device_kind = device_kind or jax.devices()[0].device_kind
        except Exception:
            return None
    return artifact_path(
        "calibration", _calibration_fingerprint(backend, device_kind),
        ".json")


def save_calibration(cal: dict, path: Optional[str] = None) -> Optional[str]:
    """Persist measured rates (atomic write; soft-fail — a read-only
    checkout must not turn a microbenchmark into an I/O error).  Returns
    the path written, or None."""
    path = path or calibration_path(cal.get("backend"),
                                    cal.get("device_kind"))
    if not path:
        return None
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path + ".tmp", "w") as f:
            json.dump(dict(cal, source="measured"), f, indent=1,
                      sort_keys=True)
        os.replace(path + ".tmp", path)
    except OSError as e:
        log_warn(f"calibration save failed ({path}): {e!r}")
        return None
    log_debug(f"calibration saved to {path}")
    return path


def load_calibration(path: Optional[str] = None) -> Optional[dict]:
    """Read a calibration sidecar (the default content-addressed one when
    ``path`` is None); None when absent/unreadable."""
    path = path or calibration_path()
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            cal = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        log_warn(f"calibration sidecar unreadable ({path}): {e!r}")
        return None
    if not all(k in cal for k in RATE_FIELDS):
        log_warn(f"calibration sidecar {path} missing rate fields; ignored")
        return None
    return cal


def resolve_calibration(path: Optional[str] = None,
                        backend: Optional[str] = None) -> dict:
    """Explicit path > saved measured sidecar > documented defaults.  An
    explicit path that is missing or invalid raises — a user who pointed
    at a calibration must never get a silently re-priced report."""
    if path:
        cal = load_calibration(path)
        if cal is None:
            raise FileNotFoundError(
                f"calibration file {path} is missing or carries no rate "
                "fields (expected a tools/gather_bound.py JSON)")
        return cal
    cal = load_calibration()
    return cal if cal is not None else default_calibration(backend)


# ---------------------------------------------------------------------------
# the model


def phase_bounds_ms(phases: Dict[str, dict], cal: dict) -> Dict[str, float]:
    """Bound time (ms) per phase at the calibrated rates:

    * ``plan_h2d``   bytes / h2d_bytes_per_s
    * ``compute``    gathers / gather_rows_per_s + flops / flops_per_s
      (same formula for the hybrid split pair ``compute_decode`` /
      ``compute_recompute`` — the decode side carries gathers, the
      recompute side orbit-scan flops, so each prices at its own
      resource)
    * ``exchange``   bytes / exchange_bytes_per_s
    * ``accumulate`` SCATTER_WEIGHT · gathers / gather_rows_per_s

    Phases with no structural counts bound at 0 (``overhead`` always)."""
    g = float(cal["gather_rows_per_s"])
    h = float(cal["h2d_bytes_per_s"])
    x = float(cal["exchange_bytes_per_s"])
    fl = float(cal["flops_per_s"])
    out = {}
    for p, c in phases.items():
        by = float(c.get("bytes", 0))
        ga = float(c.get("gathers", 0))
        f = float(c.get("flops", 0))
        if p == "plan_h2d":
            t = by / h
        elif p in ("compute", "compute_decode", "compute_recompute"):
            t = ga / g + f / fl
        elif p == "exchange":
            t = by / x
        elif p == "accumulate":
            t = SCATTER_WEIGHT * ga / g
        else:
            t = 0.0
        out[p] = t * 1e3
    return out


def attribute_phases(phases: Dict[str, dict], wall_ms: float,
                     cal: dict) -> Dict[str, dict]:
    """Split one apply's measured wall across phases.

    Measured phase walls (streamed ``plan_h2d``'s H2D waits) are taken as
    recorded; the remaining wall is distributed over the model-bounded
    phases in proportion to their bounds (so a phase's achieved-vs-bound
    fraction is bound/attributed — the same number for every attributed
    phase, which is the honest statement a host-side-only decomposition can
    make); with no bounded phases the remainder lands in ``overhead``.  The
    attributed walls sum to ``wall_ms`` exactly by construction."""
    bounds = phase_bounds_ms(phases, cal)
    measured = {p: float(c["wall_ms"]) for p, c in phases.items()
                if c.get("wall_ms") is not None}
    remaining = max(wall_ms - sum(measured.values()), 0.0)
    bounded = {p: b for p, b in bounds.items()
               if b > 0 and p not in measured}
    total_bound = sum(bounded.values())
    out = {}
    for p in PHASES:
        if p != "overhead" and p not in phases:
            continue
        c = phases.get(p, {})
        if p in measured:
            w = measured[p]
        elif p in bounded and total_bound > 0:
            w = remaining * bounded[p] / total_bound
        elif p == "overhead":
            w = remaining if total_bound <= 0 else 0.0
        else:
            w = 0.0
        b = bounds.get(p, 0.0)
        out[p] = {"wall_ms": w, "bound_ms": b,
                  "achieved_fraction": (b / w) if w > 0 else None,
                  "bytes": int(c.get("bytes", 0)),
                  "gathers": int(c.get("gathers", 0)),
                  "flops": int(c.get("flops", 0)),
                  "measured": p in measured}
    return out


#: ``pipeline="auto"`` arms only when the priced overlappable time (the
#: exchange/compute overlap plus the whole hideable plan stream) is at
#: least this share of the apply's total bound — below it the pipeline's
#: bookkeeping (split programs, prefetch workers) cannot pay for itself
#: (measured ~7% schedule overhead on a latency-free 8-chunk CPU
#: stream, BENCH_PIPELINE_r10.json).
AUTO_PIPELINE_MIN_FRACTION = 0.10

#: Depth ``auto`` picks when the plan stream (``plan_h2d``) carries a
#: meaningful share of the hideable time: staging latency hides best with
#: several uploads in flight.  A pure compute/exchange overlap needs only
#: the classic double buffer (depth 2).
AUTO_PIPELINE_DEEP = 4


def choose_pipeline_depth(counts: Dict[str, dict], cal: dict,
                          nchunks: int, n_devices: int) -> int:
    """The ``pipeline="auto"`` policy — price the overlap before building
    it (the same §22 cost model the pipelined-apply estimate uses) and
    return a depth:

    * 0 (off) when there is nothing to pipeline — a single-chunk apply,
      or a priced overlappable time (``min(compute, exchange)·(1−1/n)``
      plus the hideable ``plan_h2d`` stream) below
      :data:`AUTO_PIPELINE_MIN_FRACTION` of the total bound;
    * :data:`AUTO_PIPELINE_DEEP` when the plan stream dominates the
      hideable time (staging latency wants several uploads in flight);
    * 2 (the classic double buffer) otherwise.

    The depth is clamped to ``nchunks`` by the caller-facing contract
    (more slots than chunks buy nothing)."""
    if nchunks < 2:
        return 0
    bounds = phase_bounds_ms(counts, cal)
    total = sum(bounds.values())
    if total <= 0:
        return 0
    # hybrid mode splits compute into decode/recompute phases — the
    # overlappable compute is their sum
    comp = (bounds.get("compute", 0.0)
            + bounds.get("compute_decode", 0.0)
            + bounds.get("compute_recompute", 0.0))
    exch = bounds.get("exchange", 0.0) if n_devices > 1 else 0.0
    h2d = bounds.get("plan_h2d", 0.0)
    hideable = min(comp, exch) * (1.0 - 1.0 / nchunks) + h2d
    if hideable / total < AUTO_PIPELINE_MIN_FRACTION:
        return 0
    depth = AUTO_PIPELINE_DEEP if h2d >= 0.5 * hideable else 2
    return min(depth, nchunks)


def price_term_split(live_per_term, rows: int, group_order: int,
                     cal: dict, bytes_per_live_entry: float,
                     cplx: bool = False) -> dict:
    """Per-term recompute-vs-stream pricing — the hybrid mode's cost
    model (DESIGN.md §28), shared verbatim by the engine's ``auto``
    split, ``tools/capacity.py``'s ``--hybrid`` table, and the tests, so
    all three answer the same question from the same rates.

    Per term ``t`` (all times in ms, per apply, across all ``rows``
    padded basis rows):

    * **stream**: the term's plan slice travels H2D and decodes —
      ``live[t] · (bytes_per_live_entry / h2d + 1/gather + fmul/flops)``
      (each live entry is streamed bytes, one ``x[row]`` gather, and the
      multiply);
    * **recompute**: the term's structure is re-derived on device —
      ``rows · ((G·ORBIT_OPS + fmul) / flops)`` (the orbit scan runs on
      every row whether or not the term fires there; the send side is a
      row-major broadcast, no gather).

    ``live_per_term`` is the global live-entry census ([T] ints, summed
    over chunks/shards/ranks); ``rows`` the matching global padded row
    total (each term is scanned once per row).  Returns ``{stream_ms,
    recompute_ms, stream_mask}`` — ``stream_mask[t]`` True when
    streaming term ``t`` prices cheaper or equal."""
    from .phases import ORBIT_OPS

    live = np.asarray(live_per_term, np.float64).reshape(-1)
    g = float(cal["gather_rows_per_s"])
    h = float(cal["h2d_bytes_per_s"])
    fl = float(cal["flops_per_s"])
    fmul = 8.0 if cplx else 2.0
    per_entry_s = bytes_per_live_entry / h + 1.0 / g + fmul / fl
    stream_ms = live * per_entry_s * 1e3
    recompute_ms = np.full(
        live.shape,
        float(rows) * (max(int(group_order), 1) * ORBIT_OPS + fmul)
        / fl * 1e3)
    return {"stream_ms": stream_ms, "recompute_ms": recompute_ms,
            "stream_mask": stream_ms <= recompute_ms}


def choose_hybrid_split(live_per_term, rows: int, group_order: int,
                        cal: dict, bytes_per_live_entry: float,
                        cplx: bool = False) -> np.ndarray:
    """The ``hybrid="auto"`` policy: stream exactly the terms whose plan
    slice prices cheaper than re-deriving their structure on device
    (:func:`price_term_split`).  Deterministic in (census, rates), so
    every rank of a multi-controller job — and a later warm restore under
    the same fingerprint — resolves the identical mask."""
    return np.asarray(
        price_term_split(live_per_term, rows, group_order, cal,
                         bytes_per_live_entry, cplx)["stream_mask"], bool)


def _mean(vals: List[float]) -> float:
    return sum(vals) / len(vals) if vals else 0.0


def _price_hlo_phase(phase: str, byts: float, flops: float,
                     cal: dict) -> float:
    """Seconds the calibrated rates would charge one HLO phase bucket:
    exchange moves bytes over the interconnect, compute burns flops
    (falling back to movement when the bucket attributed none), and
    everything else stages bytes at the H2D rate.  Only the CROSS-phase
    ratios matter — :func:`hlo_phase_split` renormalizes to the
    measured wall."""
    h = float(cal.get("h2d_bytes_per_s") or 0.0) or 1e9
    x = float(cal.get("exchange_bytes_per_s") or 0.0) or h
    f = float(cal.get("flops_per_s") or 0.0) or 1e9
    if phase == "exchange":
        return byts / x
    if phase.startswith("compute"):
        return flops / f if flops > 0 else byts / h
    return byts / h


def hlo_phase_split(event: dict, group_phases: Sequence[str],
                    wall_ms: float, cal: dict) -> Dict[str, float]:
    """The third roofline column: split the measured apply wall by the
    compiled executable's HLO cost table (``hlo_cost`` event).  Each
    ``phase_bytes_*``/``phase_flops_*`` bucket is priced at the
    calibrated rates, buckets missing from the measured group fold into
    its compute phase, and the priced shares are normalized so
    Σ ``hlo_ms`` ≡ the measured wall — the *signal* is the per-phase
    split, reconciled by construction."""
    priced: Dict[str, float] = {}
    for k, v in event.items():
        if not k.startswith("phase_bytes_"):
            continue
        ph = k[len("phase_bytes_"):]
        byts = float(v or 0.0)
        flops = float(event.get(f"phase_flops_{ph}") or 0.0)
        target = ph if ph in group_phases else (
            "compute" if "compute" in group_phases else None)
        if target is None:
            continue
        priced[target] = (priced.get(target, 0.0)
                          + _price_hlo_phase(ph, byts, flops, cal))
    total = sum(priced.values())
    if total <= 0.0 or wall_ms <= 0.0:
        return {}
    return {p: wall_ms * s / total for p, s in priced.items()}


def roofline_report(events: List[dict],
                    calibration: Optional[dict] = None) -> dict:
    """The full roofline report for one run: per (engine, mode) group the
    mean steady apply (the first apply per group is dropped as the
    compile/warm-up one whenever ≥2 were recorded), phase attribution,
    binding resource, and the pipelined-apply speedup estimate."""
    cal = calibration or resolve_calibration()
    groups: Dict[tuple, List[dict]] = {}
    for ev in events:
        if ev.get("kind") == "apply_phases" and ev.get("phases"):
            # pipelined applies form their OWN group per depth: a run that
            # records sequential AND pipelined applies of one (engine,
            # mode) reports them side by side — that comparison IS the
            # measured-vs-priced overlap story below
            depth = int((ev.get("pipeline") or {}).get("depth") or 0)
            groups.setdefault(
                (str(ev.get("engine")), str(ev.get("mode")), depth),
                []).append(ev)
    out = {"calibration": {k: cal.get(k) for k in
                           RATE_FIELDS + ("backend", "device_kind",
                                          "source")},
           "groups": {}}
    for (engine, mode, depth), evs in sorted(groups.items()):
        steady = evs[1:] if len(evs) > 1 else evs
        wall = _mean([float(e.get("wall_ms") or 0.0) for e in steady])
        nchunks = max(int(steady[-1].get("chunks") or 1), 1)
        # mean structural counts + mean measured phase walls over the
        # steady applies (counts are constant per (mode, columns); the
        # mean keeps mixed-column runs honest)
        phase_names = sorted({p for e in steady for p in e["phases"]})
        agg: Dict[str, dict] = {}
        for p in phase_names:
            recs = [e["phases"].get(p) or {} for e in steady]
            walls = [float(r["wall_ms"]) for r in recs
                     if r.get("wall_ms") is not None]
            agg[p] = {"bytes": int(_mean([r.get("bytes", 0) for r in recs])),
                      "gathers": int(_mean([r.get("gathers", 0)
                                            for r in recs])),
                      "flops": int(_mean([r.get("flops", 0) for r in recs])),
                      "wall_ms": _mean(walls) if walls else None}
        attributed = attribute_phases(agg, wall, cal)
        bound_total = sum(a["bound_ms"] for a in attributed.values())
        binding = max(attributed,
                      key=lambda p: attributed[p]["bound_ms"]) \
            if bound_total > 0 else "overhead"
        comp = sum(attributed.get(p, {}).get("wall_ms", 0.0)
                   for p in ("compute", "compute_decode",
                             "compute_recompute"))
        exch = attributed.get("exchange", {}).get("wall_ms", 0.0)
        overlap = min(comp, exch) * (1.0 - 1.0 / nchunks) \
            if nchunks > 1 else 0.0
        pipelined = max(wall - overlap, 1e-9)
        stalls = [c.get("stall_ms") for e in steady
                  for c in (e.get("chunk_timeline") or [])
                  if c.get("stall_ms") is not None]
        grp = {
            "applies": len(evs),
            "steady_applies": len(steady),
            "wall_ms": round(wall, 4),
            "chunks": nchunks,
            "phases": {p: {k: (round(v, 4) if isinstance(v, float) else v)
                           for k, v in a.items()}
                       for p, a in attributed.items()},
            "binding_phase": binding,
            "binding_resource": PHASE_RESOURCE.get(binding, binding),
            "roofline_fraction": round(bound_total / wall, 4)
            if wall > 0 else None,
            "pipelined_speedup_estimate": round(wall / pipelined, 3),
            "pipelined_overlap_ms": round(overlap, 4),
        }
        if stalls:
            grp["mean_chunk_stall_ms"] = round(_mean(stalls), 4)
        if depth:
            pipes = [e.get("pipeline") or {} for e in steady]
            grp["pipeline_depth"] = depth
            # only MEASURED values aggregate: a fused pipeline records
            # depth alone (no host-driven chunk loop), and an absent
            # measurement must not render as a perfect 0-ms barrier
            for k in ("barrier_ms", "hidden_ms", "overlap_fraction"):
                vals = [float(p[k]) for p in pipes
                        if p.get(k) is not None]
                if vals:
                    grp[k] = round(_mean(vals), 4)
        key = f"{engine}/{mode}" + (f"+pipe{depth}" if depth else "")
        out["groups"][key] = grp
    # measured-vs-priced: when a run holds BOTH the sequential and a
    # pipelined group of one (engine, mode), put the PR-7 estimate (priced
    # off the sequential phases) next to the measured pipelined wall, and
    # flag a pipeline whose measured overlap fell below half its estimate
    # (only when the estimate is worth chasing — a CPU-rig run whose
    # priced overlap is ~0 must not cry wolf)
    for key, grp in out["groups"].items():
        if "+pipe" not in key:
            continue
        base = out["groups"].get(key.split("+pipe", 1)[0])
        if not base or not base.get("wall_ms"):
            continue
        wall_b, wall_p = float(base["wall_ms"]), float(grp["wall_ms"])
        priced_overlap = float(base["pipelined_overlap_ms"])
        measured_overlap = max(wall_b - wall_p, 0.0)
        grp["measured_speedup"] = round(wall_b / max(wall_p, 1e-9), 3)
        grp["priced_speedup"] = base["pipelined_speedup_estimate"]
        grp["measured_overlap_ms"] = round(measured_overlap, 4)
        grp["priced_overlap_ms"] = round(priced_overlap, 4)
        grp["overlap_below_estimate"] = bool(
            priced_overlap >= 0.02 * wall_b
            and measured_overlap < 0.5 * priced_overlap)
    # autotuner rows (DESIGN.md §30): the chosen configs and any
    # drift-triggered re-tunes this run recorded, plus a per-group
    # priced-vs-tuned-vs-measured triple — "priced" is the calibrated
    # bound of the structural counts (roofline_fraction's numerator),
    # "tuned" the search's pre-build estimate for the adopted config,
    # "measured" the steady apply wall
    tune_cfgs = [e for e in events if e.get("kind") == "tune_config"]
    retunes = [e for e in events if e.get("kind") == "retune"]
    if tune_cfgs or retunes:
        out["tuning"] = {
            "configs": [{k: e.get(k) for k in
                         ("engine", "mode", "token", "priced_ms",
                          "source", "search_s")} for e in tune_cfgs],
            "retunes": [{k: e.get(k) for k in
                         ("engine", "mode", "apply", "old_token",
                          "new_token", "ratio", "priced_ms",
                          "rebuild_s")} for e in retunes],
        }
        for key, grp in out["groups"].items():
            eng_mode = key.split("+pipe", 1)[0]
            match = [e for e in tune_cfgs
                     if f"{e.get('engine')}/{e.get('mode')}" == eng_mode]
            if match:
                grp["tuned_token"] = str(match[-1].get("token"))
                grp["tuned_priced_ms"] = float(
                    match[-1].get("priced_ms") or 0.0)
    # HLO third column (ISSUE 19): every compiled apply left one
    # `hlo_cost` event; match it to its group by the program name the
    # compile path uses (f"{engine}_{mode}_apply") and split the
    # measured wall by the HLO cost table so each phase row shows
    # priced-vs-HLO-vs-measured side by side
    hlo_by_program: Dict[str, dict] = {}
    for ev in events:
        if ev.get("kind") == "hlo_cost":
            hlo_by_program[str(ev.get("program"))] = ev   # newest wins
    if hlo_by_program:
        for key, grp in out["groups"].items():
            engine, _, mode = key.split("+pipe", 1)[0].partition("/")
            ev = hlo_by_program.get(f"{engine}_{mode}_apply")
            if ev is None:
                continue
            split = hlo_phase_split(ev, tuple(grp["phases"]),
                                    float(grp["wall_ms"]), cal)
            if not split:
                continue
            for p, v in split.items():
                grp["phases"][p]["hlo_ms"] = round(v, 4)
            grp["hlo"] = {
                "program": str(ev.get("program")),
                "fingerprint": str(ev.get("fingerprint", ""))[:16],
                "flops": float(ev.get("flops") or 0.0),
                "bytes": float(ev.get("bytes") or 0.0),
                "n_ops": int(ev.get("n_ops") or 0),
                "artifact": str(ev.get("artifact") or ""),
            }
    return out


def reconcile_error(report: dict) -> float:
    """Max relative |Σ phase walls − measured wall| / wall over the
    report's groups — the reconciliation the roofline-check gate asserts
    stays within tolerance (≈0 by construction; a drift means the
    attribution broke)."""
    worst = 0.0
    for grp in report.get("groups", {}).values():
        wall = float(grp.get("wall_ms") or 0.0)
        if wall <= 0:
            continue
        s = sum(float(a.get("wall_ms") or 0.0)
                for a in grp.get("phases", {}).values())
        worst = max(worst, abs(s - wall) / wall)
    return worst


def print_roofline(report: dict) -> None:
    cal = report.get("calibration", {})
    print(f"calibration: {cal.get('source')} "
          f"(backend={cal.get('backend')}"
          + (f", {cal.get('device_kind')}" if cal.get("device_kind")
             else "") + ")")
    print("  " + "  ".join(f"{k}={cal.get(k):.3g}" for k in RATE_FIELDS
                           if cal.get(k)))
    for name, grp in sorted(report.get("groups", {}).items()):
        print(f"\n{name}: {grp['steady_applies']} steady applies, "
              f"wall {grp['wall_ms']:.3f} ms/apply, "
              f"{grp['chunks']} chunk(s)")
        # third column only when this run captured HLO cost profiles —
        # reports from older runs render byte-identically
        has_hlo = any(a.get("hlo_ms") is not None
                      for a in grp["phases"].values())
        print(f"  {'phase':<12} {'wall ms':>10} {'bound ms':>10} "
              + (f"{'hlo ms':>10} " if has_hlo else "")
              + f"{'achieved':>9} {'bytes':>14} {'gathers':>12}")
        for p in PHASES:
            a = grp["phases"].get(p)
            if a is None:
                continue
            ach = a.get("achieved_fraction")
            if ach is None:
                cell = "-"
            elif a.get("measured") and ach > 1.0:
                # a measured wall BELOW the un-overlapped bound: the phase
                # is hidden behind other work (the double-buffered plan
                # stream doing its job) — a fraction > 1 would misread
                cell = "hidden"
            else:
                cell = f"{ach:.1%}"
            hlo_cell = ""
            if has_hlo:
                hv = a.get("hlo_ms")
                hlo_cell = (f"{hv:>10.4f} " if hv is not None
                            else f"{'-':>10} ")
            print(f"  {p:<12} {a['wall_ms']:>10.4f} {a['bound_ms']:>10.4f} "
                  + hlo_cell
                  + f"{cell:>9} "
                  f"{a['bytes']:>14,} {a['gathers']:>12,}"
                  + ("  (measured)" if a.get("measured") else ""))
        if grp.get("hlo"):
            h = grp["hlo"]
            print(f"  hlo: {h['program']} [{h['fingerprint']}] "
                  f"{h['n_ops']} ops, {h['flops']:.3g} flops, "
                  f"{h['bytes']:.3g} bytes accessed")
        frac = grp.get("roofline_fraction")
        print(f"  binding resource: {grp['binding_resource']} "
              f"(phase {grp['binding_phase']}"
              + (f", run at {frac:.1%} of the combined roofline)"
                 if frac is not None else ")"))
        if grp.get("tuned_priced_ms") is not None:
            bound = sum(a["bound_ms"] for a in grp["phases"].values())
            print(f"  priced vs tuned vs measured: bound {bound:.4f} ms | "
                  f"tuned {grp['tuned_priced_ms']:.4f} ms "
                  f"[{grp['tuned_token']}] | measured "
                  f"{grp['wall_ms']:.4f} ms")
        if grp.get("mean_chunk_stall_ms") is not None:
            print(f"  mean plan-stream chunk stall: "
                  f"{grp['mean_chunk_stall_ms']:.4f} ms")
        if grp.get("pipeline_depth"):
            frac = grp.get("overlap_fraction")
            if grp.get("barrier_ms") is not None:
                print(f"  pipeline depth {grp['pipeline_depth']}: "
                      f"time-at-barrier {grp['barrier_ms']:.4f} ms/apply, "
                      f"{grp.get('hidden_ms', 0.0):.4f} ms staged behind "
                      "compute"
                      + (f" ({frac:.0%} of the staging latency hidden)"
                         if frac is not None else ""))
            else:
                print(f"  pipeline depth {grp['pipeline_depth']} "
                      "(in-program schedule — no host-measured barrier "
                      "split)")
            if grp.get("measured_speedup") is not None:
                print(f"  measured vs priced: {grp['measured_speedup']:.2f}x"
                      f" measured ({grp['measured_overlap_ms']:.3f} ms "
                      f"overlapped) vs {grp['priced_speedup']:.2f}x priced "
                      f"({grp['priced_overlap_ms']:.3f} ms)")
                if grp.get("overlap_below_estimate"):
                    print("  WARNING: measured overlap fell below 50% of "
                          "the roofline estimate — the pipeline is not "
                          "hiding what the model priced (check depth, "
                          "chunk count, and the calibration)")
        else:
            print(f"  pipelined-apply estimate: overlap exchange with chunk "
                  f"compute saves {grp['pipelined_overlap_ms']:.3f} ms "
                  f"-> {grp['pipelined_speedup_estimate']:.2f}x")
    tuning = report.get("tuning")
    if tuning:
        print("\ntuning:")
        for c in tuning.get("configs", []):
            print(f"  {c['engine']}/{c['mode']}: {c['token']} "
                  f"priced {float(c['priced_ms'] or 0.0):.4f} ms "
                  f"[{c['source']}]"
                  + (f" (search {float(c['search_s']):.2f} s)"
                     if c.get("search_s") else ""))
        for r in tuning.get("retunes", []):
            print(f"  retune {r['engine']}/{r['mode']} @ apply "
                  f"{r['apply']}: {r['old_token']} -> {r['new_token']} "
                  f"(measured/priced {float(r['ratio']):.2f}x, rebuilt in "
                  f"{float(r['rebuild_s']):.2f} s)")
