"""HLO-level cost attribution for compiled apply executables.

Every engine mode is one static program per config (the GSPMD premise),
so the optimized HLO of a compiled executable — together with XLA's own
``cost_analysis()`` totals — is a *stable, content-addressable*
description of the apply.  This module captures that description once
per compile:

* :func:`parse_hlo_ops` reads the optimized HLO text and lists every
  instruction with its opcode, output-shape bytes, and the ``op_name``
  metadata the tracer attached.
* :func:`classify_op` buckets each instruction into the §22 phase
  taxonomy (``plan_h2d`` / ``compute`` / ``exchange`` / ``accumulate``
  / ``overhead``) keyed on opcode first and ``op_name`` substrings for
  refinement — the same names the engines annotate via TraceAnnotation.
* :func:`attribute_costs` distributes the executable's whole-program
  ``cost_analysis()`` totals (flops / bytes accessed) over the parsed
  ops so per-op and per-phase costs *sum exactly* to the program
  totals (the largest op absorbs rounding).
* :func:`diff_profiles` compares two profile artifacts op-by-op with
  the same direction-aware gate semantics as ``obs_report diff`` —
  every HLO cost is cost-like, growth is a regression.

Import-dual like ``obs/slo.py``: inside the package,
:func:`record_executable_costs` also emits an ``hlo_cost`` event and
writes a content-addressed artifact (``hlo-profile/<fp2>/<fp>.json``)
next to the XLA cache; loaded standalone by file (``tools/obs_report.py
profile`` and ``tools/profile_diff.py``, which must never import jax)
only the pure parse/attribute/diff surface exists and capture is inert.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
from typing import Dict, List, Optional, Sequence, Tuple

try:                                    # package mode
    from ..utils.logging import log_debug as _log_debug
    from .events import emit as _emit
    from .events import obs_enabled as _obs_enabled
    from .metrics import counter as _counter
    _STANDALONE = False
except ImportError:                     # file-loaded by tools/*
    _STANDALONE = True

    def _obs_enabled():
        return False

    def _emit(kind, **fields):
        return None

    def _log_debug(msg):
        return None

    def _counter(name, **labels):
        raise RuntimeError("no metrics registry in standalone mode")

__all__ = [
    "PHASE_OPCODES",
    "classify_op",
    "parse_hlo_ops",
    "attribute_costs",
    "profile_fingerprint",
    "build_profile",
    "load_profile",
    "hottest_ops",
    "diff_profiles",
    "print_profile",
    "print_profile_diff",
    "record_executable_costs",
    "executable_costs",
    "reset_hlo",
]

#: Artifact schema version (bump on layout change, never reuse).
PROFILE_VERSION = 1

#: How many per-op rows ride on the ``hlo_cost`` event itself (the full
#: table lives in the artifact; the event stays ring-buffer friendly).
EVENT_TOP_OPS = 8

# ---------------------------------------------------------------------------
# phase classification

#: opcode → phase.  Collectives are exchange; scatter-shaped writes are
#: accumulate; host↔device staging is plan_h2d; free structural ops are
#: overhead; everything else (dot/gather/fusion/elementwise) is compute.
PHASE_OPCODES: Dict[str, str] = {
    "all-to-all": "exchange",
    "all-reduce": "exchange",
    "all-gather": "exchange",
    "all-reduce-start": "exchange",
    "all-reduce-done": "exchange",
    "collective-permute": "exchange",
    "collective-permute-start": "exchange",
    "collective-permute-done": "exchange",
    "reduce-scatter": "exchange",
    "send": "exchange",
    "recv": "exchange",
    "scatter": "accumulate",
    "select-and-scatter": "accumulate",
    "dynamic-update-slice": "accumulate",
    "parameter": "plan_h2d",
    "copy": "plan_h2d",
    "copy-start": "plan_h2d",
    "copy-done": "plan_h2d",
    "infeed": "plan_h2d",
    "outfeed": "plan_h2d",
    "tuple": "overhead",
    "get-tuple-element": "overhead",
    "bitcast": "overhead",
    "bitcast-convert": "overhead",
    "reshape": "overhead",
    "constant": "overhead",
    "iota": "overhead",
    "after-all": "overhead",
    "partition-id": "overhead",
    "replica-id": "overhead",
}

#: ``op_name`` metadata substrings that refine a compute-bucketed op —
#: fusions carry the traced jaxpr path, so a fused scatter-add still
#: lands in accumulate and a fused ppermute in exchange.
_OPNAME_PHASE: Tuple[Tuple[str, str], ...] = (
    ("ppermute", "exchange"),
    ("all_to_all", "exchange"),
    ("psum", "exchange"),
    ("all_gather", "exchange"),
    ("scatter-add", "accumulate"),
    ("scatter_add", "accumulate"),
    ("segment_sum", "accumulate"),
)

#: bytes per element for HLO shape dtypes (default 4 when unknown).
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

#: opcodes that can carry flops (flop totals are distributed over these,
#: weighted by output bytes; pure data movement never gets flops).
_FLOP_OPCODES = frozenset((
    "fusion", "dot", "convolution", "reduce", "reduce-window", "scatter",
    "select-and-scatter", "all-reduce", "reduce-scatter", "multiply",
    "add", "subtract", "divide", "exponential", "log", "rsqrt", "sqrt",
    "tanh", "power", "cholesky", "triangular-solve", "sort", "map",
))


def classify_op(opcode: str, op_name: str = "") -> str:
    """Phase bucket for one HLO instruction: opcode table first, then
    ``op_name`` metadata substrings refine compute-bucketed ops."""
    phase = PHASE_OPCODES.get(opcode, "compute")
    if phase == "compute" and op_name:
        low = op_name.lower()
        for sub, refined in _OPNAME_PHASE:
            if sub in low:
                return refined
    return phase


# ---------------------------------------------------------------------------
# HLO text parsing

_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*"
    r"(?P<shape>\([^=]*?\)|[\w\[\]{},\s/#*]+?)\s+"
    r"(?P<opcode>[\w\-]+)\(")

_OPNAME_RE = re.compile(r'op_name="([^"]*)"')

_SHAPE_RE = re.compile(r"(?P<dtype>[a-z]+\d*)\[(?P<dims>[\d,]*)\]")


def _shape_bytes(shape: str) -> int:
    """Total bytes of one HLO shape string (tuple shapes sum their
    leaves; token/opaque shapes count zero)."""
    total = 0
    for m in _SHAPE_RE.finditer(shape):
        nelem = 1
        dims = m.group("dims")
        if dims:
            for d in dims.split(","):
                nelem *= int(d)
        total += nelem * _DTYPE_BYTES.get(m.group("dtype"), 4)
    return total


def parse_hlo_ops(hlo_text: str) -> List[dict]:
    """Every instruction of the optimized HLO as
    ``{"name", "opcode", "phase", "shape_bytes", "op_name"}`` rows.
    Computation headers / braces / metadata-only lines are skipped."""
    ops: List[dict] = []
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if not m:
            continue
        opcode = m.group("opcode")
        nm = _OPNAME_RE.search(line)
        op_name = nm.group(1) if nm else ""
        ops.append({
            "name": m.group("name"),
            "opcode": opcode,
            "phase": classify_op(opcode, op_name),
            "shape_bytes": _shape_bytes(m.group("shape")),
            "op_name": op_name,
        })
    return ops


# ---------------------------------------------------------------------------
# cost attribution

def _distribute(total: float, weights: Sequence[float]) -> List[float]:
    """Split ``total`` proportionally to ``weights`` so the parts sum to
    ``total`` *exactly* — the largest-weight part absorbs the rounding
    remainder.  All-zero weights → uniform split."""
    n = len(weights)
    if n == 0 or total <= 0:
        return [0.0] * n
    wsum = float(sum(weights))
    if wsum <= 0:
        parts = [total / n] * n
    else:
        parts = [total * (w / wsum) for w in weights]
    # pin the exact sum on the largest part
    imax = max(range(n), key=lambda i: parts[i])
    parts[imax] += total - sum(parts)
    return parts


def attribute_costs(hlo_text: str, totals: Dict[str, float]) -> dict:
    """Distribute whole-program ``cost_analysis()`` totals over parsed
    ops.  Per-op weight is the output-shape byte count (the only
    structural size signal the HLO text carries); flops are spread over
    flop-capable opcodes only.  Per-op and per-phase sums equal the
    program totals exactly.  Returns ``{"ops": [...], "phases": {...},
    "totals": {...}}``."""
    ops = parse_hlo_ops(hlo_text)
    t_bytes = float(totals.get("bytes", 0.0))
    t_flops = float(totals.get("flops", 0.0))

    byte_w = [float(o["shape_bytes"]) for o in ops]
    op_bytes = _distribute(t_bytes, byte_w)
    flop_w = [float(o["shape_bytes"]) if o["opcode"] in _FLOP_OPCODES
              else 0.0 for o in ops]
    if not any(flop_w):                  # no flop-capable op parsed
        flop_w = byte_w
    op_flops = _distribute(t_flops, flop_w)

    out_ops: List[dict] = []
    phases: Dict[str, dict] = {}
    for o, b, fl in zip(ops, op_bytes, op_flops):
        row = {"name": o["name"], "opcode": o["opcode"],
               "phase": o["phase"], "bytes": b, "flops": fl}
        out_ops.append(row)
        ph = phases.setdefault(o["phase"],
                               {"bytes": 0.0, "flops": 0.0, "ops": 0})
        ph["bytes"] += b
        ph["flops"] += fl
        ph["ops"] += 1
    return {
        "ops": out_ops,
        "phases": phases,
        "totals": {"bytes": t_bytes, "flops": t_flops,
                   "transcendentals": float(
                       totals.get("transcendentals", 0.0))},
    }


def profile_fingerprint(hlo_text: str) -> str:
    """Content address of one compiled program: sha256 of its optimized
    HLO text.  A recompile that changes the program changes the
    fingerprint; an identical program re-lowered hits the same one."""
    return hashlib.sha256(hlo_text.encode()).hexdigest()


def build_profile(key: str, hlo_text: str, totals: Dict[str, float],
                  program: Optional[str] = None) -> dict:
    """Assemble the full content-addressed profile artifact dict."""
    attributed = attribute_costs(hlo_text, totals)
    return {
        "version": PROFILE_VERSION,
        "key": str(key),
        "program": str(program or key),
        "fingerprint": profile_fingerprint(hlo_text),
        "totals": attributed["totals"],
        "phases": attributed["phases"],
        "ops": attributed["ops"],
    }


def load_profile(path: str) -> dict:
    """Read one profile artifact from disk (raises on malformed files —
    callers are CLIs that want the traceback, not a None)."""
    with open(path) as f:
        prof = json.load(f)
    if not isinstance(prof, dict) or "ops" not in prof:
        raise ValueError(f"not an hlo profile artifact: {path}")
    return prof


def hottest_ops(profile: dict, top: int = 3) -> List[dict]:
    """The ``top`` most expensive ops by attributed bytes (the universal
    cost axis — flops are zero for movement-bound programs)."""
    ops = sorted(profile.get("ops", ()),
                 key=lambda o: (-float(o.get("bytes", 0.0)),
                                -float(o.get("flops", 0.0)),
                                o.get("name", "")))
    return ops[:max(int(top), 0)]


# ---------------------------------------------------------------------------
# differential profiling

def diff_profiles(base: dict, new: dict, threshold: float = 0.25,
                  top: int = 10) -> dict:
    """Op-by-op diff of two profile artifacts with ``obs_report diff``
    gate semantics: every HLO cost is cost-like, so growth beyond
    ``threshold`` (relative) is a regression.  Ops are matched by name
    first, falling back to ``opcode#ordinal`` so renamed-but-identical
    programs still align.  Returns ``{"rows", "regressions",
    "appeared", "vanished", "same_program"}``; rows/regressions are
    sorted worst-first and capped at ``top``."""
    def _index(prof):
        seen: Dict[str, int] = {}
        out = {}
        for o in prof.get("ops", ()):
            ordinal = seen.get(o["opcode"], 0)
            seen[o["opcode"]] = ordinal + 1
            out[o["name"]] = (o, f"{o['opcode']}#{ordinal}")
        return out

    bi, ni = _index(base), _index(new)
    b_alias = {alias: op for op, alias in bi.values()}
    matched: List[Tuple[dict, dict]] = []
    appeared: List[dict] = []
    used_aliases = set()
    for name, (op, alias) in ni.items():
        if name in bi:
            matched.append((bi[name][0], op))
            used_aliases.add(bi[name][1])
        elif alias in b_alias:
            matched.append((b_alias[alias], op))
            used_aliases.add(alias)
        else:
            appeared.append(op)
    vanished = [op for op, alias in bi.values()
                if alias not in used_aliases
                and op["name"] not in ni]

    rows: List[dict] = []
    for b_op, n_op in matched:
        for axis in ("bytes", "flops"):
            b_v = float(b_op.get(axis, 0.0))
            n_v = float(n_op.get(axis, 0.0))
            if b_v <= 0.0 and n_v <= 0.0:
                continue
            delta = n_v - b_v
            ratio = (n_v / b_v) if b_v > 0 else float("inf")
            rows.append({
                "name": n_op["name"], "opcode": n_op["opcode"],
                "phase": n_op.get("phase", "compute"), "axis": axis,
                "base": b_v, "new": n_v, "delta": delta, "ratio": ratio,
                "regressed": (delta > 0
                              and (b_v <= 0
                                   or delta / b_v > float(threshold))),
            })
    rows.sort(key=lambda r: (-(r["delta"] if r["delta"] > 0 else 0.0),
                             r["name"]))
    regressions = [r for r in rows if r["regressed"]]
    return {
        "rows": rows[:max(int(top), 1)],
        "regressions": regressions[:max(int(top), 1)],
        "appeared": appeared[:max(int(top), 1)],
        "vanished": vanished[:max(int(top), 1)],
        "same_program": (base.get("fingerprint")
                         == new.get("fingerprint")),
    }


# ---------------------------------------------------------------------------
# rendering (shared by obs_report profile and tools/profile_diff.py)

def _fmt_qty(v: float) -> str:
    for unit, div in (("G", 1e9), ("M", 1e6), ("k", 1e3)):
        if abs(v) >= div:
            return f"{v / div:.2f}{unit}"
    return f"{v:.0f}"


def print_profile(profile: dict, top: int = 10, out=None) -> None:
    """Human rendering of one profile artifact: identity line, phase
    table, hottest-op table."""
    import sys
    w = out or sys.stdout
    w.write(f"program   {profile.get('program', '?')}\n")
    w.write(f"key       {profile.get('key', '?')}\n")
    w.write(f"artifact  {profile.get('fingerprint', '?')[:16]}\n")
    t = profile.get("totals", {})
    w.write(f"totals    flops={_fmt_qty(t.get('flops', 0.0))}  "
            f"bytes={_fmt_qty(t.get('bytes', 0.0))}\n")
    w.write(f"{'phase':<20}{'bytes':>12}{'flops':>12}{'ops':>6}\n")
    for ph in sorted(profile.get("phases", {})):
        row = profile["phases"][ph]
        w.write(f"{ph:<20}{_fmt_qty(row['bytes']):>12}"
                f"{_fmt_qty(row['flops']):>12}{row['ops']:>6}\n")
    w.write(f"hottest ops (top {top}):\n")
    w.write(f"  {'op':<32}{'opcode':<22}{'phase':<14}"
            f"{'bytes':>10}{'flops':>10}\n")
    for o in hottest_ops(profile, top):
        w.write(f"  {o['name'][:31]:<32}{o['opcode'][:21]:<22}"
                f"{o['phase']:<14}{_fmt_qty(o['bytes']):>10}"
                f"{_fmt_qty(o['flops']):>10}\n")


def print_profile_diff(diff: dict, out=None) -> None:
    """Human rendering of a :func:`diff_profiles` result."""
    import sys
    w = out or sys.stdout
    if diff.get("same_program"):
        w.write("programs are byte-identical (same fingerprint)\n")
    n_reg = len(diff.get("regressions", ()))
    w.write(f"{len(diff.get('rows', ()))} changed op-axes, "
            f"{n_reg} regressed, {len(diff.get('appeared', ()))} new, "
            f"{len(diff.get('vanished', ()))} gone\n")
    if diff.get("rows"):
        w.write(f"  {'op':<32}{'axis':<7}{'base':>10}{'new':>10}"
                f"{'ratio':>8}  flag\n")
        for r in diff["rows"]:
            flag = "REGRESSED" if r["regressed"] else ""
            ratio = ("inf" if r["ratio"] == float("inf")
                     else f"{r['ratio']:.2f}x")
            w.write(f"  {r['name'][:31]:<32}{r['axis']:<7}"
                    f"{_fmt_qty(r['base']):>10}{_fmt_qty(r['new']):>10}"
                    f"{ratio:>8}  {flag}\n")
    for label, ops in (("new ops", diff.get("appeared", ())),
                       ("vanished ops", diff.get("vanished", ()))):
        for o in ops:
            w.write(f"  {label}: {o['name']} ({o['opcode']}, "
                    f"{_fmt_qty(float(o.get('bytes', 0.0)))}B)\n")


# ---------------------------------------------------------------------------
# package-mode capture (inert standalone)

_lock = threading.Lock()
_profiles: Dict[str, dict] = {}


def _cost_totals(compiled) -> Optional[Dict[str, float]]:
    """Normalize ``compiled.cost_analysis()`` — some backends return a
    list with one dict per computation, some a bare dict."""
    try:
        ca = compiled.cost_analysis()
    except Exception as e:
        _log_debug(f"cost_analysis unavailable: {e!r}")
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    return {
        "flops": float(ca.get("flops", 0.0) or 0.0),
        "bytes": float(ca.get("bytes accessed", 0.0) or 0.0),
        "transcendentals": float(ca.get("transcendentals", 0.0) or 0.0),
    }


def record_executable_costs(key: str, compiled,
                            program: Optional[str] = None,
                            **fields) -> Optional[dict]:
    """Capture the HLO cost profile of one freshly compiled executable:
    parse its optimized HLO, attribute ``cost_analysis()`` totals over
    ops and phases, store the profile in the process registry, emit an
    ``hlo_cost`` event (totals + phase split + top ops + artifact
    path), and persist the content-addressed artifact next to the XLA
    cache.  Soft-fail throughout; returns the profile dict or None."""
    if _STANDALONE or not _obs_enabled():
        return None
    totals = _cost_totals(compiled)
    if totals is None:
        return None
    try:
        hlo_text = compiled.as_text()
    except Exception as e:
        _log_debug(f"hlo text unavailable for {key}: {e!r}")
        return None
    try:
        prof = build_profile(key, hlo_text, totals, program=program)
    except Exception as e:
        _log_debug(f"hlo attribution failed for {key}: {e!r}")
        return None
    path = _save_profile_artifact(prof)
    if path:
        prof["artifact"] = path
    with _lock:
        _profiles[str(key)] = prof
    _counter("hlo_profile_count",
             program=prof["program"]).inc()
    phase_bytes = {f"phase_bytes_{ph}": row["bytes"]
                   for ph, row in prof["phases"].items()}
    phase_flops = {f"phase_flops_{ph}": row["flops"]
                   for ph, row in prof["phases"].items()}
    _emit("hlo_cost",
          key=prof["key"], program=prof["program"],
          fingerprint=prof["fingerprint"],
          artifact=prof.get("artifact", ""),
          flops=prof["totals"]["flops"],
          bytes=prof["totals"]["bytes"],
          transcendentals=prof["totals"]["transcendentals"],
          n_ops=len(prof["ops"]),
          top_ops=hottest_ops(prof, EVENT_TOP_OPS),
          **phase_bytes, **phase_flops, **fields)
    return prof


def _save_profile_artifact(prof: dict) -> Optional[str]:
    """Write the content-addressed artifact
    (``hlo-profile/<fp2>/<fp>.json``); soft-fail like every cache
    write.  Re-capturing an unchanged program is a cache hit: same
    fingerprint, same path, file simply rewritten with identical
    bytes."""
    from ..utils.artifacts import artifact_path, artifacts_enabled

    if not artifacts_enabled():
        return None
    try:
        path = artifact_path("hlo-profile", prof["fingerprint"], ".json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(prof, f, sort_keys=True)
        os.replace(tmp, path)
        return path
    except OSError as e:
        _log_debug(f"hlo-profile artifact save skipped: {e!r}")
        return None


def executable_costs() -> Dict[str, dict]:
    """Snapshot of every captured HLO cost profile, keyed by program
    specialization key."""
    with _lock:
        return {k: dict(v) for k, v in _profiles.items()}


def reset_hlo() -> None:
    """Drop all captured profiles (test isolation)."""
    with _lock:
        _profiles.clear()
