"""Metric direction registry — the ONE place that says which way is up.

Every gate in the repo (``obs_report diff``, ``bench_trend gate``, the
check scripts that wrap them) needs the same answer to the same
question: for metric X, is a LOWER new value the regression (rates,
speedups, throughputs) or a HIGHER one (walls, bytes, error bounds)?
Until the solve service each tool carried its own copy of that list;
this module is the shared table both import, so registering a new
metric's direction (e.g. ``serve_solves_per_min``: higher is better)
happens exactly once.

The rule is tag-based, not an exact-name whitelist: any metric whose
name contains one of :data:`HIGHER_IS_BETTER_TAGS` is higher-is-better,
everything else numeric is cost-like (growth is the regression) — which
is the DELIBERATE registration for error metrics like
``compress_rel_err``/``compress_drift_max``: numerical error growing is
the regression, so they gate correctly under the default rule — and for
the elastic-resume walls ``resume_reshard_s`` / ``resume_rebuild_plan_s``
(``make elastic-check``): time spent redistributing a checkpoint or
rebuilding a per-D′ plan on resume is a cost, so growth gates under the
default rule; register them here (by falling through) exactly once.

The hybrid-mode trio registers the same way (``make hybrid-check``,
DESIGN.md §28): ``hybrid_plan_bytes`` and ``hybrid_steady_apply_ms``
are cost-like — encoded partial-term plan bytes or the merged chunk
program's wall growing is the regression — and deliberately fall
through to the default; ``hybrid_stream_term_fraction`` rides the
trend as CONTEXT (which side of the priced split the terms landed on),
not a gated direction — neither growth nor shrinkage is a regression
per se, the priced split is whatever the rates make it.

The autotuner's metrics (``make tune-check``, DESIGN.md §30) register
the same way: ``autotuned_steady_apply_ms``, ``tune_search_s`` and
``best_hand_steady_apply_ms`` are cost-like — the tuned leg's wall, the
knob search's own cost, or the hand-set bar growing is the regression —
and deliberately fall through to the default;
``autotuned_steady_speedup`` carries the ``speedup`` tag, so shrinkage
gates as the regression under the existing rule.

The profiling plane's metrics (``make profile-check``, DESIGN.md §32)
register the same way: ``hlo_flops`` and ``hlo_bytes`` are the compiled
apply's whole-program cost-analysis totals — the program getting more
expensive is the regression — and ``profile_overhead_pct`` is the
measured cost of observing (trace start/stop over un-profiled apply
wall), a pure cost; all three deliberately fall through to the
cost-like default.
"""

from __future__ import annotations

__all__ = ["HIGHER_IS_BETTER_TAGS", "is_higher_better",
           "METRIC_HELP", "metric_meta"]

#: Substring tags marking rate-like metrics (higher is better).
#: ``solves_per_min`` covers the solve service's throughput
#: (``serve_solves_per_min``); latency percentiles
#: (``serve_p99_latency_ms``) fall through to the cost-like default.
HIGHER_IS_BETTER_TAGS = (
    "iters_per_s", "speedup", "_rate", "hit_rate",
    "compress_ratio", "overlap_fraction", "solves_per_min",
    # dynamics throughputs (DESIGN.md §29): Chebyshev moments and
    # accepted evolution steps per second — rates, so shrinkage is the
    # regression; the paired error metrics (kpm_dos_rel_err,
    # evolve_norm_drift, evolve_energy_drift) deliberately fall through
    # to the cost-like default (error growth is the regression)
    "moments_per_s", "steps_per_s",
)


def is_higher_better(metric: str) -> bool:
    """True when a LOWER value of ``metric`` is the regression."""
    return any(tag in metric for tag in HIGHER_IS_BETTER_TAGS)


#: Help strings for the exporter's ``# HELP`` lines, keyed by the BASE
#: instrument name (no labels).  This table rides next to the direction
#: tags deliberately: the OpenMetrics exporter (``obs/export.py``) and the
#: trend gates (``bench_trend``, ``obs_report diff``) read the SAME file,
#: so a metric's type, direction and meaning are registered exactly once
#: and the scrape plane can never drift from the gate plane.  A metric
#: absent here still exports (help falls back to the name) — the table is
#: documentation, not an allowlist.
METRIC_HELP = {
    "matvec_apply_ms": "Wall time of one eager matvec apply (ms)",
    "double_buffer_stall_ms": "Producer wait on a busy device buffer (ms)",
    "plan_stream_stall_ms": "Apply wait on plan-chunk staging (ms)",
    "bytes_h2d": "Host-to-device bytes copied",
    "bytes_d2h": "Device-to-host bytes copied",
    "exchange_bytes": "Cross-shard exchange payload bytes",
    "artifact_cache": "Artifact-cache events by kind/event label",
    "aot_executable_cache": "AOT executable cache hits/misses",
    "retrace_count": "Program retraces (shape/layout cache misses)",
    "engine_table_bytes": "Resident engine structure-table bytes",
    "ell_table_bytes": "Resident ELL structure-table bytes",
    "stream_plan_bytes": "Resolved streamed-plan bytes (RAM or disk tier)",
    "hbm_bytes_in_use": "Device memory in use at the last watermark poll",
    "hbm_peak_bytes": "Peak device memory over the process lifetime",
    "executable_temp_bytes": "Compiler-reported executable temp allocation",
    "oom_events": "OomError diagnoses attached to resource exhaustion",
    "compress_rel_err": "Measured streamed-plan decode relative error",
    "matvec_output_norm": "Norm of the last probed apply output",
    "matvec_nonfinite": "NaN/Inf elements counted by the health probes",
    "exchange_overflow": "Exchange-capacity overflow events",
    "exchange_invalid": "Invalid exchange-slot events",
    "health_events": "Numerical-health events by level",
    "fault_injected": "Injected faults fired (DMT_FAULT sites)",
    "io_retry": "Idempotent I/O reads retried",
    "engine_pool_bytes": "Serve-plane engine pool resident bytes",
    "engine_pool_max_bytes": "Serve-plane engine pool byte budget",
    "engine_pool_engines": "Warm engines resident in the serve pool",
    "job_queue_depth": "Solve-service jobs queued or running",
    "serve_batch_width": "Jobs packed into the in-flight solver batch",
    "slo_alert_count": "SLO burn-rate alerts fired (lifetime)",
    "flight_dump_count": "Flight-recorder post-mortem bundles written",
    "hlo_profile_count": "HLO cost profiles captured at compile time",
    "profile_capture_count": "Profiler trace captures by kind "
                             "(sampled/triggered/manual)",
    "profile_overhead_latch_count":
        "Sampled profiling latched off by the overhead guard",
    "hlo_flops": "Whole-program flops from the compiled apply's "
                 "HLO cost analysis",
    "hlo_bytes": "Whole-program bytes accessed from the compiled "
                 "apply's HLO cost analysis",
    "profile_overhead_pct": "Measured profiling overhead (trace "
                            "start/stop cost over un-profiled "
                            "apply wall, percent)",
}


def metric_meta(name: str) -> dict:
    """Everything the telemetry plane knows about base metric ``name``:
    ``{"help": str, "higher_is_better": bool}``.  The instrument TYPE
    (counter/gauge/histogram) is a property of the live registry, not of
    the name — the exporter takes it from the snapshot section the series
    appears in."""
    return {"help": METRIC_HELP.get(name, name.replace("_", " ")),
            "higher_is_better": is_higher_better(name)}
