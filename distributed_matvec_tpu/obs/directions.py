"""Metric direction registry — the ONE place that says which way is up.

Every gate in the repo (``obs_report diff``, ``bench_trend gate``, the
check scripts that wrap them) needs the same answer to the same
question: for metric X, is a LOWER new value the regression (rates,
speedups, throughputs) or a HIGHER one (walls, bytes, error bounds)?
Until the solve service each tool carried its own copy of that list;
this module is the shared table both import, so registering a new
metric's direction (e.g. ``serve_solves_per_min``: higher is better)
happens exactly once.

The rule is tag-based, not an exact-name whitelist: any metric whose
name contains one of :data:`HIGHER_IS_BETTER_TAGS` is higher-is-better,
everything else numeric is cost-like (growth is the regression) — which
is the DELIBERATE registration for error metrics like
``compress_rel_err``/``compress_drift_max``: numerical error growing is
the regression, so they gate correctly under the default rule — and for
the elastic-resume walls ``resume_reshard_s`` / ``resume_rebuild_plan_s``
(``make elastic-check``): time spent redistributing a checkpoint or
rebuilding a per-D′ plan on resume is a cost, so growth gates under the
default rule; register them here (by falling through) exactly once.

The hybrid-mode trio registers the same way (``make hybrid-check``,
DESIGN.md §28): ``hybrid_plan_bytes`` and ``hybrid_steady_apply_ms``
are cost-like — encoded partial-term plan bytes or the merged chunk
program's wall growing is the regression — and deliberately fall
through to the default; ``hybrid_stream_term_fraction`` rides the
trend as CONTEXT (which side of the priced split the terms landed on),
not a gated direction — neither growth nor shrinkage is a regression
per se, the priced split is whatever the rates make it.

The autotuner's metrics (``make tune-check``, DESIGN.md §30) register
the same way: ``autotuned_steady_apply_ms``, ``tune_search_s`` and
``best_hand_steady_apply_ms`` are cost-like — the tuned leg's wall, the
knob search's own cost, or the hand-set bar growing is the regression —
and deliberately fall through to the default;
``autotuned_steady_speedup`` carries the ``speedup`` tag, so shrinkage
gates as the regression under the existing rule.
"""

from __future__ import annotations

__all__ = ["HIGHER_IS_BETTER_TAGS", "is_higher_better"]

#: Substring tags marking rate-like metrics (higher is better).
#: ``solves_per_min`` covers the solve service's throughput
#: (``serve_solves_per_min``); latency percentiles
#: (``serve_p99_latency_ms``) fall through to the cost-like default.
HIGHER_IS_BETTER_TAGS = (
    "iters_per_s", "speedup", "_rate", "hit_rate",
    "compress_ratio", "overlap_fraction", "solves_per_min",
    # dynamics throughputs (DESIGN.md §29): Chebyshev moments and
    # accepted evolution steps per second — rates, so shrinkage is the
    # regression; the paired error metrics (kpm_dos_rel_err,
    # evolve_norm_drift, evolve_energy_drift) deliberately fall through
    # to the cost-like default (error growth is the regression)
    "moments_per_s", "steps_per_s",
)


def is_higher_better(metric: str) -> bool:
    """True when a LOWER value of ``metric`` is the regression."""
    return any(tag in metric for tag in HIGHER_IS_BETTER_TAGS)
