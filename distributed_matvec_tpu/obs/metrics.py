"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

One pillar of the telemetry subsystem (see ``obs/__init__``).  A *series* is
an instrument name plus a set of string labels, e.g.::

    counter("artifact_cache", kind="structure", event="hit").inc()
    histogram("matvec_apply_ms", engine="local").observe(dt_ms)
    gauge("ell_table_bytes", engine="distributed").set(eng.ell_nbytes)

Instruments are created on first use and live for the process (the same
lifetime as the AOT executable cache they often describe); :func:`snapshot`
returns the whole registry as plain JSON-able data, which the harnesses emit
as a ``metrics_snapshot`` event so one JSONL stream carries both timelines
and totals.

Disabled-path contract (the zero-overhead guarantee, guard-tested in
``tests/test_obs.py``): with the layer off every accessor returns the shared
:data:`NULL` no-op instrument — no allocation, no registry mutation, no
device work.  All updates are host-side Python on numbers already resident
on the host; instrumentation never calls ``block_until_ready`` or fetches a
``jax.Array``, so recording can never add a host↔device sync to a hot path.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Dict, Optional, Sequence, Tuple

from .events import obs_enabled

__all__ = [
    "counter",
    "gauge",
    "histogram",
    "snapshot",
    "series_name",
    "reset_metrics",
    "Counter",
    "Gauge",
    "Histogram",
    "NULL",
    "DEFAULT_BUCKETS",
]

# Default histogram upper bounds (ms-oriented: apply latencies span ~0.1 ms
# CPU smoke configs to ~10 s cold distributed applies); a final +inf bucket
# is implicit.
DEFAULT_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
                   250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0)


class _Null:
    """Shared no-op instrument returned by every accessor when the layer is
    disabled — callers never branch on enablement themselves."""

    __slots__ = ()

    def inc(self, amount=1):
        pass

    def set(self, value):
        pass

    def observe(self, value):
        pass

    @property
    def value(self):
        return 0


NULL = _Null()


class Counter:
    """Monotonically increasing count (events, bytes)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, amount=1):
        self.value += amount


class Gauge:
    """Last-write-wins scalar (sizes, capacities)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, value):
        self.value = float(value)


class Histogram:
    """Fixed-bucket histogram with sum/count (latency distributions).

    ``buckets`` are inclusive upper bounds; one overflow bucket is
    appended.  Bucket geometry is fixed at series creation — a later call
    with different ``buckets`` reuses the existing series unchanged (the
    registry is process-wide; silent re-bucketing would corrupt it).
    """

    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.buckets = tuple(float(b) for b in buckets)
        if any(b2 <= b1 for b1, b2 in zip(self.buckets, self.buckets[1:])):
            raise ValueError(f"histogram buckets must be strictly "
                             f"increasing, got {self.buckets}")
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value):
        v = float(value)
        self.counts[bisect_left(self.buckets, v)] += 1
        self.sum += v
        self.count += 1

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def to_dict(self) -> dict:
        return {"buckets": list(self.buckets), "counts": list(self.counts),
                "sum": self.sum, "count": self.count}


_Key = Tuple[str, str, Tuple[Tuple[str, str], ...]]
_lock = threading.Lock()
_registry: Dict[_Key, object] = {}


def _labels_key(labels: dict) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def series_name(name: str, labels: dict) -> str:
    """Canonical flat series id: ``name{k=v,...}`` (labels sorted)."""
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in _labels_key(labels))
    return f"{name}{{{inner}}}"


def _series(kind: str, cls, name: str, labels: dict, *args):
    if not obs_enabled():
        return NULL
    key = (kind, name, _labels_key(labels))
    inst = _registry.get(key)
    if inst is None:
        with _lock:
            inst = _registry.get(key)
            if inst is None:
                inst = cls(*args)
                _registry[key] = inst
    return inst


def counter(name: str, **labels) -> Counter:
    return _series("counter", Counter, name, labels)


def gauge(name: str, **labels) -> Gauge:
    return _series("gauge", Gauge, name, labels)


def histogram(name: str, buckets: Optional[Sequence[float]] = None,
              **labels) -> Histogram:
    return _series("histogram", Histogram, name, labels,
                   buckets if buckets is not None else DEFAULT_BUCKETS)


def snapshot() -> dict:
    """The whole registry as plain data:
    ``{"counters": {series: value}, "gauges": {...},
    "histograms": {series: {buckets, counts, sum, count}}}``."""
    out = {"counters": {}, "gauges": {}, "histograms": {}}
    with _lock:
        items = list(_registry.items())
    for (kind, name, lk), inst in items:
        sname = series_name(name, dict(lk))
        if kind == "counter":
            out["counters"][sname] = inst.value
        elif kind == "gauge":
            out["gauges"][sname] = inst.value
        else:
            out["histograms"][sname] = inst.to_dict()
    return out


def reset_metrics() -> None:
    """Drop every series (tests)."""
    with _lock:
        _registry.clear()
