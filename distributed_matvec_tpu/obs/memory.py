"""Device-memory observability: ledger, watermarks, executable analysis,
OOM forensics.

Fourth pillar of the telemetry subsystem (see ``obs/__init__``).  The whole
engine design pivots on HBM headroom — mode selection (ell vs fused), batch
widths, and "will this basis fit?" all come down to bytes — yet before this
module the only signals were hand-estimated comments and trial-and-OOM.
Four producers report through here:

* **Ledger** (:func:`track` / :func:`ledger_tree` / :func:`emit_ledger`):
  a process-wide registry of named allocations.  Engines, the distributed
  plan stream, solvers, and the artifact loader register what they hold
  (ELL/fused tables, double-buffer slots, Krylov workspace, staged exchange
  buffers) under ``/``-separated attribution paths; the tree rolls totals
  up per component and is emitted as ``memory_ledger`` events.  Entries are
  *live*: :meth:`Handle.release` (or the owner being garbage-collected,
  via ``weakref.finalize``) removes them.
* **Watermark sampler** (:func:`sample_watermark` / :func:`watermark_due`):
  polls ``device.memory_stats()`` around engine init, plan uploads, and
  every ``memory_every``-th apply, publishing ``hbm_bytes_in_use`` /
  ``hbm_peak_bytes`` gauges and ``memory_watermark`` events.  Backends
  without stats (the CPU client returns ``None``) soft-fail once and stay
  silent — the ledger and executable analysis remain the advisory sources
  there.
* **Compiled-executable analysis** (:func:`record_executable_analysis`):
  captures ``compiled.memory_analysis()`` (argument / output / temp /
  generated-code bytes) for every AOT-cached executable at compile time,
  emits it as ``memory_analysis`` events, and stores a JSON sidecar next
  to the XLA artifact cache so predicted-vs-measured peak is diffable
  across runs.
* **OOM forensics** (:func:`attach_oom` / :class:`OomError`): engine
  build/apply errors carrying ``RESOURCE_EXHAUSTED`` gain a structured
  :class:`MemoryReport` (ledger tree + last watermark + executable
  analyses + remediation suggestions), emitted as a critical
  ``memory_report`` event and re-raised as the typed :class:`OomError`.

Disabled-path contract (the PR-2 guard, extended): with ``DMT_OBS=off``
every producer is a no-op — :func:`track` returns the shared
:data:`NULL_HANDLE`, :func:`watermark_due` is False, analyses record
nothing, and :func:`attach_oom` returns ``None`` so the original error
propagates untouched.  All the hot-path hooks live on the error path or
behind a cadence check; the apply program itself never changes.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..utils.config import get_config
from ..utils.logging import log_debug, log_warn
from .events import emit, obs_enabled
from .metrics import counter, gauge

__all__ = [
    "Handle",
    "NULL_HANDLE",
    "track",
    "track_tree",
    "ledger_entries",
    "ledger_tree",
    "ledger_total",
    "host_rss_bytes",
    "emit_ledger",
    "next_instance",
    "sample_watermark",
    "watermark_due",
    "last_watermark",
    "record_executable_analysis",
    "executable_analyses",
    "MemoryReport",
    "OomError",
    "is_resource_exhausted",
    "build_memory_report",
    "remediation",
    "attach_oom",
    "reset_memory",
]


# ---------------------------------------------------------------------------
# ledger

_lock = threading.Lock()
_ledger: Dict[str, dict] = {}           # path -> entry dict (insertion order)
_instances: Dict[str, int] = {}         # per-kind engine/solver counters

#: Finalizer-safe deferred releases.  Handle.release is the target of the
#: engines' ``weakref.finalize``, which the garbage collector may run in
#: the MIDDLE of a ledger operation on the same thread (any allocation
#: inside a ``with _lock:`` block can trigger a collection) — taking the
#: non-reentrant ``_lock`` there deadlocks the process (observed: an
#: engine finalizer firing inside ``ledger_entries``'s snapshot
#: comprehension froze the whole test suite).  So release never locks: it
#: queues its paths on this list (``list.append`` is atomic under the
#: GIL, and the GC never starts a nested collection from a finalizer),
#: and every locked ledger operation drains the queue first.
_released: List[List[str]] = []


def _drain_released_locked() -> None:
    """Apply queued finalizer releases; the caller holds ``_lock``."""
    while _released:
        for p in _released.pop():
            _ledger.pop(p, None)


@dataclass
class Handle:
    """A live ledger registration; :meth:`release` removes every path this
    handle owns (idempotent).  :meth:`set` re-points one path's byte count
    — growing workspaces (block-Lanczos bases) update in place instead of
    re-registering."""

    paths: List[str] = field(default_factory=list)

    def set(self, path: str, nbytes: int) -> None:
        with _lock:
            _drain_released_locked()
            ent = _ledger.get(path)
            if ent is not None:
                ent["bytes"] = int(nbytes)

    def release(self) -> None:
        # GC-safe by construction: NO lock here (see ``_released``)
        paths, self.paths = self.paths, []
        if paths:
            _released.append(paths)


class _NullHandle(Handle):
    """Shared no-op handle returned when the layer is disabled."""

    __slots__ = ()

    def __init__(self):
        super().__init__(paths=[])

    def set(self, path, nbytes):
        pass

    def release(self):
        pass


NULL_HANDLE = _NullHandle()


def next_instance(kind: str) -> str:
    """A readable unique attribution id for one engine/solver instance
    (``local:0``, ``distributed:1``, ...) — ledger paths must not collide
    when a process holds several engines of the same kind."""
    with _lock:
        i = _instances.get(kind, 0)
        _instances[kind] = i + 1
    return f"{kind}:{i}"


def track(path: str, nbytes: int, device: str = "",
          handle: Optional[Handle] = None, **meta) -> Handle:
    """Register one named allocation under a ``/``-separated attribution
    path (``engine/local:0/structure/idx``).  Re-tracking an existing path
    replaces it (a rebuilt table supersedes the old entry).  Returns the
    handle owning the registration (pass ``handle=`` to accumulate several
    paths under one owner)."""
    if not obs_enabled():
        return NULL_HANDLE
    h = handle if handle is not None else Handle()
    ent = {"bytes": int(nbytes), "device": str(device)}
    for k, v in meta.items():
        ent[k] = v
    with _lock:
        _drain_released_locked()
        _ledger[path] = ent
        if path not in h.paths:
            h.paths.append(path)
    return h


def track_tree(path: str, tree, device: str = "",
               handle: Optional[Handle] = None, **meta) -> Handle:
    """Register the summed ``nbytes`` of a pytree of arrays under one
    path (the engines' table bundles are pytrees)."""
    if not obs_enabled():
        return NULL_HANDLE
    try:
        import jax

        total = sum(int(getattr(leaf, "nbytes", 0))
                    for leaf in jax.tree_util.tree_leaves(tree))
    except Exception:
        total = int(getattr(tree, "nbytes", 0))
    return track(path, total, device=device, handle=handle, **meta)


def ledger_entries() -> Dict[str, dict]:
    """Snapshot of the live ledger: {path: {bytes, device, ...meta}}."""
    with _lock:
        _drain_released_locked()
        return {p: dict(e) for p, e in _ledger.items()}


def ledger_tree() -> dict:
    """The live ledger as a nested attribution tree: each node carries the
    rolled-up ``bytes`` of its subtree plus ``children``; leaf nodes keep
    their entry metadata."""
    root = {"bytes": 0, "children": {}}
    for path, ent in ledger_entries().items():
        node = root
        node["bytes"] += ent["bytes"]
        for part in path.split("/"):
            node = node["children"].setdefault(
                part, {"bytes": 0, "children": {}})
            node["bytes"] += ent["bytes"]
        for k, v in ent.items():
            if k != "bytes":
                node[k] = v
    return root


def ledger_total(prefix: Optional[str] = None,
                 device: Optional[str] = None) -> int:
    """Total live bytes, optionally restricted to paths under ``prefix``
    and/or to one ``device`` class (``"host"`` for host-RAM entries like
    the streamed engine's plan; ``"device"`` for HBM-resident arrays)."""
    total = 0
    for path, ent in ledger_entries().items():
        if prefix is not None and path != prefix \
                and not path.startswith(prefix + "/"):
            continue
        if device is not None and ent.get("device") != device:
            continue
        total += ent["bytes"]
    return total


def host_rss_bytes() -> int:
    """This process's current resident-set size in bytes (0 when the
    platform exposes none) — the host-RAM watermark companion to the
    device ``memory_stats()`` sampler, read by the streamed engine's plan
    accounting and the OOM forensics report.  Proc-based (no psutil
    dependency); soft-fails to 0 anywhere /proc is absent."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return 0


def emit_ledger(context: str, **fields) -> Optional[dict]:
    """One ``memory_ledger`` event: the current attribution tree + total
    plus caller context (engines pass mode / sizes / T0 so the capacity
    planner can work from the snapshot alone)."""
    if not obs_enabled():
        return None
    return emit("memory_ledger", context=str(context),
                total_bytes=int(ledger_total()),
                entries=ledger_entries(), **fields)


# ---------------------------------------------------------------------------
# watermark sampler

_wm_lock = threading.Lock()
_wm_unsupported = False       # first None/failing memory_stats() latches
_last_watermark: Optional[dict] = None


def _device_stats() -> Optional[List[dict]]:
    """Per-local-device ``memory_stats()`` rows, or None when the backend
    exposes none (latched after the first miss so the per-apply cadence
    never re-pays a failing query)."""
    global _wm_unsupported
    if _wm_unsupported:
        return None
    try:
        import jax

        rows = []
        for d in jax.local_devices():
            st = d.memory_stats()
            if not st:
                continue
            rows.append({
                "device": f"{d.platform}:{d.id}",
                "bytes_in_use": int(st.get("bytes_in_use", 0)),
                "peak_bytes_in_use": int(st.get("peak_bytes_in_use", 0)),
                "bytes_limit": int(st.get("bytes_limit", 0)),
            })
    except Exception as e:
        with _wm_lock:
            _wm_unsupported = True
        log_debug(f"device memory_stats unavailable: {e!r}")
        return None
    if not rows:
        with _wm_lock:
            _wm_unsupported = True
        log_debug("device memory_stats unavailable on this backend "
                  "(advisory mode: ledger + executable analysis only)")
        return None
    return rows


def sample_watermark(tag: str, **fields) -> Optional[dict]:
    """Poll device memory and publish one ``memory_watermark`` event plus
    the ``hbm_bytes_in_use`` / ``hbm_peak_bytes`` gauges.  Returns the
    sample dict, or None when the layer is off or the backend has no
    stats (soft-fail: never raises)."""
    global _last_watermark
    if not obs_enabled():
        return None
    rows = _device_stats()
    if rows is None:
        return None
    in_use = sum(r["bytes_in_use"] for r in rows)
    peak = max(r["peak_bytes_in_use"] for r in rows)
    limit = sum(r["bytes_limit"] for r in rows)
    sample = {"tag": str(tag), "bytes_in_use": in_use,
              "peak_bytes": peak, "bytes_limit": limit, "devices": rows}
    gauge("hbm_bytes_in_use").set(in_use)
    gauge("hbm_peak_bytes").set(peak)
    with _wm_lock:
        _last_watermark = sample
    emit("memory_watermark", **sample, **fields)
    return sample


def watermark_due(apply_index: int) -> bool:
    """Whether eager apply ``apply_index`` should sample a watermark: the
    first and every ``memory_every``-th apply.  Always False when the
    layer is off or the backend already proved statless, so the hot path
    never branches further."""
    if not obs_enabled() or _wm_unsupported:
        return False
    every = max(int(get_config().memory_every), 1)
    return apply_index % every == 0


def last_watermark() -> Optional[dict]:
    """The most recent watermark sample (OOM forensics context), or None."""
    with _wm_lock:
        return dict(_last_watermark) if _last_watermark else None


# ---------------------------------------------------------------------------
# compiled-executable memory analysis

_exec_analyses: Dict[str, dict] = {}

_ANALYSIS_FIELDS = (
    ("argument_bytes", "argument_size_in_bytes"),
    ("output_bytes", "output_size_in_bytes"),
    ("temp_bytes", "temp_size_in_bytes"),
    ("alias_bytes", "alias_size_in_bytes"),
    ("generated_code_bytes", "generated_code_size_in_bytes"),
)


def record_executable_analysis(key: str, compiled,
                               program: Optional[str] = None,
                               **fields) -> Optional[dict]:
    """Capture ``compiled.memory_analysis()`` for one AOT executable
    (``key`` identifies the compiled specialization; ``program`` the
    human-readable program name): stores it in the process registry, emits
    a ``memory_analysis`` event, sets the
    ``executable_temp_bytes{program=...}`` gauge, and writes a JSON
    sidecar next to the XLA artifact cache (all soft-fail).  Returns the
    analysis dict, or None when disabled/unavailable."""
    if not obs_enabled():
        return None
    try:
        ma = compiled.memory_analysis()
    except Exception as e:
        log_debug(f"memory_analysis unavailable for {key}: {e!r}")
        return None
    if ma is None:
        return None
    ana = {"key": str(key), "program": str(program or key)}
    for out_key, attr in _ANALYSIS_FIELDS:
        ana[out_key] = int(getattr(ma, attr, 0) or 0)
    ana["peak_estimate_bytes"] = (ana["argument_bytes"]
                                  + ana["output_bytes"]
                                  + ana["temp_bytes"])
    with _lock:
        _exec_analyses[str(key)] = dict(ana)
    gauge("executable_temp_bytes",
          program=ana["program"]).set(ana["temp_bytes"])
    emit("memory_analysis", **ana, **fields)
    _save_analysis_sidecar(str(key), ana)
    return ana


def _save_analysis_sidecar(name: str, ana: dict) -> None:
    """Persist one analysis next to the XLA artifact cache tree so the
    capacity planner and run-diff can read compile-time memory facts
    without re-running; soft-fail like every other cache write."""
    from ..utils.artifacts import artifact_path, artifacts_enabled

    if not artifacts_enabled():
        return
    try:
        import hashlib

        fp = hashlib.sha256(name.encode()).hexdigest()
        path = artifact_path("xla-analysis", fp, ".json")
        with open(path, "w") as f:
            json.dump(ana, f, sort_keys=True)
    except OSError as e:
        log_debug(f"memory-analysis sidecar save skipped: {e!r}")


def executable_analyses() -> Dict[str, dict]:
    """Snapshot of every captured executable analysis, keyed by program."""
    with _lock:
        return {k: dict(v) for k, v in _exec_analyses.items()}


# ---------------------------------------------------------------------------
# OOM forensics

class OomError(RuntimeError):
    """A device ``RESOURCE_EXHAUSTED`` failure with forensics attached:
    ``.report`` carries the :class:`MemoryReport` dict (ledger tree, last
    watermark, executable analyses, remediation)."""

    def __init__(self, message: str, report: dict):
        super().__init__(message)
        self.report = report


@dataclass
class MemoryReport:
    """Structured OOM forensics: what was resident (ledger), what the
    device said (watermark), what the compiler predicted (analyses), and
    what to try next (remediation)."""

    context: dict
    ledger: dict
    ledger_total_bytes: int
    watermark: Optional[dict]
    executables: Dict[str, dict]
    remediation: List[str]
    host_rss_bytes: int = 0

    def to_dict(self) -> dict:
        return {"context": self.context, "ledger": self.ledger,
                "ledger_total_bytes": self.ledger_total_bytes,
                "watermark": self.watermark,
                "executables": self.executables,
                "remediation": self.remediation,
                "host_rss_bytes": self.host_rss_bytes}


_OOM_MARKERS = ("resource_exhausted", "out of memory", "out-of-memory")


def is_resource_exhausted(exc: BaseException) -> bool:
    """Whether an exception is a device out-of-memory failure.  Matched on
    the message — jaxlib's ``XlaRuntimeError`` carries the gRPC-style
    ``RESOURCE_EXHAUSTED:`` prefix and the allocator says ``Out of
    memory``; matching text keeps this independent of which jaxlib
    exception class this version raises."""
    msg = f"{type(exc).__name__}: {exc}".lower()
    return any(m in msg for m in _OOM_MARKERS)


def remediation(context: dict) -> List[str]:
    """Suggested ways out of the OOM the context describes, most effective
    first.  These are the levers the engines actually expose — the point
    is that the error message names them instead of leaving the operator
    to rediscover the design doc."""
    mode = str(context.get("mode", ""))
    engine = str(context.get("engine", ""))
    phase = str(context.get("phase", ""))
    out = []
    if mode in ("streamed", "hybrid"):
        out.append(
            "set tune=static (DMT_TUNE=static): the autotuner prices the "
            "row-chunk / compress / pipeline / plan-tier cross-product "
            "against the calibrated roofline and picks the cheapest "
            "feasible config — usually the right knobs without hand-tuning")
    if mode in ("ell", "compact"):
        out.append(
            "switch to mode='streamed' (DistributedEngine): the routing "
            "plan lives in host RAM and streams per apply — fused-level "
            "device memory at near-plan-bandwidth apply speed")
        out.append(
            "switch to mode='fused' (recomputes structure per apply: "
            "O(B*T) scratch instead of resident O(N*T0) tables)")
        if mode == "ell":
            out.append(
                "mode='compact' fits isotropic real sectors in 4 B/entry "
                "(~1/3 of the standard ELL tables)")
    if phase == "init":
        out.append(
            "lower ell_build_budget_gb (DMT_ELL_BUILD_BUDGET_GB) to force "
            "the two-pass low-memory build bounded by the packed table "
            "size")
    out.append(
        "lower matvec_batch_size (DMT_MATVEC_BATCH_SIZE): per-chunk "
        "scratch and fused exchange buffers scale with the row chunk")
    out.append(
        "narrow the apply batch (fewer RHS columns per matvec): gather "
        "scratch scales with vec_width")
    if engine == "distributed":
        out.append(
            "add shards (more devices / a larger mesh): per-device table "
            "and vector bytes scale ~1/D")
    else:
        out.append(
            "shard over a mesh with DistributedEngine: per-device bytes "
            "scale ~1/D")
    out.append(
        "run tools/capacity.py against this run's obs stream for "
        "per-mode bytes/row and the max basis size this device fits")
    return out


def build_memory_report(**context) -> MemoryReport:
    """Assemble the forensics snapshot for an OOM (or for inspection)."""
    return MemoryReport(
        context=dict(context),
        ledger=ledger_tree(),
        ledger_total_bytes=ledger_total(),
        watermark=last_watermark(),
        executables=executable_analyses(),
        remediation=remediation(context),
        host_rss_bytes=host_rss_bytes(),
    )


def attach_oom(exc: BaseException, **context) -> Optional[OomError]:
    """OOM forensics entry point for the engines' error paths: when the
    layer is on and ``exc`` is a ``RESOURCE_EXHAUSTED`` failure, emit the
    critical ``memory_report`` event and return a typed :class:`OomError`
    for the caller to ``raise ... from exc``.  Returns None otherwise —
    the caller re-raises the original, so with ``DMT_OBS=off`` this is a
    provable no-op and non-OOM errors are never rewritten."""
    if not obs_enabled() or not is_resource_exhausted(exc):
        return None
    report = build_memory_report(**context)
    rd = report.to_dict()
    counter("oom_events").inc()
    emit("memory_report", level="critical", error=f"{exc}"[:500], **rd)
    try:
        # the flight recorder's OOM trigger: the bundle carries this
        # report plus the open-span stack and the last ring events —
        # lazily imported (flight imports this module the same way)
        from .flight import flight_dump

        flight_dump("oom", error=f"{exc}"[:500], report=rd)
    except Exception:
        pass
    detail = " ".join(f"{k}={v}" for k, v in context.items())
    lines = "\n  - ".join(report.remediation)
    msg = (f"device memory exhausted ({detail}): {exc}\n"
           f"resident per the memory ledger: "
           f"{report.ledger_total_bytes / 1e9:.3f} GB"
           + (f"; last watermark peak "
              f"{report.watermark['peak_bytes'] / 1e9:.3f} GB"
              if report.watermark else "")
           + f"\nremediation:\n  - {lines}")
    log_warn(f"OOM forensics: {detail} "
             f"(ledger {report.ledger_total_bytes / 1e9:.3f} GB resident)")
    return OomError(msg, rd)


# ---------------------------------------------------------------------------


def reset_memory() -> None:
    """Drop ledger, analyses, watermark state and the unsupported latch
    (tests).  The per-kind instance counters are deliberately NOT reset:
    handles (and engine GC finalizers) from before the reset stay live,
    and reusing an instance id would let a stale finalizer release a
    NEW owner's identically-named paths."""
    global _wm_unsupported, _last_watermark
    with _lock:
        _drain_released_locked()
        _ledger.clear()
        _exec_analyses.clear()
    with _wm_lock:
        _wm_unsupported = False
        _last_watermark = None
