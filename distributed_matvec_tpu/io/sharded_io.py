"""Chunked / per-shard vector I/O for the ≥10⁹-state regime.

The reference reads and writes big datasets in hyperslab chunks and
per-locale blocks (``MyHDF5.chpl:105-162, 272-333``) because no locale can
hold a global array.  The analogs here:

* :func:`stream_block_to_shards` — a block-order (global sorted) dataset,
  e.g. a golden ``/x`` next to ``/representatives``
  (input_for_matvec.py:28-46), is read in hyperslab chunks, hash-routed
  (``localeIdxOf``), and appended to per-shard datasets.  Chunks ascend and
  block order is ascending-state order, so each shard's stream lands in
  exactly the per-shard sorted order the engine consumes — this is
  ``arrFromBlockToHashed`` (BlockToHashed.chpl:87-208) as streaming I/O,
  with bounded memory.
* :func:`save_hashed_vector` / :func:`load_hashed_shard` — a hashed
  ``[D, M(, k)]`` array (eigenvectors, checkpoint state) written one shard
  at a time with the pad rows stripped, and read back per shard (the
  per-locale block read of ``readDatasetAsBlocks``, MyHDF5.chpl:272-286).
  In a multi-process run each process writes/reads only its addressable
  shards.

Shard-aligned vector files carry the counts they were written with, so a
consumer can assemble the padded ``[D, M]`` device array directly (see
``DistributedEngine.from_shards`` for the representative-side analog).
"""

from __future__ import annotations

import contextlib
from typing import Optional

import numpy as np

from ..enumeration.host import shard_index

__all__ = ["stream_block_to_shards", "save_hashed_vector",
           "save_hashed_vectors", "load_hashed_shard",
           "load_hashed_meta", "hashed_vector_counts",
           "hashed_shard_reader"]

_CHUNK = 1 << 20


def stream_block_to_shards(src_path: str, out_path: str, n_shards: int,
                           x_dataset: str = "x",
                           reps_dataset: str = "representatives",
                           name: str = "v",
                           chunk: int = _CHUNK) -> np.ndarray:
    """Route a block-order dataset into per-shard datasets, chunk by chunk.

    ``src_path[x_dataset]`` may be rank-1 [N] or a batch [k, N] (the golden
    generator's transposed layout, input_for_matvec.py:43-46); the output
    shard datasets are [c_d] or [c_d, k].  Returns the per-shard counts.
    """
    import h5py

    with h5py.File(src_path, "r") as fin, h5py.File(out_path, "w") as fout:
        reps = fin[reps_dataset]
        xd = fin[x_dataset]
        batch = xd.ndim == 2
        n = reps.shape[0]
        if (xd.shape[-1] if batch else xd.shape[0]) != n:
            raise ValueError(
                f"{x_dataset} has {xd.shape} entries for {n} representatives")
        counts = np.zeros(n_shards, np.int64)
        g = fout.create_group(f"vector_shards/{name}")
        dsets = []
        for d in range(n_shards):
            shape = (0, xd.shape[0]) if batch else (0,)
            maxshape = (None, xd.shape[0]) if batch else (None,)
            chunks = (min(chunk, _CHUNK),) + ((xd.shape[0],) if batch else ())
            dsets.append(g.create_dataset(str(d), shape=shape,
                                          maxshape=maxshape, dtype=xd.dtype,
                                          chunks=chunks))
        for s in range(0, n, chunk):
            e = min(s + chunk, n)
            r_c = reps[s:e]
            x_c = xd[:, s:e].T if batch else xd[s:e]
            owner = shard_index(np.asarray(r_c, np.uint64), n_shards)
            order = np.argsort(owner, kind="stable")
            x_s = x_c[order]
            bounds = np.searchsorted(owner[order], np.arange(n_shards + 1))
            for d in range(n_shards):
                lo, hi = bounds[d], bounds[d + 1]
                if lo == hi:
                    continue
                ds = dsets[d]
                o = ds.shape[0]
                ds.resize((o + hi - lo,) + ds.shape[1:])
                ds[o:] = x_s[lo:hi]
                counts[d] += hi - lo
        fout.attrs["counts"] = counts
        fout.attrs["n_shards"] = n_shards
    return counts


def save_hashed_vector(path: str, xh, counts, name: str = "v") -> None:
    """Write a hashed ``[D, M(, k)]`` array one shard at a time, pad rows
    stripped; only shards addressable by this process are written (pass the
    same ``counts`` the layout/manifest carries).

    HDF5 has no concurrent-writer support, so in a multi-process run each
    rank writes its OWN file (``path.r<rank>``); :func:`load_hashed_shard`
    finds a shard in whichever file holds it."""
    save_hashed_vectors(path, {name: xh}, counts)


def save_hashed_vectors(path: str, vectors: dict, counts,
                        meta: Optional[dict] = None) -> None:
    """Write several named hashed arrays in ONE atomic file pass — the
    rewrite cost is paid once, not once per vector (a k-eigenvector save
    would otherwise re-copy all earlier vectors k times).

    Atomic write (matching save_engine_structure / enumerate_to_shards):
    the whole file is built at a temp path and ``os.replace``d, so a crash
    mid-save can't leave a corrupt or mixed-generation vector file, and
    each rewritten group is recreated wholesale so stale shard datasets
    from an earlier save with a different D/counts can't survive.  All
    other file content (other vector groups, co-located datasets/groups,
    root attrs) is carried over; an unreadable previous file is an error —
    silently replacing it would destroy co-located data the caller never
    asked us to touch.

    ``meta`` (scalars/small arrays) is written under ``/ckpt_meta`` in the
    SAME atomic pass, replacing any previous meta — so checkpoint metadata
    and the vectors it describes can never be of mixed generations (see
    solve/lanczos.py's multi-process checkpoint)."""
    import os
    import tempfile

    import h5py
    import jax

    from ..utils import faults

    counts = np.asarray(counts, np.int64)
    D = counts.size
    if jax.process_count() > 1:
        path = f"{path}.r{jax.process_index()}"
    faults.check("ckpt_write", path=path)
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(os.path.abspath(path)) or ".", suffix=".tmp")
    os.close(fd)
    try:
        with h5py.File(tmp, "w") as fout:
            if os.path.exists(path):
                with h5py.File(path, "r") as fin:
                    for k in fin:
                        if k == "vector_shards":
                            dst = fout.require_group("vector_shards")
                            for other in fin["vector_shards"]:
                                if other not in vectors:
                                    fin.copy(f"vector_shards/{other}", dst,
                                             name=other)
                        elif k == "ckpt_meta" and meta is not None:
                            pass             # replaced wholesale below
                        else:
                            fin.copy(k, fout, name=k)
                    for k, v in fin.attrs.items():
                        if k not in ("counts", "n_shards"):
                            fout.attrs[k] = v
            for name, xh in vectors.items():
                g = fout.require_group(f"vector_shards/{name}")
                for d in range(D):
                    shard = None
                    if isinstance(xh, dict):
                        # pre-fetched host pieces {d: rows} — lets callers
                        # stage one device row at a time (solve/lanczos.py)
                        shard = xh.get(d)
                    elif isinstance(xh, jax.Array):
                        for piece in xh.addressable_shards:
                            if piece.index[0].start == d:
                                shard = np.asarray(piece.data)[0]
                                break
                    else:
                        shard = np.asarray(xh)[d]
                    if shard is None:
                        continue            # another process's shard
                    g.create_dataset(str(d), data=shard[: counts[d]])
            if meta is not None:
                g = fout.require_group("ckpt_meta")
                for k, v in meta.items():
                    if isinstance(v, str):
                        g.attrs[k] = v      # h5py rejects numpy str scalars
                        continue
                    a = np.asarray(v)
                    if a.ndim == 0:
                        g.attrs[k] = a[()]
                    else:
                        g.create_dataset(k, data=a)
            fout.attrs["counts"] = counts
            fout.attrs["n_shards"] = D
        faults.check("ckpt_rename", path=path)
        os.replace(tmp, path)
        from ..utils.artifacts import note_artifact_ok

        note_artifact_ok(path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def load_hashed_meta(path: str,
                     expected_fingerprint: Optional[str] = None
                     ) -> Optional[dict]:
    """The ``/ckpt_meta`` group of a hashed-vector file (attrs + datasets),
    searched across ``path`` and any per-rank ``path.r*`` files; None when
    absent.

    ``expected_fingerprint`` keeps the scan going past candidates whose
    ``fingerprint`` attr doesn't match — without it, a stale base-path file
    left by an earlier single-process run would mask valid per-rank ``.r*``
    checkpoints and a resume would silently start fresh."""
    import glob
    import h5py

    for cand in [path] + sorted(glob.glob(f"{path}.r*")):
        try:
            with h5py.File(cand, "r") as f:
                if "ckpt_meta" not in f:
                    continue
                if not _fingerprint_ok(f, expected_fingerprint):
                    continue
                g = f["ckpt_meta"]
                out = {k: g.attrs[k] for k in g.attrs}
                for k in g:
                    out[k] = g[k][...]
                return out
        except OSError:
            continue
    return None


def _fingerprint_ok(f, expected_fingerprint: Optional[str]) -> bool:
    """True when ``expected_fingerprint`` is unset or matches the file's
    ``/ckpt_meta`` fingerprint attr — the filter that keeps a stale
    base-path file from an earlier run from shadowing valid per-rank
    ``.r*`` files in the scans below."""
    if expected_fingerprint is None:
        return True
    if "ckpt_meta" not in f:
        return False
    return (str(f["ckpt_meta"].attrs.get("fingerprint", ""))
            == expected_fingerprint)


def _generation_ok(f, match_meta: Optional[dict]) -> bool:
    """True when the file's own ``/ckpt_meta`` generation scalars
    (``m``, ``total_iters``) agree with the checkpoint metadata the
    caller already selected.  Per-rank ``.r*`` files are written without
    a barrier, so a crash between rank saves leaves files of MIXED
    generations that all pass the fingerprint filter — and a thick
    restart SHRINKS ``m``, so a stale file can satisfy every shard fetch
    of a newer, smaller checkpoint.  Fetching from such a file would
    silently splice old basis rows into the resumed solve."""
    if match_meta is None:
        return True
    if "ckpt_meta" not in f:
        return False
    attrs = f["ckpt_meta"].attrs
    for k in ("m", "total_iters"):
        if k not in match_meta:
            continue
        if k not in attrs or int(attrs[k]) != int(match_meta[k]):
            return False
    return True


@contextlib.contextmanager
def hashed_shard_reader(path: str,
                        expected_fingerprint: Optional[str] = None,
                        match_meta: Optional[dict] = None):
    """Scan-once, open-once shard reader over ``path`` and its per-rank
    ``path.r*`` files.  Candidates are globbed, opened, and filtered ONE
    time — by ``expected_fingerprint`` (the stale-file filter of
    :func:`load_hashed_shard`) AND by generation agreement of each
    file's own ``/ckpt_meta`` against ``match_meta``, the metadata the
    caller already selected — then the yielded ``fetch(d, name)`` serves
    every per-(row, shard) read from the already-open files.

    A checkpoint restore reads O(m·D) shard slices; per-call
    :func:`load_hashed_shard` scans would bill ~m·D glob+open+close
    cycles to the trend-gated ``resume_reshard_s``.  The generation
    filter is a correctness matter, not an optimization: barrier-free
    per-rank saves mean mixed-generation ``.r*`` files can coexist under
    one fingerprint, and a fetch that fell through to a stale file would
    splice rows of a different Krylov basis into the resume.  A shard
    absent from every same-generation file raises ``KeyError`` — the
    caller's existing incomplete-checkpoint degrade path."""
    import glob

    import h5py

    files = []
    try:
        for cand in [path] + sorted(glob.glob(f"{path}.r*")):
            try:
                f = h5py.File(cand, "r")
            except OSError:
                continue
            if (_fingerprint_ok(f, expected_fingerprint)
                    and _generation_ok(f, match_meta)):
                files.append(f)
            else:
                f.close()

        def fetch(d: int, name: str = "v") -> np.ndarray:
            key = f"vector_shards/{name}"
            sd = str(d)
            for f in files:
                if key in f and sd in f[key]:
                    return f[key][sd][...]
            raise KeyError(
                f"shard {d} of {name!r} not found under {path}(.r*) in "
                "the restored checkpoint generation")

        yield fetch
    finally:
        for f in files:
            f.close()


def load_hashed_shard(path: str, d: int, name: str = "v",
                      expected_fingerprint: Optional[str] = None
                      ) -> np.ndarray:
    """One shard's rows of a saved hashed vector (pad rows NOT included).
    Looks in ``path`` first, then in any per-rank ``path.r*`` files a
    multi-process save produced; ``expected_fingerprint`` skips files whose
    ``/ckpt_meta`` fingerprint differs (checkpoint consumers MUST pass it —
    otherwise a stale base-path file shadows the valid per-rank data its
    metadata was already fingerprint-matched against)."""
    import glob
    import h5py

    key = f"vector_shards/{name}"
    for cand in [path] + sorted(glob.glob(f"{path}.r*")):
        try:
            with h5py.File(cand, "r") as f:
                if not _fingerprint_ok(f, expected_fingerprint):
                    continue
                if key in f and str(d) in f[key]:
                    return f[key][str(d)][...]
        except OSError:
            continue
    raise KeyError(f"shard {d} of {name!r} not found under {path}(.r*)")


def hashed_vector_counts(path: str,
                         expected_fingerprint: Optional[str] = None
                         ) -> Optional[np.ndarray]:
    """The ``counts`` attr of a hashed-vector file, searched across ``path``
    and any per-rank ``path.r*`` files (a multi-process save writes only to
    ``path.r<rank>``; every rank's file carries the full counts array).
    ``expected_fingerprint`` applies the same stale-file filter as
    :func:`load_hashed_shard`."""
    import glob
    import h5py

    for cand in [path] + sorted(glob.glob(f"{path}.r*")):
        try:
            with h5py.File(cand, "r") as f:
                if not _fingerprint_ok(f, expected_fingerprint):
                    continue
                return np.asarray(f.attrs["counts"], np.int64)
        except (OSError, KeyError):
            continue
    return None
