"""HDF5 I/O + representative checkpoint/restore.

Replaces the reference's ``MyHDF5.chpl`` (direct C-HDF5 hyperslab machinery,
:26-333) and the compute-or-restore logic of ``Diagonalize.chpl:227-246``:

  * output file layout (groups created by Diagonalize.chpl:276-279):
      /basis/representatives        u64 [N]
      /basis/norms                  f64 [N]          (ours; the reference
                                                      recomputes norms)
      /hamiltonian/eigenvalues      f64 [k]
      /hamiltonian/eigenvectors     f64/c128 [k, N]  (row-major like the
                                                      golden generator's
                                                      transposed layout,
                                                      input_for_matvec.py:43-46)
      /hamiltonian/residuals        f64 [k]
      /observables/<name>           f64 scalar ⟨ψ₀|O|ψ₀⟩ per YAML observable
  * golden-file layout (input_for_matvec.py:28-46): /representatives, /x, /y.

On a sharded run, hashed-layout arrays are converted to block (global sorted)
order by :class:`~..parallel.shuffle.HashedLayout` before writing — the
``arrFromHashedToBlock`` step of ``saveEigenvectors`` (Diagonalize.chpl:248-256).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

__all__ = [
    "save_basis",
    "load_basis",
    "save_eigen",
    "load_eigen",
    "save_golden",
    "load_golden",
    "save_observables",
    "make_or_restore_representatives",
    "save_engine_structure",
    "load_engine_structure",
]


def _h5py():
    try:
        import h5py
        return h5py
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            "h5py is required for HDF5 I/O; it is unavailable in this "
            "environment"
        ) from e


def save_engine_structure(path: str, fingerprint: str, mode: str,
                          payload: dict) -> None:
    """Checkpoint a precomputed engine structure under /engine_structure.

    Extends the reference's representative checkpoint (`makeBasisStates`,
    Diagonalize.chpl:227-246) one level up: the ELL/compact structure build
    costs minutes at scale (square_6x6: 6.5 min on-device) but is a pure
    function of (basis, operator, mode) — captured in ``fingerprint`` — so
    a rerun can restore it in I/O time.  Scalars go to attrs, arrays to
    datasets; None values are skipped.

    The sidecar is written to a temp file in the same directory and then
    ``os.replace``d onto ``path``: concurrent writers (every rank of a
    multi-host driver constructing the same engine) each produce a complete
    file and the rename is atomic, so a reader never observes an interleaved
    half-write.  The fingerprint is still written last as a second line of
    defence against a writer killed mid-save.
    """
    import os
    import tempfile

    from ..utils import faults

    h5py = _h5py()
    faults.check("ckpt_write", path=path)
    dirname = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(suffix=".h5.tmp", dir=dirname)
    os.close(fd)
    # mkstemp creates 0600; widen to a conventional checkpoint mode so the
    # rename does not narrow readability vs the previous in-place h5py
    # create (reading the umask would mutate process-global state under
    # JAX's background threads, so use a fixed mode)
    os.chmod(tmp, 0o644)
    try:
        with h5py.File(tmp, "w") as f:
            g = f.create_group("engine_structure")
            g.attrs["mode"] = mode
            for k, v in payload.items():
                if v is None:
                    continue
                if np.isscalar(v):
                    g.attrs[k] = v
                else:
                    g.create_dataset(k, data=np.asarray(v))
            # fingerprint LAST: a partially written file (killed mid-save)
            # then fails the fingerprint check instead of restoring garbage
            g.attrs["fingerprint"] = fingerprint
        faults.check("ckpt_rename", path=path)
        os.replace(tmp, path)
        # a complete fresh file landed: clear any corruption history so
        # the healed path is not one transient blip away from quarantine
        from ..utils.artifacts import note_artifact_ok

        note_artifact_ok(path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_engine_structure(path: str, fingerprint: str) -> Optional[dict]:
    """Restore a structure checkpoint; None unless the fingerprint matches
    (a stale checkpoint for a different basis/operator/mode is ignored, not
    an error)."""
    import os

    from ..utils import faults

    if not path or not os.path.exists(path):
        return None
    h5py = _h5py()

    def _read():
        faults.check("artifact_read", path=path)
        with h5py.File(path, "r") as f:
            if "engine_structure" not in f:
                return None
            g = f["engine_structure"]
            if str(g.attrs.get("fingerprint", "")) != fingerprint:
                return None
            out = {k: g.attrs[k] for k in g.attrs}
            for k in g:
                out[k] = g[k][...]
            return out

    try:
        # bounded retry for the transient case; a persistently
        # truncated/corrupt checkpoint rebuilds AND feeds the
        # corrupt/quarantine tally (utils/artifacts.py)
        return faults.with_retries("artifact_read", _read)
    except OSError as e:
        from ..utils.artifacts import note_artifact_corrupt

        note_artifact_corrupt(path, "structure", e)
        return None


def save_basis(path: str, representatives: np.ndarray,
               norms: Optional[np.ndarray] = None) -> None:
    """Write /basis/representatives (+ norms) — the checkpoint side of
    ``makeBasisStates`` (Diagonalize.chpl:237-243, MyHDF5.chpl:309-333)."""
    h5 = _h5py()
    with h5.File(path, "a") as f:
        g = f.require_group("basis")
        for name in ("representatives", "norms"):
            if name in g:
                del g[name]
        g.create_dataset("representatives",
                         data=np.asarray(representatives, np.uint64))
        if norms is not None:
            g.create_dataset("norms", data=np.asarray(norms, np.float64))


def load_basis(path: str) -> Optional[Tuple[np.ndarray, Optional[np.ndarray]]]:
    """(representatives, norms|None) if the checkpoint exists, else None —
    the restore probe of Diagonalize.chpl:228-235."""
    import os

    h5 = _h5py()
    if not os.path.exists(path):
        return None
    with h5.File(path, "r") as f:
        if "basis/representatives" not in f:
            return None
        reps = f["basis/representatives"][...].astype(np.uint64)
        norms = (f["basis/norms"][...].astype(np.float64)
                 if "basis/norms" in f else None)
        return reps, norms


def make_or_restore_representatives(basis, path: Optional[str],
                                    save: bool = True) -> bool:
    """Build the basis, restoring representatives from ``path`` when present
    (exact ``makeBasisStates`` semantics, Diagonalize.chpl:227-246).

    Returns True if restored from checkpoint, False if computed (and, when a
    path is given and ``save`` is True, checkpointed).  In a multi-process
    run every rank should RESTORE from the same path (so all ranks agree on
    the representative set even against a stale checkpoint) but only one
    rank should ``save``.

    The restore read is retried (transient disk blips) and a persistently
    corrupt checkpoint degrades to a rebuild + the corrupt/quarantine tally
    — it used to propagate the OSError and kill the run."""
    if path is not None:
        import os

        from ..utils import faults

        def _load():
            if os.path.exists(path):
                faults.check("artifact_read", path=path)
            return load_basis(path)

        try:
            got = faults.with_retries("artifact_read", _load)
        except OSError as e:
            from ..utils.artifacts import note_artifact_corrupt

            note_artifact_corrupt(path, "basis", e)
            got = None
        if got is not None:
            reps, norms = got
            basis.unchecked_set_representatives(reps, norms)
            return True
    basis.build()
    if path is not None and save:
        from ..utils.artifacts import note_artifact_ok

        try:
            save_basis(path, basis.representatives, basis.norms)
            note_artifact_ok(path)
        except OSError as e:
            # a corrupt pre-existing file refuses h5py appends too — move
            # it aside (it already failed its read above) and write fresh;
            # if even that fails, the checkpoint is lost but the run lives
            from ..utils.artifacts import quarantine_artifact
            from ..utils.logging import log_warn

            if quarantine_artifact(path, "basis", reason=repr(e)):
                try:
                    save_basis(path, basis.representatives, basis.norms)
                    note_artifact_ok(path)
                except OSError as e2:
                    log_warn(f"basis checkpoint save skipped: {e2!r}")
            else:
                log_warn(f"basis checkpoint save skipped: {e!r}")
    return False


def save_eigen(path: str, eigenvalues: np.ndarray,
               eigenvectors: Optional[np.ndarray] = None,
               residuals: Optional[np.ndarray] = None) -> None:
    """Write /hamiltonian/{eigenvalues,eigenvectors,residuals}
    (Diagonalize.chpl:248-256)."""
    h5 = _h5py()
    with h5.File(path, "a") as f:
        g = f.require_group("hamiltonian")
        for name in ("eigenvalues", "eigenvectors", "residuals"):
            if name in g:
                del g[name]
        g.create_dataset("eigenvalues", data=np.asarray(eigenvalues))
        if eigenvectors is not None:
            g.create_dataset("eigenvectors", data=np.asarray(eigenvectors))
        if residuals is not None:
            g.create_dataset("residuals", data=np.asarray(residuals))


def load_eigen(path: str):
    h5 = _h5py()
    with h5.File(path, "r") as f:
        g = f["hamiltonian"]
        return (
            g["eigenvalues"][...],
            g["eigenvectors"][...] if "eigenvectors" in g else None,
            g["residuals"][...] if "residuals" in g else None,
        )


def save_observables(path: str, values) -> dict:
    """Write ⟨ψ|O|ψ⟩ scalars under /observables (Diagonalize.chpl:276-279's
    output group).  ``values`` is a sequence of (name, value); duplicate
    names are disambiguated with a numeric suffix so no result is silently
    dropped.  Returns the name → value mapping actually written."""
    h5 = _h5py()
    written = {}
    for name, val in values:
        key, k = name, 2
        while key in written:
            key = f"{name}_{k}"
            k += 1
        written[key] = float(val)
    with h5.File(path, "a") as f:
        g = f.require_group("observables")
        for key, val in written.items():
            if key in g:
                del g[key]
            g.create_dataset(key, data=val)
    return written


def save_golden(path: str, representatives: np.ndarray, x: np.ndarray,
                y: np.ndarray) -> None:
    """Write a golden matvec file: /representatives, /x, /y=Hx — the layout
    the reference's generator emits (input_for_matvec.py:28-46) and its
    matvec test consumes (TestMatrixVectorProduct.chpl:25-59).  ``x``/``y``
    are stored as [k, N] batches (rank-1 input is promoted to k=1, matching
    the generator's transposed layout, :43-46)."""
    h5 = _h5py()
    x = np.atleast_2d(np.asarray(x))
    y = np.atleast_2d(np.asarray(y))
    with h5.File(path, "w") as f:
        f.create_dataset("representatives",
                         data=np.asarray(representatives, np.uint64))
        f.create_dataset("x", data=x)
        f.create_dataset("y", data=y)


def load_golden(path: str) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(representatives, x [k, N], y [k, N]) from a golden matvec file."""
    h5 = _h5py()
    with h5.File(path, "r") as f:
        return (f["representatives"][...].astype(np.uint64),
                np.atleast_2d(f["x"][...]), np.atleast_2d(f["y"][...]))
