"""HDF5 golden/checkpoint I/O (the MyHDF5.chpl layer)."""

from .hdf5 import (  # noqa: F401
    load_basis,
    load_eigen,
    make_or_restore_representatives,
    save_basis,
    save_eigen,
)
