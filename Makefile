# Test/bench harness — the analog of the reference Makefile's check targets
# (/root/reference/Makefile:79-126).  Everything runs from a plain checkout;
# no install step needed.

PYTHON ?= python

.PHONY: check check-fast check-solve smoke dryrun bench warm-cache clean

check:
	$(PYTHON) -m pytest tests/ -q

check-fast:
	$(PYTHON) -m pytest tests/ -q -x -k "not distributed and not reference"

check-solve:
	$(PYTHON) -m pytest tests/test_solve.py tests/test_reference_configs.py -q

smoke:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --smoke

dryrun:
	$(PYTHON) __graft_entry__.py

bench:
	$(PYTHON) bench.py

# Pre-build the artifact caches (basis / structure / XLA) for the bench
# configs so engine construction in later processes is seconds, not minutes.
warm-cache:
	$(PYTHON) tools/warm_cache.py --configs cpu

clean:
	find . -name '__pycache__' -type d -exec rm -rf {} + 2>/dev/null; true
	rm -f distributed_matvec_tpu/enumeration/_native_*.so
