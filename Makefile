# Test/bench harness — the analog of the reference Makefile's check targets
# (/root/reference/Makefile:79-126).  Everything runs from a plain checkout;
# no install step needed.

PYTHON ?= python

# obs-check scratch + gate (see tools/obs_report.py; threshold is the
# relative regression bound on the gated metrics)
OBS_CHECK_DIR ?= /tmp/dmt_obs_check
OBS_THRESHOLD ?= 0.2
# health-check gate: max relative probe overhead on chain-16 device_ms
HEALTH_THRESHOLD ?= 0.02

.PHONY: check check-fast check-solve smoke dryrun bench warm-cache \
	obs-check health-check mem-check stream-check fault-check \
	roofline-check compress-check trace-check pipeline-check \
	hybrid-check serve-check elastic-check dynamics-check tune-check \
	slo-check profile-check clean

check:
	$(PYTHON) -m pytest tests/ -q
	$(MAKE) obs-check
	$(MAKE) health-check
	$(MAKE) mem-check
	$(MAKE) stream-check
	$(MAKE) compress-check
	$(MAKE) roofline-check
	$(MAKE) pipeline-check
	$(MAKE) hybrid-check
	$(MAKE) trace-check
	$(MAKE) serve-check
	$(MAKE) dynamics-check
	$(MAKE) fault-check
	$(MAKE) elastic-check
	$(MAKE) tune-check
	$(MAKE) slo-check
	$(MAKE) profile-check

check-fast:
	$(PYTHON) -m pytest tests/ -q -x -k "not distributed and not reference"

check-solve:
	$(PYTHON) -m pytest tests/test_solve.py tests/test_reference_configs.py -q

smoke:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --smoke

dryrun:
	$(PYTHON) __graft_entry__.py

bench:
	$(PYTHON) bench.py

# Pre-build the artifact caches (basis / structure / XLA) for the bench
# configs so engine construction in later processes is seconds, not minutes.
warm-cache:
	$(PYTHON) tools/warm_cache.py --configs cpu

# CI perf gate: run the smoke bench with the telemetry sink ON, check the
# event stream summarizes (engine-init split, cache hit rates, solver
# traces), and fail if chain-16 device_ms regressed more than
# OBS_THRESHOLD against the recorded BENCH_DETAIL.json.  The fresh detail
# goes to a scratch path so the recorded artifact stays the baseline.
# NB: the baseline is wall-clock from the machine that recorded it — on
# markedly different hardware, re-record BENCH_DETAIL.json (make smoke) or
# raise OBS_THRESHOLD rather than chasing cross-machine timing noise.
# Wall-clock on a shared host is noisy, so the gate retries: a spurious
# spike passes on a later attempt, a GENUINE regression fails all three.
obs-check:
	rm -rf $(OBS_CHECK_DIR) && mkdir -p $(OBS_CHECK_DIR)
	@ok=1; for i in 1 2 3; do \
	  JAX_PLATFORMS=cpu DMT_OBS_DIR=$(OBS_CHECK_DIR)/run$$i \
	    $(PYTHON) bench.py --smoke \
	    --detail-out $(OBS_CHECK_DIR)/new$$i.json || exit 1; \
	  $(PYTHON) tools/obs_report.py summarize $(OBS_CHECK_DIR)/run$$i \
	    || exit 1; \
	  if $(PYTHON) tools/obs_report.py diff BENCH_DETAIL.json \
	      $(OBS_CHECK_DIR)/new$$i.json --config chain_16 \
	      --metric device_ms --threshold $(OBS_THRESHOLD); then \
	    ok=0; break; \
	  else \
	    echo "obs-check: attempt $$i gated as regressed; retrying" \
	      "(timing noise vs a genuine regression resolves by attempt 3)"; \
	  fi; \
	done; exit $$ok

# Memory-observability gate (tools/mem_check.py): chain-16 smoke run,
# asserting the device-memory ledger reconciles with ell_nbytes exactly
# and with the apply executable's memory_analysis() within tolerance,
# that the obs stream carries memory_ledger/memory_analysis events the
# capacity planner can read, and that a healthy run emits ZERO
# OOM/critical memory events.
mem-check:
	JAX_PLATFORMS=cpu $(PYTHON) tools/mem_check.py

# Streamed-mode gate (tools/stream_check.py): bit-identity of streamed vs
# fused applies (single + batch + <x,Hx>), exchange counters preserved, a
# direction-aware obs_report diff gate on the steady-state (second+)
# streamed speedup (retried — timing noise vs genuine regression resolves
# by attempt 3), DMT_ARTIFACT_CACHE=off pure host-RAM streaming with zero
# disk writes, and the plan sidecar save/restore round-trip.
stream-check:
	JAX_PLATFORMS=cpu $(PYTHON) tools/stream_check.py

# Compressed-plan-stream gate (tools/compress_check.py): lossless/f32
# codec round trip, the measured-error gate (lossless <= 1e-12 vs fused,
# measured 0.0; f32 <= 1e-6), off-tier bit-identity with bitpacked rok,
# the Pallas decode kernel (interpret) vs the XLA decode path, encoded
# plan bytes >= 2.5x smaller gated via `obs_report diff --phases`
# (phase_plan_h2d_bytes down, compute flat), and the PROGRESS.jsonl
# trend gate guarding compress_ratio.  Deterministic, ~40 s on CPU.
compress-check:
	JAX_PLATFORMS=cpu $(PYTHON) tools/compress_check.py

# Phase-attribution gate (tools/roofline_check.py): apply HLO
# byte-identity with phase probes on vs off (local ell + distributed
# fused), `obs_report roofline` model-vs-measured reconciliation on a
# live streamed run (phase walls sum to the measured apply wall within
# 10%, binding resource named, pipelined-apply estimate finite), and the
# bench_trend gate passing on an appended record AND firing on a
# synthetic 10x regression.  Deterministic, ~30 s on the CPU rig.
roofline-check:
	JAX_PLATFORMS=cpu $(PYTHON) tools/roofline_check.py

# Pipelined-apply gate (tools/pipeline_check.py): bit-identity of
# pipelined vs sequential applies (fused + streamed, single + k=3 batch,
# counters preserved), the PR-7 pipelined-apply estimate reconciling
# against the measured pipelined wall within 25% (retried for timing
# noise), a REAL 2-process run with a deterministic 8 ms/chunk staging
# latency injected on rank 1 showing the `report --ranks` time-at-barrier
# cut >= 2x with pipeline_depth=4 (the straggling rank's steady applies
# faster too), and the PROGRESS.jsonl trend gate firing on a synthetic
# barrier_ms regression.  Deterministic, ~45 s on the CPU rig.
pipeline-check:
	JAX_PLATFORMS=cpu $(PYTHON) tools/pipeline_check.py

# Hybrid-split gate (tools/hybrid_check.py, DESIGN.md §28): degenerate
# all-stream/all-recompute splits equal the existing streamed apply
# bit-for-bit (plan bytes equal / strictly below), a pinned mixed split
# stays bit-identical to pure streamed at pipeline depths {0, 2} with
# counters preserved, the auto split prices deterministically at the
# documented default rates (artifact cache off => no measured sidecar),
# single-chunk hybrid plans resolve pipeline auto to sequential,
# `obs_report diff --phases` shows plan_h2d bytes DOWN with the merged
# exchange/accumulate counts exactly flat, the offline per-term pricer
# reaches a genuine mix under the TPU rates (recommendation flips to
# hybrid when it beats both pure tiers; price_job prices hybrid specs),
# and the PROGRESS.jsonl trend gate fires on a synthetic 3x
# hybrid_plan_bytes regression.  Deterministic, ~45 s on the CPU rig.
hybrid-check:
	JAX_PLATFORMS=cpu $(PYTHON) tools/hybrid_check.py

# Tracing gate (tools/trace_check.py): apply HLO byte-identity with
# tracing on vs off (local ell; streamed result bit-identity rides
# along), DMT_OBS=off emits zero spans (provable no-op), a REAL 2-rank
# recorded run agrees on one trace id and exports a Perfetto JSON with
# balanced B/E pairs nesting chunk < apply < iteration < solve on both
# rank tracks, and `obs_report watch --once` renders a dashboard frame
# from it.  Deterministic, ~60 s on the CPU rig.
trace-check:
	JAX_PLATFORMS=cpu $(PYTHON) tools/trace_check.py

# Solve-service gate (tools/serve_check.py): a scripted bench.py --serve
# load-gen leg (8 mixed jobs, 3 bases) asserting per-job eigenvalues
# match sequential solo runs at rtol 1e-12, measured engine-pool sharing
# (builds < jobs), batched throughput beating solo (retried for timing
# noise), the obs_report watch queue panel rendering; a SIGTERM drain of
# a spool-backed apps/solve_service.py slowed via DMT_FAULT
# (exit 75, in-flight jobs respooled as queued, relaunch drains them —
# the job-level PR 6 checkpoint contract); and the bench_trend gate
# passing on the recorded serve metrics then FIRING on a synthetic 10x
# throughput/latency regression.  Deterministic seeds, ~90 s on CPU.
serve-check:
	JAX_PLATFORMS=cpu $(PYTHON) tools/serve_check.py

# Telemetry-plane gate (tools/slo_check.py, DESIGN.md §31): a clean
# chain-12 solve where the registry snapshot, a REAL ephemeral-port
# /metrics scrape, the textfile, and the events.jsonl metrics_snapshot
# agree EXACTLY (OpenMetrics parity) with zero SLO alerts; DMT_OBS=off
# binding no socket and writing nothing (provable no-op); a 6-job
# spool drained clean vs under DMT_FAULT=solver_block:delay — the SAME
# pinned serve_p99_latency_ms target passes then fails `obs_report slo`
# (exit 1) with slo_alert events in the burned stream; and a forced
# heartbeat stall (exit 76) leaving exactly one valid content-addressed
# post-mortem bundle naming the stuck chunk span (`obs_report
# postmortem` verifies).  Deterministic (the injected delay dwarfs
# scheduler noise), ~60 s on the CPU rig.
slo-check:
	JAX_PLATFORMS=cpu $(PYTHON) tools/slo_check.py

# Dynamics gate (tools/dynamics_check.py, DESIGN.md §29): KPM moments
# on a streamed chain_12 engine match the dense matrix's own Chebyshev
# recurrence at 1e-12 with the plan provably built ONCE (engine_init
# counted once across bounds + every moment), the Jackson-kernel DOS
# matches the exact spectrum through the SAME kernel within the
# stochastic tolerance, exp(-iHt) matches dense expm at rtol 1e-10
# with unitarity drift < 1e-12/step, the max_basis_size-capped
# thick-restart block Lanczos reaches the full-memory E0 at rtol
# 1e-12 with every restart inside the cap, a SIGTERMed mid-trajectory
# apps/dynamics.py run exits 75 and resumes bit-consistently, and the
# kpm_moments_per_s / evolve_steps_per_s trend gate passes then FIRES
# on a synthetic 10x regression.  Deterministic, ~25 s on the CPU rig.
dynamics-check:
	JAX_PLATFORMS=cpu $(PYTHON) tools/dynamics_check.py

# Chaos gate (tools/fault_check.py): the ROADMAP's resumed-run
# bit-consistency acceptance as a repeatable gate — kill a 2-device solve
# mid-iteration (SIGTERM → EXIT_PREEMPTED with a safe-point checkpoint;
# SIGKILL → cadence checkpoint), resume with the same argv, and assert the
# resumed E0 matches an uninterrupted run to rtol 1e-12; then inject each
# DMT_FAULT site (artifact read, checkpoint write/rename, exchange, plan
# upload, disk-tier plan-chunk read incl. a checksum-corrupt sidecar) and
# assert the documented retry/degrade/rebuild behavior, bit-identically.
# Deterministic seeds, < 90 s on the CPU rig.
fault-check:
	JAX_PLATFORMS=cpu $(PYTHON) tools/fault_check.py

# Elastic-solve gate (tools/elastic_check.py): topology-portable
# checkpoints on the 2↔4 virtual-device CPU rig — SIGKILL a 4-device
# solve mid-iteration and resume on 2 (and the reverse), resumed E0 ==
# uninterrupted E0 at rtol 1e-12 with a solver_checkpoint{resharded}
# event; a chain_16 solve rides a full shrink+grow cycle under a dumb
# supervisor with no operator intervention; matching-D restores stay
# reshard-free; an injected ckpt_reshard fault degrades the restore to a
# fresh (still-correct) solve; a SIGTERMed 2-device solve service drains
# its respooled jobs on 1 device with admission re-priced against the
# live capacity; streamed plans rebuilt at D′ emit plan_reshard; and
# resume_reshard_s / resume_rebuild_plan_s gate in bench_trend
# (pass on repeat, fire on a synthetic 10x regression).  ~90 s warm
# on CPU, up to ~4 min cold.
elastic-check:
	JAX_PLATFORMS=cpu $(PYTHON) tools/elastic_check.py

# Self-tuning gate (tools/tune_check.py, DESIGN.md §30): a 10x-wrong
# flop-rate calibration flips the static argmin, the live posterior
# converges measured-vs-priced to within 25% in <=4 windows and its
# re-search lands exactly on the correctly-calibrated rig's config; a
# REAL live-mode engine seeded with a poisoned tuned artifact under a
# 50x-optimistic calibration drifts at the first window close and
# re-keys ONLY one apply after a window boundary (never mid-apply),
# with every apply correct vs the dense reference and bit-identical
# per knob token; the learned posterior reaches tools/capacity.py
# (price_job rate_source == "posterior"); and the bench_trend gate
# passes on a repeat autotuned_steady_apply_ms record then FIRES on a
# synthetic 3x regression.  Isolated artifact root, deterministic,
# ~5 s on the CPU rig; retried for timing noise in the live leg.
tune-check:
	@ok=1; for i in 1 2 3; do \
	  if JAX_PLATFORMS=cpu $(PYTHON) tools/tune_check.py; then \
	    ok=0; break; \
	  else \
	    echo "tune-check: attempt $$i failed; retrying (live-leg" \
	      "timing noise vs a genuine break resolves by attempt 3)"; \
	  fi; \
	done; exit $$ok

# Continuous-profiling gate (tools/profile_check.py, DESIGN.md §32):
# every precompile() miss records an HLO cost profile whose phase
# buckets sum EXACTLY to the executable's cost_analysis() totals,
# content-addressed next to the XLA cache and round-tripping through
# load_profile; the apply HLO is byte-identical with
# DMT_PROFILE=sampled vs off; sampled trace windows at a cadence priced
# from the rig's own measured capture cost stay under the 2% overhead
# budget (re-priced and retried in-process — the capture stop cost is
# noisy on a shared host); `obs_report roofline` gains the hlo-ms third
# column summing to the measured wall; a forced bench_trend gate
# failure triggers a flight-recorder bundle naming the hottest ops; and
# tools/profile_diff.py passes on a self-diff then FIRES naming a
# synthetically 10x-regressed op in its top rows.  ~60 s on the CPU rig
# (the overhead leg must amortize real profiler captures).
profile-check:
	JAX_PLATFORMS=cpu $(PYTHON) tools/profile_check.py

# Numerical-health gate (tools/health_check.py): chain-16 smoke applies
# with probes on vs off in ONE process (same warm engine — cross-process
# wall-clock would measure cache state, not probe cost), asserting the
# probe overhead on device_ms stays under HEALTH_THRESHOLD and that a
# healthy probes-on Lanczos solve emits ZERO health warnings.  Retries
# live inside the tool (same noise rationale as obs-check above).
health-check:
	JAX_PLATFORMS=cpu $(PYTHON) tools/health_check.py \
	  --threshold $(HEALTH_THRESHOLD)

clean:
	find . -name '__pycache__' -type d -exec rm -rf {} + 2>/dev/null; true
	rm -f distributed_matvec_tpu/enumeration/_native_*.so
