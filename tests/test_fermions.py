"""Fermionic operators end-to-end: Jordan-Wigner algebra, spinless hopping
models, and spinful Hubbard — engines vs an independent dense reference.

The reference treats fermions through the same nonbranching-term kernels as
spins (particle type only changes dispatch — FFI.chpl:85-88, product
enumeration StatesEnumeration.chpl:225-255).  Here the production path is the
term compiler's JW atoms (``expression._fermion_atoms``); the trusted path is
``dense_ref.fermion_site_operator_matrix`` (explicit Z-string Kronecker
products, no shared algebra).
"""

import numpy as np
import pytest
import scipy.sparse as sp

from distributed_matvec_tpu.models.basis import (
    SpinfulFermionBasis,
    SpinlessFermionBasis,
)
from distributed_matvec_tpu.models.operator import Operator
from distributed_matvec_tpu.parallel.engine import LocalEngine

from dense_ref import fermion_site_operator_matrix

ATOL, RTOL = 1e-13, 1e-12


def term_table_matrix(op: Operator, n_bits: int) -> np.ndarray:
    """Full-space matrix from the *production* nonbranching terms via the
    slow per-state ``apply_int`` path (independent of the engine kernels)."""
    dim = 1 << n_bits
    h = np.zeros((dim, dim), dtype=np.complex128)
    for t in op.terms:
        for alpha in range(dim):
            v, beta = t.apply_int(alpha)
            if v != 0:
                h[beta, alpha] += v
    return h


def dense_restricted(h_full: sp.csr_matrix, states: np.ndarray) -> np.ndarray:
    idx = states.astype(np.int64)
    return np.asarray(h_full.todense())[np.ix_(idx, idx)]


# ---------------------------------------------------------------------------
# Algebra: the compiled terms reproduce the JW matrices exactly
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kind", ["c", "c+", "n"])
@pytest.mark.parametrize("site", [0, 1, 3])
def test_single_mode_operator_matches_jw_matrix(kind, site):
    n = 4
    basis = SpinlessFermionBasis(n)  # no particle-number restriction
    # subscripts are placeholders into the sites row (YAML schema)
    text = {"c": "c_0", "c+": "c†_0", "n": "c†_0 c_0"}[kind]
    op = Operator.from_expressions(basis, [(text, [[site]])])
    ours = term_table_matrix(op, n)
    ref = np.asarray(fermion_site_operator_matrix(n, kind, site).todense())
    np.testing.assert_allclose(ours, ref, atol=1e-14)


def test_canonical_anticommutation_relations():
    """{c_i, c†_j} = δ_ij, {c_i, c_j} = 0 — on the dense matrices built from
    the production term tables (4 modes, full Fock space)."""
    n = 4
    basis = SpinlessFermionBasis(n)

    def mat(text, site):
        return term_table_matrix(
            Operator.from_expressions(basis, [(text, [[site]])]), n)

    c = [mat("c_0", i) for i in range(n)]
    cd = [mat("c†_0", i) for i in range(n)]
    eye = np.eye(1 << n)
    for i in range(n):
        for j in range(n):
            anti = c[i] @ cd[j] + cd[j] @ c[i]
            np.testing.assert_allclose(
                anti, eye if i == j else 0 * eye, atol=1e-14,
                err_msg=f"{{c_{i}, c†_{j}}}")
            np.testing.assert_allclose(
                c[i] @ c[j] + c[j] @ c[i], 0 * eye, atol=1e-14,
                err_msg=f"{{c_{i}, c_{j}}}")


# ---------------------------------------------------------------------------
# Spinless fermions: tight-binding + interaction through the engines
# ---------------------------------------------------------------------------

def spinless_tV_chain(n: int, particles, t=1.0, V=2.0) -> Operator:
    """H = −t Σ (c†_i c_{i+1} + h.c.) + V Σ n_i n_{i+1} (open chain)."""
    basis = SpinlessFermionBasis(n, particles)
    bonds = [[i, i + 1] for i in range(n - 1)]
    return Operator.from_expressions(
        basis,
        [(f"-{t} (c†₀ c₁ + c†₁ c₀)", bonds), (f"{V} n₀ n₁", bonds)],
        name="tV_chain",
    )


@pytest.mark.parametrize("n,particles", [(4, 2), (5, 2), (6, 3), (5, None)])
@pytest.mark.parametrize("mode", ["ell", "fused"])
def test_spinless_engine_matches_dense(n, particles, mode, rng):
    op = spinless_tV_chain(n, particles)
    op.basis.build()
    assert op.is_hermitian and op.effective_is_real
    h_full = sp.csr_matrix((1 << n, 1 << n), dtype=np.complex128)
    for i in range(n - 1):
        hop = (fermion_site_operator_matrix(n, "c+", i)
               @ fermion_site_operator_matrix(n, "c", i + 1))
        h_full = h_full - (hop + hop.getH())
        h_full = h_full + 2.0 * (
            fermion_site_operator_matrix(n, "n", i)
            @ fermion_site_operator_matrix(n, "n", i + 1))
    h_ref = dense_restricted(h_full, op.basis.representatives)
    assert np.abs(h_ref.imag).max() < 1e-14

    x = rng.random(op.basis.number_states) - 0.5
    y_host = op.matvec_host(x)
    np.testing.assert_allclose(y_host, h_ref.real @ x, atol=ATOL, rtol=RTOL)

    eng = LocalEngine(op, batch_size=7, mode=mode)
    np.testing.assert_allclose(
        np.asarray(eng.matvec(x)), h_ref.real @ x, atol=ATOL, rtol=RTOL)


def test_spinless_distributed_engine(rng):
    from distributed_matvec_tpu.parallel.distributed import DistributedEngine

    op = spinless_tV_chain(6, 3)
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    y_ref = op.matvec_host(x)
    for mode in ("ell", "fused"):
        eng = DistributedEngine(op, n_devices=4, mode=mode)
        np.testing.assert_allclose(
            eng.matvec_global(x), y_ref, atol=ATOL, rtol=RTOL)


# ---------------------------------------------------------------------------
# Spinful fermions: Hubbard model; JW strings cross the ↑/↓ sector boundary
# ---------------------------------------------------------------------------

def hubbard(n_sites: int, n_up, n_down, t=1.0, U=4.0) -> Operator:
    """Hubbard chain on ``n_sites`` physical sites (2·n bits: low = ↑,
    high = ↓ — StatesEnumeration.chpl:225-255 sector layout)."""
    basis = SpinfulFermionBasis(n_sites, n_up, n_down)
    up = lambda i: i                    # noqa: E731
    dn = lambda i: n_sites + i          # noqa: E731
    hop_rows = []
    for i in range(n_sites - 1):
        hop_rows += [[up(i), up(i + 1)], [dn(i), dn(i + 1)]]
    int_rows = [[up(i), dn(i)] for i in range(n_sites)]
    return Operator.from_expressions(
        basis,
        [(f"-{t} (c†₀ c₁ + c†₁ c₀)", hop_rows), (f"{U} n₀ n₁", int_rows)],
        name="hubbard",
    )


@pytest.mark.parametrize("n,nu,nd", [(2, 1, 1), (3, 2, 1), (3, 1, 1)])
@pytest.mark.parametrize("mode", ["ell", "fused"])
def test_hubbard_engine_matches_dense(n, nu, nd, mode, rng):
    op = hubbard(n, nu, nd)
    op.basis.build()
    assert op.is_hermitian
    nb = 2 * n
    h_full = sp.csr_matrix((1 << nb, 1 << nb), dtype=np.complex128)
    for s in (0, n):  # spin sectors offset into the bit space
        for i in range(n - 1):
            hop = (fermion_site_operator_matrix(nb, "c+", s + i)
                   @ fermion_site_operator_matrix(nb, "c", s + i + 1))
            h_full = h_full - (hop + hop.getH())
    for i in range(n):
        h_full = h_full + 4.0 * (
            fermion_site_operator_matrix(nb, "n", i)
            @ fermion_site_operator_matrix(nb, "n", n + i))
    h_ref = dense_restricted(h_full, op.basis.representatives)

    x = rng.random(op.basis.number_states) - 0.5
    np.testing.assert_allclose(
        op.matvec_host(x), h_ref.real @ x, atol=ATOL, rtol=RTOL)
    eng = LocalEngine(op, mode=mode)
    np.testing.assert_allclose(
        np.asarray(eng.matvec(x)), h_ref.real @ x, atol=ATOL, rtol=RTOL)


def test_cross_sector_jw_string():
    """An ↑↔↓ mixing term c†_{0↑} c_{0↓}: its JW string spans the entire ↑
    sector — the sign convention the round-1 review called untested."""
    n = 2
    nb = 2 * n
    basis = SpinfulFermionBasis(n)  # no number restriction: full Fock space
    op = Operator.from_expressions(
        basis, [("c†₀ c₁ + c†₁ c₀", [[0, n + 0], [1, n + 1]])])
    ours = term_table_matrix(op, nb)
    ref = sp.csr_matrix((1 << nb, 1 << nb), dtype=np.complex128)
    for i in range(n):
        m = (fermion_site_operator_matrix(nb, "c+", i)
             @ fermion_site_operator_matrix(nb, "c", n + i))
        ref = ref + m + m.getH()
    np.testing.assert_allclose(ours, np.asarray(ref.todense()), atol=1e-14)


def test_hubbard_ground_state_energy():
    """2-site Hubbard at half filling: E₀ = (U − √(U² + 16t²))/2 analytically."""
    from distributed_matvec_tpu.solve.lanczos import lanczos

    t, U = 1.0, 4.0
    op = hubbard(2, 1, 1, t=t, U=U)
    op.basis.build()
    eng = LocalEngine(op)
    res = lanczos(eng.matvec, op.basis.number_states, k=1, max_iters=50,
                  seed=3)
    e_exact = (U - np.sqrt(U * U + 16 * t * t)) / 2
    np.testing.assert_allclose(res.eigenvalues[0], e_exact, atol=1e-10)


def test_fermion_yaml_config_round_trip(tmp_path, rng):
    """Fermionic bases are loadable from the YAML schema via the `particle`
    key (basis JSON dispatch parity, FFI.chpl:85-88) and the loaded
    Hamiltonian matches the programmatic one."""
    from distributed_matvec_tpu.models.yaml_io import load_config_from_yaml

    path = str(tmp_path / "tv.yaml")
    with open(path, "w") as f:
        f.write("""
basis: {particle: spinless_fermion, number_sites: 8, number_particles: 4}
hamiltonian:
  name: tV
  terms:
    - {expression: "-1.0 (c†₀ c₁ + c†₁ c₀)", sites: &b [[0,1],[1,2],[2,3],[3,4],[4,5],[5,6],[6,7]]}
    - {expression: "2.0 n₀ n₁", sites: *b}
""")
    cfg = load_config_from_yaml(path)
    assert isinstance(cfg.basis, SpinlessFermionBasis)
    cfg.basis.build()
    ref = spinless_tV_chain(8, 4, t=1.0, V=2.0)
    ref.basis.build()
    np.testing.assert_array_equal(cfg.basis.representatives,
                                  ref.basis.representatives)
    x = rng.random(cfg.basis.number_states) - 0.5
    np.testing.assert_allclose(cfg.hamiltonian.matvec_host(x),
                               ref.matvec_host(x), atol=1e-14, rtol=1e-13)

    # spinful dispatch
    path2 = str(tmp_path / "h.yaml")
    with open(path2, "w") as f:
        f.write("basis: {particle: spinful_fermion, number_sites: 3, "
                "number_up: 2, number_down: 1}\n")
    cfg2 = load_config_from_yaml(path2)
    from distributed_matvec_tpu.models.basis import SpinfulFermionBasis
    assert isinstance(cfg2.basis, SpinfulFermionBasis)
    cfg2.basis.build()
    assert cfg2.basis.number_states == 3 * 3  # C(3,2)*C(3,1)
