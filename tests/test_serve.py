"""Solve-service tests (serve/, DESIGN.md §26): spec keys + fingerprint
grouping, batch packing up to the block width, priced admission
accept/queue/reject against a synthetic calibration, LRU engine-pool
eviction under a byte budget, heterogeneous per-column convergence in
``lanczos_block`` (honest residuals across narrowing restarts),
end-to-end drains (in-memory and spooled), SIGTERM-drain requeue, the
watch queue panel, and the REAL 2-process leg where two same-basis jobs
provably share one engine build."""

import importlib.util
import json
import os

import numpy as np
import pytest

from distributed_matvec_tpu import obs
from distributed_matvec_tpu.serve import (DONE, EnginePool, JobQueue,
                                          JobSpec, REJECTED, Scheduler,
                                          SolveService, estimate_dimension,
                                          submit_to_spool)
from distributed_matvec_tpu.solve import lanczos_block
from distributed_matvec_tpu.utils import preempt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: synthetic rate calibration for admission tests — deterministic, no
#: gather_bound run needed
RATES = {"gather_rows_per_s": 1e8, "h2d_bytes_per_s": 1e9,
         "flops_per_s": 1e9, "exchange_bytes_per_s": 1e9,
         "backend": "cpu", "device_kind": "synthetic",
         "source": "synthetic"}


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _chain_spec(job_id, n=10, **kw):
    kw.setdefault("basis", {"number_spins": n, "hamming_weight": n // 2})
    kw.setdefault("tol", 1e-10)
    kw.setdefault("max_iters", 400)
    return JobSpec(job_id=job_id, **kw)


# ---------------------------------------------------------------------------
# specs


def test_spec_roundtrip_and_engine_key():
    a = _chain_spec("a", k=1)
    b = _chain_spec("b", k=3, tol=1e-6)          # solver targets differ
    c = _chain_spec("c", n=8)                    # basis differs
    d = _chain_spec("d", mode="fused")           # engine mode differs
    assert a.engine_key() == b.engine_key()
    assert a.engine_key() != c.engine_key()
    assert a.engine_key() != d.engine_key()
    back = JobSpec.from_json(a.to_json())
    assert back.engine_key() == a.engine_key()
    assert back.tol == a.tol and back.job_id == "a"
    # a spec needs exactly one model source
    with pytest.raises(ValueError):
        JobSpec(job_id="x")
    with pytest.raises(ValueError):
        JobSpec(job_id="x", basis={"number_spins": 4}, yaml="m.yaml")


def test_engine_key_tracks_yaml_content(tmp_path):
    """A yaml model is keyed by file CONTENT: an edited model must never
    hit the warm pool's engine for the old Hamiltonian."""
    path = str(tmp_path / "m.yaml")
    with open(path, "w") as f:
        f.write("basis: {number_spins: 8}\n")
    k1 = JobSpec(job_id="y1", yaml=path).engine_key()
    assert JobSpec(job_id="y2", yaml=path).engine_key() == k1
    with open(path, "w") as f:
        f.write("basis: {number_spins: 10}\n")
    assert JobSpec(job_id="y3", yaml=path).engine_key() != k1
    # ...and one spec's key is cached: grouping decisions stay
    # consistent even if the file changes while the job is queued
    s = JobSpec(job_id="y4", yaml=path)
    k4 = s.engine_key()
    with open(path, "w") as f:
        f.write("basis: {number_spins: 12}\n")
    assert s.engine_key() == k4


def test_spool_resubmission_runs_again(tmp_path):
    serve_dir = str(tmp_path / "spool")
    queue = JobQueue(serve_dir)
    sched = Scheduler(queue=queue, rates=None)
    submit_to_spool(serve_dir, _chain_spec("re1", n=8, k=1))
    assert sched.adopt_spool() == 1
    assert sched.drain(scan_spool=False) == 1
    assert len(queue.result("re1")["eigenvalues"]) == 1
    # the submitter overwrites the spec (same id, now k=2): the SAME
    # service instance must adopt and run it again, not serve the stale
    # terminal record forever
    submit_to_spool(serve_dir, _chain_spec("re1", n=8, k=2))
    assert sched.adopt_spool() == 1
    assert queue.status("re1") == "queued"
    sched.drain(scan_spool=False)
    rec = queue.result("re1")
    assert rec["status"] == "done" and len(rec["eigenvalues"]) == 2


def test_unreadable_spool_file_reported_once(tmp_path):
    serve_dir = str(tmp_path / "spool")
    queue = JobQueue(serve_dir)
    bad = os.path.join(serve_dir, "queue", "torn.json")
    with open(bad, "w") as f:
        f.write("{not json")
    before = len(obs.events("job_event"))
    for _ in range(3):
        assert queue.scan_spool() == 0
    evs = [e for e in obs.events("job_event")[before:]
           if e.get("status") == "unreadable"]
    assert len(evs) == 1
    # a rewritten (changed) file is re-examined
    with open(bad, "w") as f:
        f.write(_chain_spec("torn", n=8).to_json())
    assert queue.scan_spool() == 1


def test_column_seed_deterministic():
    assert _chain_spec("a").column_seed() == _chain_spec("a").column_seed()
    assert _chain_spec("a").column_seed() != _chain_spec("b").column_seed()
    assert _chain_spec("a", seed=7).column_seed() == 7


def test_estimate_dimension():
    assert estimate_dimension({"number_spins": 10, "hamming_weight": 5}) \
        == 252
    assert estimate_dimension({"number_spins": 4}) == 16
    red = estimate_dimension({"number_spins": 10, "hamming_weight": 5,
                              "spin_inversion": 1})
    assert red == 126


# ---------------------------------------------------------------------------
# capacity pricing (tools/capacity.price_job — the importable API)


def test_price_job_estimates_and_fits():
    cap = _load_tool("capacity")
    small = _chain_spec("s", k=2).pricing()
    out = cap.price_job(small, calibration=RATES, hbm_gb=16.0)
    assert out["fits"] and out["priced"]
    assert out["est_apply_ms"] is not None and out["est_apply_ms"] >= 0
    assert out["est_solve_s"] == pytest.approx(
        out["est_apply_ms"] * out["est_iters"] / 1e3, abs=5e-4)
    # iteration model capped by the spec's own budget
    assert out["est_iters"] == min(cap.EST_COLUMNS_PER_EIGENPAIR * 2, 400)
    # without a calibration the memory verdict still lands
    out2 = cap.price_job(small, calibration=None, hbm_gb=16.0)
    assert out2["fits"] and out2["est_apply_ms"] is None


def test_price_job_reject_and_unpriced():
    cap = _load_tool("capacity")
    huge = _chain_spec("h", n=64).pricing()      # C(64,32) ~ 1.8e18 rows
    out = cap.price_job(huge, calibration=RATES, hbm_gb=16.0)
    assert not out["fits"] and "device" in out["reason"]
    # yaml submissions have no dimension before the basis builds —
    # admission stays optimistic, explicitly marked unpriced
    y = JobSpec(job_id="y", yaml="/tmp/nonexistent.yaml")
    out3 = cap.price_job(y.pricing(), calibration=RATES)
    assert out3["fits"] and not out3["priced"]


# ---------------------------------------------------------------------------
# admission


def test_admission_accept_queue_reject(tmp_path):
    sched = Scheduler(queue=JobQueue(), rates=RATES, hbm_gb=16.0,
                      accept_horizon_s=0.0)
    v1 = sched.submit(_chain_spec("j1", n=16))
    assert v1["verdict"] == "accept" and v1["eta_s"] == 0.0
    # backlog now carries j1's priced est_solve_s: the horizon of 0 puts
    # every later job behind it -> verdict "queue" with the priced ETA
    v2 = sched.submit(_chain_spec("j2", n=16))
    assert v2["verdict"] == "queue" and v2["eta_s"] > 0.0
    assert sched.queue.status("j2") == "queued"
    # a job that cannot fit the device budget is rejected terminally
    v3 = sched.submit(_chain_spec("j3", n=64))
    assert v3["verdict"] == "reject"
    assert sched.queue.status("j3") == REJECTED
    assert "reason" in sched.queue.result("j3")
    # a deadline the priced finish cannot meet is also a reject
    v4 = sched.submit(_chain_spec("j4", n=16, deadline_s=1e-9))
    assert v4["verdict"] == "reject"
    assert "deadline" in sched.queue.result("j4")["reason"]


# ---------------------------------------------------------------------------
# grouping + packing


def test_fingerprint_grouping_and_packing():
    sched = Scheduler(queue=JobQueue(), rates=None, block_width=2)
    order = []
    for i, n in enumerate((10, 10, 8, 10, 8, 10)):
        s = _chain_spec(f"j{i}", n=n)
        s.submit_ts = 100.0 + i          # deterministic FIFO order
        sched.queue.submit(s)
        order.append((s.job_id, s.engine_key()))
    b1 = sched.next_batch()
    # the earliest-submitted group (chain_10) goes first, packed to the
    # block width in (submit_ts, job_id) order
    assert [s.job_id for s in b1] == ["j0", "j1"]
    assert len({s.engine_key() for s in b1}) == 1
    for s in b1:
        sched.queue.finish(s, DONE)
    b2 = sched.next_batch()
    assert [s.job_id for s in b2] == ["j2", "j4"]   # chain_8 head is older
    for s in b2:
        sched.queue.finish(s, DONE)
    assert [s.job_id for s in sched.next_batch()] == ["j3", "j5"]


# ---------------------------------------------------------------------------
# engine pool


class _FakeEngine:
    def __init__(self, nbytes):
        self.ell_nbytes = int(nbytes)


def test_pool_lru_eviction_under_byte_budget():
    built = []

    def builder(spec):
        built.append(spec.job_id)
        return _FakeEngine(4 * 1024)

    pool = EnginePool(max_bytes=10 * 1024, builder=builder)
    s1, s2, s3 = (_chain_spec("p1", n=8), _chain_spec("p2", n=10),
                  _chain_spec("p3", n=12))
    e1 = pool.acquire(s1)
    assert pool.acquire(s1) is e1            # hit, no rebuild
    assert built == ["p1"] and pool.hits == 1
    pool.acquire(s2)
    assert pool.total_bytes() == 8 * 1024 and len(pool) == 2
    pool.acquire(s1)                         # refresh p1's recency
    pool.acquire(s3)                         # 12 KB > budget -> evict LRU
    assert pool.evictions == 1
    assert s2.engine_key() not in pool       # p2 was least recent
    assert s1.engine_key() in pool and s3.engine_key() in pool
    # a rebuilt evictee counts a new build — engine_init once per
    # residency, not once per key forever
    pool.acquire(s2)
    assert built == ["p1", "p2", "p3", "p2"]


def test_pool_single_oversized_engine_survives_its_own_insert():
    pool = EnginePool(max_bytes=1, builder=lambda s: _FakeEngine(1 << 20))
    eng = pool.acquire(_chain_spec("big", n=8))
    assert len(pool) == 1 and pool.acquire(_chain_spec("big", n=8)) is eng
    # ...and is evicted by the NEXT insertion
    pool.acquire(_chain_spec("other", n=10))
    assert len(pool) == 1 and pool.evictions == 1


# ---------------------------------------------------------------------------
# heterogeneous per-column convergence (solve/lanczos.py)


def _dense_mv(n=60, seed=0):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((n, n))
    A = (A + A.T) / 2
    return A, (lambda x: A @ x)


def test_column_targets_honest_convergence():
    A, mv = _dense_mv()
    ev = np.linalg.eigvalsh(A)
    targets = [{"k": 1, "tol": 1e-12, "job_id": "tight"},
               {"k": 2, "tol": 1e-7, "job_id": "mid"},
               {"k": 1, "tol": 1e-4, "job_id": "loose"}]
    res = lanczos_block(mv, n=A.shape[0], column_targets=targets,
                        max_iters=600)
    assert res.converged and res.column_results is not None
    by = {cr["job_id"]: cr for cr in res.column_results}
    assert set(by) == {"tight", "mid", "loose"}
    # every target converged against ITS OWN tolerance, and the claimed
    # residual is honest: the true eigenvalue error respects the
    # quadratic bound even across narrowing restarts (the naive
    # column-truncation this replaces measured 1e-6 errors on 1e-10
    # claims)
    for cr in res.column_results:
        assert cr["converged"]
        assert len(cr["eigenvalues"]) == cr["k"]
        assert np.all(cr["residuals"]
                      < cr["tol"] * np.maximum(1, np.abs(cr["eigenvalues"])))
    assert abs(by["tight"]["eigenvalues"][0] - ev[0]) \
        < 1e-10 * abs(ev[0])
    # the loose job exited earlier than the tight one
    assert by["loose"]["iters"] <= by["tight"]["iters"]
    # the exits narrowed the block through at least one restart
    narrows = obs.events("solver_restart_narrow")
    assert narrows and narrows[-1]["new_width"] < narrows[-1]["width"]


def test_column_targets_eigenvectors_across_restarts():
    A, mv = _dense_mv(n=40, seed=3)
    targets = [{"k": 1, "tol": 1e-10, "job_id": "a"},
               {"k": 1, "tol": 1e-4, "job_id": "b"}]
    res = lanczos_block(mv, n=40, column_targets=targets, max_iters=400,
                        compute_eigenvectors=True)
    for cr in res.column_results:
        assert cr["converged"]
        v = np.asarray(cr["eigenvectors"][0])
        w = cr["eigenvalues"][0]
        # the materialized vector reproduces its snapshot's residual
        # claim (the "b" vector predates a narrowing restart and was
        # assembled before the restart dropped its blocks)
        assert np.linalg.norm(A @ v - w * v) \
            < 10 * cr["tol"] * max(1, abs(w))


def test_column_target_budget_exhaustion_exits_unconverged():
    """A batched job's OWN max_iters is enforced: its column exits
    unconverged at its budget instead of riding the batch to the widest
    job's budget (a batch must never bill a job more columns than its
    spec — and its admission pricing — allowed)."""
    A, mv = _dense_mv(n=50, seed=2)
    targets = [{"k": 1, "tol": 1e-14, "max_iters": 8, "job_id": "tiny"},
               {"k": 1, "tol": 1e-8, "job_id": "full"}]
    res = lanczos_block(mv, n=50, column_targets=targets, max_iters=400)
    by = {cr["job_id"]: cr for cr in res.column_results}
    assert not by["tiny"]["converged"]
    assert by["tiny"]["iters"] <= 8
    assert by["full"]["converged"]
    assert not res.converged          # not every target converged


def test_spool_write_failure_does_not_resolve_forever(tmp_path):
    """A failed done/-write (full disk) must NOT leave the job's queue/
    file to be re-adopted as a resubmission — the service would re-solve
    it in a loop.  The record stays pending and the move is retried on
    later scans."""
    serve_dir = str(tmp_path / "spool")
    queue = JobQueue(serve_dir)
    sched = Scheduler(queue=queue, rates=None)
    submit_to_spool(serve_dir, _chain_spec("wf1", n=8))
    sched.adopt_spool()
    ddir = os.path.join(serve_dir, "done")
    os.rmdir(ddir)
    with open(ddir, "w") as f:        # done/ now a FILE: writes fail
        f.write("x")
    assert sched.drain(scan_spool=False) == 1
    assert queue.status("wf1") == "done"
    # the queue/ file stays (crash-safety net) but is NOT re-adopted
    assert os.path.exists(os.path.join(serve_dir, "queue", "wf1.json"))
    assert sched.adopt_spool() == 0
    assert queue.status("wf1") == "done"
    # heal the spool: the next scan retries and completes the move
    os.remove(ddir)
    os.makedirs(ddir)
    assert sched.adopt_spool() == 0
    assert os.path.exists(os.path.join(ddir, "wf1.json"))
    assert not os.path.exists(os.path.join(serve_dir, "queue",
                                           "wf1.json"))


def test_column_targets_default_path_unchanged():
    A, mv = _dense_mv(n=30, seed=1)
    res = lanczos_block(mv, n=30, k=2, tol=1e-10, max_iters=300)
    assert res.column_results is None
    ev = np.linalg.eigvalsh(A)
    assert np.allclose(res.eigenvalues, ev[:2], rtol=1e-10)


def test_column_targets_validation():
    _, mv = _dense_mv(n=20)
    with pytest.raises(ValueError):
        lanczos_block(mv, n=20, column_targets=[])
    with pytest.raises(ValueError):
        lanczos_block(mv, n=20, block_size=2,
                      column_targets=[{"k": 1}] * 3)


# ---------------------------------------------------------------------------
# end-to-end drain


def test_drain_end_to_end_shares_engines_and_matches_solo():
    from distributed_matvec_tpu.serve.pool import build_engine

    queue, pool = JobQueue(), EnginePool()
    sched = Scheduler(queue=queue, pool=pool, rates=None)
    specs = [_chain_spec("e1", n=10, k=1),
             _chain_spec("e2", n=10, k=2, tol=1e-9),
             _chain_spec("e3", n=10, k=1, tol=1e-8),
             _chain_spec("e4", n=8, k=1)]
    for s in specs:
        assert sched.submit(s)["verdict"] == "accept"
    assert sched.drain(scan_spool=False) == 4
    # 2 distinct bases -> 2 engine builds for 4 jobs: measured sharing
    assert pool.builds == 2
    for s in specs:
        rec = queue.result(s.job_id)
        assert rec["status"] == "done" and rec["converged"]
        assert rec["latency_ms"] > 0 and rec["batch_width"] >= 1
        eng = build_engine(s)
        solo = lanczos_block(eng.matvec, n=eng.n_states, k=s.k, tol=s.tol,
                             max_iters=s.max_iters, seed=s.column_seed())
        for w_b, w_s in zip(rec["eigenvalues"], solo.eigenvalues):
            assert abs(w_b - w_s) <= 1e-12 * abs(w_s)


def test_spool_roundtrip_and_service_drain(tmp_path):
    serve_dir = str(tmp_path / "spool")
    for i in range(3):
        submit_to_spool(serve_dir, _chain_spec(f"sp{i}", n=8))
    assert len(os.listdir(os.path.join(serve_dir, "queue"))) == 3
    svc = SolveService(serve_dir, rates=None)
    assert svc.run(drain=True) == 0
    assert os.listdir(os.path.join(serve_dir, "queue")) == []
    done = sorted(os.listdir(os.path.join(serve_dir, "done")))
    assert done == ["sp0.json", "sp1.json", "sp2.json"]
    with open(os.path.join(serve_dir, "done", "sp0.json")) as f:
        rec = json.load(f)
    assert rec["status"] == "done" and rec["spec"]["job_id"] == "sp0"
    assert np.isfinite(rec["eigenvalues"][0])


def test_sigterm_drain_requeues_in_flight(tmp_path):
    """A latched preemption signal drains the service at the next safe
    point: run() returns 75 and every unfinished job's spool file is
    still under queue/ — a relaunch resumes the undone work."""
    serve_dir = str(tmp_path / "spool")
    for i in range(2):
        submit_to_spool(serve_dir, _chain_spec(f"pre{i}", n=10))
    svc = SolveService(serve_dir, rates=None)
    preempt.trigger()                   # the latch a SIGTERM would set
    try:
        rc = svc.run(drain=True)
    finally:
        preempt.reset()
    assert rc == preempt.EXIT_PREEMPTED
    # nothing finished; both specs still spooled as queued
    assert sorted(os.listdir(os.path.join(serve_dir, "queue"))) \
        == ["pre0.json", "pre1.json"]
    assert os.listdir(os.path.join(serve_dir, "done")) == []
    # relaunch (fresh latch) drains them
    assert SolveService(serve_dir, rates=None).run(drain=True) == 0
    assert sorted(os.listdir(os.path.join(serve_dir, "done"))) \
        == ["pre0.json", "pre1.json"]


class _PreemptingEngine:
    """Dense stand-in engine whose matvec latches a preemption after a
    few applies — the signal lands MID-BATCH, so the solver's
    block-boundary safe point is what surfaces it."""

    def __init__(self, n=24, seed=5, at_call=3):
        rng = np.random.default_rng(seed)
        A = rng.standard_normal((n, n))
        self.A = (A + A.T) / 2
        self.n_states = n
        self.calls = 0
        self.at_call = at_call

    def matvec(self, X):
        self.calls += 1
        if self.calls == self.at_call:
            preempt.trigger()
        return self.A @ X


def test_mid_solve_preemption_requeues_batch():
    """Preempted raised INSIDE a batch (the solver's block-boundary safe
    point, PR 6 machinery) requeues the whole batch instead of losing
    it."""
    queue = JobQueue()
    pool = EnginePool(builder=lambda s: _PreemptingEngine())
    sched = Scheduler(queue=queue, pool=pool, rates=None)
    sched.submit(_chain_spec("mid1", n=10))
    sched.submit(_chain_spec("mid2", n=10))
    try:
        with pytest.raises(preempt.Preempted):
            sched.drain(scan_spool=False)
    finally:
        preempt.reset()
    assert {s.job_id for s in queue.queued()} == {"mid1", "mid2"}
    assert queue.running() == []


def test_failed_batch_marks_jobs_failed_not_crashing():
    queue = JobQueue()
    pool = EnginePool(builder=lambda s: (_ for _ in ()).throw(
        RuntimeError("boom")))
    sched = Scheduler(queue=queue, pool=pool, rates=None)
    sched.submit(_chain_spec("f1", n=8))
    assert sched.drain(scan_spool=False) == 1
    rec = queue.result("f1")
    assert rec["status"] == "failed" and "boom" in rec["error"]


# ---------------------------------------------------------------------------
# telemetry: job events, spans, watch panel


def test_job_events_and_per_job_spans():
    before = len(obs.events("job_event"))
    spans_before = len([e for e in obs.events("span")
                        if e.get("cat") == "job"])
    sched = Scheduler(queue=JobQueue(), rates=None)
    sched.submit(_chain_spec("t1", n=8))
    sched.drain(scan_spool=False)
    evs = obs.events("job_event")[before:]
    statuses = [e["status"] for e in evs if e.get("job_id") == "t1"]
    assert statuses == ["queued", "running", "done"]
    # every lifecycle event envelope-stamped with the job's own id
    if obs.trace_id() is not None:
        assert all(e.get("job_id") == "t1" for e in evs)
    job_spans = [e for e in obs.events("span")
                 if e.get("cat") == "job"][spans_before:]
    assert len(job_spans) == 1
    assert job_spans[0]["name"] == "job:t1"
    assert job_spans[0]["dur_ms"] > 0
    # the job span is a CHILD of its batch's span in the trace tree
    batch_spans = [e for e in obs.events("span") if e.get("cat") == "batch"]
    assert job_spans[0]["parent_span_id"] \
        in {e["span_id"] for e in batch_spans}


def test_watch_queue_panel_renders_and_stays_out_of_plain_runs():
    rep = _load_tool("obs_report")
    base = [{"seq": 0, "ts": 1.0, "rank": 0, "n_ranks": 1,
             "kind": "matvec_apply", "engine": "local", "wall_ms": 1.0,
             "bytes": 0}]
    frame = rep.watch_frame(base)
    assert "serve" not in frame and "pool" not in frame
    evs = base + [
        {"seq": 1, "ts": 2.0, "rank": 0, "kind": "job_event",
         "job_id": "w1", "status": "done"},
        {"seq": 2, "ts": 2.1, "rank": 0, "kind": "job_event",
         "job_id": "w2", "status": "running"},
        {"seq": 3, "ts": 2.2, "rank": 0, "kind": "admission",
         "job_id": "w2", "verdict": "accept", "eta_s": 0.0},
        {"seq": 4, "ts": 2.3, "rank": 0, "kind": "admission",
         "job_id": "w3", "verdict": "reject"},
        {"seq": 5, "ts": 2.4, "rank": 0, "kind": "engine_pool",
         "event": "build", "engines": 2, "pool_bytes": 1 << 20,
         "pool_max_bytes": 1 << 30, "builds": 2, "hits": 3,
         "evictions": 1},
    ]
    frame = rep.watch_frame(evs)
    assert "serve     2 job(s): 1 running, 1 done" in frame
    assert "accept 1" in frame and "reject 1" in frame
    assert "pool      2 engine(s)" in frame
    assert "builds 2, hits 3, evictions 1" in frame


def test_scheduler_adopts_spool_and_rejects_unfit(tmp_path):
    serve_dir = str(tmp_path / "spool")
    submit_to_spool(serve_dir, _chain_spec("ok", n=8))
    submit_to_spool(serve_dir, _chain_spec("nofit", n=64))
    sched = Scheduler(queue=JobQueue(serve_dir), rates=RATES, hbm_gb=16.0)
    assert sched.adopt_spool() == 2
    assert sched.queue.status("nofit") == REJECTED
    assert sched.queue.status("ok") == "queued"
    # the rejection is terminal on disk too
    assert os.path.exists(os.path.join(serve_dir, "done", "nofit.json"))


# ---------------------------------------------------------------------------
# the REAL 2-process leg


@pytest.mark.skipif(os.cpu_count() == 1,
                    reason="two 4-device ranks wedge XLA's intra-process "
                           "collective rendezvous on a 1-CPU host (3/4 "
                           "participants arrive, the solve never returns)")
def test_multihost_serve_two_ranks(tmp_path):
    """2-process run (multihost worker harness, serve leg): two
    same-basis jobs drained through a rank-local-mesh engine pool share
    ONE engine build per rank — engine_init counted once — with both
    jobs' E0 asserted in the worker."""
    import socket
    import subprocess
    import sys as _sys

    rep = _load_tool("obs_report")
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    run = tmp_path / "serve_run"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["DMT_MH_SERVE"] = "1"
    env["DMT_OBS_DIR"] = str(run)
    procs = [subprocess.Popen(
        [_sys.executable, worker, str(pid), "2", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid}:\n{out[-2000:]}"
        assert f"[p{pid}] SERVE_OK builds=1 hits=1" in out, out[-2000:]
        assert f"[p{pid}] MULTIHOST_OK" in out, out[-2000:]

    events = rep.load_events(str(run))
    for r in (0, 1):
        inits = [e for e in events if e["rank"] == r
                 and e["kind"] == "engine_init"]
        # ONE engine build on each rank for the two jobs — the pool
        # sharing the satellite demands, read from the telemetry the
        # same way the acceptance criterion words it
        assert len(inits) == 1, [e.get("engine") for e in inits]
        done = [e for e in events if e["rank"] == r
                and e["kind"] == "job_event" and e["status"] == "done"]
        assert {e.get("job_id") for e in done} == {"mh0", "mh1"}
        pool_evs = [e for e in events if e["rank"] == r
                    and e["kind"] == "engine_pool"]
        assert [e["event"] for e in pool_evs].count("build") == 1
        assert [e["event"] for e in pool_evs].count("hit") == 1
