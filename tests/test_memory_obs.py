"""Memory observability (obs/memory.py): device-memory ledger, watermark
sampler, compiled-executable analysis, OOM forensics, the capacity planner,
and the ``ell_nbytes`` parity contract.

Runs on the CPU backend, where ``device.memory_stats()`` is None — the
watermark paths are exercised through their soft-fail contract; ledger and
executable analysis carry the load (the advisory mode DESIGN.md §19
documents).
"""

import importlib.util
import json
import os
import sys

import numpy as np
import pytest

from distributed_matvec_tpu import obs
from distributed_matvec_tpu.obs import memory as obs_mem

from test_operator import build_heisenberg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def clean_obs():
    obs.reset_all()
    yield
    obs.reset_all()


@pytest.fixture
def obs_off(monkeypatch):
    monkeypatch.setenv("DMT_OBS", "off")


# ---------------------------------------------------------------------------
# ledger


def test_ledger_track_tree_total_release(clean_obs):
    h = obs_mem.track("engine/local:0/structure/idx", 1000, device="hbm")
    obs_mem.track("engine/local:0/structure/coeff", 2000, handle=h)
    obs_mem.track("engine/local:0/diag", 500, handle=h)
    h2 = obs_mem.track("solver/lanczos:0/krylov_basis", 4000)
    assert obs_mem.ledger_total() == 7500
    assert obs_mem.ledger_total("engine/local:0/structure") == 3000
    assert obs_mem.ledger_total("engine") == 3500
    tree = obs_mem.ledger_tree()
    assert tree["bytes"] == 7500
    assert tree["children"]["engine"]["bytes"] == 3500
    assert tree["children"]["engine"]["children"]["local:0"][
        "children"]["structure"]["bytes"] == 3000
    # re-track replaces (a rebuilt table supersedes), set() re-points
    obs_mem.track("engine/local:0/structure/idx", 1500, handle=h)
    assert obs_mem.ledger_total("engine/local:0/structure") == 3500
    h2.set("solver/lanczos:0/krylov_basis", 8000)
    assert obs_mem.ledger_total("solver") == 8000
    h.release()
    assert obs_mem.ledger_total() == 8000
    h.release()                                     # idempotent
    h2.release()
    assert obs_mem.ledger_total() == 0
    # ledger events carry the entry map + total
    obs_mem.track("a/b", 7)
    ev = obs_mem.emit_ledger("unit", n_states=3)
    assert ev["kind"] == "memory_ledger" and ev["total_bytes"] == 7
    assert ev["entries"]["a/b"]["bytes"] == 7 and ev["n_states"] == 3


def test_ledger_track_tree_sums_pytree_leaves(clean_obs):
    import jax.numpy as jnp

    tree = {"a": jnp.zeros(10, jnp.float64),
            "b": (jnp.zeros(4, jnp.int32), jnp.zeros(2, jnp.float64))}
    obs_mem.track_tree("x/t", tree)
    assert obs_mem.ledger_total("x") == 80 + 16 + 16


def test_ledger_disabled_noop(clean_obs, obs_off):
    h = obs_mem.track("a/b", 100)
    assert h is obs_mem.NULL_HANDLE
    assert obs_mem.track_tree("a/c", {}) is obs_mem.NULL_HANDLE
    assert obs_mem.ledger_total() == 0
    assert obs_mem.emit_ledger("unit") is None
    assert obs.events() == []


# ---------------------------------------------------------------------------
# watermark sampler (CPU: soft-fail/advisory contract)


def test_watermark_soft_fail_on_cpu(clean_obs):
    """The CPU client has no memory_stats: the sampler returns None, emits
    nothing, latches unsupported (so the per-apply cadence goes quiet),
    and never raises."""
    assert obs_mem.sample_watermark("unit") is None
    assert obs.events("memory_watermark") == []
    assert obs_mem.last_watermark() is None
    # latched: watermark_due is False even on the cadence boundary
    assert obs_mem.watermark_due(0) is False
    assert obs.snapshot()["gauges"] == {}


def test_watermark_due_cadence_and_disabled(clean_obs, monkeypatch):
    from distributed_matvec_tpu.utils.config import get_config, update_config

    # pretend the backend supports stats (the latch is what CPU flips)
    monkeypatch.setattr(obs_mem, "_wm_unsupported", False)
    saved = get_config().memory_every
    update_config(memory_every=4)
    try:
        assert [i for i in range(9) if obs_mem.watermark_due(i)] == [0, 4, 8]
    finally:
        update_config(memory_every=saved)
    monkeypatch.setenv("DMT_OBS", "off")
    assert obs_mem.watermark_due(0) is False


def test_watermark_event_shape_with_fake_stats(clean_obs, monkeypatch):
    """With stats available (faked — the CPU backend has none), the sample
    publishes rank-tagged events + gauges and feeds last_watermark."""
    rows = [{"device": "tpu:0", "bytes_in_use": 100, "peak_bytes_in_use": 250,
             "bytes_limit": 1000}]
    monkeypatch.setattr(obs_mem, "_device_stats", lambda: rows)
    s = obs_mem.sample_watermark("engine_init/local", extra=1)
    assert s["bytes_in_use"] == 100 and s["peak_bytes"] == 250
    ev = obs.events("memory_watermark")[-1]
    assert ev["tag"] == "engine_init/local" and ev["rank"] == 0
    assert ev["peak_bytes"] == 250 and ev["extra"] == 1
    snap = obs.snapshot()["gauges"]
    assert snap["hbm_bytes_in_use"] == 100
    assert snap["hbm_peak_bytes"] == 250
    assert obs_mem.last_watermark()["peak_bytes"] == 250


# ---------------------------------------------------------------------------
# ell_nbytes parity: reported totals == summed nbytes of live table leaves
# for EVERY engine mode (the hand-maintained totals this PR derives from
# structure_arrays(); these tests hand-enumerate the expected leaves so a
# new table added without registration fails loudly)


def _leaf_bytes(tree):
    import jax

    return sum(int(a.nbytes) for a in jax.tree_util.tree_leaves(tree))


@pytest.mark.parametrize("mode", ["ell", "compact", "fused"])
def test_local_ell_nbytes_parity(clean_obs, mode):
    from distributed_matvec_tpu.parallel.engine import LocalEngine

    op = build_heisenberg(10, 5, None, ())
    eng = LocalEngine(op, mode=mode)
    if mode == "ell":
        expected = eng._ell_idx.nbytes + eng._ell_coeff.nbytes
        if eng._ell_tail is not None:
            expected += sum(a.nbytes for a in eng._ell_tail)
    elif mode == "compact":
        expected = (eng._c_idx.nbytes + eng._c_inv_n.nbytes
                    + eng._c_n_parts.nbytes)
        if eng._c_tail is not None:
            expected += sum(a.nbytes for a in eng._c_tail)
    else:
        expected = 0
    assert eng.ell_nbytes == expected
    assert _leaf_bytes(eng.structure_arrays()) == expected
    # and the ledger registered exactly those bytes under structure/
    assert obs.ledger_total(
        f"engine/{eng._mem_instance}/structure") == expected


@pytest.mark.parametrize("mode", ["ell", "compact", "fused"])
def test_distributed_ell_nbytes_parity(clean_obs, mode):
    from distributed_matvec_tpu.parallel.distributed import DistributedEngine

    op = build_heisenberg(10, 5, None, ())
    eng = DistributedEngine(op, n_devices=4, mode=mode, batch_size=64)
    if mode == "ell":
        expected = (eng._ell_idx.nbytes + eng._ell_coeff.nbytes
                    + eng._qin.nbytes)
        if eng._ell_tail is not None:
            expected += sum(a.nbytes for a in eng._ell_tail)
    elif mode == "compact":
        # includes the derived norm tables the pre-PR hand-maintained
        # total silently dropped (it reported 0 for compact)
        expected = (eng._c_idx.nbytes + eng._qin.nbytes
                    + eng._c_inv_n.nbytes + eng._c_n_parts.nbytes
                    + eng._c_norms.nbytes)
        if eng._c_tail is not None:
            expected += sum(a.nbytes for a in eng._c_tail)
    else:
        expected = 0
    assert eng.ell_nbytes == expected
    assert _leaf_bytes(eng.structure_arrays()) == expected
    assert obs.ledger_total(
        f"engine/{eng._mem_instance}/structure") == expected


# ---------------------------------------------------------------------------
# engine integration: ledger registration, planner context, analyses


def test_engine_init_emits_ledger_with_planner_context(clean_obs):
    from distributed_matvec_tpu.parallel.engine import (LocalEngine,
                                                        clear_program_cache)

    op = build_heisenberg(10, 5, None, ())
    clear_program_cache()           # deterministic cold compile → analyses
    eng = LocalEngine(op, mode="ell")
    led = obs.events("memory_ledger")
    assert led, "engine init emitted no memory_ledger event"
    ev = led[-1]
    assert ev["context"] == "engine_init/local"
    assert ev["mode"] == "ell" and ev["engine"] == "local"
    assert ev["n_states"] == op.basis.number_states
    assert ev["table_bytes"] == eng.ell_nbytes
    assert ev["T0"] == eng._ell_T0 and ev["num_terms"] == eng.num_terms
    assert ev["total_bytes"] >= ev["table_bytes"]
    # every resident group is attributed under this engine instance
    base = f"engine/{eng._mem_instance}"
    for part in ("operator_tables", "lookup", "basis_rows", "diag"):
        assert obs_mem.ledger_entries().get(f"{base}/{part}"), part
    # the cold build captured executable analyses for the AOT programs
    anas = obs.events("memory_analysis")
    assert anas and all("argument_bytes" in a and "temp_bytes" in a
                        for a in anas)
    assert any(a["program"] == "ell_fill_chunk" for a in anas)
    # table-bytes gauge mirrors the property
    assert obs.snapshot()["gauges"][
        "engine_table_bytes{engine=local}"] == eng.ell_nbytes


def test_engine_ledger_released_on_gc(clean_obs):
    import gc

    from distributed_matvec_tpu.parallel.engine import LocalEngine

    op = build_heisenberg(10, 5, None, ())
    eng = LocalEngine(op, mode="ell")
    base = f"engine/{eng._mem_instance}"
    assert obs.ledger_total(base) > 0
    del eng
    gc.collect()
    assert obs.ledger_total(base) == 0


def test_apply_memory_analysis_reconciles_with_ledger(clean_obs, rng):
    """The acceptance reconciliation: the apply executable's compile-time
    argument accounting equals the ledger's bytes for what the apply
    consumes (x + structure tables + diag) within 5%."""
    from distributed_matvec_tpu.parallel.engine import LocalEngine

    op = build_heisenberg(10, 5, None, ())
    eng = LocalEngine(op, mode="ell")
    n = op.basis.number_states
    x = np.asarray(rng.random(n) - 0.5)
    ana = eng.apply_memory_analysis(x)
    assert ana is not None and ana["program"] == "local_ell_apply"
    expected = x.nbytes + eng.ell_nbytes + eng._diag.nbytes
    assert abs(ana["argument_bytes"] - expected) \
        <= 0.05 * ana["argument_bytes"]
    # recorded in the registry + stream + gauge; repeat call is cached
    assert obs.events("memory_analysis")[-1]["program"] == "local_ell_apply"
    assert eng.apply_memory_analysis(x) == ana


def test_solver_registers_and_releases_workspace(clean_obs):
    from distributed_matvec_tpu.parallel.engine import LocalEngine
    from distributed_matvec_tpu.solve import lanczos, lanczos_block

    op = build_heisenberg(10, 5, None, ())
    eng = LocalEngine(op, mode="ell")
    seen = {}
    orig = obs_mem.track

    def spy(path, nbytes, **kw):
        seen[path] = nbytes
        return orig(path, nbytes, **kw)

    try:
        obs_mem.track = spy
        lanczos(eng.matvec, op.basis.number_states, k=1, max_iters=32,
                tol=1e-10, seed=3)
        lanczos_block(eng.matvec, op.basis.number_states, k=1, max_iters=8,
                      seed=3)
    finally:
        obs_mem.track = orig
    ks = list(seen)
    assert any(p.startswith("solver/lanczos:") for p in ks), ks
    assert any(p.startswith("solver/lanczos_block:") for p in ks), ks
    assert all(v > 0 for v in seen.values())
    # completed solves release their workspace entries
    assert obs_mem.ledger_total("solver") == 0


# ---------------------------------------------------------------------------
# OOM forensics


_OOM_MSG = ("RESOURCE_EXHAUSTED: Out of memory allocating 11906150400 "
            "bytes (allocated so far: 4295852032 bytes)")


def test_oom_fault_injection_report_shape(clean_obs, rng):
    """A fault-injected RESOURCE_EXHAUSTED on the apply surfaces as a typed
    OomError with the structured MemoryReport attached and one critical
    memory_report event — without a real OOM."""
    from distributed_matvec_tpu.parallel.engine import LocalEngine

    op = build_heisenberg(10, 5, None, ())
    eng = LocalEngine(op, mode="ell")
    x = rng.random(op.basis.number_states) - 0.5

    def boom(_x):
        raise RuntimeError(_OOM_MSG)

    eng._matvec = boom
    with pytest.raises(obs.OomError) as exc_info:
        eng.matvec(x)
    err = exc_info.value
    assert isinstance(err.__cause__, RuntimeError)
    rep = err.report
    assert rep["context"] == {"engine": "local", "mode": "ell",
                              "phase": "apply",
                              "n_states": op.basis.number_states}
    assert rep["ledger_total_bytes"] == obs.ledger_total() > 0
    assert rep["ledger"]["children"]["engine"]["bytes"] > 0
    assert rep["watermark"] is None            # CPU: advisory mode
    fixes = "\n".join(rep["remediation"])
    assert "fused" in fixes and "batch" in fixes and "shard" in fixes
    assert "capacity.py" in fixes
    assert "remediation" in str(err)           # message names the levers
    ev = obs.events("memory_report")[-1]
    assert ev["level"] == "critical" and ev["rank"] == 0
    assert ev["context"]["engine"] == "local"
    assert ev["remediation"] == rep["remediation"]
    assert "RESOURCE_EXHAUSTED" in ev["error"]
    assert obs.snapshot()["counters"]["oom_events"] == 1


def test_oom_init_phase_remediation(clean_obs, monkeypatch):
    """An OOM during the structure build carries phase=init and suggests
    the two-pass low-memory build."""
    from distributed_matvec_tpu.parallel import engine as E

    op = build_heisenberg(10, 5, None, ())
    monkeypatch.setattr(E.LocalEngine, "_build_ell",
                        lambda self: (_ for _ in ()).throw(
                            RuntimeError(_OOM_MSG)))
    with pytest.raises(obs.OomError) as exc_info:
        E.LocalEngine(op, mode="ell")
    rep = exc_info.value.report
    assert rep["context"]["phase"] == "init"
    assert any("ell_build_budget_gb" in r for r in rep["remediation"])


def test_non_oom_errors_pass_through_unwrapped(clean_obs, rng):
    from distributed_matvec_tpu.parallel.engine import LocalEngine

    op = build_heisenberg(10, 5, None, ())
    eng = LocalEngine(op, mode="ell")
    x = rng.random(op.basis.number_states) - 0.5

    def boom(_x):
        raise ValueError("plain bug, not memory")

    eng._matvec = boom
    with pytest.raises(ValueError, match="plain bug"):
        eng.matvec(x)
    assert obs.events("memory_report") == []


def test_oom_guard_disabled_noop(clean_obs, rng, monkeypatch):
    """DMT_OBS=off: the original error propagates untouched, nothing is
    emitted, and the forensics builder is provably never invoked."""
    from distributed_matvec_tpu.parallel.engine import LocalEngine

    op = build_heisenberg(10, 5, None, ())
    eng = LocalEngine(op, mode="ell")
    x = rng.random(op.basis.number_states) - 0.5
    monkeypatch.setenv("DMT_OBS", "off")
    obs.reset_all()

    def explode(**ctx):
        raise AssertionError("forensics built while obs disabled")

    monkeypatch.setattr(obs_mem, "build_memory_report", explode)

    def boom(_x):
        raise RuntimeError(_OOM_MSG)

    eng._matvec = boom
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        eng.matvec(x)
    assert obs.events() == []


def test_engine_apply_disabled_zero_memory_overhead(clean_obs, rng,
                                                    monkeypatch):
    """The PR-2 guard extended to the memory pillar: with the layer off an
    engine apply samples no watermark, touches no ledger, and returns
    bit-identical results."""
    from distributed_matvec_tpu.parallel.engine import LocalEngine

    op = build_heisenberg(10, 5, None, ())
    eng = LocalEngine(op, mode="ell")
    x = rng.random(op.basis.number_states) - 0.5
    y_on = np.asarray(eng.matvec(x))

    monkeypatch.setenv("DMT_OBS", "off")
    obs.reset_all()

    def explode(*a, **k):
        raise AssertionError("memory layer touched while disabled")

    monkeypatch.setattr(obs_mem, "_device_stats", explode)
    monkeypatch.setattr(obs_mem, "emit_ledger", explode)
    y_off = np.asarray(eng.matvec(x))
    np.testing.assert_array_equal(y_on, y_off)
    assert obs.events() == []
    assert obs_mem.ledger_total() == 0


def test_is_resource_exhausted_matching(clean_obs):
    assert obs_mem.is_resource_exhausted(RuntimeError(_OOM_MSG))
    assert obs_mem.is_resource_exhausted(
        Exception("jaxlib.xla_extension.XlaRuntimeError: "
                  "RESOURCE_EXHAUSTED: ..."))
    assert obs_mem.is_resource_exhausted(MemoryError("Out of memory"))
    assert not obs_mem.is_resource_exhausted(ValueError("shape mismatch"))
    assert not obs_mem.is_resource_exhausted(
        RuntimeError("INVALID_ARGUMENT: bad operand"))


# ---------------------------------------------------------------------------
# capacity planner


def _write_snapshot(tmp_path, **ledger_fields):
    run = tmp_path / "rank_0"
    run.mkdir(parents=True, exist_ok=True)
    ev = {"seq": 0, "ts": 0.0, "proc": 0, "rank": 0, "n_ranks": 1,
          "kind": "memory_ledger", "context": "engine_init/local",
          "total_bytes": 2_000_000, "entries": {},
          "engine": "local", "mode": "ell", "n_states": 100_000,
          "n_padded": 100_352, "T0": 12, "num_terms": 16, "pair": False,
          "table_bytes": 1_600_000}
    ev.update(ledger_fields)
    ana = {"seq": 1, "ts": 0.0, "proc": 0, "rank": 0, "n_ranks": 1,
           "kind": "memory_analysis", "key": "local_ell_apply@x",
           "program": "local_ell_apply", "argument_bytes": 2_000_000,
           "output_bytes": 800_000, "temp_bytes": 50_000,
           "peak_estimate_bytes": 2_850_000}
    with open(run / "events.jsonl", "w") as f:
        f.write(json.dumps(ev) + "\n" + json.dumps(ana) + "\n")
    return str(tmp_path)


def test_capacity_plan_from_snapshot(tmp_path, capsys):
    cap = _load_tool("capacity")
    run = _write_snapshot(tmp_path)
    assert cap.main(["--snapshot", run, "--hbm-gb", "16"]) == 0
    out = capsys.readouterr().out
    assert "calibrated from a measured ell engine" in out
    assert "max rows/device" in out
    for mode in ("ell", "compact", "fused"):
        assert mode in out
    # measured calibration wins over the analytic formula for ell
    snap = cap.load_snapshot(run)
    led = snap["ledger"]
    rep = cap.plan(led["n_states"], led["num_terms"], led["T0"],
                   led["pair"], 16.0, 1, 3, 1,
                   measured={k: led[k] for k in
                             ("mode", "n_states", "n_padded", "T0",
                              "table_bytes")})
    assert rep["modes"]["ell"]["structure_bytes_per_row"] == pytest.approx(
        1_600_000 / 100_352, abs=0.01)    # report rounds to 2 decimals
    assert rep["modes"]["fused"]["structure_bytes_per_row"] == 0
    # per-device max scales with the budget (same calibration both sides)
    rep32 = cap.plan(led["n_states"], led["num_terms"], led["T0"],
                     led["pair"], 32.0, 1, 3, 1,
                     measured={k: led[k] for k in
                               ("mode", "n_states", "n_padded", "T0",
                                "table_bytes")})
    assert rep32["modes"]["ell"]["max_rows_per_device"] == \
        2 * rep["modes"]["ell"]["max_rows_per_device"]


def test_capacity_recommendation_modes_and_shards(tmp_path):
    cap = _load_tool("capacity")
    rep = cap.plan(63_000_000, 36, 24, False, 16.0, 8, 3, 1)
    rec = cap.recommend(rep, None)
    assert rec["recommended_mode"] == "ell"
    assert rec["recommended_devices"] <= 8
    # a basis too big for the mesh names the minimal-shard mode
    rec_big = cap.recommend(rep, 10_000_000_000)
    assert rec_big["recommended_mode"] == "fused"
    assert rec_big["recommended_devices"] > 8


def test_capacity_explicit_params_json(capsys):
    cap = _load_tool("capacity")
    assert cap.main(["--n-states", "1e6", "--num-terms", "20", "--t0", "12",
                     "--pair", "--json"]) == 0
    data = json.loads(capsys.readouterr().out)
    m = data["report"]["modes"]
    assert m["ell"]["structure_bytes_per_row"] == 12 * 20   # pair: 16 B cf
    assert data["recommendation"]["recommended_mode"] == "ell"


def test_capacity_snapshot_without_ledger_fails_loudly(tmp_path):
    cap = _load_tool("capacity")
    run = tmp_path / "rank_0"
    run.mkdir(parents=True)
    (run / "events.jsonl").write_text(
        json.dumps({"kind": "engine_init", "n_states": 5}) + "\n")
    with pytest.raises(ValueError, match="memory_ledger"):
        cap.load_snapshot(str(tmp_path))


# ---------------------------------------------------------------------------
# obs_report: memory sections + memory regression gate


def test_obs_report_summarize_memory_section(clean_obs, tmp_path,
                                             monkeypatch):
    rep = _load_tool("obs_report")
    run = tmp_path / "run"
    monkeypatch.setenv("DMT_OBS_DIR", str(run))
    obs.emit("memory_ledger", context="engine_init/local", engine="local",
             mode="ell", n_states=100, T0=6, table_bytes=9000,
             total_bytes=12000,
             entries={"engine/local:0/structure/idx": {"bytes": 6000},
                      "engine/local:0/structure/coeff": {"bytes": 3000},
                      "engine/local:0/diag": {"bytes": 3000}})
    obs.emit("memory_watermark", tag="apply/local", bytes_in_use=5000,
             peak_bytes=8000, bytes_limit=100000, devices=[])
    obs.emit("memory_watermark", tag="apply/local", bytes_in_use=4000,
             peak_bytes=9000, bytes_limit=100000, devices=[])
    obs.emit("memory_analysis", key="local_ell_apply@x",
             program="local_ell_apply", argument_bytes=9000,
             output_bytes=800, temp_bytes=123, generated_code_bytes=0,
             peak_estimate_bytes=9923)
    obs.emit("memory_report", level="critical",
             context={"engine": "local", "mode": "ell"},
             ledger_total_bytes=12000, error="RESOURCE_EXHAUSTED",
             remediation=["switch to mode='fused'"])
    obs.flush()
    obs.reset()

    s = rep.run_summary(rep.load_events(str(run)))
    mem = s["memory"]
    assert mem["ledger_total_bytes"][0] == 12000
    assert mem["peak_hbm_bytes"][0] == 9000            # max over samples
    top = mem["top_allocations"][0]
    assert top[0]["path"] == "engine/local:0/structure/idx"
    assert [t["bytes"] for t in top] == [6000, 3000, 3000]
    assert mem["ledger_context"][0]["T0"] == 6
    exe = mem["executables"]["local_ell_apply@x"]
    assert exe["temp_bytes"] == 123
    assert len(mem["oom_events"]) == 1
    assert mem["oom_events"][0]["remediation"] == ["switch to mode='fused'"]
    rep.print_summary(s)                 # renderer must not throw
    # report --memory renders the same digest
    assert rep.main(["report", str(run), "--memory"]) == 0


def test_obs_report_rank_table_peak_hbm_column(tmp_path):
    rep = _load_tool("obs_report")
    run = tmp_path / "run"
    for r, peak in ((0, 111), (1, 222)):
        d = run / f"rank_{r}"
        d.mkdir(parents=True)
        evs = [{"seq": 0, "ts": 1000.0, "proc": r, "rank": r, "n_ranks": 2,
                "kind": "memory_watermark", "tag": "apply",
                "bytes_in_use": 1, "peak_bytes": peak, "bytes_limit": 10},
               {"seq": 1, "ts": 1001.0, "proc": r, "rank": r, "n_ranks": 2,
                "kind": "memory_watermark", "tag": "apply",
                "bytes_in_use": 1, "peak_bytes": peak - 1,
                "bytes_limit": 10}]
        with open(d / "events.jsonl", "w") as f:
            for ev in evs:
                f.write(json.dumps(ev) + "\n")
    table = rep.rank_table(rep.load_events(str(run)))
    rows = {row["rank"]: row for row in table["rows"]}
    assert rows[0]["peak_hbm"] == 111 and rows[1]["peak_hbm"] == 222
    rep.print_rank_report(table, show_ranks=True)


def _mem_detail(path, table_bytes, temp_bytes=1000, device_ms=10.0):
    detail = {"chain_16": {"config": "heisenberg_chain_16",
                           "device_ms": device_ms,
                           "table_bytes": table_bytes,
                           "executable_temp_bytes": temp_bytes}}
    path.write_text(json.dumps(detail))
    return str(path)


def test_obs_report_diff_memory_gate(tmp_path):
    rep = _load_tool("obs_report")
    base = _mem_detail(tmp_path / "base.json", table_bytes=1_000_000)
    grown = _mem_detail(tmp_path / "grown.json", table_bytes=1_500_000)
    shrunk = _mem_detail(tmp_path / "shrunk.json", table_bytes=700_000)
    # +50% tables beyond the 20% gate → regression, but ONLY when the
    # memory gate is requested
    assert rep.main(["diff", base, grown, "--threshold", "0.2"]) == 0
    assert rep.main(["diff", base, grown, "--threshold", "0.2",
                     "--memory"]) == 1
    # direction-aware: shrinking tables is an improvement
    assert rep.main(["diff", base, shrunk, "--threshold", "0.2",
                     "--memory"]) == 0
    # temp-bytes growth gates too
    hot = _mem_detail(tmp_path / "hot.json", table_bytes=1_000_000,
                      temp_bytes=5000)
    assert rep.main(["diff", base, hot, "--threshold", "0.2",
                     "--memory"]) == 1
    # --memory composes with an explicit perf gate
    slow = _mem_detail(tmp_path / "slow.json", table_bytes=1_000_000,
                       device_ms=20.0)
    assert rep.main(["diff", base, slow, "--threshold", "0.2",
                     "--memory"]) == 1


# ---------------------------------------------------------------------------
# executable-analysis registry


def test_record_executable_analysis_registry_and_gauge(clean_obs):
    import jax
    import jax.numpy as jnp

    ex = jax.jit(lambda a: a @ a).lower(jnp.ones((32, 32))).compile()
    ana = obs_mem.record_executable_analysis("unit@1", ex, program="unit")
    assert ana["argument_bytes"] == 32 * 32 * 8
    assert ana["output_bytes"] == 32 * 32 * 8
    assert ana["peak_estimate_bytes"] >= ana["argument_bytes"]
    assert obs_mem.executable_analyses()["unit@1"]["program"] == "unit"
    assert obs.snapshot()["gauges"][
        "executable_temp_bytes{program=unit}"] == ana["temp_bytes"]
    ev = obs.events("memory_analysis")[-1]
    assert ev["key"] == "unit@1" and ev["program"] == "unit"


def test_record_executable_analysis_disabled_and_soft_fail(clean_obs,
                                                           monkeypatch):
    class _Broken:
        def memory_analysis(self):
            raise NotImplementedError("backend has none")

    assert obs_mem.record_executable_analysis("b@1", _Broken()) is None
    assert obs.events("memory_analysis") == []
    monkeypatch.setenv("DMT_OBS", "off")

    class _Explodes:
        def memory_analysis(self):
            raise AssertionError("touched while disabled")

    assert obs_mem.record_executable_analysis("c@1", _Explodes()) is None
