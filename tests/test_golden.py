"""Golden-file matvec harness — the analog of the reference's
``TestMatrixVectorProduct.chpl`` (:25-59): load a golden HDF5 file
(/representatives, /x, /y), rebuild the basis from the YAML config, check
the enumerated representatives equal the stored ones
(TestStatesEnumeration.chpl:32), and check engine matvecs against /y at the
golden tolerances (atol 1e-14 / rtol 1e-12, TestMatrixVectorProduct.chpl:15-16).

Goldens are produced by ``tools/make_golden.py`` (the ``input_for_matvec.py``
analog, seed 42); here they are generated once per session into a tmp dir
from the reference's own YAML configs.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from distributed_matvec_tpu.io.hdf5 import load_golden
from distributed_matvec_tpu.models.yaml_io import load_config_from_yaml
from distributed_matvec_tpu.parallel.distributed import DistributedEngine
from distributed_matvec_tpu.parallel.engine import LocalEngine

DATA = "/root/reference/data"
ATOL, RTOL = 1e-14, 1e-12  # TestMatrixVectorProduct.chpl:15-16

CONFIGS = ["heisenberg_chain_10.yaml", "heisenberg_kagome_12.yaml"]

require_data = pytest.mark.skipif(
    not os.path.isdir(DATA), reason="reference data not mounted"
)


@pytest.fixture(scope="module")
def golden_dir(tmp_path_factory):
    out = tmp_path_factory.mktemp("golden")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    args = [sys.executable, os.path.join(repo, "tools", "make_golden.py"),
            "-o", str(out)] + [os.path.join(DATA, c) for c in CONFIGS]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    subprocess.run(args, check=True, env=env, timeout=300)
    return out


@require_data
@pytest.mark.parametrize("config", CONFIGS)
def test_golden_matvec(golden_dir, config):
    name = os.path.splitext(config)[0]
    reps, x, y = load_golden(os.path.join(golden_dir, "matvec", f"{name}.h5"))
    cfg = load_config_from_yaml(os.path.join(DATA, config))
    cfg.basis.build()
    # representative equality — TestStatesEnumeration.chpl:32
    np.testing.assert_array_equal(cfg.basis.representatives, reps)
    eng = LocalEngine(cfg.hamiltonian)
    for k in range(x.shape[0]):
        np.testing.assert_allclose(np.asarray(eng.matvec(x[k])), y[k],
                                   atol=ATOL, rtol=RTOL)


@require_data
def test_golden_matvec_distributed(golden_dir):
    name = os.path.splitext(CONFIGS[0])[0]
    reps, x, y = load_golden(
        os.path.join(golden_dir, "matvec", f"{name}.h5"))
    cfg = load_config_from_yaml(os.path.join(DATA, CONFIGS[0]))
    cfg.basis.build()
    ndev = min(4, len(__import__("jax").devices()))
    eng = DistributedEngine(cfg.hamiltonian, n_devices=ndev)
    for k in range(x.shape[0]):
        np.testing.assert_allclose(eng.matvec_global(x[k]), y[k],
                                   atol=ATOL, rtol=RTOL)
