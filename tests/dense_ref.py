"""Independent dense/sparse reference implementation used only by tests.

This is the trusted path standing in for the reference's golden-data generator
(``/root/reference/input_for_matvec.py``, which used the independent OpenMP
``lattice_symmetries`` Python package).  It deliberately shares **no algebra**
with the production code:

  * operators are built as explicit Kronecker products of 2x2 matrices
    (scipy.sparse), never via nonbranching masks;
  * permutations act through the per-bit loop ``Permutation.apply_int``, never
    via shift/mask networks;
  * the symmetry-adapted matrix is ``B† H B`` with an explicitly materialized
    isometry B of normalized projected basis vectors.

Bit convention matches the package docs: bit i ↔ site i, bit 1 ↔ σᶻ = +1.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np
import scipy.sparse as sp

from distributed_matvec_tpu.models.expression import SymbolicExpression
from distributed_matvec_tpu.models.symmetry import SymmetryGroup

_PAULI = {
    "I": np.eye(2, dtype=np.complex128),
    # basis ordering: index 0 = bit 0 (down), index 1 = bit 1 (up)
    "x": np.array([[0, 1], [1, 0]], dtype=np.complex128),
    "y": np.array([[0, 1j], [-1j, 0]], dtype=np.complex128),  # [b_out, b_in]
    "z": np.array([[-1, 0], [0, 1]], dtype=np.complex128),
    "+": np.array([[0, 0], [1, 0]], dtype=np.complex128),  # |1⟩⟨0|? see note
    "-": np.array([[0, 1], [0, 0]], dtype=np.complex128),
    "n": np.array([[0, 0], [0, 1]], dtype=np.complex128),
}
# Note on σ±: with bit 1 = up, σ⁺ = |↑⟩⟨↓| maps bit 0 → bit 1, i.e. entry
# M[1, 0] = 1.  σʸ: M[1,0] = ⟨↑|σʸ|↓⟩ = −i·(−1)... with the standard
# (↑,↓)-ordered matrix [[0,−i],[i,0]] we have ⟨↑|σʸ|↓⟩ = −i ⇒ M[1,0] = −i and
# M[0,1] = +i, which is what the array above encodes in [b_out, b_in] indexing.

assert _PAULI["y"][1, 0] == -1j and _PAULI["y"][0, 1] == 1j


def site_operator_matrix(n_sites: int, kind: str, site: int) -> sp.csr_matrix:
    """Full 2^n matrix of a single-site operator via Kronecker products."""
    mat = sp.identity(1, dtype=np.complex128, format="csr")
    for i in range(n_sites):
        m = _PAULI[kind] if i == site else _PAULI["I"]
        # state index α = Σ b_i 2^i  ⇒  site 0 is the *fastest* index ⇒ it goes
        # rightmost in the kron chain: M = M_{n-1} ⊗ … ⊗ M_0
        mat = sp.kron(sp.csr_matrix(m), mat, format="csr")
    return mat


# Fermionic mode matrices in [b_out, b_in] indexing with bit = occupation.
# Jordan-Wigner parity Z = (−1)^n = diag(+1 empty, −1 occupied); annihilator
# a|1⟩ = |0⟩ ⇒ a[0, 1] = 1.  Mode ordering: mode 0 is rightmost in the kron
# chain (fastest index), and the JW string multiplies all modes *below* the
# target — the convention of ``expression._fermion_atoms`` (s = bits < site).
_FERMI = {
    "a": np.array([[0, 1], [0, 0]], dtype=np.complex128),
    "a+": np.array([[0, 0], [1, 0]], dtype=np.complex128),
    "Z": np.array([[1, 0], [0, -1]], dtype=np.complex128),
    "n": np.array([[0, 0], [0, 1]], dtype=np.complex128),
    "I": np.eye(2, dtype=np.complex128),
}


def fermion_site_operator_matrix(n_sites: int, kind: str, site: int) -> sp.csr_matrix:
    """Full 2^n matrix of c†/c/n at ``site`` with the Jordan-Wigner string.

    Independent of the production term tables: built purely from Kronecker
    products of 2×2 mode matrices (c_i = Z⊗…⊗Z⊗a⊗I⊗…⊗I with Z on every
    mode below i).
    """
    local = {"c": "a", "c+": "a+", "n": "n"}[kind]
    mat = sp.identity(1, dtype=np.complex128, format="csr")
    for i in range(n_sites):
        if i == site:
            m = _FERMI[local]
        elif i < site and kind in ("c", "c+"):
            m = _FERMI["Z"]
        else:
            m = _FERMI["I"]
        mat = sp.kron(sp.csr_matrix(m), mat, format="csr")
    return mat


def expression_matrix(
    n_sites: int,
    expr: SymbolicExpression,
    sites_rows: Sequence[Sequence[int]],
) -> sp.csr_matrix:
    """Full-space matrix of Σ_rows expr(row)."""
    dim = 1 << n_sites
    total = sp.csr_matrix((dim, dim), dtype=np.complex128)
    for row in sites_rows:
        row = list(row) if isinstance(row, (list, tuple)) else [row]
        for term in expr.terms:
            m = sp.identity(dim, dtype=np.complex128, format="csr") * term.coeff
            for family, kind, placeholder in term.factors:
                site = row[placeholder]
                if family == "spin":
                    m = m @ site_operator_matrix(n_sites, kind, site)
                else:
                    m = m @ fermion_site_operator_matrix(n_sites, kind, site)
            total = total + m
    return total


def operator_matrix_full(
    n_sites: int,
    exprs: Sequence[Tuple[SymbolicExpression, Sequence[Sequence[int]]]],
) -> sp.csr_matrix:
    dim = 1 << n_sites
    total = sp.csr_matrix((dim, dim), dtype=np.complex128)
    for expr, rows in exprs:
        total = total + expression_matrix(n_sites, expr, rows)
    return total


def brute_force_representatives(
    n_sites: int,
    states: Sequence[int],
    group: SymmetryGroup,
) -> Tuple[np.ndarray, np.ndarray]:
    """Orbit-minimum representatives + norms by per-element python loops."""
    inv_mask = (1 << n_sites) - 1
    reps: List[int] = []
    norms: List[float] = []
    for alpha in states:
        orbit = []
        stab_sum = 0.0 + 0.0j
        for g, (perm, chi, flip) in enumerate(
            zip(group.perms, group.characters, group.flip)
        ):
            beta = perm.apply_int(int(alpha))
            if flip:
                beta ^= inv_mask
            orbit.append(beta)
            if beta == alpha:
                stab_sum += chi
        norm2 = stab_sum.real / len(group.perms)
        if min(orbit) == alpha and norm2 > 1e-12:
            reps.append(alpha)
            norms.append(np.sqrt(norm2))
    return np.array(reps, dtype=np.uint64), np.array(norms)


def symmetry_isometry(
    n_sites: int,
    reps: np.ndarray,
    norms: np.ndarray,
    group: SymmetryGroup,
) -> sp.csr_matrix:
    """B: [2^n, n_reps] with columns |r̃⟩ = (1/(|G|·‖P r‖)) Σ_g χ*(g) |g·r⟩."""
    inv_mask = (1 << n_sites) - 1
    dim = 1 << n_sites
    cols, rows, vals = [], [], []
    for j, (r, nrm) in enumerate(zip(reps, norms)):
        amp: dict = {}
        for perm, chi, flip in zip(group.perms, group.characters, group.flip):
            beta = perm.apply_int(int(r))
            if flip:
                beta ^= inv_mask
            amp[beta] = amp.get(beta, 0.0) + np.conj(chi)
        for beta, a in amp.items():
            a = a / (len(group.perms) * nrm)
            if abs(a) > 1e-14:
                rows.append(beta)
                cols.append(j)
                vals.append(a)
    return sp.csr_matrix((vals, (rows, cols)), shape=(dim, len(reps)))


def projected_matrix(
    n_sites: int,
    h_full: sp.csr_matrix,
    reps: np.ndarray,
    norms: np.ndarray,
    group: SymmetryGroup,
) -> np.ndarray:
    b = symmetry_isometry(n_sites, reps, norms, group)
    h_eff = (b.getH() @ h_full @ b).toarray()
    return h_eff
