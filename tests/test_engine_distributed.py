"""Multi-device matvec: hash-sharded engine vs LocalEngine vs host matvec.

The analog of the reference's GASNet-smp multi-locale testing
(SURVEY.md §4): 2/4/8 virtual CPU devices stand in for locales; the
engine must be bit-compatible with the single-device path at the golden
tolerances (TestMatrixVectorProduct.chpl:15-16).
"""

import jax
import numpy as np
import pytest

from distributed_matvec_tpu.models.basis import SpinBasis
from distributed_matvec_tpu.parallel.distributed import DistributedEngine
from distributed_matvec_tpu.parallel.shuffle import HashedLayout

from test_operator import build_heisenberg

ATOL, RTOL = 1e-13, 1e-12

def _ndev() -> int:
    """Device count, queried lazily: a module-import-time ``jax.devices()``
    initializes the backend during pytest collection, where an XLA-level
    fatal (bad XLA_FLAGS, dead plugin) aborts the whole run instead of
    failing one module."""
    return len(jax.devices())


# string condition → evaluated lazily at test setup, not at import
needs_8 = pytest.mark.skipif("_ndev() < 8", reason="needs 8 virtual devices")


# -- layout shuffles ---------------------------------------------------------


@pytest.mark.parametrize("n_shards", [2, 4, 8])
@pytest.mark.parametrize("batch", [None, 3])
def test_shuffle_round_trip(n_shards, batch, rng):
    """Block→hashed→block identity — the Example02 property test
    (example/Example02.chpl:20-48) on fabricated batched vectors."""
    states = np.sort(rng.choice(2**40, size=501, replace=False)).astype(np.uint64)
    layout = HashedLayout(states, n_shards, pad_multiple=8)
    shape = (states.size,) if batch is None else (states.size, batch)
    arr = rng.random(shape)
    hashed = layout.to_hashed(arr)
    assert hashed.shape[:2] == (n_shards, layout.shard_size)
    back = layout.from_hashed(hashed)
    np.testing.assert_array_equal(back, arr)
    # device path agrees with host path
    np.testing.assert_array_equal(
        np.asarray(layout.to_hashed_device(arr)), hashed)
    np.testing.assert_array_equal(
        np.asarray(layout.from_hashed_device(hashed)), arr)


def test_shuffle_counts_match_hash(rng):
    states = np.arange(1000, dtype=np.uint64) * np.uint64(2654435761)
    layout = HashedLayout(np.sort(states), 4, pad_multiple=8)
    assert layout.counts.sum() == states.size
    from distributed_matvec_tpu.enumeration.host import shard_index

    owner = shard_index(np.sort(states), 4)
    np.testing.assert_array_equal(layout.counts, np.bincount(owner, minlength=4))


# -- distributed matvec ------------------------------------------------------

DIST_CONFIGS = [
    # (n, hw, inv, syms, n_devices)
    (8, 4, None, (), 2),
    (10, 5, None, (), 4),
    (12, 6, None, (), 8),
    (10, 5, -1, (), 8),
    (12, 6, 1, [([*range(1, 12), 0], 0)], 8),          # chain_24_symm shape
    (10, 5, None, [([*range(1, 10), 0], 1)], 4),       # complex characters
]


@pytest.mark.parametrize("mode", ["ell", "fused"])
@pytest.mark.parametrize("n,hw,inv,syms,ndev", DIST_CONFIGS)
def test_distributed_matches_host(n, hw, inv, syms, ndev, mode, rng):
    if len(jax.devices()) < ndev:
        pytest.skip(f"needs {ndev} devices")
    op = build_heisenberg(n, hw, inv, syms)
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    if not op.effective_is_real:
        x = x.astype(np.complex128)
    eng = DistributedEngine(op, n_devices=ndev, mode=mode, batch_size=64)
    y = eng.matvec_global(x)
    np.testing.assert_allclose(y, op.matvec_host(x), atol=ATOL, rtol=RTOL)


@needs_8
@pytest.mark.parametrize("mode", ["ell", "compact", "fused"])
def test_distributed_matches_local_engine(mode, rng):
    from distributed_matvec_tpu.parallel.engine import LocalEngine

    op = build_heisenberg(12, 6, 1, [([*range(1, 12), 0], 0)])
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    local = LocalEngine(op, mode=mode)
    dist = DistributedEngine(op, n_devices=8, mode=mode, batch_size=32)
    np.testing.assert_allclose(
        dist.matvec_global(x), np.asarray(local.matvec(x)), atol=ATOL, rtol=RTOL
    )


@needs_8
@pytest.mark.parametrize("mode", ["ell", "compact", "fused"])
def test_distributed_batch(mode, rng):
    op = build_heisenberg(10, 5, None, ())
    op.basis.build()
    n = op.basis.number_states
    X = rng.random((n, 3)) - 0.5
    eng = DistributedEngine(op, n_devices=8, mode=mode)
    Y = eng.from_hashed(eng.matvec(eng.to_hashed(X)))
    for k in range(3):
        np.testing.assert_allclose(
            Y[:, k], op.matvec_host(X[:, k]), atol=ATOL, rtol=RTOL
        )


@needs_8
def test_distributed_batch_fused_pair(rng):
    """Fused batches must ride the pair (re, im) layout too: hashed
    [D, M, k, 2] in one program."""
    from distributed_matvec_tpu.utils.config import update_config

    op = build_heisenberg(10, 5, None, [([*range(1, 10), 0], 1)])
    op.basis.build()
    assert not op.effective_is_real
    n = op.basis.number_states
    X = (rng.random((n, 3)) - 0.5) + 1j * (rng.random((n, 3)) - 0.5)
    update_config(complex_pair="on")
    try:
        eng = DistributedEngine(op, n_devices=8, mode="fused")
        assert eng.pair
        Y = eng.matvec_global(X)
    finally:
        update_config(complex_pair="auto")
    for k in range(3):
        np.testing.assert_allclose(
            Y[:, k], op.matvec_host(X[:, k]), atol=ATOL, rtol=RTOL
        )


@needs_8
def test_distributed_batch_fused_economics(rng):
    """A fused k=4 batch shares the routing (hash, sort, all_to_all index
    side) across columns, so it must cost well under 4 single applies —
    the gate is <= 1.5x one apply (generous vs the measured ~1.1x, to
    absorb CPU timing noise)."""
    import time

    op = build_heisenberg(12, 6, None, ())
    op.basis.build()
    n = op.basis.number_states
    eng = DistributedEngine(op, n_devices=8, mode="fused")
    x1 = eng.to_hashed(rng.random(n) - 0.5)
    x4 = eng.to_hashed(rng.random((n, 4)) - 0.5)
    # warm both programs (compile + first-call counter check)
    eng.matvec(x1).block_until_ready()
    eng.matvec(x4).block_until_ready()

    def best_of(f, reps=5):
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            f()
            ts.append(time.perf_counter() - t0)
        return min(ts)

    # re-measure up to 3 times: a wall-clock ratio on shared CI hardware
    # can be skewed by a transient load spike, which retrying absorbs
    # without weakening the gate itself
    for attempt in range(3):
        t1 = best_of(lambda: eng.matvec(x1, check=False).block_until_ready())
        t4 = best_of(lambda: eng.matvec(x4, check=False).block_until_ready())
        if t4 <= 1.5 * t1 + 1e-3:
            break
    else:
        raise AssertionError((t4, t1))


@needs_8
def test_fused_overflow_detection(rng):
    """A deliberately tiny all_to_all capacity must be *detected*, not
    silently wrong — the analog of the reference's bounded-buffer flow
    control (DistributedMatrixVector.chpl:456, :638-661)."""
    from distributed_matvec_tpu.utils.config import get_config, update_config

    op = build_heisenberg(12, 6)
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    cfg = get_config()
    saved = (cfg.all_to_all_capacity_factor, cfg.remote_buffer_size)
    update_config(all_to_all_capacity_factor=1.0, remote_buffer_size=8)
    try:
        eng = DistributedEngine(op, n_devices=8, mode="fused", batch_size=128)
        with pytest.raises(RuntimeError, match="overflow"):
            eng.matvec(eng.to_hashed(x))
    finally:
        update_config(all_to_all_capacity_factor=saved[0],
                      remote_buffer_size=saved[1])


@needs_8
def test_distributed_dot_matches_host(rng):
    op = build_heisenberg(10, 5)
    op.basis.build()
    n = op.basis.number_states
    a, b = rng.random(n), rng.random(n)
    eng = DistributedEngine(op, n_devices=8)
    got = float(eng.dot(eng.to_hashed(a), eng.to_hashed(b)))
    assert abs(got - np.dot(a, b)) < 1e-10


def test_graft_entry_dryrun():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge

    if len(jax.devices()) >= 8:
        ge.dryrun_multichip(8)
    else:
        pytest.skip("needs 8 devices")


@pytest.mark.parametrize("mode", ["ell", "compact"])
def test_distributed_ell_split_tail_exercised(mode, rng):
    """The two-level split must trigger on the sharded plan too (global T0,
    per-shard padded tail) and stay exact vs the host path."""
    op = build_heisenberg(16, 8, None)
    op.basis.build()
    eng = DistributedEngine(op, n_devices=4, mode=mode)
    assert eng._ell_T0 < eng.num_terms, "split did not trigger"
    tail = eng._ell_tail if mode == "ell" else eng._c_tail
    assert tail is not None, "tail path not exercised"
    n = op.basis.number_states
    x = rng.random(n) - 0.5
    np.testing.assert_allclose(eng.matvec_global(x), op.matvec_host(x),
                               atol=1e-13, rtol=1e-12)


@pytest.mark.parametrize("mode", ["ell", "compact"])
def test_split_gather_distributed_matches_plain(mode, rng):
    from distributed_matvec_tpu.utils.config import update_config

    op = build_heisenberg(12, 6, None)
    op.basis.build()
    n = op.basis.number_states
    x = rng.random(n) - 0.5
    X = rng.random((n, 2)) - 0.5
    update_config(split_gather="off")
    ref = DistributedEngine(op, n_devices=4, mode=mode)
    y_ref = ref.matvec_global(x)
    Y_ref = ref.from_hashed(ref.matvec(ref.to_hashed(X)))
    update_config(split_gather="on")
    try:
        eng = DistributedEngine(op, n_devices=4, mode=mode)
        y = eng.matvec_global(x)
        Y = eng.from_hashed(eng.matvec(eng.to_hashed(X)))
    finally:
        update_config(split_gather="auto")
    np.testing.assert_allclose(y, y_ref, atol=1e-14, rtol=1e-14)
    np.testing.assert_allclose(Y, Y_ref, atol=1e-14, rtol=1e-14)


def test_distributed_compact_refusals():
    """Distributed compact refuses complex sectors and anisotropic couplings
    exactly like the local engine."""
    from distributed_matvec_tpu.models.basis import SpinBasis
    from distributed_matvec_tpu.models.lattices import (chain_edges,
                                                        heisenberg_from_edges)
    from distributed_matvec_tpu.utils.config import update_config

    b = SpinBasis(8, 4)
    op = heisenberg_from_edges(b, chain_edges(8)) \
        + 0.44 * heisenberg_from_edges(b, [(i, (i + 2) % 8)
                                           for i in range(8)])
    b.build()
    with pytest.raises(ValueError, match="single off-diagonal magnitude"):
        DistributedEngine(op, n_devices=2, mode="compact")

    b2 = SpinBasis(10, 5, None, [([1, 2, 3, 4, 5, 6, 7, 8, 9, 0], 1)])
    op2 = heisenberg_from_edges(b2, chain_edges(10))
    b2.build()
    update_config(complex_pair="on")
    try:
        with pytest.raises(ValueError, match="real sector"):
            DistributedEngine(op2, n_devices=2, mode="compact")
    finally:
        update_config(complex_pair="auto")


@needs_8
@pytest.mark.parametrize("mode", ["ell", "compact"])
def test_distributed_structure_cache(mode, tmp_path, rng):
    """The distributed routing plan checkpoints and restores bit-identically,
    keyed per mesh size (a D=4 plan must not satisfy a D=2 engine)."""
    op = build_heisenberg(12, 6, 1, [([*range(1, 12), 0], 0)])
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    cache = str(tmp_path / "c.h5")
    e1 = DistributedEngine(op, n_devices=4, mode=mode, structure_cache=cache)
    assert not e1.structure_restored
    y1 = e1.matvec_global(x)
    e2 = DistributedEngine(op, n_devices=4, mode=mode, structure_cache=cache)
    assert e2.structure_restored
    np.testing.assert_array_equal(y1, e2.matvec_global(x))
    e3 = DistributedEngine(op, n_devices=2, mode=mode, structure_cache=cache)
    assert not e3.structure_restored


@needs_8
@pytest.mark.parametrize("mode", ["ell", "compact", "fused"])
def test_engine_from_shards_all_modes(mode, tmp_path, rng):
    """Shard-native engines in EVERY mode (VERDICT r3 missing #3): the plan
    builds stream peer shards from the enumeration file one at a time —
    the global basis is never built — and match the host matvec; the
    per-shard structure cache restores bit-identically, keyed by the shard
    manifest fingerprint."""
    from distributed_matvec_tpu.enumeration.native import native_available
    from distributed_matvec_tpu.enumeration.sharded import enumerate_to_shards
    from distributed_matvec_tpu.models.lattices import (
        chain_edges, heisenberg_from_edges)
    from distributed_matvec_tpu.models.yaml_io import operator_from_dict

    if not native_available():
        pytest.skip("native kernel unavailable")
    n, hw = 12, 6
    syms = [([*range(1, n), 0], 0)]
    ref_basis = SpinBasis(number_spins=n, hamming_weight=hw,
                          spin_inversion=1, symmetries=list(syms))
    ref_basis.build()
    path = str(tmp_path / "shards.h5")
    enumerate_to_shards(n, hw, ref_basis.group, 8, path)

    ham = {"terms": [{"expression": "σˣ₀ σˣ₁ + σʸ₀ σʸ₁ + σᶻ₀ σᶻ₁",
                      "sites": [[i, (i + 1) % n] for i in range(n)]}]}
    fresh = SpinBasis(number_spins=n, hamming_weight=hw,
                      spin_inversion=1, symmetries=list(syms))
    op = operator_from_dict(ham, fresh)
    cache = str(tmp_path / "reps.h5")
    eng = DistributedEngine.from_shards(op, path, n_devices=8, mode=mode,
                                        structure_cache=cache)
    assert not fresh.is_built               # truly global-array-free
    assert eng.n_states == ref_basis.number_states

    op_ref = heisenberg_from_edges(ref_basis, chain_edges(n))
    x = rng.random(ref_basis.number_states) - 0.5
    y = eng.matvec_global(x)
    np.testing.assert_allclose(y, op_ref.matvec_host(x),
                               atol=1e-13, rtol=1e-12)

    if mode in ("ell", "compact"):
        assert not eng.structure_restored
        fresh2 = SpinBasis(number_spins=n, hamming_weight=hw,
                           spin_inversion=1, symmetries=list(syms))
        op2 = operator_from_dict(ham, fresh2)
        e2 = DistributedEngine.from_shards(op2, path, n_devices=8, mode=mode,
                                           structure_cache=cache)
        assert e2.structure_restored and not fresh2.is_built
        np.testing.assert_array_equal(y, e2.matvec_global(x))


@needs_8
@pytest.mark.slow
def test_plan_build_memory_bounded():
    """The streaming plan build must never materialize the dense
    [D, M, T] host arrays the old build used (~36 GB at chain_36_symm).
    chain_24 (N=2.7M, T=24) as the tractable proxy: the dense build's
    transients (owner/idx/coeff + the argsort copies of _split_tables)
    exceed 3.5 GB here; the streaming build + jax runtime + final packed
    structure measured 2.0 GB.  Bound 2.7 GB — fails if anyone
    reintroduces a full-width host materialization, with headroom for
    allocator noise."""
    import os
    import subprocess
    import sys
    import textwrap

    code = textwrap.dedent("""
        import resource, sys
        import jax
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_enable_x64", True)
        import numpy as np
        from distributed_matvec_tpu.models.basis import SpinBasis
        from distributed_matvec_tpu.models.yaml_io import operator_from_dict
        basis = SpinBasis(number_spins=24, hamming_weight=12)
        basis.build()
        op = operator_from_dict(
            {"terms": [{"expression":
                        "\\u03c3\\u02e3\\u2080 \\u03c3\\u02e3\\u2081 + "
                        "\\u03c3\\u02b8\\u2080 \\u03c3\\u02b8\\u2081 + "
                        "\\u03c3\\u1dbb\\u2080 \\u03c3\\u1dbb\\u2081",
                        "sites": [[i, (i + 1) % 24] for i in range(24)]}]},
            basis)
        from distributed_matvec_tpu.parallel.distributed import (
            DistributedEngine)
        eng = DistributedEngine(op, n_devices=8, mode="ell")
        x = np.random.default_rng(0).standard_normal(basis.number_states)
        y = eng.matvec_global(x)
        assert np.isfinite(y).all()
        peak_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss // 1024
        print("PEAK_MB", peak_mb)
        sys.exit(0 if peak_mb < 2700 else 17)
    """)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "true"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = os.path.join(os.path.dirname(__file__), os.pardir)
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=env, timeout=540)
    assert r.returncode == 0, (r.returncode, r.stdout[-500:], r.stderr[-800:])


@pytest.mark.slow
def test_multihost_two_process(tmp_path):
    """A REAL multi-controller run: 2 jax.distributed processes, 4 CPU
    devices each, one 8-device mesh — the DCN analog of the reference's
    GASNet substrates (env/chpl-env-*.sh).  Each process packs only its
    addressable plan shards; all three engine modes matvec + a Lanczos
    block against single-process truth, then a shard-native from_shards
    engine where each process loads only its own shards from the file
    (multihost_worker.py)."""
    import os
    import socket
    import subprocess
    import sys

    from distributed_matvec_tpu.enumeration.native import native_available
    from distributed_matvec_tpu.enumeration.sharded import enumerate_to_shards

    shards = ""
    if native_available():
        b = SpinBasis(12, 6)
        shards = str(tmp_path / "mh_shards.h5")
        enumerate_to_shards(12, 6, b.group, 8, shards)

    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    with socket.socket() as s:              # free port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [subprocess.Popen(
        [sys.executable, worker, str(pid), "2", str(port)]
        + ([shards] if shards else []),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
        for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid}:\n{out[-2000:]}"
        assert f"[p{pid}] MULTIHOST_OK" in out, out[-2000:]
        if shards:      # the shard-native leg must actually have run
            assert f"[p{pid}] from_shards compact: matvec" in out, out[-2000:]
            assert f"[p{pid}] from_shards resumed E0/4" in out, out[-2000:]
            assert f"[p{pid}] lobpcg E0/4" in out, out[-2000:]


@needs_8
def test_fused_exchange_counters_reach_obs(rng):
    """Satellite: the overflow/invalid counters the fused apply computes
    on-device are no longer dropped on the non-debug path — after the
    deferred drain they are visible (at zero, the healthy reading) as obs
    counters, alongside the per-apply rank-tagged matvec_apply events."""
    from distributed_matvec_tpu import obs

    obs.reset_all()
    try:
        op = build_heisenberg(10, 5)
        op.basis.build()
        x = rng.random(op.basis.number_states) - 0.5
        eng = DistributedEngine(op, n_devices=8, mode="fused")
        xh = eng.to_hashed(x)
        eng.matvec(xh)
        eng.matvec(xh)
        snap = obs.snapshot()                  # drains pending fetches
        c = snap["counters"]
        assert c.get("exchange_overflow{engine=distributed}") == 0
        assert c.get("exchange_invalid{engine=distributed}") == 0
        assert c.get("exchange_bytes{engine=distributed}", 0) > 0
        applies = obs.events("matvec_apply")
        assert len(applies) == 2
        assert all(ev["engine"] == "distributed" and ev["bytes"] > 0
                   and ev["rank"] == 0 for ev in applies)
        assert [ev["apply"] for ev in applies] == [0, 1]
        shards = obs.events("rank_shards")
        assert shards and shards[-1]["states"] == op.basis.number_states
    finally:
        obs.reset_all()


@needs_8
@pytest.mark.parametrize("mode", ["ell", "compact"])
def test_distributed_scan_branch(mode, rng, monkeypatch):
    """The lax.scan fallback of the term loops (taken only at LARGE T0,
    where unrolling would blow the program) must agree with the host —
    under shard_map the zero scan carries need varying-axes marking, which
    the unrolled branch never exercises (chain_36-scale regression)."""
    from distributed_matvec_tpu.parallel import distributed as dist_mod

    monkeypatch.setattr(dist_mod, "unroll_terms_ok",
                        lambda *a, **k: False)
    op = build_heisenberg(12, 6, 1, [([*range(1, 12), 0], 0)])
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    eng = DistributedEngine(op, n_devices=8, mode=mode, batch_size=32)
    np.testing.assert_allclose(eng.matvec_global(x), op.matvec_host(x),
                               atol=ATOL, rtol=RTOL)


@needs_8
def test_fused_overflow_detected_under_trace(rng):
    """The distributed twin of the local traced-validation test (ADVICE
    r4 medium): a jit-only caller hitting a too-small all_to_all capacity
    gets a trace-time RuntimeWarning, run-time counter validation via
    ``jax.debug.callback``, and a sticky RuntimeError from the next eager
    matvec."""
    import time

    from distributed_matvec_tpu.utils.config import get_config, update_config

    op = build_heisenberg(12, 6)
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    cfg = get_config()
    saved = (cfg.all_to_all_capacity_factor, cfg.remote_buffer_size)
    update_config(all_to_all_capacity_factor=1.0, remote_buffer_size=8)
    try:
        eng = DistributedEngine(op, n_devices=8, mode="fused",
                                batch_size=128)
        xh = eng.to_hashed(x)
        with pytest.warns(RuntimeWarning, match="traced before any eager"):
            try:
                jax.block_until_ready(jax.jit(eng.matvec)(xh))
            except Exception:
                pass        # callback exception may surface through the jit
        deadline = time.time() + 10
        while eng._deferred_failure is None and time.time() < deadline:
            time.sleep(0.05)
        with pytest.raises(RuntimeError, match="overflow"):
            eng.matvec(xh)
    finally:
        update_config(all_to_all_capacity_factor=saved[0],
                      remote_buffer_size=saved[1])
