"""The ops tooling must not bit-rot: scale_bench end-to-end on a small
config (CPU), including the representative checkpoint and the engine
structure cache it wires up."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_ENABLE_X64="true")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "scale_bench.py"),
         "--config", "heisenberg_chain_16.yaml",
         "--out", str(tmp_path / "c16.h5"), "--solver-iters", "4", *args],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    return [json.loads(line) for line in r.stdout.splitlines()
            if line.startswith("{")]


def test_sharded_enum_scale_ranks_cli(tmp_path):
    """sharded_enum_scale --ranks: the multi-process enumeration CLI path
    end-to-end (2 spawned ranks, finalize, census) on a small config; a
    rerun restores every part."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_ENABLE_X64="true")
    out = str(tmp_path / "s16.h5")
    cmd = [sys.executable,
           os.path.join(REPO, "tools", "sharded_enum_scale.py"),
           "--config", "heisenberg_chain_16", "--out", out,
           "--shards", "4", "--ranks", "2", "--threads-per-rank", "1"]
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=420,
                       env=env, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "CENSUS_OK" in r.stdout
    assert os.path.exists(out) and os.path.exists(out + ".part1")
    r2 = subprocess.run(cmd, capture_output=True, text=True, timeout=420,
                        env=env, cwd=REPO)
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert "restored" in r2.stdout and "CENSUS_OK" in r2.stdout


def test_example_sharded_pipeline(tmp_path):
    """The shard-native pipeline example must keep running end to end
    (2-rank enumeration → census → compact from_shards → solve →
    per-shard eigenvector save); E0 is pinned to the chain_16 anchor."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_ENABLE_X64="true",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    r = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "examples", "example_sharded_pipeline.py"),
         "--num-spins", "16", "--ranks", "2",
         "--workdir", str(tmp_path)],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert r.returncode == 0, (r.stdout[-800:], r.stderr[-1500:])
    assert "census OK" in r.stdout
    assert "E[0] = -28.5691854" in r.stdout       # 4 × (−7.1422963606)
    assert "saved per shard" in r.stdout


def test_scale_bench_end_to_end(tmp_path):
    phases = _run(["--mode", "compact"], tmp_path)
    by = {p["phase"]: p for p in phases}
    assert by["enumerate"]["n_states"] == 12870
    assert not by["enumerate"]["restored"]
    assert by["engine_build"]["ell_gb"] >= 0
    assert by["matvec"]["ms_per_apply"] > 0
    assert by["lanczos"]["iters"] == 4
    assert not by["engine_build"]["structure_restored"]
    # second run restores the representatives AND the engine structure
    phases2 = _run(["--mode", "compact"], tmp_path)
    by2 = {p["phase"]: p for p in phases2}
    assert by2["enumerate"]["restored"]
    assert by2["engine_build"]["structure_restored"]
    assert os.path.exists(str(tmp_path / "c16.h5") + ".structure.h5")
