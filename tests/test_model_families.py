"""Model families beyond the reference's shipped configs (XXZ, TFIM, J1-J2):
the expression compiler + engines must handle them with no special cases.
Ground truths: the independent dense Kronecker path (dense_ref) and, for the
TFIM, the exact free-fermion solution."""

import numpy as np
import pytest

import dense_ref
from distributed_matvec_tpu.models.expression import parse_expression
from distributed_matvec_tpu.models.lattices import (
    chain_edges, j1j2_square, kagome_36_edges, kagome_torus_edges,
    pyrochlore_edges, square_diagonal_edges, square_edges,
    transverse_field_ising_chain, xxz_chain)
from distributed_matvec_tpu.parallel.engine import LocalEngine
from distributed_matvec_tpu.solve import lanczos

ATOL, RTOL = 1e-13, 1e-12


@pytest.fixture
def rng():
    return np.random.default_rng(11)


def _dense(op, exprs):
    h_full = dense_ref.operator_matrix_full(op.basis.number_spins, exprs)
    return dense_ref.projected_matrix(
        op.basis.number_spins, h_full, op.basis.representatives,
        op.basis.norms, op.basis.group)


def test_kagome_torus_structure():
    """Periodic kagome clusters (the benchmark-kagome-36 geometry): every
    site coordination-4, bond count 6 per unit cell, 36 sites at 4×3."""
    for lx, ly in ((4, 3), (3, 4), (3, 3)):
        edges = kagome_torus_edges(lx, ly)
        n = 3 * lx * ly
        deg = np.zeros(n, int)
        for i, j in edges:
            assert 0 <= i < n and 0 <= j < n and i != j
            deg[i] += 1
            deg[j] += 1
        assert (deg == 4).all()
        assert len(edges) == 6 * lx * ly
    assert len(kagome_36_edges()) == 72
    assert max(max(e) for e in kagome_36_edges()) == 35


def test_pyrochlore_structure():
    """Periodic pyrochlore (benchmark-pyrochlore-2x2x2 geometry): every
    site coordination-6, 12 bonds per 4-site cell, 32 sites at 2×2×2."""
    edges = pyrochlore_edges(2, 2, 2)
    n = 32
    deg = np.zeros(n, int)
    for i, j in edges:
        assert 0 <= i < n and 0 <= j < n and i != j
        deg[i] += 1
        deg[j] += 1
    assert (deg == 6).all()
    assert len(edges) == 96


@pytest.mark.parametrize("name,n,edges", [
    ("kagome_2x2", 12, kagome_torus_edges(2, 2)),
    ("pyrochlore_1x1x1", 4, pyrochlore_edges(1, 1, 1)),
])
def test_torus_lattices_vs_independent(name, n, edges):
    """σ-Heisenberg on the small periodic clusters against the independent
    bit-op apply (wrap-doubled bonds carried identically by both sides)."""
    from independent_ref import enumerate_fixed_hw, heisenberg_apply
    from distributed_matvec_tpu.models.basis import SpinBasis
    from distributed_matvec_tpu.models.yaml_io import operator_from_dict

    basis = SpinBasis(number_spins=n, hamming_weight=n // 2)
    op = operator_from_dict({"terms": [{
        "expression": "σˣ₀ σˣ₁ + σʸ₀ σʸ₁ + σᶻ₀ σᶻ₁",
        "sites": [[i, j] for i, j in edges]}]}, basis)
    basis.build()
    states = enumerate_fixed_hw(n, n // 2)
    x = np.random.default_rng(13).standard_normal(states.size)
    y_ind = heisenberg_apply(states, edges, x)
    np.testing.assert_allclose(op.matvec_host(x), y_ind,
                               atol=ATOL, rtol=RTOL)
    np.testing.assert_allclose(np.asarray(LocalEngine(op).matvec(x)), y_ind,
                               atol=ATOL, rtol=RTOL)


@pytest.mark.parametrize("delta", [0.0, 0.5, 2.5])
def test_xxz_engine_matches_dense(delta, rng):
    op = xxz_chain(8, delta=delta)
    op.basis.build()
    sites = [list(e) for e in chain_edges(8)]
    h = _dense(op, [
        (parse_expression("σˣ₀ σˣ₁"), sites),
        (parse_expression("σʸ₀ σʸ₁"), sites),
        (parse_expression(f"{delta!r} × σᶻ₀ σᶻ₁"), sites),
    ])
    x = rng.random(op.basis.number_states) - 0.5
    eng = LocalEngine(op)
    np.testing.assert_allclose(np.asarray(eng.matvec(x)), (h @ x).real,
                               atol=ATOL, rtol=RTOL)


def test_tfim_ground_state_matches_exact():
    """TFIM ring E0 from the free-fermion solution:
    E0 = -(1/2)·Σ_k ε(k), ε(k) = 2·sqrt(1 + h² − 2h·cos k) over the proper
    momenta k = 2π(m+1/2)/n (even-parity sector holds the ground state)."""
    n, h = 10, 0.7
    op = transverse_field_ising_chain(n, h=h)
    op.basis.build()
    assert op.basis.number_states == 2**n
    eng = LocalEngine(op)
    res = lanczos(eng.matvec, op.basis.number_states, k=1, tol=1e-12,
                  seed=5)
    ks = 2 * np.pi * (np.arange(n) + 0.5) / n
    e0_exact = -np.sum(np.sqrt(1 + h * h - 2 * h * np.cos(ks)))
    assert abs(float(res.eigenvalues[0]) - e0_exact) < 1e-8, (
        float(res.eigenvalues[0]), e0_exact)


def test_j1j2_engine_matches_dense(rng):
    op = j1j2_square(2, 4, j2=0.35)
    op.basis.build()
    s1 = [list(e) for e in square_edges(2, 4)]
    s2 = [list(e) for e in square_diagonal_edges(2, 4)]
    exprs = []
    for s, pre in ((s1, ""), (s2, "0.35 × ")):
        exprs += [(parse_expression(f"{pre}σ{a}₀ σ{a}₁"), s)
                  for a in "ˣʸᶻ"]
    h = _dense(op, exprs)
    x = rng.random(op.basis.number_states) - 0.5
    eng = LocalEngine(op)
    np.testing.assert_allclose(np.asarray(eng.matvec(x)), (h @ x).real,
                               atol=ATOL, rtol=RTOL)


def test_kagome_torus_momentum_sectors():
    """2D translation symmetry on the kagome torus (the symmetry-adapted
    form the kagome_36 scale workload uses): on the 2×2 torus (12 sites)
    the sector census must tile the full hamming space, the (0,0)+inversion
    sector must contain the full-basis ground state, and the symmetrized
    engine's E0 must match a dense diagonalization of the UNsymmetrized
    Hamiltonian (independent of the symmetry machinery)."""
    from distributed_matvec_tpu.models.basis import SpinBasis
    from distributed_matvec_tpu.models.lattices import (
        heisenberg_from_edges, kagome_torus_translations)

    lx, ly, hw = 2, 2, 6
    n = 3 * lx * ly
    edges = kagome_torus_edges(lx, ly)

    # census tiles the hamming space over all momentum pairs
    from math import comb
    total = 0
    for kx in range(lx):
        for ky in range(ly):
            b = SpinBasis(n, hw, None,
                          kagome_torus_translations(lx, ly, kx, ky))
            total += b.group.sector_dimension_census(hw)
    assert total == comb(n, hw)

    basis = SpinBasis(n, hw, 1, kagome_torus_translations(lx, ly, 0, 0))
    op = heisenberg_from_edges(basis, edges, spin_half_ops=True)
    basis.build()

    # ground truth from the TEXTBOOK bit-ops reference on the full
    # 924-state hamming space — shares nothing with the expression
    # compiler or the symmetry machinery (σ-form; S = σ/2 ⇒ ÷4)
    from independent_ref import enumerate_fixed_hw, heisenberg_apply

    states = enumerate_fixed_hw(n, hw)
    eye = np.eye(states.size)
    h = np.column_stack(
        [heisenberg_apply(states, edges, eye[:, i]) / 4.0
         for i in range(states.size)])
    e0_full = np.linalg.eigvalsh(h)[0]

    eng = LocalEngine(op, mode="ell")
    r = lanczos(eng.matvec, basis.number_states, k=1, tol=1e-11,
                max_iters=300)
    np.testing.assert_allclose(r.eigenvalues[0], e0_full, atol=1e-9)
