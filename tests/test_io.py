"""HDF5 I/O and checkpoint/restore round-trips (MyHDF5 + Diagonalize analog)."""

import os

import numpy as np
import pytest

h5py = pytest.importorskip("h5py")

from distributed_matvec_tpu.io import (
    load_basis,
    load_eigen,
    make_or_restore_representatives,
    save_basis,
    save_eigen,
)
from distributed_matvec_tpu.models.basis import SpinBasis

# N=10 ring golden ground energy (σ-form = 4× S-form): 4·(−4.5154463544)
_RING10_E0 = 4 * (-4.515446354)
_RING10_YAML = """
basis: {number_spins: 10, hamming_weight: 5}
hamiltonian:
  name: H
  terms:
    - {expression: "σˣ₀ σˣ₁", sites: &l [[0,1],[1,2],[2,3],[3,4],[4,5],[5,6],[6,7],[7,8],[8,9],[9,0]]}
    - {expression: "σʸ₀ σʸ₁", sites: *l}
    - {expression: "σᶻ₀ σᶻ₁", sites: *l}
"""
_APP = os.path.join(os.path.dirname(__file__), os.pardir, "apps",
                    "diagonalize.py")


def _write_ring_yaml(tmp_path):
    yaml_path = str(tmp_path / "m.yaml")
    with open(yaml_path, "w") as f:
        f.write(_RING10_YAML)
    return yaml_path


def _cli_env(**extra):
    return dict(os.environ, JAX_PLATFORMS="cpu", JAX_ENABLE_X64="true",
                PYTHONPATH="/root/repo", **extra)


def test_basis_checkpoint_round_trip(tmp_path):
    path = str(tmp_path / "out.h5")
    b = SpinBasis(12, 6, 1, [([*range(1, 12), 0], 0)])
    restored = make_or_restore_representatives(b, path)
    assert not restored                      # first run computes + saves
    reps, norms = b.representatives.copy(), b.norms.copy()

    b2 = SpinBasis(12, 6, 1, [([*range(1, 12), 0], 0)])
    restored = make_or_restore_representatives(b2, path)
    assert restored                          # second run restores
    np.testing.assert_array_equal(b2.representatives, reps)
    np.testing.assert_allclose(b2.norms, norms, atol=1e-15)


def test_basis_checkpoint_without_path_builds():
    b = SpinBasis(8, 4)
    assert make_or_restore_representatives(b, None) is False
    assert b.is_built


def test_save_load_basis_overwrite(tmp_path):
    path = str(tmp_path / "b.h5")
    save_basis(path, np.arange(5, dtype=np.uint64))
    save_basis(path, np.arange(7, dtype=np.uint64),
               np.ones(7))                   # overwrite grows
    reps, norms = load_basis(path)
    assert reps.size == 7 and norms.size == 7


def test_eigen_round_trip(tmp_path):
    path = str(tmp_path / "e.h5")
    w = np.array([-21.5, -20.1])
    V = np.random.default_rng(0).random((2, 10))
    r = np.array([1e-12, 1e-11])
    save_eigen(path, w, V, r)
    w2, V2, r2 = load_eigen(path)
    np.testing.assert_array_equal(w, w2)
    np.testing.assert_array_equal(V, V2)
    np.testing.assert_array_equal(r, r2)
    # overwrite with fewer evals must not leave stale data
    save_eigen(path, w[:1], V[:1], r[:1])
    w3, V3, _ = load_eigen(path)
    assert w3.size == 1 and V3.shape[0] == 1


def test_diagonalize_cli_end_to_end(tmp_path):
    """The full driver: YAML → solve → HDF5, then restore on rerun —
    Diagonalize.chpl:258-332 parity."""
    import subprocess
    import sys

    yaml_path = _write_ring_yaml(tmp_path)
    out = str(tmp_path / "m.h5")
    env = _cli_env()
    r = subprocess.run([sys.executable, _APP, yaml_path, "-o", out,
                        "-k", "1"],
                       capture_output=True, text=True, env=env, timeout=240)
    assert r.returncode == 0, r.stderr[-2000:]
    w, V, res = load_eigen(out)
    assert abs(w[0] - _RING10_E0) < 1e-7
    assert res[0] < 1e-8
    # rerun hits the restore path
    r2 = subprocess.run([sys.executable, _APP, yaml_path, "-o", out,
                         "-k", "1"],
                        capture_output=True, text=True, env=env, timeout=240)
    assert r2.returncode == 0 and "restored from" in r2.stdout


def test_diagonalize_cli_distributed(tmp_path):
    """The driver on a 4-device virtual mesh (--devices): hashed solve +
    hashed→block eigenvector conversion for I/O must agree with the
    single-device ground state."""
    import subprocess
    import sys

    yaml_path = _write_ring_yaml(tmp_path)
    out = str(tmp_path / "m.h5")
    env = _cli_env(XLA_FLAGS="--xla_force_host_platform_device_count=4")
    r = subprocess.run([sys.executable, _APP, yaml_path, "-o", out,
                        "-k", "1", "--devices", "4"],
                       capture_output=True, text=True, env=env, timeout=240)
    assert r.returncode == 0, r.stderr[-2000:]
    w, V, res = load_eigen(out)
    assert abs(w[0] - _RING10_E0) < 1e-7
    assert res[0] < 1e-8
    # eigenvector written in block (global sorted) order: H·v = E·v on host
    from distributed_matvec_tpu.models.yaml_io import load_config_from_yaml

    cfg = load_config_from_yaml(yaml_path)
    cfg.basis.build()
    v = np.asarray(V[0])
    r_norm = np.linalg.norm(cfg.hamiltonian.matvec_host(v) - w[0] * v)
    assert r_norm < 1e-7, r_norm


def test_diagonalize_cli_observables(tmp_path):
    """--observables computes ⟨ψ₀|O|ψ₀⟩ and saves it under /observables.
    For the ring ground state the total magnetization Σσᶻ is exactly 0."""
    import subprocess
    import sys

    yaml_path = str(tmp_path / "m.yaml")
    with open(yaml_path, "w") as f:
        f.write(_RING10_YAML)
        f.write("""
observables:
  - name: total_sz
    terms:
      - {expression: "σᶻ₀", sites: [[0],[1],[2],[3],[4],[5],[6],[7],[8],[9]]}
  - name: nn_corr
    terms:
      - {expression: "σˣ₀ σˣ₁ + σʸ₀ σʸ₁ + σᶻ₀ σᶻ₁", sites: [[0, 1]]}
""")
    out = str(tmp_path / "m.h5")
    r = subprocess.run([sys.executable, _APP, yaml_path, "-o", out,
                        "-k", "1", "--observables"],
                       capture_output=True, text=True, env=_cli_env(),
                       timeout=240)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "<total_sz>" in r.stdout
    with h5py.File(out, "r") as f:
        val = float(f["observables/total_sz"][()])
        corr = float(f["observables/nn_corr"][()])
    assert abs(val) < 1e-9, val
    # the off-diagonal correlator goes through the fused ENGINE in the
    # driver — cross-check against the independent host matvec on the
    # saved eigenvector
    from distributed_matvec_tpu.models.yaml_io import load_config_from_yaml

    cfg = load_config_from_yaml(yaml_path, observables=True)
    cfg.basis.build()
    _, V, _ = load_eigen(out)
    psi = np.asarray(V[0])
    obs = next(o for o in cfg.observables if o.name == "nn_corr")
    want = float(np.vdot(psi, obs.matvec_host(psi)).real)
    assert abs(corr - want) < 1e-10, (corr, want)
    # translation invariance of the ring GS: Σσᶻ = 0 but the bond
    # correlator is E0 / n_bonds (H is the sum of 10 identical bonds)
    w, _, _ = load_eigen(out)
    assert abs(corr - w[0] / 10) < 1e-6, (corr, w[0] / 10)


def test_diagonalize_cli_observables_distributed(tmp_path):
    """--observables on a 4-device mesh: expectation runs through the
    distributed fused engine (to_hashed → matvec → dot) and must agree
    with the host value."""
    import subprocess
    import sys

    yaml_path = str(tmp_path / "m.yaml")
    with open(yaml_path, "w") as f:
        f.write(_RING10_YAML)
        f.write("""
observables:
  - name: nn_corr
    terms:
      - {expression: "σˣ₀ σˣ₁ + σʸ₀ σʸ₁ + σᶻ₀ σᶻ₁", sites: [[0, 1]]}
""")
    out = str(tmp_path / "m.h5")
    env = _cli_env(XLA_FLAGS="--xla_force_host_platform_device_count=4")
    r = subprocess.run([sys.executable, _APP, yaml_path, "-o", out,
                        "-k", "1", "--devices", "4", "--observables"],
                       capture_output=True, text=True, env=env, timeout=240)
    assert r.returncode == 0, r.stderr[-2000:]
    with h5py.File(out, "r") as f:
        corr = float(f["observables/nn_corr"][()])
    w, V, _ = load_eigen(out)
    from distributed_matvec_tpu.models.yaml_io import load_config_from_yaml

    cfg = load_config_from_yaml(yaml_path, observables=True)
    cfg.basis.build()
    psi = np.asarray(V[0])
    want = float(np.vdot(psi, cfg.observables[0].matvec_host(psi)).real)
    assert abs(corr - want) < 1e-10, (corr, want)
    assert abs(corr - w[0] / 10) < 1e-6


@pytest.mark.slow
def test_diagonalize_cli_multihost(tmp_path):
    """--coordinator/--num-processes drive a REAL 2-process multi-controller
    run of the driver (4 CPU devices per process, one 8-device mesh); rank 0
    owns the output file.  Exercises the path the flags exist for."""
    import socket
    import subprocess
    import sys

    yaml_path = _write_ring_yaml(tmp_path)
    out = str(tmp_path / "m.h5")
    env = _cli_env(XLA_FLAGS="--xla_force_host_platform_device_count=4")
    # one retry: under heavy load the jax.distributed coordinator
    # rendezvous can time out spuriously
    for attempt in range(2):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs = [subprocess.Popen(
            [sys.executable, _APP, yaml_path, "-o", out, "-k", "1",
             "--devices", "8",
             "--coordinator", f"127.0.0.1:{port}",
             "--num-processes", "2", "--process-id", str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
            for pid in range(2)]
        try:
            outs = [p.communicate(timeout=420)[0] for p in procs]
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            raise
        if all(p.returncode == 0 for p in procs) or attempt:
            break
    for pid, (p, o) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {pid}:\n{o[-2000:]}"
    w, V, res = load_eigen(out)
    assert abs(w[0] - _RING10_E0) < 1e-7
    assert res[0] < 1e-8
