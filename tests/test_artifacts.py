"""Default-on artifact cache: warm-start engines, basis checkpoints,
fingerprint safety, and the batched multi-RHS apply they feed.

The suite-wide conftest forces ``DMT_ARTIFACT_CACHE=off`` (hermeticity —
engines must not restore structures a previous session left in ~/.cache);
these tests re-enable the layer against a session-scoped tmp root.
"""

import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from distributed_matvec_tpu.parallel.engine import LocalEngine

from test_operator import build_heisenberg

ATOL, RTOL = 1e-13, 1e-12
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="session")
def artifact_root_dir(tmp_path_factory):
    # session-scoped: JAX's persistent compilation cache dir is process
    # global once set, so it must outlive any single test's tmp_path
    return str(tmp_path_factory.mktemp("artifacts"))


@pytest.fixture
def artifacts_on(artifact_root_dir, monkeypatch):
    monkeypatch.setenv("DMT_ARTIFACT_CACHE", "on")
    monkeypatch.setenv("DMT_ARTIFACT_DIR", artifact_root_dir)
    return artifact_root_dir


def test_artifacts_off_no_restore(tmp_path, monkeypatch):
    """With the layer off (the suite default) engines never restore."""
    from distributed_matvec_tpu.utils.artifacts import (
        artifacts_enabled, default_structure_cache)
    assert not artifacts_enabled()
    assert default_structure_cache("ab" * 32) is None
    op = build_heisenberg(10, 5, None, ())
    e1 = LocalEngine(op, mode="ell")
    e2 = LocalEngine(op, mode="ell")
    assert not e1.structure_restored and not e2.structure_restored


def test_warm_start_round_trip(artifacts_on, rng):
    """Cold build fills the cache; a warm engine over a FRESH basis object
    restores representatives + structure with zero structure-build kernel
    launches, and its matvec matches the cold engine to the golden
    tolerances."""
    op1 = build_heisenberg(12, 6, 1, ())
    e1 = LocalEngine(op1, mode="ell")
    assert not e1.structure_restored          # cold: cache was empty
    n = op1.basis.number_states
    x = rng.random(n) - 0.5
    y1 = np.asarray(e1.matvec(x))

    # fresh operator/basis objects: nothing carried over in memory
    op2 = build_heisenberg(12, 6, 1, ())
    assert not op2.basis.is_built
    e2 = LocalEngine(op2, mode="ell")
    assert e2.basis_restored                  # representatives from basis/
    assert e2.structure_restored              # tables from structure/
    # zero structure-build kernel launches: the timer scope never opened
    assert "build_structure" not in e2.timer.root.children
    np.testing.assert_allclose(np.asarray(e2.matvec(x)), y1,
                               atol=ATOL, rtol=RTOL)


def test_fingerprint_mismatch_rebuilds(artifacts_on, rng):
    """A different operator (2H) or different padding (batch_size) must
    MISS the cache and rebuild cleanly — restored tables keyed by content,
    never by name."""
    op = build_heisenberg(10, 5, None, ())
    e1 = LocalEngine(op, mode="ell", batch_size=64)
    assert not e1.structure_restored
    n = op.basis.number_states
    x = rng.random(n) - 0.5

    # same basis, same batch: hit
    e2 = LocalEngine(build_heisenberg(10, 5, None, ()), mode="ell",
                     batch_size=64)
    assert e2.structure_restored

    # scaled operator: different term tables -> miss, and 2H·x == 2·(H·x)
    op2 = 2.0 * build_heisenberg(10, 5, None, ())
    e3 = LocalEngine(op2, mode="ell", batch_size=64)
    assert not e3.structure_restored
    np.testing.assert_allclose(np.asarray(e3.matvec(x)),
                               2.0 * np.asarray(e1.matvec(x)),
                               atol=1e-12)

    # different padding geometry: different fingerprint -> miss
    e4 = LocalEngine(build_heisenberg(10, 5, None, ()), mode="ell",
                     batch_size=32)
    assert not e4.structure_restored
    np.testing.assert_allclose(np.asarray(e4.matvec(x)),
                               np.asarray(e1.matvec(x)), atol=ATOL,
                               rtol=RTOL)


def test_size_cap_skips_default_save(artifacts_on, monkeypatch, rng):
    """A structure beyond artifact_max_gb is rebuilt per process instead of
    filling the cache disk (default-path saves only)."""
    from distributed_matvec_tpu.utils.config import get_config
    monkeypatch.setattr(get_config(), "artifact_max_gb", 1e-9)
    op = build_heisenberg(8, 4, None, ())
    e1 = LocalEngine(op, mode="ell")
    assert not e1.structure_restored
    e2 = LocalEngine(build_heisenberg(8, 4, None, ()), mode="ell")
    assert not e2.structure_restored          # save was size-capped away


def test_basis_artifact_round_trip(artifacts_on):
    from distributed_matvec_tpu.utils.artifacts import make_or_restore_basis
    op1 = build_heisenberg(14, 7, None, ())
    assert make_or_restore_basis(op1.basis) is False     # fresh build
    op2 = build_heisenberg(14, 7, None, ())
    assert make_or_restore_basis(op2.basis) is True      # checkpoint hit
    np.testing.assert_array_equal(op1.basis.representatives,
                                  op2.basis.representatives)
    np.testing.assert_array_equal(op1.basis.norms, op2.basis.norms)
    # a different sector must not hit the same checkpoint
    op3 = build_heisenberg(14, 6, None, ())
    assert make_or_restore_basis(op3.basis) is False


def test_compact_mode_warm_start(artifacts_on, rng):
    op1 = build_heisenberg(10, 5, None, ())
    e1 = LocalEngine(op1, mode="compact")
    assert not e1.structure_restored
    x = rng.random(op1.basis.number_states) - 0.5
    y1 = np.asarray(e1.matvec(x))
    e2 = LocalEngine(build_heisenberg(10, 5, None, ()), mode="compact")
    assert e2.structure_restored
    assert "build_structure" not in e2.timer.root.children
    np.testing.assert_allclose(np.asarray(e2.matvec(x)), y1,
                               atol=ATOL, rtol=RTOL)


def test_distributed_warm_start(artifacts_on, rng):
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    from distributed_matvec_tpu.parallel.distributed import DistributedEngine
    op1 = build_heisenberg(10, 5, None, ())
    e1 = DistributedEngine(op1, n_devices=2, mode="ell", batch_size=64)
    assert not e1.structure_restored
    x = rng.random(op1.basis.number_states) - 0.5
    y1 = np.asarray(e1.matvec_global(x))
    e2 = DistributedEngine(build_heisenberg(10, 5, None, ()), n_devices=2,
                           mode="ell", batch_size=64)
    assert e2.basis_restored and e2.structure_restored
    assert "build_plan" not in e2.timer.root.children
    np.testing.assert_allclose(np.asarray(e2.matvec_global(x)), y1,
                               atol=ATOL, rtol=RTOL)


def test_batched_multi_rhs_matches_single(rng):
    """[N, 4] native apply == 4 single applies at the golden tolerances
    (the acceptance contract of the batched gather-once path)."""
    op = build_heisenberg(12, 6, None, ())
    eng = LocalEngine(op, mode="ell")
    n = op.basis.number_states
    X = rng.random((n, 4)) - 0.5
    Y = np.asarray(eng.matvec(X))
    assert Y.shape == (n, 4)
    for j in range(4):
        np.testing.assert_allclose(Y[:, j], np.asarray(eng.matvec(X[:, j])),
                                   atol=ATOL, rtol=RTOL)


def test_warm_cache_tool(artifact_root_dir, tmp_path):
    """tools/warm_cache.py fills the cache; a second run restores
    everything (the `make warm-cache` → fast-bench contract)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_ENABLE_X64="true")
    env.pop("DMT_ARTIFACT_CACHE", None)
    root = str(tmp_path / "warmroot")

    def run():
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "warm_cache.py"),
             "--configs", "smoke", "--artifact-dir", root],
            capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
        assert r.returncode == 0, (r.stdout[-800:], r.stderr[-1500:])
        import json
        lines = [json.loads(li) for li in r.stdout.splitlines() if li]
        assert lines[0]["artifact_root"] == root
        return {d["config"]: d for d in lines[1:]}

    cold = run()
    assert not cold["chain_16"]["basis_restored"]
    assert not cold["chain_16"]["structure_restored"]
    warm = run()
    assert warm["chain_16"]["basis_restored"]
    assert warm["chain_16"]["structure_restored"]
    assert os.path.isdir(os.path.join(root, "structure"))
    assert os.path.isdir(os.path.join(root, "basis"))
