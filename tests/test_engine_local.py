"""Single-device jitted matvec vs host matvec and the dense reference.

The golden-test contract of TestMatrixVectorProduct.chpl:15-23 (atol 1e-14 /
rtol 1e-12, full pipeline) applied to the device path, in both engine modes
(precomputed-ELL and fused/on-the-fly).
"""

import numpy as np
import pytest

from distributed_matvec_tpu.models.basis import SpinBasis
from distributed_matvec_tpu.parallel.engine import LocalEngine

from test_operator import CONFIGS, build_heisenberg, dense_effective_matrix

ATOL, RTOL = 1e-13, 1e-12


@pytest.mark.parametrize("mode", ["ell", "fused"])
@pytest.mark.parametrize("n,hw,inv,syms", CONFIGS)
def test_local_engine_matches_dense(n, hw, inv, syms, mode, rng):
    op = build_heisenberg(n, hw, inv, syms)
    op.basis.build()
    h_eff = dense_effective_matrix(op)
    x = rng.random(op.basis.number_states) - 0.5
    if not op.effective_is_real:
        x = x.astype(np.complex128)
    eng = LocalEngine(op, batch_size=61, mode=mode)  # force chunking + padding
    y = np.asarray(eng.matvec(x))
    y_ref = h_eff @ x
    if op.effective_is_real:
        y_ref = y_ref.real
    np.testing.assert_allclose(y, y_ref, atol=ATOL, rtol=RTOL)


@pytest.mark.parametrize("mode", ["ell", "fused"])
def test_single_chunk_path(mode, rng):
    op = build_heisenberg(8, 4)
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    eng = LocalEngine(op, mode=mode)  # batch larger than basis → one chunk
    assert eng.num_chunks == 1
    y = np.asarray(eng.matvec(x))
    np.testing.assert_allclose(y, op.matvec_host(x), atol=ATOL, rtol=RTOL)


@pytest.mark.parametrize("mode", ["ell", "fused"])
def test_batch_matvec_matches_columns(mode, rng):
    """Rank-2 batches: matvec(X)[:, i] == matvec(X[:, i]) — the numVectors
    contract of ls_chpl_matrix_vector_product (DistributedMatrixVector.chpl:1095-1110)."""
    op = build_heisenberg(10, 5, -1)
    op.basis.build()
    n = op.basis.number_states
    X = rng.random((n, 3)) - 0.5
    eng = LocalEngine(op, batch_size=100, mode=mode)
    Y = np.asarray(eng.matvec(X))
    for k in range(X.shape[1]):
        np.testing.assert_allclose(
            Y[:, k], np.asarray(eng.matvec(X[:, k])), atol=ATOL, rtol=RTOL
        )


@pytest.mark.parametrize("mode", ["ell", "fused"])
def test_engine_detects_sector_violation(mode):
    """σˣ alone breaks hamming conservation → engine must raise (the halt
    analog of DistributedMatrixVector.chpl:113-118).  In ell mode the check
    fires at structure-build time, in fused mode on the first matvec."""
    from distributed_matvec_tpu.models.operator import Operator

    basis = SpinBasis(6, 3)
    op = Operator.from_expressions(basis, [("σˣ₀", [[0], [1]])])
    basis.build()
    with pytest.raises(RuntimeError, match="outside the basis"):
        eng = LocalEngine(op, mode=mode)
        eng.matvec(np.ones(basis.number_states))


def test_matvec_is_jit_cached(rng):
    op = build_heisenberg(10, 5, -1)
    op.basis.build()
    eng = LocalEngine(op, batch_size=32)
    x = rng.random(op.basis.number_states) - 0.5
    y1 = eng.matvec(x)
    y2 = eng.matvec(2 * x)
    np.testing.assert_allclose(2 * np.asarray(y1), np.asarray(y2), atol=1e-13)


def test_non_hermitian_rejected():
    from distributed_matvec_tpu.models.operator import Operator

    basis = SpinBasis(4, 2)
    op = Operator.from_expressions(basis, [("σ⁺₀ σ⁻₁", [[0, 1]])])
    basis.build()
    assert not op.is_hermitian
    with pytest.raises(ValueError, match="Hermitian"):
        LocalEngine(op)


def test_ell_split_tail_path_exercised(rng):
    """Deterministically drive the two-level ELL split (main + scatter tail).

    A periodic Heisenberg chain in the hamming sector has skewed row widths
    (~50% fill), so the split must trigger; assert it did — a tail bug must
    not be able to hide behind an unsplit table — and that the split matvec
    still matches the host path at golden tolerances.
    """
    op = build_heisenberg(16, 8, None)
    op.basis.build()
    eng = LocalEngine(op, mode="ell")
    assert eng._ell_T0 < eng.num_terms, "split did not trigger"
    assert eng._ell_tail is not None, "tail path not exercised"
    n = op.basis.number_states
    x = rng.random(n) - 0.5
    np.testing.assert_allclose(np.asarray(eng.matvec(x)), op.matvec_host(x),
                               atol=1e-13, rtol=1e-12)
    X = np.stack([x, rng.random(n) - 0.5], axis=1)
    Y = np.asarray(eng.matvec(X))
    for k in range(2):
        np.testing.assert_allclose(Y[:, k], op.matvec_host(X[:, k]),
                                   atol=1e-13, rtol=1e-12)


def test_lowmem_build_matches_onepass(rng):
    """The two-pass low-memory ELL build (count → pack) produces the exact
    tables of the one-pass build: same split point, bit-identical matvec.
    Exercised on a config with a scatter tail (the tricky sequential-slab
    assembly) and on a complex momentum sector in pair form."""
    from distributed_matvec_tpu.utils.config import update_config

    cases = [
        (16, 8, None, (), "auto"),       # real, tail path triggers
        (12, 6, None,
         [([1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 0], 2)], "on"),  # pair
    ]
    from distributed_matvec_tpu.utils.config import get_config

    prev_budget = get_config().ell_build_budget_gb
    prev_pair = get_config().complex_pair
    for n, hw, inv, syms, pairmode in cases:
        op = build_heisenberg(n, hw, inv, syms)
        op.basis.build()
        update_config(complex_pair=pairmode)
        try:
            eng_ref = LocalEngine(op, batch_size=61, mode="ell")
            update_config(ell_build_budget_gb=1e-9)   # force two-pass
            eng_lm = LocalEngine(op, batch_size=61, mode="ell")
        finally:
            update_config(ell_build_budget_gb=prev_budget,
                          complex_pair=prev_pair)
        assert eng_lm._ell_T0 == eng_ref._ell_T0
        if eng_ref._ell_tail is not None:
            assert eng_lm._ell_tail is not None
        N = op.basis.number_states
        x = rng.random(N) - 0.5
        if not op.effective_is_real:
            x = x + 1j * (rng.random(N) - 0.5)
        y_ref = np.asarray(eng_ref.matvec(x))
        y_lm = np.asarray(eng_lm.matvec(x))
        np.testing.assert_array_equal(y_ref, y_lm)


def test_compact_mode_matches_dense(rng):
    """compact mode (sign-tagged 4 B/entry, coefficients derived as
    W·s·n(j)/n(i) at matvec time) matches the dense reference for isotropic
    Heisenberg sectors, rank-1 and rank-2, both gather paths."""
    from distributed_matvec_tpu.utils.config import get_config, update_config

    prev = get_config().split_gather
    op = build_heisenberg(12, 6, 1,
                          [([1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 0], 0),
                           ([11, 10, 9, 8, 7, 6, 5, 4, 3, 2, 1, 0], 0)])
    op.basis.build()
    h = dense_effective_matrix(op)
    N = op.basis.number_states
    x = rng.random(N) - 0.5
    X = rng.random((N, 3)) - 0.5
    try:
        for sg in ("off", "on"):
            update_config(split_gather=sg)
            eng = LocalEngine(op, batch_size=61, mode="compact")
            np.testing.assert_allclose(np.asarray(eng.matvec(x)), h @ x,
                                       atol=1e-13, rtol=1e-12)
            np.testing.assert_allclose(np.asarray(eng.matvec(X)), h @ X,
                                       atol=1e-13, rtol=1e-12)
    finally:
        update_config(split_gather=prev)


def test_compact_mode_xxz_qualifies(rng):
    """Anisotropy (Δ) only rescales the DIAGONAL, so the XXZ chain keeps a
    single off-diagonal magnitude and qualifies for compact mode."""
    from distributed_matvec_tpu.models.lattices import xxz_chain

    op = xxz_chain(10, delta=0.37)
    op.basis.build()
    eng = LocalEngine(op, mode="compact")
    n = op.basis.number_states
    x = rng.random(n) - 0.5
    np.testing.assert_allclose(np.asarray(eng.matvec(x)), op.matvec_host(x),
                               atol=1e-13, rtol=1e-12)


def test_compact_mode_refusals():
    """compact mode must refuse anisotropic couplings (several off-diagonal
    magnitudes) and complex-character sectors."""
    from distributed_matvec_tpu.models.basis import SpinBasis
    from distributed_matvec_tpu.models.lattices import (chain_edges,
                                                        heisenberg_from_edges)

    b = SpinBasis(8, 4)
    op = heisenberg_from_edges(b, chain_edges(8)) \
        + 0.44 * heisenberg_from_edges(b, [(i, (i + 2) % 8)
                                           for i in range(8)])
    b.build()
    with pytest.raises(ValueError, match="single off-diagonal magnitude"):
        LocalEngine(op, mode="compact")

    b2 = SpinBasis(10, 5, None, [([1, 2, 3, 4, 5, 6, 7, 8, 9, 0], 1)])
    op2 = heisenberg_from_edges(b2, chain_edges(10))
    b2.build()
    with pytest.raises(ValueError, match="real sector"):
        LocalEngine(op2, mode="compact")


def test_structure_cache_roundtrip(tmp_path, rng):
    """ELL/compact structure checkpoints restore bit-identically and are
    keyed by a fingerprint: a different operator must NOT reuse them."""
    path = str(tmp_path / "cache.h5")
    op = build_heisenberg(12, 6, 1,
                          [([1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 0], 0),
                           ([11, 10, 9, 8, 7, 6, 5, 4, 3, 2, 1, 0], 0)])
    op.basis.build()
    N = op.basis.number_states
    x = rng.random(N) - 0.5

    for mode in ("ell", "compact"):
        eng1 = LocalEngine(op, batch_size=61, mode=mode,
                           structure_cache=path)
        y1 = np.asarray(eng1.matvec(x))
        # second construction must restore, not rebuild
        import distributed_matvec_tpu.parallel.engine as E
        builder = "_build_ell" if mode == "ell" else "_build_compact"
        orig = getattr(E.LocalEngine, builder)
        def _boom(self):
            raise AssertionError("structure cache was not used")
        setattr(E.LocalEngine, builder, _boom)
        try:
            eng2 = LocalEngine(op, batch_size=61, mode=mode,
                               structure_cache=path)
        finally:
            setattr(E.LocalEngine, builder, orig)
        np.testing.assert_array_equal(y1, np.asarray(eng2.matvec(x)))

    # a different operator (scaled coupling) must invalidate the cache
    op2 = 2.0 * build_heisenberg(
        12, 6, 1, [([1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 0], 0),
                   ([11, 10, 9, 8, 7, 6, 5, 4, 3, 2, 1, 0], 0)])
    op2.basis.build()
    eng3 = LocalEngine(op2, batch_size=61, mode="ell",
                       structure_cache=path)
    np.testing.assert_allclose(np.asarray(eng3.matvec(x)),
                               2.0 * np.asarray(
                                   LocalEngine(op, batch_size=61,
                                               mode="ell").matvec(x)),
                               atol=1e-13)


def test_structure_cache_pair_roundtrip(tmp_path, rng):
    """Pair-form (re,im)-f64 coefficient tables checkpoint and restore
    bit-identically too (complex momentum sector)."""
    from distributed_matvec_tpu.utils.config import get_config, update_config

    path = str(tmp_path / "pair.h5")
    op = build_heisenberg(12, 6, None,
                          [([1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 0], 2)])
    op.basis.build()
    N = op.basis.number_states
    x = (rng.random(N) - 0.5) + 1j * (rng.random(N) - 0.5)
    prev = get_config().complex_pair
    update_config(complex_pair="on")
    try:
        e1 = LocalEngine(op, batch_size=61, mode="ell",
                         structure_cache=path)
        assert e1.pair and not e1.structure_restored
        y1 = np.asarray(e1.matvec(x))
        e2 = LocalEngine(op, batch_size=61, mode="ell",
                         structure_cache=path)
        assert e2.structure_restored
        np.testing.assert_array_equal(y1, np.asarray(e2.matvec(x)))
        # a native-c128 engine must NOT reuse the pair checkpoint
        update_config(complex_pair="off")
        e3 = LocalEngine(op, batch_size=61, mode="ell",
                         structure_cache=path)
        assert not e3.structure_restored
        np.testing.assert_allclose(np.asarray(e3.matvec(x)), y1,
                                   atol=1e-15, rtol=1e-14)
    finally:
        update_config(complex_pair=prev)


def test_ell_split_cost_model_properties():
    """choose_ell_split: scatter-heavy layouts are rejected, truncation-only
    wins are kept, and degenerate histograms fall back to the full table."""
    from distributed_matvec_tpu.parallel.engine import choose_ell_split

    T, n = 16, 1000
    # all rows full width → no split possible
    hist = np.zeros(T + 1, np.int64)
    hist[T] = n
    assert choose_ell_split(hist, n, T) == (T, 0, T)
    # uniform narrow rows → pure truncation (no tail) must be kept
    hist = np.zeros(T + 1, np.int64)
    hist[4] = n
    T0, S, Tmax = choose_ell_split(hist, n, T)
    assert (T0, S, Tmax) == (4, 0, 4)
    # a few wide rows over a narrow bulk → split with a small tail
    hist = np.zeros(T + 1, np.int64)
    hist[4] = n - 10
    hist[T] = 10
    T0, S, Tmax = choose_ell_split(hist, n, T)
    assert T0 == 4 and S == 10 and Tmax == T
    # empty basis → full-width fallback, no crash
    assert choose_ell_split(np.zeros(T + 1, np.int64), 0, T) == (T, 0, 0)


def test_ell_split_gate_uses_real_rows():
    """Padded rows (nnz=0) must not widen the tail budget: with few real
    rows among many pad rows the whole operator must NOT land in the tail."""
    from distributed_matvec_tpu.parallel.engine import choose_ell_split

    T = 10
    hist = np.zeros(T + 1, np.int64)
    hist[0] = 772       # pad rows
    hist[T] = 252       # real rows, all full width
    T0, S, Tmax = choose_ell_split(hist, 1024, T, real_rows=252)
    assert T0 == T and S == 0, "all-real-rows tail slipped past the gate"


def test_split_gather_matches_plain(rng):
    """Forcing the triple-f32 split-gather path (ops/split_gather.py) must
    reproduce the plain-gather matvec to the last ulp — f64 and complex
    sectors, rank-1 and rank-2, ell and fused modes.  (The split/join itself
    is exact; the residual ~1-ulp wiggle comes from XLA fusing the two
    separately compiled programs differently, e.g. CPU FMA contraction.)"""
    from distributed_matvec_tpu.utils.config import update_config

    cases = [
        build_heisenberg(12, 6, None),                       # f64
        build_heisenberg(10, 5, None, [([*range(1, 10), 0], 1)]),  # c128
    ]
    for op in cases:
        op.basis.build()
        n = op.basis.number_states
        x = rng.random(n) - 0.5
        X = np.stack([x, rng.random(n) - 0.5], axis=1)
        for mode in ("ell", "fused"):
            update_config(split_gather="off")
            ref_eng = LocalEngine(op, mode=mode)
            y_ref = np.asarray(ref_eng.matvec(x))
            Y_ref = np.asarray(ref_eng.matvec(X))
            update_config(split_gather="on")
            try:
                eng = LocalEngine(op, mode=mode)
                y = np.asarray(eng.matvec(x))
                Y = np.asarray(eng.matvec(X))
            finally:
                update_config(split_gather="auto")
            np.testing.assert_allclose(y, y_ref, atol=1e-14, rtol=1e-14)
            np.testing.assert_allclose(Y, Y_ref, atol=1e-14, rtol=1e-14)


def test_complex_on_tpu_guard(monkeypatch):
    """Complex sectors must fail LOUDLY on a TPU backend (this platform's
    compiler hangs on any complex128 program) — not hang for hours; the
    allow_complex_on_tpu knob bypasses the guard."""
    import jax

    from distributed_matvec_tpu.parallel.engine import check_complex_backend
    from distributed_matvec_tpu.utils.config import update_config

    from distributed_matvec_tpu.utils.config import get_config

    check_complex_backend(True)                  # real: never gated
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    with pytest.raises(RuntimeError, match="complex128.*TPU"):
        check_complex_backend(False)
    check_complex_backend(False, platform="cpu")  # CPU mesh on TPU host: ok
    prev = get_config().allow_complex_on_tpu
    update_config(allow_complex_on_tpu=True)
    try:
        check_complex_backend(False)             # override allows
    finally:
        update_config(allow_complex_on_tpu=prev)


def test_traced_matvec_validates_via_callback():
    """A caller that only ever runs ``engine.matvec`` under its own jit
    (no eager probe) must still get loud sector-violation detection: a
    one-time RuntimeWarning at trace time, run-time validation through
    ``jax.debug.callback``, and a sticky failure re-raised by the next
    eager matvec even when the runtime swallows the callback exception."""
    import time

    import jax

    from distributed_matvec_tpu.models.operator import Operator

    basis = SpinBasis(6, 3)
    op = Operator.from_expressions(basis, [("σˣ₀", [[0], [1]])])
    basis.build()
    eng = LocalEngine(op, mode="fused")
    x = np.ones(basis.number_states)
    with pytest.warns(RuntimeWarning, match="traced before any eager"):
        try:
            jax.block_until_ready(jax.jit(eng.matvec)(x))
        except Exception:
            pass            # the callback's own exception may surface here
    deadline = time.time() + 10         # callbacks may complete async
    while eng._deferred_failure is None and time.time() < deadline:
        time.sleep(0.05)
    with pytest.raises(RuntimeError, match="outside the basis"):
        eng.matvec(x)


def test_traced_matvec_callback_marks_checked(rng):
    """The positive side: a VALID operator traced first validates through
    the callback and marks the engine checked — later eager calls skip
    re-validation and match the eager result."""
    import time

    import jax

    op = build_heisenberg(10, 5)
    op.basis.build()
    eng = LocalEngine(op, mode="fused", batch_size=32)
    x = rng.random(op.basis.number_states) - 0.5
    with pytest.warns(RuntimeWarning, match="traced before any eager"):
        y = np.asarray(jax.jit(eng.matvec)(x))
    deadline = time.time() + 10
    while not eng._checked and time.time() < deadline:
        time.sleep(0.05)
    assert eng._checked and eng._deferred_failure is None
    np.testing.assert_allclose(y, np.asarray(eng.matvec(x)),
                               atol=ATOL, rtol=RTOL)
