"""Single-device jitted matvec vs host matvec and the dense reference.

The golden-test contract of TestMatrixVectorProduct.chpl:15-23 (atol 1e-14 /
rtol 1e-12, full pipeline) applied to the device path.
"""

import numpy as np
import pytest

from distributed_matvec_tpu.models.basis import SpinBasis
from distributed_matvec_tpu.parallel.engine import LocalEngine

from test_operator import CONFIGS, build_heisenberg, dense_effective_matrix

ATOL, RTOL = 1e-13, 1e-12


@pytest.mark.parametrize("n,hw,inv,syms", CONFIGS)
def test_local_engine_matches_dense(n, hw, inv, syms, rng):
    op = build_heisenberg(n, hw, inv, syms)
    op.basis.build()
    h_eff = dense_effective_matrix(op)
    x = rng.random(op.basis.number_states) - 0.5
    if not op.effective_is_real:
        x = x.astype(np.complex128)
    eng = LocalEngine(op, batch_size=61)  # force multiple chunks + padding
    y = np.asarray(eng.matvec(x))
    y_ref = h_eff @ x
    if op.effective_is_real:
        y_ref = y_ref.real
    np.testing.assert_allclose(y, y_ref, atol=ATOL, rtol=RTOL)


def test_single_chunk_path(rng):
    op = build_heisenberg(8, 4)
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    eng = LocalEngine(op)  # batch larger than basis → one chunk
    assert eng.num_chunks == 1
    y = np.asarray(eng.matvec(x))
    np.testing.assert_allclose(y, op.matvec_host(x), atol=ATOL, rtol=RTOL)


def test_engine_detects_sector_violation():
    """σˣ alone breaks hamming conservation → engine must raise."""
    from distributed_matvec_tpu.models.operator import Operator

    basis = SpinBasis(6, 3)
    op = Operator.from_expressions(basis, [("σˣ₀", [[0], [1]])])
    basis.build()
    eng = LocalEngine(op)
    with pytest.raises(RuntimeError, match="outside the basis"):
        eng.matvec(np.ones(basis.number_states))


def test_matvec_is_jit_cached(rng):
    op = build_heisenberg(10, 5, -1)
    op.basis.build()
    eng = LocalEngine(op, batch_size=32)
    x = rng.random(op.basis.number_states) - 0.5
    y1 = eng.matvec(x)
    y2 = eng.matvec(2 * x)
    np.testing.assert_allclose(2 * np.asarray(y1), np.asarray(y2), atol=1e-13)
