"""Fault-tolerance layer: deterministic injection, retry/quarantine I/O,
preemption-safe solves, heartbeat watchdog (ISSUE 6).

In-process legs of the chaos story (`tools/fault_check.py` drives the
subprocess kill/resume legs): the ``DMT_FAULT`` registry semantics and its
provable inertness when unset (no-op singleton + byte-identical apply
HLO, the ``DMT_OBS=off`` guard style), the bounded-retry helper, the
corrupt-artifact rebuild/quarantine policy on every existing failure path
(basis checkpoint, structure sidecar, streamed disk-tier plan chunks),
the concurrent-writer atomicity of ``os.replace`` sidecar saves, the
SIGTERM latch → generation-consistent checkpoint → ``Preempted`` contract
in both Lanczos and LOBPCG, and the stall watchdog's report."""

import gc
import json
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_matvec_tpu import obs
from distributed_matvec_tpu.solve import lanczos, lanczos_block, lobpcg
from distributed_matvec_tpu.utils import faults, preempt
from distributed_matvec_tpu.utils.config import get_config, update_config
from test_operator import build_heisenberg


@pytest.fixture
def clean_faults(monkeypatch):
    """Fresh fault registry + latch + obs state; everything restored."""
    monkeypatch.delenv("DMT_FAULT", raising=False)
    faults.reset()
    preempt.reset()
    obs.reset_all()
    yield monkeypatch
    faults.reset()
    preempt.reset()
    obs.reset_all()


def _arm(monkeypatch, spec):
    monkeypatch.setenv("DMT_FAULT", spec)
    faults.reset()


# ---------------------------------------------------------------------------
# registry semantics


def test_faults_unset_is_noop_singleton(clean_faults):
    """Unset → the shared null registry: check() is inert for every site
    and no state/instrument is created."""
    assert not faults.enabled()
    r1 = faults._registry()
    faults.check("exchange")
    faults.check("anything_at_all", exc=RuntimeError)
    assert faults._registry() is r1 is faults._NULL
    assert faults.fired_count("exchange") == 0
    assert obs.events() == []


def test_fault_fires_then_heals(clean_faults):
    """Default n=1: exactly one failure, then the site is spent — the
    shape every retry path needs."""
    _arm(clean_faults, "artifact_read")
    with pytest.raises(OSError, match=r"\[fault-injection\]"):
        faults.check("artifact_read")
    faults.check("artifact_read")          # healed
    assert faults.fired_count("artifact_read") == 1
    kinds = [e["kind"] for e in obs.events()]
    assert "fault_injected" in kinds
    assert obs.snapshot()["counters"][
        "fault_injected{site=artifact_read}"] == 1


def test_fault_spec_fields(clean_faults):
    """skip/n windows and caller-chosen exception types."""
    _arm(clean_faults, "s:skip=2:n=2")
    for _ in range(2):
        faults.check("s", exc=RuntimeError)     # skipped
    for _ in range(2):
        with pytest.raises(RuntimeError):
            faults.check("s", exc=RuntimeError)
    faults.check("s", exc=RuntimeError)         # budget spent
    assert faults.fired_count("s") == 2


def test_fault_probability_deterministic(clean_faults):
    """p < 1 draws from a per-site seeded RNG: two processes (registries)
    with the same spec fire on the same call sequence."""
    def fire_pattern():
        faults.reset()
        hits = []
        for i in range(64):
            try:
                faults.check("p", exc=OSError)
            except OSError:
                hits.append(i)
        return hits

    clean_faults.setenv("DMT_FAULT", "p:p=0.25:n=1000:seed=7")
    a = fire_pattern()
    b = fire_pattern()
    assert a == b and 4 < len(a) < 32


def test_fault_delay_injects_latency_not_error(clean_faults):
    import time

    _arm(clean_faults, "slow:delay=30:n=2")
    t0 = time.perf_counter()
    faults.check("slow")
    dt = time.perf_counter() - t0
    assert dt >= 0.025
    assert faults.fired_count("slow") == 1      # recorded, nothing raised


def test_fault_spec_errors_are_loud(clean_faults):
    """A typo'd chaos spec must not silently test nothing."""
    for bad in ("site:nope=1", "site:p", ":p=1"):
        clean_faults.setenv("DMT_FAULT", bad)
        faults.reset()
        with pytest.raises(faults.FaultSpecError):
            faults.check("site")
    faults.reset()


def test_with_retries_heals_and_exhausts(clean_faults):
    calls = []

    def flaky(fail_times):
        def fn():
            calls.append(1)
            if len(calls) <= fail_times:
                raise OSError("transient")
            return "ok"
        return fn

    assert faults.with_retries("t", flaky(2), attempts=3,
                               base_s=0.001) == "ok"
    assert len(calls) == 3
    assert obs.snapshot()["counters"]["io_retry{site=t}"] == 2
    calls.clear()
    with pytest.raises(OSError):
        faults.with_retries("t", flaky(99), attempts=3, base_s=0.001)
    assert len(calls) == 3


def test_apply_hlo_byte_identical_with_faults_armed(clean_faults):
    """The acceptance guard: every fault site is host-side, so the
    compiled apply program is byte-identical whether DMT_FAULT is armed
    or not (same contract as the DMT_OBS=off / health-probe guards)."""
    from distributed_matvec_tpu.parallel.engine import LocalEngine

    op = build_heisenberg(10, 5)
    op.basis.build()
    eng = LocalEngine(op)
    x = np.random.default_rng(0).standard_normal(op.basis.number_states)

    def hlo():
        return jax.jit(eng._apply_fn).lower(
            jnp.asarray(x), eng._operands).compile().as_text()

    base = hlo()
    _arm(clean_faults, "exchange,plan_upload:n=3,artifact_read:p=0.5")
    assert faults.enabled()
    assert hlo() == base


# ---------------------------------------------------------------------------
# corrupt-artifact rebuild + quarantine (the existing failure paths,
# finally exercised by injected failures)


def test_corrupt_basis_artifact_rebuilds_then_quarantines(
        clean_faults, tmp_path):
    """A truncated basis checkpoint in the artifact cache must rebuild
    (not crash), count artifact_cache{event=corrupt}, and be quarantined
    into .quarantine/ on the second failing read."""
    from distributed_matvec_tpu.utils.artifacts import (artifact_path,
                                                        basis_fingerprint,
                                                        make_or_restore_basis)

    clean_faults.setenv("DMT_ARTIFACT_CACHE", "on")
    clean_faults.setenv("DMT_ARTIFACT_DIR", str(tmp_path / "art"))
    op = build_heisenberg(10, 5)
    basis = op.basis
    path = artifact_path("basis", basis_fingerprint(basis), ".h5")
    with open(path, "wb") as f:
        f.write(b"\x89HDF\r\n\x1a\nthis is not a real hdf5 file")

    assert make_or_restore_basis(basis, save=False) is False
    assert basis.is_built                       # rebuilt despite the file
    c = obs.snapshot()["counters"]
    assert c["artifact_cache{event=corrupt,kind=basis}"] == 1
    assert os.path.exists(path)                 # first failure: kept

    # the path fails AGAIN (persistent bit-rot): quarantined, and the
    # post-rebuild save then heals the cache with a fresh checkpoint
    b2 = build_heisenberg(10, 5).basis
    assert make_or_restore_basis(b2) is False and b2.is_built
    qdir = os.path.join(os.path.dirname(path), ".quarantine")
    assert os.path.isdir(qdir) and len(os.listdir(qdir)) == 1
    kinds = [e["kind"] for e in obs.events()]
    assert "artifact_quarantine" in kinds
    # third construction restores the healed checkpoint
    b3 = build_heisenberg(10, 5).basis
    assert make_or_restore_basis(b3) is True


def test_corrupt_structure_checkpoint_rebuilds(clean_faults, tmp_path):
    """An unreadable explicit structure sidecar is a miss (engine builds
    fresh and overwrites it), not an error."""
    from distributed_matvec_tpu.parallel.distributed import DistributedEngine

    op = build_heisenberg(10, 5)
    op.basis.build()
    cache = str(tmp_path / "plan.h5")
    sidecar = f"{cache}.dist2.structure.h5"
    with open(sidecar, "wb") as f:
        f.write(b"garbage" * 64)
    eng = DistributedEngine(op, n_devices=2, mode="ell",
                            structure_cache=cache)
    assert not eng.structure_restored
    assert obs.snapshot()["counters"][
        "artifact_cache{event=corrupt,kind=structure}"] >= 1
    # the fresh build replaced the sidecar atomically; a second engine
    # restores it
    eng2 = DistributedEngine(op, n_devices=2, mode="ell",
                             structure_cache=cache)
    assert eng2.structure_restored


def test_os_replace_concurrent_writers(tmp_path):
    """Two writers hammering the same sidecar path while a reader loops:
    the reader must only ever observe a complete, fingerprint-valid file
    (the os.replace atomicity the save path promises)."""
    from distributed_matvec_tpu.io.hdf5 import (load_engine_structure,
                                                save_engine_structure)

    path = str(tmp_path / "race.h5")
    payload = {"a": np.arange(4096), "b": np.ones(1000)}
    stop = threading.Event()
    errors = []

    def writer(tag):
        i = 0
        while not stop.is_set():
            try:
                save_engine_structure(path, f"fp-{tag}", "ell",
                                      dict(payload, tag=tag))
            except Exception as e:       # pragma: no cover
                errors.append(e)
                return
            i += 1

    threads = [threading.Thread(target=writer, args=(t,))
               for t in ("w0", "w1")]
    for t in threads:
        t.start()
    good = 0
    try:
        for _ in range(200):
            for fp in ("fp-w0", "fp-w1"):
                got = load_engine_structure(path, fp)
                if got is not None:
                    # complete: the payload written with that fingerprint
                    assert got["tag"] == fp[3:]
                    assert got["a"].shape == (4096,)
                    good += 1
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not errors
    assert good > 0                       # the race actually exercised reads


def test_stream_disk_tier_corrupt_chunk_rebuilds(clean_faults, tmp_path):
    """Satellite: a corrupt ``*.stream.h5`` sidecar chunk on the DISK tier
    logs artifact_cache{event=corrupt} and rebuilds that chunk's plan from
    structure bit-identically instead of raising mid-apply; the sidecar's
    second failure quarantines it and the plan returns to host RAM."""
    import h5py

    from distributed_matvec_tpu.parallel.distributed import DistributedEngine

    clean_faults.setenv("DMT_ARTIFACT_CACHE", "on")
    clean_faults.setenv("DMT_ARTIFACT_DIR", str(tmp_path / "art"))
    old = get_config().stream_plan_ram_gb
    update_config(stream_plan_ram_gb=0.0)
    try:
        op = build_heisenberg(12, 6)
        op.basis.build()
        n = op.basis.number_states
        x = np.random.default_rng(3).standard_normal(n)

        e1 = DistributedEngine(op, n_devices=2, mode="streamed")
        xh = e1.to_hashed(x)
        y_ref = np.asarray(e1.matvec(xh))
        assert e1._plan_chunks is None, "disk tier must be active"
        path = list(e1._plan_disk.values())[0]
        del e1, xh
        gc.collect()

        e2 = DistributedEngine(op, n_devices=2, mode="streamed")
        assert e2.structure_restored and e2._plan_chunks is None

        def corrupt():
            for fobj in list(e2._plan_files.values()):
                fobj.close()
            e2._plan_files.clear()
            with h5py.File(path, "r+") as f:
                f["engine_structure"]["dest_0_0"][...] = 0

        corrupt()
        y = np.asarray(e2.matvec(e2.to_hashed(x)))
        np.testing.assert_array_equal(y, y_ref)
        c = obs.snapshot()["counters"]
        assert c["artifact_cache{event=corrupt,kind=stream_plan}"] == 1
        assert any(e["kind"] == "plan_chunk_rebuilt" for e in obs.events())
        assert os.path.exists(path)          # first failure: kept

        # second corruption: quarantine + full rebuild back into RAM
        corrupt()
        e2._plan_repaired.clear()
        y = np.asarray(e2.matvec(e2.to_hashed(x)))
        np.testing.assert_array_equal(y, y_ref)
        assert not os.path.exists(path)
        assert e2._plan_chunks is not None and e2._plan_disk is None
        c = obs.snapshot()["counters"]
        assert c["artifact_cache{event=quarantine,kind=stream_plan}"] == 1
    finally:
        update_config(stream_plan_ram_gb=old)


def test_stream_ram_restore_rejects_corrupt_sidecar(clean_faults, tmp_path):
    """RAM-tier restores verify the per-chunk checksums once up front: a
    corrupt sidecar is a miss (fresh build), never a silently-wrong plan."""
    import h5py

    from distributed_matvec_tpu.parallel.distributed import DistributedEngine

    clean_faults.setenv("DMT_ARTIFACT_CACHE", "on")
    clean_faults.setenv("DMT_ARTIFACT_DIR", str(tmp_path / "art"))
    op = build_heisenberg(12, 6)
    op.basis.build()
    x = np.random.default_rng(3).standard_normal(op.basis.number_states)

    e1 = DistributedEngine(op, n_devices=2, mode="streamed")
    y_ref = np.asarray(e1.matvec(e1.to_hashed(x)))
    root = str(tmp_path / "art")
    sidecars = [os.path.join(dp, f) for dp, _, fs in os.walk(root)
                for f in fs if f.endswith(".stream.h5")]
    assert len(sidecars) == 1
    del e1
    gc.collect()
    with h5py.File(sidecars[0], "r+") as f:
        f["engine_structure"]["coeff_1_0"][...] = 0.5

    e2 = DistributedEngine(op, n_devices=2, mode="streamed")
    assert not e2.structure_restored          # corrupt → miss → rebuild
    y = np.asarray(e2.matvec(e2.to_hashed(x)))
    np.testing.assert_array_equal(y, y_ref)
    assert obs.snapshot()["counters"][
        "artifact_cache{event=corrupt,kind=stream_plan}"] >= 1


def test_fault_site_plan_chunk_read_retries(clean_faults, tmp_path):
    """A transient disk-tier read failure heals inside the apply (bounded
    retry), with io_retry accounting."""
    from distributed_matvec_tpu.parallel.distributed import DistributedEngine

    clean_faults.setenv("DMT_ARTIFACT_CACHE", "on")
    clean_faults.setenv("DMT_ARTIFACT_DIR", str(tmp_path / "art"))
    old = get_config().stream_plan_ram_gb
    update_config(stream_plan_ram_gb=0.0)
    try:
        op = build_heisenberg(12, 6)
        op.basis.build()
        x = np.random.default_rng(3).standard_normal(op.basis.number_states)
        eng = DistributedEngine(op, n_devices=2, mode="streamed")
        assert eng._plan_chunks is None
        y_ref = np.asarray(eng.matvec(eng.to_hashed(x)))
        _arm(clean_faults, "plan_chunk_read:n=1")
        y = np.asarray(eng.matvec(eng.to_hashed(x)))
        np.testing.assert_array_equal(y, y_ref)
        assert faults.fired_count("plan_chunk_read") == 1
        assert obs.snapshot()["counters"][
            "io_retry{site=plan_chunk_read}"] >= 1
    finally:
        update_config(stream_plan_ram_gb=old)


# ---------------------------------------------------------------------------
# preemption-safe solves


def _dense_problem(n=400, seed=3):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((n, n))
    A = (A + A.T) / 2
    Aj = jnp.asarray(A)
    return A, (lambda x: Aj @ x)


def test_lanczos_preempt_checkpoints_and_resumes_bit_consistent(
        clean_faults, tmp_path):
    """The latch → safe-point checkpoint → Preempted → resume loop, with
    the resumed E0 matching an uninterrupted solve to rtol 1e-12 (the
    ROADMAP acceptance, in-process form)."""
    A, mv = _dense_problem()
    want = lanczos(mv, 400, k=1, tol=1e-11, max_iters=300, check_every=8)
    assert want.converged
    ck = str(tmp_path / "lz.h5")

    preempt.trigger()
    with pytest.raises(preempt.Preempted) as ei:
        lanczos(mv, 400, k=1, tol=1e-11, max_iters=300, check_every=8,
                checkpoint_path=ck, checkpoint_every=100)
    assert ei.value.solver == "lanczos" and ei.value.iters == 8
    kinds = [(e["kind"], e.get("status"), e.get("reason"))
             for e in obs.events()]
    assert ("solver_checkpoint", "written", "preempt") in kinds
    assert any(k == "solver_preempted" for k, _, _ in kinds)

    preempt.reset()
    res = lanczos(mv, 400, k=1, tol=1e-11, max_iters=300, check_every=8,
                  checkpoint_path=ck)
    assert res.resumed_from == 8 and res.converged
    rel = abs(res.eigenvalues[0] - want.eigenvalues[0]) \
        / abs(want.eigenvalues[0])
    assert rel < 1e-12


def test_lanczos_ckpt_write_fault_degrades_softly(clean_faults, tmp_path):
    """An injected checkpoint-write failure must not kill the solve: it
    converges, emits solver_checkpoint{status=failed}, and a later
    generation lands."""
    A, mv = _dense_problem()
    ck = str(tmp_path / "lz.h5")
    _arm(clean_faults, "ckpt_write:n=1")
    res = lanczos(mv, 400, k=1, tol=1e-11, max_iters=300, check_every=8,
                  checkpoint_path=ck, checkpoint_every=1)
    assert res.converged
    statuses = [e.get("status") for e in obs.events()
                if e["kind"] == "solver_checkpoint"]
    assert "failed" in statuses and "written" in statuses


def test_lanczos_block_preempts_cleanly(clean_faults):
    op = build_heisenberg(10, 5)
    op.basis.build()
    from distributed_matvec_tpu.parallel.engine import LocalEngine

    eng = LocalEngine(op)
    preempt.trigger()
    with pytest.raises(preempt.Preempted):
        lanczos_block(eng.matvec, op.basis.number_states, k=2,
                      max_iters=60)
    preempt.reset()


def test_preempt_latch_and_handler_contract(clean_faults):
    """trigger() latches; ensure_installed is idempotent and the handler
    only sets the flag (checked via direct invocation — sending real
    signals inside pytest is rude to the runner)."""
    assert not preempt.requested()
    assert preempt.ensure_installed()
    assert preempt.ensure_installed()       # idempotent
    import signal as _sig

    preempt._handler(_sig.SIGTERM, None)
    assert preempt.requested()
    assert preempt.signal_number() == _sig.SIGTERM
    assert preempt.agreed(False) is True
    preempt.reset()
    assert not preempt.requested()


def test_lobpcg_checkpoint_resume_and_preempt(clean_faults, tmp_path):
    """Satellite: LOBPCG checkpoint/resume parity — a budget-truncated
    segmented solve resumes with cumulative iterations and converges to
    the dense truth; a latched preemption exits at a segment boundary
    with the checkpoint written."""
    op = build_heisenberg(10, 5)
    op.basis.build()
    from distributed_matvec_tpu.parallel.engine import LocalEngine

    eng = LocalEngine(op)
    n = op.basis.number_states
    want = np.linalg.eigvalsh(op.to_sparse().toarray())[0]
    ck = str(tmp_path / "lob.h5")

    evals1, _, it1 = lobpcg(eng.matvec, n, k=1, tol=1e-9, max_iters=12,
                            checkpoint_path=ck, checkpoint_every=6)
    assert it1 <= 12
    evals2, V2, it2 = lobpcg(eng.matvec, n, k=1, tol=1e-9, max_iters=400,
                             checkpoint_path=ck, checkpoint_every=50)
    assert it2 > it1                        # cumulative, resumed
    assert any(e["kind"] == "solver_resume" for e in obs.events())
    np.testing.assert_allclose(evals2[0], want, atol=1e-6)
    assert V2.shape == (n, 1)

    # preemption between segments: checkpoint written, Preempted raised
    os.remove(ck)
    preempt.trigger()
    with pytest.raises(preempt.Preempted) as ei:
        lobpcg(eng.matvec, n, k=1, tol=1e-12, max_iters=400,
               checkpoint_path=ck, checkpoint_every=5)
    assert ei.value.solver == "lobpcg"
    assert os.path.exists(ck)
    preempt.reset()
    evals3, _, it3 = lobpcg(eng.matvec, n, k=1, tol=1e-8, max_iters=400,
                            checkpoint_path=ck, checkpoint_every=100)
    assert it3 > ei.value.iters
    np.testing.assert_allclose(evals3[0], want, atol=1e-5)


def test_lobpcg_checkpoint_keyed_by_operator(clean_faults, tmp_path):
    """A rerun against an edited Hamiltonian of the same size must MISS
    the foreign block (same contract as the Lanczos checkpoints)."""
    from distributed_matvec_tpu.models.yaml_io import operator_from_dict
    from distributed_matvec_tpu.parallel.engine import LocalEngine

    op1 = build_heisenberg(10, 5)
    op1.basis.build()
    n = op1.basis.number_states
    ck = str(tmp_path / "lob.h5")
    lobpcg(LocalEngine(op1).matvec, n, k=1, tol=1e-9, max_iters=10,
           checkpoint_path=ck, checkpoint_every=5)

    ham2 = {"terms": [{"expression": "2.5 σᶻ₀ σᶻ₁ + σˣ₀ σˣ₁ + σʸ₀ σʸ₁",
                       "sites": [[i, (i + 1) % 10] for i in range(10)]}]}
    b2 = type(op1.basis)(number_spins=10, hamming_weight=5)
    op2 = operator_from_dict(ham2, b2)
    op2.basis.build()
    obs.reset_all()
    evals, _, _ = lobpcg(LocalEngine(op2).matvec, n, k=1, tol=1e-9,
                         max_iters=400, checkpoint_path=ck,
                         checkpoint_every=100)
    assert not any(e["kind"] == "solver_resume" for e in obs.events())
    want2 = np.linalg.eigvalsh(op2.to_sparse().toarray())[0]
    np.testing.assert_allclose(evals[0], want2, atol=1e-6)


# ---------------------------------------------------------------------------
# obs flush on signal/atexit (satellite)


def test_obs_sink_flush_registered_and_preempt_events_on_disk(
        clean_faults, tmp_path):
    """Opening the sink registers the atexit flush backstop, and the
    preemption path's final events (checkpoint-written included) are on
    disk in rank_0/events.jsonl before the exception even reaches the
    caller — never lost with the process."""
    # NB the events() FUNCTION re-exported by obs/__init__ shadows the
    # submodule on attribute lookup — fetch the module itself
    import importlib

    ev_mod = importlib.import_module("distributed_matvec_tpu.obs.events")

    update_config(obs_dir=str(tmp_path / "obs"))
    try:
        A, mv = _dense_problem()
        ck = str(tmp_path / "lz.h5")
        preempt.trigger()
        with pytest.raises(preempt.Preempted):
            lanczos(mv, 400, k=1, tol=1e-11, max_iters=300, check_every=8,
                    checkpoint_path=ck, checkpoint_every=100)
        assert ev_mod._atexit_registered
        path = os.path.join(str(tmp_path / "obs"), "rank_0",
                            "events.jsonl")
        with open(path) as f:
            lines = [json.loads(line) for line in f if line.strip()]
        kinds = [(e["kind"], e.get("status")) for e in lines]
        assert ("solver_checkpoint", "written") in kinds
        assert ("solver_preempted", None) in kinds
    finally:
        preempt.reset()
        update_config(obs_dir="")


# ---------------------------------------------------------------------------
# heartbeat watchdog


def test_heartbeat_stall_report(clean_faults, tmp_path):
    """A peer whose beat file goes stale past the timeout produces one
    stall_report event naming the rank and its age, and the on_stall hook
    fires exactly once (the default hook aborts; tests capture)."""
    from distributed_matvec_tpu.parallel.heartbeat import HeartbeatWatchdog

    d = str(tmp_path / "run")
    hb_dir = os.path.join(d, "heartbeat")
    os.makedirs(hb_dir)
    stale = os.path.join(hb_dir, "rank_1.hb")
    with open(stale, "w") as f:
        f.write("0\n")
    os.utime(stale, (1.0, 1.0))            # beat from 1970: definitely stale

    reports = []
    wd = HeartbeatWatchdog(d, interval_s=0.05, timeout_s=5.0, rank=0,
                           n_ranks=2, on_stall=reports.append)
    wd.start()
    t = wd._thread
    assert t is not None
    t.join(timeout=10)
    assert not t.is_alive(), "watchdog thread never reported the stall"
    wd.stop()
    assert len(reports) == 1
    assert reports[0]["stalled"] == [1]
    # pre-watchdog beat files take the startup grace (a relaunch must not
    # be killed by its dead predecessor's files), so the reported age is
    # measured from watchdog start — ≥ the timeout, rounded to 0.1
    assert reports[0]["ages_s"]["1"] >= 5.0
    evs = [e for e in obs.events() if e["kind"] == "stall_report"]
    assert len(evs) == 1 and evs[0]["stalled"] == [1]
    # this rank's own beat landed
    assert os.path.exists(os.path.join(hb_dir, "rank_0.hb"))


def test_heartbeat_healthy_peers_stay_quiet(clean_faults, tmp_path):
    from distributed_matvec_tpu.parallel.heartbeat import HeartbeatWatchdog

    d = str(tmp_path / "run")
    reports = []
    wd = HeartbeatWatchdog(d, interval_s=0.05, timeout_s=60.0, rank=0,
                           n_ranks=2, on_stall=reports.append)
    with wd:
        # peer beats freshly
        peer = HeartbeatWatchdog(d, interval_s=0.05, timeout_s=60.0,
                                 rank=1, n_ranks=2,
                                 on_stall=reports.append)
        peer.beat()
        import time

        time.sleep(0.3)
    assert reports == []
    assert not any(e["kind"] == "stall_report" for e in obs.events())


def test_heartbeat_single_rank_inert(clean_faults, tmp_path):
    from distributed_matvec_tpu.parallel.heartbeat import HeartbeatWatchdog

    wd = HeartbeatWatchdog(str(tmp_path), rank=0, n_ranks=1)
    wd.start()
    assert wd._thread is None               # nothing to watch
    wd.stop()
