"""Telemetry subsystem (obs/): metrics registry, event sink, TreeTimer
bridge, report tooling, and the disabled-path zero-overhead guard.

The suite-wide conftest strips ``DMT_OBS_DIR``/``DMT_OBS`` from the
environment, so the layer runs in its default state here: enabled,
in-memory only.  Tests that exercise the JSONL sink point it at tmp_path
and reset the module state around themselves.
"""

import importlib.util
import json
import os
import sys

import numpy as np
import pytest

from distributed_matvec_tpu import obs
from distributed_matvec_tpu.obs import metrics as obs_metrics

# NB: obs.events (the accessor function) shadows the submodule attribute on
# the package, and `import ... as` resolves through that same attribute —
# sys.modules holds the real module
obs_events = sys.modules["distributed_matvec_tpu.obs.events"]
from distributed_matvec_tpu.utils.timers import TreeTimer

from test_operator import build_heisenberg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_obs_report():
    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(REPO, "tools", "obs_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def clean_obs():
    """Fresh event buffer + registry, restored state afterwards."""
    obs.reset_all()
    yield
    obs.reset_all()


@pytest.fixture
def obs_off(monkeypatch):
    monkeypatch.setenv("DMT_OBS", "off")


# ---------------------------------------------------------------------------
# TreeTimer (satellite: to_dict/scope_total edge cases + emit bridge)


def test_treetimer_empty():
    t = TreeTimer("empty")
    d = t.to_dict()
    assert d == {"total": 0.0, "count": 0, "children": {}}
    assert t.scope_total() == 0.0                  # root, never stopped
    assert t.scope_total("missing") == 0.0
    assert t.scope_total("a", "b", "c") == 0.0


def test_treetimer_reentered_scope():
    t = TreeTimer()
    for _ in range(3):
        with t.scope("phase"):
            with t.scope("inner"):
                pass
    node = t.root.children["phase"]
    assert node.count == 3 and len(node.samples) == 3
    assert node.children["inner"].count == 3
    d = t.to_dict()
    assert d["children"]["phase"]["count"] == 3
    assert d["children"]["phase"]["children"]["inner"]["count"] == 3
    assert t.scope_total("phase") == pytest.approx(node.total)
    assert t.scope_total("phase", "inner") >= 0.0


def test_treetimer_mean_and_err_n1():
    t = TreeTimer()
    with t.scope("once"):
        pass
    node = t.root.children["once"]
    s = node.mean_and_err()
    assert "±" not in s and "mean" not in s        # n=1: total only
    assert float(s) == pytest.approx(node.total, abs=1e-6)
    # n=2 grows the ± suffix
    with t.scope("once"):
        pass
    assert "±" in node.mean_and_err()


def test_treetimer_emit_bridge(clean_obs):
    t = TreeTimer("bridge")
    with t.scope("a"):
        with t.scope("b"):
            pass
    ev = t.emit(config="unit")
    assert ev is not None and ev["kind"] == "timer_tree"
    assert ev["timer"] == "bridge" and ev["config"] == "unit"
    assert ev["tree"]["children"]["a"]["children"]["b"]["count"] == 1
    # the event is valid JSON and landed in the in-memory buffer
    json.loads(json.dumps(ev))
    assert obs.events("timer_tree")[-1]["seq"] == ev["seq"]


def test_treetimer_emit_disabled(clean_obs, obs_off):
    t = TreeTimer()
    with t.scope("a"):
        pass
    assert t.emit() is None
    assert obs.events() == []


# ---------------------------------------------------------------------------
# metrics registry


def test_counter_labeling(clean_obs):
    obs.counter("hits", engine="local").inc()
    obs.counter("hits", engine="local").inc(2)
    obs.counter("hits", engine="distributed").inc(5)
    obs.counter("hits").inc(7)
    snap = obs.snapshot()["counters"]
    assert snap["hits{engine=local}"] == 3
    assert snap["hits{engine=distributed}"] == 5
    assert snap["hits"] == 7
    # label ORDER is canonicalized: same series either way
    obs.counter("c", a="1", b="2").inc()
    obs.counter("c", b="2", a="1").inc()
    assert obs.snapshot()["counters"]["c{a=1,b=2}"] == 2


def test_gauge(clean_obs):
    obs.gauge("bytes", what="tables").set(123.5)
    obs.gauge("bytes", what="tables").set(7)
    assert obs.snapshot()["gauges"]["bytes{what=tables}"] == 7.0


def test_histogram_bucketing(clean_obs):
    h = obs.histogram("lat_ms", buckets=(1.0, 10.0, 100.0))
    for v in (0.5, 1.0, 5.0, 99.0, 1e6):
        h.observe(v)
    d = h.to_dict()
    # bounds are inclusive: 1.0 lands in the first bucket; 1e6 overflows
    assert d["buckets"] == [1.0, 10.0, 100.0]
    assert d["counts"] == [2, 1, 1, 1]
    assert d["count"] == 5
    assert d["sum"] == pytest.approx(0.5 + 1.0 + 5.0 + 99.0 + 1e6)
    assert h.mean == pytest.approx(d["sum"] / 5)
    snap = obs.snapshot()["histograms"]["lat_ms"]
    assert snap["counts"] == [2, 1, 1, 1]
    with pytest.raises(ValueError):
        obs_metrics.Histogram(buckets=(5.0, 1.0))


def test_metrics_disabled_null(clean_obs, obs_off):
    assert obs.counter("x") is obs_metrics.NULL
    assert obs.gauge("x") is obs_metrics.NULL
    assert obs.histogram("x") is obs_metrics.NULL
    obs.counter("x", a="b").inc(5)                 # all no-ops
    obs.histogram("x").observe(1.0)
    assert obs.snapshot() == {"counters": {}, "gauges": {},
                              "histograms": {}}


# ---------------------------------------------------------------------------
# event sink


def test_emit_envelope_and_buffer(clean_obs):
    e0 = obs.emit("alpha", x=1)
    e1 = obs.emit("beta", y=[1, 2])
    assert (e0["seq"], e1["seq"]) == (0, 1)        # monotonic per process
    assert e0["kind"] == "alpha" and e0["proc"] == 0 and e0["ts"] > 0
    assert [e["kind"] for e in obs.events()] == ["alpha", "beta"]
    assert obs.events("beta") == [e1]


def test_jsonl_round_trip(clean_obs, tmp_path, monkeypatch):
    run = tmp_path / "run"
    monkeypatch.setenv("DMT_OBS_DIR", str(run))
    obs.emit("one", arr=np.arange(3), val=np.float64(2.5),
             n=np.int64(7))
    obs.emit("two", nested={"a": [1.5, 2.5]})
    obs.flush()
    path = obs.event_path()
    assert path == str(run / "events.p0.jsonl")
    lines = [json.loads(ln) for ln in
             open(path).read().strip().splitlines()]
    assert [e["kind"] for e in lines] == ["one", "two"]
    assert lines[0]["arr"] == [0, 1, 2]            # numpy made plain
    assert lines[0]["val"] == 2.5 and lines[0]["n"] == 7
    assert [e["seq"] for e in lines] == [0, 1]
    obs.reset()                                    # release the file handle


def test_sink_write_fails_soft(clean_obs, monkeypatch, capsys):
    # /dev/null/... cannot be created: the sink must warn once, disable
    # itself, and keep the in-memory stream alive — never raise
    monkeypatch.setenv("DMT_OBS_DIR", "/dev/null/nope")
    e = obs.emit("still_recorded", i=0)
    assert e is not None
    obs.emit("still_recorded", i=1)
    assert len(obs.events("still_recorded")) == 2
    err = capsys.readouterr().err
    assert err.count("event sink disabled") == 1   # warned ONCE


def test_emit_disabled(clean_obs, obs_off):
    assert obs.emit("nope") is None
    assert obs.events() == []
    assert not obs.obs_enabled()


# ---------------------------------------------------------------------------
# engine integration + the disabled-path zero-overhead guard


def test_engine_emits_init_and_apply_metrics(clean_obs, rng):
    from distributed_matvec_tpu.parallel.engine import (LocalEngine,
                                                        clear_program_cache)
    op = build_heisenberg(10, 5, None, ())
    # earlier tests may have warmed the process-wide AOT cache; a cold one
    # makes the compile/retrace counters deterministic
    clear_program_cache()
    eng = LocalEngine(op, mode="ell")
    inits = obs.events("engine_init")
    assert inits and inits[-1]["engine"] == "local"
    ev = inits[-1]
    assert ev["mode"] == "ell" and ev["n_states"] == op.basis.number_states
    for key in ("build_structure_s", "compile_s", "transfer_s", "diag_s",
                "init_s", "structure_restored", "basis_restored"):
        assert key in ev
    # cold build in a fresh registry: AOT executables were compiled — but a
    # healthy cold start compiles each distinct program ONCE, which is NOT
    # a retrace
    snap = obs.snapshot()["counters"]
    assert snap.get("aot_executable_cache{event=compile}", 0) >= 1
    assert snap.get("retrace_count", 0) == 0
    # same builder programs at a different shape key: a genuine retrace
    from distributed_matvec_tpu.parallel.engine import pad_to_multiple
    LocalEngine(op, mode="ell",
                batch_size=pad_to_multiple(op.basis.number_states, 8) // 2)
    assert obs.snapshot()["counters"].get("retrace_count", 0) >= 1

    x = rng.random(op.basis.number_states) - 0.5
    before = obs.histogram("matvec_apply_ms", engine="local").count
    eng.matvec(x)
    after = obs.histogram("matvec_apply_ms", engine="local").count
    assert after == before + 1


def test_engine_apply_disabled_zero_overhead(clean_obs, rng, monkeypatch):
    """The acceptance guard: with the layer off, an engine apply records
    nothing, touches no sink, and returns bit-identical results."""
    op = build_heisenberg(10, 5, None, ())
    from distributed_matvec_tpu.parallel.engine import LocalEngine
    eng = LocalEngine(op, mode="ell")
    x = rng.random(op.basis.number_states) - 0.5
    y_on = np.asarray(eng.matvec(x))

    monkeypatch.setenv("DMT_OBS", "off")
    obs.reset_all()

    def _explode(*a, **k):                         # any sink touch is a bug
        raise AssertionError("obs layer touched while disabled")

    monkeypatch.setattr(obs_events, "_write", _explode)
    assert obs.histogram("matvec_apply_ms", engine="local") \
        is obs_metrics.NULL
    y_off = np.asarray(eng.matvec(x))
    np.testing.assert_array_equal(y_on, y_off)
    assert obs.events() == []
    assert obs.snapshot() == {"counters": {}, "gauges": {},
                              "histograms": {}}


def test_lanczos_emits_convergence_trace(clean_obs, rng):
    op = build_heisenberg(10, 5, None, ())
    from distributed_matvec_tpu.parallel.engine import LocalEngine
    from distributed_matvec_tpu.solve import lanczos
    eng = LocalEngine(op, mode="ell")
    res = lanczos(eng.matvec, op.basis.number_states, k=1, max_iters=48,
                  tol=1e-10, seed=3)
    traces = obs.events("lanczos_trace")
    assert traces, "no convergence trace emitted"
    # residuals decrease to convergence; the last trace matches the result
    last = traces[-1]
    assert last["ritz"][0] == pytest.approx(float(res.eigenvalues[0]))
    ends = obs.events("solver_end")
    assert ends and ends[-1]["converged"] == res.converged


# ---------------------------------------------------------------------------
# obs_report


def _write_detail(path, device_ms, iters_per_s=100.0):
    detail = {"chain_16": {"config": "heisenberg_chain_16",
                           "device_ms": device_ms,
                           "engine_init_s": 1.0,
                           "lanczos_iters_per_s": iters_per_s},
              "broken": {"error": "Boom()"}}
    path.write_text(json.dumps(detail))
    return str(path)


def test_obs_report_diff_regression(tmp_path):
    rep = _load_obs_report()
    base = _write_detail(tmp_path / "base.json", device_ms=10.0)
    ok = _write_detail(tmp_path / "ok.json", device_ms=11.0)
    bad = _write_detail(tmp_path / "bad.json", device_ms=13.0)
    # +10% within a 20% gate; +30% beyond it → exit 1
    assert rep.main(["diff", base, ok, "--threshold", "0.2"]) == 0
    assert rep.main(["diff", base, bad, "--threshold", "0.2"]) == 1
    # improvement is never a regression
    assert rep.main(["diff", bad, base, "--threshold", "0.2"]) == 0
    # direction-aware: a rate metric gates on DECREASE
    slow = _write_detail(tmp_path / "slow.json", device_ms=10.0,
                         iters_per_s=50.0)
    assert rep.main(["diff", base, slow, "--threshold", "0.2",
                     "--metric", "lanczos_iters_per_s"]) == 1
    assert rep.main(["diff", slow, base, "--threshold", "0.2",
                     "--metric", "lanczos_iters_per_s"]) == 0
    # no overlap at all is its own (non-zero) failure mode
    empty = tmp_path / "empty.json"
    empty.write_text("{}")
    assert rep.main(["diff", base, str(empty)]) == 2


def test_obs_report_diff_config_filter(tmp_path):
    rep = _load_obs_report()
    base = _write_detail(tmp_path / "b.json", device_ms=10.0)
    bad = _write_detail(tmp_path / "n.json", device_ms=20.0)
    # the regressed config filtered OUT → nothing in common → rc 2
    assert rep.main(["diff", base, bad, "--config", "kagome"]) == 2
    assert rep.main(["diff", base, bad, "--config", "chain_16"]) == 1


def test_obs_report_summarize_run_dir(clean_obs, tmp_path, monkeypatch):
    """A crafted run (engine init + solver trace + snapshot, two procs)
    reconstructs the init split, cache hit rate, and residual series."""
    rep = _load_obs_report()
    run = tmp_path / "run"
    monkeypatch.setenv("DMT_OBS_DIR", str(run))
    obs.emit("engine_init", engine="local", mode="ell", n_states=100,
             pair=False, basis_restored=True, structure_restored=False,
             build_structure_s=2.0, compile_s=0.5, kernels_s=1.5,
             transfer_s=0.25, diag_s=0.125, init_s=3.0)
    obs.emit("solver_start", solver="lanczos", k=1, tol=1e-10)
    obs.emit("lanczos_trace", solver="lanczos", iter=16, basis_size=16,
             ritz=[-28.1], residual=[1.0])
    obs.emit("lanczos_trace", solver="lanczos", iter=32, basis_size=32,
             ritz=[-28.5], residual=[1e-11])
    obs.emit("solver_end", solver="lanczos", iters=32, converged=True,
             eigenvalues=[-28.5])
    obs.emit("bench_result", config="heisenberg_chain_16", device_ms=2.5,
             n_states=100)
    obs.emit("metrics_snapshot", metrics={"counters": {
        "artifact_cache{event=hit,kind=structure}": 3,
        "artifact_cache{event=miss,kind=structure}": 1,
        "aot_executable_cache{event=hit}": 7,
        "aot_executable_cache{event=compile}": 1,
        "bytes_h2d{path=engine_tables}": 1024,
        "retrace_count": 1}})
    obs.flush()
    obs.reset()
    # a second process's stream must merge in (proc, seq) order
    (run / "events.p1.jsonl").write_text(json.dumps(
        {"seq": 0, "ts": 0.0, "proc": 1, "kind": "engine_init",
         "engine": "distributed", "mode": "ell", "n_states": 100,
         "basis_restored": False, "structure_restored": True,
         "build_structure_s": 0.0, "compile_s": 0.0, "kernels_s": 0.0,
         "transfer_s": 0.1, "diag_s": 0.0, "init_s": 0.2}) + "\n")

    s = rep.run_summary(rep.load_events(str(run)))
    assert s["processes"] == [0, 1]
    assert len(s["engine_inits"]) == 2
    local = s["engine_inits"][0]
    assert (local["build_structure_s"], local["compile_s"],
            local["transfer_s"]) == (2.0, 0.5, 0.25)
    caches = s["cache"]["caches"]
    assert caches["artifact_cache/structure"]["hit_rate"] == 0.75
    assert caches["aot_executable_cache"]["hit_rate"] == pytest.approx(7 / 8)
    assert s["cache"]["bytes_h2d"] == 1024
    assert s["cache"]["retrace_count"] == 1
    sv = s["solvers"][0]
    assert sv["converged"] is True
    assert [t["iter"] for t in sv["trace"]] == [16, 32]
    assert sv["trace"][-1]["residual"] == [1e-11]
    assert s["bench"]["heisenberg_chain_16"]["device_ms"] == 2.5
    # the human renderer must not throw on the same summary
    rep.print_summary(s)


def test_obs_report_load_events_jsonl_and_torn_line(tmp_path, capsys):
    rep = _load_obs_report()
    f = tmp_path / "e.jsonl"
    f.write_text(json.dumps({"seq": 0, "proc": 0, "kind": "a"}) + "\n"
                 + '{"seq": 1, "proc": 0, "ki')       # torn final line
    evs = rep.load_events(str(f))
    assert [e["kind"] for e in evs] == ["a"]


# ---------------------------------------------------------------------------
# satellites: logging + profiling


def test_log_warn_and_process_index_cache(capsys):
    from distributed_matvec_tpu.utils import logging as L
    L.log_warn("disk ", "full")
    err = capsys.readouterr().err
    assert "[Warn] [0] disk full" in err
    # cached after first success: later calls never re-query jax
    assert L._proc_idx is not None
    assert L._process_index() == L._proc_idx


def test_maybe_profile_override(monkeypatch, tmp_path):
    from distributed_matvec_tpu.utils import profiling
    from distributed_matvec_tpu.utils.config import update_config
    calls = []

    class _Trace:
        def __init__(self, d, create_perfetto_link=False):
            calls.append(d)

        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

    import jax
    monkeypatch.setattr(jax.profiler, "trace", _Trace)
    # config field unset: no-op
    update_config(profile_dir="")
    with profiling.maybe_profile():
        pass
    assert calls == []
    # explicit override wins without touching global config
    with profiling.maybe_profile(profile_dir=str(tmp_path / "p")):
        pass
    assert calls == [str(tmp_path / "p")]
    # config fallback still works; explicit "" forces the no-op over it
    update_config(profile_dir=str(tmp_path / "cfg"))
    try:
        with profiling.maybe_profile():
            pass
        assert calls[-1] == str(tmp_path / "cfg")
        with profiling.maybe_profile(profile_dir=""):
            pass
        assert calls[-1] == str(tmp_path / "cfg")  # unchanged
    finally:
        update_config(profile_dir="")
