"""Telemetry subsystem (obs/): metrics registry, event sink, TreeTimer
bridge, report tooling, and the disabled-path zero-overhead guard.

The suite-wide conftest strips ``DMT_OBS_DIR``/``DMT_OBS`` from the
environment, so the layer runs in its default state here: enabled,
in-memory only.  Tests that exercise the JSONL sink point it at tmp_path
and reset the module state around themselves.
"""

import importlib.util
import json
import os
import sys

import numpy as np
import pytest

from distributed_matvec_tpu import obs
from distributed_matvec_tpu.obs import metrics as obs_metrics

# NB: obs.events (the accessor function) shadows the submodule attribute on
# the package, and `import ... as` resolves through that same attribute —
# sys.modules holds the real module
obs_events = sys.modules["distributed_matvec_tpu.obs.events"]
from distributed_matvec_tpu.utils.timers import TreeTimer

from test_operator import build_heisenberg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_obs_report():
    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(REPO, "tools", "obs_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def clean_obs():
    """Fresh event buffer + registry, restored state afterwards."""
    obs.reset_all()
    yield
    obs.reset_all()


@pytest.fixture
def obs_off(monkeypatch):
    monkeypatch.setenv("DMT_OBS", "off")


# ---------------------------------------------------------------------------
# TreeTimer (satellite: to_dict/scope_total edge cases + emit bridge)


def test_treetimer_empty():
    t = TreeTimer("empty")
    d = t.to_dict()
    assert d == {"total": 0.0, "count": 0, "children": {}}
    assert t.scope_total() == 0.0                  # root, never stopped
    assert t.scope_total("missing") == 0.0
    assert t.scope_total("a", "b", "c") == 0.0


def test_treetimer_reentered_scope():
    t = TreeTimer()
    for _ in range(3):
        with t.scope("phase"):
            with t.scope("inner"):
                pass
    node = t.root.children["phase"]
    assert node.count == 3 and len(node.samples) == 3
    assert node.children["inner"].count == 3
    d = t.to_dict()
    assert d["children"]["phase"]["count"] == 3
    assert d["children"]["phase"]["children"]["inner"]["count"] == 3
    assert t.scope_total("phase") == pytest.approx(node.total)
    assert t.scope_total("phase", "inner") >= 0.0


def test_treetimer_mean_and_err_n1():
    t = TreeTimer()
    with t.scope("once"):
        pass
    node = t.root.children["once"]
    s = node.mean_and_err()
    assert "±" not in s and "mean" not in s        # n=1: total only
    assert float(s) == pytest.approx(node.total, abs=1e-6)
    # n=2 grows the ± suffix
    with t.scope("once"):
        pass
    assert "±" in node.mean_and_err()


def test_treetimer_emit_bridge(clean_obs):
    t = TreeTimer("bridge")
    with t.scope("a"):
        with t.scope("b"):
            pass
    ev = t.emit(config="unit")
    assert ev is not None and ev["kind"] == "timer_tree"
    assert ev["timer"] == "bridge" and ev["config"] == "unit"
    assert ev["tree"]["children"]["a"]["children"]["b"]["count"] == 1
    # the event is valid JSON and landed in the in-memory buffer
    json.loads(json.dumps(ev))
    assert obs.events("timer_tree")[-1]["seq"] == ev["seq"]


def test_treetimer_emit_disabled(clean_obs, obs_off):
    t = TreeTimer()
    with t.scope("a"):
        pass
    assert t.emit() is None
    assert obs.events() == []


# ---------------------------------------------------------------------------
# metrics registry


def test_counter_labeling(clean_obs):
    obs.counter("hits", engine="local").inc()
    obs.counter("hits", engine="local").inc(2)
    obs.counter("hits", engine="distributed").inc(5)
    obs.counter("hits").inc(7)
    snap = obs.snapshot()["counters"]
    assert snap["hits{engine=local}"] == 3
    assert snap["hits{engine=distributed}"] == 5
    assert snap["hits"] == 7
    # label ORDER is canonicalized: same series either way
    obs.counter("c", a="1", b="2").inc()
    obs.counter("c", b="2", a="1").inc()
    assert obs.snapshot()["counters"]["c{a=1,b=2}"] == 2


def test_gauge(clean_obs):
    obs.gauge("bytes", what="tables").set(123.5)
    obs.gauge("bytes", what="tables").set(7)
    assert obs.snapshot()["gauges"]["bytes{what=tables}"] == 7.0


def test_histogram_bucketing(clean_obs):
    h = obs.histogram("lat_ms", buckets=(1.0, 10.0, 100.0))
    for v in (0.5, 1.0, 5.0, 99.0, 1e6):
        h.observe(v)
    d = h.to_dict()
    # bounds are inclusive: 1.0 lands in the first bucket; 1e6 overflows
    assert d["buckets"] == [1.0, 10.0, 100.0]
    assert d["counts"] == [2, 1, 1, 1]
    assert d["count"] == 5
    assert d["sum"] == pytest.approx(0.5 + 1.0 + 5.0 + 99.0 + 1e6)
    assert h.mean == pytest.approx(d["sum"] / 5)
    snap = obs.snapshot()["histograms"]["lat_ms"]
    assert snap["counts"] == [2, 1, 1, 1]
    with pytest.raises(ValueError):
        obs_metrics.Histogram(buckets=(5.0, 1.0))


def test_metrics_disabled_null(clean_obs, obs_off):
    assert obs.counter("x") is obs_metrics.NULL
    assert obs.gauge("x") is obs_metrics.NULL
    assert obs.histogram("x") is obs_metrics.NULL
    obs.counter("x", a="b").inc(5)                 # all no-ops
    obs.histogram("x").observe(1.0)
    assert obs.snapshot() == {"counters": {}, "gauges": {},
                              "histograms": {}}


# ---------------------------------------------------------------------------
# event sink


def test_emit_envelope_and_buffer(clean_obs):
    e0 = obs.emit("alpha", x=1)
    e1 = obs.emit("beta", y=[1, 2])
    assert (e0["seq"], e1["seq"]) == (0, 1)        # monotonic per process
    assert e0["kind"] == "alpha" and e0["proc"] == 0 and e0["ts"] > 0
    # rank-tagged envelope: rank mirrors proc, n_ranks the process count
    assert e0["rank"] == 0 and e0["n_ranks"] == 1
    assert [e["kind"] for e in obs.events()] == ["alpha", "beta"]
    assert obs.events("beta") == [e1]


def test_jsonl_round_trip(clean_obs, tmp_path, monkeypatch):
    run = tmp_path / "run"
    monkeypatch.setenv("DMT_OBS_DIR", str(run))
    obs.emit("one", arr=np.arange(3), val=np.float64(2.5),
             n=np.int64(7))
    obs.emit("two", nested={"a": [1.5, 2.5]})
    obs.flush()
    path = obs.event_path()
    assert path == str(run / "rank_0" / "events.jsonl")
    lines = [json.loads(ln) for ln in
             open(path).read().strip().splitlines()]
    assert [e["kind"] for e in lines] == ["one", "two"]
    assert lines[0]["arr"] == [0, 1, 2]            # numpy made plain
    assert lines[0]["val"] == 2.5 and lines[0]["n"] == 7
    assert [e["seq"] for e in lines] == [0, 1]
    obs.reset()                                    # release the file handle


def test_sink_write_fails_soft(clean_obs, monkeypatch, capsys):
    # /dev/null/... cannot be created: the sink must warn once, disable
    # itself, and keep the in-memory stream alive — never raise
    monkeypatch.setenv("DMT_OBS_DIR", "/dev/null/nope")
    e = obs.emit("still_recorded", i=0)
    assert e is not None
    obs.emit("still_recorded", i=1)
    assert len(obs.events("still_recorded")) == 2
    err = capsys.readouterr().err
    assert err.count("event sink disabled") == 1   # warned ONCE


def test_emit_disabled(clean_obs, obs_off):
    assert obs.emit("nope") is None
    assert obs.events() == []
    assert not obs.obs_enabled()


# ---------------------------------------------------------------------------
# engine integration + the disabled-path zero-overhead guard


def test_engine_emits_init_and_apply_metrics(clean_obs, rng):
    from distributed_matvec_tpu.parallel.engine import (LocalEngine,
                                                        clear_program_cache)
    op = build_heisenberg(10, 5, None, ())
    # earlier tests may have warmed the process-wide AOT cache; a cold one
    # makes the compile/retrace counters deterministic
    clear_program_cache()
    eng = LocalEngine(op, mode="ell")
    inits = obs.events("engine_init")
    assert inits and inits[-1]["engine"] == "local"
    ev = inits[-1]
    assert ev["mode"] == "ell" and ev["n_states"] == op.basis.number_states
    for key in ("build_structure_s", "compile_s", "transfer_s", "diag_s",
                "init_s", "structure_restored", "basis_restored"):
        assert key in ev
    # cold build in a fresh registry: AOT executables were compiled — but a
    # healthy cold start compiles each distinct program ONCE, which is NOT
    # a retrace
    snap = obs.snapshot()["counters"]
    assert snap.get("aot_executable_cache{event=compile}", 0) >= 1
    assert snap.get("retrace_count", 0) == 0
    # same builder programs at a different shape key: a genuine retrace
    from distributed_matvec_tpu.parallel.engine import pad_to_multiple
    LocalEngine(op, mode="ell",
                batch_size=pad_to_multiple(op.basis.number_states, 8) // 2)
    assert obs.snapshot()["counters"].get("retrace_count", 0) >= 1

    x = rng.random(op.basis.number_states) - 0.5
    before = obs.histogram("matvec_apply_ms", engine="local").count
    eng.matvec(x)
    after = obs.histogram("matvec_apply_ms", engine="local").count
    assert after == before + 1


def test_engine_apply_disabled_zero_overhead(clean_obs, rng, monkeypatch):
    """The acceptance guard: with the layer off, an engine apply records
    nothing, touches no sink, and returns bit-identical results."""
    op = build_heisenberg(10, 5, None, ())
    from distributed_matvec_tpu.parallel.engine import LocalEngine
    eng = LocalEngine(op, mode="ell")
    x = rng.random(op.basis.number_states) - 0.5
    y_on = np.asarray(eng.matvec(x))

    monkeypatch.setenv("DMT_OBS", "off")
    obs.reset_all()

    def _explode(*a, **k):                         # any sink touch is a bug
        raise AssertionError("obs layer touched while disabled")

    monkeypatch.setattr(obs_events, "_write", _explode)
    assert obs.histogram("matvec_apply_ms", engine="local") \
        is obs_metrics.NULL
    y_off = np.asarray(eng.matvec(x))
    np.testing.assert_array_equal(y_on, y_off)
    assert obs.events() == []
    assert obs.snapshot() == {"counters": {}, "gauges": {},
                              "histograms": {}}


def test_lanczos_emits_convergence_trace(clean_obs, rng):
    op = build_heisenberg(10, 5, None, ())
    from distributed_matvec_tpu.parallel.engine import LocalEngine
    from distributed_matvec_tpu.solve import lanczos
    eng = LocalEngine(op, mode="ell")
    res = lanczos(eng.matvec, op.basis.number_states, k=1, max_iters=48,
                  tol=1e-10, seed=3)
    traces = obs.events("lanczos_trace")
    assert traces, "no convergence trace emitted"
    # residuals decrease to convergence; the last trace matches the result
    last = traces[-1]
    assert last["ritz"][0] == pytest.approx(float(res.eigenvalues[0]))
    ends = obs.events("solver_end")
    assert ends and ends[-1]["converged"] == res.converged


# ---------------------------------------------------------------------------
# obs_report


def _write_detail(path, device_ms, iters_per_s=100.0):
    detail = {"chain_16": {"config": "heisenberg_chain_16",
                           "device_ms": device_ms,
                           "engine_init_s": 1.0,
                           "lanczos_iters_per_s": iters_per_s},
              "broken": {"error": "Boom()"}}
    path.write_text(json.dumps(detail))
    return str(path)


def test_obs_report_diff_regression(tmp_path):
    rep = _load_obs_report()
    base = _write_detail(tmp_path / "base.json", device_ms=10.0)
    ok = _write_detail(tmp_path / "ok.json", device_ms=11.0)
    bad = _write_detail(tmp_path / "bad.json", device_ms=13.0)
    # +10% within a 20% gate; +30% beyond it → exit 1
    assert rep.main(["diff", base, ok, "--threshold", "0.2"]) == 0
    assert rep.main(["diff", base, bad, "--threshold", "0.2"]) == 1
    # improvement is never a regression
    assert rep.main(["diff", bad, base, "--threshold", "0.2"]) == 0
    # direction-aware: a rate metric gates on DECREASE
    slow = _write_detail(tmp_path / "slow.json", device_ms=10.0,
                         iters_per_s=50.0)
    assert rep.main(["diff", base, slow, "--threshold", "0.2",
                     "--metric", "lanczos_iters_per_s"]) == 1
    assert rep.main(["diff", slow, base, "--threshold", "0.2",
                     "--metric", "lanczos_iters_per_s"]) == 0
    # no overlap at all is its own (non-zero) failure mode
    empty = tmp_path / "empty.json"
    empty.write_text("{}")
    assert rep.main(["diff", base, str(empty)]) == 2


def test_obs_report_diff_config_filter(tmp_path):
    rep = _load_obs_report()
    base = _write_detail(tmp_path / "b.json", device_ms=10.0)
    bad = _write_detail(tmp_path / "n.json", device_ms=20.0)
    # the regressed config filtered OUT → nothing in common → rc 2
    assert rep.main(["diff", base, bad, "--config", "kagome"]) == 2
    assert rep.main(["diff", base, bad, "--config", "chain_16"]) == 1


def test_obs_report_summarize_run_dir(clean_obs, tmp_path, monkeypatch):
    """A crafted run (engine init + solver trace + snapshot, two procs)
    reconstructs the init split, cache hit rate, and residual series."""
    rep = _load_obs_report()
    run = tmp_path / "run"
    monkeypatch.setenv("DMT_OBS_DIR", str(run))
    obs.emit("engine_init", engine="local", mode="ell", n_states=100,
             pair=False, basis_restored=True, structure_restored=False,
             build_structure_s=2.0, compile_s=0.5, kernels_s=1.5,
             transfer_s=0.25, diag_s=0.125, init_s=3.0)
    obs.emit("solver_start", solver="lanczos", k=1, tol=1e-10)
    obs.emit("lanczos_trace", solver="lanczos", iter=16, basis_size=16,
             ritz=[-28.1], residual=[1.0])
    obs.emit("lanczos_trace", solver="lanczos", iter=32, basis_size=32,
             ritz=[-28.5], residual=[1e-11])
    obs.emit("solver_end", solver="lanczos", iters=32, converged=True,
             eigenvalues=[-28.5])
    obs.emit("bench_result", config="heisenberg_chain_16", device_ms=2.5,
             n_states=100)
    obs.emit("metrics_snapshot", metrics={"counters": {
        "artifact_cache{event=hit,kind=structure}": 3,
        "artifact_cache{event=miss,kind=structure}": 1,
        "aot_executable_cache{event=hit}": 7,
        "aot_executable_cache{event=compile}": 1,
        "bytes_h2d{path=engine_tables}": 1024,
        "exchange_overflow{engine=distributed}": 0,
        "exchange_invalid{engine=distributed}": 2,
        "retrace_count": 1}})
    obs.flush()
    obs.reset()
    # a second rank's stream must merge in (rank, seq) order
    (run / "rank_1").mkdir()
    (run / "rank_1" / "events.jsonl").write_text(json.dumps(
        {"seq": 0, "ts": 0.0, "proc": 1, "rank": 1, "n_ranks": 2,
         "kind": "engine_init",
         "engine": "distributed", "mode": "ell", "n_states": 100,
         "basis_restored": False, "structure_restored": True,
         "build_structure_s": 0.0, "compile_s": 0.0, "kernels_s": 0.0,
         "transfer_s": 0.1, "diag_s": 0.0, "init_s": 0.2}) + "\n")

    s = rep.run_summary(rep.load_events(str(run)))
    assert s["processes"] == [0, 1]
    assert len(s["engine_inits"]) == 2
    local = s["engine_inits"][0]
    assert (local["build_structure_s"], local["compile_s"],
            local["transfer_s"]) == (2.0, 0.5, 0.25)
    caches = s["cache"]["caches"]
    assert caches["artifact_cache/structure"]["hit_rate"] == 0.75
    assert caches["aot_executable_cache"]["hit_rate"] == pytest.approx(7 / 8)
    assert s["cache"]["bytes_h2d"] == 1024
    assert s["cache"]["retrace_count"] == 1
    # the overflow/invalid exchange counters are surfaced even at zero
    assert s["health"]["counters"][
        "exchange_overflow{engine=distributed}"] == 0
    assert s["health"]["counters"][
        "exchange_invalid{engine=distributed}"] == 2
    sv = s["solvers"][0]
    assert sv["converged"] is True
    assert [t["iter"] for t in sv["trace"]] == [16, 32]
    assert sv["trace"][-1]["residual"] == [1e-11]
    assert s["bench"]["heisenberg_chain_16"]["device_ms"] == 2.5
    # the human renderer must not throw on the same summary
    rep.print_summary(s)


def test_obs_report_load_events_jsonl_and_torn_line(tmp_path, capsys):
    rep = _load_obs_report()
    f = tmp_path / "e.jsonl"
    f.write_text(json.dumps({"seq": 0, "proc": 0, "kind": "a"}) + "\n"
                 + '{"seq": 1, "proc": 0, "ki')       # torn final line
    evs = rep.load_events(str(f))
    assert [e["kind"] for e in evs] == ["a"]


# ---------------------------------------------------------------------------
# numerical-health probes + solver watchdog


@pytest.fixture
def health_every_1():
    """Probe cadence 1 (every apply), restored afterwards."""
    from distributed_matvec_tpu.utils.config import get_config, update_config
    saved = get_config().health_every
    update_config(health_every=1)
    yield
    update_config(health_every=saved)


def test_health_probe_nan_event_and_strict(clean_obs, rng, monkeypatch,
                                           health_every_1):
    """A NaN injected into the input fires a `health` event with the
    correct rank + nonfinite count; DMT_HEALTH=strict turns it into a
    HealthError raised from the apply itself."""
    from distributed_matvec_tpu.parallel.engine import LocalEngine
    op = build_heisenberg(10, 5, None, ())
    eng = LocalEngine(op, mode="ell")
    n = op.basis.number_states
    x = rng.random(n) - 0.5
    x[3] = np.nan
    eng.matvec(x)
    obs.drain_health()
    evs = obs.events("health")
    assert evs, "no health event for a NaN-carrying apply"
    ev = evs[-1]
    assert ev["check"] == "nonfinite_output" and ev["level"] == "critical"
    assert ev["rank"] == 0 and ev["engine"] == "local"
    assert ev["count"] >= 1                      # NaN propagated to outputs
    snap = obs.snapshot()
    assert snap["counters"]["matvec_nonfinite{engine=local}"] >= 1
    assert snap["counters"]["health_events{level=critical}"] >= 1

    monkeypatch.setenv("DMT_HEALTH", "strict")
    with pytest.raises(obs.HealthError, match="nonfinite_output"):
        eng.matvec(x)


def test_health_probe_disabled_compiled_out(clean_obs, rng, monkeypatch):
    """DMT_OBS=off guard (the PR-2 pattern extended to the probes): no
    probe program is ever dispatched, results stay bit-identical, AND the
    apply program itself carries no probe ops in ANY mode — the probe is a
    separate piggyback program, so toggling it can neither change nor
    retrace the hot program."""
    import jax
    import jax.numpy as jnp

    from distributed_matvec_tpu.obs import health as H
    from distributed_matvec_tpu.parallel.engine import LocalEngine
    op = build_heisenberg(10, 5, None, ())
    eng = LocalEngine(op, mode="ell")
    n = op.basis.number_states
    x = rng.random(n) - 0.5
    y_on = np.asarray(eng.matvec(x))

    monkeypatch.setenv("DMT_OBS", "off")
    obs.reset_all()

    def _explode(*a, **k):
        raise AssertionError("health probe dispatched while obs disabled")

    monkeypatch.setattr(H, "_stats", _explode)
    assert not obs.probes_enabled()
    y_off = np.asarray(eng.matvec(x))
    np.testing.assert_array_equal(y_on, y_off)
    assert obs.events() == []
    hlo = jax.jit(eng._apply_fn).lower(
        jnp.asarray(x), eng._operands).compile().as_text()
    assert "is-finite" not in hlo.lower()


def test_omega_estimate_thresholds(clean_obs):
    """Healthy recurrence → ω ~ ε (quiet); a collapsing β explodes the
    estimate past the warn/critical thresholds."""
    from distributed_matvec_tpu.obs import health as H
    rng_ = np.random.default_rng(0)
    alph = rng_.normal(0.0, 1.0, 64)
    bet = np.abs(rng_.normal(1.0, 0.1, 64)) + 0.5
    assert H.omega_estimate(alph, bet, 0, 64) < H.OMEGA_WARN

    bet_bad = bet.copy()
    bet_bad[40] = 1e-13                          # near-breakdown step
    om = H.omega_estimate(alph, bet_bad, 0, 64)
    assert om >= H.OMEGA_CRITICAL


def test_solver_watchdog_events_and_strict(clean_obs, monkeypatch):
    from distributed_matvec_tpu.solve.lanczos import _Watchdog
    wd = _Watchdog("lanczos")
    # converged closure is the happy path: no event
    wd.breakdown(10, 1e-16, converged=True)
    assert obs.events("solver_health") == []
    wd.breakdown(10, 1e-16, converged=False)
    ev = obs.events("solver_health")[-1]
    assert ev["check"] == "beta_breakdown" and ev["level"] == "critical"
    assert ev["solver"] == "lanczos" and ev["rank"] == 0

    # stagnation: warn only after STALL_CHECKS flat convergence checks
    wd2 = _Watchdog("lanczos")
    for _ in range(_Watchdog.STALL_CHECKS + 1):
        wd2.check_stagnation(np.array([1e-3]), 1)
    stalls = [e for e in obs.events("solver_health")
              if e["check"] == "ritz_stagnation"]
    assert stalls and stalls[-1]["level"] == "warn"

    monkeypatch.setenv("DMT_HEALTH", "strict")
    with pytest.raises(obs.HealthError, match="beta_breakdown"):
        wd.breakdown(11, 1e-16, converged=False)


def test_lanczos_trace_carries_omega(clean_obs, rng):
    """The per-check lanczos_trace events gain the ω estimate, and a
    healthy converging solve emits zero solver_health events."""
    from distributed_matvec_tpu.parallel.engine import LocalEngine
    from distributed_matvec_tpu.solve import lanczos
    op = build_heisenberg(10, 5, None, ())
    eng = LocalEngine(op, mode="ell")
    res = lanczos(eng.matvec, op.basis.number_states, k=1, max_iters=48,
                  tol=1e-10, seed=3)
    assert res.converged
    traces = obs.events("lanczos_trace")
    assert traces and "omega" in traces[-1]
    assert traces[-1]["omega"] < 1e-8            # healthy: ~eps
    # healthy = zero warn/critical; the selective-reorth fallback marker
    # (level "info") may legitimately fire as Ritz pairs converge
    assert [e for e in obs.events("solver_health")
            if e.get("level") in ("warn", "critical")] == []
    assert obs.events("health") == []
    # the block solver carries the (scalarized) omega estimate too
    from distributed_matvec_tpu.solve import lanczos_block
    lanczos_block(eng.matvec, op.basis.number_states, k=1, max_iters=24,
                  tol=1e-8, seed=3)
    blk = [e for e in obs.events("lanczos_trace")
           if e["solver"] == "lanczos_block"]
    assert len(blk) >= 2 and "omega" in blk[-1]
    assert blk[-1]["omega"] < 1e-8


# ---------------------------------------------------------------------------
# cross-rank merge / skew / straggler report


def _write_rank_events(run, rank, events):
    d = run / f"rank_{rank}"
    d.mkdir(parents=True, exist_ok=True)
    with open(d / "events.jsonl", "w") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")


def _toy_two_rank_run(run, skew=5.0, n_apply=6, late_apply=3,
                      late_s=0.040):
    """A deliberately imbalanced 2-rank toy run: rank 1's clock runs
    ``skew`` seconds ahead and its apply ``late_apply`` arrives
    ``late_s`` late — the straggler the report must attribute."""
    t0 = 1000.0
    for r, off in ((0, 0.0), (1, skew)):
        evs = []

        def ev(kind, ts, **fields):
            e = {"seq": len(evs), "ts": round(ts + off, 6), "proc": r,
                 "rank": r, "n_ranks": 2, "kind": kind}
            e.update(fields)
            evs.append(e)

        ev("rank_shards", t0, engine="distributed", mode="ell",
           n_shards=8, shard_size=128,
           shards=[0, 1, 2, 3] if r == 0 else [4, 5, 6, 7],
           states=460 if r == 0 else 464)
        ev("engine_init", t0 + 1.0, engine="distributed", mode="ell",
           n_states=924, basis_restored=False, structure_restored=False,
           build_structure_s=0.8 if r == 0 else 0.9, compile_s=0.1,
           kernels_s=0.1, transfer_s=0.05, diag_s=0.01, init_s=1.2)
        for i in range(n_apply):
            late = late_s if (r == 1 and i == late_apply) else 0.0
            ev("matvec_apply", t0 + 2.0 + 0.1 * i + late,
               engine="distributed", apply=i, wall_ms=2.0, bytes=100_000)
        ev("metrics_snapshot", t0 + 3.0, metrics={
            "counters": {"exchange_bytes{engine=distributed}": 600_000},
            "gauges": {},
            "histograms": {"double_buffer_stall_ms": {
                "buckets": [1.0], "counts": [3, 0],
                "sum": 1.5, "count": 3}}})
        _write_rank_events(run, r, evs)


def test_obs_report_merge_and_straggler(tmp_path):
    rep = _load_obs_report()
    run = tmp_path / "run"
    _toy_two_rank_run(run, skew=5.0, n_apply=6, late_apply=3)
    events = rep.load_events(str(run))
    assert sorted({e["rank"] for e in events}) == [0, 1]

    # the median-based skew estimate recovers the 5 s clock offset without
    # being polluted by the straggling apply
    offsets = rep.estimate_skew(events)
    assert offsets[0] == 0.0
    assert abs(offsets[1] - 5.0) < 5e-3

    merged, _ = rep.merge_events(events)
    adj = [e["ts_adj"] for e in merged]
    assert adj == sorted(adj)                    # ONE ordered timeline
    for r in (0, 1):                             # per-rank seq order kept
        seqs = [e["seq"] for e in merged if e["rank"] == r]
        assert seqs == sorted(seqs)
    # after correction the two ranks interleave (uncorrected, all of rank
    # 0 would precede all of rank 1 by 5 s)
    order = [e["rank"] for e in merged]
    assert order != sorted(order)

    table = rep.rank_table(events)
    rows = {row["rank"]: row for row in table["rows"]}
    assert rows[0]["states"] == 460 and rows[1]["states"] == 464
    per_bytes = [rows[r]["bytes_exchanged"] for r in (0, 1)]
    mean_b = sum(per_bytes) / 2
    assert all(abs(b - mean_b) <= 0.12 * mean_b for b in per_bytes)
    assert rows[0]["plan_wall_s"] == pytest.approx(0.8)
    assert rows[1]["db_stall_ms"] == pytest.approx(1.5)

    st = table["straggler"]
    assert st["applies"] == 6
    # the deliberate straggler is attributed to rank 1, apply 3, with
    # excess = max - median = late/2 for two ranks
    assert st["worst"][0]["rank"] == 1 and st["worst"][0]["apply"] == 3
    assert st["worst"][0]["excess_ms"] == pytest.approx(20.0, rel=0.1)
    assert st["per_rank"][1]["straggled"] >= 1
    # rank 0 sat at the barrier for the late apply
    assert st["per_rank"][0]["barrier_wait_ms"] > 0
    rep.print_rank_report(table, show_ranks=True)   # renderer must not throw


def test_obs_report_legacy_and_mixed_layouts(tmp_path, capsys):
    """Legacy flat events.p*.jsonl dirs still load; a dir holding BOTH a
    legacy and a rank_*/ run (reused DMT_OBS_DIR across the upgrade) reads
    only the current layout and warns instead of interleaving two runs'
    duplicate seq numbers into one corrupt timeline."""
    rep = _load_obs_report()
    run = tmp_path / "run"
    run.mkdir()
    (run / "events.p0.jsonl").write_text(json.dumps(
        {"seq": 0, "proc": 0, "kind": "old"}) + "\n")
    assert [e["kind"] for e in rep.load_events(str(run))] == ["old"]
    (run / "rank_0").mkdir()
    (run / "rank_0" / "events.jsonl").write_text(json.dumps(
        {"seq": 0, "rank": 0, "n_ranks": 1, "kind": "new"}) + "\n")
    evs = rep.load_events(str(run))
    assert [e["kind"] for e in evs] == ["new"]
    assert "ignoring 1 legacy" in capsys.readouterr().err


def test_obs_report_replica_run_flagged_non_collective(tmp_path):
    """Rank-local replica engines (overlapping shard ids across ranks) are
    flagged so barrier columns read as progress skew, not barrier waits."""
    rep = _load_obs_report()
    run = tmp_path / "run"
    for r in (0, 1):
        _write_rank_events(run, r, [
            {"seq": 0, "ts": 1000.0, "rank": r, "n_ranks": 2,
             "kind": "rank_shards", "engine": "distributed", "mode": "ell",
             "n_shards": 4, "shard_size": 64,
             "shards": [0, 1, 2, 3], "states": 924}])
    table = rep.rank_table(rep.load_events(str(run)))
    assert table["collective"] is False
    rep.print_rank_report(table, show_ranks=True)


def test_obs_report_summarize_tolerates_rank_layout(clean_obs, tmp_path,
                                                    monkeypatch):
    """summarize over the rank-subdirectory layout the sink now writes."""
    rep = _load_obs_report()
    run = tmp_path / "run"
    monkeypatch.setenv("DMT_OBS_DIR", str(run))
    obs.emit("bench_result", config="c16", device_ms=1.5)
    obs.flush()
    obs.reset()
    assert (run / "rank_0" / "events.jsonl").exists()
    s = rep.run_summary(rep.load_events(str(run)))
    assert s["bench"]["c16"]["device_ms"] == 1.5
    rep.print_summary(s)


def test_follow_poll_rotation(tmp_path):
    """tail --follow survives rotation (new inode), in-place truncation,
    and truncation that regrew past the old offset between polls, without
    losing the recreated file's events."""
    rep = _load_obs_report()
    f = tmp_path / "events.jsonl"
    f.write_text(json.dumps({"seq": 0, "kind": "a"}) + "\n")
    fs = str(f)
    state = {fs: (rep._stat_id(fs), f.stat().st_size, rep._head_bytes(fs))}
    partial = {}
    with open(f, "a") as fh:                     # plain append
        fh.write(json.dumps({"seq": 1, "kind": "b"}) + "\n")
    assert [e["kind"] for e in rep._follow_poll([fs], state, partial)] \
        == ["b"]
    os.remove(f)                                 # rotation: new inode
    f.write_text(json.dumps({"seq": 0, "kind": "c"}) + "\n")
    assert [e["kind"] for e in rep._follow_poll([fs], state, partial)] \
        == ["c"]
    f.write_text("")                             # truncation in place
    assert rep._follow_poll([fs], state, partial) == []
    with open(f, "a") as fh:
        fh.write(json.dumps({"seq": 0, "kind": "d"}) + "\n")
    assert [e["kind"] for e in rep._follow_poll([fs], state, partial)] \
        == ["d"]
    # truncated AND regrown past the old offset before the next poll
    # (same inode, larger size — only the head fingerprint catches it)
    f.write_text(json.dumps({"seq": 0, "kind": "e", "pad": "x" * 64}) + "\n"
                 + json.dumps({"seq": 1, "kind": "f"}) + "\n")
    assert [e["kind"] for e in rep._follow_poll([fs], state, partial)] \
        == ["e", "f"]


def test_multihost_obs_rank_merge(tmp_path):
    """A REAL 2-process run (multihost worker harness, fast leg): rank-
    tagged events land under rank_0/ and rank_1/, merge produces one
    ordered timeline, and the skew table reports per-rank survivor states
    and bytes within the enumeration's ±12% balance bound."""
    import socket
    import subprocess
    import sys as _sys

    rep = _load_obs_report()
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    run = tmp_path / "obs_run"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["DMT_MH_FAST"] = "1"
    env["DMT_OBS_DIR"] = str(run)
    procs = [subprocess.Popen(
        [_sys.executable, worker, str(pid), "2", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid}:\n{out[-2000:]}"
        assert f"[p{pid}] MULTIHOST_OK" in out, out[-2000:]

    assert (run / "rank_0" / "events.jsonl").exists()
    assert (run / "rank_1" / "events.jsonl").exists()
    events = rep.load_events(str(run))
    ranks = sorted({e["rank"] for e in events})
    assert ranks == [0, 1]
    assert all(e.get("n_ranks") == 2 for e in events)

    merged, offsets = rep.merge_events(events)
    assert set(offsets) == {0, 1}
    adj = [e["ts_adj"] for e in merged]
    assert adj == sorted(adj)                    # one ordered timeline
    for r in ranks:
        seqs = [e["seq"] for e in merged if e["rank"] == r]
        assert seqs == sorted(seqs)

    table = rep.rank_table(events)
    rows = {row["rank"]: row for row in table["rows"]}
    # This leg runs identical rank-local REPLICA engines (the CPU backend
    # cannot execute cross-process programs), so states/bytes are equal
    # across ranks by construction: these are stream-integrity checks —
    # every rank's census and per-apply bytes survived the merge within
    # the ±12% bound.  The bound's DISCRIMINATING test (unequal ranks,
    # deliberate straggler) is test_obs_report_merge_and_straggler.
    states = [rows[r]["states"] for r in ranks]
    mean_s = sum(states) / 2
    assert all(s and abs(s - mean_s) <= 0.12 * mean_s for s in states), \
        states
    per_bytes = [rows[r]["bytes_exchanged"] for r in ranks]
    mean_b = sum(per_bytes) / 2
    assert all(b > 0 and abs(b - mean_b) <= 0.12 * mean_b
               for b in per_bytes), per_bytes
    assert table["collective"] is False          # replicas, flagged as such
    n_apply = rows[0]["applies"]
    assert n_apply >= 4
    assert all(rows[r]["applies"] == n_apply for r in ranks)
    assert all(rows[r]["plan_wall_s"] is not None for r in ranks)
    assert table["straggler"]["applies"] >= 4
    rep.print_rank_report(table, show_ranks=True)


# ---------------------------------------------------------------------------
# satellites: logging + profiling


def test_log_warn_and_process_index_cache(capsys):
    from distributed_matvec_tpu.utils import logging as L
    L.log_warn("disk ", "full")
    err = capsys.readouterr().err
    assert "[Warn] [0] disk full" in err
    # cached after first success: later calls never re-query jax
    assert L._proc_idx is not None
    assert L._process_index() == L._proc_idx


def test_maybe_profile_override(monkeypatch, tmp_path):
    from distributed_matvec_tpu.utils import profiling
    from distributed_matvec_tpu.utils.config import update_config
    calls = []

    class _Trace:
        def __init__(self, d, create_perfetto_link=False):
            calls.append(d)

        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

    import jax
    monkeypatch.setattr(jax.profiler, "trace", _Trace)
    # config field unset: no-op
    update_config(profile_dir="")
    with profiling.maybe_profile():
        pass
    assert calls == []
    # explicit override wins without touching global config
    with profiling.maybe_profile(profile_dir=str(tmp_path / "p")):
        pass
    assert calls == [str(tmp_path / "p")]
    # config fallback still works; explicit "" forces the no-op over it
    update_config(profile_dir=str(tmp_path / "cfg"))
    try:
        with profiling.maybe_profile():
            pass
        assert calls[-1] == str(tmp_path / "cfg")
        with profiling.maybe_profile(profile_dir=""):
            pass
        assert calls[-1] == str(tmp_path / "cfg")  # unchanged
    finally:
        update_config(profile_dir="")
