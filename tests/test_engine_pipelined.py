"""Pipelined distributed applies (DESIGN.md §25) vs the sequential truth.

A ``pipeline_depth >= 2`` apply restructures the schedule — plan fetches
prefetched by worker threads, produce/exchange split programs with the
exchange decomposed into ``ppermute`` rounds (streamed), or the in-program
software pipeline (fused) — but NEVER the arithmetic: exchanges retire in
chunk order and the staged exchange reassembles the monolithic
``all_to_all`` layout element-for-element, so every result here is
asserted bit-identical to the sequential schedule (which is itself
bit-identical to fused).  Plus: the depth knob's parsing/auto policy, the
structural counters, the apply_phases pipeline record, and a REAL
2-process leg where pipelining must cut the measured time-at-barrier.
"""

import os

import jax
import numpy as np
import pytest

from distributed_matvec_tpu import obs
from distributed_matvec_tpu.parallel.distributed import (DistributedEngine,
                                                         _staged_all_to_all)
from distributed_matvec_tpu.utils.config import update_config

from test_operator import build_heisenberg


def _ndev() -> int:
    return len(jax.devices())


needs_8 = pytest.mark.skipif("_ndev() < 8", reason="needs 8 virtual devices")
needs_4 = pytest.mark.skipif("_ndev() < 4", reason="needs 4 virtual devices")


PIPE_CONFIGS = [
    # (n, hw, inv, syms, ndev) — a |G|>1 sector, a trivial group on a
    # wider mesh (D−1 = 3 ppermute rounds), and a complex-character
    # sector (c128 on CPU)
    (12, 6, 1, [([*range(1, 12), 0], 0)], 2),
    (10, 5, None, (), 4),
    (10, 5, None, [([*range(1, 10), 0], 1)], 4),
]


def _build(n, hw, inv, syms):
    op = build_heisenberg(n, hw, inv, syms)
    op.basis.build()
    return op


@pytest.mark.parametrize("mode", ["streamed", "fused"])
@pytest.mark.parametrize("n,hw,inv,syms,ndev", PIPE_CONFIGS)
def test_pipelined_bit_identical(mode, n, hw, inv, syms, ndev, rng):
    """Acceptance: pipelined y == sequential y to the BIT — fused and
    streamed, real and complex sectors, multi-round staged exchange."""
    if _ndev() < ndev:
        pytest.skip(f"needs {ndev} devices")
    op = _build(n, hw, inv, syms)
    x = rng.random(op.basis.number_states) - 0.5
    if not op.effective_is_real:
        x = x.astype(np.complex128)
    seq = DistributedEngine(op, n_devices=ndev, mode=mode, batch_size=32,
                            pipeline_depth=0)
    pipe = DistributedEngine(op, n_devices=ndev, mode=mode, batch_size=32,
                             pipeline_depth=4)
    assert seq.pipeline_depth == 0
    assert pipe.pipeline_depth >= 2
    ys = np.asarray(seq.matvec(seq.to_hashed(x)))
    yp = np.asarray(pipe.matvec(pipe.to_hashed(x)))
    np.testing.assert_array_equal(ys, yp)


@needs_8
def test_pipelined_batch_and_wide_batch_bit_identical(rng):
    """k<=4 batches ride one pipelined stream; k=6 splits into column
    groups that each re-stream — both bit-identical to sequential."""
    op = _build(10, 5, None, ())
    n = op.basis.number_states
    seq = DistributedEngine(op, n_devices=8, mode="streamed", batch_size=32,
                            pipeline_depth=0)
    pipe = DistributedEngine(op, n_devices=8, mode="streamed", batch_size=32,
                             pipeline_depth=2)
    for k in (3, 6):
        X = rng.random((n, k)) - 0.5
        Ys = np.asarray(seq.matvec(seq.to_hashed(X)))
        Yp = np.asarray(pipe.matvec(pipe.to_hashed(X)))
        np.testing.assert_array_equal(Ys, Yp)


@needs_4
def test_depth_sweep_and_clamp(rng):
    """Every depth >= 2 gives the same bits; depth is clamped to the
    chunk count (streamed) and to 2 (fused — the in-program pipeline is
    one in-flight exchange deep)."""
    op = _build(10, 5, None, ())
    x = rng.random(op.basis.number_states) - 0.5
    seq = DistributedEngine(op, n_devices=4, mode="streamed", batch_size=32,
                            pipeline_depth=0)
    ys = np.asarray(seq.matvec(seq.to_hashed(x)))
    nchunks = seq._plan_nchunks_v
    assert nchunks >= 2
    for depth in (2, 3, nchunks + 7):
        pipe = DistributedEngine(op, n_devices=4, mode="streamed",
                                 batch_size=32, pipeline_depth=depth)
        assert pipe.pipeline_depth == min(depth, nchunks)
        np.testing.assert_array_equal(
            ys, np.asarray(pipe.matvec(pipe.to_hashed(x))))
    fp = DistributedEngine(op, n_devices=4, mode="fused", batch_size=32,
                           pipeline_depth=6)
    assert fp.pipeline_depth == 2       # reported honestly
    np.testing.assert_array_equal(
        ys, np.asarray(fp.matvec(fp.to_hashed(x))))


@needs_4
def test_counters_preserved_and_overflow_still_raises(rng):
    """Structural overflow/invalid totals are identical between the
    schedules, and a deliberately tiny exchange capacity still fails
    loudly through the pipelined fused program."""
    op = _build(10, 5, None, ())
    seq = DistributedEngine(op, n_devices=4, mode="streamed", batch_size=32,
                            pipeline_depth=0)
    pipe = DistributedEngine(op, n_devices=4, mode="streamed", batch_size=32,
                             pipeline_depth=3)
    assert (pipe._stream_overflow, pipe._stream_invalid) \
        == (seq._stream_overflow, seq._stream_invalid)
    x = rng.random(op.basis.number_states) - 0.5
    cfg = update_config(remote_buffer_size=8)
    try:
        with pytest.warns(RuntimeWarning, match="capacity"):
            eng = DistributedEngine(op, n_devices=4, mode="fused",
                                    batch_size=32, pipeline_depth=2)
        with pytest.raises(RuntimeError, match="overflowed"):
            eng.matvec(eng.to_hashed(x))
    finally:
        update_config(remote_buffer_size=150_000)


def test_knob_parsing_and_mode_applicability():
    """Constructor beats config; junk values are loud; single-program
    plan modes (ell) always resolve depth 0."""
    op = _build(10, 5, None, ())
    cfg = update_config(pipeline="3")
    try:
        eng = DistributedEngine(op, n_devices=2, mode="streamed",
                                batch_size=32)
        assert eng.pipeline_depth == 3
        eng0 = DistributedEngine(op, n_devices=2, mode="streamed",
                                 batch_size=32, pipeline_depth=0)
        assert eng0.pipeline_depth == 0
        ell = DistributedEngine(op, n_devices=2, mode="ell")
        assert ell.pipeline_depth == 0
        with pytest.raises(ValueError, match="pipeline depth"):
            DistributedEngine(op, n_devices=2, mode="streamed",
                              batch_size=32, pipeline_depth="sideways")
    finally:
        update_config(pipeline="off")


def test_auto_depth_policy():
    """`auto` consults the §22 cost model: multi-chunk streamed applies
    (whose plan stream dominates the hideable time) pick the deep
    setting; a single-chunk apply stays off."""
    from distributed_matvec_tpu.obs import roofline as R

    cal = R.default_calibration("cpu")
    counts = {"plan_h2d": {"bytes": 10_000_000},
              "compute": {"bytes": 0, "gathers": 0, "flops": 1_000_000},
              "exchange": {"bytes": 100_000},
              "accumulate": {"gathers": 1000}}
    assert R.choose_pipeline_depth(counts, cal, 1, 2) == 0
    assert R.choose_pipeline_depth(counts, cal, 8, 2) == R.AUTO_PIPELINE_DEEP
    # nothing hideable: no stream, no exchange worth the bookkeeping
    lean = {"plan_h2d": {"bytes": 0},
            "compute": {"gathers": 10_000_000},
            "exchange": {"bytes": 0},
            "accumulate": {"gathers": 1000}}
    assert R.choose_pipeline_depth(lean, cal, 8, 2) == 0
    op = _build(10, 5, None, ())
    eng = DistributedEngine(op, n_devices=2, mode="streamed", batch_size=32,
                            pipeline_depth="auto")
    assert eng.pipeline_depth in (0, 2, R.AUTO_PIPELINE_DEEP)


def test_staged_exchange_equals_all_to_all(rng):
    """The ppermute decomposition reassembles the monolithic all_to_all
    layout element-for-element (the §25 bit-identity cornerstone)."""
    if _ndev() < 4:
        pytest.skip("needs 4 devices")
    from jax.sharding import Mesh, PartitionSpec as P

    from distributed_matvec_tpu.parallel.mesh import (SHARD_AXIS,
                                                      shard_map_compat)

    D, cap = 4, 6
    mesh = Mesh(np.array(jax.devices()[:D]), (SHARD_AXIS,))
    x = rng.random((D, D, cap))

    def mono(a):
        return jax.lax.all_to_all(a[0], SHARD_AXIS, 0, 0, tiled=True)[None]

    def staged(a):
        return _staged_all_to_all(a[0], SHARD_AXIS)[None]

    spec = P(SHARD_AXIS, None, None)
    f_mono = shard_map_compat(mono, mesh=mesh, in_specs=(spec,),
                              out_specs=spec)
    f_staged = shard_map_compat(staged, mesh=mesh, in_specs=(spec,),
                                out_specs=spec)
    np.testing.assert_array_equal(np.asarray(jax.jit(f_mono)(x)),
                                  np.asarray(jax.jit(f_staged)(x)))


@needs_4
def test_apply_phases_pipeline_record(rng):
    """Pipelined applies emit the measured overlap/time-at-barrier split
    (depth, barrier_ms, hidden_ms, overlap_fraction) and a measured
    `exchange` phase; sequential applies don't grow a pipeline record."""
    op = _build(10, 5, None, ())
    x = rng.random(op.basis.number_states) - 0.5
    seq = DistributedEngine(op, n_devices=4, mode="streamed", batch_size=32,
                            pipeline_depth=0)
    pipe = DistributedEngine(op, n_devices=4, mode="streamed", batch_size=32,
                             pipeline_depth=3)
    seq.matvec(seq.to_hashed(x))
    pipe.matvec(pipe.to_hashed(x))
    evs = [e for e in obs.events("apply_phases")
           if e.get("engine") == "distributed"
           and e.get("mode") == "streamed"]
    assert len(evs) >= 2
    assert "pipeline" not in evs[-2]
    p = evs[-1]["pipeline"]
    assert p["depth"] == 3
    assert p["barrier_ms"] >= 0.0
    assert p["hidden_ms"] >= 0.0
    assert p["overlap_fraction"] is None or 0.0 <= p["overlap_fraction"] <= 1.0
    assert evs[-1]["phases"]["exchange"].get("wall_ms") is not None
    # the roofline report groups the two schedules side by side and
    # prices measured-vs-priced
    from distributed_matvec_tpu.obs import roofline as R

    rep = R.roofline_report(evs, R.default_calibration("cpu"))
    assert "distributed/streamed" in rep["groups"]
    pg = rep["groups"].get("distributed/streamed+pipe3")
    assert pg and pg["pipeline_depth"] == 3
    assert pg.get("measured_speedup") is not None
    assert pg.get("priced_speedup") is not None


@needs_4
def test_prefetcher_error_propagates(rng, monkeypatch):
    """A worker-thread fetch failure surfaces on the apply thread as the
    original exception (the sequential degrade contract, not a hang)."""
    op = _build(10, 5, None, ())
    x = rng.random(op.basis.number_states) - 0.5
    pipe = DistributedEngine(op, n_devices=4, mode="streamed", batch_size=32,
                             pipeline_depth=2)
    pipe.matvec(pipe.to_hashed(x))          # healthy warm-up

    def boom(ci, degrade=True):
        raise OSError(f"synthetic fetch failure on chunk {ci}")

    monkeypatch.setattr(pipe, "_fetch_plan_chunk", boom)
    with pytest.raises(OSError, match="synthetic fetch failure"):
        pipe.matvec(pipe.to_hashed(x))


def test_multihost_pipelined_barrier_cut(tmp_path):
    """A REAL 2-process run (multihost worker, DMT_MH_PIPE leg): with a
    deterministic per-chunk staging latency injected on rank 1 only, the
    pipelined run must cut the measured time-at-barrier vs the sequential
    run AND speed up the straggling rank's applies — asserted from the
    recorded telemetry the way `obs_report report --ranks` computes it.
    The bound here is 1.5x: this leg runs inside the (heavily loaded)
    tier-1 suite, where scheduler jitter eats into the cut; the
    acceptance's >=2x criterion is gated by the standalone
    `make pipeline-check` (measured 4-34x there)."""
    import importlib.util
    import re
    import socket
    import subprocess
    import sys as _sys

    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(os.path.dirname(__file__), os.pardir,
                                   "tools", "obs_report.py"))
    rep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rep)
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    base_env = {k: v for k, v in os.environ.items()
                if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    base_env["DMT_FAULT"] = "plan_upload:delay=12:n=1000000:rank=1"
    base_env["DMT_MH_PIPE_APPLIES"] = "6"

    waits, steady = {}, {}
    for leg, depth in (("seq", 0), ("pipe", 4)):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        run = tmp_path / f"run_{leg}"
        env = dict(base_env, DMT_MH_PIPE=str(depth),
                   DMT_OBS_DIR=str(run))
        procs = [subprocess.Popen(
            [_sys.executable, worker, str(pid), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env) for pid in range(2)]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=300)
                outs.append(out)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            raise
        for pid, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"{leg} worker {pid}:\n{out[-2000:]}"
            assert f"[p{pid}] MULTIHOST_OK" in out, out[-2000:]
        m = re.search(r"\[p1\] PIPE_STEADY_MS ([0-9.]+)", outs[1])
        assert m, outs[1][-2000:]
        steady[leg] = float(m.group(1))
        table = rep.rank_table(rep.load_events(str(run)))
        rows = {row["rank"]: row for row in table["rows"]}
        waits[leg] = float(rows[0]["barrier_wait_ms"] or 0.0)
    cut = waits["seq"] / max(waits["pipe"], 1e-9)
    assert cut >= 1.5, (waits, steady)
    assert steady["pipe"] <= steady["seq"], (waits, steady)


def test_pipelined_disk_tier_corrupt_chunk_repairs_on_apply_thread(
        rng, tmp_path, monkeypatch):
    """A corrupt disk-tier sidecar chunk under a PIPELINED apply: the
    prefetch worker only MARKS the read failure (degrade=False), the
    repair (per-chunk rebuild from structure) runs on the apply thread
    exactly as in the sequential schedule, prefetching resumes for the
    chunks still ahead, and the result stays bit-identical."""
    import gc

    import h5py

    from distributed_matvec_tpu.utils.config import get_config

    monkeypatch.setenv("DMT_ARTIFACT_CACHE", "on")
    monkeypatch.setenv("DMT_ARTIFACT_DIR", str(tmp_path / "art"))
    old = get_config().stream_plan_ram_gb
    update_config(stream_plan_ram_gb=0.0)
    try:
        op = _build(12, 6, None, ())
        x = rng.random(op.basis.number_states) - 0.5
        e1 = DistributedEngine(op, n_devices=2, mode="streamed",
                               batch_size=64, pipeline_depth=0)
        y_ref = np.asarray(e1.matvec(e1.to_hashed(x)))
        assert e1._plan_chunks is None, "disk tier must be active"
        path = list(e1._plan_disk.values())[0]
        del e1
        gc.collect()

        e2 = DistributedEngine(op, n_devices=2, mode="streamed",
                               batch_size=64, pipeline_depth=3)
        assert e2.structure_restored and e2._plan_chunks is None
        for fobj in list(e2._plan_files.values()):
            fobj.close()
        e2._plan_files.clear()
        with h5py.File(path, "r+") as f:
            f["engine_structure"]["dest_0_1"][...] = 0   # mid-stream chunk
        y = np.asarray(e2.matvec(e2.to_hashed(x)))
        np.testing.assert_array_equal(y, y_ref)
        assert any(e["kind"] == "plan_chunk_rebuilt" for e in obs.events())
    finally:
        update_config(stream_plan_ram_gb=old)
