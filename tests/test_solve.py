"""Eigensolvers vs dense eigh — the Diagonalize driver contract.

The reference validates its solver through PRIMME's own residuals and the
golden HDF5 eigenvalues (Diagonalize.chpl:248-256); here the ground truth is
dense diagonalization of the symmetry-adapted matrix at 1e-10.
"""

import numpy as np
import pytest

from distributed_matvec_tpu.parallel.engine import LocalEngine
from distributed_matvec_tpu.solve import lanczos, lanczos_block, lobpcg

from test_operator import build_heisenberg, dense_effective_matrix

TOL = 1e-9


def _dense_evals(op, k):
    h = dense_effective_matrix(op)
    w = np.linalg.eigvalsh(h)
    return w[:k]


@pytest.mark.parametrize("n,hw,inv,syms", [
    (10, 5, None, ()),
    (12, 6, 1, [([*range(1, 12), 0], 0)]),
    (8, 4, None, [([*range(1, 8), 0], 1)]),   # complex sector
])
def test_lanczos_ground_state(n, hw, inv, syms):
    op = build_heisenberg(n, hw, inv, syms)
    op.basis.build()
    eng = LocalEngine(op)
    want = _dense_evals(op, 2)
    res = lanczos(eng.matvec, op.basis.number_states, k=2, tol=1e-11,
                  compute_eigenvectors=True, seed=5)
    assert res.converged
    np.testing.assert_allclose(res.eigenvalues, want, atol=1e-9)
    # eigenvector residual ‖Hv − λv‖
    v = res.eigenvectors[0]
    hv = np.asarray(eng.matvec(v))
    r = np.linalg.norm(hv - res.eigenvalues[0] * np.asarray(v))
    assert r < 1e-7


@pytest.mark.parametrize("n,hw,inv,syms,k,p", [
    (12, 6, None, (), 4, 4),                   # real sector, k == block
    (12, 6, 1, [([*range(1, 12), 0], 0)], 3, 2),  # symmetry-reduced, k > p
    (8, 4, None, [([*range(1, 8), 0], 1)], 2, 2),   # complex sector (c128)
])
def test_lanczos_block_ground_states(n, hw, inv, syms, k, p):
    """Block Lanczos over the engine's batched [N, p] matvec reproduces the
    dense lowest-k spectrum (including near-degenerate clusters a
    single-vector recurrence resolves only sequentially)."""
    op = build_heisenberg(n, hw, inv, syms)
    op.basis.build()
    eng = LocalEngine(op)
    want = _dense_evals(op, k)
    res = lanczos_block(eng.matvec, op.basis.number_states, k=k,
                        block_size=p, tol=1e-11, max_iters=400,
                        compute_eigenvectors=True, seed=7)
    assert res.converged
    np.testing.assert_allclose(res.eigenvalues, want, atol=1e-8)
    for lam, v in zip(res.eigenvalues, res.eigenvectors):
        hv = np.asarray(eng.matvec(np.asarray(v)))
        assert np.linalg.norm(hv - lam * np.asarray(v)) < 1e-6


def test_lanczos_block_rejects_pair_engines():
    from distributed_matvec_tpu.utils.config import get_config, update_config
    op = build_heisenberg(8, 4, None, [([*range(1, 8), 0], 1)])
    op.basis.build()
    prev = get_config().complex_pair
    update_config(complex_pair="on")
    try:
        eng = LocalEngine(op)
        assert eng.pair
        with pytest.raises(ValueError, match="pair-mode"):
            lanczos_block(eng.matvec, op.basis.number_states, k=1)
    finally:
        update_config(complex_pair=prev)


def test_lanczos_distributed(rng):
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    from distributed_matvec_tpu.parallel.distributed import DistributedEngine

    op = build_heisenberg(12, 6)
    op.basis.build()
    eng = DistributedEngine(op, n_devices=4)
    want = _dense_evals(op, 1)
    v0 = eng.random_hashed(seed=11)
    res = lanczos(eng.matvec, v0=v0, k=1, tol=1e-11)
    assert res.converged
    np.testing.assert_allclose(res.eigenvalues[:1], want, atol=1e-9)


@pytest.mark.parametrize("mcap", [24, 51])   # 51: not a multiple of the GS
def test_lanczos_thick_restart(mcap):        # row-block — clamp regression
    rng = np.random.default_rng(0)
    A = rng.standard_normal((400, 400))
    A = (A + A.T) / 2
    import jax.numpy as jnp

    Aj = jnp.asarray(A)
    res = lanczos(lambda x: Aj @ x, 400, k=2, max_basis_size=mcap,
                  min_restart_size=8, tol=1e-10, max_iters=400,
                  compute_eigenvectors=True)
    want = np.linalg.eigvalsh(A)[:2]
    assert res.converged
    np.testing.assert_allclose(res.eigenvalues, want, atol=1e-8)
    v = np.asarray(res.eigenvectors[0])
    assert np.linalg.norm(A @ v - res.eigenvalues[0] * v) < 1e-7


def test_lanczos_wrapped_method_not_hijacked():
    """A bound method other than engine.matvec must keep its own semantics
    (the bound_matvec substitution only applies to the stock matvec)."""
    import jax.numpy as jnp

    op = build_heisenberg(10, 5)
    op.basis.build()
    sigma = 7.0

    class Shifted(LocalEngine):
        def shifted(self, x):
            return self.matvec(x) - sigma * jnp.asarray(x)

    sh = Shifted(op)
    plain = lanczos(LocalEngine(op).matvec, op.basis.number_states, k=1,
                    tol=1e-10)
    res = lanczos(sh.shifted, op.basis.number_states, k=1, tol=1e-10)
    np.testing.assert_allclose(res.eigenvalues[0],
                               plain.eigenvalues[0] - sigma, atol=1e-8)


def test_lobpcg_ground_state():
    op = build_heisenberg(10, 5)
    op.basis.build()
    eng = LocalEngine(op)
    want = _dense_evals(op, 2)
    evals, evecs, iters = lobpcg(eng.matvec, op.basis.number_states, k=2,
                                 tol=1e-10, seed=2)
    np.testing.assert_allclose(evals, want, atol=1e-7)


def test_lobpcg_distributed_real():
    """LOBPCG over a DistributedEngine runs in the hashed flat space (one
    all_to_all per block apply) and returns block-order eigenvectors."""
    import jax as _jax

    if len(_jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from distributed_matvec_tpu.parallel.distributed import DistributedEngine

    op = build_heisenberg(10, 5)
    op.basis.build()
    n = op.basis.number_states
    eng = DistributedEngine(op, n_devices=8)
    want = _dense_evals(op, 2)
    evals, V, iters = lobpcg(eng.matvec, n, k=2, tol=1e-10, seed=2)
    np.testing.assert_allclose(evals, want, atol=1e-7)
    # block-order eigenvectors: H v = E v via the host matvec.  This pins
    # the hashed→block unshuffle (a layout bug gives an O(1) residual);
    # the threshold is solver-noise-tolerant, eigenvalue accuracy above
    # carries the precision check.
    for i in range(2):
        r = np.linalg.norm(op.matvec_host(V[:, i]) - evals[i] * V[:, i])
        assert r < 1e-3, r


def test_lobpcg_distributed_pair():
    """Distributed pair-form complex sector (previously an explicit
    refusal): LOBPCG in the hashed (re, im) flat space vs dense truth."""
    import jax as _jax

    if len(_jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from distributed_matvec_tpu.parallel.distributed import DistributedEngine
    from distributed_matvec_tpu.utils.config import update_config

    op = build_heisenberg(12, 6, None, [([*range(1, 12), 0], 2)])
    op.basis.build()
    assert not op.effective_is_real
    n = op.basis.number_states
    Hd = op.to_sparse().toarray()
    want = np.linalg.eigvalsh(Hd)[:2]
    update_config(complex_pair="on")
    try:
        eng = DistributedEngine(op, n_devices=8)
        assert eng.pair
        evals, V, iters = lobpcg(eng.matvec, n, k=2, tol=1e-10, seed=4)
    finally:
        update_config(complex_pair="auto")
    np.testing.assert_allclose(evals, want, atol=1e-6)
    assert np.iscomplexobj(V) and V.shape == (n, 2)
    for i in range(2):
        r = np.linalg.norm(Hd @ V[:, i] - evals[i] * V[:, i])
        assert r < 1e-5, r


def test_lanczos_checkpoint_resume(tmp_path):
    """Mid-solve checkpoint/resume (beyond the reference: PRIMME state is
    never saved there).  A truncated run checkpoints its Krylov state; the
    rerun resumes — cumulative iteration count, same converged result as
    an uninterrupted solve."""
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    A = rng.standard_normal((400, 400))
    A = (A + A.T) / 2
    Aj = jnp.asarray(A)
    mv = lambda x: Aj @ x                       # noqa: E731
    want = np.linalg.eigvalsh(A)[0]
    ck = str(tmp_path / "lz.h5")

    partial_res = lanczos(mv, 400, k=1, tol=1e-11, max_iters=24,
                          check_every=8, checkpoint_path=ck,
                          checkpoint_every=1)
    assert not partial_res.converged
    import os
    assert os.path.exists(ck + ".structure.h5") or os.path.exists(ck)

    # an exhausted-budget resume still returns the checkpointed estimates
    # instead of empty arrays (loop body never runs)
    stuck = lanczos(mv, 400, k=1, tol=1e-11, max_iters=24,
                    check_every=8, checkpoint_path=ck)
    assert stuck.resumed_from == 24 and stuck.eigenvalues.size == 1

    resumed = lanczos(mv, 400, k=1, tol=1e-11, max_iters=300,
                      check_every=8, checkpoint_path=ck)
    assert resumed.resumed_from == 24           # genuinely resumed
    assert resumed.converged
    assert resumed.num_iters > 24               # cumulative, not restarted
    np.testing.assert_allclose(resumed.eigenvalues[0], want, atol=1e-9)

    # a different vector space must MISS the checkpoint, not crash
    B = A[:300, :300]
    Bj = jnp.asarray(B)
    fresh = lanczos(lambda x: Bj @ x, 300, k=1, tol=1e-10, max_iters=300,
                    check_every=8, checkpoint_path=ck)
    assert fresh.resumed_from == 0 and fresh.converged
    np.testing.assert_allclose(fresh.eigenvalues[0],
                               np.linalg.eigvalsh(B)[0], atol=1e-8)


def test_lanczos_checkpoint_keyed_by_operator(tmp_path):
    """An engine-backed solve keys its checkpoint by the operator: a rerun
    against an EDITED Hamiltonian with the same lattice (same vector shape)
    must MISS the foreign Krylov state and converge to the new operator's
    ground state, not silently restore the old one (ADVICE r3)."""
    from test_operator import build_heisenberg
    from distributed_matvec_tpu.models.yaml_io import operator_from_dict
    from distributed_matvec_tpu.parallel.engine import LocalEngine

    op1 = build_heisenberg(10, 5)
    op1.basis.build()
    eng1 = LocalEngine(op1)
    ck = str(tmp_path / "lz.h5")
    r1 = lanczos(eng1.matvec, op1.basis.number_states, k=1, tol=1e-11,
                 max_iters=24, check_every=8, checkpoint_path=ck,
                 checkpoint_every=1)
    assert not r1.converged

    # the SAME operator rebuilt from scratch resumes (fingerprint is a pure
    # function of the problem, not the object identity)
    op1b = build_heisenberg(10, 5)
    op1b.basis.build()
    r3 = lanczos(LocalEngine(op1b).matvec, op1b.basis.number_states, k=1,
                 tol=1e-11, max_iters=24, check_every=8, checkpoint_path=ck)
    assert r3.resumed_from == 24

    # same basis, different couplings → same shape, different operator
    ham2 = {"terms": [{"expression": "2.5 σᶻ₀ σᶻ₁ + σˣ₀ σˣ₁ + σʸ₀ σʸ₁",
                       "sites": [[i, (i + 1) % 10] for i in range(10)]}]}
    b2 = type(op1.basis)(number_spins=10, hamming_weight=5)
    op2 = operator_from_dict(ham2, b2)
    op2.basis.build()
    eng2 = LocalEngine(op2)
    r2 = lanczos(eng2.matvec, op2.basis.number_states, k=1, tol=1e-10,
                 max_iters=300, check_every=8, checkpoint_path=ck)
    assert r2.resumed_from == 0              # foreign state refused
    want2 = np.linalg.eigvalsh(op2.to_sparse().toarray())[0]
    np.testing.assert_allclose(r2.eigenvalues[0], want2, atol=1e-8)


def test_lanczos_checkpoint_resume_restart_boundary(tmp_path):
    """Resume across a thick-restart boundary: the checkpoint written after
    a restart carries the arrowhead (lock) state and still converges to
    the truth."""
    import jax.numpy as jnp

    rng = np.random.default_rng(4)
    A = rng.standard_normal((300, 300))
    A = (A + A.T) / 2
    Aj = jnp.asarray(A)
    mv = lambda x: Aj @ x                       # noqa: E731
    ck = str(tmp_path / "lz.h5")
    partial_res = lanczos(mv, 300, k=1, tol=1e-12, max_iters=40,
                          max_basis_size=24, min_restart_size=8,
                          check_every=8, checkpoint_path=ck,
                          checkpoint_every=1)
    assert not partial_res.converged
    resumed = lanczos(mv, 300, k=1, tol=1e-12, max_iters=400,
                      max_basis_size=24, min_restart_size=8,
                      check_every=8, checkpoint_path=ck)
    assert resumed.resumed_from == 40
    assert resumed.converged and resumed.num_iters > 40
    np.testing.assert_allclose(resumed.eigenvalues[0],
                               np.linalg.eigvalsh(A)[0], atol=1e-9)


def test_lobpcg_private_api_present():
    """Multi-process LOBPCG runs jax's UNJITTED lobpcg body under its own
    jit (solve/lobpcg.py:100-107); that body is reached through the
    private ``_lobpcg_standard_callable.__wrapped__``.  Pin the dependency
    here so a jax upgrade that removes it fails CI loudly instead of
    silently degrading the advertised capability to 'use lanczos'."""
    from jax.experimental.sparse.linalg import _lobpcg_standard_callable

    assert callable(getattr(_lobpcg_standard_callable, "__wrapped__", None))
