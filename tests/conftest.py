"""Test configuration.

Multi-device testing strategy (SURVEY.md §4): the reference tests multi-locale
runs via GASNet-smp oversubscription on one box; we use XLA's virtual CPU
device pool instead — 8 virtual CPU devices, as the driver's multichip dry-run
does.  The environment may pin JAX_PLATFORMS to a hardware backend (and
sitecustomize may import jax before us), so we *force* the CPU platform via
jax.config, not setdefault.
"""

import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_ENABLE_X64"] = "true"
# Hermetic tests: the default-on artifact cache (utils/artifacts.py) would
# otherwise let engines restore structures written by earlier sessions (or
# earlier tests) from ~/.cache, flipping `structure_restored` expectations.
# Tests that exercise the layer re-enable it against a tmp_path root.
os.environ["DMT_ARTIFACT_CACHE"] = "off"
# Telemetry stays ON (default, in-memory — the instrumented hot paths run
# under test) but never inherits a sink directory from the environment;
# tests that exercise the JSONL sink point it at tmp_path themselves.
os.environ.pop("DMT_OBS_DIR", None)
os.environ.pop("DMT_OBS", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)
