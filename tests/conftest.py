"""Test configuration.

Multi-device testing strategy (SURVEY.md §4): the reference tests multi-locale
runs via GASNet-smp oversubscription on one box; we use XLA's virtual CPU
device pool instead — 8 virtual CPU devices, as the driver's multichip dry-run
does.  Must be set before the first ``import jax`` anywhere.
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["JAX_ENABLE_X64"] = "true"

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)
