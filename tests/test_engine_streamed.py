"""Streamed engine mode: precomputed plan streaming vs the fused truth.

The streamed apply must be BIT-identical to fused — same chunking, same
bucket routing (`_bucket_positions` is shared), same accumulation order —
while never re-running the orbit scan: the plan is resolved once (build or
artifact-cache restore), lives in host RAM (or the sidecar disk tier), and
streams H2D per apply.  Plus the selective-reorthogonalization satellite:
ω-gated window MGS must reproduce full-reorth eigenvalues.
"""

import os

import jax
import numpy as np
import pytest

from distributed_matvec_tpu.parallel.distributed import DistributedEngine
from distributed_matvec_tpu.utils.config import update_config

from test_operator import build_heisenberg

ATOL, RTOL = 1e-13, 1e-12


def _ndev() -> int:
    return len(jax.devices())


needs_8 = pytest.mark.skipif("_ndev() < 8", reason="needs 8 virtual devices")
needs_4 = pytest.mark.skipif("_ndev() < 4", reason="needs 4 virtual devices")


STREAM_CONFIGS = [
    # (n, hw, inv, syms, ndev) — one |G|>1 chain-style sector, one trivial
    # group, one complex-character sector (c128 on CPU)
    (12, 6, 1, [([*range(1, 12), 0], 0)], 8),
    (10, 5, None, (), 4),
    (10, 5, None, [([*range(1, 10), 0], 1)], 4),
]


@pytest.mark.parametrize("n,hw,inv,syms,ndev", STREAM_CONFIGS)
def test_streamed_bit_identical_to_fused(n, hw, inv, syms, ndev, rng):
    """Acceptance: streamed y == fused y to the BIT (and ⟨x,Hx⟩ with it)
    on a |G|>1 config and a trivial-group config."""
    if _ndev() < ndev:
        pytest.skip(f"needs {ndev} devices")
    op = build_heisenberg(n, hw, inv, syms)
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    if not op.effective_is_real:
        x = x.astype(np.complex128)
    ef = DistributedEngine(op, n_devices=ndev, mode="fused", batch_size=64)
    es = DistributedEngine(op, n_devices=ndev, mode="streamed",
                           batch_size=64)
    yf = np.asarray(ef.matvec(ef.to_hashed(x)))
    ys = np.asarray(es.matvec(es.to_hashed(x)))
    np.testing.assert_array_equal(yf, ys)
    assert complex(ef.dot(ef.to_hashed(x), jax.numpy.asarray(yf))) \
        == complex(es.dot(es.to_hashed(x), jax.numpy.asarray(ys)))
    # and both agree with the host truth
    np.testing.assert_allclose(es.from_hashed(ys), op.matvec_host(x),
                               atol=ATOL, rtol=RTOL)


@needs_8
def test_streamed_batch_bit_identical(rng):
    """A k=3 multi-RHS apply streams each plan chunk once and still equals
    the fused batch bit-for-bit (same program shape per column count)."""
    op = build_heisenberg(10, 5, None, ())
    op.basis.build()
    n = op.basis.number_states
    X = rng.random((n, 3)) - 0.5
    ef = DistributedEngine(op, n_devices=8, mode="fused")
    es = DistributedEngine(op, n_devices=8, mode="streamed")
    Yf = np.asarray(ef.matvec(ef.to_hashed(X)))
    Ys = np.asarray(es.matvec(es.to_hashed(X)))
    np.testing.assert_array_equal(Yf, Ys)
    Y = es.from_hashed(Ys)
    for k in range(3):
        np.testing.assert_allclose(Y[:, k], op.matvec_host(X[:, k]),
                                   atol=ATOL, rtol=RTOL)


@needs_4
def test_streamed_multichunk_and_single_device(rng):
    """Chunked plans (batch_size < shard rows) and the D=1 degenerate mesh
    both stream correctly."""
    op = build_heisenberg(10, 5, None, ())
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    want = op.matvec_host(x)
    for ndev, bs in ((4, 16), (1, 32)):
        es = DistributedEngine(op, n_devices=ndev, mode="streamed",
                               batch_size=bs)
        assert es._plan_nchunks_v > 1
        np.testing.assert_allclose(
            es.from_hashed(es.matvec(es.to_hashed(x))), want,
            atol=ATOL, rtol=RTOL)


@needs_4
def test_streamed_counters_preserved(rng):
    """The structural overflow/invalid counters survive the plan: a
    too-small exchange capacity fails LOUDLY at build time (fused defers
    the same failure to the first apply), and a healthy run's applies keep
    the exchange_overflow/exchange_invalid obs series visible at zero."""
    from distributed_matvec_tpu import obs

    op = build_heisenberg(10, 5, None, ())
    op.basis.build()
    update_config(remote_buffer_size=8, all_to_all_capacity_factor=1.0)
    try:
        with pytest.warns(RuntimeWarning):
            with pytest.raises(RuntimeError, match="overflowed"):
                DistributedEngine(op, n_devices=4, mode="streamed",
                                  batch_size=64)
    finally:
        update_config(remote_buffer_size=150_000,
                      all_to_all_capacity_factor=1.25)

    obs.reset_all()
    try:
        es = DistributedEngine(op, n_devices=4, mode="streamed")
        xh = es.to_hashed(rng.random(op.basis.number_states) - 0.5)
        for _ in range(2):
            es.matvec(xh)
        obs.health_event_count()            # drain deferred fetches
        counters = obs.snapshot()["counters"]
        for name in ("exchange_overflow", "exchange_invalid"):
            hits = {k: v for k, v in counters.items()
                    if k.startswith(name)}
            assert hits and all(v == 0 for v in hits.values()), (name, hits)
    finally:
        obs.reset_all()


@needs_4
def test_streamed_plan_cache_roundtrip(tmp_path, rng, monkeypatch):
    """The plan sidecar under the artifact cache: built once, restored by
    the next construction (bit-identically), still correct with the cache
    OFF (pure host-RAM, no writes), and readable from the DISK tier when
    the RAM budget excludes it."""
    op = build_heisenberg(12, 6, 1, [([*range(1, 12), 0], 0)])
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5

    monkeypatch.setenv("DMT_ARTIFACT_CACHE", "on")
    monkeypatch.setenv("DMT_ARTIFACT_DIR", str(tmp_path / "art"))
    e1 = DistributedEngine(op, n_devices=4, mode="streamed")
    assert not e1.structure_restored
    y1 = np.asarray(e1.matvec(e1.to_hashed(x)))
    e2 = DistributedEngine(op, n_devices=4, mode="streamed")
    assert e2.structure_restored
    np.testing.assert_array_equal(
        y1, np.asarray(e2.matvec(e2.to_hashed(x))))

    # disk tier: a zero RAM budget keeps the restored plan on disk
    update_config(stream_plan_ram_gb=0.0)
    try:
        e3 = DistributedEngine(op, n_devices=4, mode="streamed")
        assert e3.structure_restored
        assert e3._plan_chunks is None and e3._plan_disk
        np.testing.assert_array_equal(
            y1, np.asarray(e3.matvec(e3.to_hashed(x))))
    finally:
        update_config(stream_plan_ram_gb=8.0)

    # cache off: no restore, no disk writes, same answer
    monkeypatch.setenv("DMT_ARTIFACT_CACHE", "off")
    before = {p for p in (tmp_path / "art").rglob("*")}
    e4 = DistributedEngine(op, n_devices=4, mode="streamed")
    assert not e4.structure_restored
    assert e4._plan_chunks is not None and e4._plan_disk is None
    np.testing.assert_array_equal(
        y1, np.asarray(e4.matvec(e4.to_hashed(x))))
    assert {p for p in (tmp_path / "art").rglob("*")} == before


@needs_4
def test_streamed_plan_bytes_in_ledger(rng):
    """The host-RAM plan is a first-class memory-ledger citizen
    (device="host") and rides the engine_init memory_ledger context as
    plan_bytes — what tools/capacity.py calibrates the streamed tier
    from."""
    from distributed_matvec_tpu import obs
    from distributed_matvec_tpu.obs import memory as obs_memory

    obs.reset_all()
    try:
        es = DistributedEngine(op := build_op_cached(), n_devices=4,
                               mode="streamed")
        assert es.plan_bytes > 0
        assert obs_memory.ledger_total(device="host") >= es.plan_bytes
        led = [e for e in obs.events("memory_ledger")
               if e.get("mode") == "streamed"]
        assert led and int(led[-1]["plan_bytes"]) == es.plan_bytes
        ps = obs.events("plan_stream")
        assert ps and ps[-1]["plan_bytes"] == es.plan_bytes
        assert ps[-1]["tier"] == "ram"
    finally:
        obs.reset_all()


_op_cache = {}


def build_op_cached():
    op = _op_cache.get("op")
    if op is None:
        op = build_heisenberg(10, 5, None, ())
        op.basis.build()
        _op_cache["op"] = op
    return op


@needs_4
def test_streamed_refuses_outer_trace_solvers(rng):
    """bound_matvec (and therefore lanczos()/lobpcg) cannot trace a
    streamed engine; lanczos_block drives it eagerly and agrees with the
    plan-resident truth."""
    from distributed_matvec_tpu.solve import lanczos, lanczos_block

    op = build_op_cached()
    n = op.basis.number_states
    es = DistributedEngine(op, n_devices=4, mode="streamed")
    with pytest.raises(NotImplementedError):
        es.bound_matvec()
    with pytest.raises(ValueError, match="lanczos_block"):
        lanczos(es.matvec, v0=es.random_hashed(seed=1), k=1)

    res = lanczos_block(es.matvec, k=2, block_size=2, max_iters=80,
                        seed=3, compute_eigenvectors=True)
    ell = DistributedEngine(op, n_devices=4, mode="ell")
    ref = lanczos(ell.matvec, v0=ell.random_hashed(seed=1), k=2, tol=1e-10)
    np.testing.assert_allclose(res.eigenvalues, ref.eigenvalues,
                               atol=1e-8)
    # eigenvectors come back hashed; residual check through the engine
    v = res.eigenvectors[0]
    assert v.shape == (es.n_devices, es.shard_size)
    hv = np.asarray(es.matvec(v))
    np.testing.assert_allclose(
        hv, res.eigenvalues[0] * np.asarray(v), atol=1e-6)


def test_local_engine_streamed_pointer():
    from distributed_matvec_tpu.parallel.engine import LocalEngine

    op = build_op_cached()
    with pytest.raises(ValueError, match="DistributedEngine"):
        LocalEngine(op, mode="streamed")


# -- selective reorthogonalization (satellite) ------------------------------


def test_selective_reorth_matches_full(rng):
    """Selective (ω-gated window) Lanczos reproduces full-reorth
    eigenvalues to machine precision, including through thick restarts."""
    from distributed_matvec_tpu.parallel.engine import LocalEngine
    from distributed_matvec_tpu.solve import lanczos

    op = build_heisenberg(14, 7)
    op.basis.build()
    n = op.basis.number_states
    eng = LocalEngine(op, mode="ell")
    full = lanczos(eng.matvec, n, k=2, tol=1e-11, seed=4, reorth="full")
    sel = lanczos(eng.matvec, n, k=2, tol=1e-11, seed=4,
                  reorth="selective")
    assert sel.converged and full.converged
    np.testing.assert_allclose(sel.eigenvalues, full.eigenvalues,
                               rtol=1e-12)
    # restart path
    full_r = lanczos(eng.matvec, n, k=1, tol=1e-11, seed=4, reorth="full",
                     max_basis_size=24)
    sel_r = lanczos(eng.matvec, n, k=1, tol=1e-11, seed=4,
                    reorth="selective", max_basis_size=24)
    np.testing.assert_allclose(sel_r.eigenvalues, full_r.eigenvalues,
                               rtol=1e-12)


def test_selective_reorth_fallback_event(rng, monkeypatch):
    """When ω crosses √ε the block is redone with the full sweep and a
    solver_health event marks the trigger — forced here by dropping the
    threshold to 0."""
    from distributed_matvec_tpu import obs
    from distributed_matvec_tpu.obs import health as obs_health
    from distributed_matvec_tpu.parallel.engine import LocalEngine
    from distributed_matvec_tpu.solve import lanczos

    op = build_heisenberg(12, 6)
    op.basis.build()
    n = op.basis.number_states
    eng = LocalEngine(op, mode="ell")
    obs.reset_all()
    monkeypatch.setattr(obs_health, "OMEGA_WARN", 0.0)
    try:
        res = lanczos(eng.matvec, n, k=1, tol=1e-10, seed=6,
                      reorth="selective")
        assert res.converged
        evs = [e for e in obs.events("solver_health")
               if e.get("check") == "selective_reorth_fallback"]
        assert evs, "no fallback event despite a zero threshold"
        ref = lanczos(eng.matvec, n, k=1, tol=1e-10, seed=6, reorth="full")
        np.testing.assert_allclose(res.eigenvalues, ref.eigenvalues,
                                   rtol=1e-12)
    finally:
        obs.reset_all()


def test_selective_reorth_pair_sector(rng):
    """Pair-mode (complex momentum sector forced to (re,im)-f64) solves
    stay correct under the selective policy — the window projects J·W
    rows too."""
    from distributed_matvec_tpu.parallel.engine import LocalEngine
    from distributed_matvec_tpu.solve import lanczos

    op = build_heisenberg(10, 5, None, [([*range(1, 10), 0], 1)])
    op.basis.build()
    assert not op.effective_is_real
    update_config(complex_pair="on")
    try:
        eng = LocalEngine(op, mode="ell")
        assert eng.pair
        n = op.basis.number_states
        full = lanczos(eng.matvec, n, k=1, tol=1e-10, seed=2,
                       reorth="full")
        sel = lanczos(eng.matvec, n, k=1, tol=1e-10, seed=2,
                      reorth="selective")
        np.testing.assert_allclose(sel.eigenvalues, full.eigenvalues,
                                   rtol=1e-11)
    finally:
        update_config(complex_pair="auto")
