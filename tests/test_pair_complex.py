"""(re, im)-f64 pair engines for complex-character momentum sectors.

The TPU compiler on this platform cannot handle complex128 (see
``check_complex_backend``); complex sectors run in *pair* form instead:
vectors carry a trailing (re, im) axis, the Hermitian H on C^N acts as the
real-symmetric [[Hr, −Hi], [Hi, Hr]] on R^{2N}, and Lanczos orthogonalizes
against J·V (J = multiply by i) — which is exactly complex Lanczos in f64
arithmetic.  These tests force ``complex_pair="on"`` on CPU and compare
against the independent dense Kronecker+projector reference and native-c128
results at the reference's tolerances (TestMatrixVectorProduct.chpl:15-16).
"""

import numpy as np
import pytest

from distributed_matvec_tpu.ops.kernels import (complex_from_pair,
                                                pair_from_complex)
from distributed_matvec_tpu.parallel.distributed import DistributedEngine
from distributed_matvec_tpu.parallel.engine import LocalEngine
from distributed_matvec_tpu.solve import lanczos, lobpcg
from distributed_matvec_tpu.utils.config import update_config

from test_operator import build_heisenberg, dense_effective_matrix

ATOL, RTOL = 1e-13, 1e-12

# Momentum sectors with genuinely complex characters; n=12 sector 2 has
# orbits whose character sum cancels exactly (norm must snap to 0).
SECTORS = [
    (10, 5, [([1, 2, 3, 4, 5, 6, 7, 8, 9, 0], 1)]),
    (12, 6, [([1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 0], 2)]),
    (12, 6, [([1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 0], 3)]),
]


@pytest.fixture
def pair_mode():
    update_config(complex_pair="on")
    yield
    update_config(complex_pair="auto")


def _complex_sector_op(n, hw, syms):
    op = build_heisenberg(n, hw, None, syms)
    op.basis.build()
    assert not op.effective_is_real
    return op


@pytest.mark.parametrize("mode", ["ell", "fused"])
@pytest.mark.parametrize("n,hw,syms", SECTORS)
def test_local_pair_matches_dense(n, hw, syms, mode, pair_mode, rng):
    op = _complex_sector_op(n, hw, syms)
    h = dense_effective_matrix(op)
    N = op.basis.number_states
    x = (rng.random(N) - 0.5) + 1j * (rng.random(N) - 0.5)
    X = (rng.random((N, 3)) - 0.5) + 1j * (rng.random((N, 3)) - 0.5)
    eng = LocalEngine(op, batch_size=61, mode=mode)
    assert eng.pair
    # complex in → complex out (host conversion round-trip)
    y = np.asarray(eng.matvec(x))
    np.testing.assert_allclose(y, h @ x, atol=ATOL, rtol=RTOL)
    # pair in → pair out (the solver-facing form)
    yp = np.asarray(eng.matvec(pair_from_complex(x)))
    np.testing.assert_allclose(complex_from_pair(yp), h @ x,
                               atol=ATOL, rtol=RTOL)
    # rank-2 batch
    Y = np.asarray(eng.matvec(X))
    np.testing.assert_allclose(Y, h @ X, atol=ATOL, rtol=RTOL)


@pytest.mark.parametrize("mode", ["ell", "fused"])
@pytest.mark.parametrize("n_devices", [2, 4, 8])
def test_distributed_pair_matches_dense(n_devices, mode, pair_mode, rng):
    op = _complex_sector_op(12, 6, SECTORS[1][2])
    h = dense_effective_matrix(op)
    N = op.basis.number_states
    x = (rng.random(N) - 0.5) + 1j * (rng.random(N) - 0.5)
    X = (rng.random((N, 3)) - 0.5) + 1j * (rng.random((N, 3)) - 0.5)
    eng = DistributedEngine(op, n_devices=n_devices, mode=mode)
    assert eng.pair
    np.testing.assert_allclose(eng.matvec_global(x), h @ x,
                               atol=ATOL, rtol=RTOL)
    Yh = eng.matvec(eng.to_hashed(X))
    np.testing.assert_allclose(complex_from_pair(eng.from_hashed(Yh)),
                               h @ X, atol=ATOL, rtol=RTOL)


def test_pair_matches_native_c128(pair_mode, rng):
    """Pair and native-c128 engines agree to machine precision."""
    op = _complex_sector_op(12, 6, SECTORS[1][2])
    N = op.basis.number_states
    x = (rng.random(N) - 0.5) + 1j * (rng.random(N) - 0.5)
    y_pair = np.asarray(LocalEngine(op, mode="ell").matvec(x))
    update_config(complex_pair="off")
    y_native = np.asarray(LocalEngine(op, mode="ell").matvec(x))
    np.testing.assert_allclose(y_pair, y_native, atol=1e-15, rtol=1e-14)


def test_pair_lanczos_no_phantom_degeneracy(pair_mode):
    """J-aware Lanczos returns each eigenvalue ONCE (complex Lanczos in f64),
    not the doubled spectrum of the naive realification."""
    op = _complex_sector_op(12, 6, SECTORS[1][2])
    h = dense_effective_matrix(op)
    w = np.linalg.eigvalsh(h)
    eng = LocalEngine(op, mode="ell")
    res = lanczos(eng.matvec, n=op.basis.number_states, k=3, tol=1e-10,
                  compute_eigenvectors=True)
    assert res.converged
    np.testing.assert_allclose(res.eigenvalues, w[:3], atol=1e-9)
    # eigenvector solves the COMPLEX eigenproblem
    v = np.asarray(res.eigenvectors[0])
    vc = complex_from_pair(v)
    assert np.linalg.norm(h @ vc - res.eigenvalues[0] * vc) < 1e-8


def test_pair_lanczos_distributed(pair_mode):
    op = _complex_sector_op(12, 6, SECTORS[1][2])
    w = np.linalg.eigvalsh(dense_effective_matrix(op))
    eng = DistributedEngine(op, n_devices=4, mode="ell")
    res = lanczos(eng.matvec, v0=eng.random_hashed(seed=7), k=2, tol=1e-10)
    assert res.converged
    np.testing.assert_allclose(res.eigenvalues, w[:2], atol=1e-9)


def test_pair_lobpcg(pair_mode):
    """Blocked LOBPCG on the realified operator: J-copies filtered, complex
    eigenvectors returned, eigenvalues match dense."""
    op = _complex_sector_op(12, 6, SECTORS[1][2])
    h = dense_effective_matrix(op)
    w = np.linalg.eigvalsh(h)
    eng = LocalEngine(op, mode="ell")
    evals, evecs, _ = lobpcg(eng.matvec, op.basis.number_states, k=3,
                             tol=1e-8, max_iters=300)
    np.testing.assert_allclose(evals, w[:3], atol=1e-7)
    assert np.iscomplexobj(evecs)
    for i in range(3):
        r = np.linalg.norm(h @ evecs[:, i] - evals[i] * evecs[:, i])
        assert r < 1e-5


def test_pair_lobpcg_degenerate_spectrum(rng):
    """The J-copy filter must NOT drop genuinely degenerate eigenvalues:
    complex Gram-Schmidt keeps an independent degenerate partner while
    discarding the realification copies."""
    n = 40
    lam = np.concatenate([[-2.0, -1.0, -1.0], np.linspace(0.5, 3.0, n - 3)])
    A = rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n))
    Q, _ = np.linalg.qr(A)
    H = (Q * lam) @ Q.conj().T
    H = (H + H.conj().T) / 2

    import jax.numpy as jnp
    Hr = jnp.asarray(H.real)
    Hi = jnp.asarray(H.imag)

    def mv(X):
        # pair batch [n, m, 2], jit-traceable (lobpcg_standard jits it)
        Xr, Xi = X[..., 0], X[..., 1]
        Yr = jnp.tensordot(Hr, Xr, axes=[[1], [0]]) \
            - jnp.tensordot(Hi, Xi, axes=[[1], [0]])
        Yi = jnp.tensordot(Hr, Xi, axes=[[1], [0]]) \
            + jnp.tensordot(Hi, Xr, axes=[[1], [0]])
        return jnp.stack([Yr, Yi], axis=-1)

    evals, evecs, _ = lobpcg(mv, n, k=3, tol=1e-9, max_iters=500, pair=True)
    np.testing.assert_allclose(evals, [-2.0, -1.0, -1.0], atol=1e-6)
    # returned complex vectors are orthonormal even inside the cluster
    G = evecs.conj().T @ evecs
    np.testing.assert_allclose(G, np.eye(3), atol=1e-6)


def test_pair_dot_is_complex(pair_mode, rng):
    """DistributedEngine.dot returns the full complex overlap in pair mode."""
    op = _complex_sector_op(10, 5, SECTORS[0][2])
    eng = DistributedEngine(op, n_devices=2, mode="ell")
    N = op.basis.number_states
    a = (rng.random(N) - 0.5) + 1j * (rng.random(N) - 0.5)
    b = (rng.random(N) - 0.5) + 1j * (rng.random(N) - 0.5)
    got = eng.dot(eng.to_hashed(a), eng.to_hashed(b))
    np.testing.assert_allclose(got, np.vdot(a, b), atol=1e-13)


def test_pair_rejects_bad_shapes(pair_mode):
    op = _complex_sector_op(10, 5, SECTORS[0][2])
    eng = LocalEngine(op, mode="ell")
    with pytest.raises(ValueError, match="pair-mode"):
        eng.matvec(np.ones(op.basis.number_states))   # real [N]: ambiguous
    deng = DistributedEngine(op, n_devices=2, mode="ell")
    with pytest.raises(ValueError, match="pair-mode"):
        deng.matvec(np.ones((2, deng.shard_size)))


def test_diagonalize_cli_pair(tmp_path, pair_mode):
    """The driver CLI solves a complex momentum sector end-to-end in pair
    mode and saves complex eigenvectors."""
    import h5py
    import yaml

    cfg = {
        "basis": {"number_spins": 10, "hamming_weight": 5,
                  "symmetries": [
                      {"permutation": [1, 2, 3, 4, 5, 6, 7, 8, 9, 0],
                       "sector": 1}]},
        "hamiltonian": {"name": "H", "terms": [
            {"expression": "σˣ₀ σˣ₁", "sites": [[i, (i + 1) % 10]
                                                for i in range(10)]},
            {"expression": "σʸ₀ σʸ₁", "sites": [[i, (i + 1) % 10]
                                                for i in range(10)]},
            {"expression": "σᶻ₀ σᶻ₁", "sites": [[i, (i + 1) % 10]
                                                for i in range(10)]},
        ]},
    }
    yml = tmp_path / "momentum.yaml"
    yml.write_text(yaml.dump(cfg))
    out = tmp_path / "momentum.h5"

    import sys
    sys.path.insert(0, "apps")
    import diagonalize
    rc = diagonalize.main([str(yml), "-o", str(out), "-k", "2",
                           "--tol", "1e-10"])
    assert rc == 0

    op = _complex_sector_op(10, 5, SECTORS[0][2])
    w = np.linalg.eigvalsh(dense_effective_matrix(op))
    with h5py.File(out, "r") as f:
        evals = f["hamiltonian/eigenvalues"][...]
        evecs = f["hamiltonian/eigenvectors"][...]
    np.testing.assert_allclose(evals, w[:2], atol=1e-9)
    assert np.iscomplexobj(evecs)


def test_diagonalize_cli_observables_complex_psi(tmp_path, pair_mode):
    """A REAL observable on a COMPLEX momentum-sector ground state: the
    driver must compute psi^dagger O psi (via the [Re, Im] two-column
    batch), not (Re psi)^T O (Re psi) — the silent-truncation regression."""
    import h5py
    import yaml

    cfg = {
        "basis": {"number_spins": 10, "hamming_weight": 5,
                  "symmetries": [
                      {"permutation": [1, 2, 3, 4, 5, 6, 7, 8, 9, 0],
                       "sector": 1}]},
        "hamiltonian": {"name": "H", "terms": [
            {"expression": "σˣ₀ σˣ₁ + σʸ₀ σʸ₁ + σᶻ₀ σᶻ₁",
             "sites": [[i, (i + 1) % 10] for i in range(10)]},
        ]},
        "observables": [
            {"name": "nn_corr",
             "terms": [{"expression": "σˣ₀ σˣ₁ + σʸ₀ σʸ₁ + σᶻ₀ σᶻ₁",
                        "sites": [[0, 1]]}]},
        ],
    }
    yml = tmp_path / "momentum_obs.yaml"
    yml.write_text(yaml.dump(cfg))
    out = tmp_path / "momentum_obs.h5"

    import sys
    sys.path.insert(0, "apps")
    import diagonalize
    rc = diagonalize.main([str(yml), "-o", str(out), "-k", "1",
                           "--tol", "1e-10", "--observables"])
    assert rc == 0

    from distributed_matvec_tpu.models.yaml_io import load_config_from_yaml
    c = load_config_from_yaml(str(yml), observables=True)
    c.basis.build()
    with h5py.File(out, "r") as f:
        psi = f["hamiltonian/eigenvectors"][0]
        got = float(f["observables/nn_corr"][()])
    assert np.iscomplexobj(psi) and np.abs(psi.imag).max() > 1e-3
    want = float(np.vdot(psi, c.observables[0].matvec_host(psi)).real)
    assert abs(got - want) < 1e-10, (got, want)
    # the truncated value would differ measurably
    wrong = float(psi.real @ c.observables[0].matvec_host(psi.real).real)
    assert abs(got - wrong) > 1e-6, "test is vacuous: Re-only equals full"
