"""Phase-level performance attribution: apply_phases events, the roofline
cost model, rate-calibration sidecars, and the bench-trend gate.

The exactness contract (ISSUE 7 satellite): per-phase bytes/gathers/flops
sum to the event's whole-apply totals EXACTLY, and cross-check against
independent engine quantities (``plan_bytes``, ``_exchange_nbytes``); the
roofline model's attributed phase walls sum to the measured apply wall
exactly by construction; the recorded BENCH_STREAM_r05.json streamed run
reconciles against the model to a documented tolerance.
"""

import importlib.util
import json
import os
import sys

import jax
import numpy as np
import pytest

from distributed_matvec_tpu import obs
from distributed_matvec_tpu.obs import phases as obs_phases
from distributed_matvec_tpu.obs import roofline as R
from distributed_matvec_tpu.parallel.distributed import DistributedEngine
from distributed_matvec_tpu.parallel.engine import LocalEngine

from test_operator import build_heisenberg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def clean_obs():
    obs.reset_all()
    yield
    obs.reset_all()


def _ndev() -> int:
    return len(jax.devices())


def _phase_event(engine):
    evs = [e for e in obs.events("apply_phases")
           if e.get("engine") == engine]
    assert evs, f"no apply_phases event from {engine}"
    return evs[-1]


def _assert_totals_exact(ev):
    """The exactness invariant: per-phase counts sum to the totals."""
    for field, total in (("bytes", ev["bytes_total"]),
                         ("gathers", ev["gathers_total"]),
                         ("flops", ev["flops_total"])):
        assert sum(p[field] for p in ev["phases"].values()) == total


# ---------------------------------------------------------------------------
# engine instrumentation


def test_local_ell_phases_exact(clean_obs, rng):
    op = build_heisenberg(10, 5, None, ())
    eng = LocalEngine(op, mode="ell")
    x = rng.random(op.basis.number_states) - 0.5
    eng.matvec(x)
    # satellite: LocalEngine now emits matvec_apply (engine="local")
    ma = [e for e in obs.events("matvec_apply")
          if e.get("engine") == "local"]
    assert ma and ma[-1]["bytes"] == 0 and ma[-1]["wall_ms"] > 0
    ev = _phase_event("local")
    assert ev["mode"] == "ell" and ev["columns"] == 1
    _assert_totals_exact(ev)
    # structural gather count: one gather per table slot (main + tail)
    g_main = eng._ell_T0 * eng.n_padded
    g_tail = int(eng._ell_tail[1].shape[0] * eng._ell_tail[1].shape[1]) \
        if eng._ell_tail is not None else 0
    assert ev["phases"]["compute"]["gathers"] == g_main + g_tail
    assert ev["phases"]["exchange"]["bytes"] == 0
    assert ev["phases"]["plan_h2d"]["bytes"] == 0


def test_local_batch_columns_scale_bytes(clean_obs, rng):
    """A k-column batch gathers k× the vector bytes but the same slots."""
    op = build_heisenberg(10, 5, None, ())
    eng = LocalEngine(op, mode="ell")
    n = op.basis.number_states
    eng.matvec(rng.random(n) - 0.5)
    ev1 = _phase_event("local")
    eng.matvec(rng.random((n, 3)) - 0.5)
    ev3 = _phase_event("local")
    assert ev3["columns"] == 3
    assert ev3["phases"]["compute"]["gathers"] \
        == ev1["phases"]["compute"]["gathers"]
    assert ev3["phases"]["compute"]["flops"] \
        == 3 * ev1["phases"]["compute"]["flops"]


def test_local_fused_phases(clean_obs, rng):
    op = build_heisenberg(10, 5, None, ())
    eng = LocalEngine(op, mode="fused", batch_size=64)
    eng.matvec(rng.random(op.basis.number_states) - 0.5)
    ev = _phase_event("local")
    assert ev["mode"] == "fused" and ev["chunks"] == eng.num_chunks
    _assert_totals_exact(ev)
    # the orbit scan rides the flops term: strictly more work per entry
    # than the pure multiply-add of ell mode
    g = ev["phases"]["compute"]["gathers"]
    assert g == eng.n_padded * eng.num_terms
    assert ev["phases"]["compute"]["flops"] > 2 * g


def test_distributed_streamed_phase_cross_checks(clean_obs, rng):
    """plan_h2d bytes == the engine's own plan_bytes, exchange bytes ==
    _exchange_nbytes, the chunk timeline covers every streamed chunk, and
    the measured plan_h2d wall is the summed chunk stalls."""
    if _ndev() < 4:
        pytest.skip("needs 4 virtual devices")
    op = build_heisenberg(10, 5, None, ())
    op.basis.build()
    eng = DistributedEngine(op, n_devices=4, mode="streamed",
                            batch_size=32)
    xh = eng.to_hashed(rng.random(op.basis.number_states) - 0.5)
    eng.matvec(xh)
    ev = _phase_event("distributed")
    assert ev["mode"] == "streamed"
    _assert_totals_exact(ev)
    assert ev["phases"]["plan_h2d"]["bytes"] == int(eng.plan_bytes)
    assert ev["phases"]["exchange"]["bytes"] == eng._exchange_nbytes(xh)
    assert ev["chunks"] == eng._plan_nchunks_v
    tl = ev["chunk_timeline"]
    assert [c["chunk"] for c in tl] == list(range(eng._plan_nchunks_v))
    stalls = sum(c.get("stall_ms", 0.0) for c in tl)
    assert ev["phases"]["plan_h2d"]["wall_ms"] == pytest.approx(
        stalls, abs=1e-3)
    # the timeline is drained per apply, not accumulated across applies
    eng.matvec(xh)
    ev2 = _phase_event("distributed")
    assert len(ev2["chunk_timeline"]) == eng._plan_nchunks_v


def test_distributed_ell_phase_exchange_bytes(clean_obs, rng):
    if _ndev() < 4:
        pytest.skip("needs 4 virtual devices")
    op = build_heisenberg(10, 5, None, ())
    op.basis.build()
    eng = DistributedEngine(op, n_devices=4, mode="ell")
    xh = eng.to_hashed(rng.random(op.basis.number_states) - 0.5)
    eng.matvec(xh)
    ev = _phase_event("distributed")
    _assert_totals_exact(ev)
    assert ev["phases"]["exchange"]["bytes"] == eng._exchange_nbytes(xh)
    assert ev["phases"]["exchange"]["bytes"] \
        == [e for e in obs.events("matvec_apply")
            if e.get("engine") == "distributed"][-1]["bytes"]
    assert ev["phases"]["plan_h2d"]["bytes"] == 0


def test_phases_disabled_no_events_bit_identical(clean_obs, rng,
                                                 monkeypatch):
    """DMT_PHASES=off: no apply_phases events, results bit-identical,
    matvec_apply still flows (phases off is narrower than obs off)."""
    op = build_heisenberg(10, 5, None, ())
    eng = LocalEngine(op, mode="ell")
    x = rng.random(op.basis.number_states) - 0.5
    y_on = np.asarray(eng.matvec(x))
    assert obs.events("apply_phases")
    obs.reset_all()
    monkeypatch.setenv("DMT_PHASES", "off")
    assert not obs.phases_enabled()
    y_off = np.asarray(eng.matvec(x))
    np.testing.assert_array_equal(y_on, y_off)
    assert obs.events("apply_phases") == []
    assert obs.events("matvec_apply")


def test_phases_imply_obs(monkeypatch):
    monkeypatch.setenv("DMT_OBS", "off")
    assert not obs.phases_enabled()


# ---------------------------------------------------------------------------
# roofline model


def _synthetic_streamed_event(wall_ms, plan_bytes, stall_ms, nchunks,
                              exch_bytes=1 << 20, seg=1 << 16):
    return {"kind": "apply_phases", "engine": "distributed",
            "mode": "streamed", "apply": 1, "wall_ms": wall_ms,
            "chunks": nchunks, "columns": 1,
            "phases": {
                "plan_h2d": {"bytes": plan_bytes, "gathers": 0, "flops": 0,
                             "wall_ms": stall_ms},
                "compute": {"bytes": 1 << 20, "gathers": 0,
                            "flops": 1 << 22},
                "exchange": {"bytes": exch_bytes, "gathers": 0, "flops": 0},
                "accumulate": {"bytes": seg * 8, "gathers": seg,
                               "flops": seg}},
            "bytes_total": 0, "gathers_total": 0, "flops_total": 0}


def test_attribution_sums_to_wall_exactly():
    cal = R.default_calibration("cpu")
    phases = {"plan_h2d": {"bytes": 10 << 20, "wall_ms": 1.5},
              "compute": {"gathers": 5_000_000, "flops": 10_000_000},
              "exchange": {"bytes": 4 << 20},
              "accumulate": {"gathers": 250_000}}
    att = R.attribute_phases(phases, 300.0, cal)
    total = sum(a["wall_ms"] for a in att.values())
    assert total == pytest.approx(300.0, rel=1e-12)
    assert att["plan_h2d"]["measured"] and att["plan_h2d"]["wall_ms"] == 1.5
    for p, a in att.items():
        if a["wall_ms"] > 0 and a["bound_ms"] > 0:
            assert 0 < a["achieved_fraction"] <= 1.0 + 1e-9


def test_roofline_report_binding_and_pipeline():
    evs = [_synthetic_streamed_event(100.0, 50 << 20, 2.0, 8)
           for _ in range(4)]
    rep = R.roofline_report(evs, R.default_calibration("cpu"))
    grp = rep["groups"]["distributed/streamed"]
    assert grp["binding_phase"] in obs_phases.PHASES
    assert grp["binding_resource"] \
        == obs_phases.PHASE_RESOURCE[grp["binding_phase"]]
    assert R.reconcile_error(rep) < 1e-3
    # 8 chunks with nonzero compute AND exchange → a real overlap window
    assert grp["pipelined_speedup_estimate"] > 1.0


def test_roofline_first_apply_dropped():
    """The compile-bearing first apply must not pollute the steady mean."""
    evs = [_synthetic_streamed_event(1000.0, 1 << 20, 0.1, 2),
           _synthetic_streamed_event(10.0, 1 << 20, 0.1, 2),
           _synthetic_streamed_event(10.0, 1 << 20, 0.1, 2)]
    rep = R.roofline_report(evs, R.default_calibration("cpu"))
    assert rep["groups"]["distributed/streamed"]["wall_ms"] \
        == pytest.approx(10.0)


def test_roofline_reconciles_recorded_bench_stream_r05():
    """Satellite: model vs the RECORDED chain_24_symm streamed artifact.

    Documented tolerance: (a) attributed phase walls reconcile with the
    recorded steady apply wall to <10% (exact by construction here); (b)
    the calibrated CPU-rig bound total never exceeds the measured wall —
    a run cannot beat the roofline (the recorded 75.1 ms apply moves
    11.8 MB of plan + exchange in well under its wall at CPU rates); (c)
    the recorded near-zero plan-stream stall is consistent with the
    model's fully-overlapped H2D reading (measured plan_h2d wall ≪ its
    un-overlapped bound would be at several GB/s)."""
    with open(os.path.join(REPO, "BENCH_STREAM_r05.json")) as f:
        rec = json.load(f)["stream_chain_24_symm"]
    wall = float(rec["streamed_steady_apply_ms"])
    ev = _synthetic_streamed_event(
        wall, int(rec["plan_bytes"]), float(rec["plan_stream_stall_ms"]),
        nchunks=1)
    rep = R.roofline_report([ev, ev], R.default_calibration("cpu"))
    grp = rep["groups"]["distributed/streamed"]
    phase_sum = sum(p["wall_ms"] for p in grp["phases"].values())
    assert abs(phase_sum - wall) / wall < 0.10          # (a)
    bound_total = sum(p["bound_ms"] for p in grp["phases"].values())
    assert bound_total <= wall                          # (b)
    h2d = grp["phases"]["plan_h2d"]
    assert h2d["wall_ms"] < 1.0 and h2d["bound_ms"] > h2d["wall_ms"]  # (c)
    assert grp["binding_resource"]


# ---------------------------------------------------------------------------
# calibration sidecar


def test_calibration_roundtrip_content_addressed(tmp_path, monkeypatch):
    monkeypatch.setenv("DMT_ARTIFACT_CACHE", "on")
    monkeypatch.setenv("DMT_ARTIFACT_DIR", str(tmp_path))
    p1 = R.calibration_path()
    assert p1 and str(tmp_path) in p1 and "calibration" in p1
    assert R.calibration_path() == p1          # stable (content-addressed)
    assert R.load_calibration() is None
    cal = dict(R.default_calibration("cpu"), gather_rows_per_s=123e6,
               device_kind=jax.devices()[0].device_kind)
    saved = R.save_calibration(cal)
    assert saved == p1 and os.path.exists(saved)
    got = R.load_calibration()
    assert got["gather_rows_per_s"] == 123e6
    assert got["source"] == "measured"
    # resolve: measured sidecar wins over defaults
    assert R.resolve_calibration()["gather_rows_per_s"] == 123e6
    # explicit path wins over everything
    other = tmp_path / "cal.json"
    other.write_text(json.dumps(dict(cal, gather_rows_per_s=9e6)))
    assert R.resolve_calibration(str(other))["gather_rows_per_s"] == 9e6
    # an explicit path that is missing raises — never a silent re-price
    with pytest.raises(FileNotFoundError):
        R.resolve_calibration(str(tmp_path / "nope.json"))


def test_calibration_disabled_artifact_layer(monkeypatch):
    monkeypatch.setenv("DMT_ARTIFACT_CACHE", "off")
    assert R.calibration_path() is None
    assert R.save_calibration(R.default_calibration("cpu")) is None
    # the model still works from defaults
    assert R.resolve_calibration()["source"] == "default"


def test_capacity_consumes_calibration():
    capacity = _load_tool("capacity")
    rates = dict(R.default_calibration("cpu"))
    rep = capacity.plan(1_000_000, 36, 24, False, 16.0, 4, 3, 1,
                        rates=rates)
    m = rep["modes"]["ell"]
    assert m["est_apply_ms"] == pytest.approx(
        (1_000_000 / 4) * 24 / rates["gather_rows_per_s"] * 1e3, rel=1e-6)
    assert "est_apply_ms" in rep["modes"]["streamed"]
    assert rep["rates"]["source"] == "default"
    # without rates the column is absent (pre-calibration behavior intact)
    rep0 = capacity.plan(1_000_000, 36, 24, False, 16.0, 4, 3, 1)
    assert "est_apply_ms" not in rep0["modes"]["ell"]


# ---------------------------------------------------------------------------
# bench trend


def test_bench_trend_append_load_gate(tmp_path):
    bt = _load_tool("bench_trend")
    progress = tmp_path / "PROGRESS.jsonl"
    # driver-style foreign lines must be ignored, never corrupted
    progress.write_text(
        '{"ts": 1, "wall_s": 2.0, "round": 1, "commits": 1}\n'
        "not json at all\n")
    detail = {"chain_16": {"config": "heisenberg_chain_16",
                           "n_states": 12870, "device_ms": 1.0,
                           "lanczos_iters_per_s": 100.0,
                           "phase_compute_bytes": 1000,
                           "irrelevant_metric_xyz": 5.0}}
    rec = bt.compact_record(detail, "smoke", "cpu", ts=10.0)
    assert "irrelevant_metric_xyz" not in rec["configs"]["heisenberg_chain_16"]
    assert rec["configs"]["heisenberg_chain_16"]["phase_compute_bytes"] == 1000
    assert bt.append_record(str(progress), rec)
    recs = bt.load_records(str(progress))
    assert len(recs) == 1                      # foreign lines skipped
    # identical second record → gate passes
    bt.append_record(str(progress),
                     bt.compact_record(detail, "smoke", "cpu", ts=20.0))
    rows, regressions, newest = bt.gate(bt.load_records(str(progress)), 0.3)
    assert newest and rows and not regressions
    # regression: device_ms 2x up AND iters/s 2x down both fire
    bad = {"chain_16": dict(detail["chain_16"], device_ms=2.0,
                            lanczos_iters_per_s=50.0)}
    bt.append_record(str(progress),
                     bt.compact_record(bad, "smoke", "cpu", ts=30.0))
    rows, regressions, _ = bt.gate(bt.load_records(str(progress)), 0.3)
    assert {(c, m) for c, m, *_ in regressions} == {
        ("heisenberg_chain_16", "device_ms"),
        ("heisenberg_chain_16", "lanczos_iters_per_s")}
    # a config whose basis size changed is a new experiment, not a trend
    resized = {"chain_16": dict(bad["chain_16"], n_states=999,
                                device_ms=50.0)}
    bt.append_record(str(progress),
                     bt.compact_record(resized, "smoke", "cpu", ts=40.0))
    rows, regressions, _ = bt.gate(bt.load_records(str(progress)), 0.3)
    assert not regressions
    # different mode never compares against smoke history
    full = bt.compact_record(bad, "full", "cpu", ts=50.0)
    bt.append_record(str(progress), full)
    rows, regressions, newest = bt.gate(bt.load_records(str(progress)), 0.3)
    assert newest["mode"] == "full" and not rows


def test_bench_trend_single_record_passes(tmp_path):
    bt = _load_tool("bench_trend")
    progress = tmp_path / "P.jsonl"
    bt.append_record(str(progress), bt.compact_record(
        {"c": {"config": "c", "device_ms": 1.0}}, "smoke", "cpu"))
    rows, regressions, newest = bt.gate(bt.load_records(str(progress)), 0.3)
    assert newest is None and not rows and not regressions


# ---------------------------------------------------------------------------
# obs_report surfaces


def _load_obs_report():
    return _load_tool("obs_report")


def test_obs_report_phases_summary_and_diff_gate(tmp_path):
    orep = _load_obs_report()
    evs = [_synthetic_streamed_event(50.0, 1 << 20, 0.5, 4)
           for _ in range(3)]
    ph = orep.phases_summary(evs)
    grp = ph["distributed/streamed"]
    assert grp["applies"] == 3 and grp["chunks"] == 4
    assert grp["phases"]["plan_h2d"]["measured_wall_ms"] == 0.5
    orep.print_phases_section(ph)              # renders without error

    # diff --phases: phase bytes growth gates (prefix match), flat passes
    base = {"cfg": {"device_ms": 1.0, "phase_plan_h2d_bytes": 100.0,
                    "phase_compute_gathers": 1000.0}}
    new = {"cfg": {"device_ms": 1.0, "phase_plan_h2d_bytes": 200.0,
                   "phase_compute_gathers": 1000.0}}
    rows, regressions, common = orep.diff_runs(
        base, new, 0.2, gate_metrics=list(orep._PHASE_GATE))
    assert common and regressions
    assert regressions[0][1] == "phase_plan_h2d_bytes"
    rows, regressions, _ = orep.diff_runs(
        base, dict(base), 0.2, gate_metrics=list(orep._PHASE_GATE))
    assert not regressions


def test_obs_report_roofline_subcommand(tmp_path, capsys):
    orep = _load_obs_report()
    run = tmp_path / "events.jsonl"
    with open(run, "w") as f:
        for ev in [_synthetic_streamed_event(80.0, 8 << 20, 1.0, 4)] * 3:
            f.write(json.dumps(ev) + "\n")
    rc = orep.main(["roofline", str(run)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "binding resource" in out and "pipelined-apply estimate" in out
    rc = orep.main(["roofline", str(run), "--json"])
    out = capsys.readouterr().out
    rep = json.loads(out)
    assert "distributed/streamed" in rep["groups"]
    # no apply_phases events → explicit exit 2, not a crash
    empty = tmp_path / "empty.jsonl"
    empty.write_text(json.dumps({"kind": "engine_init"}) + "\n")
    assert orep.main(["roofline", str(empty)]) == 2


def test_obs_report_report_phases_flag(tmp_path, capsys):
    orep = _load_obs_report()
    run = tmp_path / "run"
    (run / "rank_0").mkdir(parents=True)
    with open(run / "rank_0" / "events.jsonl", "w") as f:
        ev = dict(_synthetic_streamed_event(10.0, 1 << 10, 0.1, 2),
                  seq=0, ts=1.0, proc=0, rank=0)
        f.write(json.dumps(ev) + "\n")
    rc = orep.main(["report", str(run), "--phases"])
    out = capsys.readouterr().out
    assert rc == 0 and "phase attribution" in out
