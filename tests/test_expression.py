"""Expression parser + nonbranching-term compiler vs the independent dense path."""

import numpy as np
import pytest
import scipy.sparse as sp

from distributed_matvec_tpu.models.expression import (
    NonbranchingTerm,
    parse_expression,
    simplify_terms,
)

import dense_ref


def term_matrix(n_sites: int, t: NonbranchingTerm) -> np.ndarray:
    """Materialize one nonbranching term by brute force over all states."""
    dim = 1 << n_sites
    m = np.zeros((dim, dim), dtype=np.complex128)
    for alpha in range(dim):
        v, beta = t.apply_int(alpha)
        m[beta, alpha] += v
    return m


def expr_to_matrix_via_terms(n_sites, text, sites_rows):
    expr = parse_expression(text)
    dim = 1 << n_sites
    total = np.zeros((dim, dim), dtype=np.complex128)
    for row in sites_rows:
        for t in expr.instantiate(row):
            total += term_matrix(n_sites, t)
    return total


CASES = [
    ("σˣ₀ σˣ₁", [[0, 1]]),
    ("σʸ₀ σʸ₁", [[0, 1]]),
    ("σᶻ₀ σᶻ₁", [[0, 1]]),
    ("0.8 × σˣ₀ σˣ₁", [[1, 2]]),
    ("σ⁺₀ σ⁻₁", [[0, 2]]),
    ("σ⁺₀ σ⁻₁ + σ⁻₀ σ⁺₁", [[0, 1]]),
    ("Sˣ₀ Sˣ₁", [[0, 1]]),
    ("2 × σᶻ₀", [[0], [1], [2]]),
    ("σˣ₀ σʸ₁ σᶻ₂", [[0, 1, 2]]),
    ("σʸ₀", [[1]]),
    ("σˣ₀ σˣ₁ + σʸ₀ σʸ₁ + σᶻ₀ σᶻ₁", [[0, 1], [1, 2], [2, 0]]),
    ("1.5 × σ⁺₀", [[2]]),
    ("σᶻ₀ σᶻ₁ - σˣ₀", [[0, 1]]),
]


@pytest.mark.parametrize("text,rows", CASES)
def test_expression_matches_dense_kron(text, rows):
    n = 3
    expr = parse_expression(text)
    ours = expr_to_matrix_via_terms(n, text, rows)
    dense = dense_ref.expression_matrix(n, expr, rows).toarray()
    np.testing.assert_allclose(ours, dense, atol=1e-14)


def test_same_site_products_multiply():
    # σ⁺σ⁻ on the same site = n (projector onto bit 1)
    n = 2
    ours = expr_to_matrix_via_terms(n, "σ⁺₀ σ⁻₀", [[0]])
    expected = np.diag([0, 1, 0, 1]).astype(np.complex128)
    np.testing.assert_allclose(ours, expected, atol=1e-14)


def test_pauli_algebra_identities():
    # σˣσʸ = iσᶻ on one site
    n = 1
    xy = expr_to_matrix_via_terms(n, "σˣ₀ σʸ₀", [[0]])
    z = expr_to_matrix_via_terms(n, "σᶻ₀", [[0]])
    np.testing.assert_allclose(xy, 1j * z, atol=1e-14)


def test_heisenberg_bond_grouping():
    """σˣσˣ+σʸσʸ share one flip mask: groups = 1 off-diag (2 legs) + 1 diag."""
    expr = parse_expression("σˣ₀ σˣ₁ + σʸ₀ σʸ₁ + σᶻ₀ σᶻ₁")
    terms = expr.instantiate([0, 1])
    off = [t for t in terms if not t.is_diagonal]
    diag = [t for t in terms if t.is_diagonal]
    assert len(diag) == 1
    xs = {t.x for t in off}
    assert xs == {0b11}
    assert len(off) == 2  # sign-mask-free and sign-masked legs


def test_compose_is_operator_product(rng):
    dim = 1 << 3
    for _ in range(50):
        t1 = NonbranchingTerm(
            complex(rng.normal(), rng.normal()),
            x=int(rng.integers(8)),
            s=int(rng.integers(8)),
            m=(m1 := int(rng.integers(8))),
            r=int(rng.integers(8)) & m1,
        )
        t2 = NonbranchingTerm(
            complex(rng.normal(), rng.normal()),
            x=int(rng.integers(8)),
            s=int(rng.integers(8)),
            m=(m2 := int(rng.integers(8))),
            r=int(rng.integers(8)) & m2,
        )
        prod = t1.compose(t2)
        expected = term_matrix(3, t1) @ term_matrix(3, t2)
        got = term_matrix(3, prod) if prod is not None else np.zeros((dim, dim))
        np.testing.assert_allclose(got, expected, atol=1e-13)


def test_dagger(rng):
    for _ in range(30):
        t = NonbranchingTerm(
            complex(rng.normal(), rng.normal()),
            x=int(rng.integers(8)),
            s=int(rng.integers(8)),
            m=(m := int(rng.integers(8))),
            r=int(rng.integers(8)) & m,
        )
        np.testing.assert_allclose(
            term_matrix(3, t.dagger()), term_matrix(3, t).conj().T, atol=1e-13
        )


def test_simplify_groups_and_drops_zeros():
    a = NonbranchingTerm(1.0, x=1)
    b = NonbranchingTerm(2.0, x=1)
    c = NonbranchingTerm(-3.0, x=1)
    assert simplify_terms([a, b, c]) == []
    out = simplify_terms([a, b])
    assert len(out) == 1 and out[0].v == 3.0


def test_parenthesised_products_preserve_operator_order():
    """Regression: (σˣ₀) σʸ₀ must equal σˣσʸ = iσᶻ, not σʸσˣ = −iσᶻ."""
    n = 1
    got = expr_to_matrix_via_terms(n, "(σˣ₀) σʸ₀", [[0]])
    z = expr_to_matrix_via_terms(n, "σᶻ₀", [[0]])
    np.testing.assert_allclose(got, 1j * z, atol=1e-14)
    # and the distributed-sum case
    got2 = expr_to_matrix_via_terms(2, "(σ⁺₀ + σ⁻₀) σᶻ₀", [[0]])
    ref = expr_to_matrix_via_terms(2, "σ⁺₀ σᶻ₀ + σ⁻₀ σᶻ₀", [[0]])
    np.testing.assert_allclose(got2, ref, atol=1e-14)
