"""Self-tuning runtime (DESIGN.md §30): search, artifacts, posterior,
live loop, engine integration.

The contracts pinned here:

* the static knob search is a PURE function of (stats, rates, mode) —
  two runs, or two ranks, always return the same argmin, and the
  fixed-width encode/decode round-trips every knob exactly (the
  agreement vector can never garble a config);
* tuning artifacts round-trip through the content-addressed cache, and
  the fingerprint folds the RATES in — a re-calibration is a miss,
  never a stale hit;
* a tuned engine is BIT-identical to a hand-set engine at the same
  knobs (and to the untuned default — the §30 search space only
  contains value-exact choices), and shares the hand-set engine's
  structure fingerprint, so the sidecar caches are shared too;
* explicit constructor knobs beat the tuned values (tuning is a
  default-filler, never an override);
* the posterior's log-EMA update math walks a mis-calibration toward
  the measured wall at the documented gain;
* a REAL 2-process job's ranks agree on ONE tuned config.
"""

import os
import re
import socket
import subprocess
import sys

import jax
import numpy as np
import pytest

from distributed_matvec_tpu import tune
from distributed_matvec_tpu.obs.roofline import phase_bounds_ms
from distributed_matvec_tpu.parallel.distributed import DistributedEngine
from distributed_matvec_tpu.utils.config import update_config

from test_operator import build_heisenberg


def _ndev() -> int:
    return len(jax.devices())


needs_4 = pytest.mark.skipif("_ndev() < 4", reason="needs 4 virtual devices")

#: A mid-size streamed geometry: multi-chunk at the small batch
#: candidates, single-chunk at the large ones — the grid exercises both.
STATS = {"shard_size": 40960, "num_terms": 24, "n_my_shards": 1,
         "n_devices": 4, "pair": False, "cplx": False, "columns": 1,
         "group_order": 2, "ram_budget_bytes": 8e9,
         "disk_available": True}

CAL = {"gather_rows_per_s": 25e6, "h2d_bytes_per_s": 8e9,
       "exchange_bytes_per_s": 4e9, "flops_per_s": 5e9,
       "backend": "cpu", "device_kind": "cpu", "source": "default"}


@pytest.fixture
def art_root(tmp_path, monkeypatch):
    """Isolated artifact cache — tuning artifacts/posteriors land here,
    never in the developer's real cache."""
    root = tmp_path / "artifacts"
    monkeypatch.setenv("DMT_ARTIFACT_DIR", str(root))
    monkeypatch.setenv("DMT_ARTIFACT_CACHE", "on")
    return root


@pytest.fixture
def tune_off():
    """Restore the tune knob whatever a test does to it."""
    yield
    update_config(tune="off")


# ---------------------------------------------------------------------------
# pure search


def test_search_deterministic():
    a = tune.choose_config(STATS, CAL, "streamed")
    b = tune.choose_config(dict(STATS), dict(CAL), "streamed")
    assert a.token() == b.token()
    assert a.priced_ms == pytest.approx(b.priced_ms)
    # the argmin really is the argmin over the enumerated grid
    prices = [tune.price_config(STATS, c, CAL)
              for c in tune.knob_grid(STATS, "streamed")]
    assert a.priced_ms == pytest.approx(min(prices))


def test_search_value_exact_tiers_only():
    for mode in ("streamed", "hybrid"):
        for cand in tune.knob_grid(STATS, mode):
            assert cand.stream_compress in ("off", "lossless")


def test_grid_disk_forced_when_ram_cannot_hold_the_plan():
    stats = dict(STATS, ram_budget_bytes=1.0)
    assert all(c.plan_tier == "disk"
               for c in tune.knob_grid(stats, "streamed"))


def test_encode_decode_roundtrip():
    for cand in tune.knob_grid(STATS, "hybrid"):
        back = tune.TunedConfig.decode(cand.encode(), cand.mode)
        assert back.same_knobs(cand), (cand.token(), back.token())


def test_fingerprint_misses_on_calibration_change():
    fp = tune.tuning_fingerprint(STATS, CAL, "streamed")
    assert fp == tune.tuning_fingerprint(dict(STATS), dict(CAL), "streamed")
    assert fp != tune.tuning_fingerprint(
        STATS, dict(CAL, flops_per_s=2 * CAL["flops_per_s"]), "streamed")
    assert fp != tune.tuning_fingerprint(STATS, CAL, "hybrid")
    assert fp != tune.tuning_fingerprint(
        dict(STATS, shard_size=STATS["shard_size"] + 8), CAL, "streamed")


# ---------------------------------------------------------------------------
# artifacts


def test_tuned_artifact_roundtrip(art_root):
    cfg = tune.choose_config(STATS, CAL, "streamed")
    fp = tune.tuning_fingerprint(STATS, CAL, "streamed")
    path = tune.save_tuned(fp, cfg, STATS, CAL, search_s=0.01)
    assert path and os.path.exists(path)
    back = tune.load_tuned(fp)
    assert back is not None and back.same_knobs(cfg)
    assert back.source == "artifact"
    # a re-calibration is a MISS (rates are folded into the address)
    fp2 = tune.tuning_fingerprint(
        STATS, dict(CAL, flops_per_s=CAL["flops_per_s"] * 10), "streamed")
    assert tune.load_tuned(fp2) is None
    # find_tuned surfaces the saved record for capacity/serve
    recs = tune.find_tuned("streamed", "cpu")
    assert recs and recs[0]["fingerprint"] == fp
    assert tune.TunedConfig.from_dict(recs[0]["config"]).same_knobs(cfg)


def test_posterior_sidecar_roundtrip(art_root):
    post = tune.RatePosterior(CAL)
    post.update({"compute": {"bytes": 0, "gathers": 10 ** 7,
                             "flops": 10 ** 8}}, wall_ms=100.0)
    assert tune.save_posterior(post, "streamed")
    d = tune.load_posterior("cpu", "cpu", "streamed")
    assert d is not None and d["source"] == "posterior"
    back = tune.RatePosterior.from_dict(d)
    for k in ("gather_rows_per_s", "flops_per_s"):
        assert back.rates()[k] == pytest.approx(post.rates()[k])


# ---------------------------------------------------------------------------
# posterior math


def test_posterior_shared_correction_math():
    post = tune.RatePosterior(CAL)
    counts = {"compute": {"bytes": 0, "gathers": 2 * 10 ** 6,
                          "flops": 5 * 10 ** 7}}
    before = post.rates()
    priced = sum(phase_bounds_ms(counts, before).values())
    post.update(counts, wall_ms=10.0 * priced)
    after = post.rates()
    # one shared ratio rho = priced/measured = 0.1, log-EMA gain 0.6
    for f in ("gather_rows_per_s", "flops_per_s"):
        assert after[f] == pytest.approx(before[f] * 0.1 ** 0.6, rel=1e-9)
    # untouched rates stay put
    assert after["exchange_bytes_per_s"] == pytest.approx(
        before["exchange_bytes_per_s"])


def test_posterior_direct_observation_math():
    post = tune.RatePosterior(CAL)
    by = 16 * 10 ** 6
    counts = {"plan_h2d": {"bytes": by, "gathers": 0, "flops": 0}}
    # measured 4 ms for 16 MB -> observed 4 GB/s vs the 8 GB/s prior:
    # ratio 0.5 at gain 0.6
    post.update(counts, wall_ms=4.0, measured={"plan_h2d": 4.0})
    assert post.rates()["h2d_bytes_per_s"] == pytest.approx(
        8e9 * 0.5 ** 0.6, rel=1e-9)


def test_posterior_converges_ten_x_miscalibration():
    post = tune.RatePosterior(CAL)
    counts = {"compute": {"bytes": 0, "gathers": 10 ** 6,
                          "flops": 10 ** 7},
              "plan_h2d": {"bytes": 10 ** 7, "gathers": 0, "flops": 0}}
    true_wall = 10.0 * sum(phase_bounds_ms(counts, CAL).values())
    ratios = []
    for _ in range(4):
        priced = sum(phase_bounds_ms(counts, post.rates()).values())
        ratios.append(true_wall / priced)
        post.update(counts, true_wall)
    final = sum(phase_bounds_ms(counts, post.rates()).values())
    assert abs(true_wall / final - 1.0) < 0.25, ratios
    # and the walk is monotone toward 1 (the documented EMA trajectory)
    assert all(b < a for a, b in zip(ratios, ratios[1:])), ratios


def test_live_tuner_window_discipline(monkeypatch):
    monkeypatch.setenv("DMT_ARTIFACT_CACHE", "off")
    cfg = tune.choose_config(STATS, CAL, "streamed")
    t = tune.LiveTuner("streamed", STATS, CAL, cfg, window=2)
    counts = tune.model_counts(STATS, cfg)
    priced = sum(phase_bounds_ms(counts, CAL).values())
    assert t.observe(counts, priced) is None          # compile apply: skipped
    assert not t.window_closed and t.windows == 0
    assert t.observe(counts, priced) is None
    assert not t.window_closed
    prop = t.observe(counts, priced)                  # closes window 1
    assert t.window_closed and t.windows == 1
    assert prop is None                               # ratio ~1: no drift
    assert t.last_ratio == pytest.approx(1.0, rel=0.01)
    # a rebuild restarts the window and skips the next compile wall
    t.note_rebuild(cfg)
    assert t.observe(counts, priced) is None and t.windows == 1


# ---------------------------------------------------------------------------
# engine integration


def _build(op, **kw):
    return DistributedEngine(op, n_devices=4, mode="streamed", **kw)


@needs_4
def test_tuned_engine_bit_identity(art_root, tune_off, rng):
    """The §30 acceptance: tuned == hand-set at the same knobs, BIT for
    bit, sharing one structure fingerprint (and == the untuned default —
    every searched knob is value-exact)."""
    op = build_heisenberg(12, 6, None, ())
    op.basis.build()
    x = rng.random(op.basis.number_states) - 0.5
    eng_plain = _build(op)
    y_plain = np.asarray(eng_plain.matvec(eng_plain.to_hashed(x)))
    update_config(tune="static")
    eng_t = _build(op)
    update_config(tune="off")
    t = eng_t._tuned
    assert t is not None and t.source in ("search", "artifact")
    y_t = np.asarray(eng_t.matvec(eng_t.to_hashed(x)))
    assert np.array_equal(y_t, y_plain), "tuned lost bit-identity"
    # hand-set twin at the tuned knobs
    update_config(stream_compress=t.stream_compress)
    try:
        eng_h = _build(op, batch_size=eng_t.batch_size,
                       pipeline_depth=t.pipeline_depth)
    finally:
        update_config(stream_compress="off")
    assert eng_h._structure_fingerprint() == eng_t._structure_fingerprint()
    y_h = np.asarray(eng_h.matvec(eng_h.to_hashed(x)))
    assert np.array_equal(y_t, y_h), "tuned != hand-set at the same knobs"


@needs_4
def test_tuned_artifact_restore_and_explicit_override(art_root, tune_off,
                                                      rng):
    op = build_heisenberg(10, 5, None, ())
    op.basis.build()
    update_config(tune="static")
    eng1 = _build(op)
    assert eng1._tuned is not None and eng1._tuned.source == "search"
    # repeat build: the search is skipped, the artifact restores
    eng2 = _build(op)
    assert eng2._tuned is not None and eng2._tuned.source == "artifact"
    assert eng2._tuned.same_knobs(eng1._tuned)
    assert eng2.batch_size == eng1.batch_size
    # an explicit constructor knob BEATS the tuned value (24 is small
    # enough to survive the shard-size clamp on this sector)
    eng3 = _build(op, batch_size=24)
    assert eng3.batch_size == 24
    # ...and the override is honored identically to an untuned engine
    # at the same explicit knob (bit-identity is per-knob-set: a
    # different row chunking legally reorders the accumulate)
    update_config(tune="off")
    eng_plain = _build(op, batch_size=24)
    x = rng.random(op.basis.number_states) - 0.5
    y3 = np.asarray(eng3.matvec(eng3.to_hashed(x)))
    yp = np.asarray(eng_plain.matvec(eng_plain.to_hashed(x)))
    assert np.array_equal(y3, yp)


def test_bad_tune_knob_rejected(tune_off):
    op = build_heisenberg(10, 5, None, ())
    op.basis.build()
    update_config(tune="bogus")
    with pytest.raises(ValueError, match="unknown tune setting"):
        DistributedEngine(op, n_devices=2, mode="streamed")


@needs_4
def test_tune_config_event_emitted(art_root, tune_off):
    from distributed_matvec_tpu import obs

    op = build_heisenberg(10, 5, None, ())
    op.basis.build()
    update_config(tune="static")
    eng = _build(op)
    evs = [e for e in obs.events("tune_config")
           if e.get("engine") == "distributed"
           and e.get("mode") == "streamed"]
    assert evs and evs[-1]["token"] == eng._tuned.token()
    assert evs[-1]["source"] in ("search", "artifact")


# ---------------------------------------------------------------------------
# real 2-process agreement


def test_two_process_tune(tmp_path):
    """A REAL 2-process run (multihost worker, DMT_MH_TUNE leg): both
    ranks must print the SAME tuned config token — one static program
    fleet-wide — with bit-identity and correctness asserted in-worker."""
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["DMT_MH_TUNE"] = "1"
    env["DMT_OBS_DIR"] = str(tmp_path / "run")
    env["DMT_ARTIFACT_DIR"] = str(tmp_path / "artifacts")
    env["DMT_ARTIFACT_CACHE"] = "on"
    procs = [subprocess.Popen(
        [sys.executable, worker, str(pid), "2", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    tokens = []
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid}:\n{out[-2000:]}"
        assert f"[p{pid}] MULTIHOST_OK" in out, out[-2000:]
        m = re.search(rf"\[p{pid}\] TUNE_CONFIG (\S+)", out)
        assert m, out[-2000:]
        tokens.append(m.group(1))
    assert tokens[0] == tokens[1], f"ranks disagreed: {tokens}"
