"""Distributed-memory enumeration (StatesEnumeration.chpl:305-514 analog):
representatives stream into per-shard datasets — never a global host array —
validated against the hash layout of the ordinary enumeration and against
the pure-combinatorics sector-dimension census.
"""

import numpy as np
import pytest

from distributed_matvec_tpu.enumeration.native import native_available
from distributed_matvec_tpu.enumeration.sharded import (
    enumerate_to_shards, load_shard, shard_manifest)
from distributed_matvec_tpu.models.basis import SpinBasis
from distributed_matvec_tpu.models.symmetry import SymmetryGroup
from distributed_matvec_tpu.parallel.shuffle import HashedLayout

needs_native = pytest.mark.skipif(not native_available(),
                                  reason="native kernel unavailable")

SECTOR_CASES = [
    (12, 6, None, ()),
    (12, 6, 1, [([*range(1, 12), 0], 0), ([*range(11, -1, -1)], 0)]),
    (10, 5, -1, ()),
    (10, 5, None, [([*range(1, 10), 0], 1)]),     # complex characters
    (10, 5, None, [([*range(1, 10), 0], 5)]),     # momentum pi
    (14, 7, 1, [([*range(1, 14), 0], 7)]),        # mixed, nontrivial sector
]


@pytest.mark.parametrize("n,hw,inv,syms", SECTOR_CASES)
def test_census_matches_enumeration(n, hw, inv, syms):
    """The projector-trace census (pure combinatorics, no enumeration)
    equals the enumerated sector size across sector types."""
    b = SpinBasis(number_spins=n, hamming_weight=hw, spin_inversion=inv,
                  symmetries=list(syms))
    b.build()
    assert b.group.sector_dimension_census(hw) == b.number_states


@needs_native
@pytest.mark.parametrize("n,hw,inv,syms", SECTOR_CASES[:4])
@pytest.mark.parametrize("n_shards", [4, 8])
def test_shards_match_hash_layout(n, hw, inv, syms, n_shards, tmp_path):
    """Shard contents must be exactly the HashedLayout partition of the
    ordinary (global) enumeration: same states, same norms, same per-shard
    sorted order."""
    b = SpinBasis(number_spins=n, hamming_weight=hw, spin_inversion=inv,
                  symmetries=list(syms))
    b.build()
    path = str(tmp_path / "shards.h5")
    man = enumerate_to_shards(n, hw, b.group, n_shards, path)
    assert not man["restored"]
    assert man["total"] == b.number_states
    layout = HashedLayout(b.representatives, n_shards)
    np.testing.assert_array_equal(man["counts"], layout.counts)
    reps_h = layout.to_hashed(b.representatives, fill=0)
    norms_h = layout.to_hashed(b.norms, fill=0.0)
    for d in range(n_shards):
        s, nn = load_shard(path, d)
        c = layout.counts[d]
        assert s.size == c
        np.testing.assert_array_equal(s, reps_h[d, :c])
        np.testing.assert_allclose(nn, norms_h[d, :c], atol=1e-14)
        assert (np.diff(s.astype(np.int64)) > 0).all()   # sorted, unique


@needs_native
def test_shards_restore(tmp_path):
    b = SpinBasis(number_spins=12, hamming_weight=6)
    b.build()
    path = str(tmp_path / "s.h5")
    man1 = enumerate_to_shards(12, 6, b.group, 4, path)
    assert not man1["restored"]
    man2 = enumerate_to_shards(12, 6, b.group, 4, path)
    assert man2["restored"] and man2["total"] == man1["total"]
    # different parameters must NOT restore (fingerprint mismatch)
    man3 = enumerate_to_shards(12, 6, b.group, 8, path)
    assert not man3["restored"] and man3["total"] == man1["total"]
    assert shard_manifest(path)["n_shards"] == 8


def _mp_enum_worker(args):
    """Module-level worker (picklable for spawn): one rank's slice of a
    multi-process enumeration.  The group is rebuilt in-process — ranks
    share nothing but the output directory."""
    n, hw, inv, syms, n_shards, path, rank, n_ranks = args
    from distributed_matvec_tpu.enumeration.sharded import enumerate_to_shards
    from distributed_matvec_tpu.models.basis import SpinBasis

    b = SpinBasis(number_spins=n, hamming_weight=hw, spin_inversion=inv,
                  symmetries=[list(s) for s in syms])
    man = enumerate_to_shards(n, hw, b.group, n_shards, path,
                              rank=rank, n_ranks=n_ranks)
    return man["total"]


@needs_native
@pytest.mark.parametrize("n_ranks", [2, 3])
def test_multiprocess_enumeration_matches_single(n_ranks, tmp_path):
    """Cross-process parallel enumeration (the per-locale concurrent
    enumeration of StatesEnumeration.chpl:321-334): every rank enumerates a
    disjoint index-space slice in its own OS process, the finalize step
    census-validates the union, and the combined shards are bit-identical
    to a single-process enumeration of the same sector."""
    import multiprocessing as mp
    from concurrent.futures import ProcessPoolExecutor

    from distributed_matvec_tpu.enumeration.sharded import finalize_shard_parts

    n, hw, inv = 14, 7, 1
    syms = (([*range(1, 14), 0], 0),)
    n_shards = 8
    b = SpinBasis(number_spins=n, hamming_weight=hw, spin_inversion=inv,
                  symmetries=[list(s) for s in syms])
    b.build()

    single = str(tmp_path / "single.h5")
    enumerate_to_shards(n, hw, b.group, n_shards, single)

    multi = str(tmp_path / "multi.h5")
    ctx = mp.get_context("spawn")
    with ProcessPoolExecutor(max_workers=n_ranks, mp_context=ctx) as ex:
        totals = list(ex.map(_mp_enum_worker, [
            (n, hw, inv, syms, n_shards, multi, r, n_ranks)
            for r in range(n_ranks)]))
    # disjoint slices: rank totals sum to the sector dimension
    assert sum(totals) == b.number_states
    man = finalize_shard_parts(n, hw, b.group, n_shards, multi, n_ranks)
    assert man["total"] == b.number_states
    sman = shard_manifest(single)
    assert man["counts"] == sman["counts"]
    for d in range(n_shards):
        s1, w1 = load_shard(single, d)
        s2, w2 = load_shard(multi, d)
        np.testing.assert_array_equal(s1, s2)
        np.testing.assert_allclose(w1, w2, atol=1e-14)
        assert (np.diff(s2.astype(np.int64)) > 0).all()

    # restore semantics: a rerun of any rank and of the finalize is a no-op
    man_r = _mp_enum_worker((n, hw, inv, syms, n_shards, multi, 0, n_ranks))
    assert man_r == totals[0]
    man2 = finalize_shard_parts(n, hw, b.group, n_shards, multi, n_ranks)
    assert man2["restored"] and man2["total"] == man["total"]


@needs_native
def test_multiprocess_enumeration_feeds_engine(tmp_path):
    """A part-manifest shard file is a first-class engine input: the
    DistributedEngine built from it matches the host matvec."""
    import jax as _jax

    if len(_jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from distributed_matvec_tpu.enumeration.sharded import finalize_shard_parts
    from distributed_matvec_tpu.models.yaml_io import operator_from_dict
    from distributed_matvec_tpu.parallel.distributed import DistributedEngine

    n, hw = 12, 6
    b = SpinBasis(number_spins=n, hamming_weight=hw)
    path = str(tmp_path / "mp.h5")
    for r in range(2):
        enumerate_to_shards(n, hw, b.group, 8, path, rank=r, n_ranks=2)
    finalize_shard_parts(n, hw, b.group, 8, path, 2)

    ham = {"terms": [{"expression": "σˣ₀ σˣ₁ + σʸ₀ σʸ₁ + σᶻ₀ σᶻ₁",
                      "sites": [[i, (i + 1) % n] for i in range(n)]}]}
    fresh = SpinBasis(number_spins=n, hamming_weight=hw)
    op = operator_from_dict(ham, fresh)
    eng = DistributedEngine.from_shards(op, path, n_devices=8)

    ref_basis = SpinBasis(number_spins=n, hamming_weight=hw)
    ref_basis.build()
    op_ref = operator_from_dict(ham, ref_basis)
    x = np.random.default_rng(11).standard_normal(ref_basis.number_states)
    np.testing.assert_allclose(eng.matvec_global(x), op_ref.matvec_host(x),
                               atol=1e-13, rtol=1e-12)


def test_census_chain_40_symm_value():
    """The scale target's census: 137 846 528 820 candidates reduce to
    861 725 794 representatives under the 160-element symmetry group —
    the number the chain_40 sharded run must reproduce."""
    g = SymmetryGroup.build(
        40, [([*range(1, 40), 0], 0), ([*range(39, -1, -1)], 0)],
        spin_inversion=1)
    assert len(g) == 160
    assert g.sector_dimension_census(20) == 861_725_794


@needs_native
def test_engine_from_shards(tmp_path):
    """DistributedEngine.from_shards: engine built straight from the shard
    file with an UNBUILT basis — no global representative array anywhere —
    must match the conventional engine and the host matvec, and solve to
    the same ground state from a shard-native random start."""
    import jax as _jax

    if len(_jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from distributed_matvec_tpu.models.yaml_io import operator_from_dict
    from distributed_matvec_tpu.parallel.distributed import DistributedEngine
    from distributed_matvec_tpu.solve import lanczos

    n, hw = 12, 6
    syms = [([*range(1, n), 0], 0), ([*range(n - 1, -1, -1)], 0)]
    ref_basis = SpinBasis(number_spins=n, hamming_weight=hw,
                          spin_inversion=1, symmetries=list(syms))
    ref_basis.build()
    path = str(tmp_path / "shards.h5")
    enumerate_to_shards(n, hw, ref_basis.group, 8, path)

    ham = {"terms": [{"expression": "σˣ₀ σˣ₁ + σʸ₀ σʸ₁ + σᶻ₀ σᶻ₁",
                      "sites": [[i, (i + 1) % n] for i in range(n)]}]}
    fresh_basis = SpinBasis(number_spins=n, hamming_weight=hw,
                            spin_inversion=1, symmetries=list(syms))
    op = operator_from_dict(ham, fresh_basis)
    eng = DistributedEngine.from_shards(op, path, n_devices=8)
    assert not fresh_basis.is_built          # truly global-array-free
    assert eng.n_states == ref_basis.number_states

    # hashed matvec vs the host path on the built twin
    op_ref = operator_from_dict(ham, ref_basis)
    x = np.random.default_rng(3).standard_normal(ref_basis.number_states)
    y = eng.matvec_global(x)                 # lazy layout materialization
    np.testing.assert_allclose(y, op_ref.matvec_host(x),
                               atol=1e-13, rtol=1e-12)

    # shard-native solve: random_hashed never touches a global array
    res = lanczos(eng.matvec, v0=eng.random_hashed(seed=5), k=1, tol=1e-10)
    want = np.linalg.eigvalsh(op_ref.to_sparse().toarray())[0]
    assert abs(float(res.eigenvalues[0]) - want) < 1e-8


@needs_native
def test_cli_shards_saves_sharded_eigenvectors(tmp_path):
    """--shards WITHOUT --no-eigenvectors: the driver saves eigenvectors one
    shard at a time (vector_shards/eigenvector_i) — never a global [N]
    array — and the reassembled state is the true ground state (residual
    check against the independent host matvec).  Observables run on the
    hashed psi directly."""
    import os
    import subprocess
    import sys

    import h5py

    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_ENABLE_X64="true",
               PYTHONPATH=os.path.join(os.path.dirname(__file__), os.pardir),
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    app = os.path.join(os.path.dirname(__file__), os.pardir, "apps",
                       "diagonalize.py")
    n, hw = 10, 5
    yml = str(tmp_path / "m.yaml")
    with open(yml, "w") as f:
        f.write("""
basis: {number_spins: 10, hamming_weight: 5}
hamiltonian:
  name: H
  terms:
    - {expression: "σˣ₀ σˣ₁ + σʸ₀ σʸ₁ + σᶻ₀ σᶻ₁", sites: [[0,1],[1,2],[2,3],[3,4],[4,5],[5,6],[6,7],[7,8],[8,9],[9,0]]}
observables:
  - name: nn
    terms:
      - {expression: "σˣ₀ σˣ₁ + σʸ₀ σʸ₁ + σᶻ₀ σᶻ₁", sites: [[0, 1]]}
""")
    shards = str(tmp_path / "s.h5")
    b = SpinBasis(number_spins=n, hamming_weight=hw)
    b.build()
    enumerate_to_shards(n, hw, b.group, 8, shards)
    out = str(tmp_path / "out.h5")
    r = subprocess.run(
        [sys.executable, app, yml, "-o", out, "--shards", shards,
         "-k", "1", "--observables"],
        capture_output=True, text=True, env=env, timeout=240)
    assert r.returncode == 0, (r.stdout[-800:], r.stderr[-1500:])

    from distributed_matvec_tpu.io.sharded_io import (
        hashed_vector_counts, load_hashed_shard)
    from distributed_matvec_tpu.models.yaml_io import load_config_from_yaml
    from distributed_matvec_tpu.parallel.shuffle import HashedLayout

    counts = hashed_vector_counts(out)
    layout = HashedLayout(b.representatives, 8)
    np.testing.assert_array_equal(counts, layout.counts)
    # reassemble the block-order psi from the per-shard datasets
    psi_h = np.zeros((8, layout.shard_size))
    for d in range(8):
        rows = load_hashed_shard(out, d, name="eigenvector_0")
        assert rows.shape == (counts[d],)
        psi_h[d, : counts[d]] = rows
    psi = layout.from_hashed(psi_h)
    with h5py.File(out, "r") as f:
        e0 = float(f["hamiltonian/eigenvalues"][0])
        assert "hamiltonian/eigenvectors" not in f   # no global array saved
        corr = float(f["observables/nn"][()])
    cfg = load_config_from_yaml(yml, hamiltonian=True)
    cfg.basis.build()
    resid = np.linalg.norm(cfg.hamiltonian.matvec_host(psi) - e0 * psi)
    assert abs(np.linalg.norm(psi) - 1) < 1e-10
    assert resid < 1e-8, resid
    assert abs(corr - e0 / n) < 1e-6                 # ring bond correlator


def test_stream_block_to_shards_matches_layout(tmp_path, rng):
    """Chunked block→shard vector routing (MyHDF5 hyperslab + B2H analog)
    must equal HashedLayout.to_hashed exactly, rank-1 and batch."""
    from distributed_matvec_tpu.io.hdf5 import save_golden
    from distributed_matvec_tpu.io.sharded_io import (
        load_hashed_shard, stream_block_to_shards)

    b = SpinBasis(number_spins=14, hamming_weight=7)
    b.build()
    n = b.number_states
    X = rng.random((3, n)) - 0.5            # golden layout: [k, N]
    src = str(tmp_path / "golden.h5")
    save_golden(src, b.representatives, X, X)
    out = str(tmp_path / "xshards.h5")
    counts = stream_block_to_shards(src, out, 8, chunk=777)

    layout = HashedLayout(b.representatives, 8)
    np.testing.assert_array_equal(counts, layout.counts)
    want = layout.to_hashed(X.T, fill=0)     # [D, M, k]
    for d in range(8):
        got = load_hashed_shard(out, d)
        np.testing.assert_array_equal(got, want[d, : counts[d]])


def test_save_load_hashed_vector_round_trip(tmp_path, rng):
    """Per-shard hashed-vector checkpoint (readDatasetAsBlocks analog):
    device array in, pad rows stripped on disk, per-shard reads back."""
    import jax as _jax

    if len(_jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from distributed_matvec_tpu.io.sharded_io import (
        hashed_vector_counts, load_hashed_shard, save_hashed_vector)
    from test_operator import build_heisenberg
    from distributed_matvec_tpu.parallel.distributed import DistributedEngine

    op = build_heisenberg(12, 6)
    op.basis.build()
    eng = DistributedEngine(op, n_devices=8)
    xh = eng.random_hashed(seed=9)
    path = str(tmp_path / "v.h5")
    save_hashed_vector(path, xh, eng.counts)
    np.testing.assert_array_equal(hashed_vector_counts(path), eng.counts)
    xh_np = np.asarray(xh)
    for d in range(8):
        got = load_hashed_shard(path, d)
        np.testing.assert_array_equal(got, xh_np[d, : eng.counts[d]])


@needs_native
def test_cli_shards_observables(tmp_path):
    """--shards + --observables: observables run through shard-native
    engines from the SAME shard file (no per-observable global basis
    rebuild); value cross-checked against the host matvec."""
    import subprocess
    import sys
    import os

    import h5py

    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_ENABLE_X64="true",
               PYTHONPATH="/root/repo",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    app = os.path.join(os.path.dirname(__file__), os.pardir, "apps",
                       "diagonalize.py")
    yml = str(tmp_path / "m.yaml")
    with open(yml, "w") as f:
        f.write("""
basis: {number_spins: 10, hamming_weight: 5}
hamiltonian:
  name: H
  terms:
    - {expression: "σˣ₀ σˣ₁ + σʸ₀ σʸ₁ + σᶻ₀ σᶻ₁", sites: &l [[0,1],[1,2],[2,3],[3,4],[4,5],[5,6],[6,7],[7,8],[8,9],[9,0]]}
observables:
  - name: nn
    terms:
      - {expression: "σˣ₀ σˣ₁ + σʸ₀ σʸ₁ + σᶻ₀ σᶻ₁", sites: [[0, 1]]}
""")
    shards = str(tmp_path / "s.h5")
    from distributed_matvec_tpu.enumeration.sharded import enumerate_to_shards
    b = SpinBasis(number_spins=10, hamming_weight=5)
    b.build()
    enumerate_to_shards(10, 5, b.group, 8, shards)
    out = str(tmp_path / "out.h5")
    r = subprocess.run(
        [sys.executable, app, yml, "-o", out, "--shards", shards,
         "-k", "1", "--observables"],
        capture_output=True, text=True, env=env, timeout=240)
    assert r.returncode == 0, (r.stdout[-800:], r.stderr[-1500:])
    with h5py.File(out, "r") as f:
        corr = float(f["observables/nn"][()])
        psi = f["hamiltonian/eigenvalues"][...]
    # bond correlator of the 10-ring GS = E0 / 10
    assert abs(corr - psi[0] / 10) < 1e-6, (corr, psi[0] / 10)


def test_rank_file_meta_and_counts_discovery(tmp_path, rng):
    """ADVICE r4 low items: (a) ``hashed_vector_counts`` must read counts
    when a multi-process save wrote only ``path.r<rank>`` files; (b) a
    stale base-path ``/ckpt_meta`` must not mask valid per-rank
    checkpoints when the caller filters by fingerprint."""
    from distributed_matvec_tpu.io.sharded_io import (
        hashed_vector_counts, load_hashed_meta, save_hashed_vectors)

    base = str(tmp_path / "v.h5")
    counts = np.array([2, 1], np.int64)
    xh = rng.random((2, 3))
    # simulate the rank-0 file of a multi-process run (a single-process
    # save writes to the exact path it is given)
    save_hashed_vectors(base + ".r0", {"v": xh}, counts,
                        meta={"fingerprint": "good", "m": 3})
    assert load_hashed_meta(base) is not None
    np.testing.assert_array_equal(hashed_vector_counts(base), counts)

    # a stale base-path file from an earlier single-process run
    save_hashed_vectors(base, {"v": xh}, counts,
                        meta={"fingerprint": "stale", "m": 1})
    got = load_hashed_meta(base)                   # unfiltered scan: stale
    assert str(got["fingerprint"]) == "stale"
    got = load_hashed_meta(base, expected_fingerprint="good")
    assert got is not None and int(got["m"]) == 3
    assert load_hashed_meta(base, expected_fingerprint="nope") is None


@needs_native
def test_reshard_cross_mesh_agreement(tmp_path):
    """``reshard_shards`` 8→4 plus the state-keyed probe: the re-routed
    file must hold exactly the HashedLayout-4 partition, and fused engines
    on the two mesh sizes must produce the same global ⟨x, Hx⟩ / ‖Hx‖ —
    the cross-mesh verification protocol the chain_40 scale run uses
    (tools/scale_apply.py)."""
    import jax as _jax

    if len(_jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from distributed_matvec_tpu.enumeration.sharded import reshard_shards
    from distributed_matvec_tpu.parallel.distributed import DistributedEngine
    from test_operator import build_heisenberg

    op = build_heisenberg(14, 7, 1, [([*range(1, 14), 0], 0)])
    b = op.basis
    b.build()
    p8 = str(tmp_path / "s8.h5")
    p4 = str(tmp_path / "s4.h5")
    enumerate_to_shards(14, 7, b.group, 8, p8)
    man4 = reshard_shards(p8, p4, 4, group=b.group)
    # restore path: same fingerprint → no rewrite
    assert reshard_shards(p8, p4, 4, group=b.group)["restored"]
    # with the group, the resharded file is indistinguishable from a
    # direct 4-shard enumeration
    direct = enumerate_to_shards(14, 7, b.group, 4,
                                 str(tmp_path / "d4.h5"))
    assert man4["fingerprint"] == direct["fingerprint"]
    assert man4["counts"] == direct["counts"]
    layout4 = HashedLayout(b.representatives, 4)
    for d in range(4):
        s, nn = load_shard(p4, d)
        c = layout4.counts[d]
        np.testing.assert_array_equal(
            s, layout4.to_hashed(b.representatives, fill=0)[d, :c])
        np.testing.assert_array_equal(
            nn, layout4.to_hashed(b.norms, fill=0.0)[d, :c])

    e8 = DistributedEngine.from_shards(op, p8, n_devices=8, mode="fused")
    e4 = DistributedEngine.from_shards(op, p4, n_devices=4, mode="fused")
    x8, x4 = e8.state_keyed_hashed(salt=3), e4.state_keyed_hashed(salt=3)
    # the probe is a pure function of the state: identical global vector
    np.testing.assert_allclose(
        float(np.linalg.norm(np.asarray(x8))),
        float(np.linalg.norm(np.asarray(x4))), rtol=1e-13)
    y8, y4 = e8.matvec(x8), e4.matvec(x4)
    s8 = float(e8.dot(x8, y8))
    s4 = float(e4.dot(x4, y4))
    np.testing.assert_allclose(s8, s4, rtol=1e-12)
    np.testing.assert_allclose(float(np.linalg.norm(np.asarray(y8))),
                               float(np.linalg.norm(np.asarray(y4))),
                               rtol=1e-12)
