"""Elastic solves: topology-portable checkpoints, D→D′ resharded resume.

Pins the contracts of ``parallel/reshard.py`` + the solver restore paths
(DESIGN.md §27):

* the checkpoint topology stanza round-trips (D, shard size, counts,
  partition fingerprint);
* the reshard redistribution is EXACTLY the permutation the target
  layout defines — bit-identical to ``to_hashed`` at D′ for every
  (D, D′) ∈ {1, 2, 4}², pair tails included;
* Lanczos resumes a D-written checkpoint at D′ in both directions with
  the iteration count carried over and E0 unchanged; LOBPCG does the
  same and agrees with Lanczos;
* a checkpoint written under a FOREIGN partition (different shard hash)
  is refused with a pointer, and an injected torn reshard
  (``DMT_FAULT=ckpt_reshard``) degrades to a fresh solve — never a
  half-redistributed basis;
* legacy fixed-D (v1) checkpoints still restore unchanged on matching D;
* the serve layer re-admits against LIVE capacity and prunes warm
  engines whose mesh no longer fits; the heartbeat watchdog scopes its
  scan to the current rank set and ages out departed ranks' beat files;
* a REAL 2-process run (multihost worker, elastic leg) reshards
  per-rank ``.r*`` checkpoint files written at the old topology.
"""

import os
import time

import jax
import numpy as np
import pytest

from distributed_matvec_tpu import obs
from distributed_matvec_tpu.models.basis import SpinBasis
from distributed_matvec_tpu.models.lattices import (chain_edges,
                                                    heisenberg_from_edges)
from distributed_matvec_tpu.parallel.distributed import DistributedEngine
from distributed_matvec_tpu.parallel.reshard import (PartitionMismatch,
                                                     Resharder,
                                                     partition_fingerprint,
                                                     topology_stanza)
from distributed_matvec_tpu.solve import lanczos, lobpcg
from distributed_matvec_tpu.utils import faults


def make_op(n=10):
    basis = SpinBasis(number_spins=n, hamming_weight=n // 2)
    return heisenberg_from_edges(basis, chain_edges(n))


def _reshard_events(solver=None, status="resharded"):
    return [e for e in obs.events("solver_checkpoint")
            if e.get("status") == status
            and (solver is None or e.get("solver") == solver)]


# ---------------------------------------------------------------------------
# stanza + permutation core


def test_partition_fingerprint_and_stanza():
    fp = partition_fingerprint()
    assert fp.startswith("splitmix64:") and fp == partition_fingerprint()
    eng = DistributedEngine(make_op(), n_devices=2, mode="fused")
    st = topology_stanza(eng)
    assert st["ckpt_version"] == 2
    assert st["topology_d"] == 2
    assert st["topology_m"] == eng.shard_size
    assert np.array_equal(st["topology_counts"], eng.counts)
    assert st["partition_fp"] == fp
    # non-hashed owners carry no stanza (fixed-topology by construction)
    assert topology_stanza(None) == {}


@pytest.mark.parametrize("d_src", [1, 2, 4])
@pytest.mark.parametrize("d_dst", [1, 2, 4])
def test_reshard_is_the_layout_permutation(d_src, d_dst, rng):
    """Redistributed rows are BIT-IDENTICAL to hashing the same global
    vector directly at D′ — reshard is a permutation, not arithmetic."""
    op = make_op()
    src = DistributedEngine(op, n_devices=d_src, mode="fused")
    dst = DistributedEngine(make_op(), n_devices=d_dst, mode="fused")
    x = rng.standard_normal(op.basis.number_states)
    xh_src = np.asarray(src.to_hashed(x))
    plan = Resharder(dst, d_src, src.counts)
    rows = plan.reshard_rows(
        lambda i, s: xh_src[s][: int(src.counts[s])], 1, dtype=np.float64)
    assert np.array_equal(np.asarray(rows[0]), np.asarray(dst.to_hashed(x)))


def test_reshard_pair_tail(rng):
    """Trailing (re, im) pair axes ride the same permutation."""
    op = make_op()
    src = DistributedEngine(op, n_devices=4, mode="fused")
    dst = DistributedEngine(make_op(), n_devices=2, mode="fused")
    xt = rng.standard_normal((op.basis.number_states, 2))
    xh = np.asarray(src.to_hashed(xt))
    plan = Resharder(dst, 4, src.counts, tail=(2,))
    rows = plan.reshard_rows(lambda i, s: xh[s][: int(src.counts[s])], 1)
    assert np.array_equal(np.asarray(rows[0]), np.asarray(dst.to_hashed(xt)))


def test_reshard_refuses_foreign_partition():
    """Counts that disagree with the recomputed partition = a different
    shard hash: refusal names the mismatch instead of scattering rows."""
    dst = DistributedEngine(make_op(), n_devices=2, mode="fused")
    src = DistributedEngine(make_op(), n_devices=4, mode="fused")
    with pytest.raises(PartitionMismatch, match="different shard hash"):
        Resharder(dst, 4, np.asarray(src.counts) + 1)


# ---------------------------------------------------------------------------
# lanczos: resharded resume


def _ckpt_solve(eng, ck, **kw):
    return lanczos(eng.matvec, v0=eng.random_hashed(seed=3), k=1,
                   tol=1e-12, checkpoint_path=str(ck), **kw)


def test_lanczos_resume_resharded_both_directions(tmp_path):
    op = make_op(12)
    eng2 = DistributedEngine(op, n_devices=2, mode="ell")
    ref = lanczos(eng2.matvec, v0=eng2.random_hashed(seed=3), k=1,
                  tol=1e-12, max_iters=400)
    e0 = float(ref.eigenvalues[0])

    ck = tmp_path / "ck.h5"
    part = _ckpt_solve(eng2, ck, max_iters=24, check_every=8,
                       checkpoint_every=1)
    assert not part.converged

    # grow 2 → 4: resumed iterations carried over, E0 bit-for-bit class
    eng4 = DistributedEngine(make_op(12), n_devices=4, mode="ell")
    res4 = _ckpt_solve(eng4, ck, max_iters=400)
    assert res4.resumed_from == 24
    assert abs(float(res4.eigenvalues[0]) - e0) <= 1e-12 * abs(e0)
    ev = _reshard_events("lanczos")[-1]
    assert (ev["d_from"], ev["d_to"]) == (2, 4) and ev["reshard_s"] > 0

    # shrink 4 → 1 from the checkpoint the D=4 run kept writing
    eng1 = DistributedEngine(make_op(12), n_devices=1, mode="ell")
    res1 = _ckpt_solve(eng1, ck, max_iters=400)
    assert res1.resumed_from > 0
    assert abs(float(res1.eigenvalues[0]) - e0) <= 1e-12 * abs(e0)
    ev = _reshard_events("lanczos")[-1]
    assert ev["d_to"] == 1


def test_topology_stanza_roundtrip_in_checkpoint(tmp_path):
    """The stanza written with a single-controller engine checkpoint is
    readable next to the rows it describes."""
    import h5py

    eng = DistributedEngine(make_op(), n_devices=2, mode="ell")
    ck = tmp_path / "ck.h5"
    _ckpt_solve(eng, ck, max_iters=8, check_every=4, checkpoint_every=1)
    with h5py.File(str(ck), "r") as f:
        g = f["engine_structure"]
        assert int(g.attrs["topology_d"]) == 2
        assert int(g.attrs["ckpt_version"]) == 2
        assert str(g.attrs["partition_fp"]) == partition_fingerprint()
        assert np.array_equal(g["topology_counts"][...], eng.counts)


def test_partition_fp_mismatch_refused_with_pointer(tmp_path):
    """A checkpoint stamped with a FOREIGN partition fingerprint (a
    different hash seed) is refused — fresh solve, event naming both
    fingerprints — instead of being resharded into garbage."""
    import h5py

    eng2 = DistributedEngine(make_op(), n_devices=2, mode="ell")
    ck = tmp_path / "ck.h5"
    _ckpt_solve(eng2, ck, max_iters=8, check_every=4, checkpoint_every=1)
    with h5py.File(str(ck), "r+") as f:
        f["engine_structure"].attrs["partition_fp"] = "splitmix64:deadbeef"
    eng4 = DistributedEngine(make_op(), n_devices=4, mode="ell")
    res = _ckpt_solve(eng4, ck, max_iters=200)
    assert res.resumed_from == 0 and res.converged
    evs = _reshard_events(status="refused_partition")
    assert evs, "no refusal event"
    assert evs[-1]["checkpoint_partition"] == "splitmix64:deadbeef"
    assert evs[-1]["build_partition"] == partition_fingerprint()


def test_legacy_v1_checkpoint_restores_on_matching_d(tmp_path):
    """A pre-elastic checkpoint (shape-keyed fingerprint, no topology
    stanza) still restores unchanged on the SAME device count."""
    import h5py

    eng = DistributedEngine(make_op(), n_devices=2, mode="ell")
    ck = tmp_path / "ck.h5"
    part = _ckpt_solve(eng, ck, max_iters=16, check_every=8,
                       checkpoint_every=1)
    assert not part.converged
    # rewrite the file into the v1 format: legacy fingerprint, no stanza
    shape = (eng.n_devices, eng.shard_size)
    from distributed_matvec_tpu.solve.lanczos import _operator_key
    legacy_fp = (f"{shape}|{np.dtype(np.float64).str}"
                 f"|{_operator_key(eng)}|lanczos-v2")
    with h5py.File(str(ck), "r+") as f:
        g = f["engine_structure"]
        g.attrs["fingerprint"] = legacy_fp
        for k in ("topology_d", "topology_m", "partition_fp",
                  "ckpt_version"):
            del g.attrs[k]
        del g["topology_counts"]
    n_ev = len(_reshard_events())
    res = _ckpt_solve(eng, ck, max_iters=400)
    assert res.resumed_from == 16
    assert len(_reshard_events()) == n_ev, \
        "matching-D legacy restore must not reshard"


def test_ckpt_reshard_fault_degrades_to_fresh(tmp_path):
    """The injected ``ckpt_reshard`` fault (registry contract: one
    ``[fault-injection]``-prefixed OSError) makes the D→D′ restore
    degrade to a fresh — still converged — solve."""
    eng2 = DistributedEngine(make_op(), n_devices=2, mode="ell")
    ck = tmp_path / "ck.h5"
    _ckpt_solve(eng2, ck, max_iters=16, check_every=8, checkpoint_every=1)
    eng4 = DistributedEngine(make_op(), n_devices=4, mode="ell")
    os.environ["DMT_FAULT"] = "ckpt_reshard:n=1"
    faults.reset()
    try:
        res = _ckpt_solve(eng4, ck, max_iters=300)
    finally:
        os.environ.pop("DMT_FAULT", None)
        faults.reset()
    assert res.resumed_from == 0 and res.converged
    assert faults.fired_count("ckpt_reshard") == 0  # reset above
    evs = _reshard_events(status="reshard_failed")
    assert evs and "[fault-injection]" in evs[-1]["error"]


def test_shard_reader_rejects_mixed_generations(tmp_path):
    """Barrier-free per-rank saves can leave same-fingerprint ``.r*``
    files of DIFFERENT generations (a SIGKILL between rank saves right
    after a thick restart, which SHRINKS ``m``); restore fetches must
    stay inside the generation the selected metadata names — a stale
    file satisfying a fetch would splice old basis rows into the
    resume."""
    from distributed_matvec_tpu.io.sharded_io import (hashed_shard_reader,
                                                      save_hashed_vectors)

    base = str(tmp_path / "ck.h5")
    fresh = np.arange(4.0)
    stale = -np.arange(4.0)
    save_hashed_vectors(f"{base}.r0", {"krylov_0": fresh[None]},
                        counts=[4],
                        meta={"fingerprint": "fp", "m": 2,
                              "total_iters": 12})
    save_hashed_vectors(f"{base}.r1", {"krylov_0": stale[None],
                                       "krylov_7": stale[None]},
                        counts=[4],
                        meta={"fingerprint": "fp", "m": 5,
                              "total_iters": 40})
    sel = {"m": 2, "total_iters": 12}
    with hashed_shard_reader(base, expected_fingerprint="fp",
                             match_meta=sel) as fetch:
        assert np.array_equal(fetch(0, name="krylov_0"), fresh)
        with pytest.raises(KeyError):   # only the STALE generation has it
            fetch(0, name="krylov_7")
    # the same fetch without the generation filter proves the stale file
    # would otherwise have answered
    with hashed_shard_reader(base, expected_fingerprint="fp") as fetch:
        assert np.array_equal(fetch(0, name="krylov_7"), stale)


def test_single_process_resume_of_multiproc_rank_files(tmp_path):
    """A multi-process incarnation left per-rank ``.r*`` checkpoint
    files on shared storage and the fleet shrank to ONE process: the
    single-controller restore must fall through to the sharded-format
    scan (and reshard D→D′) instead of silently starting the multi-hour
    solve fresh."""
    import h5py

    from distributed_matvec_tpu.io.sharded_io import save_hashed_vectors

    op = make_op(12)
    eng2 = DistributedEngine(op, n_devices=2, mode="ell")
    ref = lanczos(eng2.matvec, v0=eng2.random_hashed(seed=3), k=1,
                  tol=1e-12, max_iters=400)
    e0 = float(ref.eigenvalues[0])
    ck = tmp_path / "ck.h5"
    part = _ckpt_solve(eng2, ck, max_iters=24, check_every=8,
                       checkpoint_every=1)
    assert not part.converged
    # convert the checkpoint into the per-rank sharded-format files a
    # 2-process run would have written (rank r holds shard r only)
    with h5py.File(str(ck), "r") as f:
        g = f["engine_structure"]
        V = g["V"][...]
        meta = {k: g.attrs[k] for k in g.attrs}
        for k in g:
            if k != "V":
                meta[k] = g[k][...]
    counts = np.asarray(eng2.counts, np.int64)
    for rank in (0, 1):
        rows = {f"krylov_{i}": {rank: V[i, rank, : counts[rank]]}
                for i in range(V.shape[0])}
        save_hashed_vectors(f"{ck}.r{rank}", rows, counts, meta=meta)
    os.remove(str(ck))

    eng4 = DistributedEngine(make_op(12), n_devices=4, mode="ell")
    res = _ckpt_solve(eng4, ck, max_iters=400)
    assert res.resumed_from == 24
    assert abs(float(res.eigenvalues[0]) - e0) <= 1e-12 * abs(e0)
    ev = _reshard_events("lanczos")[-1]
    assert (ev["d_from"], ev["d_to"]) == (2, 4)


# ---------------------------------------------------------------------------
# lobpcg twin


def test_lobpcg_resume_resharded_parity_with_lanczos(tmp_path):
    op = make_op(12)
    eng2 = DistributedEngine(op, n_devices=2, mode="ell")
    lref = lanczos(eng2.matvec, v0=eng2.random_hashed(seed=3), k=1,
                   tol=1e-12, max_iters=400)
    e0 = float(lref.eigenvalues[0])

    ck = tmp_path / "ck_lob.h5"
    evals_p, _, it_p = lobpcg(eng2.matvec, eng2.n_states, k=1, tol=1e-9,
                              max_iters=20, checkpoint_path=str(ck),
                              checkpoint_every=10)
    eng4 = DistributedEngine(make_op(12), n_devices=4, mode="ell")
    evals_r, _, it_r = lobpcg(eng4.matvec, eng4.n_states, k=1, tol=1e-9,
                              max_iters=300, checkpoint_path=str(ck),
                              checkpoint_every=50)
    resumes = [e for e in obs.events("solver_resume")
               if e.get("solver") == "lobpcg"]
    assert resumes and resumes[-1]["iters"] == it_p
    assert _reshard_events("lobpcg"), "lobpcg restore never resharded"
    # parity with the Lanczos answer at the solver's own tolerance
    assert abs(evals_r[0] - e0) <= 1e-7 * abs(e0)


def test_lobpcg_legacy_v1_flat_checkpoint_restores(tmp_path):
    """A pre-elastic distributed LOBPCG checkpoint stored FLAT padded
    columns under the v1 fingerprint — it must still warm-start on the
    same device count (the v1 compat contract, LOBPCG flavor)."""
    import h5py

    eng = DistributedEngine(make_op(), n_devices=2, mode="ell")
    ck = tmp_path / "ck_lob_v1.h5"
    _, _, it_p = lobpcg(eng.matvec, eng.n_states, k=1, tol=1e-9,
                        max_iters=20, checkpoint_path=str(ck),
                        checkpoint_every=10)
    # rewrite the v2 file into the v1 format: legacy fingerprint, no
    # stanza, rows FLATTENED to the padded [dim] columns v1 stored
    from distributed_matvec_tpu.solve.lanczos import _operator_key
    dim = eng.n_devices * eng.shard_size
    with h5py.File(str(ck), "r+") as f:
        g = f["engine_structure"]
        cols = g["V"].shape[0]
        V_flat = g["V"][...].reshape(cols, dim)
        del g["V"]
        g.create_dataset("V", data=V_flat)
        g.attrs["fingerprint"] = (f"lobpcg|{dim}|{cols}|0"
                                  f"|{_operator_key(eng)}|v1")
        for k in ("topology_d", "topology_m", "partition_fp",
                  "ckpt_version"):
            del g.attrs[k]
        del g["topology_counts"]
    _, _, _ = lobpcg(eng.matvec, eng.n_states, k=1, tol=1e-9,
                     max_iters=300, checkpoint_path=str(ck),
                     checkpoint_every=50)
    resumes = [e for e in obs.events("solver_resume")
               if e.get("solver") == "lobpcg"]
    assert resumes and resumes[-1]["iters"] == it_p, \
        "v1 flat LOBPCG checkpoint did not warm-start"


# ---------------------------------------------------------------------------
# serve-layer elasticity (satellite)


def test_pool_drops_warm_engine_on_mesh_shrink():
    from distributed_matvec_tpu.serve import EnginePool, JobSpec

    spec = JobSpec(job_id="el-pool",
                   basis={"number_spins": 10, "hamming_weight": 5},
                   k=1, mode="ell", n_devices=2)
    pool = EnginePool(live_devices=4)
    eng = pool.acquire(spec)
    assert eng.n_devices == 2
    # same topology: warm hit
    assert pool.acquire(spec) is eng and pool.hits == 1
    # the fleet shrinks under the pool: the warm engine must be dropped
    # and rebuilt clamped to what exists
    pool.live_devices = 1
    eng1 = pool.acquire(spec)
    assert eng1 is not eng and getattr(eng1, "n_devices", 1) == 1
    evict = [e for e in obs.events("engine_pool")
             if e.get("reason") == "mesh_mismatch"]
    assert evict and evict[-1]["live_devices"] == 1
    clamp = obs.events("engine_clamp")
    assert clamp and clamp[-1]["requested_devices"] == 2
    # the fleet REGROWS: the engine clamped during the shrink must not
    # keep serving the spec undersized while admission prices the full
    # live capacity — dropped and rebuilt at min(spec, live)
    pool.live_devices = 4
    eng4 = pool.acquire(spec)
    assert eng4 is not eng1 and eng4.n_devices == 2


def test_admission_prices_live_capacity():
    from distributed_matvec_tpu.serve import (EnginePool, JobQueue,
                                              JobSpec, Scheduler)

    sched = Scheduler(queue=JobQueue(), pool=EnginePool(live_devices=1),
                      rates=None, live_devices=1)
    v = sched.admit(JobSpec(job_id="el-adm",
                            basis={"number_spins": 10,
                                   "hamming_weight": 5},
                            mode="ell", n_devices=4))
    assert v["live_devices"] == 1 and v["priced_devices"] == 1
    adm = [e for e in obs.events("admission")
           if e.get("job_id") == "el-adm"]
    assert adm and adm[-1]["live_devices"] == 1


# ---------------------------------------------------------------------------
# heartbeat rank-set awareness (satellite)


def test_heartbeat_ignores_and_ages_out_departed_ranks(tmp_path):
    from distributed_matvec_tpu.parallel.heartbeat import HeartbeatWatchdog

    hb_dir = tmp_path / "hb"
    os.makedirs(hb_dir / "heartbeat")
    # leftovers of a 4-rank era, stale for ages
    old = time.time() - 3600
    for r in (1, 2, 3):
        p = hb_dir / "heartbeat" / f"rank_{r}.hb"
        p.write_text("0.0\n")
        os.utime(p, (old, old))
    stalls = []
    wd = HeartbeatWatchdog(str(hb_dir), interval_s=0.05, timeout_s=0.2,
                           rank=0, n_ranks=2,
                           on_stall=lambda rep: stalls.append(rep))
    wd.start()
    try:
        # departed ranks' files swept on start; rank_1 (in set) kept
        names = sorted(os.listdir(hb_dir / "heartbeat"))
        assert "rank_2.hb" not in names and "rank_3.hb" not in names
        # a live peer beats: no stall, and the scan never names a
        # departed rank even past the grace window
        deadline = time.time() + 1.0
        while time.time() < deadline:
            (hb_dir / "heartbeat" / "rank_1.hb").write_text(
                f"{time.time():.3f}\n")
            time.sleep(0.05)
        assert not stalls, stalls
        # the scan is scoped to the rank set by construction
        report = wd.scan()
        assert report is None
    finally:
        wd.stop()
    # a NOT-YET-STALE out-of-set file is never swept: a live concurrent
    # larger run's peers beat every interval_s, so their files are
    # RECENT but still predate a freshly constructed watchdog — deleting
    # one would open a one-beat window in which that run sees the file
    # missing and aborts spuriously.  Staleness past timeout_s, not age
    # relative to this watchdog, decides.
    wd2 = HeartbeatWatchdog(str(hb_dir), timeout_s=60.0, rank=0, n_ranks=2,
                            on_stall=lambda rep: None)
    live = hb_dir / "heartbeat" / "rank_8.hb"
    live.write_text("x\n")
    recent = time.time() - 1.0          # beat 1 s ago — before wd2._t0
    os.utime(live, (recent, recent))
    fresh = hb_dir / "heartbeat" / "rank_9.hb"
    fresh.write_text("x\n")
    ahead = time.time() + 60
    os.utime(fresh, (ahead, ahead))
    wd2._age_out_departed()
    assert live.exists() and fresh.exists()


def test_heartbeat_still_reports_a_real_stall(tmp_path):
    """Rank-set scoping must not swallow GENUINE stalls of live peers."""
    from distributed_matvec_tpu.parallel.heartbeat import HeartbeatWatchdog

    stalls = []
    wd = HeartbeatWatchdog(str(tmp_path), interval_s=0.05, timeout_s=0.3,
                           rank=0, n_ranks=2,
                           on_stall=lambda rep: stalls.append(rep))
    wd.start()
    try:
        deadline = time.time() + 3.0
        while not stalls and time.time() < deadline:
            time.sleep(0.05)
    finally:
        wd.stop()
    assert stalls and stalls[0]["stalled"] == [1]


# ---------------------------------------------------------------------------
# the REAL 2-process leg


def test_multihost_elastic_two_ranks(tmp_path):
    """2-process run (multihost worker harness, elastic leg): each rank
    writes a sharded checkpoint on a rank-local 4-device mesh (per-rank
    ``.r*`` files at the OLD topology), then resumes the same solve on a
    2-device mesh — the restore reshards across the multi-rank file
    layout and the resumed E0 matches the exact ground state."""
    import socket
    import subprocess
    import sys as _sys

    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["DMT_MH_ELASTIC"] = str(tmp_path)
    procs = [subprocess.Popen(
        [_sys.executable, worker, str(pid), "2", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid}:\n{out[-2000:]}"
        assert f"[p{pid}] elastic resumed E0/4" in out, out[-2000:]
        assert f"[p{pid}] MULTIHOST_OK" in out, out[-2000:]
