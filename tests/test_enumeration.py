"""Host enumeration: bit tricks, rank/unrank, hashing, representatives."""

import math

import numpy as np
import pytest

from distributed_matvec_tpu.enumeration import host as en
from distributed_matvec_tpu.models.symmetry import SymmetryGroup

import dense_ref


def test_next_state_fixed_hamming_small():
    # semantic reference from StatesEnumeration.chpl:21-30
    def slow(v):
        m = bin(v).count("1")
        v += 1
        while bin(v).count("1") != m:
            v += 1
        return v

    for v in [1, 2, 3, 5, 7, 0b1010, 0b0111, 0b110100, (1 << 10) - 1]:
        assert en.next_state_fixed_hamming(v) == slow(v)


@pytest.mark.parametrize("n,k", [(4, 2), (6, 3), (8, 1), (8, 8), (10, 5), (12, 0), (16, 4)])
def test_fixed_hamming_states(n, k):
    s = en.fixed_hamming_states(n, k)
    assert s.size == math.comb(n, k)
    assert (np.diff(s.astype(np.int64)) > 0).all()  # strictly ascending
    assert (np.bitwise_count(s) == k).all()
    # first and last match the min/max estimates
    if k > 0:
        assert s[0] == (1 << k) - 1
        assert s[-1] == ((1 << k) - 1) << (n - k)


def test_fixed_hamming_states_match_next_state_iteration():
    s = en.fixed_hamming_states(8, 3)
    v = (1 << 3) - 1
    for expected in s:
        assert v == expected
        v = en.next_state_fixed_hamming(v)


@pytest.mark.parametrize("n,k", [(8, 3), (10, 5), (12, 4)])
def test_rank_unrank_roundtrip(n, k):
    s = en.fixed_hamming_states(n, k)
    ranks = en.fixed_hamming_rank(s)
    np.testing.assert_array_equal(ranks, np.arange(s.size, dtype=np.uint64))
    for r in [0, 1, s.size // 2, s.size - 1]:
        assert en.fixed_hamming_unrank(r, k) == s[r]


def test_hash64_is_splitmix64_finalizer():
    # independently computed splitmix64 finalizer values
    def ref(x):
        mask = (1 << 64) - 1
        x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & mask
        x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & mask
        return x ^ (x >> 31)

    xs = np.array([0, 1, 2, 12345, (1 << 63) | 12345], dtype=np.uint64)
    got = en.hash64(xs)
    for x, g in zip(xs, got):
        assert int(g) == ref(int(x))


def test_shard_index_range():
    s = en.fixed_hamming_states(12, 6)
    for n_shards in (1, 2, 4, 8):
        idx = en.shard_index(s, n_shards)
        assert idx.min() >= 0 and idx.max() < n_shards
        if n_shards > 1:
            counts = np.bincount(idx, minlength=n_shards)
            # hash-balanced to within a few σ
            assert counts.min() > 0.5 * s.size / n_shards


@pytest.mark.parametrize(
    "n,hw,gens,inv",
    [
        (10, 5, [], -1),                              # chain_10-style inversion only
        (8, 4, [([1, 2, 3, 4, 5, 6, 7, 0], 0)], None),  # translation sector 0
        (8, 4, [([1, 2, 3, 4, 5, 6, 7, 0], 1)], None),  # complex characters
        (8, 4, [([1, 2, 3, 4, 5, 6, 7, 0], 0), ([7, 6, 5, 4, 3, 2, 1, 0], 0)], 1),
        (12, 6, [([2, 10, 0, 4, 3, 7, 11, 5, 9, 8, 1, 6], 1)], None),  # issue_01.yaml group
        (9, None, [([1, 2, 3, 4, 5, 6, 7, 8, 0], 3)], None),  # no hamming sector
    ],
)
def test_enumerate_representatives_vs_brute_force(n, hw, gens, inv):
    group = SymmetryGroup.build(n, gens, inv)
    candidates = en.all_states(n, hw)
    reps, norms = en.enumerate_representatives(n, hw, group)
    ref_reps, ref_norms = dense_ref.brute_force_representatives(n, candidates, group)
    np.testing.assert_array_equal(reps, ref_reps)
    np.testing.assert_allclose(norms, ref_norms, atol=1e-13)
    assert (np.diff(reps.astype(np.int64)) > 0).all()


def test_chain_10_inversion_count():
    # C(10,5)/2 = 126 representatives (data/heisenberg_chain_10.yaml sector)
    group = SymmetryGroup.build(10, [], -1)
    reps, norms = en.enumerate_representatives(10, 5, group)
    assert reps.size == 126
    np.testing.assert_allclose(norms, np.sqrt(0.5))


def test_state_info_consistency():
    """state_info of any state maps into the enumerated representative set."""
    group = SymmetryGroup.build(
        8, [([1, 2, 3, 4, 5, 6, 7, 0], 0), ([7, 6, 5, 4, 3, 2, 1, 0], 0)], 1
    )
    reps, _ = en.enumerate_representatives(8, 4, group)
    all_s = en.all_states(8, 4)
    r, chars, norms = group.state_info(all_s)
    live = norms > 0
    assert np.isin(r[live], reps).all()
    # orbit-invariance of the norm
    np.testing.assert_allclose(norms, group.state_info(r)[2], atol=1e-13)


def test_square_edges_keeps_doubled_wrap_bonds():
    """Regression: periodic 4x2 torus has doubled vertical bonds."""
    from distributed_matvec_tpu.models.lattices import chain_edges, square_edges

    e42 = square_edges(4, 2)
    assert e42.count((0, 4)) == 2
    assert chain_edges(2) == [(0, 1), (1, 0)]
    # no duplicates for sizes > 2
    e44 = square_edges(4, 4)
    assert len(e44) == len(set(e44)) == 32


def test_basis_json_roundtrip_preserves_subclass():
    from distributed_matvec_tpu.models.basis import (
        SpinBasis,
        SpinfulFermionBasis,
        SpinlessFermionBasis,
    )

    b = SpinfulFermionBasis(3, 2, 1)
    b2 = SpinBasis.from_json(b.to_json())
    assert isinstance(b2, SpinfulFermionBasis)
    np.testing.assert_array_equal(
        b.build().representatives, b2.build().representatives
    )
    assert b.number_states == 9  # C(3,2)·C(3,1)
    s = SpinlessFermionBasis(5, 2)
    s2 = SpinBasis.from_json(s.to_json())
    assert isinstance(s2, SpinlessFermionBasis)
    assert s2.build().number_states == 10


# -- native (C++) enumeration kernel ----------------------------------------


def _native_or_skip():
    import pytest

    from distributed_matvec_tpu.enumeration import native

    if not native.native_available():
        pytest.skip("no C++ toolchain")
    return native


def test_native_matches_numpy_enumeration():
    """The streaming C++ kernel must agree exactly (states AND norms) with
    the portable NumPy path on every sector shape: translation, momentum,
    translation×parity×inversion, no-hamming."""
    from distributed_matvec_tpu.enumeration import host
    from distributed_matvec_tpu.models.symmetry import SymmetryGroup

    native = _native_or_skip()
    configs = [
        (8, 4, [([*range(1, 8), 0], 0)], None),
        (10, 5, [([*range(1, 10), 0], 1)], None),         # complex sector
        (12, 6, [([*range(1, 12), 0], 0),
                 ([*reversed(range(12))], 0)], 1),
        (13, 6, [([*range(1, 13), 0], 3)], None),
        (12, None, [([*range(1, 12), 0], 0)], None),      # no hamming
        (16, 8, [([*range(1, 16), 0], 0),
                 ([*reversed(range(16))], 0)], -1),       # antisymmetric inv
    ]
    for n, hw, syms, inv in configs:
        g = SymmetryGroup.build(n, syms, inv)
        s_np, n_np = host.enumerate_representatives(n, hw, g)
        s_c, n_c = native.enumerate_representatives_native(n, hw, g)
        np.testing.assert_array_equal(s_np, s_c)
        np.testing.assert_allclose(n_np, n_c, atol=1e-14)


def test_native_chunking_boundaries():
    """Many tiny chunks must tile the range without loss or duplication."""
    from distributed_matvec_tpu.enumeration import host
    from distributed_matvec_tpu.models.symmetry import SymmetryGroup

    native = _native_or_skip()
    g = SymmetryGroup.build(14, [([*range(1, 14), 0], 0)])
    s_ref, _ = host.enumerate_representatives(14, 7, g)
    for n_chunks in (1, 3, 64, 500):
        s_c, _ = native.enumerate_representatives_native(
            14, 7, g, n_chunks=n_chunks)
        np.testing.assert_array_equal(s_ref, s_c)


def test_build_uses_backend_dispatch():
    from distributed_matvec_tpu.models.basis import SpinBasis
    from distributed_matvec_tpu.utils.config import update_config

    _native_or_skip()
    syms = [([*range(1, 12), 0], 0)]
    try:
        update_config(enumeration_backend="native")
        b1 = SpinBasis(12, 6, None, syms).build()
        update_config(enumeration_backend="numpy")
        b2 = SpinBasis(12, 6, None, syms).build()
    finally:
        update_config(enumeration_backend="auto")
    np.testing.assert_array_equal(b1.representatives, b2.representatives)
    np.testing.assert_allclose(b1.norms, b2.norms, atol=1e-14)
